#!/usr/bin/env python
"""On-chip smoke for the Pallas kernels + the serving path (r3 VERDICT
item 1: all three kernels must execute COMPILED — ``interpret=False`` —
on the real chip at least once; they auto-fall back to the interpreter
off-TPU, so CPU CI never exercises Mosaic lowering).

Run the moment the TPU tunnel is up:

    python tpu_smoke.py            # axon/TPU platform from the env

Prints one JSON line: per-kernel ok/error (each validated against the
interpreter result) + a tiny end-to-end serving read on device.
"""

from __future__ import annotations

import json
import sys
import time

import numpy as np


def main() -> int:
    out = {"platform": None, "kernels": {}, "serving": None}
    t0 = time.time()
    import jax

    if "--cpu" in sys.argv:
        # plumbing check off-chip (compiled Pallas is expected to fail
        # here — Mosaic lowers for TPU only); forcing the platform
        # BEFORE any jax op also dodges a wedged axon tunnel
        jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp

    out["platform"] = jax.devices()[0].platform
    out["backend_init_s"] = round(time.time() - t0, 1)
    on_tpu = out["platform"] in ("tpu", "axon")
    from antidote_tpu.materializer import pallas_kernels as pk

    rng = np.random.default_rng(0)

    def check(name, fn):
        t = time.time()
        try:
            fn()
            out["kernels"][name] = {"ok": True,
                                    "s": round(time.time() - t, 1)}
        except Exception as e:  # noqa: BLE001 - smoke reports, not raises
            out["kernels"][name] = {"ok": False, "error": repr(e)[:300]}

    # 1. counter fold (masked sum under VC dominance)
    def counter():
        m, k, d = 256, 8, 4
        deltas = jnp.asarray(rng.integers(-5, 6, (m, k)), jnp.int32)
        ops_vc = jnp.asarray(rng.integers(0, 50, (m, k, d)), jnp.int32)
        n_ops = jnp.asarray(rng.integers(0, k + 1, (m,)), jnp.int32)
        base_vc = jnp.zeros((m, d), jnp.int32)
        read_vc = jnp.full((m, d), 25, jnp.int32)
        got = pk._counter_fold_call(deltas, ops_vc, n_ops, base_vc,
                                    read_vc, 128, False)  # compiled
        want = pk._counter_fold_call(deltas, ops_vc, n_ops, base_vc,
                                     read_vc, 128, True)  # interpreter
        np.testing.assert_array_equal(np.asarray(got[0]),
                                      np.asarray(want[0]))
        np.testing.assert_array_equal(np.asarray(got[1]),
                                      np.asarray(want[1]))

    # 2. stable min (streaming clock-matrix min-reduce)
    def stable():
        clocks = jnp.asarray(rng.integers(0, 1000, (4096, 8)), jnp.int32)
        got = pk.stable_min(clocks, interpret=False)
        np.testing.assert_array_equal(
            np.asarray(got), np.asarray(clocks).min(axis=0))

    # 3. OR-set presence
    def orset():
        m, e, d = 256, 8, 4
        addvc = jnp.asarray(rng.integers(0, 9, (m, e, d)), jnp.int32)
        rmvc = jnp.asarray(rng.integers(0, 9, (m, e, d)), jnp.int32)
        elems_lo = jnp.asarray(rng.integers(0, 2, (m, e)), jnp.int32)
        got = pk.orset_presence(addvc, rmvc, elems_lo, interpret=False)
        want = pk.orset_presence(addvc, rmvc, elems_lo, interpret=True)
        np.testing.assert_array_equal(np.asarray(got), np.asarray(want))

    check("counter_fold", counter)
    check("stable_min", stable)
    check("orset_presence", orset)

    # 4. tiny end-to-end serving read on the chip
    try:
        from antidote_tpu.api import AntidoteNode
        from antidote_tpu.config import AntidoteConfig

        node = AntidoteNode(AntidoteConfig(
            n_shards=4, max_dcs=2, keys_per_table=64, ops_per_key=8,
            batch_buckets=(16, 64), use_pallas=on_tpu))
        node.update_objects([("k", "set_aw", "b", ("add_all", ["x", "y"])),
                             ("c", "counter_pn", "b", ("increment", 7))])
        node.update_objects([("k", "set_aw", "b", ("remove", "x"))])
        vals, _ = node.read_objects([("k", "set_aw", "b"),
                                     ("c", "counter_pn", "b")])
        assert vals == [["y"], 7], vals
        out["serving"] = {"ok": True}
    except Exception as e:  # noqa: BLE001
        out["serving"] = {"ok": False, "error": repr(e)[:300]}

    out["all_ok"] = (all(v.get("ok") for v in out["kernels"].values())
                     and bool(out["serving"] and out["serving"]["ok"]))
    print(json.dumps(out))
    return 0 if out["all_ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
