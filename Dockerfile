# antidote_tpu node image — the env-var-driven single-node release the
# reference ships (/root/reference/Dockerfiles/Dockerfile:3-13 shape).
# For TPU hosts, base on a jax[tpu]-provisioned image instead and the
# same entrypoint serves from the chip.
FROM python:3.12-slim

ENV PB_PORT=8087 \
    PB_IP=0.0.0.0 \
    METRICS_PORT=3001 \
    DC_ID=0 \
    SHARDS=16 \
    MAX_DCS=8 \
    DATA_DIR=/data \
    JAX_PLATFORMS=cpu

RUN apt-get update \
    && apt-get install -y --no-install-recommends g++ \
    && rm -rf /var/lib/apt/lists/* \
    && pip install --no-cache-dir "jax[cpu]" numpy msgpack

WORKDIR /opt/antidote_tpu
COPY antidote_tpu ./antidote_tpu
# build the native WAL + router once at image build
RUN python -c "from antidote_tpu.log.wal import _load_lib; assert _load_lib()" \
    && python -c "from antidote_tpu.store.router import shard_batch; shard_batch(['k'], ['b'], 4)"

VOLUME /data
EXPOSE 8087 3001

ENTRYPOINT ["sh", "-c", "exec python -m antidote_tpu.console serve \
    --host ${PB_IP} --port ${PB_PORT} --metrics-port ${METRICS_PORT} \
    --dc-id ${DC_ID} --shards ${SHARDS} --max-dcs ${MAX_DCS} \
    --log-dir ${DATA_DIR}"]
