# antidote_tpu node image — the env-var-driven single-node release the
# reference ships (/root/reference/Dockerfiles/Dockerfile:3-13 shape).
# For TPU hosts, base on a jax[tpu]-provisioned image instead and the
# same entrypoint serves from the chip.
FROM python:3.12-slim

ENV PB_PORT=8087 \
    PB_IP=0.0.0.0 \
    METRICS_PORT=3001 \
    DC_ID=0 \
    SHARDS=16 \
    MAX_DCS=8 \
    KEYS_PER_TABLE=65536 \
    INTERDC=1 \
    INTERDC_PORT=8086 \
    PUBLIC_HOST="" \
    DATA_DIR=/data \
    JAX_PLATFORMS=cpu

RUN apt-get update \
    && apt-get install -y --no-install-recommends g++ \
    && rm -rf /var/lib/apt/lists/* \
    && pip install --no-cache-dir "jax[cpu]" numpy msgpack

WORKDIR /opt/antidote_tpu
COPY antidote_tpu ./antidote_tpu
# build the native WAL + router once at image build
RUN python -c "from antidote_tpu.log.wal import _load_lib; assert _load_lib()" \
    && python -c "from antidote_tpu.store.router import shard_batch; shard_batch(['k'], ['b'], 4)"

VOLUME /data
EXPOSE 8087 8086 3001

# INTERDC=1 attaches the geo-replication plane on the fixed
# INTERDC_PORT (publishable through -p); set PUBLIC_HOST to the name
# remote DCs reach this container by — descriptors advertise it.
# Any other INTERDC value (0/false/empty) serves a standalone DC.
ENTRYPOINT ["sh", "-c", "IFLAGS=''; \
    if [ \"${INTERDC}\" = \"1\" ]; then \
      IFLAGS=\"--interdc --interdc-port ${INTERDC_PORT}\"; \
      [ -n \"${PUBLIC_HOST}\" ] && IFLAGS=\"$IFLAGS --public-host ${PUBLIC_HOST}\"; \
    fi; \
    exec python -m antidote_tpu.console serve \
    --host ${PB_IP} --port ${PB_PORT} --metrics-port ${METRICS_PORT} \
    --dc-id ${DC_ID} --shards ${SHARDS} --max-dcs ${MAX_DCS} \
    --keys-per-table ${KEYS_PER_TABLE} ${IFLAGS} \
    --log-dir ${DATA_DIR}"]
