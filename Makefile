# Operator/CI entrypoints (reference analogue: /root/reference/Makefile:79-111
# — compile/test/dialyzer/elvis).  This image has no third-party
# linter, so `lint` runs the stdlib AST gate; ruff/mypy configs live in
# pyproject.toml for hosts that have them.

PY ?= python

.PHONY: test smoke chaos saturation perf-smoke restart-smoke coldtier-smoke replica-smoke fleet-smoke proxy-smoke escrow-smoke mesh-smoke hotkey-smoke tenant-smoke native native-check socket-storm lint bench bench-wire multichip all

all: lint smoke

# full suite (serial; ~10-12 min on the 1-core CI host); long chaos
# soaks are opt-in via `make chaos`
test:
	$(PY) -m pytest tests/ -q -m 'not slow'

# the whole fault-injection suite INCLUDING the slow soaks: seeded
# partitions, endpoint crash/restart, drop/dup/delay storms, mid-handoff
# crashes — every scenario ends with byte-identical converged snapshots
chaos:
	$(PY) -m pytest tests/test_chaos.py -q

# overload smoke (PR 4): the seeded saturation-storm + ENOSPC chaos
# scenario (typed sheds, bounded RSS, clean read-only entry/exit,
# byte-identical convergence) plus a short write-plane saturation sweep
# asserting the structural bounds (typed sheds occur, no latency wedge)
saturation:
	$(PY) -m pytest tests/test_overload.py \
	  "tests/test_chaos.py::test_saturation_storm_enospc_bounded_and_converges" -q
	$(PY) bench_wire.py --saturation --smoke --assert-bounds

# serving-pipeline smoke (ISSUE 5): ~30s read-only north-star wire run;
# fails when read throughput drops below 0.8x the frozen perf_smoke
# entry in BENCH_WIRE_cpu.json — the CI tripwire for the lock-split
# epoch-read plane (runs alongside `make saturation` in CI).
# The ISSUE 6 write-plane twin rides the same target: ~30s write-heavy
# run gated at 0.8x the frozen perf_smoke_write entry (cross-connection
# group commit + parallel WAL + certification bypass tripwire).
# Neither gate ever ratchets its floor.
perf-smoke:
	$(PY) bench_wire.py --perf-smoke --assert-bounds --json BENCH_WIRE_cpu.json
	$(PY) bench_wire.py --perf-smoke-write --assert-bounds --json BENCH_WIRE_cpu.json

# native planes (ISSUE 16): rebuild BOTH checked-in .so's (inter-DC
# pump + serving front-end) with the ONE pinned flag set, embedding
# each source's sha256; `native-check` fails CI when a checked-in
# binary was built from different source than what's in the tree (the
# drift a hand-run g++ line can't detect)
native:
	$(PY) -m antidote_tpu.native_build

native-check:
	$(PY) -m antidote_tpu.native_build --check

# >=1k-socket accept-plane storm (ISSUE 16): structural gate only —
# every socket connects AND gets served, zero protocol errors, and the
# native front-end serves whole-batch hits with the fleet attached;
# the frozen `sockets` entry in BENCH_WIRE_cpu.json is never a ratchet
socket-storm:
	$(PY) bench_wire.py --sockets 1024 --assert-bounds

# checkpointed fast-restart smoke (ISSUE 8): populates through the
# durable commit path, SIGKILLs, measures full-replay vs checkpoint+tail
# recovery in cold subprocesses, and asserts the STRUCTURAL gates only
# (fast < full, byte-identical recovered state, WAL bytes reclaimed) —
# the frozen BENCH_RESTART_cpu.json numbers are never a ratchet
restart-smoke:
	$(PY) tools/bench_restart.py --smoke --assert-bounds
	$(PY) -m pytest tests/test_checkpoint.py -q

# beyond-RAM survival (ISSUE 13): cold-tier + Merkle unit suite, the
# incremental-vs-full stamp gate (delta rows == dirty writes, bytes and
# wall-clock undercut the rebase), and a small beyond-budget populate →
# SIGKILL → cold recovery run asserting the STRUCTURAL gates only
# (resident rows ≤ budget + one rebase window, sample reads byte-exact
# after fault-in) — the frozen BENCH_RESTART_cpu.json curves are never
# a ratchet
coldtier-smoke:
	$(PY) -m pytest tests/test_coldtier.py -q
	$(PY) tools/bench_restart.py --incremental --smoke --assert-bounds
	$(PY) tools/bench_restart.py --coldtier-smoke --assert-bounds

# follower read tier (ISSUE 9): the deterministic follower suite plus a
# short live fanout run — owner + followers boot for real, SessionClients
# assert read-your-writes on every write→read pair, and the gate is
# STRUCTURAL only (zero session violations, nonzero throughput); the
# frozen follower_fanout scaling curve in BENCH_WIRE_cluster_cpu.json is
# never a ratchet
replica-smoke:
	$(PY) -m pytest tests/test_follower.py -q
	$(PY) bench_wire.py --follower-fanout --smoke --assert-bounds

# planet-scale session fabric (ISSUE 11): the session-algebra/ring/apb
# property suite plus one live hash-routed 4-follower fanout point with
# the COVERAGE gate — zero session violations and every follower's ring
# arcs actually served reads.  STRUCTURAL only; the frozen 8-follower
# curve in BENCH_WIRE_cluster_cpu.json is never a ratchet
fleet-smoke:
	$(PY) -m pytest tests/test_session_fabric.py -q
	$(PY) bench_wire.py --fleet-smoke --assert-bounds

# symmetric serving fabric (ISSUE 17): the proxy/forward/fleet-health
# suite plus one live run of ring-OBLIVIOUS clients through ONE entry
# follower — writes forward to the owner, foreign-arc reads proxy one
# hop, own-arc reads serve locally.  The gate is STRUCTURAL only: zero
# surfaced typed redirects, zero session violations, nonzero forwarded
# read AND write traffic; the frozen proxy_fanout hop-cost point in
# BENCH_WIRE_cluster_cpu.json is never a throughput ratchet
proxy-smoke:
	$(PY) -m pytest tests/test_proxy.py -q
	$(PY) bench_wire.py --proxy-fanout --smoke --assert-bounds

# escrow economy (ISSUE 18): the bounded-counter suite (typed refusals,
# conservation under seeded interleavings, apb round-trip, forwarded
# refusals) plus one live two-DC Zipf flash-sale storm.  The gate is
# STRUCTURAL only: zero oversell (no SKU acks past its minted
# inventory; converged value == inventory - acked at BOTH DCs), zero
# protocol errors, typed refusals actually seen, and live rights-
# transfer traffic; the frozen goodput numbers in BENCH_ESCROW_cpu.json
# are never a CI ratchet
escrow-smoke:
	$(PY) -m pytest tests/test_bcounter.py -q
	$(PY) bench_wire.py --flash-sale --smoke --assert-bounds

# mesh serving plane (ISSUE 10): the deterministic mesh suite on the
# forced 8-device CPU mesh (read parity byte-identical with the
# single-chip plane, per-shard incremental publish, pmin == host stable
# time, donation under commits) plus a short scaling run — the gate is
# STRUCTURAL only (parity clean, burst publish ∝ dirty rows, artifact
# shape); the frozen BENCH_MESH_cpu.json curve is never a throughput
# ratchet (2-core container — see its host_note)
mesh-smoke:
	JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
	$(PY) -m pytest tests/test_mesh.py -q
	$(PY) tools/bench_mesh.py --smoke --assert-bounds

# celebrity-key materializer (ISSUE 15): the fold-strategy parity suite
# plus a short one-key over-ring run timing every strategy the store can
# route it to (serial scan / assoc delta / chunked long / mesh-sharded /
# Pallas ring kernel) with concurrent snapshot readers.  The gate is
# STRUCTURAL only (byte parity, every strategy ran, readers progressed);
# the frozen BENCH_HOTKEY_cpu.json speedups (assoc + mesh_assoc >= 4x
# serial on the full 1M-op freeze) are never a CI ratchet
hotkey-smoke:
	$(PY) -m pytest tests/test_fold_parity.py -q
	$(PY) tools/bench_hotkey.py --smoke --assert-bounds

# multi-tenant QoS (ISSUE 19): the WFQ/quota/identity property suite
# (DRR shares, work conservation, per-key retry streaks, typed
# tenant_busy end-to-end over both dialects incl. a forwarding
# follower) plus one live aggressor+victim storm at a 4:1 weight
# ratio.  The gate is STRUCTURAL only: the aggressor's quota actually
# trips, the victim sees ZERO typed refusals, both tenants progress;
# the frozen inflation/share curves in BENCH_TENANT_cpu.json are never
# a CI ratchet (2-core container — see its host_note)
tenant-smoke:
	$(PY) -m pytest tests/test_tenancy.py -q
	$(PY) bench_wire.py --tenants --smoke --assert-bounds

# fast fundamental tier, <90s: clocks, router, WAL, metadata, txn layer,
# wire codecs, store tables, observability, console, supervision
smoke:
	$(PY) -m pytest -q -m smoke

lint:
	$(PY) tools/lint.py
	@if command -v ruff >/dev/null 2>&1; then ruff check .; fi

bench:
	$(PY) bench.py

bench-wire:
	$(PY) bench_wire.py

multichip:
	JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
	$(PY) -c "import __graft_entry__ as g; g.dryrun_multichip(8); print('ok')"
