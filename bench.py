#!/usr/bin/env python
"""North-star benchmark: snapshot-read throughput on a 1M-key OR-set.

BASELINE.json workload: ``antidote_crdt_set_aw``, Zipfian access, batched
snapshot reads vs a sequential host materializer re-implementing the
reference's per-key walk (clocksi_materializer:materialize_intern +
apply_operations, /root/reference/src/clocksi_materializer.erl:111-197) in
plain Python with dict vector clocks.

Two numbers are reported (r1 VERDICT items 1-2):

* ``value`` — the SERVING PATH: reads through
  ``TypedTable.read_resolved`` (host shard routing + freshness check +
  snapshot-version select + versioned ring fold + device value
  resolution), with one batch in five at a historical VC so the
  materializer fold (``fold_batch``) is inside the timed loop.  Pipelined
  batches model basho_bench's concurrent workers.
* ``device_kernel_reads_per_s`` — the device-only kernel loop (head gather
  + OR-set presence resolution), isolating what the chip does from what
  the ~50-100 ms dev-tunnel RTT costs; on a real PCIe host the serving
  number approaches it.

Process layout (fail-soft, r1 VERDICT item 1): the parent runs the real
bench in a CHILD process with a hard wall-clock timeout (TPU backend init
has been observed to hang >8 min in this environment), retries once, then
falls back to JAX_PLATFORMS=cpu with a smaller key count.  The parent
ALWAYS prints exactly one JSON line on stdout and exits 0; failures are
reported in an ``"error"`` field, never as a traceback + rc=1.

r2 VERDICT item 1 additions: every phase logs start/end + elapsed on
stderr so a timeout localizes itself; the child arms
``faulthandler.dump_traceback_later`` so a hang prints the stuck Python
stack; the Pallas in-path dispatch (the only delta between the CPU run
that worked and the TPU run that hung) is bisected — the first TPU
attempt runs ``--pallas off`` (pure XLA, the configuration proven on
CPU), and Pallas is then tried as a separate UPGRADE attempt whose
failure cannot lose the landed number.

Usage: python bench.py [--smoke] [--keys N] [--pallas auto|on|off]
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import time

import numpy as np

_T0 = time.time()


def log(*a):
    print(f"[bench {time.time() - _T0:8.1f}s]", *a, file=sys.stderr, flush=True)


METRIC = "serving_read_throughput_set_aw_zipf"


# ---------------------------------------------------------------------------
# parent: fail-soft orchestration
# ---------------------------------------------------------------------------
def _run_attempt(extra_args, env_over, timeout_s):
    """Run the child; return (parsed_json | None, note)."""
    cmd = [sys.executable, os.path.abspath(__file__), "--child"] + extra_args
    env = dict(os.environ)
    env.update(env_over)
    log(f"parent: attempt {' '.join(extra_args) or '(default)'} "
        f"env={env_over} timeout={timeout_s}s")
    try:
        res = subprocess.run(
            cmd, env=env, timeout=timeout_s,
            stdout=subprocess.PIPE, stderr=sys.stderr,
        )
    except subprocess.TimeoutExpired:
        return None, f"timeout after {timeout_s}s"
    out = res.stdout.decode(errors="replace")
    for line in reversed(out.strip().splitlines()):
        line = line.strip()
        if line.startswith("{"):
            try:
                return json.loads(line), None
            except json.JSONDecodeError:
                continue
    return None, f"child rc={res.returncode}, no JSON line"


def parent(args):
    smoke = ["--smoke"] if args.smoke else []
    t_tpu = int(os.environ.get("ANTIDOTE_BENCH_TPU_TIMEOUT", "1200"))
    t_retry = int(os.environ.get("ANTIDOTE_BENCH_RETRY_TIMEOUT", "420"))
    t_cpu = int(os.environ.get("ANTIDOTE_BENCH_CPU_TIMEOUT", "900"))
    t_pallas = int(os.environ.get("ANTIDOTE_BENCH_PALLAS_TIMEOUT", "600"))
    if args.smoke:
        t_tpu, t_retry, t_cpu = min(t_tpu, 600), min(t_retry, 300), min(t_cpu, 600)
        t_pallas = min(t_pallas, 300)
    keyarg = ["--keys", str(args.keys)] if args.keys else []
    cpu_keys = ["--keys", str(args.keys or (20_000 if args.smoke else 200_000))]
    # Bisect plan (r2 VERDICT item 1): the TPU attempt that hung was the
    # only configuration running the Pallas in-path dispatch, so by
    # default the landing attempts force --pallas off and Pallas runs as
    # an upgrade.  An explicit --pallas on/off is honored verbatim (and
    # disables the bisect: there is nothing to upgrade to).
    land_pallas = "off" if args.pallas == "auto" else args.pallas
    plan = [
        (smoke + keyarg + ["--pallas", land_pallas], {}, t_tpu),
        (smoke + keyarg + ["--pallas", land_pallas], {}, t_retry),
        (smoke + cpu_keys + ["--pallas", land_pallas], {"JAX_PLATFORMS": "cpu"}, t_cpu),
    ]
    notes = []
    got = None
    for i, (extra, env_over, timeout_s) in enumerate(plan):
        t_land0 = time.time()
        got, note = _run_attempt(extra, env_over, timeout_s)
        land_wall = time.time() - t_land0
        if got is not None:
            break
        notes.append(f"attempt{i + 1}[{env_over.get('JAX_PLATFORMS', 'default')}]: {note}")
    if got is None:
        print(json.dumps({
            "metric": METRIC, "value": 0.0, "unit": "reads/s", "vs_baseline": 0.0,
            "error": "; ".join(notes),
        }))
        return 0
    # Upgrade attempt: same platform, Pallas dispatch ON.  Only replaces
    # the landed result if it finishes AND serves faster.  Budget at
    # least 1.5x the landed run's wall clock + compile margin, so a
    # healthy-but-slower Pallas run isn't misreported as a hang — but
    # never push total parent wall clock past the pre-upgrade worst case
    # (t_tpu + t_retry + t_cpu): an outer harness deadline calibrated to
    # that envelope must not kill us mid-upgrade and lose the landed
    # number.
    total_left = (t_tpu + t_retry + t_cpu) - (time.time() - _T0)
    if (got.get("platform") in ("tpu", "axon") and args.pallas == "auto"
            and not args.no_pallas_upgrade):
        t_pallas = max(t_pallas, int(land_wall * 1.5) + 120)
        t_pallas = int(min(t_pallas, total_left))
        if t_pallas >= 180:
            up, unote = _run_attempt(
                smoke + keyarg + ["--pallas", "on"], {}, t_pallas
            )
            if up is not None and up.get("value", 0) > got.get("value", 0):
                up["pallas_upgrade"] = (
                    f"+{(up['value'] / max(got['value'], 1) - 1) * 100:.0f}% "
                    "over XLA path"
                )
                got = up
            elif up is not None:
                got["pallas_attempt"] = (
                    f"completed but not faster ({up.get('value')} reads/s)"
                )
            else:
                got["pallas_attempt"] = f"failed: {unote}"
        else:
            got["pallas_attempt"] = "skipped: no wall-clock budget left"
    if notes:
        got["error"] = "; ".join(notes) + " (recovered)"
    print(json.dumps(got))
    return 0


# ---------------------------------------------------------------------------
# child: the measured workload
# ---------------------------------------------------------------------------
def child(args):
    import faulthandler

    # a hang now dumps the stuck Python stack every 180 s instead of
    # burning the whole parent timeout silently (r2 VERDICT weak #1)
    faulthandler.dump_traceback_later(180, repeat=True, file=sys.stderr)

    phases = {}

    class phase:
        def __init__(self, name):
            self.name = name

        def __enter__(self):
            log(f"phase {self.name}: start")
            self.t = time.perf_counter()
            return self

        def __exit__(self, *exc):
            dt = time.perf_counter() - self.t
            phases[self.name] = round(dt, 2)
            log(f"phase {self.name}: done in {dt:.1f}s")

    with phase("import_jax"):
        import jax

    # The axon site wrapper initializes the TPU backend on default-backend
    # resolution EVEN under JAX_PLATFORMS=cpu (its anti-silent-fallback
    # design); jax.config.update is honored, so mirror the env var into
    # the config before any backend resolution (same trick as
    # tests/conftest.py).
    want = os.environ.get("JAX_PLATFORMS")
    if want and "," not in want:
        jax.config.update("jax_platforms", want)

    from antidote_tpu.config import AntidoteConfig, enable_compilation_cache

    enable_compilation_cache()
    from antidote_tpu.crdt import get_type
    from antidote_tpu.store import TypedTable

    n_keys = args.keys or (20_000 if args.smoke else 1_000_000)
    n_shards = 8
    ops_per_key = 3
    pop_batch = 16384
    serve_batch = 16384 if n_keys >= 100_000 else 4096
    device_batch = 4096
    serve_batches = 20 if args.smoke else 60
    device_batches = 100 if args.smoke else 400
    baseline_reads = 500 if args.smoke else 2000
    hist_every = 5  # 1 in 5 serving batches reads at a historical VC

    with phase("backend_init"):
        platform = jax.default_backend()
        n_dev = len(jax.devices())
    if args.pallas == "auto":
        use_pallas = platform in ("tpu", "axon")
    else:
        use_pallas = args.pallas == "on"
    cfg = AntidoteConfig(
        n_shards=n_shards,
        max_dcs=4,
        ops_per_key=16,
        snap_versions=2,
        set_slots=16,
        keys_per_table=(n_keys + n_shards - 1) // n_shards,
        # fine write buckets + exact serve buckets: a 1k-op Zipfian append
        # (with its hot-key GC chunking) must not pad to the 16k serve
        # shape — that padded the per-chunk head fold 16x (r4 mixed-load
        # collapse, VERDICT item 2)
        batch_buckets=(256, 1024, 4096, 8192, 16384),
        use_pallas=use_pallas,
    )
    ty = get_type("set_aw")
    rng = np.random.default_rng(7)
    d = cfg.max_dcs
    bw = ty.eff_b_width(cfg)
    log(f"child: platform={platform} devices={n_dev} n_keys={n_keys} "
        f"shards={n_shards} use_pallas={use_pallas}")
    n_rows = (n_keys + n_shards - 1) // n_shards
    table = TypedTable(ty, cfg, n_rows=n_rows, n_shards=n_shards)
    for s in range(n_shards):
        table.used_rows[s] = (n_keys - s + n_shards - 1) // n_shards

    def srows(keys):
        return keys % n_shards, keys // n_shards

    # ---- populate: ops_per_key adds per key (+ removes on 10% of keys) ----
    keys = np.repeat(np.arange(n_keys, dtype=np.int64), ops_per_key)
    rng.shuffle(keys)
    elems = rng.integers(1, 1 << 62, size=keys.shape[0], dtype=np.int64)
    total = keys.shape[0]
    lane0 = np.arange(1, total + 1, dtype=np.int32)  # commit order on lane 0
    # first-seen add per key (removes observe it)
    first_idx = np.full(n_keys, -1, np.int64)
    rev = np.arange(total - 1, -1, -1)
    first_idx[keys[rev]] = rev
    valid_first = first_idx >= 0
    first_add_vc = np.zeros(n_keys, np.int32)
    first_add_elem = np.zeros(n_keys, np.int64)
    first_add_vc[valid_first] = lane0[first_idx[valid_first]]
    first_add_elem[valid_first] = elems[first_idx[valid_first]]

    with phase("populate"):
        zeros_b = np.zeros((pop_batch, bw), np.int32)
        for lo in range(0, total, pop_batch):
            hi = min(lo + pop_batch, total)
            m = hi - lo
            vcs = np.zeros((m, d), np.int32)
            vcs[:, 0] = lane0[lo:hi]
            ss, rr = srows(keys[lo:hi])
            table.append(ss, rr, elems[lo:hi, None], zeros_b[:m], vcs,
                         np.zeros(m, np.int32))
            if (lo // pop_batch) % 50 == 0:
                log(f"populate: {hi}/{total}")
        clock0 = total
        rm_keys = rng.choice(n_keys, size=n_keys // 10, replace=False).astype(np.int64)
        rm_keys = rm_keys[valid_first[rm_keys]]
        nrm = rm_keys.shape[0]
        for lo in range(0, nrm, pop_batch):
            hi = min(lo + pop_batch, nrm)
            m = hi - lo
            kk = rm_keys[lo:hi]
            eff_b = np.zeros((m, bw), np.int32)
            eff_b[:, 0] = 1
            eff_b[:, 1] = first_add_vc[kk]
            vcs = np.zeros((m, d), np.int32)
            vcs[:, 0] = clock0 + 1 + lo + np.arange(m, dtype=np.int32)
            ss, rr = srows(kk)
            table.append(ss, rr, first_add_elem[kk, None], eff_b, vcs,
                         np.zeros(m, np.int32))
        final_t = clock0 + nrm
        final_clock = np.zeros(d, np.int32)
        final_clock[0] = final_t
        # pin the serving epoch at the loaded snapshot — the GST pin a
        # serving deployment performs; mixed-phase reads at final_clock
        # stay pure gathers while appends advance the live head
        table.publish_epoch()
        mid_t = int(total * 0.6)  # historical point: 60% through the add stream
        mid_clock = np.zeros(d, np.int32)
        mid_clock[0] = mid_t
        log(f"populate: {total + nrm} ops total")

    # ---- host Zipfian sampler (the serving path routes on host) ----
    w = 1.0 / np.arange(1, n_keys + 1, dtype=np.float64) ** 1.0
    cdf = np.cumsum(w / w.sum())

    def sample(size):
        return np.searchsorted(cdf, rng.random(size)).astype(np.int64)

    # =======================================================================
    # measured 1: SERVING PATH — flat one-gather serving read end to end
    # (read_resolved_flat: no [P, M'] routing/unrouting on the host —
    #  r3 VERDICT weak #3 closed this serving-vs-kernel gap)
    # =======================================================================
    vc_final_b = np.broadcast_to(final_clock, (serve_batch, d))
    vc_mid_b = np.broadcast_to(mid_clock, (serve_batch, d))
    # pre-generated key stream: the workload generator is not the system
    # under test (basho_bench pre-computes its keygen distributions too)
    n_streams = 37
    streams = [sample(serve_batch) for _ in range(n_streams)]

    def serve_one(i):
        kk = streams[i % n_streams]
        ss, rr = srows(kk)
        vcs = vc_mid_b if (i % hist_every == hist_every - 1) else vc_final_b
        return table.read_resolved_flat(ss, rr, vcs)

    def fold_snap():
        return dict(table.fold_dispatches)

    def fold_delta(before, after):
        keys = set(before) | set(after)
        d_ = {k: after.get(k, 0) - before.get(k, 0) for k in sorted(keys)}
        return {k: v for k, v in d_.items() if v}

    fold_pre_serve = fold_snap()
    # warmup/compile both VC variants; timed separately so a compile hang
    # (vs execute hang) localizes itself in the logs
    with phase("warmup_serve_fresh"):
        resolved, fresh, complete = serve_one(0)
        np.asarray(resolved["top"])
    with phase("warmup_serve_hist"):
        resolved, fresh, complete = serve_one(hist_every - 1)
        np.asarray(resolved["top"])
    # unpipelined per-batch latency
    lat = []
    stale_hist = []
    with phase("serve_latency"):
        for i in range(6):
            tb = time.perf_counter()
            resolved, fresh, complete = serve_one(i)
            np.asarray(resolved["top"]), np.asarray(resolved["count"])
            lat.append(time.perf_counter() - tb)
            log(f"serve_latency batch {i}: {lat[-1] * 1e3:.1f}ms")
            if i % hist_every == hist_every - 1:
                stale_hist.append(1.0 - np.asarray(fresh).mean())
    lat_ms = np.asarray(lat) * 1e3
    # pipelined throughput (≈ basho_bench's concurrent workers)
    import collections

    q = collections.deque()
    depth = 8
    with phase("serve_pipeline"):
        t0 = time.perf_counter()
        for i in range(serve_batches):
            resolved, fresh, complete = serve_one(i)
            for x in resolved.values():
                x.copy_to_host_async()
            q.append(resolved)
            if len(q) > depth:
                old = q.popleft()
                np.asarray(old["top"])
            if i % 10 == 9:
                log(f"serve_pipeline: {i + 1}/{serve_batches}")
        while q:
            np.asarray(q.popleft()["top"])
        serve_elapsed = time.perf_counter() - t0
    fold_serve = fold_delta(fold_pre_serve, fold_snap())
    # fresh-vs-historical latency split: the 1-in-hist_every batch is the
    # one that pays the ring fold (strategy-dispatched); the rest resolve
    # off the head and show the strategy-independent floor
    lat_fresh_ms = [lat[i] * 1e3 for i in range(len(lat))
                    if i % hist_every != hist_every - 1]
    lat_hist_ms = [lat[i] * 1e3 for i in range(len(lat))
                   if i % hist_every == hist_every - 1]
    serving_rps = serve_batches * serve_batch / serve_elapsed
    log(f"serving path: {serving_rps:,.0f} reads/s "
        f"(batch={serve_batch}, hist 1/{hist_every}, "
        f"stale_frac_hist={np.mean(stale_hist):.2f}, "
        f"batch p50={np.percentile(lat_ms, 50):.1f}ms)")

    # =======================================================================
    # measured 2: DEVICE KERNEL — head gather + presence resolve on device
    # =======================================================================
    import jax.numpy as jnp

    cdf_dev = jnp.asarray(cdf, jnp.float32)
    he, ha, hr, ho = (table.head["elems"], table.head["addvc"],
                      table.head["rmvc"], table.head["ovf"])

    @jax.jit
    def device_step(prng, cdf_d, elems_h, addvc_h, rmvc_h, ovf_h):
        prng, sub = jax.random.split(prng)
        u = jax.random.uniform(sub, (device_batch,))
        kk = jnp.searchsorted(cdf_d, u)
        s, r = kk % n_shards, kk // n_shards
        state = {
            "elems": elems_h[s, r], "addvc": addvc_h[s, r],
            "rmvc": rmvc_h[s, r], "ovf": ovf_h[s, r],
        }
        out = ty.resolve(cfg, state)
        return prng, jnp.concatenate(
            [out["top"], out["count"][:, None].astype(jnp.int64)], axis=-1
        )

    prng = jax.random.PRNGKey(3)
    with phase("warmup_device_kernel"):
        for _ in range(3):
            prng, ev = device_step(prng, cdf_dev, he, ha, hr, ho)
            np.asarray(ev)
    rtt = []
    with phase("device_latency"):
        for _ in range(5):
            tb = time.perf_counter()
            prng, ev = device_step(prng, cdf_dev, he, ha, hr, ho)
            np.asarray(ev)
            rtt.append(time.perf_counter() - tb)
    rtt_ms = np.asarray(rtt) * 1e3
    q = collections.deque()
    depth = 32
    with phase("device_pipeline"):
        t0 = time.perf_counter()
        for i in range(device_batches):
            prng, ev = device_step(prng, cdf_dev, he, ha, hr, ho)
            ev.copy_to_host_async()
            q.append(ev)
            if len(q) > depth:
                np.asarray(q.popleft())
            if i % 100 == 99:
                log(f"device_pipeline: {i + 1}/{device_batches}")
        while q:
            np.asarray(q.popleft())
        device_elapsed = time.perf_counter() - t0
    device_rps = device_batches * device_batch / device_elapsed
    log(f"device kernel: {device_rps:,.0f} reads/s  "
        f"rtt p50={np.percentile(rtt_ms, 50):.2f}ms")

    # =======================================================================
    # baseline: sequential host materializer (reference-style walk)
    # =======================================================================
    with phase("baseline_build"):
        ops_by_key = {}
        for i in range(total):
            ops_by_key.setdefault(int(keys[i]), []).append(
                ({"dc0": int(lane0[i])}, "add", int(elems[i]))
            )
        for j in range(nrm):
            k = int(rm_keys[j])
            ops_by_key.setdefault(k, []).append(
                ({"dc0": int(clock0 + 1 + j)}, "rm",
                 (int(first_add_elem[k]), {"dc0": int(first_add_vc[k])}))
            )

    def baseline_read(k, read_vc_dict):
        # the reference fold: per-op dict-VC dominance check, then apply
        adds, rms = {}, {}
        for op_vc, kind, payload in ops_by_key.get(k, ()):
            included = all(op_vc.get(dc, 0) <= read_vc_dict.get(dc, 0)
                           for dc in op_vc)
            if not included:
                continue
            if kind == "add":
                e = payload
                cur = adds.setdefault(e, {})
                for dc, t in op_vc.items():
                    cur[dc] = max(cur.get(dc, 0), t)
            else:
                e, obs = payload
                cur = rms.setdefault(e, {})
                for dc, t in obs.items():
                    cur[dc] = max(cur.get(dc, 0), t)
        return [e for e, avc in adds.items()
                if any(t > rms.get(e, {}).get(dc, 0) for dc, t in avc.items())]

    final_vc_dict = {"dc0": final_t}
    mid_vc_dict = {"dc0": mid_t}
    bkeys = sample(baseline_reads)
    with phase("baseline_run"):
        t0 = time.perf_counter()
        for k in bkeys:
            baseline_read(int(k), final_vc_dict)
        base_rps = baseline_reads / (time.perf_counter() - t0)
    log(f"baseline(host python per-key fold): {base_rps:,.0f} reads/s")

    # ---- correctness spot-check: serving values == host materializer ----
    with phase("spot_check"):
        for at_clock, at_dict, tag in (
            (final_clock, final_vc_dict, "final"),
            (mid_clock, mid_vc_dict, "historical"),
        ):
            chk = bkeys[:32].astype(np.int64)
            ss, rr = srows(chk)
            out, fresh, complete = table.read_resolved(
                ss, rr, np.broadcast_to(at_clock, (32, d))
            )
            assert complete.all()
            for i, k in enumerate(chk):
                ref = sorted(baseline_read(int(k), at_dict))
                cnt = int(out["count"][i])
                dev = sorted(int(e) for e in out["top"][i] if e != 0)
                assert cnt == len(ref), (tag, int(k), cnt, len(ref))
                if cnt <= ty.resolve_top:
                    assert dev == ref, (tag, int(k), dev, ref)
    log("spot-check: serving values match host materializer "
        "(fresh + historical) on 64 keys")

    # ---- mixed load: appends (with ring-GC folds) interleave the serve
    # pipeline — the r3 VERDICT asked for append/GC measured UNDER load,
    # not only correctness-tested (run LAST: the writes advance the table
    # past the clocks the earlier phases and the spot check read at)
    write_batch = max(256, serve_batch // 16)
    mixed_batches = max(8, serve_batches)
    writes = 0

    def mixed_append(i):
        nonlocal writes
        kk = streams[(i * 7 + 3) % n_streams][:write_batch]
        ss, rr = srows(kk)
        vcs = np.zeros((write_batch, d), np.int32)
        vcs[:, 0] = final_t + writes + 1 + np.arange(write_batch)
        table.append(ss, rr,
                     rng.integers(1, 1 << 62, size=(write_batch, 1),
                                  dtype=np.int64),
                     np.zeros((write_batch, bw), np.int32), vcs,
                     np.zeros(write_batch, np.int32))
        writes += write_batch

    fold_pre_mixed = fold_snap()
    with phase("warmup_mixed"):
        # compile the append/GC/stale-serve shapes outside the timer —
        # several appends, because Zipfian hot-key chunking exercises a
        # family of (row-bucket, fold-window) shapes, not one
        for wi in range(6):
            mixed_append(-1 - wi)
        r0, _, _ = serve_one(0)
        np.asarray(r0["top"])
    with phase("mixed_load"):
        mq = collections.deque()
        t0 = time.perf_counter()
        for i in range(mixed_batches):
            mixed_append(i)
            resolved, fresh, complete = serve_one(i)  # reads at old final
            for x in resolved.values():
                x.copy_to_host_async()
            mq.append(resolved)
            if len(mq) > 8:
                np.asarray(mq.popleft()["top"])
        while mq:
            np.asarray(mq.popleft()["top"])
        mixed_elapsed = time.perf_counter() - t0
    fold_mixed = fold_delta(fold_pre_mixed, fold_snap())
    mixed_read_rps = mixed_batches * serve_batch / mixed_elapsed
    mixed_write_rps = (writes - 6 * write_batch) / mixed_elapsed  # minus warmup
    log(f"mixed load: {mixed_read_rps:,.0f} reads/s + "
        f"{mixed_write_rps:,.0f} appends/s sustained")

    print(json.dumps({
        "metric": METRIC,
        "value": round(serving_rps, 1),
        "unit": "reads/s",
        "vs_baseline": round(serving_rps / base_rps, 2),
        "device_kernel_reads_per_s": round(device_rps, 1),
        "device_vs_baseline": round(device_rps / base_rps, 2),
        "baseline_reads_per_s": round(base_rps, 1),
        "baseline_kind": "python_host_per_key_fold",
        "n_keys": n_keys,
        "serve_batch": serve_batch,
        "historical_batch_every": hist_every,
        "stale_fraction_historical": round(float(np.mean(stale_hist)), 3),
        "serve_batch_p50_ms": round(float(np.percentile(lat_ms, 50)), 2),
        "serve_batch_p99_ms": round(float(np.percentile(lat_ms, 99)), 2),
        "mixed_read_rps": round(mixed_read_rps, 1),
        "mixed_write_rps": round(mixed_write_rps, 1),
        "device_rtt_p50_ms": round(float(np.percentile(rtt_ms, 50)), 2),
        "use_pallas": bool(cfg.use_pallas),
        "platform": platform,
        "fold_stage": {
            # what the store's strategy picker routed the serving ring
            # fold to, and how often each phase actually dispatched it
            # (warmups included — they compile the same families)
            "serving_strategy": table._fold_strategy(),
            "dispatch_serve": fold_serve,
            "dispatch_mixed": fold_mixed,
            "serve_batch_fresh_ms_p50": round(
                float(np.percentile(lat_fresh_ms, 50)), 2),
            "serve_batch_hist_ms": [round(x, 2) for x in lat_hist_ms],
        },
        "phases_s": phases,
    }))
    return 0


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--smoke", action="store_true", help="small, fast run")
    ap.add_argument("--keys", type=int, default=None)
    ap.add_argument("--pallas", choices=("auto", "on", "off"), default="auto",
                    help="force the Pallas in-path dispatch on/off "
                         "(auto = on iff TPU)")
    ap.add_argument("--no-pallas-upgrade", action="store_true",
                    help="parent: skip the Pallas upgrade attempt")
    ap.add_argument("--child", action="store_true",
                    help="internal: run the measured workload in-process")
    args = ap.parse_args()
    if args.child:
        sys.exit(child(args))
    sys.exit(parent(args))


if __name__ == "__main__":
    main()
