#!/usr/bin/env python
"""North-star benchmark: snapshot-read throughput on a 1M-key OR-set.

The BASELINE.json workload: ``antidote_crdt_set_aw`` with Zipfian access,
batched snapshot reads at the current VC through the device materializer
(per-key op-ring fold + VC dominance filtering), vs a sequential host
materializer that re-implements the reference's per-key walk
(clocksi_materializer:materialize_intern + apply_operations,
/root/reference/src/clocksi_materializer.erl:111-197) in plain Python with
dict vector clocks — the closest stand-in for the BEAM fold this machine
can run (`vs_baseline` is the speedup over it).

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": "reads/s", "vs_baseline": N, ...}

Usage: python bench.py [--smoke]
"""

from __future__ import annotations

import argparse
import json
import sys
import time

import numpy as np


def log(*a):
    print(*a, file=sys.stderr, flush=True)


def zipf_sampler(n_keys: int, s: float, rng):
    w = 1.0 / np.arange(1, n_keys + 1, dtype=np.float64) ** s
    cdf = np.cumsum(w / w.sum())

    def sample(size):
        return np.searchsorted(cdf, rng.random(size)).astype(np.int64)

    return sample


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--smoke", action="store_true", help="small, fast run")
    ap.add_argument("--keys", type=int, default=None)
    args = ap.parse_args()

    import jax

    from antidote_tpu.config import AntidoteConfig
    from antidote_tpu.crdt import get_type
    from antidote_tpu.store import TypedTable

    n_keys = args.keys or (20_000 if args.smoke else 1_000_000)
    ops_per_key = 3
    read_batch = 4096
    timed_batches = 100 if args.smoke else 400
    pop_batch = 16384
    baseline_reads = 500 if args.smoke else 2000

    cfg = AntidoteConfig(
        n_shards=1,
        max_dcs=4,
        ops_per_key=16,
        snap_versions=2,
        set_slots=16,
        keys_per_table=n_keys,
        batch_buckets=(read_batch, pop_batch),
    )
    ty = get_type("set_aw")
    rng = np.random.default_rng(7)
    d = cfg.max_dcs
    bw = ty.eff_b_width(cfg)

    log(f"bench: platform={jax.devices()[0].platform} n_keys={n_keys}")
    table = TypedTable(ty, cfg, n_rows=n_keys, n_shards=1)
    table.used_rows[0] = n_keys  # rows pre-bound: row == key

    # ---- populate: ops_per_key adds per key (+ removes on 10% of keys) ----
    keys = np.repeat(np.arange(n_keys, dtype=np.int64), ops_per_key)
    rng.shuffle(keys)
    elems = rng.integers(1, 1 << 62, size=keys.shape[0], dtype=np.int64)
    total = keys.shape[0]
    # per-op commit VC: lane 0 strictly increasing in commit order
    lane0 = np.arange(1, total + 1, dtype=np.int32)
    # remember the add VC of the first-seen add per key (for removes)
    first_add_vc = np.zeros(n_keys, np.int32)
    first_add_elem = np.zeros(n_keys, np.int64)
    seen_first = np.zeros(n_keys, bool)
    firsts = ~seen_first[keys]
    # compute first occurrence of each key in the shuffled stream
    first_idx = np.full(n_keys, -1, np.int64)
    rev = np.arange(total - 1, -1, -1)
    first_idx[keys[rev]] = rev  # later writes win => first occurrence
    valid_first = first_idx >= 0
    first_add_vc[valid_first] = lane0[first_idx[valid_first]]
    first_add_elem[valid_first] = elems[first_idx[valid_first]]

    t0 = time.perf_counter()
    zeros_b = np.zeros((pop_batch, bw), np.int32)
    for lo in range(0, total, pop_batch):
        hi = min(lo + pop_batch, total)
        m = hi - lo
        vcs = np.zeros((m, d), np.int32)
        vcs[:, 0] = lane0[lo:hi]
        table.append(
            np.zeros(m, np.int64),
            keys[lo:hi],
            elems[lo:hi, None],
            zeros_b[:m],
            vcs,
            np.zeros(m, np.int32),
        )
    clock0 = total
    # removes: 10% of keys lose their first-added element
    rm_keys = rng.choice(n_keys, size=n_keys // 10, replace=False).astype(np.int64)
    rm_keys = rm_keys[valid_first[rm_keys]]
    nrm = rm_keys.shape[0]
    for lo in range(0, nrm, pop_batch):
        hi = min(lo + pop_batch, nrm)
        m = hi - lo
        kk = rm_keys[lo:hi]
        eff_b = np.zeros((m, bw), np.int32)
        eff_b[:, 0] = 1  # remove
        eff_b[:, 1] = first_add_vc[kk]  # observed add dot on lane 0
        vcs = np.zeros((m, d), np.int32)
        vcs[:, 0] = clock0 + 1 + lo + np.arange(m, dtype=np.int32)
        table.append(
            np.zeros(m, np.int64),
            kk,
            first_add_elem[kk, None],
            eff_b,
            vcs,
            np.zeros(m, np.int32),
        )
    final_clock = np.zeros(d, np.int32)
    final_clock[0] = clock0 + nrm
    log(f"populate: {total + nrm} ops in {time.perf_counter() - t0:.1f}s")

    # ---- measured: Zipfian batched snapshot reads ----
    # The timed loop is device-resident: Zipfian key sampling (inverse CDF),
    # head-state gather, and OR-set presence resolution all run on device;
    # the per-batch host↔device traffic is only the returned values.  (The
    # dev tunnel to the chip has ~50 ms fixed host→device latency, which
    # would otherwise measure the tunnel, not the materializer.)
    import jax.numpy as jnp

    w = 1.0 / np.arange(1, n_keys + 1, dtype=np.float64) ** 1.0
    cdf = jnp.asarray(np.cumsum(w / w.sum()), jnp.float32)

    @jax.jit
    def read_step(prng, cdf, head_elems, head_addvc, head_rmvc):
        prng, sub = jax.random.split(prng)
        u = jax.random.uniform(sub, (read_batch,))
        kk = jnp.searchsorted(cdf, u)
        elems = head_elems[0, kk]                      # [B, E]
        present = jnp.any(head_addvc[0, kk] > head_rmvc[0, kk], axis=-1)
        present = present & (elems != 0)
        # compact the value view: up to 4 present elements + true count
        # (keys needing more re-fetch the full row; none in this workload)
        order = jnp.argsort(~present, axis=-1, stable=True)[:, :4]
        top = jnp.take_along_axis(jnp.where(present, elems, 0), order, axis=-1)
        out = jnp.concatenate(
            [top, present.sum(-1, keepdims=True).astype(jnp.int64)], axis=-1
        )
        return prng, out

    # reads at the current VC are exact via the head (verify once)
    hvc = np.asarray(table.head_vc[0, :64])
    assert (hvc <= final_clock).all()

    prng = jax.random.PRNGKey(3)
    he, ha, hr = table.head["elems"], table.head["addvc"], table.head["rmvc"]
    for _ in range(3):  # warmup/compile
        prng, ev = read_step(prng, cdf, he, ha, hr)
        np.asarray(ev)
    # single-request round-trip latency (includes the dev tunnel's ~100 ms
    # fixed RTT; a real PCIe host would see microseconds here)
    lat = []
    for _ in range(5):
        tb = time.perf_counter()
        prng, ev = read_step(prng, cdf, he, ha, hr)
        np.asarray(ev)
        lat.append(time.perf_counter() - tb)
    lat_ms = np.asarray(lat) * 1e3
    # throughput: pipelined async value fetches — the moral equivalent of
    # basho_bench's 100 concurrent workers keeping the server busy
    import collections

    q = collections.deque()
    depth = 32
    t0 = time.perf_counter()
    for _ in range(timed_batches):
        prng, ev = read_step(prng, cdf, he, ha, hr)
        ev.copy_to_host_async()
        q.append(ev)
        if len(q) > depth:
            np.asarray(q.popleft())
    while q:
        np.asarray(q.popleft())
    elapsed = time.perf_counter() - t0
    tpu_rps = timed_batches * read_batch / elapsed
    log(f"device: {tpu_rps:,.0f} reads/s  rtt p50={np.percentile(lat_ms, 50):.2f}ms")

    # correctness spot-check: head values match the host materializer
    sample = zipf_sampler(n_keys, 1.0, rng)

    # ---- baseline: sequential host materializer (reference-style walk) ----
    ops_by_key = {}
    for i in range(total):
        ops_by_key.setdefault(int(keys[i]), []).append(
            ({"dc0": int(lane0[i])}, "add", int(elems[i]))
        )
    for j in range(nrm):
        k = int(rm_keys[j])
        ops_by_key.setdefault(k, []).append(
            ({"dc0": int(clock0 + 1 + j)}, "rm",
             (int(first_add_elem[k]), {"dc0": int(first_add_vc[k])}))
        )
    read_vc_dict = {"dc0": int(final_clock[0])}

    def baseline_read(k):
        # the reference fold: per-op dict-VC dominance check, then apply
        adds, rms = {}, {}
        for op_vc, kind, payload in ops_by_key.get(k, ()):
            included = all(op_vc.get(dc, 0) <= read_vc_dict.get(dc, 0)
                           for dc in op_vc)
            if not included:
                continue
            if kind == "add":
                e = payload
                cur = adds.setdefault(e, {})
                for dc, t in op_vc.items():
                    cur[dc] = max(cur.get(dc, 0), t)
            else:
                e, obs = payload
                cur = rms.setdefault(e, {})
                for dc, t in obs.items():
                    cur[dc] = max(cur.get(dc, 0), t)
        return [e for e, avc in adds.items()
                if any(t > rms.get(e, {}).get(dc, 0) for dc, t in avc.items())]

    bkeys = sample(baseline_reads)
    t0 = time.perf_counter()
    for k in bkeys:
        baseline_read(int(k))
    base_rps = baseline_reads / (time.perf_counter() - t0)
    log(f"baseline(host python per-key fold): {base_rps:,.0f} reads/s")

    # correctness spot-check: device head values == host materializer values
    chk = bkeys[:32].astype(np.int64)
    state, fresh = table.read_latest(
        np.zeros(32, np.int64), chk, np.broadcast_to(final_clock, (32, d))
    )
    assert fresh.all()
    for i, k in enumerate(chk):
        pres = (state["addvc"][i] > state["rmvc"][i]).any(-1) & (
            state["elems"][i] != 0
        )
        dev = sorted(int(e) for e, p in zip(state["elems"][i], pres) if p)
        ref = sorted(baseline_read(int(k)))
        assert dev == ref, (int(k), dev, ref)
    log("spot-check: device values match host materializer on 32 keys")

    print(json.dumps({
        "metric": "snapshot_read_throughput_set_aw_zipf",
        "value": round(tpu_rps, 1),
        "unit": "reads/s",
        "vs_baseline": round(tpu_rps / base_rps, 2),
        "n_keys": n_keys,
        "read_batch": read_batch,
        "p50_ms": round(float(np.percentile(lat_ms, 50)), 3),
        "p99_ms": round(float(np.percentile(lat_ms, 99)), 3),
        "baseline_reads_per_s": round(base_rps, 1),
        "baseline_kind": "python_host_per_key_fold",
        "platform": jax.devices()[0].platform,
    }))


if __name__ == "__main__":
    main()
