"""Multi-node-per-DC clustering (r2 VERDICT item 7), in-process tier.

A 2-member DC over real intra-DC RPC sockets: cross-member transactions
(coordinator on either member), sequencer-chained commit clocks,
first-committer-wins certification across members, stable-time
aggregation, and inter-DC replication from/to a clustered DC.  The
4-OS-process CT-style suite builds on this in test_cluster_processes.py.
"""

import threading

import numpy as np
import pytest

from antidote_tpu.cluster import (ClusterMember, ClusterNode, attach_interdc,
                                  cluster_query_router, fabric_id_of)
from antidote_tpu.config import AntidoteConfig
from antidote_tpu.txn.manager import AbortError


def _cfg(**kw):
    base = dict(n_shards=4, max_dcs=3, ops_per_key=8, keys_per_table=64,
                batch_buckets=(16, 64))
    base.update(kw)
    return AntidoteConfig(**base)


@pytest.fixture
def duo():
    cfg = _cfg()
    m0 = ClusterMember(cfg, dc_id=0, member_id=0, n_members=2)
    m1 = ClusterMember(cfg, dc_id=0, member_id=1, n_members=2)
    m0.connect(1, *m1.address)
    m1.connect(0, *m0.address)
    yield cfg, m0, m1
    m0.close(), m1.close()


def test_cross_member_txn_and_reads(duo):
    cfg, m0, m1 = duo
    n0, n1 = ClusterNode(m0), ClusterNode(m1)
    # shard of int key k is k % 4; members own {0,2} and {1,3}
    assert sorted(m0.shards) == [0, 2] and sorted(m1.shards) == [1, 3]
    # one txn from member 0 touches BOTH members' shards
    vc = n0.update_objects([
        (0, "counter_pn", "b", ("increment", 5)),   # shard 0 -> m0
        (1, "counter_pn", "b", ("increment", 7)),   # shard 1 -> m1
        (3, "set_aw", "b", ("add_all", ["x", "y"])),  # shard 3 -> m1
    ])
    assert int(vc[0]) == 1  # first DC timestamp
    # both coordinators read the same values at the commit clock
    for n in (n0, n1):
        n.member.refresh_peer_clocks()
        vals, _ = n.read_objects([
            (0, "counter_pn", "b"), (1, "counter_pn", "b"),
            (3, "set_aw", "b"),
        ], clock=vc)
        assert vals[0] == 5 and vals[1] == 7
        assert sorted(vals[2]) == ["x", "y"]


def test_observed_remove_generates_at_owner(duo):
    cfg, m0, m1 = duo
    n0 = ClusterNode(m0)
    vc = n0.update_objects([(1, "set_aw", "b", ("add_all", ["a", "b"]))])
    m0.refresh_peer_clocks()
    # remove needs the owner's state (observed add dots live on m1)
    vc2 = n0.update_objects([(1, "set_aw", "b", ("remove", "a"))],
                            clock=vc)
    m0.refresh_peer_clocks()
    vals, _ = n0.read_objects([(1, "set_aw", "b")], clock=vc2)
    assert vals[0] == ["b"]


def test_cross_member_certification(duo):
    cfg, m0, m1 = duo
    n0, n1 = ClusterNode(m0), ClusterNode(m1)
    # two coordinators race on the SAME key owned by m1
    t0 = n0.start_transaction()
    t1 = n1.start_transaction()
    n0.update_objects([(1, "counter_pn", "b", ("increment", 1))], t0)
    n1.update_objects([(1, "counter_pn", "b", ("increment", 1))], t1)
    n0.commit_transaction(t0)
    with pytest.raises(AbortError):
        n1.commit_transaction(t1)
    m0.refresh_peer_clocks()
    vals, _ = n0.read_objects([(1, "counter_pn", "b")])
    assert vals[0] == 1


def test_commit_clock_chains_apply_in_order(duo):
    """Concurrent coordinators' commits on one shard apply in ts order
    even when the commit fan-outs interleave (the sequencer's per-shard
    prev-ts chain gates application)."""
    cfg, m0, m1 = duo
    n0, n1 = ClusterNode(m0), ClusterNode(m1)
    errs = []
    final_vcs = [None, None]

    def worker(n, lo):
        try:
            for i in range(10):
                # distinct keys per worker on the SAME shards (1 and 2):
                # concurrent timestamps on one shard chain, zero cert
                # conflicts — interleaved commit fan-outs must still
                # apply in ts order
                final_vcs[lo] = n.update_objects([
                    (1 + 4 * (lo + 1), "counter_pn", "b", ("increment", 1)),
                    (2 + 4 * (lo + 1), "counter_pn", "b", ("increment", 1)),
                ])
        except Exception as e:  # pragma: no cover
            errs.append(repr(e))

    ts = [threading.Thread(target=worker, args=(n, i))
          for i, n in enumerate((n0, n1))]
    for t in ts:
        t.start()
    for t in ts:
        t.join(timeout=60)
    assert not errs, errs
    m0.refresh_peer_clocks()
    m1.refresh_peer_clocks()
    at = np.maximum(final_vcs[0], final_vcs[1])
    vals, _ = n0.read_objects([(5, "counter_pn", "b"),
                               (9, "counter_pn", "b"),
                               (6, "counter_pn", "b"),
                               (10, "counter_pn", "b")], clock=at)
    assert vals == [10, 10, 10, 10]
    # chains drained: every shard's applied own-ts reached the
    # sequencer's frontier for it, nothing buffered
    assert m0.seq.counter == 20
    for m in (m0, m1):
        for s in m.shards:
            assert not m.chain_wait[s], (s, m.chain_wait[s])
            assert m.applied_ts[s] == m0.seq.last_ts.get(s, 0)


def test_stable_aggregation_and_snapshot_safety(duo):
    cfg, m0, m1 = duo
    n0, n1 = ClusterNode(m0), ClusterNode(m1)
    vc = n0.update_objects([(1, "counter_pn", "b", ("increment", 1))])
    # after gossip + the idle-shard safe-time advance, every member's
    # aggregated stable reaches the sequencer frontier: a clock-pinned
    # read on the OTHER member resolves without any inter-DC traffic
    m0.refresh_peer_clocks()
    m1.refresh_peer_clocks()
    assert int(m0.stable_vc()[0]) == 1
    assert int(m1.stable_vc()[0]) == 1
    vals, _ = n1.read_objects([(1, "counter_pn", "b")], clock=vc)
    assert vals[0] == 1
    # the stable snapshot never claims remote-DC state it has not seen
    assert int(m0.stable_vc()[1]) == 0 and int(m0.stable_vc()[2]) == 0
    # and never overshoots the sequencer frontier
    assert int(m0.stable_vc()[0]) <= m0.seq.counter


def test_interdc_from_clustered_dc():
    """DC0 = 2 members, DC1 = single node; replication flows both ways
    with per-member chains and catch-up routing."""
    from antidote_tpu.api.node import AntidoteNode
    from antidote_tpu.interdc.replica import DCReplica
    from antidote_tpu.interdc.transport import LoopbackHub

    cfg = _cfg()
    hub = LoopbackHub()
    m0 = ClusterMember(cfg, dc_id=0, member_id=0, n_members=2)
    m1 = ClusterMember(cfg, dc_id=0, member_id=1, n_members=2)
    m0.connect(1, *m1.address)
    m1.connect(0, *m0.address)
    r0a = attach_interdc(m0, hub)
    r0b = attach_interdc(m1, hub)
    node1 = AntidoteNode(cfg, dc_id=1)
    r1 = DCReplica(node1, hub)
    route = cluster_query_router({0: 2}, cfg.n_shards)
    r1.route_query = route
    # full mesh subscriptions
    for sub in (r0a, r0b):
        sub.observe_dc(r1)
    r1.observe_dc(r0a)
    r1.observe_dc(r0b)

    n0 = ClusterNode(m0)
    vc = n0.update_objects([
        (0, "counter_pn", "b", ("increment", 3)),
        (1, "set_aw", "b", ("add", "cross")),
    ])
    hub.pump()
    vals, _ = node1.read_objects([(0, "counter_pn", "b"),
                                  (1, "set_aw", "b")], clock=vc)
    assert vals[0] == 3 and vals[1] == ["cross"]

    # reverse direction: DC1 writes, the clustered DC0 reads causally
    vc1 = node1.update_objects([(2, "counter_pn", "b", ("increment", 9))])
    hub.pump()
    m0.refresh_peer_clocks()
    m1.refresh_peer_clocks()
    vals, _ = n0.read_objects([(2, "counter_pn", "b")], clock=vc1)
    assert vals[0] == 9

    # catch-up through the router: drop a DC0->DC1 message, heal via the
    # owning member's chain
    hub.drop_next(fabric_id_of(0, 1), 1, n=1)
    vc2 = n0.update_objects([(1, "set_aw", "b", ("add", "lost"))])
    hub.pump()
    r0b.heartbeat()
    hub.pump()
    vals, _ = node1.read_objects([(1, "set_aw", "b")], clock=vc2)
    assert sorted(vals[0]) == ["cross", "lost"]
    m0.close(), m1.close()


def test_interdc_catchup_reroutes_after_live_move():
    """Geo-replication follows live shard ownership (r5 VERDICT item 2):
    a shard moves between DC0 members WHILE DC1 subscribes; the handoff
    carries the egress chain, the new owner's stamps teach DC1 the
    (owner, epoch) route, and a lost message on the MOVED chain is
    caught up from the NEW owner — the boot-time modular router would
    still point at the old one, whose window was cleared at relinquish."""
    from antidote_tpu.api.node import AntidoteNode
    from antidote_tpu.interdc.replica import DCReplica
    from antidote_tpu.interdc.transport import LoopbackHub

    cfg = _cfg()
    hub = LoopbackHub()
    m0 = ClusterMember(cfg, dc_id=0, member_id=0, n_members=2)
    m1 = ClusterMember(cfg, dc_id=0, member_id=1, n_members=2)
    m0.connect(1, *m1.address)
    m1.connect(0, *m0.address)
    r0a = attach_interdc(m0, hub)
    r0b = attach_interdc(m1, hub)
    node1 = AntidoteNode(cfg, dc_id=1)
    r1 = DCReplica(node1, hub)
    r1.route_query = cluster_query_router({0: 2}, cfg.n_shards)
    for sub in (r0a, r0b):
        sub.observe_dc(r1)
    r1.observe_dc(r0a)
    r1.observe_dc(r0b)

    n0 = ClusterNode(m0)
    # establish chain (0, shard 0) at member 0 and replicate it
    vc = n0.update_objects([(0, "counter_pn", "b", ("increment", 3))])
    hub.pump()
    vals, _ = node1.read_objects([(0, "counter_pn", "b")], clock=vc)
    assert vals == [3]

    # live-move shard 0 from member 0 to member 1 (the two-phase legs
    # the join driver runs) — the egress chain state must travel with it
    data = m0.m_export_shard(0, 1)
    m1.m_import_shard(data)
    m0.m_relinquish_shard(0, 1)
    assert 0 in m1.shards and 0 not in m0.shards
    # the egress chain continued at the importer; the source reset
    assert int(r0b.pub_opid[0]) >= 1 and int(r0a.pub_opid[0]) == 0
    # old owner's window is gone; new owner's continues the chain
    assert len(r0a.sent[0]) == 0 and len(r0b.sent[0]) >= 1

    # DROP the next message on the moved chain: catch-up must query the
    # NEW owner's fabric id (learned from its epoch-stamped messages)
    hub.drop_next(fabric_id_of(0, 1), 1, n=1)
    n1c = ClusterNode(m1)
    vc2 = n1c.update_objects([(0, "counter_pn", "b", ("increment", 4))])
    hub.pump()
    r0b.heartbeat()  # the ping reveals the gap and carries (owner, epoch)
    hub.pump()
    assert r1.shard_route[(0, 0)][0] == 1  # DC1 learned the new owner
    vals, _ = node1.read_objects([(0, "counter_pn", "b")], clock=vc2)
    assert vals == [7]

    # and the chain keeps flowing normally from the new owner
    vc3 = n0.update_objects([(0, "counter_pn", "b", ("increment", 1))])
    hub.pump()
    vals, _ = node1.read_objects([(0, "counter_pn", "b")], clock=vc3)
    assert vals == [8]
    m0.close(), m1.close()


def test_adopt_shard_without_extras_resumes_chain_from_wal(tmp_path):
    """Rolling-upgrade shape: the handoff package carries NO interdc
    extras (pre-extras exporter).  The importer must recompute the
    egress opid from the imported WAL — resuming at 0 would make remote
    subscribers drop the new owner's first N commits as duplicates."""
    from antidote_tpu.interdc.transport import LoopbackHub
    from antidote_tpu.store import handoff as _handoff

    cfg = _cfg()
    hub = LoopbackHub()
    m0 = ClusterMember(cfg, dc_id=0, member_id=0, n_members=2,
                       log_dir=str(tmp_path / "m0"))
    m1 = ClusterMember(cfg, dc_id=0, member_id=1, n_members=2,
                       log_dir=str(tmp_path / "m1"))
    m0.connect(1, *m1.address)
    m1.connect(0, *m0.address)
    r0a = attach_interdc(m0, hub)
    r0b = attach_interdc(m1, hub)
    try:
        n0 = ClusterNode(m0)
        for _ in range(3):
            n0.update_objects([(0, "counter_pn", "b", ("increment", 1))])
        assert int(r0a.pub_opid[0]) == 3
        # manual move, stripping the extras the exporter attached
        data = m0.m_export_shard(0, 1)
        pkg = _handoff.unpack(data)
        pkg.pop("x", None)
        m1.m_import_shard(_handoff.pack(pkg))
        m0.m_relinquish_shard(0, 1)
        # the importer resumed the chain at the WAL-derived position
        assert int(r0b.pub_opid[0]) == 3
        vc = ClusterNode(m1).update_objects(
            [(0, "counter_pn", "b", ("increment", 1))])
        assert int(r0b.pub_opid[0]) == 4
        assert int(vc[0]) == 4
    finally:
        m0.close(), m1.close()
