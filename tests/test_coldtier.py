"""Beyond-RAM survival (ISSUE 13): cold tier, incremental checkpoint
chains, and Merkle-split divergence repair.

Three invariant families:

  * **cold tier** — eviction only ever drops rows whose live head_vc is
    byte-equal to the anchor sidecar's stamp; reads fault evicted rows
    back in EXACTLY (same values at the same VC stamps); refusals (rate
    cap, injected I/O fault, CRC failure) are typed ColdMiss — never a
    bottom read; the resident budget holds under sustained writes.
  * **Merkle tree** — root equality tracks the flat shard_digest oracle;
    a single-row flip localizes to exactly one leaf in O(fanout·depth)
    hash comparisons and heals by a range-restricted fetch (no
    re-bootstrap); an unsubscribed peer lane types as ``unsubscribed``.
  * **chains** — full + delta compose byte-identical to the all-full
    oracle; a corrupt/missing mid-chain link falls back to the prefix +
    a longer WAL tail; the scrubber retires corrupt links and forces a
    rebase.
"""

import os

import numpy as np
import pytest

from antidote_tpu import faults
from antidote_tpu.api import AntidoteNode
from antidote_tpu.config import AntidoteConfig
from antidote_tpu.log import checkpoint as ckpt
from antidote_tpu.overload import ColdMiss
from antidote_tpu.store.kv import shard_digest
from antidote_tpu.store.merkle import MerkleIndex, get_merkle, leaf_of

pytestmark = pytest.mark.smoke


@pytest.fixture
def dcfg():
    return AntidoteConfig(
        n_shards=4, max_dcs=3, ops_per_key=8, snap_versions=2,
        set_slots=8, mv_slots=4, rga_slots=16, keys_per_table=64,
        batch_buckets=(16, 64), wal_segments=3,
    )


@pytest.fixture(autouse=True)
def _no_leftover_faults():
    yield
    faults.uninstall()


def populate(node, n, start=0, mult=1):
    for i in range(start, start + n):
        node.update_objects([(i, "counter_pn", "b",
                              ("increment", (i + 1) * mult))])


# ---------------------------------------------------------------------------
# cold tier
# ---------------------------------------------------------------------------
def test_evict_fault_read_roundtrip_exact_vc(dcfg, tmp_path):
    """Evicted keys fault back in byte-exact: values AND the head VC
    stamps (the exactness the divergence digests then depend on)."""
    node = AntidoteNode(dcfg, log_dir=str(tmp_path / "w"),
                        resident_rows=1 << 30)
    populate(node, 32)
    vcs = {}
    for i in range(32):
        tname, shard, row = node.store.directory[(i, "b")]
        t = node.store.tables[tname]
        vcs[i] = np.asarray(t.head_vc[shard, row]).copy()
    node.checkpoint_now()
    cold = node.store.cold
    cold.budget = 8
    evicted = cold.evict_now(max_rows=1024)
    assert evicted >= 24, evicted
    assert cold.resident_rows() <= 8
    assert len(cold.cold_set) == evicted
    # a cold key has NO directory entry (epoch fast paths fall back)
    cold_key = next(iter(cold.cold_set))[0]
    assert (cold_key, "b") not in node.store.directory
    # read it back: fault-in restores value + exact head VC stamp
    vals, _ = node.read_objects([(cold_key, "counter_pn", "b")])
    assert vals == [cold_key + 1]
    tname, shard, row = node.store.directory[(cold_key, "b")]
    t = node.store.tables[tname]
    assert (np.asarray(t.head_vc[shard, row]) == vcs[cold_key]).all()
    assert cold.faults == 1
    assert node.metrics.coldtier_events.value(event="fault") == 1
    # every key still reads exact (bulk fault-in)
    vals, _ = node.read_objects([(i, "counter_pn", "b")
                                 for i in range(32)])
    assert vals == [i + 1 for i in range(32)]
    node.store.log.close()


def test_budget_enforced_under_sustained_writes(dcfg, tmp_path):
    """The --resident-rows budget holds on the commit path once an
    image covers eviction candidates; writes are never refused."""
    node = AntidoteNode(dcfg, log_dir=str(tmp_path / "w"),
                        resident_rows=24)
    populate(node, 24)
    node.checkpoint_now()
    populate(node, 72, start=24)
    cold = node.store.cold
    # the 24 image-covered keys were evicted as the budget demanded;
    # the uncovered remainder waits for the next stamp (soft budget —
    # pressure requested a checkpoint instead of refusing writes)
    assert len(cold.cold_set) == 24
    node.checkpoint_now(full=True)
    populate(node, 8, start=96)
    assert cold.resident_rows() <= 24 + 8
    vals, _ = node.read_objects([(i, "counter_pn", "b")
                                 for i in range(104)])
    assert vals == [i + 1 for i in range(104)]
    node.store.log.close()


def test_dirty_rows_are_not_evictable(dcfg, tmp_path):
    """A row written since the anchor stamp fails the head_vc equality
    probe and stays resident — eviction can never lose a write."""
    node = AntidoteNode(dcfg, log_dir=str(tmp_path / "w"),
                        resident_rows=1 << 30)
    populate(node, 8)
    node.checkpoint_now()
    node.update_objects([(3, "counter_pn", "b", ("increment", 100))])
    cold = node.store.cold
    cold.budget = 1
    cold.evict_now(max_rows=1024)
    assert (3, "b") in node.store.directory  # dirty: kept resident
    assert (5, "b") not in node.store.directory  # clean: evicted
    vals, _ = node.read_objects([(3, "counter_pn", "b"),
                                 (5, "counter_pn", "b")])
    assert vals == [104, 6]
    node.store.log.close()


def test_cold_fault_rate_cap_and_injected_fault_typed(dcfg, tmp_path):
    """Past the rate cap — or behind an injected coldtier.fault — the
    read is refused with a typed ColdMiss carrying a retry hint; the
    key is NEVER served bottom."""
    node = AntidoteNode(dcfg, log_dir=str(tmp_path / "w"),
                        resident_rows=1 << 30)
    populate(node, 12)
    node.checkpoint_now()
    cold = node.store.cold
    cold.budget = 2
    cold.evict_now(max_rows=1024)
    cold.budget = 1 << 30  # stop re-evicting what we fault in
    cold.fault_rate_cap = 2.0
    ok, refused = 0, 0
    for i in range(6):
        if (i, "b") not in cold.cold_set:
            continue
        try:
            vals, _ = node.read_objects([(i, "counter_pn", "b")])
            assert vals == [i + 1]  # exact, never bottom
            ok += 1
        except ColdMiss as e:
            assert e.retry_after_ms >= 25
            refused += 1
    assert ok == 2 and refused >= 1
    assert node.metrics.coldtier_events.value(event="refused") >= 1
    # injected fault site: typed refusal, retriable
    cold.fault_rate_cap = 0.0
    victim = next(iter(cold.cold_set))
    faults.install(faults.FaultPlan(seed=5).io_error("coldtier.fault",
                                                     times=1))
    with pytest.raises(ColdMiss):
        node.read_objects([(victim[0], "counter_pn", "b")])
    faults.uninstall()
    vals, _ = node.read_objects([(victim[0], "counter_pn", "b")])
    assert vals == [victim[0] + 1]
    node.store.log.close()


def test_cold_sidecar_row_crc_catches_bit_rot(dcfg, tmp_path):
    """A flipped byte in the sidecar row is caught by the per-row CRC at
    fault-in: typed ColdMiss (and a forced-rebase nudge), never a wrong
    value."""
    node = AntidoteNode(dcfg, log_dir=str(tmp_path / "w"),
                        resident_rows=1 << 30)
    populate(node, 8)
    node.checkpoint_now()
    cold = node.store.cold
    cold.budget = 1
    cold.evict_now(max_rows=1024)
    cold.budget = 1 << 30
    victim = sorted(cold.cold_set)[0]
    ref = cold.refs[victim]
    # flip one byte of the victim's head field inside cold.bin
    sc = cold._sidecar(ref.src)
    tman = sc.man["tables"][ref.tname]
    f0 = sorted(tman["fields"])[0]
    spec = tman["fields"][f0]
    rb = int(np.dtype(spec["dtype"]).itemsize
             * max(1, int(np.prod(spec["shape"]))))
    off = spec["off"] + (ref.shard * tman["rows"] + ref.srow) * rb
    path = ckpt.cold_path(node.store.log.dir, ref.src)
    with open(path, "r+b") as f:
        f.seek(off)
        b = f.read(1)
        f.seek(off)
        f.write(bytes([b[0] ^ 0xFF]))
    cold._drop_sidecar_cache()
    with pytest.raises(ColdMiss, match="verification"):
        node.read_objects([(victim[0], "counter_pn", "b")])
    assert node.metrics.coldtier_events.value(event="crc_fail") == 1
    assert node.checkpointer.force_rebase is True
    # the forced rebase re-reads every row; the corrupt one is LOST and
    # tombstoned typed-permanent (surfaced, never silent)
    node.checkpoint_now()
    with pytest.raises(ColdMiss, match="peer"):
        node.read_objects([(victim[0], "counter_pn", "b")])
    assert victim in cold.lost
    # every other key still exact
    others = [i for i in range(8) if (i, "b") != victim]
    vals, _ = node.read_objects([(i, "counter_pn", "b") for i in others])
    assert vals == [i + 1 for i in others]
    node.store.log.close()


def test_cold_miss_typed_on_the_wire(dcfg, tmp_path):
    """The wire server maps ColdMiss to a typed cold_miss error reply
    with the retry hint (RemoteColdMiss client-side)."""
    from antidote_tpu.proto.client import AntidoteClient, RemoteColdMiss
    from antidote_tpu.proto.server import ProtocolServer

    node = AntidoteNode(dcfg, log_dir=str(tmp_path / "w"),
                        resident_rows=1 << 30)
    populate(node, 8)
    node.checkpoint_now()
    cold = node.store.cold
    cold.budget = 1
    cold.evict_now(max_rows=1024)
    cold.budget = 1 << 30
    victim = next(iter(cold.cold_set))
    srv = ProtocolServer(node, port=0)
    try:
        c = AntidoteClient(port=srv.port)
        # persistent rule: the read pipeline's merged→solo retry would
        # absorb a one-shot fault (and that retry-absorption is GOOD —
        # a transient fault-in error self-heals invisibly)
        faults.install(faults.FaultPlan(seed=6).io_error("coldtier.fault"))
        with pytest.raises(RemoteColdMiss) as ei:
            c.read_objects([(victim[0], "counter_pn", "b")])
        assert ei.value.retry_after_ms >= 25
        faults.uninstall()
        vals, _ = c.read_objects([(victim[0], "counter_pn", "b")])
        assert vals == [victim[0] + 1]
        c.close()
    finally:
        srv.close()
        node.store.log.close()


def test_cold_keys_recover_cold_and_fault_on_demand(dcfg, tmp_path):
    """Recovery of a beyond-RAM image installs only the resident set;
    cold keys register fault-in refs and read exact on demand."""
    node = AntidoteNode(dcfg, log_dir=str(tmp_path / "w"),
                        resident_rows=1 << 30)
    populate(node, 40)
    node.checkpoint_now()
    cold = node.store.cold
    cold.budget = 10
    cold.evict_now(max_rows=1024)
    n_cold = len(cold.cold_set)
    assert n_cold >= 24
    node.checkpoint_now(full=True)  # image carries the cold appendix
    node.store.log.close()
    n2 = AntidoteNode(dcfg, log_dir=str(tmp_path / "w"), recover=True,
                      resident_rows=1 << 30)
    assert len(n2.store.cold.cold_set) == n_cold
    assert len(n2.store.directory) == 40 - n_cold
    vals, _ = n2.read_objects([(i, "counter_pn", "b") for i in range(40)])
    assert vals == [i + 1 for i in range(40)]
    assert n2.store.cold.faults == n_cold
    n2.store.log.close()


# ---------------------------------------------------------------------------
# Merkle tree units
# ---------------------------------------------------------------------------
def test_merkle_root_tracks_flat_digest_oracle(dcfg, tmp_path):
    """Root equality ⟺ flat shard_digest equality, across genuinely
    different states and across replicas reaching the same state."""
    a = AntidoteNode(dcfg, log_dir=str(tmp_path / "a"))
    b = AntidoteNode(dcfg, log_dir=str(tmp_path / "b"))
    ops = [(i, "counter_pn", "b", ("increment", i + 1)) for i in range(24)]
    for node in (a, b):
        for op in ops:
            # identical single-op commits mint identical clocks
            node.update_objects([op])
    for shard in range(dcfg.n_shards):
        assert shard_digest(a.store, shard) == shard_digest(b.store, shard)
        assert get_merkle(a.store).root(shard) == \
            get_merkle(b.store).root(shard)
    # diverge ONE key: exactly its shard's digest and root change
    tname, shard, row = a.store.directory[(7, "b")]
    t = a.store.tables[tname]
    f0 = next(iter(t.head))
    t.head[f0] = t.head[f0].at[shard, row].set(999)
    a.store.drop_cached_value((7, "b"))
    mk = get_merkle(a.store)
    for s in range(dcfg.n_shards):
        mk.rescan(s)
        flat_eq = shard_digest(a.store, s) == shard_digest(b.store, s)
        root_eq = mk.root(s) == get_merkle(b.store).root(s)
        assert flat_eq == root_eq == (s != shard)
    a.store.log.close(), b.store.log.close()


def test_merkle_single_flip_localizes_to_one_leaf(dcfg, tmp_path):
    """A single-row flip changes exactly ONE leaf hash, and a top-down
    walk reaches it in O(fanout·depth) comparisons — the pinned
    O(log n) probe count."""
    a = AntidoteNode(dcfg, log_dir=str(tmp_path / "a"))
    b = AntidoteNode(dcfg, log_dir=str(tmp_path / "b"))
    for node in (a, b):
        for i in range(50):
            node.update_objects([(i, "counter_pn", "b",
                                  ("increment", 1))])
    mka, mkb = get_merkle(a.store), get_merkle(b.store)
    tname, shard, row = a.store.directory[(13, "b")]
    t = a.store.tables[tname]
    f0 = next(iter(t.head))
    t.head[f0] = t.head[f0].at[shard, row].set(999)
    a.store.drop_cached_value((13, "b"))
    mka.rescan(shard)
    la = mka._refresh(shard)
    lb = mkb._refresh(shard)
    diff = [i for i, (x, y) in enumerate(zip(la, lb)) if x != y]
    assert diff == [leaf_of(13, "b", mka.n_leaves)]
    # walk: follow mismatching children only, count comparisons
    probes = 0
    frontier = [(0, 0)]
    for level in range(mka.depth()):
        nxt = []
        for _lv, idx in frontier:
            ca = mka.children(shard, level, idx)
            cb = mkb.children(shard, level, idx)
            probes += len(ca)
            for child, (x, y) in enumerate(zip(ca, cb)):
                if x != y:
                    nxt.append((level + 1, idx * mka.fanout + child))
        frontier = nxt
    assert [i for _l, i in frontier] == diff
    assert probes <= mka.fanout * mka.depth(), probes  # O(log n), not O(n)
    a.store.log.close(), b.store.log.close()


def test_merkle_incremental_marks_match_full_rebuild(dcfg, tmp_path):
    """Incrementally-maintained leaves equal a from-scratch rebuild
    after arbitrary writes (the maintenance-correctness pin)."""
    node = AntidoteNode(dcfg, log_dir=str(tmp_path / "w"))
    for i in range(30):
        node.update_objects([(i, "counter_pn", "b", ("increment", 1))])
    mk = get_merkle(node.store)
    roots0 = [mk.root(s) for s in range(dcfg.n_shards)]
    for i in range(0, 30, 3):
        node.update_objects([(i, "counter_pn", "b", ("increment", 5))])
    incr = [mk.root(s) for s in range(dcfg.n_shards)]
    fresh = MerkleIndex(node.store)
    rebuilt = [fresh.root(s) for s in range(dcfg.n_shards)]
    assert incr == rebuilt
    assert incr != roots0
    node.store.log.close()


def test_chain_with_evictions_recovers_without_resident_rows_flag(
        dcfg, tmp_path):
    """A chain whose delta links record evictions must recover EXACTLY
    even when the restart omits --resident-rows: install_delta attaches
    a cold tier itself rather than dropping the evicted keys' directory
    entries into silent bottoms."""
    node = AntidoteNode(dcfg, log_dir=str(tmp_path / "w"),
                        resident_rows=12)
    node.start_checkpointer(interval_s=0.0, rebase_every=64)
    populate(node, 12)
    node.checkpoint_now(full=True)
    populate(node, 24, start=12)  # evicts the first 12 (anchored)
    assert len(node.store.cold.cold_set) == 12
    s = node.checkpoint_now()  # delta recording the evictions
    assert s["kind"] == "delta"
    node.store.log.close()
    n2 = AntidoteNode(dcfg, log_dir=str(tmp_path / "w"), recover=True)
    assert n2.store.cold is not None  # attached by the chain compose
    vals, _ = n2.read_objects([(i, "counter_pn", "b") for i in range(36)])
    assert vals == [i + 1 for i in range(36)]
    n2.store.log.close()


def test_follower_bootstraps_from_beyond_ram_owner(dcfg, tmp_path):
    """A follower of a cold-bearing owner ships the cold sidecar with
    the image, stages it, persists it into its own first local rebase,
    and serves every key — resident and cold — exactly."""
    import sys

    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    from test_follower import converge, mk_owner

    from antidote_tpu.interdc import FollowerReplica, LoopbackHub

    hub = LoopbackHub()
    owner, orep = mk_owner(dcfg, hub, tmp_path)
    owner.enable_cold_tier(0)
    populate(owner, 24)
    owner.checkpoint_now()
    owner.store.cold.budget = 6
    owner.store.cold.evict_now(max_rows=1024)
    owner.store.cold.budget = 0
    n_cold = len(owner.store.cold.cold_set)
    assert n_cold >= 16
    owner.checkpoint_now(full=True)  # the image a follower will fetch
    fnode = AntidoteNode(dcfg, log_dir=str(tmp_path / "fol"))
    fol = FollowerReplica(fnode, hub, "fcold",
                          owner_client_addr=("h", 1), fabric_id=99)
    mode = fol.attach(orep.descriptor())
    assert mode == "image"
    # the follower registered the owner's cold keys against its OWN
    # locally-persisted sidecar (the staged import was consumed by the
    # forced local rebase)
    assert fnode.store.cold is not None
    assert len(fnode.store.cold.cold_set) == n_cold
    assert not fnode.store.cold._extra_sources
    objs = [(i, "counter_pn", "b") for i in range(24)]
    converge(owner, orep, hub, fnode, objs)
    got, _ = fnode.read_objects(objs)  # faults the cold ones in locally
    assert got == [i + 1 for i in range(24)]
    assert all(v == "ok" for v in fol.check_divergence().values())
    owner.store.log.close(), fnode.store.log.close()


def test_unsubscribed_peer_lane_types_divergence(dcfg, tmp_path):
    """A follower of a geo-replicated owner that was never given the
    peer DC's descriptor reports 'unsubscribed' (typed, counted) for
    lanes only the peer advances — not an eternally-green 'skipped'."""
    import sys

    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    from test_follower import converge, mk_follower, mk_owner

    from antidote_tpu.interdc import DCReplica, LoopbackHub

    hub = LoopbackHub()
    owner, orep = mk_owner(dcfg, hub, tmp_path)
    peer = AntidoteNode(dcfg, dc_id=1, log_dir=str(tmp_path / "peer"))
    prep = DCReplica(peer, hub, "dc1")
    orep.observe_dc(prep), prep.observe_dc(orep)
    owner.update_objects([("k", "counter_pn", "b", ("increment", 1))])
    owner.checkpoint_now()
    # follower gets ONLY the owner's descriptor — no --follower-peers
    fnode, fol, _mode = mk_follower(dcfg, hub, tmp_path, orep)
    converge(owner, orep, hub, fnode, [("k", "counter_pn", "b")])
    # the PEER commits: the owner's lane-1 clock advances, the
    # follower's never can (it holds no dc1 subscription)
    peer.update_objects([("p", "counter_pn", "b", ("increment", 7))])
    prep.heartbeat()
    hub.pump()
    owner.update_objects([("k", "counter_pn", "b", ("increment", 1))])
    orep.heartbeat()
    hub.pump()
    res = fol.check_divergence()
    assert "unsubscribed" in res.values(), res
    assert fnode.metrics.divergence_checks.value(
        result="unsubscribed") >= 1
    assert fol.divergence_counts.get("unsubscribed", 0) >= 1
    owner.store.log.close(), peer.store.log.close()
    fnode.store.log.close()
