"""Clustered-DC parity for the three formerly single-node-only features
(r3 VERDICT missing #3): read-your-writes in open interactive txns,
GentleRain snapshots, and bounded-counter escrow — on the multi-member
topology, mirroring the reference running clocksi/gr/bcountermgr CT
suites on multidc (/root/reference/test/multidc/)."""

import pytest

from antidote_tpu.cluster import (ClusterMember, ClusterNode, attach_interdc,
                                  cluster_query_router)
from antidote_tpu.config import AntidoteConfig
from antidote_tpu.meta import MetaDataStore
from antidote_tpu.overload import InsufficientRightsError
from antidote_tpu.txn.manager import AbortError


def _cfg(**kw):
    base = dict(n_shards=4, max_dcs=3, ops_per_key=8, keys_per_table=64,
                batch_buckets=(16, 64))
    base.update(kw)
    return AntidoteConfig(**base)


def _duo(cfg, meta=None):
    m0 = ClusterMember(cfg, dc_id=0, member_id=0, n_members=2,
                       meta=meta() if meta else None)
    m1 = ClusterMember(cfg, dc_id=0, member_id=1, n_members=2,
                       meta=meta() if meta else None)
    m0.connect(1, *m1.address)
    m1.connect(0, *m0.address)
    return m0, m1


def _key_on(cfg, member, tag):
    from antidote_tpu.store.kv import key_to_shard

    for i in range(10_000):
        k = f"{tag}{i}"
        if key_to_shard(k, "b", cfg.n_shards) in member.shards:
            return k
    raise AssertionError


# ---------------------------------------------------------------------------
# read-your-writes in open cluster txns
# ---------------------------------------------------------------------------
def test_cluster_read_your_writes():
    """An open cluster txn sees its own pending writes — on keys owned
    by BOTH the coordinating member and its peer (the owner overlays
    the txn's effects on the snapshot state)."""
    cfg = _cfg()
    m0, m1 = _duo(cfg)
    c1 = ClusterNode(m1)
    k_local = _key_on(cfg, m1, "l")
    k_remote = _key_on(cfg, m0, "r")
    txn = c1.start_transaction()
    c1.update_objects([(k_local, "counter_pn", "b", ("increment", 2)),
                       (k_remote, "set_aw", "b", ("add", "x"))], txn)
    vals = c1.read_objects([(k_local, "counter_pn", "b"),
                            (k_remote, "set_aw", "b")], txn)
    assert vals == [2, ["x"]]
    # observed-remove through the overlay: remove an element the txn
    # itself added (state-dependent downstream sees the overlaid state)
    c1.update_objects([(k_remote, "set_aw", "b", ("remove", "x"))], txn)
    vals = c1.read_objects([(k_remote, "set_aw", "b")], txn)
    assert vals == [[]]
    c1.commit_transaction(txn)
    vals, _ = c1.read_objects([(k_local, "counter_pn", "b"),
                               (k_remote, "set_aw", "b")])
    assert vals == [2, []]
    # isolation: a DIFFERENT open txn never saw any of it pre-commit
    m0.close(), m1.close()


def test_cluster_ryw_does_not_leak_to_other_txns():
    cfg = _cfg()
    m0, m1 = _duo(cfg)
    c0, c1 = ClusterNode(m0), ClusterNode(m1)
    k = _key_on(cfg, m0, "k")
    t1 = c1.start_transaction()
    c1.update_objects([(k, "counter_pn", "b", ("increment", 5))], t1)
    assert c1.read_objects([(k, "counter_pn", "b")], t1) == [5]
    t2 = c0.start_transaction()
    assert c0.read_objects([(k, "counter_pn", "b")], t2) == [0]
    c1.commit_transaction(t1)
    c0.abort_transaction(t2)
    m0.close(), m1.close()


# ---------------------------------------------------------------------------
# GentleRain on a clustered DC
# ---------------------------------------------------------------------------
def test_cluster_gr_scalar_snapshot():
    """txn_prot=gr on a 2-member DC: snapshots are the scalar GST from
    the aggregated cluster stable vector; own-DC commits remain readable
    (gr_SUITE single-dc cases on the multidc topology)."""
    def gr_meta():
        m = MetaDataStore()
        m.set_env("txn_prot", "gr")
        return m

    cfg = _cfg()
    m0, m1 = _duo(cfg, meta=gr_meta)
    assert m0.node.txm.protocol == "gr"
    c1 = ClusterNode(m1)
    k0 = _key_on(cfg, m0, "a")
    k1 = _key_on(cfg, m1, "b")
    c1.update_objects([(k0, "counter_pn", "b", ("increment", 3))])
    c1.update_objects([(k1, "counter_pn", "b", ("increment", 4))])
    vals, _ = c1.read_objects([(k0, "counter_pn", "b"),
                               (k1, "counter_pn", "b")])
    assert vals == [3, 4]
    txn = c1.start_transaction()
    # remote lanes of a gr snapshot are the scalar GST
    rest = [txn.snapshot_vc[i] for i in range(cfg.max_dcs)
            if i != 0]
    assert len(set(map(int, rest))) == 1
    c1.abort_transaction(txn)
    m0.close(), m1.close()


# ---------------------------------------------------------------------------
# clustered bounded counter
# ---------------------------------------------------------------------------
def _cluster_plus_dc1(cfg):
    """DC0 = 2 members, DC1 = single node, full mesh."""
    from antidote_tpu.api.node import AntidoteNode
    from antidote_tpu.interdc.replica import DCReplica
    from antidote_tpu.interdc.transport import LoopbackHub

    hub = LoopbackHub()
    m0, m1 = _duo(cfg)
    r0a = attach_interdc(m0, hub)
    r0b = attach_interdc(m1, hub)
    node1 = AntidoteNode(cfg, dc_id=1)
    r1 = DCReplica(node1, hub)
    route = cluster_query_router({0: 2}, cfg.n_shards)
    r1.route_query = route
    for sub in (r0a, r0b):
        sub.observe_dc(r1)
    r1.observe_dc(r0a)
    r1.observe_dc(r0b)
    return hub, m0, m1, r0a, r0b, node1, r1


def test_cluster_bcounter_guard_and_decrement():
    """Escrow guard at the key's owner: decrements within rights commit,
    beyond-rights decrements abort, foreign-lane decrements abort
    (bcountermgr_SUITE on the clustered topology)."""
    cfg = _cfg()
    m0, m1 = _duo(cfg)
    c1 = ClusterNode(m1)
    k = _key_on(cfg, m0, "bc")  # owned by the PEER of the coordinator
    c1.update_objects([(k, "counter_b", "b", ("increment", (10, 0)))])
    c1.update_objects([(k, "counter_b", "b", ("decrement", (4, 0)))])
    vals, _ = c1.read_objects([(k, "counter_b", "b")])
    assert vals == [6]
    with pytest.raises(AbortError):
        c1.update_objects([(k, "counter_b", "b", ("decrement", (7, 0)))])
    with pytest.raises(AbortError):  # foreign lane
        c1.update_objects([(k, "counter_b", "b", ("decrement", (1, 2)))])
    vals, _ = c1.read_objects([(k, "counter_b", "b")])
    assert vals == [6]
    m0.close(), m1.close()


def test_cluster_bcounter_transfer_from_clustered_dc():
    """DC1 runs out of rights for a key whose granter is the clustered
    DC0: the rights request routes to the owner member, whose
    coordinator commits the grant through the sequencer, and DC1's
    retry succeeds after the transfer replicates."""
    cfg = _cfg()
    hub, m0, m1, r0a, r0b, node1, r1 = _cluster_plus_dc1(cfg)
    c0 = ClusterNode(m0)
    k = _key_on(cfg, m1, "xf")  # owner = member 1 (not the bare-dc endpoint)
    vc = c0.update_objects([(k, "counter_b", "b", ("increment", (10, 0)))])
    hub.pump()
    # DC1 observes the counter but holds no rights
    vals, _ = node1.read_objects([(k, "counter_b", "b")], clock=vc)
    assert vals == [10]
    with pytest.raises(InsufficientRightsError):
        node1.update_objects([(k, "counter_b", "b", ("decrement", (3, 1)))])
    # the failed decrement queued a transfer request; run the loop
    moved = r1.bcounter_tick()
    assert moved >= 1
    # the grant replicates DC0 -> DC1 and becomes decrement-visible once
    # DC1's STABLE snapshot covers it (heartbeats advance idle shards)
    for attempt in range(100):
        hub.pump()
        try:
            node1.update_objects([(k, "counter_b", "b",
                                   ("decrement", (3, 1)))])
            break
        except InsufficientRightsError:
            continue
    else:
        raise AssertionError("transferred rights never became spendable")
    vals, _ = node1.read_objects([(k, "counter_b", "b")])
    assert vals == [7]
    # the clustered DC converges on the same value
    hub.pump()
    m0.refresh_peer_clocks(), m1.refresh_peer_clocks()
    for _ in range(50):
        vals_c, _ = c0.read_objects([(k, "counter_b", "b")])
        if vals_c == [7]:
            break
        hub.pump()
        m0.refresh_peer_clocks(), m1.refresh_peer_clocks()
    assert vals_c == [7]
    m0.close(), m1.close()


def test_overlay_resync_after_owner_cache_loss():
    """Incremental overlay shipping: when the owner loses its folded
    prefix (restart/eviction), the coordinator's next call triggers
    overlay-resync and transparently re-sends in full."""
    cfg = _cfg()
    m0, m1 = _duo(cfg)
    c1 = ClusterNode(m1)
    k = _key_on(cfg, m0, "rs")
    txn = c1.start_transaction()
    c1.update_objects([(k, "set_aw", "b", ("add", "a"))], txn)
    assert c1.read_objects([(k, "set_aw", "b")], txn) == [["a"]]
    c1.update_objects([(k, "set_aw", "b", ("add", "b"))], txn)
    # the owner "restarts": folded overlay prefixes are gone
    m0._overlay_fold_cache.clear()
    assert c1.read_objects([(k, "set_aw", "b")], txn) == [["a", "b"]]
    # and the incremental path resumes afterwards
    c1.update_objects([(k, "set_aw", "b", ("remove", "a"))], txn)
    assert c1.read_objects([(k, "set_aw", "b")], txn) == [["b"]]
    c1.commit_transaction(txn)
    vals, _ = c1.read_objects([(k, "set_aw", "b")])
    assert vals == [["b"]]
    m0.close(), m1.close()


def test_cluster_composite_map_reads():
    """map_rr reads through a cluster coordinator: membership + fields
    assemble across owners, nested maps recurse, and RYW covers maps in
    open txns."""
    cfg = _cfg()
    m0, m1 = _duo(cfg)
    c1 = ClusterNode(m1)
    c1.update_objects([("m", "map_rr", "b", ("update", {
        ("clicks", "counter_pn"): ("increment", 4),
        ("tags", "set_aw"): ("add", "t1"),
        ("sub", "map_rr"): ("update", {("n", "counter_pn"):
                                       ("increment", 1)}),
    }))])
    vals, _ = c1.read_objects([("m", "map_rr", "b")])
    assert vals[0][("clicks", "counter_pn")] == 4
    assert vals[0][("tags", "set_aw")] == ["t1"]
    assert vals[0][("sub", "map_rr")] == {("n", "counter_pn"): 1}
    # mixed batch: composite + plain in one read
    c1.update_objects([("p", "counter_pn", "b", ("increment", 9))])
    vals, _ = c1.read_objects([("p", "counter_pn", "b"),
                               ("m", "map_rr", "b")])
    assert vals[0] == 9 and vals[1][("clicks", "counter_pn")] == 4
    # RYW: map updates visible inside the open txn
    txn = c1.start_transaction()
    c1.update_objects([("m", "map_rr", "b", ("update", {
        ("clicks", "counter_pn"): ("increment", 1)}))], txn)
    vals = c1.read_objects([("m", "map_rr", "b")], txn)
    assert vals[0][("clicks", "counter_pn")] == 5
    c1.commit_transaction(txn)
    m0.close(), m1.close()


def test_offline_membership_resize(tmp_path):
    """DC membership change 2 -> 3 members via the offline resize tool:
    write through a 2-member cluster, quiesce, resize the log dirs,
    boot 3 members with --recover, and verify every value plus new
    commits on the grown cluster (then shrink 3 -> 1 and re-verify)."""
    from antidote_tpu.cluster.resize import resize_dc

    cfg = _cfg()
    old = [str(tmp_path / f"m{i}") for i in range(2)]
    m0 = ClusterMember(cfg, dc_id=0, member_id=0, n_members=2,
                       log_dir=old[0])
    m1 = ClusterMember(cfg, dc_id=0, member_id=1, n_members=2,
                       log_dir=old[1])
    m0.connect(1, *m1.address)
    m1.connect(0, *m0.address)
    live = [m0, m1]

    def shutdown(members):
        for m in members:
            if m.node.store.log is not None:
                m.node.store.log.close()
            m._prep_wal.close()
            m.rpc.close()
        live.clear()

    try:
        c = ClusterNode(m1)
        expect = {}
        for i in range(12):
            c.update_objects([(f"k{i}", "counter_pn", "b",
                               ("increment", i + 1)),
                              (f"s{i}", "set_aw", "b", ("add", f"e{i}"))])
            expect[(f"k{i}", "counter_pn", "b")] = i + 1
            expect[(f"s{i}", "set_aw", "b")] = [f"e{i}"]
        shutdown([m0, m1])

        new = [str(tmp_path / f"n{i}") for i in range(3)]
        resize_dc(old, new, dc_id=0)

        ms = [ClusterMember(cfg, dc_id=0, member_id=i, n_members=3,
                            log_dir=new[i], recover=True) for i in range(3)]
        live.extend(ms)
        for i, m in enumerate(ms):
            for j, o in enumerate(ms):
                if i != j:
                    m.connect(j, *o.address)
        c3 = ClusterNode(ms[1])
        vals, _ = c3.read_objects(list(expect))
        for (obj, want), got in zip(expect.items(), vals):
            assert got == want, (obj, got, want)
        # the grown cluster accepts new commits (chains continue)
        vc = c3.update_objects([("k0", "counter_pn", "b",
                                 ("increment", 100))])
        assert vc[0] > 0
        vals, _ = ClusterNode(ms[0]).read_objects([("k0", "counter_pn",
                                                    "b")])
        assert vals == [101]
        expect[("k0", "counter_pn", "b")] = 101
        shutdown(ms)

        # shrink 3 -> 1: the single member owns everything
        solo = [str(tmp_path / "solo")]
        resize_dc(new, solo, dc_id=0)
        m = ClusterMember(cfg, dc_id=0, member_id=0, n_members=1,
                          log_dir=solo[0], recover=True)
        live.append(m)
        c1 = ClusterNode(m)
        vals, _ = c1.read_objects(list(expect))
        for (obj, want), got in zip(expect.items(), vals):
            assert got == want, (obj, got, want)
        c1.update_objects([("k1", "counter_pn", "b", ("increment", 1))])
    finally:
        for m in live:
            try:
                m.close()
            except Exception:
                pass


def test_resize_preserves_commit_logged_but_not_applied(tmp_path):
    """A member killed between the durable commit record and the store
    apply holds the txn's effects only in its prepare log; resize must
    recover them through the full member machinery, not drop them."""
    import numpy as np

    from antidote_tpu.cluster.resize import resize_dc
    from antidote_tpu.store.kv import key_to_shard

    cfg = _cfg()
    old = [str(tmp_path / f"m{i}") for i in range(2)]
    m0 = ClusterMember(cfg, dc_id=0, member_id=0, n_members=2,
                       log_dir=old[0])
    m1 = ClusterMember(cfg, dc_id=0, member_id=1, n_members=2,
                       log_dir=old[1])
    m0.connect(1, *m1.address)
    m1.connect(0, *m0.address)
    c1 = ClusterNode(m1)
    k1 = _key_on(cfg, m1, "t")
    txn, ts, prev, _ = _wedge_like(c1, [(k1, "counter_pn", "b",
                                         ("increment", 77))])
    vc = [0] * cfg.max_dcs
    vc[0] = ts
    # torn window: durable commit record, no store apply
    m1._prep_append({"ev": "commit", "txid": int(txn.txid),
                     "vc": [int(x) for x in vc],
                     "prev": {int(kk): int(v) for kk, v in prev.items()}})
    for m in (m0, m1):
        m.rpc.close()
        m.node.store.log.close()
        m._prep_wal.close()

    new = [str(tmp_path / "n0")]
    resize_dc(old, new, dc_id=0)
    m = ClusterMember(cfg, dc_id=0, member_id=0, n_members=1,
                      log_dir=new[0], recover=True)
    try:
        c = ClusterNode(m)
        vals, _ = c.read_objects([(k1, "counter_pn", "b")])
        assert vals == [77], "torn-window commit lost across resize"
        # chains continue on that shard
        c.update_objects([(k1, "counter_pn", "b", ("increment", 1))])
        vals, _ = c.read_objects([(k1, "counter_pn", "b")])
        assert vals == [78]
    finally:
        m.close()


def _wedge_like(coord, updates):
    """Prepare + sequence a txn without committing (borrowed from the
    takeover suite's crash simulation)."""
    from antidote_tpu.cluster.rpc import eff_to_wire
    from antidote_tpu.store.kv import key_to_shard

    txn = coord.start_transaction()
    coord._update(updates, txn)
    by_owner = {}
    shards = set()
    for eff in txn.writeset:
        shard = key_to_shard(eff.key, eff.bucket, coord.cfg.n_shards)
        shards.add(shard)
        by_owner.setdefault(coord._owner_of_shard(shard), []).append(eff)
    snap_own = int(txn.snapshot_vc[coord.dc_id])
    for owner, effs in by_owner.items():
        wires = [eff_to_wire(e) for e in effs]
        if owner is None:
            coord.member.m_prepare(txn.txid, wires, snap_own)
        else:
            coord.member.peers[owner].call("m_prepare", txn.txid, wires,
                                           snap_own)
    ts, prev = coord._seq(sorted(shards), txn.txid)
    return txn, ts, prev, by_owner


def test_resize_retires_old_dirs(tmp_path):
    """Layout-epoch guard (r4 VERDICT item 7): after a resize, booting a
    member on an OLD-layout dir fails loudly instead of serving a stale
    pre-resize copy of moved shards."""
    import pytest as _pytest

    from antidote_tpu.cluster.member import ClusterMember
    from antidote_tpu.cluster.resize import resize_dc
    from antidote_tpu.log import LogDirMismatch, load_dir_meta

    cfg = AntidoteConfig(n_shards=4, max_dcs=2, ops_per_key=8,
                         snap_versions=2, keys_per_table=64,
                         batch_buckets=(8, 64))
    old = [str(tmp_path / "o0")]
    m0 = ClusterMember(cfg, dc_id=0, member_id=0, n_members=1,
                       log_dir=old[0])
    c = ClusterNode(m0)
    c.update_objects([("k", "counter_pn", "b", ("increment", 3))])
    m0.node.store.log.close()
    m0._prep_wal.close()
    m0.rpc.close()

    new = [str(tmp_path / "n0"), str(tmp_path / "n1")]
    resize_dc(old, new, dc_id=0)
    assert load_dir_meta(new[0])["layout_epoch"] == 1
    assert load_dir_meta(old[0])["retired_by_layout_epoch"] == 1
    # old-dir boot refuses
    with _pytest.raises(LogDirMismatch, match="retired"):
        ClusterMember(cfg, dc_id=0, member_id=0, n_members=1,
                      log_dir=old[0], recover=True)
    # new-layout members boot and serve
    ms = [ClusterMember(cfg, dc_id=0, member_id=i, n_members=2,
                        log_dir=new[i], recover=True) for i in range(2)]
    try:
        for i, m in enumerate(ms):
            for j, o in enumerate(ms):
                if i != j:
                    m.connect(j, *o.address)
        vals, _ = ClusterNode(ms[0]).read_objects([("k", "counter_pn", "b")])
        assert vals == [3]
    finally:
        for m in ms:
            m.close()
