"""antidote_pb wire compatibility (r2 VERDICT item 6).

Three layers of evidence that an existing antidotec_pb client can talk to
the server:

1. golden bytes — hand-computed proto2 wire encodings for the core
   messages (byte-for-byte, independent of our encoder);
2. a protoc cross-check — the same ``antidote.proto`` compiled by the
   real protobuf toolchain must accept our encodings and produce byte-
   identical ones (skipped when protoc/google.protobuf are unavailable);
3. a live socket round-trip in the apb dialect against ProtocolServer
   (interactive txn + static read), interleaved with the native msgpack
   dialect on the same port.
"""

import socket
import struct
import subprocess
import sys

import pytest

from antidote_tpu.api.node import AntidoteNode
from antidote_tpu.config import AntidoteConfig
from antidote_tpu.proto import apb
from antidote_tpu.proto.server import ProtocolServer

pytestmark = pytest.mark.smoke

ANTIDOTE_PROTO = r"""
syntax = "proto2";
enum CRDT_type {
    COUNTER = 3; ORSET = 4; LWWREG = 5; MVREG = 6; GMAP = 8;
    RWSET = 10; RRMAP = 11; FATCOUNTER = 12; FLAG_EW = 13;
    FLAG_DW = 14; BCOUNTER = 15;
}
message ApbErrorResp { required bytes errmsg = 1; required uint32 errcode = 2; }
message ApbCounterUpdate { optional sint64 inc = 1; }
message ApbGetCounterResp { required sint32 value = 1; }
message ApbSetUpdate {
    enum SetOpType { ADD = 1; REMOVE = 2; }
    required SetOpType optype = 1;
    repeated bytes adds = 2;
    repeated bytes rems = 3;
}
message ApbGetSetResp { repeated bytes value = 1; }
message ApbRegUpdate { required bytes value = 1; }
message ApbGetRegResp { required bytes value = 1; }
message ApbGetMVRegResp { repeated bytes values = 1; }
message ApbMapKey { required bytes key = 1; required CRDT_type type = 2; }
message ApbMapUpdate {
    repeated ApbMapNestedUpdate updates = 1;
    repeated ApbMapKey removedKeys = 2;
}
message ApbMapNestedUpdate {
    required ApbMapKey key = 1;
    required ApbUpdateOperation update = 2;
}
message ApbMapEntry { required ApbMapKey key = 1; required ApbReadObjectResp value = 2; }
message ApbGetMapResp { repeated ApbMapEntry entries = 1; }
message ApbFlagUpdate { required bool value = 1; }
message ApbGetFlagResp { required bool value = 1; }
message ApbCrdtReset { }
message ApbBoundObject {
    required bytes key = 1;
    required CRDT_type type = 2;
    required bytes bucket = 3;
}
message ApbReadObjects {
    repeated ApbBoundObject boundobjects = 1;
    required bytes transaction_descriptor = 2;
}
message ApbUpdateOperation {
    optional ApbCounterUpdate counterop = 1;
    optional ApbSetUpdate setop = 2;
    optional ApbRegUpdate regop = 3;
    optional ApbCrdtReset resetop = 4;
    optional ApbFlagUpdate flagop = 5;
    optional ApbMapUpdate mapop = 6;
}
message ApbUpdateOp {
    required ApbBoundObject boundobject = 1;
    required ApbUpdateOperation operation = 2;
}
message ApbUpdateObjects {
    repeated ApbUpdateOp updates = 1;
    required bytes transaction_descriptor = 2;
}
message ApbStartTransaction {
    optional bytes timestamp = 1;
    optional ApbTxnProperties properties = 2;
}
message ApbTxnProperties { optional uint32 read_write = 1; optional uint32 red_blue = 2; }
message ApbAbortTransaction { required bytes transaction_descriptor = 1; }
message ApbCommitTransaction { required bytes transaction_descriptor = 1; }
message ApbStaticUpdateObjects {
    required ApbStartTransaction transaction = 1;
    repeated ApbUpdateOp updates = 2;
}
message ApbStaticReadObjects {
    required ApbStartTransaction transaction = 1;
    repeated ApbBoundObject objects = 2;
}
message ApbStartTransactionResp {
    required bool success = 1;
    optional bytes transaction_descriptor = 2;
    optional uint32 errorcode = 3;
}
message ApbOperationResp { required bool success = 1; optional uint32 errorcode = 2; }
message ApbReadObjectResp {
    optional ApbGetCounterResp counter = 1;
    optional ApbGetSetResp set = 2;
    optional ApbGetRegResp reg = 3;
    optional ApbGetMVRegResp mvreg = 4;
    optional ApbGetMapResp map = 6;
    optional ApbGetFlagResp flag = 7;
}
message ApbReadObjectsResp {
    required bool success = 1;
    repeated ApbReadObjectResp objects = 2;
    optional uint32 errorcode = 3;
}
message ApbCommitResp {
    required bool success = 1;
    optional bytes commit_time = 2;
    optional uint32 errorcode = 3;
}
message ApbStaticReadObjectsResp {
    required ApbReadObjectsResp objects = 1;
    required ApbCommitResp committime = 2;
}
"""


# ---------------------------------------------------------------------------
# 1. golden bytes (hand-computed proto2 encodings)
# ---------------------------------------------------------------------------
def test_golden_bytes():
    # ApbCounterUpdate{inc=5}: tag(1,varint)=0x08, zigzag(5)=10
    assert apb.encode_msg("ApbCounterUpdate", {"inc": 5}) == b"\x08\x0a"
    # negative: zigzag(-3)=5
    assert apb.encode_msg("ApbCounterUpdate", {"inc": -3}) == b"\x08\x05"
    # ApbBoundObject{key=b"k", type=COUNTER(3), bucket=b"b"}:
    #   tag(1,len)=0x0a len=1 'k'; tag(2,varint)=0x10 3; tag(3,len)=0x1a len=1 'b'
    assert apb.encode_msg("ApbBoundObject", {
        "key": b"k", "type": 3, "bucket": b"b",
    }) == b"\x0a\x01k\x10\x03\x1a\x01b"
    # ApbSetUpdate{optype=ADD, adds=[b"x", b"y"]}
    assert apb.encode_msg("ApbSetUpdate", {
        "optype": 1, "adds": [b"x", b"y"],
    }) == b"\x08\x01\x12\x01x\x12\x01y"
    # ApbStartTransactionResp{success=true, descriptor=b"7"}
    assert apb.encode_msg("ApbStartTransactionResp", {
        "success": True, "transaction_descriptor": b"7",
    }) == b"\x08\x01\x12\x017"
    # nested: ApbUpdateOp{boundobject=..., operation={counterop={inc=1}}}
    bo = b"\x0a\x01k\x10\x03\x1a\x01b"  # 8 bytes
    op = b"\x0a\x02\x08\x02"  # operation{counterop{inc=1 -> zz 2}}, 4 bytes
    assert apb.encode_msg("ApbUpdateOp", {
        "boundobject": {"key": b"k", "type": 3, "bucket": b"b"},
        "operation": {"counterop": {"inc": 1}},
    }) == b"\x0a\x08" + bo + b"\x12\x04" + op
    # decode round-trips
    for name, d in [
        ("ApbCounterUpdate", {"inc": -12345}),
        ("ApbBoundObject", {"key": b"kk", "type": 4, "bucket": b"bb"}),
        ("ApbCommitResp", {"success": True, "commit_time": b"\x01\x02"}),
    ]:
        enc = apb.encode_msg(name, d)
        dec = apb.decode_msg(name, enc)
        for k, v in d.items():
            assert dec[k] == v, (name, k, dec)
    # frame body carries the antidote_pb_codec message code
    body = apb.encode_frame_body("ApbStartTransaction", {})
    assert body == bytes([119])
    assert apb.MSG_CODES["ApbErrorResp"] == 0
    assert apb.MSG_CODES["ApbCommitResp"] == 127


# ---------------------------------------------------------------------------
# 2. protoc cross-check
# ---------------------------------------------------------------------------
@pytest.fixture(scope="module")
def pb2(tmp_path_factory):
    protoc = None
    import shutil
    protoc = shutil.which("protoc")
    if protoc is None:
        pytest.skip("protoc not available")
    pytest.importorskip("google.protobuf")
    d = tmp_path_factory.mktemp("apbproto")
    (d / "antidote.proto").write_text(ANTIDOTE_PROTO)
    subprocess.run([protoc, f"--python_out={d}", "antidote.proto"],
                   cwd=d, check=True)
    sys.path.insert(0, str(d))
    try:
        import antidote_pb2  # noqa: F401
        return antidote_pb2
    finally:
        sys.path.remove(str(d))


CROSS_CASES = [
    ("ApbCounterUpdate", {"inc": 42}),
    ("ApbCounterUpdate", {"inc": -7}),
    ("ApbGetCounterResp", {"value": -5}),
    ("ApbBoundObject", {"key": b"mykey", "type": 4, "bucket": b"bkt"}),
    ("ApbSetUpdate", {"optype": 2, "rems": [b"a", b"bb", b"ccc"]}),
    ("ApbRegUpdate", {"value": b"hello world"}),
    ("ApbStartTransaction", {"timestamp": b"\x93\x01\x02\x03"}),
    ("ApbStartTransactionResp",
     {"success": True, "transaction_descriptor": b"17"}),
    ("ApbCommitResp", {"success": True, "commit_time": b"\x01" * 8}),
    ("ApbReadObjectsResp",
     {"success": True,
      "objects": [{"counter": {"value": 3}},
                  {"set": {"value": [b"x", b"y"]}}]}),
    ("ApbUpdateObjects",
     {"transaction_descriptor": b"1",
      "updates": [{"boundobject": {"key": b"k", "type": 3, "bucket": b"b"},
                   "operation": {"counterop": {"inc": 9}}}]}),
    ("ApbStaticReadObjects",
     {"transaction": {},
      "objects": [{"key": b"k", "type": 11, "bucket": b"b"}]}),
]


def _fill(msg, d):
    for k, v in d.items():
        if isinstance(v, dict):
            sub = getattr(msg, k)
            sub.SetInParent()  # mark presence even for empty submessages
            _fill(sub, v)
        elif isinstance(v, list):
            fld = getattr(msg, k)
            for x in v:
                if isinstance(x, dict):
                    _fill(fld.add(), x)
                else:
                    fld.append(x)
        else:
            setattr(msg, k, v)


@pytest.mark.parametrize("name,d", CROSS_CASES,
                         ids=[f"{n}-{i}" for i, (n, _) in enumerate(CROSS_CASES)])
def test_protoc_cross_check(pb2, name, d):
    ours = apb.encode_msg(name, d)
    ref = getattr(pb2, name)()
    _fill(ref, d)
    theirs = ref.SerializeToString()
    # byte-identical (both emit fields in schema order)
    assert ours == theirs, (ours.hex(), theirs.hex())
    # and the real toolchain parses our bytes back to the same content
    back = getattr(pb2, name)()
    back.ParseFromString(ours)
    assert back.SerializeToString() == theirs


# ---------------------------------------------------------------------------
# 3. live socket round-trip in the apb dialect
# ---------------------------------------------------------------------------
class _ApbConn:
    """Minimal antidotec_pb-style client: 4-byte frames, apb bodies."""

    def __init__(self, host, port):
        self.sock = socket.create_connection((host, port))

    def call(self, name, d):
        body = apb.encode_frame_body(name, d)
        self.sock.sendall(struct.pack(">I", len(body)) + body)
        (n,) = struct.unpack(">I", self._read(4))
        resp = self._read(n)
        return apb.decode_frame_body(resp)

    def _read(self, n):
        buf = b""
        while len(buf) < n:
            chunk = self.sock.recv(n - len(buf))
            assert chunk, "peer closed"
            buf += chunk
        return buf

    def close(self):
        self.sock.close()


def _mk_server():
    cfg = AntidoteConfig(n_shards=2, max_dcs=2, keys_per_table=64,
                         batch_buckets=(16, 64))
    node = AntidoteNode(cfg)
    return node, ProtocolServer(node, port=0)


def test_apb_interactive_txn_over_socket():
    node, srv = _mk_server()
    try:
        c = _ApbConn("127.0.0.1", srv.port)
        name, resp = c.call("ApbStartTransaction", {})
        assert name == "ApbStartTransactionResp" and resp["success"]
        txd = resp["transaction_descriptor"]
        name, resp = c.call("ApbUpdateObjects", {
            "transaction_descriptor": txd,
            "updates": [
                {"boundobject": {"key": b"cnt", "type": 3, "bucket": b"b"},
                 "operation": {"counterop": {"inc": 4}}},
                {"boundobject": {"key": b"st", "type": 4, "bucket": b"b"},
                 "operation": {"setop": {"optype": 1,
                                         "adds": [b"e1", b"e2"]}}},
                {"boundobject": {"key": b"rg", "type": 5, "bucket": b"b"},
                 "operation": {"regop": {"value": b"hello"}}},
                {"boundobject": {"key": b"fl", "type": 13, "bucket": b"b"},
                 "operation": {"flagop": {"value": True}}},
                {"boundobject": {"key": b"mp", "type": 11, "bucket": b"b"},
                 "operation": {"mapop": {"updates": [
                     {"key": {"key": b"f1", "type": 3},
                      "update": {"counterop": {"inc": 7}}},
                 ]}}},
            ],
        })
        assert name == "ApbOperationResp" and resp["success"], resp
        name, resp = c.call("ApbReadObjects", {
            "transaction_descriptor": txd,
            "boundobjects": [
                {"key": b"cnt", "type": 3, "bucket": b"b"},
                {"key": b"st", "type": 4, "bucket": b"b"},
            ],
        })
        assert name == "ApbReadObjectsResp" and resp["success"], resp
        assert resp["objects"][0]["counter"]["value"] == 4
        assert sorted(resp["objects"][1]["set"]["value"]) == [b"e1", b"e2"]
        name, resp = c.call("ApbCommitTransaction",
                            {"transaction_descriptor": txd})
        assert name == "ApbCommitResp" and resp["success"]
        commit_time = resp["commit_time"]

        # static read AT the commit time (client echoes the opaque clock)
        name, resp = c.call("ApbStaticReadObjects", {
            "transaction": {"timestamp": commit_time},
            "objects": [
                {"key": b"cnt", "type": 3, "bucket": b"b"},
                {"key": b"rg", "type": 5, "bucket": b"b"},
                {"key": b"fl", "type": 13, "bucket": b"b"},
                {"key": b"mp", "type": 11, "bucket": b"b"},
            ],
        })
        assert name == "ApbStaticReadObjectsResp"
        objs = resp["objects"]["objects"]
        assert objs[0]["counter"]["value"] == 4
        assert objs[1]["reg"]["value"] == b"hello"
        assert objs[2]["flag"]["value"] is True
        m = objs[3]["map"]["entries"]
        assert len(m) == 1 and m[0]["key"]["key"] == b"f1"
        assert m[0]["value"]["counter"]["value"] == 7
        c.close()
    finally:
        srv.close()


def test_apb_static_update_and_error_reply():
    node, srv = _mk_server()
    try:
        c = _ApbConn("127.0.0.1", srv.port)
        name, resp = c.call("ApbStaticUpdateObjects", {
            "transaction": {},
            "updates": [
                {"boundobject": {"key": b"k", "type": 3, "bucket": b"b"},
                 "operation": {"counterop": {"inc": 2}}},
            ],
        })
        assert name == "ApbCommitResp" and resp["success"]
        # unknown txn descriptor -> ApbErrorResp (reference catch-all shape)
        name, resp = c.call("ApbReadObjects", {
            "transaction_descriptor": b"99999",
            "boundobjects": [{"key": b"k", "type": 3, "bucket": b"b"}],
        })
        assert name == "ApbErrorResp"
        # the same socket still serves the NATIVE msgpack dialect
        from antidote_tpu.proto.codec import MessageCode, encode, read_frame, decode
        c.sock.sendall(encode(MessageCode.STATIC_READ_OBJECTS, {
            "objects": [[b"k", "counter_pn", b"b"]], "clock": None,
        }))
        frame = read_frame(c.sock)
        code, body = decode(frame)
        assert code == MessageCode.READ_OBJECTS_RESP
        assert body["values"][0] == 2
        c.close()
    finally:
        srv.close()


def test_apb_orphaned_connection_aborts_txn():
    node, srv = _mk_server()
    try:
        c = _ApbConn("127.0.0.1", srv.port)
        _, resp = c.call("ApbStartTransaction", {})
        assert node.txm._open_snaps
        c.close()
        import time
        for _ in range(100):
            if not node.txm._open_snaps:
                break
            time.sleep(0.05)
        assert not node.txm._open_snaps
    finally:
        srv.close()


def test_apb_failed_update_aborts_txn():
    """r3 review: a failed interactive update must abort the txn — never
    leave it active but unreachable (it would pin the cert-GC floor)."""
    node, srv = _mk_server()
    try:
        c = _ApbConn("127.0.0.1", srv.port)
        _, resp = c.call("ApbStartTransaction", {})
        txd = resp["transaction_descriptor"]
        # unknown CRDT_type enum 7 -> error reply
        name, resp = c.call("ApbUpdateObjects", {
            "transaction_descriptor": txd,
            "updates": [{"boundobject": {"key": b"k", "type": 7,
                                         "bucket": b"b"},
                         "operation": {"counterop": {"inc": 1}}}],
        })
        assert name == "ApbErrorResp"
        assert not node.txm._open_snaps, "txn leaked after failed update"
        assert not srv._txns
        c.close()
    finally:
        srv.close()


def test_apb_bounded_counter_ops_carry_actor_lane():
    node, srv = _mk_server()
    try:
        c = _ApbConn("127.0.0.1", srv.port)
        name, resp = c.call("ApbStaticUpdateObjects", {
            "transaction": {},
            "updates": [{"boundobject": {"key": b"bc", "type": 15,
                                         "bucket": b"b"},
                         "operation": {"counterop": {"inc": 10}}}],
        })
        assert name == "ApbCommitResp" and resp["success"], resp
        name, resp = c.call("ApbStaticReadObjects", {
            "transaction": {"timestamp": resp["commit_time"]},
            "objects": [{"key": b"bc", "type": 15, "bucket": b"b"}],
        })
        assert name == "ApbStaticReadObjectsResp"
        assert resp["objects"]["objects"][0]["counter"]["value"] == 10
        c.close()
    finally:
        srv.close()


def test_apb_bounded_counter_refusal_is_typed_and_retryable():
    """Over-decrementing a counter_b surfaces the escrow refusal as a
    typed ApbErrorResp (``insufficient_rights`` + retry hint in the
    errmsg grammar, ISSUE 18) and leaves the connection and the value
    intact — the client retries within rights on the same socket."""
    node, srv = _mk_server()
    try:
        c = _ApbConn("127.0.0.1", srv.port)
        name, resp = c.call("ApbStaticUpdateObjects", {
            "transaction": {},
            "updates": [{"boundobject": {"key": b"esc", "type": 15,
                                         "bucket": b"b"},
                         "operation": {"counterop": {"inc": 3}}}],
        })
        assert name == "ApbCommitResp" and resp["success"], resp
        # decrement beyond rights: typed refusal, not a blind abort
        name, resp = c.call("ApbStaticUpdateObjects", {
            "transaction": {},
            "updates": [{"boundobject": {"key": b"esc", "type": 15,
                                         "bucket": b"b"},
                         "operation": {"counterop": {"inc": -5}}}],
        })
        assert name == "ApbErrorResp", resp
        err = apb.parse_error_text(resp["errmsg"])
        assert err["kind"] == "insufficient_rights", err
        assert err["retry_after_ms"] > 0
        assert "need 5, hold 3" in err["detail"]
        # connection stays usable; a covered decrement commits
        name, resp = c.call("ApbStaticUpdateObjects", {
            "transaction": {},
            "updates": [{"boundobject": {"key": b"esc", "type": 15,
                                         "bucket": b"b"},
                         "operation": {"counterop": {"inc": -2}}}],
        })
        assert name == "ApbCommitResp" and resp["success"], resp
        name, resp = c.call("ApbStaticReadObjects", {
            "transaction": {"timestamp": resp["commit_time"]},
            "objects": [{"key": b"esc", "type": 15, "bucket": b"b"}],
        })
        assert resp["objects"]["objects"][0]["counter"]["value"] == 1
        c.close()
    finally:
        srv.close()


def test_apb_commit_busy_keeps_descriptor_retryable():
    """A commit-backlog shed leaves the txn OPEN for retry in the native
    dialect; the apb dialect must match — popping the descriptor before
    the outcome is known would turn the advertised busy-retry into
    KeyError('unknown transaction') and leak an unreachable open txn
    pinning the certification-GC floor."""
    node, srv = _mk_server()
    try:
        c = _ApbConn("127.0.0.1", srv.port)
        name, resp = c.call("ApbStartTransaction", {})
        txd = resp["transaction_descriptor"]
        c.call("ApbUpdateObjects", {
            "transaction_descriptor": txd,
            "updates": [{"boundobject": {"key": b"bz", "type": 3,
                                         "bucket": b"b"},
                         "operation": {"counterop": {"inc": 5}}}],
        })
        saved = node.txm.max_commit_backlog
        node.txm.max_commit_backlog = 0  # every commit sheds busy
        try:
            name, resp = c.call("ApbCommitTransaction",
                                {"transaction_descriptor": txd})
            assert name == "ApbErrorResp"
            assert resp["errmsg"].startswith(b"busy retry_after_ms="), resp
            assert node.txm._open_snaps, "busy shed must leave the txn open"
        finally:
            node.txm.max_commit_backlog = saved
        # pressure gone: the SAME descriptor commits
        name, resp = c.call("ApbCommitTransaction",
                            {"transaction_descriptor": txd})
        assert name == "ApbCommitResp" and resp["success"], resp
        name, resp = c.call("ApbStaticReadObjects", {
            "transaction": {"timestamp": resp["commit_time"]},
            "objects": [{"key": b"bz", "type": 3, "bucket": b"b"}],
        })
        assert resp["objects"]["objects"][0]["counter"]["value"] == 5
        assert not node.txm._open_snaps and not srv._txns
        c.close()
    finally:
        srv.close()
