"""Widened mesh test surface (r2 VERDICT item 9): the sharded replica
step for non-counter types, full node workloads on mesh-sharded tables,
reshard/handoff under NamedSharding, and read-while-commit interleaving
with the arrays actually laid out over the 8-device CPU mesh — the
multi-device analogues of the reference's multidc CT suites
(/root/reference/test/multidc/)."""

import jax
import numpy as np

from antidote_tpu.api import AntidoteNode
from antidote_tpu.config import AntidoteConfig
from antidote_tpu.crdt import get_type
from antidote_tpu.parallel import make_mesh, shard_axis_sharding, sharded_step_fn
from antidote_tpu.store import TypedTable, handoff


def mesh_and_sharding():
    n_dev = len(jax.devices())
    assert n_dev == 8, "conftest must force 8 virtual CPU devices"
    mesh = make_mesh(n_dev)
    return mesh, shard_axis_sharding(mesh)


def mk_cfg(n_shards=8):
    return AntidoteConfig(
        n_shards=n_shards, max_dcs=2, ops_per_key=8, snap_versions=2,
        set_slots=8, keys_per_table=16, batch_buckets=(16,),
    )


def assert_on_mesh(table, sharding):
    """The table's device arrays must actually carry the mesh layout."""
    for arr in (table.ops_a, table.snap_vc, table.head_vc):
        assert arr.sharding.is_equivalent_to(sharding, arr.ndim), (
            arr.sharding, sharding)


def test_sharded_step_set_aw():
    """The full replica step (commit scatter + pmin + versioned read) as
    ONE jitted shard_map program with the OR-set's wide effect lanes —
    the set_aw analogue of test_spmd's counter step."""
    mesh, sharding = mesh_and_sharding()
    cfg = mk_cfg()
    ty = get_type("set_aw")
    table = TypedTable(ty, cfg, sharding=sharding)
    step = sharded_step_fn(ty, cfg, mesh)

    p, ma, mr, d = cfg.n_shards, 8, 8, cfg.max_dcs
    aw, bw = ty.eff_a_width(cfg), ty.eff_b_width(cfg)
    # one add of handle (shard+1)*100 on row 0 of every shard at vc [1, 0]
    app_rows = np.zeros((p, ma), np.int64)
    app_rows[:, 1:] = table.n_rows  # padding
    app_slots = np.zeros((p, ma), np.int64)
    app_a = np.zeros((p, ma, aw), np.int64)
    app_a[:, 0, 0] = (np.arange(p) + 1) * 100
    app_b = np.zeros((p, ma, bw), np.int32)  # kind=0 (add), no observed row
    app_vc = np.zeros((p, ma, d), np.int32)
    app_vc[:, 0, 0] = 1
    app_origin = np.zeros((p, ma), np.int32)
    read_rows = np.zeros((p, mr), np.int64)
    read_n_ops = np.zeros((p, mr), np.int32)
    read_n_ops[:, 0] = 1
    read_vcs = np.zeros((p, mr, d), np.int32)
    read_vcs[..., 0] = 1
    applied_vc = np.zeros((p, d), np.int32)

    (ops_a, ops_b, ops_vc, ops_origin, state, applied, complete,
     new_applied, stable) = step(
        table.snap, table.snap_vc, table.snap_seq,
        table.ops_a, table.ops_b, table.ops_vc, table.ops_origin,
        app_rows, app_slots, app_a, app_b, app_vc, app_origin,
        read_rows, read_n_ops, read_vcs, applied_vc,
    )
    elems = np.asarray(state["elems"])  # [P, Mr, E]
    addvc = np.asarray(state["addvc"])  # [P, Mr, E, D]
    rmvc = np.asarray(state["rmvc"])
    present = (addvc > rmvc).any(-1) & (elems != 0)
    for s in range(p):
        slot = np.nonzero(present[s, 0])[0]
        assert slot.size == 1
        assert elems[s, 0, slot[0]] == (s + 1) * 100
    assert np.asarray(complete).all()
    assert (np.asarray(stable) == np.asarray([1, 0])).all()


def test_mesh_node_set_aw_and_map_rr():
    """Full client workload (set_aw adds/removes + nested map_rr fields)
    against a node whose tables live on the 8-device mesh."""
    mesh, sharding = mesh_and_sharding()
    node = AntidoteNode(mk_cfg(), sharding=sharding)
    node.update_objects([
        ("s", "set_aw", "bk", ("add_all", ["a", "b", "c"])),
        ("m", "map_rr", "bk", ("update", [
            (("cnt", "counter_pn"), ("increment", 7)),
            (("tags", "set_aw"), ("add", "x")),
        ])),
    ])
    node.update_objects([
        ("s", "set_aw", "bk", ("remove", "b")),
        ("m", "map_rr", "bk", ("update", [
            (("tags", "set_aw"), ("add", "y")),
        ])),
    ])
    vals, _ = node.read_objects([
        ("s", "set_aw", "bk"), ("m", "map_rr", "bk"),
    ])
    assert vals[0] == ["a", "c"]
    assert vals[1] == {("cnt", "counter_pn"): 7,
                       ("tags", "set_aw"): ["x", "y"]}
    assert_on_mesh(node.store.tables["set_aw"], sharding)


def test_mesh_read_while_commit_interleaving():
    """Snapshot isolation on the mesh: a txn opened before later commits
    keeps reading its snapshot (the versioned ring fold path — head is
    newer than the txn's VC), while fresh reads see the new state."""
    mesh, sharding = mesh_and_sharding()
    node = AntidoteNode(mk_cfg(), sharding=sharding)
    node.update_objects([("k", "set_aw", "bk", ("add", "v1"))])
    txn = node.start_transaction()
    # commits land after the snapshot, interleaved with snapshot reads
    for i in range(3):
        node.update_objects([("k", "set_aw", "bk", ("add", f"w{i}"))])
        vals = node.read_objects([("k", "set_aw", "bk")], txn)
        assert vals[0] == ["v1"], (i, vals[0])
    node.commit_transaction(txn)
    vals, _ = node.read_objects([("k", "set_aw", "bk")])
    assert vals[0] == ["v1", "w0", "w1", "w2"]


def test_reshard_keeps_mesh_layout():
    """Ring resize 8→16 of a mesh-sharded replica: the new store's arrays
    stay on the mesh (16 % 8 == 0) and every value survives re-routing."""
    mesh, sharding = mesh_and_sharding()
    node = AntidoteNode(mk_cfg(8), sharding=sharding)
    expect = {}
    for i in range(24):
        node.update_objects([
            (f"c{i}", "counter_pn", "bk", ("increment", i + 1)),
            (f"s{i}", "set_aw", "bk", ("add", f"e{i}")),
        ])
        expect[(f"c{i}", "counter_pn", "bk")] = i + 1
        expect[(f"s{i}", "set_aw", "bk")] = [f"e{i}"]
    new_store = handoff.reshard(node.store, mk_cfg(16), my_dc=0)
    assert_on_mesh(new_store.tables["counter_pn"], sharding)
    node2 = AntidoteNode(store=new_store)
    vals, _ = node2.read_objects(list(expect))
    for (obj, want), got in zip(expect.items(), vals):
        assert got == want, (obj, got, want)


def test_reshard_shrink_incompatible_mesh_falls_back():
    """Ring resize 8→4 of a mesh-sharded replica on an 8-device mesh:
    4 % 8 != 0 so the new store can't keep the mesh layout — reshard
    falls back to default placement instead of crashing, and every value
    survives re-routing."""
    mesh, sharding = mesh_and_sharding()
    node = AntidoteNode(mk_cfg(8), sharding=sharding)
    expect = {}
    for i in range(12):
        node.update_objects([
            (f"c{i}", "counter_pn", "bk", ("increment", i + 1))])
        expect[(f"c{i}", "counter_pn", "bk")] = i + 1
    new_store = handoff.reshard(node.store, mk_cfg(4), my_dc=0)
    assert new_store.cfg.n_shards == 4
    node2 = AntidoteNode(store=new_store)
    vals, _ = node2.read_objects(list(expect))
    for (obj, want), got in zip(expect.items(), vals):
        assert got == want, (obj, got, want)


def test_handoff_between_mesh_nodes():
    """Export every shard of a mesh-sharded replica into another
    mesh-sharded replica; the importer answers identical reads and its
    arrays remain on the mesh (the riak_core ownership-handoff analogue
    under real device placement)."""
    mesh, sharding = mesh_and_sharding()
    cfg = mk_cfg()
    a = AntidoteNode(cfg, sharding=sharding)
    expect = {}
    for i in range(16):
        a.update_objects([(f"s{i}", "set_aw", "bk", ("add_all",
                                                     [f"p{i}", f"q{i}"]))])
        expect[(f"s{i}", "set_aw", "bk")] = sorted([f"p{i}", f"q{i}"])
    for i in range(0, 16, 4):
        a.update_objects([(f"s{i}", "set_aw", "bk", ("remove", f"p{i}"))])
        expect[(f"s{i}", "set_aw", "bk")] = [f"q{i}"]
    b = AntidoteNode(cfg, sharding=sharding)
    for shard in range(cfg.n_shards):
        pkg = handoff.unpack(handoff.pack(handoff.export_shard(a.store, shard)))
        b.receive_handoff(pkg)
    vals, _ = b.read_objects(list(expect))
    for (obj, want), got in zip(expect.items(), vals):
        assert got == want, (obj, got, want)
    assert_on_mesh(b.store.tables["set_aw"], sharding)
