"""Supervision tree (antidote_sup one_for_one parity,
/root/reference/src/antidote_sup.erl:137): dead children restart in
place; exceeding the restart intensity shuts the tree down."""

import time

from antidote_tpu.supervise import Supervisor
import pytest

pytestmark = pytest.mark.smoke


class FakeService:
    def __init__(self):
        self.alive = True
        self.stopped = False

    def kill(self):
        self.alive = False

    def stop(self):
        self.stopped = True


def test_child_restarts_in_place():
    started = []

    def start():
        s = FakeService()
        started.append(s)
        return s

    sup = Supervisor(poll_s=0.02)
    sup.add("svc", start, alive=lambda s: s.alive, stop=lambda s: s.stop())
    sup.start()
    assert len(started) == 1
    started[0].kill()
    for _ in range(100):
        if len(started) == 2:
            break
        time.sleep(0.02)
    assert len(started) == 2, "dead child was not restarted"
    assert started[0].stopped, "dead child was not stopped before restart"
    assert started[1].alive
    assert sup.gave_up is None
    sup.shutdown()
    assert started[1].stopped


def test_restart_intensity_gives_up():
    """5 restarts in 10s (the reference's intensity) -> tree shutdown +
    escalation callback, not an infinite crash loop."""
    started = []
    gave = []

    def start():
        s = FakeService()
        s.alive = False  # born dead: flaps on every poll
        started.append(s)
        return s

    sup = Supervisor(poll_s=0.01, max_restarts=5, window_s=10.0,
                     on_giveup=gave.append)
    sup.add("flappy", start, alive=lambda s: s.alive,
            stop=lambda s: s.stop())
    sup.add("healthy", FakeService, alive=lambda s: s.alive,
            stop=lambda s: s.stop())
    sup.start()
    for _ in range(200):
        if gave:
            break
        time.sleep(0.02)
    assert gave == ["flappy"]
    assert sup.gave_up == "flappy"
    # intensity bound: initial start + max_restarts starts, then stop
    assert len(started) == 6
    # the healthy sibling was shut down too (tree shutdown, OTP rule)
    healthy = sup.children["healthy"]
    assert healthy.handle is None


def test_supervised_protocol_listener_restarts_on_same_port():
    """The console-serve wiring, in process: kill the protocol server
    (its accept thread exits); the supervisor rebuilds it via the
    start factory ON THE SAME PORT and clients keep working."""
    from antidote_tpu.api import AntidoteNode
    from antidote_tpu.config import AntidoteConfig
    from antidote_tpu.proto.client import AntidoteClient
    from antidote_tpu.proto.server import ProtocolServer

    node = AntidoteNode(AntidoteConfig(
        n_shards=4, max_dcs=2, keys_per_table=256, batch_buckets=(16, 64)))
    box = {}

    def start_proto():
        port = box["srv"].port if "srv" in box else 0
        box["srv"] = ProtocolServer(node, port=port)
        return box["srv"]

    sup = Supervisor(poll_s=0.05)
    sup.add("proto", start_proto, alive=lambda s: s.is_alive(),
            stop=lambda s: s.close())
    sup.start()
    first = box["srv"]
    port = first.port
    c = AntidoteClient("127.0.0.1", port)
    c.update_objects([("k", "counter_pn", "b", ("increment", 1))])
    c.close()
    first._server.shutdown()  # the listener "crashes"
    for _ in range(100):
        if box["srv"] is not first and box["srv"].is_alive():
            break
        time.sleep(0.05)
    assert box["srv"] is not first, "supervisor never restarted the child"
    assert box["srv"].port == port, "restart must rebind the same port"
    c2 = AntidoteClient("127.0.0.1", port)
    vals, _ = c2.read_objects([("k", "counter_pn", "b")])
    assert vals == [1]
    c2.close()
    sup.shutdown()


def test_release_serve_survives_hostile_frames(tmp_path):
    """End to end resilience probe against a real `console serve`
    process: an oversized frame must not take the listener down."""
    import json
    import os
    import subprocess
    import sys

    from antidote_tpu.proto.client import AntidoteClient

    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = "/root/repo:" + env.get("PYTHONPATH", "")
    p = subprocess.Popen(
        [sys.executable, "-m", "antidote_tpu.console", "serve",
         "--port", "0", "--shards", "4", "--max-dcs", "2"],
        env=env, stdout=subprocess.PIPE, stderr=subprocess.PIPE,
    )
    try:
        line = p.stdout.readline().decode()
        info = json.loads(line)
        c1 = AntidoteClient(info["host"], info["port"])
        c1.update_objects([("k", "counter_pn", "b", ("increment", 1))])
        c1.close()
        # crash the listener: a client sends a frame that explodes the
        # accept loop? — instead simulate by abusing the wire with a
        # huge frame length; the server must survive bad frames, so
        # this is a resilience probe, then confirm service continuity
        import socket
        import struct

        s = socket.create_connection((info["host"], info["port"]))
        s.sendall(struct.pack(">I", 0xFFFFFFF) + b"x")
        s.close()
        time.sleep(0.5)
        c2 = AntidoteClient(info["host"], info["port"])
        vals, _ = c2.read_objects([("k", "counter_pn", "b")])
        assert vals == [1]
        c2.close()
    finally:
        p.terminate()
        try:
            p.wait(timeout=10)
        except subprocess.TimeoutExpired:
            p.kill()
