"""Transaction-layer semantics, mirroring the reference's singledc suites
(clocksi_SUITE read-your-writes/isolation/concurrency, antidote_SUITE
static+interactive API, commit_hooks_SUITE; SURVEY §4 tier-3)."""

import pytest

from antidote_tpu.api import AbortError, AntidoteNode

pytestmark = pytest.mark.smoke


@pytest.fixture
def node(cfg):
    return AntidoteNode(cfg)


def test_static_update_then_read(node):
    vc = node.update_objects([("k1", "counter_pn", "b", ("increment", 4))])
    vals, _ = node.read_objects([("k1", "counter_pn", "b")], clock=vc)
    assert vals == [4]


def test_interactive_read_your_writes(node):
    txn = node.start_transaction()
    node.update_objects([("k", "counter_pn", "b", ("increment", 2))], txn)
    assert node.read_objects([("k", "counter_pn", "b")], txn) == [2]
    node.update_objects([("k", "counter_pn", "b", ("increment", 3))], txn)
    assert node.read_objects([("k", "counter_pn", "b")], txn) == [5]
    vc = node.commit_transaction(txn)
    vals, _ = node.read_objects([("k", "counter_pn", "b")], clock=vc)
    assert vals == [5]


def test_read_your_writes_set(node):
    txn = node.start_transaction()
    node.update_objects([("s", "set_aw", "b", ("add", "x"))], txn)
    assert node.read_objects([("s", "set_aw", "b")], txn) == [["x"]]
    node.update_objects([("s", "set_aw", "b", ("remove", "x"))], txn)
    assert node.read_objects([("s", "set_aw", "b")], txn) == [[]]
    node.commit_transaction(txn)
    vals, _ = node.read_objects([("s", "set_aw", "b")])
    assert vals == [[]]


def test_snapshot_isolation_between_txns(node):
    node.update_objects([("k", "counter_pn", "b", ("increment", 1))])
    txn = node.start_transaction()
    before = node.read_objects([("k", "counter_pn", "b")], txn)
    # another (static) txn commits concurrently
    node.update_objects([("k2", "counter_pn", "b", ("increment", 99))])
    node.update_objects([("k", "counter_pn", "b", ("increment", 99))],
                        clock=None)
    # the open txn still sees its snapshot
    after = node.read_objects([("k", "counter_pn", "b")], txn)
    assert before == after == [1]
    node.commit_transaction(txn)


def test_abort_discards_writes(node):
    txn = node.start_transaction()
    node.update_objects([("k", "counter_pn", "b", ("increment", 7))], txn)
    node.abort_transaction(txn)
    vals, _ = node.read_objects([("k", "counter_pn", "b")])
    assert vals == [0]


def test_certification_conflict_aborts_second_txn(node):
    """READ-BEARING (rmw) txns keep first-committer-wins; both read the
    key before writing, so neither takes the blind-commutative bypass
    (ISSUE 6)."""
    node.update_objects([("k", "counter_pn", "b", ("increment", 1))])
    t1 = node.start_transaction()
    t2 = node.start_transaction()
    node.read_objects([("k", "counter_pn", "b")], t1)
    node.read_objects([("k", "counter_pn", "b")], t2)
    node.update_objects([("k", "counter_pn", "b", ("increment", 10))], t1)
    node.update_objects([("k", "counter_pn", "b", ("increment", 100))], t2)
    node.commit_transaction(t1)
    with pytest.raises(AbortError):
        node.commit_transaction(t2)
    vals, _ = node.read_objects([("k", "counter_pn", "b")])
    assert vals == [11]


def test_blind_commutative_writes_never_conflict(node):
    """The ISSUE 6 certification bypass: BLIND counter increments from
    concurrent txns commute, so none aborts and none touches the
    certification stamp table — only the read-bearing txn above pays
    first-committer-wins."""
    t1 = node.start_transaction()
    t2 = node.start_transaction()
    node.update_objects([("k", "counter_pn", "b", ("increment", 10))], t1)
    node.update_objects([("k", "counter_pn", "b", ("increment", 100))], t2)
    node.commit_transaction(t1)
    node.commit_transaction(t2)  # would first-committer-abort pre-bypass
    vals, _ = node.read_objects([("k", "counter_pn", "b")])
    assert vals == [110]
    assert ("k", "b") not in node.txm.committed_keys


def test_certification_disabled_allows_both(cfg):
    node = AntidoteNode(cfg, cert=False)
    t1 = node.start_transaction()
    t2 = node.start_transaction()
    node.update_objects([("k", "counter_pn", "b", ("increment", 10))], t1)
    node.update_objects([("k", "counter_pn", "b", ("increment", 100))], t2)
    node.commit_transaction(t1)
    node.commit_transaction(t2)
    vals, _ = node.read_objects([("k", "counter_pn", "b")])
    assert vals == [110]


def test_read_only_txn_commits_at_snapshot(node):
    node.update_objects([("k", "counter_pn", "b", ("increment", 5))])
    txn = node.start_transaction()
    node.read_objects([("k", "counter_pn", "b")], txn)
    vc = node.commit_transaction(txn)
    assert (vc == txn.snapshot_vc).all()


def test_causal_clock_threading(node):
    vc1 = node.update_objects([("k", "counter_pn", "b", ("increment", 1))])
    vc2 = node.update_objects([("k", "counter_pn", "b", ("increment", 1))],
                              clock=vc1)
    assert vc2[node.dc_id] > vc1[node.dc_id]
    vals, _ = node.read_objects([("k", "counter_pn", "b")], clock=vc2)
    assert vals == [2]


def test_type_check_rejects_bad_ops(node):
    with pytest.raises(TypeError):
        node.update_objects([("k", "counter_pn", "b", ("assign", 5))])
    with pytest.raises(TypeError):
        node.update_objects([("k", "nosuch_type", "b", ("increment", 1))])
    # binding the same key to a different type fails
    node.update_objects([("k", "counter_pn", "b", ("increment", 1))])
    with pytest.raises(TypeError):
        node.update_objects([("k", "set_aw", "b", ("add", "x"))])


def test_pre_commit_hook_transforms_update(node):
    def double(kto):
        key, type_name, (kind, n) = kto
        return key, type_name, (kind, n * 2)

    node.register_pre_hook("hooked", double)
    node.update_objects([("k", "counter_pn", "hooked", ("increment", 3))])
    vals, _ = node.read_objects([("k", "counter_pn", "hooked")])
    assert vals == [6]


def test_pre_commit_hook_failure_aborts(node):
    def boom(kto):
        raise ValueError("nope")

    node.register_pre_hook("hooked", boom)
    with pytest.raises(AbortError):
        node.update_objects([("k", "counter_pn", "hooked", ("increment", 3))])
    vals, _ = node.read_objects([("k", "counter_pn", "hooked")])
    assert vals == [0]


def test_post_commit_hook_observes_commit(node):
    seen = []
    node.register_post_hook("hooked", lambda kto: seen.append(kto))
    node.update_objects([("k", "counter_pn", "hooked", ("increment", 3))])
    assert seen == [("k", "counter_pn", ("increment", 3))]


def test_post_commit_hook_failure_nonfatal(node):
    def boom(kto):
        raise ValueError("nope")

    node.register_post_hook("hooked", boom)
    vc = node.update_objects([("k", "counter_pn", "hooked", ("increment", 3))])
    vals, _ = node.read_objects([("k", "counter_pn", "hooked")], clock=vc)
    assert vals == [3]


def test_multi_key_multi_type_txn(node):
    txn = node.start_transaction()
    node.update_objects(
        [
            ("c", "counter_pn", "b", ("increment", 1)),
            ("r", "register_lww", "b", ("assign", "v")),
            ("s", "set_aw", "b", ("add_all", ["a", "b"])),
            ("f", "flag_ew", "b", ("enable", None)),
        ],
        txn,
    )
    vc = node.commit_transaction(txn)
    vals, _ = node.read_objects(
        [
            ("c", "counter_pn", "b"),
            ("r", "register_lww", "b"),
            ("s", "set_aw", "b"),
            ("f", "flag_ew", "b"),
        ],
        clock=vc,
    )
    assert vals == [1, "v", ["a", "b"], True]


def test_many_keys_across_shards(node):
    updates = [(i, "counter_pn", "b", ("increment", i)) for i in range(40)]
    vc = node.update_objects(updates)
    objs = [(i, "counter_pn", "b") for i in range(40)]
    vals, _ = node.read_objects(objs, clock=vc)
    assert vals == [i for i in range(40)]


# ---------------------------------------------------------------------------
# decoded-value cache (the host-level snapshot_cache analogue)
# ---------------------------------------------------------------------------
def test_value_cache_invalidation_on_write(node):
    """Repeated latest reads serve from the decoded-value cache; every
    write to the key (or to a map's field/membership) invalidates it —
    reads must never see a stale cached value."""
    node.update_objects([("c", "counter_pn", "b", ("increment", 1))])
    for expect in (1, 2, 3):
        vals, _ = node.read_objects([("c", "counter_pn", "b")])
        assert vals[0] == expect
        vals, _ = node.read_objects([("c", "counter_pn", "b")])  # cached
        assert vals[0] == expect
        node.update_objects([("c", "counter_pn", "b", ("increment", 1))])
    # composite: field write invalidates the assembled-map entry
    node.update_objects([("m", "map_rr", "b", ("update", {
        ("k", "counter_pn"): ("increment", 5)}))])
    vals, _ = node.read_objects([("m", "map_rr", "b")])
    assert vals[0][("k", "counter_pn")] == 5
    vals, _ = node.read_objects([("m", "map_rr", "b")])  # cached
    assert vals[0][("k", "counter_pn")] == 5
    node.update_objects([("m", "map_rr", "b", ("update", {
        ("k", "counter_pn"): ("increment", 2)}))])
    vals, _ = node.read_objects([("m", "map_rr", "b")])
    assert vals[0][("k", "counter_pn")] == 7


def test_value_cache_historical_reads_bypass(node):
    """A cached latest value must not serve an open txn's older
    snapshot (the clock= parameter is only a causal LOWER bound — the
    snapshot-isolation case is a txn opened before later commits)."""
    node.update_objects([("s", "set_aw", "b", ("add", "x"))])
    txn = node.start_transaction()  # snapshot: only x
    node.update_objects([("s", "set_aw", "b", ("add", "y"))])
    vals, _ = node.read_objects([("s", "set_aw", "b")])
    assert vals[0] == ["x", "y"]  # fills the cache at latest
    vals = node.read_objects([("s", "set_aw", "b")], txn)
    assert vals[0] == ["x"], "old snapshot served the newer cached value"
    node.commit_transaction(txn)
    vals, _ = node.read_objects([("s", "set_aw", "b")])
    assert vals[0] == ["x", "y"]


def test_value_cache_client_mutation_isolated(node):
    """Mutating a returned container must not poison the cache."""
    node.update_objects([("s2", "set_aw", "b", ("add_all", ["a", "b"]))])
    vals, _ = node.read_objects([("s2", "set_aw", "b")])
    vals[0].append("EVIL")
    vals2, _ = node.read_objects([("s2", "set_aw", "b")])
    assert vals2[0] == ["a", "b"]
    node.update_objects([("m2", "map_rr", "b", ("update", {
        ("t", "set_aw"): ("add", "z")}))])
    mv, _ = node.read_objects([("m2", "map_rr", "b")])
    mv[0][("t", "set_aw")].append("EVIL")
    mv[0][("extra", "counter_pn")] = 666
    mv2, _ = node.read_objects([("m2", "map_rr", "b")])
    assert mv2[0] == {("t", "set_aw"): ["z"]}


def test_value_cache_nested_map_mutation_isolated(node):
    """Deep containers: mutating an INNER dict of a nested map must not
    poison the cache (the copy is recursive, not one level)."""
    node.update_objects([("mm", "map_rr", "b", ("update", {
        ("n", "map_rr"): ("update", {("c", "counter_pn"): ("increment", 1)}),
    }))])
    v, _ = node.read_objects([("mm", "map_rr", "b")])
    assert v[0][("n", "map_rr")][("c", "counter_pn")] == 1
    v[0][("n", "map_rr")][("c", "counter_pn")] = 999
    v2, _ = node.read_objects([("mm", "map_rr", "b")])
    assert v2[0][("n", "map_rr")][("c", "counter_pn")] == 1


def test_overlay_dots_restamped_under_interleaved_commits(node):
    """A txn's remove observing its OWN in-txn add must survive other
    txns committing in between (the tentative own-lane dot is rewritten
    to the real commit ts at commit — restamp_own_dots)."""
    txn = node.start_transaction()
    node.update_objects([("s", "set_aw", "b", ("add", "x"))], txn)
    # interleaved commits advance the commit counter past the tentative
    for i in range(3):
        node.update_objects([(f"o{i}", "counter_pn", "b", ("increment", 1))])
    node.update_objects([("s", "set_aw", "b", ("remove", "x"))], txn)
    node.commit_transaction(txn)
    vals, _ = node.read_objects([("s", "set_aw", "b")])
    assert vals[0] == [], "same-txn remove lost under interleaving"
    # mv register: second assign observes the first's tentative id
    txn = node.start_transaction()
    node.update_objects([("r", "register_mv", "b", ("assign", "a"))], txn)
    node.update_objects([("x", "counter_pn", "b", ("increment", 1))])
    node.update_objects([("r", "register_mv", "b", ("assign", "b"))], txn)
    node.commit_transaction(txn)
    vals, _ = node.read_objects([("r", "register_mv", "b")])
    assert vals[0] == ["b"], "observed-overwrite lost under interleaving"


def test_rga_same_txn_inserts_have_distinct_uids(node):
    """One txn inserting several elements: each element's uid must stay
    unique (op-seq lane), so a later delete targets the RIGHT one."""
    txn = node.start_transaction()
    node.update_objects([("d", "rga", "b", ("insert", (0, "a")))], txn)
    node.update_objects([("d", "rga", "b", ("insert", (1, "b")))], txn)
    node.update_objects([("d", "rga", "b", ("insert", (2, "c")))], txn)
    node.commit_transaction(txn)
    vals, _ = node.read_objects([("d", "rga", "b")])
    assert vals[0] == ["a", "b", "c"]
    node.update_objects([("d", "rga", "b", ("delete", 1))])
    vals, _ = node.read_objects([("d", "rga", "b")])
    assert vals[0] == ["a", "c"], "delete hit the wrong same-commit uid"
    # interleaved-commit variant: delete an element inserted in an open
    # txn whose tentative ts got stale
    txn = node.start_transaction()
    node.update_objects([("d2", "rga", "b", ("insert", (0, "p")))], txn)
    node.update_objects([("z", "counter_pn", "b", ("increment", 1))])
    node.update_objects([("d2", "rga", "b", ("insert", (1, "q")))], txn)
    node.update_objects([("d2", "rga", "b", ("delete", 0))], txn)
    node.commit_transaction(txn)
    vals, _ = node.read_objects([("d2", "rga", "b")])
    assert vals[0] == ["q"]
