"""Symmetric serving fabric (ISSUE 17): any node is a safe entrypoint.

Unit tests pin the shared fleet-health table (DEAD_S cooldowns,
membership rebuilds, unseeded placement vs seeded failover tails) and
the deadline-propagation arithmetic of the proxy plane.  Wire tests
drive ring-oblivious clients — native AND apb — through ONE arbitrary
follower under mixed read/write/txn load: zero typed redirects
surface, read-your-writes holds, and the follower's forwarded-traffic
counters move.  The proxy-loop guard (one hop max), the send-phase
redial / exhaustion discipline at the ``proxy.forward`` chaos site,
server-side read failover around a killed arc owner, the ring-hint
learning loop, and the ``--no-server-proxy`` opt-out (which preserves
the PR-11 typed vocabulary) each get their own pin.
"""

import threading
import time
from contextlib import contextmanager
from types import SimpleNamespace

import pytest

from antidote_tpu import faults
from antidote_tpu.api import AntidoteNode
from antidote_tpu.config import AntidoteConfig
from antidote_tpu.interdc import DCReplica, FollowerReplica
from antidote_tpu.interdc.tcp import TcpFabric
from antidote_tpu.obs.metrics import NodeMetrics
from antidote_tpu.overload import DeadlineExceeded
from antidote_tpu.proto.client import (AntidoteClient, ApbClient,
                                       RemoteInsufficientRights,
                                       RemoteLagging, RemoteNotOwner,
                                       SessionClient)
from antidote_tpu.proto.proxy import FleetHealth, ProxyPlane
from antidote_tpu.proto.server import ProtocolServer

pytestmark = pytest.mark.smoke


@pytest.fixture
def cfg():
    # same shapes as the follower/chaos suites: warm XLA compile cache
    return AntidoteConfig(
        n_shards=2, max_dcs=3, ops_per_key=8, snap_versions=2,
        set_slots=4, keys_per_table=16, batch_buckets=(8,),
    )


@pytest.fixture(autouse=True)
def _disarm():
    yield
    faults.uninstall()


# ---------------------------------------------------------------------------
# Part A — units (no sockets)
# ---------------------------------------------------------------------------
def test_remaining_ms_propagates_instead_of_resetting():
    """The inner hop gets the budget LEFT, not a fresh one — queue time
    burned on the proxying node is never granted back."""
    assert ProxyPlane._remaining_ms(None) is None
    now = time.monotonic()
    ms = ProxyPlane._remaining_ms(now + 0.5)
    assert 250.0 <= ms <= 500.0
    # an already-blown deadline clamps to the 1 ms floor (the target's
    # own check_deadline then refuses it typed)
    assert ProxyPlane._remaining_ms(now - 5.0) == 1.0


def test_expired_deadline_refuses_without_dialing():
    """A dead deadline is refused typed BEFORE any channel is dialed —
    a proxy must not spend sockets on work the client already gave up
    on.  (The fake owner addr would hang a real dial.)"""
    fol = SimpleNamespace(owner_client_addr=("203.0.113.9", 9),
                          client_addr=("203.0.113.1", 1),
                          fleet_table={}, fleet_table_v=0)
    plane = ProxyPlane(fol, NodeMetrics())
    past = time.monotonic() - 1.0
    try:
        with pytest.raises(DeadlineExceeded):
            plane.proxy_read([("k", "counter_pn", "b")], None, past)
        with pytest.raises(DeadlineExceeded):
            plane.forward_update([("k", "counter_pn", "b",
                                   ("increment", 1))], None, past)
    finally:
        plane.close()


def test_fleet_health_membership_cooldown_and_agreement():
    fh = FleetHealth(vnodes=16, seed=7)
    fleet = {
        "f1": {"addr": ["h1", 1], "state": "ok"},
        "f2": {"addr": ["h2", 2], "state": "ok"},
        "f3": {"addr": ["h3", 3], "state": "down"},
    }
    fh.update_fleet(fleet)
    # a registry-DOWN follower never makes the serving ring
    assert ("h3", 3) not in fh.ring.endpoints
    cands = fh.candidates("k", "b")
    assert set(cands) == {("h1", 1), ("h2", 2)}
    pref = fh.preferred("k", "b")
    assert cands[0] == pref
    # a local connect/timeout observation kills the arc for DEAD_S
    fh.mark_dead(pref)
    assert not fh.alive(pref)
    assert fh.candidates("k", "b") == [ep for ep in cands if ep != pref]
    # cooldown expiry brings the arc back without a registry round-trip
    fh._dead[pref] = time.monotonic() - 0.01
    assert fh.alive(pref)
    assert fh.preferred("k", "b") == pref
    # placement is UNSEEDED: differently-seeded nodes agree on the
    # preferred arc owner (fleet-wide agreement), only the failover
    # tail is per-node jittered
    fh_b = FleetHealth(vnodes=16, seed=991)
    fh_b.update_fleet(fleet)
    for key in ("k", "a", "z9", "session/7"):
        assert fh_b.preferred(key, "b") == fh.preferred(key, "b")


def test_apb_errmsg_fleet_param_round_trips():
    from antidote_tpu.proto import apb

    text = apb.error_text("lagging", "behind the token", 40, ["h", 1],
                          fleet=[["fa", 10], ["fb", 11]])
    out = apb.parse_error_text(text)
    assert out["kind"] == "lagging"
    assert out["redirect"] == ["h", 1]
    assert out["fleet"] == [["fa", 10], ["fb", 11]]
    assert out["detail"] == "behind the token"
    # a foreign server's malformed fleet never crashes the parse
    out = apb.parse_error_text(b"lagging fleet=oops: x")
    assert out["kind"] == "lagging" and out["fleet"] is None


# ---------------------------------------------------------------------------
# Part B — the wire fabric (owner + followers on real sockets)
# ---------------------------------------------------------------------------
class _Pump:
    def __init__(self, *fabrics):
        self.stop = threading.Event()
        self.threads = [
            threading.Thread(target=self._loop, args=(f,), daemon=True)
            for f in fabrics
        ]
        for t in self.threads:
            t.start()

    def _loop(self, fabric):
        while not self.stop.is_set():
            try:
                fabric.pump(timeout=0.05)
            except OSError:
                time.sleep(0.02)

    def close(self):
        self.stop.set()
        for t in self.threads:
            t.join(timeout=10)


def _wire_follower(cfg, tmp_path, owner_srv, name, fid, park_s=0.1,
                   **srv_kw):
    fabric = TcpFabric(backoff_base=0.05, backoff_max=0.5)
    node = AntidoteNode(cfg, dc_id=0, log_dir=str(tmp_path / name))
    fol = FollowerReplica(node, fabric, name,
                          owner_client_addr=(owner_srv.host,
                                             owner_srv.port),
                          fabric_id=fid, park_s=park_s)
    srv = ProtocolServer(node, port=0, follower=fol, **srv_kw)
    fol.client_addr = (srv.host, srv.port)
    c = AntidoteClient(owner_srv.host, owner_srv.port)
    desc = c.get_connection_descriptor()
    c.close()
    mode = fol.attach(desc)
    return {"node": node, "fol": fol, "srv": srv, "fabric": fabric,
            "mode": mode}


@contextmanager
def _cluster(cfg, tmp_path, followers=2, **srv_kw):
    """Owner + N wire followers, fabrics pumped, fleet tables primed
    (two report rounds: register everyone, then distribute the
    complete registry snapshot to every node)."""
    ofab = TcpFabric(backoff_base=0.05, backoff_max=0.5)
    owner = AntidoteNode(cfg, dc_id=0, log_dir=str(tmp_path / "owner"))
    orep = DCReplica(owner, ofab, "dc0")
    osrv = ProtocolServer(owner, port=0, interdc=orep)
    pumps = [_Pump(ofab)]
    fs = []
    oc = None
    try:
        oc = AntidoteClient(osrv.host, osrv.port)
        oc.update_objects([("seed", "counter_pn", "b", ("increment", 1))])
        oc.checkpoint_now()
        for i in range(followers):
            fs.append(_wire_follower(cfg, tmp_path, osrv, f"pf{i + 1}",
                                     111 + i, **srv_kw))
        pumps.append(_Pump(*[f["fabric"] for f in fs]))
        for _round in range(2):
            for f in fs:
                f["fol"]._send_report()
        yield {"owner": owner, "orep": orep, "osrv": osrv, "oc": oc,
               "fs": fs}
    finally:
        if oc is not None:
            oc.close()
        for p in reversed(pumps):
            p.close()
        for f in fs:
            f["srv"].close()
            f["fabric"].close()
            f["node"].store.log.close()
        osrv.close()
        ofab.close()
        owner.store.log.close()


def test_ring_oblivious_native_client_mixed_load(cfg, tmp_path):
    """The acceptance flow: a bare AntidoteClient that knows ONE
    arbitrary follower and nothing about the ring drives writes, static
    reads, and an interactive transaction — every op succeeds (zero
    typed redirects), read-your-writes holds at the session token, and
    the follower's forwarded-traffic counters account for the hops."""
    with _cluster(cfg, tmp_path, followers=2) as cl:
        f1 = cl["fs"][0]
        assert f1["fol"].fleet_table_v >= 1  # fleet learned via reports
        fc = AntidoteClient(f1["srv"].host, f1["srv"].port)
        total, vc = 0, None
        for i in range(6):
            vc = fc.update_objects(
                [("k", "counter_pn", "b", ("increment", 1)),
                 ("s", "set_aw", "b", ("add", f"e{i}"))], clock=vc)
            total += 1
            vals, vc = fc.read_objects(
                [("k", "counter_pn", "b"), ("s", "set_aw", "b")],
                clock=vc)
            assert vals[0] == total, (i, vals)
            assert len(vals[1]) == total
        # interactive txn through the same follower: forwarded over the
        # sticky owner channel
        txn = fc.start_transaction(clock=vc)
        txn.update_objects([("k", "counter_pn", "b", ("increment", 1))])
        assert txn.read_objects([("k", "counter_pn", "b")]) == [total + 1]
        cc = txn.commit()
        total += 1
        vals, _ = fc.read_objects([("k", "counter_pn", "b")], clock=cc)
        assert vals == [total]
        # zero typed redirects surfaced to this ring-oblivious client:
        # every op above succeeded in place.  (The gate's internal
        # lagging refusals were rescued server-side — they show up as
        # proxy failovers, never as client errors.)
        m = f1["node"].metrics
        assert m.session_redirects.value(kind="not_owner",
                                         dialect="native") == 0
        st = fc.node_status()["pipeline"]["proxy"]
        assert st["forwarded"]["write"] >= 6
        assert st["forwarded"]["txn"] >= 4
        fc.close()


def test_ring_oblivious_apb_client_mixed_load(cfg, tmp_path):
    """Satellite 1: the apb dialect gets the same any-node entrypoint —
    static writes forward, interactive txns ride the sticky channel,
    reads hold RYW, and typed errors never surface while the owner is
    reachable.  (apb keys are raw bytes — a distinct keyspace from the
    native str keys.)"""
    import msgpack

    from antidote_tpu.proto import apb

    with _cluster(cfg, tmp_path, followers=2) as cl:
        f1 = cl["fs"][0]
        ac = ApbClient(f1["srv"].host, f1["srv"].port)
        total, vc = 0, None
        for i in range(4):
            vc = ac.update_objects(
                [(b"pk", "counter_pn", b"b", ("increment", 1))], clock=vc)
            total += 1
            vals, vc = ac.read_objects([(b"pk", "counter_pn", b"b")],
                                       clock=vc)
            assert vals == [total], (i, vals)
        # interactive apb txn, raw frames: START / UPDATE / READ / COMMIT
        name, resp = ac._call("ApbStartTransaction",
                              {"timestamp": msgpack.packb(
                                  [int(x) for x in vc])})
        assert name == "ApbStartTransactionResp" and resp["success"]
        td = resp["transaction_descriptor"]
        name, resp = ac._call("ApbUpdateObjects", {
            "transaction_descriptor": td,
            "updates": [apb.update_op_from_native(
                (b"pk", "counter_pn", b"b", ("increment", 1)))],
        })
        assert name == "ApbOperationResp" and resp["success"]
        name, resp = ac._call("ApbReadObjects", {
            "transaction_descriptor": td,
            "boundobjects": [{"key": b"pk",
                              "type": apb.TYPE_IDS["counter_pn"],
                              "bucket": b"b"}],
        })
        assert name == "ApbReadObjectsResp"
        assert resp["objects"][0]["counter"]["value"] == total + 1
        name, resp = ac._call("ApbCommitTransaction",
                              {"transaction_descriptor": td})
        assert name == "ApbCommitResp" and resp["success"]
        total += 1
        cc = msgpack.unpackb(resp["commit_time"], raw=False)
        vals, _ = ac.read_objects([(b"pk", "counter_pn", b"b")], clock=cc)
        assert vals == [total]
        m = f1["node"].metrics
        assert m.session_redirects.value(kind="not_owner",
                                         dialect="apb") == 0
        ac.close()


def test_session_client_learns_ring_from_hints(cfg, tmp_path):
    """Satellite 2: a SessionClient seeded with ONE follower rebuilds
    its fleet in place from the ring-hint riding proxied replies —
    no refresh_fleet round trip — and converges to the full ring."""
    with _cluster(cfg, tmp_path, followers=2) as cl:
        f1 = cl["fs"][0]
        sc = SessionClient((cl["osrv"].host, cl["osrv"].port),
                           [(f1["srv"].host, f1["srv"].port)])
        assert len(sc.ring) == 1
        deadline = time.monotonic() + 30
        i = 0
        while sc.hints_applied == 0:
            assert time.monotonic() < deadline, "no ring hint absorbed"
            sc.update_objects([(f"hk{i}", "counter_pn", "b",
                                ("increment", 1))])
            vals, _ = sc.read_objects([(f"hk{i}", "counter_pn", "b")])
            assert vals == [1], (i, vals)
            i += 1
        assert sc.stats()["ring_size"] == 2
        assert sc.redirects == 0
        sc.close()


def test_proxied_flag_is_a_one_hop_loop_guard(cfg, tmp_path):
    """A request already marked ``proxied`` is NEVER re-proxied or
    re-forwarded: the first hop owns failover, so a partitioned fleet
    degrades to the typed vocabulary instead of a forwarding cycle.
    The typed replies still carry the ring hint (teach-don't-bounce)."""
    with _cluster(cfg, tmp_path, followers=2) as cl:
        f1 = cl["fs"][0]
        fc = AntidoteClient(f1["srv"].host, f1["srv"].port)
        ahead = [int(x) + 50
                 for x in cl["owner"].store.dc_max_vc()]
        with pytest.raises(RemoteLagging) as ei:
            fc.read_objects([("k", "counter_pn", "b")], clock=ahead,
                            proxied=True)
        assert ei.value.retry_after_ms > 0
        with pytest.raises(RemoteNotOwner) as ei:
            fc.update_objects([("k", "counter_pn", "b",
                                ("increment", 1))], proxied=True)
        assert ei.value.redirect == [cl["osrv"].host, cl["osrv"].port]
        # both typed refusals taught the client the fleet anyway
        assert fc.ring_hint is not None
        assert len(fc.ring_hint["followers"]) == 2
        fc.close()


def test_forward_redials_send_phase_faults_then_surfaces_typed(cfg,
                                                              tmp_path):
    """At-most-once discipline at the ``proxy.forward`` chaos site: a
    send-phase hop death redials within the bounded budget (the write
    still commits, counted as a failover); exhausting every attempt
    surfaces the typed not_owner redirect — never a blind resend."""
    with _cluster(cfg, tmp_path, followers=1) as cl:
        f1 = cl["fs"][0]
        ep = f"{cl['osrv'].host}:{cl['osrv'].port}"
        fc = AntidoteClient(f1["srv"].host, f1["srv"].port)
        faults.install(
            faults.FaultPlan(seed=3).error("proxy.forward", key=ep,
                                           times=1))
        vc = fc.update_objects([("fk", "counter_pn", "b",
                                 ("increment", 1))])
        faults.uninstall()
        vals, _ = fc.read_objects([("fk", "counter_pn", "b")], clock=vc)
        assert vals == [1]
        st = fc.node_status()["pipeline"]["proxy"]
        assert st["forwarded"]["failover"] >= 1
        # every attempt dead: typed redirect with the owner endpoint
        faults.install(
            faults.FaultPlan(seed=4).error("proxy.forward", key=ep,
                                           times=ProxyPlane.FORWARD_ATTEMPTS))
        with pytest.raises(RemoteNotOwner) as ei:
            fc.update_objects([("fk", "counter_pn", "b",
                                ("increment", 1))])
        assert ei.value.redirect == [cl["osrv"].host, cl["osrv"].port]
        faults.uninstall()
        # the fabric heals as soon as the fault plan is gone
        vc = fc.update_objects([("fk", "counter_pn", "b",
                                 ("increment", 1))])
        vals, _ = fc.read_objects([("fk", "counter_pn", "b")], clock=vc)
        assert vals == [2]
        fc.close()


def test_server_side_read_failover_around_dead_arc_owner(cfg, tmp_path):
    """Tentpole (c): when the arc owner dies, the node holding the
    client's socket fails the read over server-side — local DEAD_S
    observation plus the seeded failover tail — and the ring-oblivious
    client never sees a typed error."""
    with _cluster(cfg, tmp_path, followers=2) as cl:
        f1, f2 = cl["fs"]
        plane = f1["srv"].proxy
        f2_ep = (f2["srv"].host, f2["srv"].port)
        key = next(f"rk{i}" for i in range(64)
                   if plane.route([(f"rk{i}", "counter_pn", "b")])
                   == f2_ep)
        fc = AntidoteClient(f1["srv"].host, f1["srv"].port)
        vc = fc.update_objects([(key, "counter_pn", "b",
                                 ("increment", 1))])
        # SIGKILL-equivalent for an in-process test: server + fabric die
        f2["srv"].close()
        f2["fabric"].close()
        f2["node"].store.log.close()
        cl["fs"].remove(f2)
        vals, _ = fc.read_objects([(key, "counter_pn", "b")], clock=vc)
        assert vals == [1]
        assert not plane.health.alive(f2_ep)  # local observation
        st = fc.node_status()["pipeline"]["proxy"]
        assert st["forwarded"]["failover"] >= 1
        assert f"{f2_ep[0]}:{f2_ep[1]}" in st["fleet"]["locally_dead"]
        fc.close()


def test_no_server_proxy_opt_out_preserves_typed_vocabulary(cfg,
                                                            tmp_path):
    """The ``--no-server-proxy`` operator escape hatch: a plane-less
    follower answers the PR-11 typed redirects (ring-aware clients
    keep their client-side failover), it just stops being a safe
    entrypoint for bare clients."""
    with _cluster(cfg, tmp_path, followers=1,
                  server_proxy=False) as cl:
        f1 = cl["fs"][0]
        assert f1["srv"].proxy is None
        fc = AntidoteClient(f1["srv"].host, f1["srv"].port)
        with pytest.raises(RemoteNotOwner) as ei:
            fc.update_objects([("k", "counter_pn", "b",
                                ("increment", 1))])
        assert ei.value.redirect == [cl["osrv"].host, cl["osrv"].port]
        ahead = [int(x) + 50
                 for x in cl["owner"].store.dc_max_vc()]
        with pytest.raises(RemoteLagging) as ei:
            fc.read_objects([("k", "counter_pn", "b")], clock=ahead)
        assert ei.value.retry_after_ms > 0
        fc.close()


def test_forwarded_bcounter_refusal_is_typed_and_at_most_once(cfg,
                                                              tmp_path):
    """ISSUE 18: a counter_b decrement through a ring-oblivious follower
    forwards to the owner; an escrow shortfall comes back as the typed
    ``insufficient_rights`` refusal (retry hint intact) with EXACTLY one
    forwarded attempt — the proxy never blind-resends a refused spend —
    and a covered decrement on the same socket commits."""
    with _cluster(cfg, tmp_path, followers=1) as cl:
        f1 = cl["fs"][0]
        fc = AntidoteClient(f1["srv"].host, f1["srv"].port)
        vc = fc.update_objects([("sku", "counter_b", "b",
                                 ("increment", (3, 0)))])
        base = fc.node_status()["pipeline"]["proxy"]["forwarded"]["write"]
        with pytest.raises(RemoteInsufficientRights) as ei:
            fc.update_objects([("sku", "counter_b", "b",
                                ("decrement", (5, 0)))], clock=vc)
        assert ei.value.retry_after_ms > 0
        assert "need 5, hold 3" in str(ei.value)
        st = fc.node_status()["pipeline"]["proxy"]
        # at-most-once: one client call, one forwarded attempt — the
        # refusal surfaced instead of being retried into an oversell
        assert st["forwarded"]["write"] == base + 1
        assert cl["owner"].txm.bcounters.refused_total == 1
        # the owner queued the shortfall for its transfer loop
        assert cl["owner"].txm.bcounters.shortfall() == 5
        # a covered decrement on the same socket commits and retires
        # nothing it shouldn't (value 3-2=1)
        vc = fc.update_objects([("sku", "counter_b", "b",
                                ("decrement", (2, 0)))], clock=vc)
        vals, _ = fc.read_objects([("sku", "counter_b", "b")], clock=vc)
        assert vals == [1]
        # escrow block rides node status (the refusal was the owner's)
        esc = cl["owner"].status()["escrow"]
        assert esc["refused_total"] == 1
        assert "escrow" in fc.node_status()  # surfaced on the wire too
        fc.close()
