"""Native serving front-end tests (ISSUE 16): frame-fuzz parity with
the Python decoder, whole-batch hit byte-parity, admission-shed parity,
fault-site coverage on the native accept path, graceful fallback, and
the SIGKILL-under-socket-storm chaos scenario.

The contract under test: the C++ front-end (accept / framing / decode /
admission / whole-batch cache hits off the GIL) is BEHAVIORALLY
INDISTINGUISHABLE from the Python socketserver plane — same typed error
replies for wrecked frames, same busy shapes with retry hints, same
bytes for a cache hit at equal epoch ids — and its durability story is
the WAL's, untouched: acked ⊆ recovered across a SIGKILL mid-storm.
"""

import json
import os
import random
import selectors
import signal
import socket
import struct
import subprocess
import sys
import time

import msgpack
import pytest

from antidote_tpu import faults
from antidote_tpu.api.node import AntidoteNode
from antidote_tpu.config import AntidoteConfig
from antidote_tpu.proto.client import AntidoteClient
from antidote_tpu.proto.codec import (
    MAX_FRAME,
    MessageCode,
    decode,
    read_frame,
)
from antidote_tpu.proto.server import ProtocolServer

_HDR = struct.Struct(">I")


@pytest.fixture(autouse=True)
def _disarm():
    yield
    faults.uninstall()


def mk_cfg():
    # same shapes as test_proto/test_overload: warm XLA compile cache
    return AntidoteConfig(
        n_shards=2, max_dcs=2, ops_per_key=8, snap_versions=2,
        set_slots=8, rga_slots=16, keys_per_table=64, batch_buckets=(8, 64),
    )


def _boot(native: bool, **kw):
    node = AntidoteNode(mk_cfg())
    srv = ProtocolServer(node, port=0, native_frontend=native, **kw)
    if native and srv.native is None:
        srv.close()
        pytest.skip("native frontend unavailable (no g++/epoll)")
    return node, srv


def _raw_frame(code: int, body) -> bytes:
    payload = bytes([code]) + msgpack.packb(body, use_bin_type=True)
    return _HDR.pack(len(payload)) + payload


def _probe(port: int, raw: bytes, timeout: float = 10.0):
    """Send raw bytes on a fresh conn, half-close, and report the
    outcome: ("reply", frame) or ("closed", None).  Half-closing makes
    the silent-drop cases deterministic on both planes — the server
    sees EOF instead of waiting forever for the rest of a frame."""
    s = socket.create_connection(("127.0.0.1", port), timeout=timeout)
    s.settimeout(timeout)
    try:
        s.sendall(raw)
        s.shutdown(socket.SHUT_WR)
        try:
            return ("reply", read_frame(s))
        except (ConnectionError, OSError):
            return ("closed", None)
    finally:
        s.close()


# ---------------------------------------------------------------------------
# basic serving + observability
# ---------------------------------------------------------------------------
@pytest.mark.smoke
def test_native_plane_serves_and_reports_stats():
    node, srv = _boot(True)
    c = AntidoteClient(port=srv.port)
    try:
        c.update_objects([("k", "counter_pn", "b", ("increment", 5))])
        vals, clock = c.read_objects([("k", "counter_pn", "b")],
                                     clock=None)
        # clocked read-your-writes still holds through the native accept
        vals2, _ = c.read_objects([("k", "counter_pn", "b")], clock=clock)
        assert vals2 == [5]
        st = srv.native.stats()
        assert st["accepted"] >= 1
        assert st["frames"] >= 3
        assert srv._pipeline_status()["native"]["open_conns"] >= 1
    finally:
        c.close()
        srv.close()


# ---------------------------------------------------------------------------
# whole-batch hit byte parity (acceptance: native replies byte-identical
# to the Python serving path at equal epoch ids)
# ---------------------------------------------------------------------------
@pytest.mark.smoke
def test_whole_batch_hit_bytes_match_python_path():
    node, srv = _boot(True, epoch_tick_ms=25)
    c = AntidoteClient(port=srv.port)
    s = None
    try:
        c.update_objects([("pk", "counter_pn", "b", ("increment", 11))])
        # let the serving epoch cover the write and the vc go quiescent:
        # with no further commits, publish keeps re-advancing the SAME
        # clock, so replies on either plane must be byte-identical
        time.sleep(0.6)
        req = _raw_frame(MessageCode.STATIC_READ_OBJECTS, {
            "objects": [["pk", "counter_pn", "b"]], "clock": None,
        })
        s = socket.create_connection(("127.0.0.1", srv.port), timeout=10)
        s.settimeout(10)
        replies = []
        deadline = time.monotonic() + 20
        hits0 = srv.native.stats()["native_hits"]
        while srv.native.stats()["native_hits"] == hits0:
            assert time.monotonic() < deadline, \
                "native plane never served a whole-batch hit"
            s.sendall(req)
            replies.append(read_frame(s))
        s.sendall(req)  # one more, definitely native-served
        replies.append(read_frame(s))
        # the first reply crossed to Python (cold mirror); the last was
        # served by the C++ mirror — byte-identical, including the
        # msgpack map layout and the commit clock
        assert replies[-1] == replies[0], (
            "native hit bytes diverge from the Python reply:\n"
            f"  python: {replies[0]!r}\n  native: {replies[-1]!r}")
        code, body = decode(replies[-1])
        assert code == MessageCode.READ_OBJECTS_RESP
        assert body["values"] == [11]
    finally:
        if s is not None:
            s.close()
        c.close()
        srv.close()


# ---------------------------------------------------------------------------
# write invalidation: clockless reads through the native mirror are
# bounded-stale and converge after every write
# ---------------------------------------------------------------------------
def test_native_mirror_invalidation_converges_and_never_overshoots():
    node, srv = _boot(True, epoch_tick_ms=25)
    c = AntidoteClient(port=srv.port)
    try:
        total = 0
        for round_ in range(8):
            total += 1
            c.update_objects(
                [("wk", "counter_pn", "b", ("increment", 1))])
            deadline = time.monotonic() + 20
            while True:
                vals, _ = c.read_objects([("wk", "counter_pn", "b")],
                                         clock=None)
                # staleness is bounded by the epoch cadence; a value
                # BEYOND the committed total would mean the mirror
                # served bytes the store never published
                assert vals[0] <= total, (round_, vals[0], total)
                if vals[0] == total:
                    break
                assert time.monotonic() < deadline, \
                    f"clockless read stuck at {vals[0]} < {total}"
                time.sleep(0.01)
            # converged: the Python fill re-armed the mirror — repeat
            # reads between writes are exactly what the fast path owns
            for _ in range(4):
                vals, _ = c.read_objects([("wk", "counter_pn", "b")],
                                         clock=None)
                assert vals == [total]
        # the loop must have exercised the native fast path for real
        assert srv.native.stats()["native_hits"] > 0
    finally:
        c.close()
        srv.close()


# ---------------------------------------------------------------------------
# frame-fuzz parity: a seeded corpus of wrecked frames answered
# IDENTICALLY by both accept planes (same typed error or same silent
# close — the Python decoder's contract is the spec)
# ---------------------------------------------------------------------------
@pytest.mark.smoke
def test_frame_fuzz_corpus_parity():
    node_n, srv_n = _boot(True)
    node_p, srv_p = _boot(False)
    cp = AntidoteClient(port=srv_p.port)
    cn = AntidoteClient(port=srv_n.port)
    try:
        for cli in (cn, cp):  # identical prefill on both nodes
            cli.update_objects(
                [("fz", "counter_pn", "b", ("increment", 3))])
        time.sleep(0.4)
        rng = random.Random(0xF00D)
        corpus = []
        # -- valid reads: served (value parity asserted below)
        corpus.append(("valid-read", _raw_frame(
            MessageCode.STATIC_READ_OBJECTS,
            {"objects": [["fz", "counter_pn", "b"]], "clock": None})))
        corpus.append(("valid-read-miss", _raw_frame(
            MessageCode.STATIC_READ_OBJECTS,
            {"objects": [["nope", "counter_pn", "b"]], "clock": None})))
        # -- counter_b frames (ISSUE 18): a valid escrow mint, then a
        #    decrement beyond rights — the typed insufficient_rights
        #    refusal (kind, detail, retry hint) must be byte-identical
        #    across both accept planes
        corpus.append(("bcounter-mint", _raw_frame(
            MessageCode.STATIC_UPDATE_OBJECTS,
            {"updates": [["bz", "counter_b", "b", ["increment", [3, 0]]]],
             "clock": None})))
        corpus.append(("bcounter-overdraw", _raw_frame(
            MessageCode.STATIC_UPDATE_OBJECTS,
            {"updates": [["bz", "counter_b", "b", ["decrement", [9, 0]]]],
             "clock": None})))
        # -- garbage msgpack bodies behind a valid header + code byte:
        #    typed ERROR_RESP (decode exception name), conn kept
        for i in range(6):
            junk = bytes(rng.randrange(256) for _ in range(
                rng.randrange(1, 40)))
            payload = bytes([MessageCode.STATIC_READ_OBJECTS]) + junk
            corpus.append((f"garbage-body-{i}",
                           _HDR.pack(len(payload)) + payload))
        # -- well-formed msgpack, wrong shape: typed ERROR_RESP too
        corpus.append(("wrong-shape", _raw_frame(
            MessageCode.STATIC_READ_OBJECTS, {"objects": 42})))
        corpus.append(("unknown-code",
                       _HDR.pack(2) + bytes([251]) + b"\xc0"))
        # -- framing violations: the Python decoder drops the conn
        #    silently (ConnectionError in read_frame_buffered) — the
        #    native plane must mirror every one of these
        corpus.append(("zero-length", _HDR.pack(0) + b"\x00"))
        corpus.append(("oversized-length", _HDR.pack(MAX_FRAME + 1)))
        corpus.append(("truncated-header", b"\x00\x00"))
        corpus.append(("empty-conn", b""))
        for i in range(4):
            n = rng.randrange(8, 200)
            sent = rng.randrange(0, n - 3)
            corpus.append((f"mid-frame-close-{i}",
                           _HDR.pack(n) + bytes(sent)))

        mismatches = []
        for name, raw in corpus:
            out_n = _probe(srv_n.port, raw)
            out_p = _probe(srv_p.port, raw)
            if out_n[0] != out_p[0]:
                mismatches.append((name, out_n[0], out_p[0]))
                continue
            if out_n[0] == "reply":
                code_n, body_n = decode(out_n[1])
                code_p, body_p = decode(out_p[1])
                if code_n != code_p:
                    mismatches.append((name, code_n, code_p))
                elif code_n == MessageCode.ERROR_RESP:
                    # typed errors must match byte-for-byte: same
                    # exception name, same detail text, same layout
                    if out_n[1] != out_p[1]:
                        mismatches.append((name, body_n, body_p))
                elif body_n.get("values") != body_p.get("values"):
                    # served reads: value parity (clocks are per-node)
                    mismatches.append(
                        (name, body_n.get("values"), body_p.get("values")))
        assert not mismatches, \
            "native/python planes diverged on:\n" + "\n".join(
                f"  {n}: native={a!r} python={b!r}"
                for n, a, b in mismatches)
    finally:
        cn.close()
        cp.close()
        srv_n.close()
        srv_p.close()


# ---------------------------------------------------------------------------
# admission-shed parity: both planes refuse with the SAME typed busy
# reply — detail string and retry hint included (acceptance criterion)
# ---------------------------------------------------------------------------
@pytest.mark.smoke
def test_admission_shed_busy_reply_parity():
    caps = dict(max_in_flight=64, max_in_flight_per_client=1)

    def shed_bytes(node, srv, in_flight):
        """Wedge the commit plane, park one admitted update, and
        capture the raw busy frame a second same-host conn receives."""
        a = socket.create_connection(("127.0.0.1", srv.port), timeout=10)
        a.settimeout(30)
        b = socket.create_connection(("127.0.0.1", srv.port), timeout=10)
        b.settimeout(10)
        try:
            with node.txm.commit_lock:
                a.sendall(_raw_frame(MessageCode.STATIC_UPDATE_OBJECTS, {
                    "updates": [["sk", "counter_pn", "b",
                                 ["increment", 1]]],
                    "clock": None,
                }))
                deadline = time.monotonic() + 20
                while in_flight() < 1:  # a admitted + parked on commit
                    assert time.monotonic() < deadline
                    time.sleep(0.005)
                # same host, cold key (no fast-path hit): per-client cap
                b.sendall(_raw_frame(MessageCode.STATIC_READ_OBJECTS, {
                    "objects": [["cold", "counter_pn", "b"]],
                    "clock": None,
                }))
                busy = read_frame(b)
            ack = read_frame(a)  # the parked update completed
            code, body = decode(ack)
            assert "commit_clock" in body, body
            return busy
        finally:
            a.close()
            b.close()

    node_n, srv_n = _boot(True, **caps)
    try:
        busy_n = shed_bytes(node_n, srv_n,
                            lambda: srv_n.native.stats()["in_flight"])
        assert srv_n.native.stats()["sheds"] >= 1
    finally:
        srv_n.close()
    node_p, srv_p = _boot(False, **caps)
    try:
        busy_p = shed_bytes(node_p, srv_p, srv_p.admission.in_flight)
    finally:
        srv_p.close()

    # the C++ admission layer mirrors overload.py exactly: same error
    # kind, same human-readable detail, same pressure-scaled hint —
    # byte-for-byte, so client backoff logic cannot tell the planes apart
    assert busy_n == busy_p, (busy_n, busy_p)
    code, body = decode(busy_n)
    assert code == MessageCode.ERROR_RESP
    assert body["error"] == "busy"
    assert body["detail"] == \
        "client 127.0.0.1 at max_in_flight_per_client=1"
    assert body["retry_after_ms"] >= 25


# ---------------------------------------------------------------------------
# fallback + fault sites on the native path
# ---------------------------------------------------------------------------
@pytest.mark.smoke
def test_env_kill_switch_falls_back_to_python_plane(monkeypatch):
    monkeypatch.setenv("ANTIDOTE_NATIVE_FRONTEND", "off")
    node = AntidoteNode(mk_cfg())
    srv = ProtocolServer(node, port=0, native_frontend=True)
    c = AntidoteClient(port=srv.port)
    try:
        assert srv.native is None  # the advertised port is socketserver's
        c.update_objects([("e", "counter_pn", "b", ("increment", 2))])
        vals, _ = c.read_objects([("e", "counter_pn", "b")])
        assert vals == [2]
        assert "native" not in srv._pipeline_status()
    finally:
        c.close()
        srv.close()


@pytest.mark.smoke
def test_injected_load_failure_falls_back_and_counts():
    from antidote_tpu.obs.metrics import net_metrics

    plan = faults.FaultPlan(seed=3)
    plan.error("native_frontend.load")
    faults.install(plan)
    before = net_metrics().frontend_fallback.value()
    node = AntidoteNode(mk_cfg())
    srv = ProtocolServer(node, port=0, native_frontend=True)
    c = AntidoteClient(port=srv.port)
    try:
        assert srv.native is None
        assert net_metrics().frontend_fallback.value() == before + 1
        c.update_objects([("f", "counter_pn", "b", ("increment", 1))])
        vals, _ = c.read_objects([("f", "counter_pn", "b")])
        assert vals == [1]
    finally:
        c.close()
        srv.close()


def test_frontend_recv_faults_fire_on_native_path():
    """frontend.recv drop/truncate rules are applied per drained frame
    on the native plane too — and an armed frontend.* rule disables
    fast-serve at boot, so NO frame can dodge the plan via a C++ hit."""
    plan = faults.FaultPlan(seed=11)
    plan.drop("frontend.recv", times=1)
    plan.truncate("frontend.recv", times=1, keep=5)
    inj = faults.install(plan)
    node, srv = _boot(True)
    try:
        req = _raw_frame(MessageCode.STATIC_READ_OBJECTS, {
            "objects": [["k", "counter_pn", "b"]], "clock": None,
        })
        # rule 1 (drop): the frame vanishes and the conn is closed —
        # the client sees EOF, never a hung socket
        out = _probe(srv.port, req)
        assert out[0] == "closed", out
        # rule 2 (truncate to 5 bytes): the mangled frame decodes to a
        # typed ERROR_RESP, exactly like the Python plane's twin site
        out = _probe(srv.port, req)
        assert out[0] == "reply", out
        code, body = decode(out[1])
        assert code == MessageCode.ERROR_RESP, body
        # rules exhausted: the plane serves normally again
        out = _probe(srv.port, req)
        assert out[0] == "reply" and \
            decode(out[1])[0] == MessageCode.READ_OBJECTS_RESP
        assert inj.fired("frontend.recv") == 2
    finally:
        srv.close()


# ---------------------------------------------------------------------------
# chaos acceptance: SIGKILL under a >=1k-socket storm with seeded
# drop/truncate faults on the native accept path — every ack made it to
# the WAL (acked ⊆ recovered), and no connection wedges
# ---------------------------------------------------------------------------
def test_sigkill_under_socket_storm_acked_subset_recovered(tmp_path):
    n_socks = 1024
    n_keys = 128  # sockets share keys: per-key acked sums stay testable
    log_dir = str(tmp_path / "wal")
    env = dict(
        os.environ, JAX_PLATFORMS="cpu",
        # seeded frame wreckage on the accept path for the whole run:
        # drops close conns mid-storm, truncates produce typed errors
        ANTIDOTE_FAULT_PLAN=json.dumps({"seed": 23, "rules": [
            {"site": "frontend.recv", "action": "drop", "p": 0.002,
             "times": 64},
            {"site": "frontend.recv", "action": "truncate", "p": 0.002,
             "times": 64, "arg": 6},
        ]}),
    )
    proc = subprocess.Popen(
        [sys.executable, "-m", "antidote_tpu.console", "serve",
         "--port", "0", "--shards", "2", "--max-dcs", "2",
         "--keys-per-table", "1024", "--log-dir", log_dir, "--sync-log",
         "--wal-segments", "3", "--max-connections", str(n_socks + 64),
         "--max-in-flight-per-client", "512"],
        stdout=subprocess.PIPE, stderr=subprocess.DEVNULL, env=env,
        text=True,
    )
    acked = [0] * n_keys
    attempted = [0] * n_keys
    socks = []
    try:
        info = json.loads(proc.stdout.readline())
        assert info["ready"] is True
        port = info["port"]

        def upd_frame(key_i):
            return _raw_frame(MessageCode.STATIC_UPDATE_OBJECTS, {
                "updates": [[f"s{key_i}", "counter_pn", "b",
                             ["increment", 1]]],
                "clock": None,
            })

        sel = selectors.DefaultSelector()
        deadline = time.monotonic() + 60
        for i in range(n_socks):
            s = socket.create_connection(("127.0.0.1", port), timeout=30)
            s.settimeout(None)
            socks.append(s)
            key_i = i % n_keys
            # state: [rxbuf, key_i, live]
            sel.register(s, selectors.EVENT_READ, [bytearray(), key_i, True])
            attempted[key_i] += 1
            s.sendall(upd_frame(key_i))
            assert time.monotonic() < deadline, \
                f"storm connect stalled at {i} sockets"

        # closed-loop storm: each ack (commit_clock reply) immediately
        # launches the next increment on that socket; busy sheds and
        # typed errors relaunch too (refused work was NOT applied)
        t_end = time.monotonic() + 6.0
        while time.monotonic() < t_end and sum(acked) < 4000:
            for sk, _ in sel.select(timeout=0.2):
                st = sk.data
                try:
                    data = sk.fileobj.recv(1 << 16)
                except OSError:
                    data = b""
                if not data:  # fault-dropped conn: dead, not wedged
                    sel.unregister(sk.fileobj)
                    st[2] = False
                    continue
                st[0] += data
                while len(st[0]) >= 4:
                    (n,) = _HDR.unpack(st[0][:4])
                    if len(st[0]) < 4 + n:
                        break
                    frame = bytes(st[0][4:4 + n])
                    del st[0][:4 + n]
                    code, body = decode(frame)
                    if code != MessageCode.ERROR_RESP:
                        assert "commit_clock" in body, body
                        acked[st[1]] += 1
                    attempted[st[1]] += 1
                    try:
                        sk.fileobj.sendall(upd_frame(st[1]))
                    except OSError:
                        sel.unregister(sk.fileobj)
                        st[2] = False
                        break
        assert sum(acked) >= 500, \
            f"storm never reached real throughput: {sum(acked)} acks"
        proc.send_signal(signal.SIGKILL)  # mid-storm, no goodbyes
        proc.wait(timeout=10)
        # no wedged conns: the kill severs EVERY remaining socket — each
        # one must observe EOF/reset promptly, none parks forever
        eof_deadline = time.monotonic() + 15
        live = [s for s in socks if not s._closed]
        for s in live:
            s.settimeout(max(0.1, eof_deadline - time.monotonic()))
            try:
                while s.recv(1 << 16):
                    pass
            except socket.timeout:
                pytest.fail("a connection wedged past the server's death")
            except (ConnectionError, OSError):
                pass  # reset counts as closed, same as EOF
    finally:
        for s in socks:
            try:
                s.close()
            except OSError:
                pass
        if proc.poll() is None:
            proc.kill()
            proc.wait(timeout=10)
    # recover twice, independently — acked ⊆ recovered ⊆ attempted per
    # key, and both recoveries are byte-identical (the WAL contract is
    # untouched by WHICH plane accepted the bytes)
    rcfg = AntidoteConfig(n_shards=2, max_dcs=2, keys_per_table=1024,
                          wal_segments=3)
    objs = [(f"s{i}", "counter_pn", "b") for i in range(n_keys)]
    recovered = []
    for _ in range(2):
        node = AntidoteNode(rcfg, log_dir=log_dir, recover=True)
        vals, _ = node.read_objects(objs)
        recovered.append(vals)
        node.store.log.close()
    assert recovered[0] == recovered[1], "recoveries diverged"
    for i in range(n_keys):
        assert acked[i] <= recovered[0][i] <= attempted[i], (
            f"s{i}: acked={acked[i]} recovered={recovered[0][i]} "
            f"attempted={attempted[i]}")
