"""Test harness: force an 8-device virtual CPU mesh.

Multi-chip hardware is unavailable in CI; the sharding layer is validated
on virtual CPU devices (the driver separately dry-runs multi-chip via
__graft_entry__.dryrun_multichip).

Note: the environment's sitecustomize imports jax at interpreter startup
(TPU tunnel plugin), so env vars set here are too late — we use
jax.config.update, which works after import as long as no backend has
been initialized yet.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
try:
    # newer jax: takes effect even after import (pre-backend-init)
    jax.config.update("jax_num_cpu_devices", 8)
except AttributeError:
    # older jax (no such option): the XLA_FLAGS set above did the job,
    # provided no backend initialized before this conftest ran
    pass

from antidote_tpu.config import enable_compilation_cache  # noqa: E402

# own cache namespace: the 8-virtual-device test config compiles with
# different machine-feature flags than 1-device server processes, and
# cross-loading the other config's AOT entries spams feature-mismatch
# warnings on every load
os.environ.setdefault(
    "ANTIDOTE_XLA_CACHE",
    os.path.join(os.path.expanduser("~"), ".cache", "antidote_tpu_xla_t8"),
)
enable_compilation_cache()

import pytest  # noqa: E402


@pytest.fixture
def cfg():
    from antidote_tpu.config import AntidoteConfig

    return AntidoteConfig(
        n_shards=4, max_dcs=3, ops_per_key=8, snap_versions=2,
        set_slots=8, mv_slots=4, rga_slots=16, keys_per_table=64,
        batch_buckets=(16, 64),
    )
