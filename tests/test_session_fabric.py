"""Property tests for the session fabric (ISSUE 11 satellites).

Three algebras pinned with seeded randomized sweeps:

  * the SESSION-TOKEN algebra — ``codec.merge_clock`` is the token
    update rule every session client folds observed clocks through, so
    read-your-writes across arbitrary failover rests on it being
    commutative, associative, idempotent, and monotone, and on its
    interaction with the follower's per-shard applied gate (a merged
    token is admitted iff every constituent clock is covered);
  * the apb ERROR-MAPPING round-trip — the typed lagging/not_owner
    redirects ride the ApbErrorResp errmsg as text
    (``apb.error_text`` / ``apb.parse_error_text``), and a session
    client's failover discipline is only as good as that round-trip;
  * the HASH-RING algebra — fleet-wide agreement on arc ownership,
    arc-only shedding when an endpoint dies, and per-client
    seeded-jitter disagreement on the fallback order (the
    anti-stampede property).
"""

import numpy as np
import pytest

from antidote_tpu.proto import apb
from antidote_tpu.proto.client import HashRing
from antidote_tpu.proto.codec import merge_clock

pytestmark = pytest.mark.smoke


# ---------------------------------------------------------------------------
# session-token algebra
# ---------------------------------------------------------------------------
def _rand_clock(rng, max_len=6):
    if rng.random() < 0.1:
        return None
    n = int(rng.integers(1, max_len + 1))
    return [int(x) for x in rng.integers(0, 50, size=n)]


def _norm(c, width):
    out = [0] * width
    if c:
        out[: len(c)] = [int(x) for x in c]
    return out


def test_merge_clock_commutative_associative_idempotent():
    rng = np.random.default_rng(11)
    for _ in range(500):
        a, b, c = (_rand_clock(rng) for _ in range(3))
        ab, ba = merge_clock(a, b), merge_clock(b, a)
        assert ab == ba, (a, b)
        assert merge_clock(merge_clock(a, b), c) \
            == merge_clock(a, merge_clock(b, c)), (a, b, c)
        aa = merge_clock(a, a)
        assert aa == (None if a is None else [int(x) for x in a])
        # identity: None is the empty token
        assert merge_clock(a, None) == (
            None if a is None else [int(x) for x in a])


def test_merge_clock_monotone_entrywise():
    """merge(a, b) dominates both inputs entry-wise (padded) — the
    property that makes the token a least-upper-bound accumulator:
    folding any observation never loses causal coverage."""
    rng = np.random.default_rng(12)
    for _ in range(500):
        a, b = _rand_clock(rng), _rand_clock(rng)
        m = merge_clock(a, b)
        if m is None:
            assert a is None and b is None
            continue
        w = len(m)
        na, nb = _norm(a, w), _norm(b, w)
        assert all(x >= y for x, y in zip(m, na))
        assert all(x >= y for x, y in zip(m, nb))
        assert m == [max(x, y) for x, y in zip(na, nb)]


def test_merge_clock_monotone_vs_applied_gate():
    """The follower gate admits a token iff the per-shard applied clock
    dominates it.  Monotonicity of the merge means: the MERGED token is
    admitted ⟺ every constituent clock is admitted — so folding more
    observations into a session can only tighten (never corrupt) the
    gate decision, and an admitted merged token proves RYW for every
    observation folded in."""
    rng = np.random.default_rng(13)
    width = 4
    for _ in range(300):
        applied = np.asarray(
            [int(x) for x in rng.integers(0, 30, size=width)], np.int64)
        a = _rand_clock(rng, max_len=width)
        b = _rand_clock(rng, max_len=width)
        m = merge_clock(a, b)

        def admitted(c):
            return (applied >= np.asarray(_norm(c, width),
                                          np.int64)).all()

        assert admitted(m) == (admitted(a) and admitted(b)), (
            applied, a, b, m)


# ---------------------------------------------------------------------------
# apb typed-error round-trips
# ---------------------------------------------------------------------------
def test_apb_error_text_round_trips():
    rng = np.random.default_rng(21)
    hosts = ["127.0.0.1", "owner.example.com", "10.0.0.7", "::1"]
    for _ in range(300):
        kind = ["lagging", "not_owner", "busy", "deadline",
                "read_only"][int(rng.integers(5))]
        retry = int(rng.integers(0, 600))
        redirect = None
        if rng.random() < 0.6:
            redirect = [hosts[int(rng.integers(len(hosts)))],
                        int(rng.integers(1, 65536))]
        detail = ["follower f1 is healing",
                  "behind the token after a 100 ms park",
                  "weird: detail: with colons",
                  "multi\nline detail"][int(rng.integers(4))]
        text = apb.error_text(kind, detail, retry, redirect)
        out = apb.parse_error_text(text)
        assert out["kind"] == kind
        assert out["retry_after_ms"] == retry
        assert out["redirect"] == redirect
        assert out["detail"] == detail


def test_apb_error_frame_round_trips_through_wire_encoding():
    """The full wire path: typed exception -> _error_resp -> proto2
    ApbErrorResp frame bytes -> decode -> parse_error_text recovers the
    typed fields the session client keys its failover on."""
    from antidote_tpu.overload import NotOwnerError, ReplicaLagging

    cases = [
        ReplicaLagging("behind the token", retry_after_ms=175,
                       redirect=("owner-host", 8087)),
        NotOwnerError(redirect=("10.1.2.3", 9001)),
    ]
    for e in cases:
        name, body = apb._error_resp(e)
        assert name == "ApbErrorResp"
        frame = apb.encode_frame_body(name, body)
        rname, resp = apb.decode_frame_body(frame)
        assert rname == "ApbErrorResp"
        out = apb.parse_error_text(resp["errmsg"])
        if isinstance(e, ReplicaLagging):
            assert out["kind"] == "lagging"
            assert out["retry_after_ms"] == 175
            assert out["redirect"] == ["owner-host", 8087]
        else:
            assert out["kind"] == "not_owner"
            assert out["redirect"] == ["10.1.2.3", 9001]
    # an untyped reference-style error parses as the catch-all
    out = apb.parse_error_text(b"KeyError: unknown transaction")
    assert out["kind"] == "error" and out["redirect"] is None
    # malformed param values from a foreign server never crash — the
    # field falls back to its default
    out = apb.parse_error_text(b"busy retry_after_ms=unknown: full")
    assert out["kind"] == "busy" and out["retry_after_ms"] == 0
    out = apb.parse_error_text(b"not_owner redirect=host:none: go away")
    assert out["kind"] == "not_owner" and out["redirect"] is None


def test_apb_update_and_value_bridges_round_trip():
    """The client-side bridges invert the server-side ones for the
    wire-expressible ops: native update tuple -> ApbUpdateOp -> the
    server's ops_from_update_operation recovers the op, and
    value_to_read_resp -> read_resp_to_value recovers the value."""
    ups = [
        (b"k", "counter_pn", b"b", ("increment", 5)),
        (b"k", "counter_pn", b"b", ("decrement", 2)),
        (b"s", "set_aw", b"b", ("add", b"x")),
        (b"s", "set_rw", b"b", ("remove_all", [b"x", b"y"])),
        (b"r", "register_lww", b"b", ("assign", b"v1")),
        (b"f", "flag_ew", b"b", ("enable", None)),
    ]
    for key, t, bucket, op in ups:
        wire = apb.update_op_from_native((key, t, bucket, op))
        frame = apb.encode_msg("ApbUpdateOp", wire)
        back = apb.decode_msg("ApbUpdateOp", frame)
        got = apb.updates_from_update_ops([back])
        assert got[0][0] == key and got[0][1] == t and got[0][2] == bucket
        kind, arg = got[0][3][0], got[0][3][1]
        if op[0] == "decrement":
            # plain counters ride a negative increment on the wire
            assert (kind, arg) == ("increment", -2)
        elif op[0] in ("add", "remove_all"):
            vals = [op[1]] if op[0] == "add" else list(op[1])
            assert kind.endswith("_all") and list(arg) == vals
        elif op[0] == "enable":
            assert kind == "enable"
        else:
            assert (kind, arg) == op
    vals = [("counter_pn", 7), ("set_aw", [b"a", b"b"]),
            ("register_lww", b"v"), ("flag_dw", True)]
    for t, v in vals:
        resp = apb.value_to_read_resp(t, v)
        frame = apb.encode_msg("ApbReadObjectResp", resp)
        back = apb.decode_msg("ApbReadObjectResp", frame)
        assert apb.read_resp_to_value(back) == v


def test_apb_map_ops_ride_the_mapop_lane():
    """Map-CRDT field ops encode through mapop (nested updates /
    removedKeys), never the set lanes — the server-side decoder
    recovers the exact field ops."""
    up = (b"m", "map_rr", b"b",
          ("update", [((b"f", "counter_pn"), ("increment", 3)),
                      ((b"g", "register_lww"), ("assign", b"v"))]))
    wire = apb.update_op_from_native(up)
    back = apb.decode_msg("ApbUpdateOp",
                          apb.encode_msg("ApbUpdateOp", wire))
    got = apb.updates_from_update_ops([back])
    assert got == [(b"m", "map_rr", b"b",
                    ("update", [((b"f", "counter_pn"),
                                 ("increment", 3)),
                                ((b"g", "register_lww"),
                                 ("assign", b"v"))]))], got
    rm = (b"m", "map_rr", b"b",
          ("remove_all", [(b"f", "counter_pn")]))
    wire = apb.update_op_from_native(rm)
    back = apb.decode_msg("ApbUpdateOp",
                          apb.encode_msg("ApbUpdateOp", wire))
    got = apb.updates_from_update_ops([back])
    assert got == [(b"m", "map_rr", b"b",
                    ("remove_all", [(b"f", "counter_pn")]))], got
    with pytest.raises(ValueError, match="no apb wire form"):
        apb._op_to_operation("map_rr", ("weird_op", None))


# ---------------------------------------------------------------------------
# hash-ring algebra
# ---------------------------------------------------------------------------
def _fleet(n):
    return [(f"10.0.0.{i}", 8000 + i) for i in range(n)]


def test_ring_fleet_wide_agreement_and_determinism():
    """Placement is seed-independent (every client agrees on each key's
    arc owner) and deterministic across rebuilds."""
    eps = _fleet(8)
    r1 = HashRing(eps, seed=1)
    r2 = HashRing(list(reversed(eps)), seed=999)
    for k in range(200):
        assert r1.preferred(k, "b") == r2.preferred(k, "b")
    # and the full order is deterministic per seed
    r1b = HashRing(eps, seed=1)
    for k in range(50):
        assert r1.order(k, "b") == r1b.order(k, "b")


def test_ring_death_sheds_only_its_arcs():
    """Removing one endpoint remaps ONLY the keys it owned: every other
    key keeps its preferred replica (the O(1)-failover property a
    modular map does not have)."""
    eps = _fleet(8)
    full = HashRing(eps)
    dead = eps[3]
    survivors = HashRing([e for e in eps if e != dead])
    moved = 0
    for k in range(2000):
        before = full.preferred(k, "b")
        after = survivors.preferred(k, "b")
        if before == dead:
            moved += 1
            assert after != dead
        else:
            assert after == before, k
    # the dead endpoint owned roughly 1/8 of the keyspace
    assert 0 < moved < 2000 * 0.3


def test_ring_arc_shares_roughly_balanced():
    shares = HashRing(_fleet(8), vnodes=64).arc_share()
    assert abs(sum(shares.values()) - 1.0) < 1e-9
    for ep, s in shares.items():
        assert 0.02 < s < 0.35, (ep, s)


def test_ring_fallback_is_seeded_jittered_per_client():
    """The anti-stampede satellite: different clients order the
    fallback tail differently (so a dead arc's load spreads), while one
    client's order stays deterministic and always starts at the common
    preferred replica."""
    eps = _fleet(8)
    rings = [HashRing(eps, seed=s) for s in range(6)]
    diverged = 0
    for k in range(100):
        orders = [r.order(k, "b") for r in rings]
        heads = {tuple(o[:1]) for o in orders}
        assert len(heads) == 1  # common preferred
        tails = {tuple(o[1:]) for o in orders}
        if len(tails) > 1:
            diverged += 1
        for o in orders:
            assert sorted(o) == sorted(eps)  # a permutation, no loss
    # with 6 seeds over 7! tail orders, essentially every key diverges
    assert diverged > 90


def test_session_client_seeds_differ_without_explicit_seed():
    from antidote_tpu.proto.client import SessionClient

    seeds = set()
    for _ in range(8):
        sc = SessionClient(("127.0.0.1", 1), _fleet(4))
        seeds.add(sc.seed)
        sc.close()
    assert len(seeds) == 8


def test_session_client_empty_read_routes_to_owner():
    """An empty objects list has no routing key: the candidate walk
    degenerates to the owner alone instead of crashing on objects[0]."""
    from antidote_tpu.proto.client import SessionClient

    sc = SessionClient(("127.0.0.1", 1), _fleet(4))
    assert list(sc._read_candidates([])) == [("127.0.0.1", 1)]
    sc.close()
