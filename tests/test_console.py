"""Console CLI + readiness probe (antidote_console / wait_init analogues,
/root/reference/src/antidote_console.erl:34-50,
/root/reference/src/wait_init.erl:50-88)."""

import json

import pytest

from antidote_tpu.api import AntidoteNode
from antidote_tpu.proto.server import ProtocolServer

pytestmark = pytest.mark.smoke


@pytest.fixture
def node(cfg):
    return AntidoteNode(cfg)


def test_check_ready_all_probes(node):
    probes = node.check_ready()
    assert set(probes) == {"types", "meta", "clocks", "log", "txn"}
    assert all(probes.values()), probes
    assert node.is_ready()


def test_ready_probe_leaves_no_state(node):
    node.check_ready()
    # the probe txn aborts: nothing committed, no value visible, and —
    # critically — no directory binding or table row allocated (reads of
    # never-written keys must not grow the tables or leak into handoffs)
    vals, _ = node.read_objects([("__ready__", "counter_pn", "__ready__")])
    assert vals == [0]
    assert node.store.locate("__ready__", "counter_pn", "__ready__",
                             create=False) is None
    assert len(node.store.directory) == 0
    # and the probe never skews op/abort dashboards
    assert node.metrics.aborted_transactions.value() == 0
    assert node.metrics.operations.value(type="update") == 0


def test_status_snapshot(node):
    node.update_objects([("k", "counter_pn", "b", ("increment", 2))])
    st = node.status()
    assert st["n_shards"] == node.cfg.n_shards
    assert st["keys"] >= 1
    assert st["tables"]["counter_pn"]["rows_used"] >= 1
    assert st["commit_counter"] == 1
    assert "ready" not in st  # passive by default (monitoring-poll safe)
    assert all(node.status(include_ready=True)["ready"].values())


def test_status_over_wire(node):
    from antidote_tpu.proto.client import AntidoteClient

    server = ProtocolServer(node, port=0)
    try:
        c = AntidoteClient(server.host, server.port)
        st = c.node_status(include_ready=True)
        assert st["dc_id"] == node.dc_id and all(st["ready"].values())
        c.close()
    finally:
        server.close()


def test_console_status_read_update(node, capsys):
    from antidote_tpu import console

    server = ProtocolServer(node, port=0)
    try:
        base = ["--host", server.host, "--port", str(server.port)]
        assert console.main(["update", *base, "k", "counter_pn", "b",
                             "increment", "5"]) == 0
        out = json.loads(capsys.readouterr().out)
        assert "commit_clock" in out
        assert console.main(["read", *base, "k", "counter_pn", "b"]) == 0
        assert json.loads(capsys.readouterr().out)["value"] == 5
        assert console.main(["status", *base]) == 0
        st = json.loads(capsys.readouterr().out)
        assert st["keys"] >= 1
        assert console.main(["ready", *base]) == 0
    finally:
        server.close()


def test_release_smoke(tmp_path):
    """The reference's release smoke test (make reltest,
    /root/reference/test/release_test.sh:1-16): boot the release entrypoint
    as a real subprocess, run one txn via the client, stop it."""
    import os
    import subprocess
    import sys

    env = dict(os.environ, JAX_PLATFORMS="cpu")
    proc = subprocess.Popen(
        [sys.executable, "-m", "antidote_tpu.console", "serve",
         "--port", "0", "--shards", "2", "--log-dir", str(tmp_path)],
        stdout=subprocess.PIPE, stderr=subprocess.DEVNULL, env=env, text=True,
    )
    try:
        line = proc.stdout.readline()  # blocks until the ready line
        info = json.loads(line)
        assert info["ready"] is True
        from antidote_tpu.proto.client import AntidoteClient

        c = AntidoteClient(info["host"], info["port"])
        c.update_objects([("k", "counter_pn", "b", ("increment", 9))])
        vals, _ = c.read_objects([("k", "counter_pn", "b")])
        assert vals == [9]
        assert all(c.node_status(include_ready=True)["ready"].values())
        c.close()
    finally:
        proc.terminate()
        proc.wait(timeout=10)


def test_console_inspect(tmp_path, cfg, capsys):
    from antidote_tpu import console

    node = AntidoteNode(cfg, log_dir=str(tmp_path))
    node.update_objects([("k", "counter_pn", "b", ("increment", 1)),
                         ("s", "set_aw", "b", ("add", "x"))])
    assert console.main(["inspect", "--log-dir", str(tmp_path)]) == 0
    out = json.loads(capsys.readouterr().out)
    total = sum(s["records"] for s in out.values())
    assert total == 2
    assert any("counter_pn" in s["records_by_type"] for s in out.values())


def test_console_cluster_commands(tmp_path, capsys):
    """ringready / cluster-status / cluster-resolve / cluster-sweep
    against a live 2-member DC (antidote_console staged-ops parity,
    /root/reference/src/antidote_console.erl:34-50)."""
    import json as _json

    from antidote_tpu.console import main as console_main
    from tests.test_cluster_processes import _spawn_duo

    env, spawned, infos = _spawn_duo(tmp_path)
    try:
        rpc = "{}:{}".format(*infos[0]["rpc"])
        assert console_main(["ringready", "--rpc", rpc]) == 0
        probes = _json.loads(capsys.readouterr().out.strip())
        assert all(probes.values()) and len(probes) == 2
        assert console_main(["cluster-status", "--rpc", rpc]) == 0
        st = _json.loads(capsys.readouterr().out.strip())
        assert st["members"] == 2 and st["owned_shards"] == [0, 2]
        assert console_main(["cluster-resolve", "--rpc", rpc]) == 0
        assert _json.loads(capsys.readouterr().out.strip()) == {"resolved": 0}
        assert console_main(["cluster-sweep", "--rpc", rpc,
                             "--grace", "0"]) == 0
        assert _json.loads(capsys.readouterr().out.strip()) == {"swept": 0}
        # a dead member flips ringready
        spawned[1].kill()
        spawned[1].wait(timeout=10)
        assert console_main(["ringready", "--rpc", rpc]) == 1
        probes = _json.loads(capsys.readouterr().out.strip())
        assert not all(probes.values())
    finally:
        for p in spawned:
            p.terminate()
        for p in spawned:
            try:
                p.wait(timeout=10)
            except Exception:
                p.kill()
