"""Sharded SPMD step on a virtual 8-device CPU mesh."""

import jax
import numpy as np

from antidote_tpu.config import AntidoteConfig
from antidote_tpu.crdt import get_type
from antidote_tpu.parallel import make_mesh, shard_axis_sharding, sharded_step_fn
from antidote_tpu.store import TypedTable


def test_sharded_step_8_devices():
    n_dev = len(jax.devices())
    assert n_dev == 8, "conftest must force 8 virtual CPU devices"
    cfg = AntidoteConfig(
        n_shards=n_dev, max_dcs=2, ops_per_key=4, snap_versions=2,
        keys_per_table=16, batch_buckets=(8,),
    )
    mesh = make_mesh(n_dev)
    sharding = shard_axis_sharding(mesh)
    ty = get_type("counter_pn")
    table = TypedTable(ty, cfg, sharding=sharding)
    step = sharded_step_fn(ty, cfg, mesh)

    p, ma, mr, d = cfg.n_shards, 8, 8, cfg.max_dcs
    # one increment of +shard on row 0 of every shard, commit vc lane0 = 1
    app_rows = np.zeros((p, ma), np.int64)
    app_rows[:, 1:] = table.n_rows  # padding
    app_slots = np.zeros((p, ma), np.int64)
    app_a = np.zeros((p, ma, ty.eff_a_width(cfg)), np.int64)
    app_a[:, 0, 0] = np.arange(p) + 1
    app_b = np.zeros((p, ma, ty.eff_b_width(cfg)), np.int32)
    app_vc = np.zeros((p, ma, d), np.int32)
    app_vc[:, 0, 0] = 1
    app_origin = np.zeros((p, ma), np.int32)
    read_rows = np.zeros((p, mr), np.int64)
    read_n_ops = np.ones((p, mr), np.int32)
    read_vcs = np.ones((p, mr, d), np.int32)
    applied_vc = np.zeros((p, d), np.int32)

    (ops_a, ops_b, ops_vc, ops_origin, state, applied, complete,
     new_applied, stable) = step(
        table.snap, table.snap_vc, table.snap_seq,
        table.ops_a, table.ops_b, table.ops_vc, table.ops_origin,
        app_rows, app_slots, app_a, app_b, app_vc, app_origin,
        read_rows, read_n_ops, read_vcs, applied_vc,
    )
    # each shard read its own incremented counter
    cnt = np.asarray(state["cnt"])
    assert (cnt[:, 0] == np.arange(p) + 1).all()
    assert np.asarray(complete).all()
    # stable snapshot = pmin over shards = [1, 0] everywhere
    st = np.asarray(stable)
    assert (st == np.asarray([1, 0])).all()
    # applied clocks advanced per shard
    assert (np.asarray(new_applied)[:, 0] == 1).all()
