"""Geo-replication across LIVE membership change, OS-process tier.

The r5 VERDICT item 2 acceptance shape: a 2-DC mesh under cross-DC
write load live-joins a member at DC0 (through the operator console),
then live-leaves a MIDDLE member and kills its process — a publisher
dies for good.  Remote catch-up must land on the NEW owners of the
moved chains via ownership-epoch gossip (the boot-time modular router
still points at the dead/old members), with no fabric reconnect and no
lost or duplicated ops: both DCs converge to the exact acked totals.

DC1 is deliberately NOT subscribed to the joiner's endpoint until after
the moves, so the moved chains accumulate a guaranteed gap — the
catch-up trigger — which only the epoch-learned route can heal (the old
owners' windows were cleared at relinquish; one of them is SIGKILLed).
"""

import json
import os
import subprocess
import sys
import threading
import time

from antidote_tpu import console
from antidote_tpu.cluster.rpc import RpcClient
from antidote_tpu.proto.client import AntidoteClient

N_KEYS = 16  # int key k -> shard k % 8


def _boot(spawned, infos, env, tmp_path, dc, member, members,
          joining=False, max_dcs=2, shards=8):
    cmd = [sys.executable, "-m", "antidote_tpu.cluster.boot",
           "--dc-id", str(dc), "--member", str(member),
           "--members", str(members), "--shards", str(shards),
           "--max-dcs", str(max_dcs),
           "--log-dir", str(tmp_path / f"d{dc}m{member}")]
    if joining:
        cmd.append("--joining")
    errlog = os.environ.get("GEO_TEST_STDERR_DIR")
    stderr = (open(os.path.join(errlog, f"d{dc}m{member}.log"), "w")
              if errlog else subprocess.DEVNULL)
    p = subprocess.Popen(cmd, env=env, stdout=subprocess.PIPE,
                         stderr=stderr)
    spawned.append(p)
    line = p.stdout.readline().decode()
    assert line, "boot process died before announcing"
    info = json.loads(line)
    infos.append(info)
    return info


def _wire(info, peers, remotes, members_by_dc):
    ctl = RpcClient(*info["rpc"])
    assert ctl.call("ctl_wire", peers, remotes, members_by_dc)
    ctl.close()


def _writer(port_info, seed, amount, acked, lock, stop, errs):
    import numpy as np

    rng = np.random.default_rng(seed)
    c = AntidoteClient(*port_info["client"])
    try:
        while not stop.is_set():
            k = int(rng.integers(N_KEYS))
            try:
                c.update_objects(
                    [(k, "counter_pn", "b", ("increment", amount))])
            except Exception as e:
                msg = str(e).lower()
                # cert conflicts AND move-window exhaustion are both
                # client-retryable non-acks: the coordinator aborted
                # every prepared leg before surfacing either
                if "abort" in msg or "unstable" in msg:
                    continue
                errs.append(repr(e))
                return
            with lock:
                acked[k] += amount
    finally:
        c.close()


def test_join_leave_kill_publisher_catchup_reroutes(tmp_path):
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = "/root/repo:" + env.get("PYTHONPATH", "")
    spawned, infos = [], []
    try:
        m0 = _boot(spawned, infos, env, tmp_path, 0, 0, 2)
        m1 = _boot(spawned, infos, env, tmp_path, 0, 1, 2)
        dc1 = _boot(spawned, infos, env, tmp_path, 1, 0, 1)
        peers0 = {0: m0["rpc"], 1: m1["rpc"]}
        remotes = {i["fabric_id"]: i["fabric"] for i in (m0, m1, dc1)}
        for i in (m0, m1):
            _wire(i, peers0, remotes, {0: 2, 1: 1})
        _wire(dc1, {0: dc1["rpc"]}, remotes, {0: 2, 1: 1})

        acked = [0] * N_KEYS
        lock = threading.Lock()
        stop = threading.Event()
        errs = []
        ts = [threading.Thread(target=_writer,
                               args=(m0, 21, 1, acked, lock, stop, errs)),
              threading.Thread(target=_writer,
                               args=(dc1, 22, 2, acked, lock, stop, errs))]
        for t in ts:
            t.start()
        time.sleep(0.8)  # cross-DC load flowing both ways

        # ---- live-join member 2 at DC0, console-driven, under load.
        # DC1 is NOT wired to the joiner yet: everything the joiner
        # publishes on its moved chains is missed — the catch-up gap.
        m2 = _boot(spawned, infos, env, tmp_path, 0, 2, 3, joining=True)
        peers3 = {0: m0["rpc"], 1: m1["rpc"], 2: m2["rpc"]}
        remotes3 = dict(remotes)
        remotes3[m2["fabric_id"]] = m2["fabric"]
        for i in (m0, m1, m2):
            _wire(i, peers3, remotes3, {0: 3, 1: 1})
        spec = ",".join(f"{m}={i['rpc'][0]}:{i['rpc'][1]}"
                        for m, i in ((0, m0), (1, m1), (2, m2)))
        assert console.main(["cluster-join", "--rpcs", spec,
                             "--joiner", "2"]) == 0
        time.sleep(0.8)  # commits land on the joiner's chains, unseen

        # ---- live-leave member 1 (a MIDDLE id) under the same load,
        # then SIGKILL its process: a publisher dies for good.  Its
        # chains moved to the survivors with their egress state.
        assert console.main(["cluster-leave", "--rpcs", spec,
                             "--leaver", "1"]) == 0
        spawned[1].kill()
        assert spawned[1].wait(timeout=30) is not None

        # ---- only NOW does DC1 learn the joiner's endpoint (new
        # wiring, not a reconnect of any existing stream).  The stale
        # modular router ({0: 3}) points moved chains at the dead m1 or
        # the relinquished old owners — only the gossiped (owner, epoch)
        # stamps can land catch-up on the real owners.
        remotes_live = {i["fabric_id"]: i["fabric"] for i in (m0, m2, dc1)}
        _wire(dc1, {0: dc1["rpc"]}, remotes_live, {0: 3, 1: 1})

        time.sleep(0.8)  # load continues on the gapped cluster
        stop.set()
        for t in ts:
            t.join(timeout=60)
        assert not errs, errs

        with lock:
            want = list(acked)
        objs = [(k, "counter_pn", "b") for k in range(N_KEYS)]

        # both DCs converge to the exact acked totals: zero lost ops
        # (catch-up healed the joiner-chain gap from the NEW owners),
        # zero duplicates (chain-clock suppression across the moves)
        deadline = time.monotonic() + 90.0
        last = None
        while True:
            ok = True
            for info in (dc1, m0, m2):
                c = AntidoteClient(*info["client"])
                try:
                    vals, _ = c.read_objects(objs)
                finally:
                    c.close()
                last = (info["rpc"], vals)
                if vals != want:
                    ok = False
                    break
            if ok:
                break
            if time.monotonic() > deadline:
                raise AssertionError(
                    f"DCs failed to converge: {last} expected {want}")
            time.sleep(0.25)

        # the survivors cover every shard between them; the leaver's id
        # stays a gap (no renumbering)
        ctl = RpcClient(*m0["rpc"])
        st0 = ctl.call("ctl_status")
        ctl.close()
        ctl = RpcClient(*m2["rpc"])
        st2 = ctl.call("ctl_status")
        ctl.close()
        assert sorted(st0["owned_shards"] + st2["owned_shards"]) == \
            list(range(8))
    finally:
        for p in spawned:
            if p.poll() is None:
                p.terminate()
        for p in spawned:
            try:
                p.wait(timeout=10)
            except subprocess.TimeoutExpired:
                p.kill()
