"""Pallas kernel parity: counter fold, stable min, OR-set presence.

Each kernel must agree with the generic JAX materializer path
(fold.fold_batch / vector.vmin / the set_aw presence rule) on randomized
inputs.  On the CPU test mesh the kernels run in interpret mode; the same
code compiles for the real chip.
"""

import numpy as np
import pytest

from antidote_tpu.config import AntidoteConfig
from antidote_tpu.crdt import get_type
from antidote_tpu.materializer import fold as fold_mod
from antidote_tpu.materializer import pallas_kernels as pk


@pytest.fixture
def rng():
    return np.random.default_rng(11)


def test_counter_fold_matches_generic(rng):
    cfg = AntidoteConfig(n_shards=1, max_dcs=3, ops_per_key=8,
                         snap_versions=2, keys_per_table=16)
    ty = get_type("counter_pn")
    b, k, d = 37, cfg.ops_per_key, cfg.max_dcs
    deltas = rng.integers(-50, 50, size=(b, k)).astype(np.int64)
    ops_vc = rng.integers(0, 6, size=(b, k, d)).astype(np.int32)
    n_ops = rng.integers(0, k + 1, size=(b,)).astype(np.int32)
    base_vc = rng.integers(0, 4, size=(b, d)).astype(np.int32)
    read_vc = base_vc + rng.integers(0, 4, size=(b, d)).astype(np.int32)
    base_cnt = rng.integers(-1000, 1000, size=(b,)).astype(np.int64)

    ops_a = np.zeros((b, k, ty.eff_a_width(cfg)), np.int64)
    ops_a[:, :, 0] = deltas
    ops_b = np.zeros((b, k, ty.eff_b_width(cfg)), np.int32)
    ops_origin = np.zeros((b, k), np.int32)
    state, applied_ref = fold_mod.fold_batch(
        ty, cfg, {"cnt": base_cnt}, ops_a, ops_b, ops_vc, ops_origin,
        n_ops, base_vc, read_vc,
    )
    cnt, applied = pk.counter_fold(
        base_cnt, deltas, ops_vc, n_ops, base_vc, read_vc, block=8,
    )
    np.testing.assert_array_equal(np.asarray(cnt), np.asarray(state["cnt"]))
    np.testing.assert_array_equal(np.asarray(applied), np.asarray(applied_ref))


def test_counter_fold_empty_ring():
    cfg = AntidoteConfig(n_shards=1, max_dcs=2, ops_per_key=4,
                         snap_versions=2, keys_per_table=8)
    b, k, d = 3, 4, 2
    cnt, applied = pk.counter_fold(
        np.asarray([5, -2, 0], np.int64), np.zeros((b, k), np.int32),
        np.zeros((b, k, d), np.int32), np.zeros((b,), np.int32),
        np.zeros((b, d), np.int32), np.ones((b, d), np.int32), block=8,
    )
    np.testing.assert_array_equal(np.asarray(cnt), [5, -2, 0])
    assert np.asarray(applied).sum() == 0


def test_stable_min_matches_numpy(rng):
    clocks = rng.integers(0, 1000, size=(777, 5)).astype(np.int32)
    out = pk.stable_min(clocks, block=64)
    np.testing.assert_array_equal(np.asarray(out), clocks.min(axis=0))


def test_stable_min_single_row():
    clocks = np.asarray([[7, 3, 9]], np.int32)
    np.testing.assert_array_equal(np.asarray(pk.stable_min(clocks)), [7, 3, 9])


def test_stable_min_empty_is_identity():
    out = np.asarray(pk.stable_min(np.zeros((0, 3), np.int32)))
    np.testing.assert_array_equal(out, np.full(3, np.iinfo(np.int32).max))


def test_counter_fold_overflow_guard():
    b, k, d = 2, 8, 2
    deltas = np.zeros((b, k), np.int64)
    deltas[0, 0] = 2**40  # would wrap the i32 kernel sum
    with pytest.raises(ValueError, match="fold_batch"):
        pk.counter_fold(
            np.zeros(b, np.int64), deltas, np.zeros((b, k, d), np.int32),
            np.full(b, k, np.int32), np.zeros((b, d), np.int32),
            np.ones((b, d), np.int32),
        )


def test_orset_presence_matches_rule(rng):
    b, e, d = 41, 8, 3
    addvc = rng.integers(0, 5, size=(b, e, d)).astype(np.int32)
    rmvc = rng.integers(0, 5, size=(b, e, d)).astype(np.int32)
    elems_lo = rng.integers(0, 3, size=(b, e)).astype(np.int32)
    want = (addvc > rmvc).any(-1) & (elems_lo != 0)
    got = np.asarray(pk.orset_presence(addvc, rmvc, elems_lo, block=16))
    np.testing.assert_array_equal(got.astype(bool), want)
