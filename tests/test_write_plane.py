"""Write-plane tests (ISSUE 6): cross-connection group commit, the
parallel segmented WAL with group fsync, and the commutative-update
certification bypass.

The reference ships ``sync_log=false`` and batches log records per
partition precisely because a per-commit fsync kills throughput (SURVEY
§7); this suite pins the rebuilt plane's semantics: blind commutative
writers never touch certification stamps, read-bearing txns still
first-committer-abort, a merged batch appends once and fsyncs once, and
recovery merges WAL segments back into exact commit order.
"""

import threading

import numpy as np
import pytest

from antidote_tpu import faults
from antidote_tpu.api.node import AntidoteNode
from antidote_tpu.config import AntidoteConfig
from antidote_tpu.txn.manager import AbortError


@pytest.fixture
def cfg():
    return AntidoteConfig(
        n_shards=2, max_dcs=2, ops_per_key=8, snap_versions=2,
        set_slots=4, keys_per_table=64, batch_buckets=(8,),
    )


@pytest.fixture(autouse=True)
def _disarm():
    yield
    faults.uninstall()


def seg_cfg(cfg, n):
    import dataclasses

    return dataclasses.replace(cfg, wal_segments=n)


# ---------------------------------------------------------------------------
# segmented WAL + recovery merge
# ---------------------------------------------------------------------------
def test_segmented_wal_replays_in_append_order(tmp_path, cfg):
    from antidote_tpu.log import LogManager

    lm = LogManager(seg_cfg(cfg, 3), str(tmp_path / "w"))
    vc = np.zeros(2, np.int64)
    for i in range(9):
        lm.log_effects([(0, f"k{i}", "counter_pn", "b",
                         np.array([i], np.int64), np.array([], np.int32),
                         vc, 0, ())])
        lm.commit_barrier([0])  # rotates: records spread over segments
    files = [p for p in (tmp_path / "w").iterdir()
             if p.name.startswith("shard_0")]
    assert len(files) == 3, files  # shard_0.wal + .s1 + .s2
    assert all(p.stat().st_size > 0 for p in files), "rotation never moved"
    # merged replay reconstructs the exact append order via "q"
    assert [r["k"] for r in lm.replay_shard(0)] == [f"k{i}"
                                                    for i in range(9)]
    # op-id chain is one monotone sequence across segments
    assert [r["id"] for r in lm.replay_shard(0)] == list(range(1, 10))
    lm.close()


def test_segmented_node_recovery_and_truncate(tmp_path, cfg):
    scfg = seg_cfg(cfg, 3)
    node = AntidoteNode(scfg, log_dir=str(tmp_path))
    for i in range(12):
        node.update_objects([(f"k{i % 5}", "counter_pn", "b",
                              ("increment", 1))])
    vals_before, _ = node.read_objects(
        [(f"k{i}", "counter_pn", "b") for i in range(5)])
    node.store.log.close()
    re = AntidoteNode(scfg, log_dir=str(tmp_path), recover=True)
    vals_after, _ = re.read_objects(
        [(f"k{i}", "counter_pn", "b") for i in range(5)])
    assert vals_after == vals_before
    # fresh appends after recovery keep the sequence monotone (no reuse)
    re.update_objects([("k0", "counter_pn", "b", ("increment", 1))])
    for shard in range(scfg.n_shards):
        qs = [r["q"] for r in re.store.log.replay_shard(shard)]
        assert qs == sorted(qs) and len(qs) == len(set(qs))
    # truncate drops every segment of the shard
    re.store.log.truncate_shard(0)
    assert list(re.store.log.replay_shard(0)) == []
    assert int(re.store.log.seqs[0]) == 0
    re.store.log.close()


def test_opening_with_fewer_segments_still_replays_all(tmp_path, cfg):
    """A dir written with 3 segments opened with 1 must not lose the
    extra segments' records (shard_segment_paths unions on-disk files)."""
    from antidote_tpu.log import LogManager

    lm = LogManager(seg_cfg(cfg, 3), str(tmp_path / "w"))
    vc = np.zeros(2, np.int64)
    for i in range(6):
        lm.log_effects([(0, f"k{i}", "counter_pn", "b",
                         np.array([1], np.int64), np.array([], np.int32),
                         vc, 0, ())])
        lm.commit_barrier([0])
    lm.close()
    lm1 = LogManager(seg_cfg(cfg, 1), str(tmp_path / "w"))
    assert [r["k"] for r in lm1.replay_shard(0)] == [f"k{i}"
                                                     for i in range(6)]
    lm1.close()


# ---------------------------------------------------------------------------
# group fsync coordinator
# ---------------------------------------------------------------------------
def test_group_fsync_ticket_and_observer(tmp_path, cfg):
    from antidote_tpu.log import LogManager

    lm = LogManager(seg_cfg(cfg, 2), str(tmp_path / "w"),
                    sync_on_commit=True)
    batches = []
    lm.on_fsync_batch = batches.append
    vc = np.zeros(2, np.int64)
    lm.log_effects([(0, "a", "counter_pn", "b", np.array([1], np.int64),
                     np.array([], np.int32), vc, 0, ())])
    t = lm.barrier_async([0])
    t.wait()  # the covering fsync completed
    assert batches and batches[0] >= 1
    # sync_log=false: the ticket is ready immediately
    lm.set_sync(False)
    lm.log_effects([(0, "b", "counter_pn", "b", np.array([1], np.int64),
                     np.array([], np.int32), vc, 0, ())])
    t2 = lm.barrier_async([0])
    t2.wait(timeout=0.001)  # would raise TimeoutError if parked
    lm.close()


def test_fsync_fault_fails_the_covering_ticket(tmp_path, cfg):
    """An injected wal.fsync error must surface on the barrier's ticket
    (the ack gate), not vanish into the coordinator thread."""
    from antidote_tpu.log import LogManager

    lm = LogManager(seg_cfg(cfg, 1), str(tmp_path / "w"),
                    sync_on_commit=True)
    vc = np.zeros(2, np.int64)
    lm.log_effects([(0, "a", "counter_pn", "b", np.array([1], np.int64),
                     np.array([], np.int32), vc, 0, ())])
    faults.install(faults.FaultPlan(seed=3).add(
        "wal.fsync", "io_error", key="shard_0.wal", times=1))
    with pytest.raises(OSError):
        lm.commit_barrier([0])
    faults.uninstall()
    lm.commit_barrier([0])  # heals once the rule exhausts
    lm.close()


def test_fsync_failure_fails_acks_typed_and_enters_read_only(tmp_path, cfg):
    """Node level: records reach the file but the covering fsync fails —
    every write-bearing ack in the batch fails TYPED (ReadOnlyError) and
    the node flips read-only until the volume heals."""
    from antidote_tpu.overload import ReadOnlyError

    node = AntidoteNode(seg_cfg(cfg, 2), log_dir=str(tmp_path))
    node.store.log.set_sync(True)
    node.update_objects([("k", "counter_pn", "b", ("increment", 1))])
    faults.install(faults.FaultPlan(seed=4).add(
        "wal.fsync", "enospc", times=1))
    with pytest.raises(ReadOnlyError):
        node.update_objects([("k", "counter_pn", "b", ("increment", 1))])
    assert node.txm.read_only_reason is not None
    faults.uninstall()
    node.txm._ro_probe_at = 0.0
    node.update_objects([("k", "counter_pn", "b", ("increment", 1))])
    assert node.txm.read_only_reason is None


# ---------------------------------------------------------------------------
# commutativity bypass matrix (ISSUE 6 satellite)
# ---------------------------------------------------------------------------
def test_blind_commutative_updates_never_touch_stamps(cfg):
    node = AntidoteNode(cfg)
    txm = node.txm
    # blind counter / set-add / flag-enable: all commute, none stamps
    group = []
    for upd in [("c", "counter_pn", "b", ("increment", 1)),
                ("s", "set_aw", "b", ("add", "x")),
                ("f", "flag_ew", "b", ("enable", None))]:
        t = txm.start_transaction()
        txm.update_objects([upd], t)
        group.append(t)
    outs = txm.commit_transactions_group(group)
    assert all(isinstance(o, np.ndarray) for o in outs)
    assert txm.committed_keys == {}
    assert node.metrics.cert_bypass.value() == 3


def test_state_dependent_ops_keep_certification(cfg):
    """A set_aw REMOVE reads state for observed-remove semantics — no
    bypass: it stamps, and a stale read-bearing peer aborts against it."""
    node = AntidoteNode(cfg)
    txm = node.txm
    node.update_objects([("s", "set_aw", "b", ("add", "x"))])
    stale = txm.start_transaction()
    txm.read_objects([("s", "set_aw", "b")], stale)
    txm.update_objects([("s", "set_aw", "b", ("add", "y"))], stale)
    node.update_objects([("s", "set_aw", "b", ("remove", "x"))])
    assert ("s", "b") in txm.committed_keys  # the remove stamped
    with pytest.raises(AbortError):
        txm.commit_transaction(stale)


def test_explicit_certify_true_defeats_the_bypass(cfg):
    """Reference parity: a txn carrying certify=true keeps full
    first-committer-wins even for blind commutative updates."""
    node = AntidoteNode(cfg)
    txm = node.txm
    t1 = txm.start_transaction(props={"certify": True})
    t2 = txm.start_transaction(props={"certify": True})
    txm.update_objects([("k", "counter_pn", "b", ("increment", 1))], t1)
    txm.update_objects([("k", "counter_pn", "b", ("increment", 1))], t2)
    assert isinstance(txm.commit_transactions_group([t1])[0], np.ndarray)
    assert ("k", "b") in txm.committed_keys  # certified txns stamp
    with pytest.raises(AbortError):
        txm.commit_transaction(t2)


def test_bypass_skips_registers_and_escrow(cfg):
    """register_lww assigns and counter_b spends are NOT blind-
    commutative: they stamp (and escrow guards still apply)."""
    node = AntidoteNode(cfg)
    txm = node.txm
    node.update_objects([("r", "register_lww", "b", ("assign", "v"))])
    assert ("r", "b") in txm.committed_keys


# ---------------------------------------------------------------------------
# cross-connection merge point (wire level)
# ---------------------------------------------------------------------------
def test_interactive_commits_merge_across_connections(cfg):
    """N client threads run interactive blind-increment txns against one
    server: every commit acks (no spurious aborts — the bypass), the
    value adds up exactly, and the merge-width histogram proves commits
    actually fused into merged batches at the locked worker."""
    from antidote_tpu.proto.client import AntidoteClient
    from antidote_tpu.proto.server import ProtocolServer

    node = AntidoteNode(cfg)
    srv = ProtocolServer(node, port=0)
    n_threads, per = 6, 10
    errs = []
    try:
        def worker(i):
            try:
                c = AntidoteClient(port=srv.port)
                for j in range(per):
                    t = c.start_transaction()
                    t.update_objects(
                        [("hot", "counter_pn", "b", ("increment", 1))])
                    t.commit()
                c.close()
            except Exception as e:  # pragma: no cover - failure detail
                errs.append(repr(e))

        ts = [threading.Thread(target=worker, args=(i,))
              for i in range(n_threads)]
        for t in ts:
            t.start()
        for t in ts:
            t.join(timeout=120)
        assert not errs, errs
        vals, _ = node.read_objects([("hot", "counter_pn", "b")])
        assert vals[0] == n_threads * per
        h = node.metrics.commit_merge_width
        assert h.count >= 1
        # the stamp table stayed empty: all blind, all bypassed
        assert node.txm.committed_keys == {}
    finally:
        srv.close()


def test_mixed_static_and_interactive_merge(cfg):
    """A static update and an interactive commit racing on different
    connections both land; the interactive rmw txn that REALLY conflicts
    still aborts with a typed remote error."""
    from antidote_tpu.proto.client import AntidoteClient, RemoteAbort
    from antidote_tpu.proto.server import ProtocolServer

    node = AntidoteNode(cfg)
    srv = ProtocolServer(node, port=0)
    try:
        c1 = AntidoteClient(port=srv.port)
        c2 = AntidoteClient(port=srv.port)
        t = c1.start_transaction()
        t.read_objects([("m", "counter_pn", "b")])
        t.update_objects([("m", "counter_pn", "b", ("increment", 10))])
        # a commit lands between the rmw txn's snapshot and its commit
        # and must stamp: make it read-bearing too
        t2 = c2.start_transaction()
        t2.read_objects([("m", "counter_pn", "b")])
        t2.update_objects([("m", "counter_pn", "b", ("increment", 100))])
        t2.commit()
        with pytest.raises(RemoteAbort):
            t.commit()
        vals, _ = c1.read_objects([("m", "counter_pn", "b")])
        assert vals[0] == 100
        c1.close(), c2.close()
    finally:
        srv.close()


def test_group_commit_window_widens_merges(cfg):
    """With a gather window, commits arriving within it fuse into one
    merged batch (merge width > 1) instead of one batch per arrival."""
    from antidote_tpu.proto.client import AntidoteClient
    from antidote_tpu.proto.server import ProtocolServer

    node = AntidoteNode(cfg)
    srv = ProtocolServer(node, port=0, group_commit_window_us=20_000)
    try:
        errs = []

        def worker(i):
            try:
                c = AntidoteClient(port=srv.port)
                for _ in range(5):
                    c.update_objects(
                        [(f"w{i}", "counter_pn", "b", ("increment", 1))])
                c.close()
            except Exception as e:  # pragma: no cover
                errs.append(repr(e))

        ts = [threading.Thread(target=worker, args=(i,)) for i in range(4)]
        for t in ts:
            t.start()
        for t in ts:
            t.join(timeout=120)
        assert not errs, errs
        h = node.metrics.commit_merge_width
        assert h.percentile(0.99) >= 2, "window never merged commits"
        st = srv._pipeline_status()
        assert st["group_commit_window_us"] == 20_000.0
    finally:
        srv.close()


def test_write_plane_status_block(tmp_path, cfg):
    node = AntidoteNode(seg_cfg(cfg, 2), log_dir=str(tmp_path))
    node.update_objects([("k", "counter_pn", "b", ("increment", 1))])
    wp = node.status()["write_plane"]
    assert wp["wal_segments"] == 2
    assert len(wp["segment_depth_bytes"]) == 2
    assert wp["sync_log"] is False
    assert wp["merge_width"]["count"] >= 1
    assert wp["cert_bypass_total"] >= 1
    assert {"count", "mean", "p50", "p99"} <= set(wp["fsync_batch"])
