"""Inter-DC replication over REAL sockets: the multidc suites rerun on the
TCP fabric (each DC gets its own fabric instance, as separate deployments
would), covering the stream path, log catch-up RPC after subscribing late,
and bcounter rights transfers over the query channel."""

import numpy as np
import pytest

from antidote_tpu.api import AntidoteNode
from antidote_tpu.config import AntidoteConfig
from antidote_tpu.interdc import DCReplica
from antidote_tpu.interdc.tcp import TcpFabric
from antidote_tpu.overload import InsufficientRightsError


@pytest.fixture
def cfg():
    return AntidoteConfig(
        n_shards=2, max_dcs=3, ops_per_key=8, snap_versions=2,
        set_slots=4, keys_per_table=16, batch_buckets=(8,),
    )


@pytest.fixture
def dcs(cfg):
    fabrics = [TcpFabric() for _ in range(3)]
    nodes = [AntidoteNode(cfg, dc_id=i) for i in range(3)]
    reps = [DCReplica(n, f, f"dc{i}")
            for i, (n, f) in enumerate(zip(nodes, fabrics))]
    TcpFabric.interconnect(fabrics)
    for a in reps:
        for b in reps:
            if a is not b:
                a.observe_dc(b)
    yield fabrics, nodes, reps
    for f in fabrics:
        f.close()


def pump_all(fabrics, rounds=6, timeout=0.3):
    """Until quiescent across every DC."""
    for _ in range(rounds):
        moved = sum(f.pump(timeout=timeout) for f in fabrics)
        if moved == 0:
            return


def test_replication_over_sockets(dcs):
    fabrics, nodes, reps = dcs
    vc = nodes[0].update_objects([("k", "counter_pn", "b", ("increment", 5))])
    pump_all(fabrics)
    for n in nodes[1:]:
        vals, _ = n.read_objects([("k", "counter_pn", "b")], clock=vc)
        assert vals == [5]


def test_multi_txn_causal_chain(dcs):
    fabrics, nodes, reps = dcs
    vc0 = nodes[0].update_objects([("s", "set_aw", "b", ("add", "a"))])
    pump_all(fabrics)
    vals, vc1 = nodes[1].read_objects([("s", "set_aw", "b")], clock=vc0)
    assert vals == [["a"]]
    vc2 = nodes[1].update_objects([("s", "set_aw", "b", ("remove", "a"))],
                                  clock=vc1)
    pump_all(fabrics)
    vals, _ = nodes[2].read_objects([("s", "set_aw", "b")], clock=vc2)
    assert vals == [[]]


def test_late_subscriber_catches_up_via_log_query(cfg):
    """DC1 subscribes only AFTER DC0 already committed: the first ping
    reveals the opid gap and the catch-up RPC replays the missed txns over
    the query connection."""
    fabrics = [TcpFabric() for _ in range(2)]
    nodes = [AntidoteNode(cfg, dc_id=i) for i in range(2)]
    reps = [DCReplica(n, f, f"dc{i}")
            for i, (n, f) in enumerate(zip(nodes, fabrics))]
    TcpFabric.interconnect(fabrics)
    try:
        # commit before anyone subscribes: the stream push goes nowhere
        nodes[0].update_objects([("k", "counter_pn", "b", ("increment", 3))])
        reps[1].observe_dc(reps[0])
        # a later heartbeat (its chain opid exposes the gap) triggers
        # catch-up through the socket query channel
        reps[0].heartbeat()
        pump_all(fabrics)
        vals, _ = nodes[1].read_objects(
            [("k", "counter_pn", "b")], clock=nodes[1].store.dc_max_vc()
        )
        assert vals == [3]
    finally:
        for f in fabrics:
            f.close()


def test_bcounter_transfer_over_socket_query_channel(dcs):
    fabrics, nodes, reps = dcs
    nodes[0].update_objects([("c", "counter_b", "b", ("increment", (10, 0)))])
    pump_all(fabrics)
    with pytest.raises(InsufficientRightsError):
        nodes[1].update_objects([("c", "counter_b", "b", ("decrement", (4, 1)))])
    assert reps[1].bcounter_tick() == 1   # RPC to DC0 over the socket
    pump_all(fabrics)
    nodes[1].update_objects([("c", "counter_b", "b", ("decrement", (4, 1)))])
    pump_all(fabrics)
    vals, _ = nodes[0].read_objects([("c", "counter_b", "b")],
                                    clock=nodes[0].store.dc_max_vc())
    assert vals[0] == 6


def test_public_host_keeps_local_dialing_on_bind_address(cfg):
    """--public-host with an external DNS/LB name must not break
    in-process observe_dc/_rpc: local dialing uses the BIND address;
    the public name appears only in exported descriptors."""
    fabrics = [TcpFabric(public_host="lb.invalid") for _ in range(2)]
    nodes = [AntidoteNode(cfg, dc_id=i) for i in range(2)]
    reps = [DCReplica(n, f, f"dc{i}")
            for i, (n, f) in enumerate(zip(nodes, fabrics))]
    TcpFabric.interconnect(fabrics)
    try:
        # in-process subscribe + catch-up RPC dial 127.0.0.1, not the
        # unresolvable advertised name
        reps[1].observe_dc(reps[0])
        nodes[0].update_objects([("k", "counter_pn", "b", ("increment", 2))])
        pump_all(fabrics)
        vals, _ = nodes[1].read_objects(
            [("k", "counter_pn", "b")], clock=nodes[1].store.dc_max_vc())
        assert vals == [2]
        # the wire descriptor carries the public name for REMOTE DCs
        assert reps[0].descriptor().address[0] == "lb.invalid"
        assert fabrics[0].address_of(0)[0] == "127.0.0.1"
    finally:
        for f in fabrics:
            f.close()


def test_parallel_writes_from_all_dcs(dcs):
    fabrics, nodes, reps = dcs
    for i, n in enumerate(nodes):
        n.update_objects([("shared", "counter_pn", "b", ("increment", i + 1))])
    pump_all(fabrics)
    target = np.maximum.reduce([n.store.dc_max_vc() for n in nodes])
    for n in nodes:
        vals, _ = n.read_objects([("shared", "counter_pn", "b")], clock=target)
        assert vals[0] == 6
