"""Multi-tenant QoS tests (ISSUE 19): weighted-fair lanes, per-tenant
quotas, and the noisy-neighbor isolation contract.

Part A drives the primitives deterministically (spec parsing, identity
derivation, the TenantLanes DRR properties — proportional shares, work
conservation, no starvation of an under-quota tenant — and the
admission gate's per-key streak discipline from satellite 1).  Part B
puts the typed ``tenant_busy`` vocabulary on real sockets: the native
dialect, the apb errmsg encoding, and a forwarding follower in between
— the refusal must still say WHICH lane refused after every hop.
"""

import queue
import threading
import time

import pytest

from antidote_tpu.api.node import AntidoteNode
from antidote_tpu.config import AntidoteConfig
from antidote_tpu.overload import AdmissionGate, BusyError, TenantBusyError
from antidote_tpu.proto.client import (
    AntidoteClient,
    ApbClient,
    RemoteBusy,
    RemoteTenantBusy,
)
from antidote_tpu.proto.server import ProtocolServer
from antidote_tpu.tenancy import (
    DEFAULT_TENANT,
    TenantLanes,
    TenantRegistry,
    TenantSpec,
    batch_rounds,
    parse_tenant_spec,
)


# ---------------------------------------------------------------------------
# Part A — primitives
# ---------------------------------------------------------------------------
@pytest.mark.smoke
def test_parse_tenant_spec_grammar():
    s = parse_tenant_spec("acme:3,max_in_flight=64,max_backlog=512")
    assert (s.name, s.weight, s.max_in_flight, s.max_backlog) == \
        ("acme", 3, 64, 512)
    s = parse_tenant_spec("free")  # weight optional
    assert (s.name, s.weight, s.max_in_flight, s.max_backlog) == \
        ("free", 1, None, None)
    for bad in ("acme:x", "acme:1,wat=3", "acme:1,max_backlog=q",
                "", "a b:1", "acme:0"):
        with pytest.raises(ValueError):
            parse_tenant_spec(bad)


@pytest.mark.smoke
def test_registry_identity_derivation():
    reg = TenantRegistry([TenantSpec("gold", 3), TenantSpec("bronze", 1)])
    assert reg.names[0] == DEFAULT_TENANT and reg.multi
    # bucket-namespace derivation: registered prefix wins, str or bytes
    assert reg.tenant_of("gold/orders") == "gold"
    assert reg.tenant_of(b"bronze/x") == "bronze"
    # unregistered prefixes and flat buckets ride the default lane —
    # a hostile client inventing prefixes cannot allocate lanes
    assert reg.tenant_of("mallory/x") == DEFAULT_TENANT
    assert reg.tenant_of("plain") == DEFAULT_TENANT
    # explicit registered tag wins over buckets; unregistered tag falls
    # back to bucket derivation
    assert reg.resolve("gold", ["bronze/x"]) == "gold"
    assert reg.resolve("mallory", ["bronze/x"]) == "bronze"
    assert reg.resolve(None, ["plain", "gold/x"]) == "gold"
    assert reg.resolve(None, ["plain"]) == DEFAULT_TENANT
    # label clamp: wire-fed values collapse onto the bounded set
    assert reg.label("gold") == "gold"
    assert reg.label("mallory") == DEFAULT_TENANT
    # an untenanted registry is just the default lane
    assert not TenantRegistry().multi


@pytest.mark.smoke
def test_untenanted_lanes_keep_plain_queue_contract():
    """With only the default lane, TenantLanes IS the old shared queue:
    FIFO order, queue.Full past maxsize (the classic global-busy reply),
    never tenant_busy."""
    lanes = TenantLanes(TenantRegistry(), maxsize=3, name="t")
    for i in range(3):
        lanes.put_nowait(i, DEFAULT_TENANT)
    with pytest.raises(queue.Full):
        lanes.put_nowait(3, DEFAULT_TENANT)
    assert [lanes.get_nowait() for _ in range(3)] == [0, 1, 2]
    with pytest.raises(queue.Empty):
        lanes.get_nowait()


def test_wfq_shares_proportional_to_weights():
    """Contended dequeue shares converge to the weight ratio: gold:3 vs
    bronze:1 backlogged together → any served window splits within 25%
    of 3:1."""
    reg = TenantRegistry([TenantSpec("gold", 3), TenantSpec("bronze", 1)])
    lanes = TenantLanes(reg, maxsize=200, name="t")
    for i in range(40):
        lanes.put_nowait(("g", i), "gold")
        lanes.put_nowait(("b", i), "bronze")
    served = [lanes.get_nowait() for _ in range(40)]
    g = sum(1 for s in served if s[0] == "g")
    b = sum(1 for s in served if s[0] == "b")
    assert g + b == 40
    # configured share of gold = 3/4; achieved within 25% relative
    assert abs(g / 40 - 0.75) <= 0.25 * 0.75
    # FIFO within each lane
    assert [s[1] for s in served if s[0] == "g"] == sorted(
        s[1] for s in served if s[0] == "g")


def test_wfq_work_conservation():
    """An idle sibling's capacity flows to the backlogged tenant: with
    only bronze queued, every dequeue serves bronze back-to-back (no
    idle credit accounting, no waiting on gold's empty lane)."""
    reg = TenantRegistry([TenantSpec("gold", 7),
                          TenantSpec("bronze", 1, max_backlog=64)])
    lanes = TenantLanes(reg, maxsize=100, name="t")
    for i in range(20):
        lanes.put_nowait(i, "bronze")
    assert [lanes.get_nowait() for _ in range(20)] == list(range(20))
    # a lane with leftover DRR credit but nothing queued is skipped,
    # not waited on: gold serves once (leaving unspent credit), then
    # bronze-only traffic flows without a stall
    lanes.put_nowait("g0", "gold")
    assert lanes.get_nowait() == "g0"
    for i in range(5):
        lanes.put_nowait(("b2", i), "bronze")
    assert [lanes.get_nowait() for _ in range(5)] == \
        [("b2", i) for i in range(5)]


def test_per_tenant_bound_never_starves_under_quota_sibling():
    """A saturated lane refuses typed WITHOUT touching its siblings:
    gold full → gold sheds tenant_busy, bronze (under quota) still
    admits and still gets served."""
    reg = TenantRegistry([TenantSpec("gold", 1, max_backlog=2),
                          TenantSpec("bronze", 1)])
    lanes = TenantLanes(reg, maxsize=16, name="t")
    lanes.put_nowait("g0", "gold")
    lanes.put_nowait("g1", "gold")
    with pytest.raises(TenantBusyError) as e:
        lanes.put_nowait("g2", "gold")
    assert e.value.tenant == "gold" and e.value.retry_after_ms >= 25
    # the victim lane is untouched
    lanes.put_nowait("b0", "bronze")
    served = [lanes.get_nowait() for _ in range(3)]
    assert "b0" in served
    assert lanes.shed_counts["gold"] == 1
    assert lanes.shed_counts["bronze"] == 0
    # repeated refusals deepen the lane's OWN pressure hint
    lanes.put_nowait("g2", "gold")
    lanes.put_nowait("g3", "gold")  # lane back at its cap of 2
    hints = []
    for _ in range(8):
        with pytest.raises(TenantBusyError) as e:
            lanes.put_nowait("gX", "gold")
        hints.append(e.value.retry_after_ms)
    assert hints[-1] > hints[0]


def test_control_items_bypass_lane_bounds():
    """Shutdown sentinels ride the control deque: they enqueue into a
    SATURATED lanes object without raising and dequeue first — a full
    lane must never wedge close()."""
    reg = TenantRegistry([TenantSpec("gold", 1, max_backlog=1)])
    lanes = TenantLanes(reg, maxsize=1, name="t")
    lanes.put_nowait("work", "gold")
    sentinel = object()
    lanes.put_nowait(sentinel)  # tenant=None: control plane
    assert lanes.get_nowait() is sentinel
    assert lanes.get_nowait() == "work"


@pytest.mark.smoke
def test_batch_rounds_weight_proportional_and_work_conserving():
    reg = TenantRegistry([TenantSpec("gold", 3), TenantSpec("bronze", 1)])
    # single tenant: one round, zero extra lock cycles
    only = [("gold", i) for i in range(8)]
    assert batch_rounds(only, lambda t: t[0], reg) == [only]
    # storm tenant way past its share: gold's round-1 slice is capped
    # at its weight-proportional quota and the victim rides round 1
    items = [("gold", i) for i in range(20)] + [("bronze", i)
                                               for i in range(2)]
    rounds = batch_rounds(items, lambda t: t[0], reg)
    flat = [x for r in rounds for x in r]
    assert sorted(map(str, flat)) == sorted(map(str, items))  # nothing lost
    assert len(rounds) >= 2
    # the victim's whole (small) backlog commits in round 1 — it never
    # waits behind the aggressor's full queue
    assert sum(1 for t in rounds[0] if t[0] == "bronze") == 2
    g1 = sum(1 for t in rounds[0] if t[0] == "gold")
    assert g1 <= (len(items) * 3) // 4  # weight-proportional cap
    # relative order within each tenant is preserved
    g = [i for (t, i) in flat if t == "gold"]
    assert g == sorted(g)


@pytest.mark.smoke
def test_admission_gate_tenant_caps_and_per_key_streaks():
    reg = TenantRegistry([TenantSpec("gold", 2, max_in_flight=1)])
    g = AdmissionGate(max_in_flight=8, max_per_client=8, tenants=reg)
    g.tenant_enter("gold")
    with pytest.raises(TenantBusyError) as e:
        g.tenant_enter("gold")
    assert e.value.tenant == "gold"
    # uncapped tenants are accounted but never refused
    for _ in range(5):
        g.tenant_enter(DEFAULT_TENANT)
    assert g.tenant_in_flight(DEFAULT_TENANT) == 5
    g.tenant_exit("gold")
    g.tenant_enter("gold")  # freed slot readmits


def test_gate_streaks_are_per_client_not_global():
    """Satellite 1: the pressure hint tracks EACH caller's refusals
    since ITS last admission — a hot client hammering the gate must not
    inflate a first-time client's backoff to the 500 ms ceiling."""
    clk = [0.0]
    g = AdmissionGate(max_in_flight=1, max_per_client=1,
                      clock=lambda: clk[0])
    g.enter("hot")
    hot_hints = []
    for _ in range(80):  # hot client hammers the full gate
        with pytest.raises(BusyError) as e:
            g.enter("hot2")
        hot_hints.append(e.value.retry_after_ms)
    assert hot_hints[-1] == 500  # deep streak hit the ceiling
    with pytest.raises(BusyError) as e:
        g.enter("newcomer")  # first refusal: the 25 ms floor
    assert e.value.retry_after_ms == 25
    # admission pops the key's OWN streak: hot2 finally gets in, then a
    # fresh refusal restarts it at the floor, not the 500 ms ceiling
    g.exit("hot")
    g.enter("hot2")
    with pytest.raises(BusyError) as e:
        g.enter("hot2")  # per-client cap (max_per_client=1)
    assert e.value.retry_after_ms == 25
    # TTL prune: advance past STREAK_TTL_S — stale streaks are forgotten
    # on the next refusal sweep once the map is large enough
    from antidote_tpu import overload as ov
    for i in range(70):
        g._streaks[f"k{i}"] = (9, clk[0])
    clk[0] += ov.STREAK_TTL_S + 1
    with g._lock:
        g._retry_hint_locked("probe")
    assert all(not k.startswith("k") for k in g._streaks)


def test_streak_map_hard_cap_under_key_flood():
    g = AdmissionGate(max_in_flight=1, max_per_client=1)
    g.enter("w")
    from antidote_tpu import overload as ov
    for i in range(ov._STREAK_MAP_MAX + 10):
        with pytest.raises(BusyError):
            g.enter(f"flood{i}")
    assert len(g._streaks) <= ov._STREAK_MAP_MAX


# ---------------------------------------------------------------------------
# Part B — the wire (typed tenant_busy end-to-end, both dialects)
# ---------------------------------------------------------------------------
def mk_cfg():
    return AntidoteConfig(
        n_shards=2, max_dcs=2, ops_per_key=8, snap_versions=2,
        set_slots=8, rga_slots=16, keys_per_table=64, batch_buckets=(8, 64),
    )


def _mk_server(**kw):
    tenants = TenantRegistry.from_flags(
        kw.pop("tenant_flags", ["gold:3,max_in_flight=1", "bronze:1"]))
    node = AntidoteNode(mk_cfg())
    return node, ProtocolServer(node, port=0, tenants=tenants, **kw)


def test_tenant_busy_typed_native_and_isolated():
    """The acceptance contract on the native dialect: a tenant at its
    own cap gets ``tenant_busy`` (RemoteTenantBusy, tenant named,
    pressure-scaled hint) while an untagged client keeps being served —
    and the global busy stays a DISTINCT type."""
    node, srv = _mk_server()
    a = AntidoteClient(port=srv.port)
    b = AntidoteClient(port=srv.port)
    try:
        # seed commit: publishes a serving epoch so victim reads ride
        # the lock-free epoch path while the write plane is wedged
        b.update_objects([("seed", "counter_pn", "plain",
                           ("increment", 1))])
        res = {}
        with node.txm.commit_lock:  # wedge the write plane
            t = threading.Thread(target=lambda: res.update(
                ok=a.update_objects(
                    [("k", "counter_pn", "gold/b", ("increment", 1))])))
            t.start()
            deadline = time.monotonic() + 10
            while srv.admission.tenant_in_flight("gold") < 1:
                assert time.monotonic() < deadline
                time.sleep(0.005)
            # gold is at max_in_flight=1: its next request refuses TYPED
            # with the lane named — bucket-derived identity
            with pytest.raises(RemoteTenantBusy) as e:
                b.update_objects(
                    [("k2", "counter_pn", "gold/b", ("increment", 1))])
            assert e.value.tenant == "gold"
            assert e.value.retry_after_ms >= 25
            assert isinstance(e.value, RemoteBusy)  # generic loops work
            # explicit connection tag maps to the same lane
            with pytest.raises(RemoteTenantBusy) as e2:
                b.update_objects(
                    [("k3", "counter_pn", "plain", ("increment", 1))],
                    tenant="gold")
            assert e2.value.tenant == "gold"
            # the VICTIM lane is untouched: untagged reads serve fine
            # while gold is wedged (noisy-neighbor isolation)
            vals, _vc = b.read_objects([("k", "counter_pn", "plain")])
            assert vals == [0]
        t.join(timeout=30)
        assert "ok" in res  # the in-flight gold write completed
        # per-tenant observability: node status carries the lane block
        st = b.node_status()
        assert st["tenants"]["multi"] is True
        assert "gold" in st["tenants"]["tenants"]
        gold = st["tenants"]["tenants"]["gold"]
        assert gold["weight"] == 3 and gold["max_in_flight"] == 1
    finally:
        a.close()
        b.close()
        srv.close()


def test_tenant_busy_rides_apb_errmsg():
    """The apb dialect derives tenant from the bucket namespace and
    round-trips the refusal through the errmsg grammar: kind
    ``tenant_busy``, ``tenant=`` kv, retry hint — decoded into the SAME
    RemoteTenantBusy the native client raises."""
    from antidote_tpu.proto import apb

    # grammar round-trip first (no server)
    text = apb.error_text("tenant_busy", "lane full", 75, tenant="gold")
    out = apb.parse_error_text(text)
    assert out["kind"] == "tenant_busy" and out["tenant"] == "gold"
    assert out["retry_after_ms"] == 75 and out["detail"] == "lane full"
    # absent kv stays None (older peers)
    assert apb.parse_error_text(b"busy retry_after_ms=50: x")["tenant"] is None

    node, srv = _mk_server()
    a = AntidoteClient(port=srv.port)
    c = ApbClient(port=srv.port)
    try:
        res = {}
        with node.txm.commit_lock:
            t = threading.Thread(target=lambda: res.update(
                ok=a.update_objects(
                    [("k", "counter_pn", "gold/b", ("increment", 1))])))
            t.start()
            deadline = time.monotonic() + 10
            while srv.admission.tenant_in_flight("gold") < 1:
                assert time.monotonic() < deadline
                time.sleep(0.005)
            with pytest.raises(RemoteTenantBusy) as e:
                c.update_objects(
                    [("k2", "counter_pn", "gold/b", ("increment", 1))])
            assert e.value.tenant == "gold"
            assert e.value.retry_after_ms >= 25
        t.join(timeout=30)
        assert "ok" in res
    finally:
        a.close()
        c.close()
        srv.close()


def test_tenant_shed_metrics_stay_bounded_and_labeled():
    """Refusals land in the tenant-labeled shed counter under the
    clamped label set, and the global shed counter distinguishes the
    tenant plane from server_queue/admission."""
    node, srv = _mk_server()
    a = AntidoteClient(port=srv.port)
    b = AntidoteClient(port=srv.port)
    try:
        m = node.metrics
        with node.txm.commit_lock:
            t = threading.Thread(target=lambda: a.update_objects(
                [("k", "counter_pn", "gold/b", ("increment", 1))]))
            t.start()
            deadline = time.monotonic() + 10
            while srv.admission.tenant_in_flight("gold") < 1:
                assert time.monotonic() < deadline
                time.sleep(0.005)
            with pytest.raises(RemoteTenantBusy):
                b.update_objects(
                    [("k2", "counter_pn", "gold/b", ("increment", 1))])
        t.join(timeout=30)
        assert m.tenant_shed.value(tenant="gold", plane="admission") >= 1
        assert m.shed.value(plane="tenant") >= 1
        # request latency observed per (clamped) tenant
        assert ("gold",) in m.tenant_request_seconds._children
    finally:
        a.close()
        b.close()
        srv.close()


# ---------------------------------------------------------------------------
# Part C — the forwarding follower (ISSUE 17 hop carries the tenant)
# ---------------------------------------------------------------------------
def test_tenant_busy_through_forwarding_follower(tmp_path):
    """Acceptance: the typed tenant refusal crosses a server-side
    forwarding hop intact.  A write enters at a FOLLOWER, is forwarded
    to the owner, the owner's gold lane refuses ``tenant_busy`` — and
    the EDGE client still sees :class:`RemoteTenantBusy` naming the
    tenant, not a generic proxy failure.  The connection-level tag
    rides the hop explicitly; the parked write proves the bucket
    namespace derives the same lane with no tag at all."""
    from test_proxy import _Pump, _wire_follower

    from antidote_tpu.interdc import DCReplica
    from antidote_tpu.interdc.tcp import TcpFabric

    cfg = AntidoteConfig(
        n_shards=2, max_dcs=3, ops_per_key=8, snap_versions=2,
        set_slots=4, keys_per_table=16, batch_buckets=(8,),
    )
    flags = ["gold:3,max_in_flight=1"]
    ofab = TcpFabric(backoff_base=0.05, backoff_max=0.5)
    owner = AntidoteNode(cfg, dc_id=0, log_dir=str(tmp_path / "owner"))
    orep = DCReplica(owner, ofab, "dc0")
    osrv = ProtocolServer(owner, port=0, interdc=orep,
                          tenants=TenantRegistry.from_flags(flags))
    pump = oc = fc = fc2 = f = None
    try:
        oc = AntidoteClient(osrv.host, osrv.port)
        oc.update_objects([("seed", "counter_pn", "b", ("increment", 1))])
        oc.checkpoint_now()
        f = _wire_follower(cfg, tmp_path, osrv, "pf1", 111,
                           tenants=TenantRegistry.from_flags(flags))
        pump = _Pump(ofab, f["fabric"])
        for _round in range(2):
            f["fol"]._send_report()
        fc = AntidoteClient(f["srv"].host, f["srv"].port)
        fc2 = AntidoteClient(f["srv"].host, f["srv"].port)
        res = {}
        with owner.txm.commit_lock:  # wedge the OWNER's write plane
            # untagged write via the follower: the owner derives gold
            # from the bucket namespace and parks it (in-flight = cap)
            t = threading.Thread(target=lambda: res.update(
                ok=fc.update_objects(
                    [("k", "counter_pn", "gold/b", ("increment", 1))])))
            t.start()
            deadline = time.monotonic() + 10
            while osrv.admission.tenant_in_flight("gold") < 1:
                assert time.monotonic() < deadline
                time.sleep(0.005)
            # tagged write via the follower: the tag crosses the hop,
            # the owner refuses typed, the refusal crosses BACK
            with pytest.raises(RemoteTenantBusy) as e:
                fc2.update_objects(
                    [("k2", "counter_pn", "plain", ("increment", 1))],
                    tenant="gold")
            assert e.value.tenant == "gold"
            assert e.value.retry_after_ms >= 25
        t.join(timeout=30)
        assert "ok" in res  # the parked forwarded write completed
    finally:
        for c in (oc, fc, fc2):
            if c is not None:
                c.close()
        if pump is not None:
            pump.close()
        if f is not None:
            f["srv"].close()
            f["fabric"].close()
            f["node"].store.log.close()
        osrv.close()
        ofab.close()
        owner.store.log.close()
