"""Wire-protocol tests over a real TCP socket — the analogue of
``pb_client_SUITE`` (/root/reference/test/singledc/pb_client_SUITE.erl:85-102):
per-CRDT coverage through the client, interactive transactions, abort,
error replies, and causal-clock chaining."""

import threading

import pytest

from antidote_tpu.api.node import AntidoteNode
from antidote_tpu.config import AntidoteConfig
from antidote_tpu.proto.client import AntidoteClient, RemoteAbort, RemoteError
from antidote_tpu.proto.server import ProtocolServer


@pytest.fixture(scope="module")
def server():
    cfg = AntidoteConfig(
        n_shards=2, max_dcs=2, ops_per_key=8, snap_versions=2,
        set_slots=8, rga_slots=16, keys_per_table=64, batch_buckets=(8, 64),
    )
    node = AntidoteNode(cfg)
    srv = ProtocolServer(node, port=0)
    yield srv
    srv.close()


@pytest.fixture()
def client(server):
    c = AntidoteClient(port=server.port)
    yield c
    c.close()


def test_static_counter_roundtrip(client):
    clock = client.update_objects([("pbc", "counter_pn", "b", ("increment", 4))])
    vals, _ = client.read_objects([("pbc", "counter_pn", "b")], clock=clock)
    assert vals[0] == 4


def test_interactive_txn(client):
    txn = client.start_transaction()
    txn.update_objects([("pbi", "counter_pn", "b", ("increment", 2))])
    # read-your-writes inside the txn
    assert txn.read_objects([("pbi", "counter_pn", "b")])[0] == 2
    clock = txn.commit()
    vals, _ = client.read_objects([("pbi", "counter_pn", "b")], clock=clock)
    assert vals[0] == 2


def test_abort_discards_writes(client):
    txn = client.start_transaction()
    txn.update_objects([("pba", "counter_pn", "b", ("increment", 9))])
    txn.abort()
    vals, _ = client.read_objects([("pba", "counter_pn", "b")])
    assert vals[0] == 0


def test_per_crdt_coverage(client):
    clock = client.update_objects([
        ("s", "set_aw", "b", ("add", 7)),
        ("s", "set_aw", "b", ("add", 9)),
        ("r", "register_lww", "b", ("assign", "hello")),
        ("mv", "register_mv", "b", ("assign", 5)),
        ("f", "flag_ew", "b", ("enable", None)),
        ("seq", "rga", "b", ("add_right", (0, "x"))),
    ])
    vals, _ = client.read_objects(
        [("s", "set_aw", "b"), ("r", "register_lww", "b"),
         ("mv", "register_mv", "b"), ("f", "flag_ew", "b"),
         ("seq", "rga", "b")],
        clock=clock,
    )
    assert sorted(vals[0]) == [7, 9]
    assert vals[1] == "hello"
    assert vals[2] == [5]
    assert vals[3] is True
    assert vals[4] == ["x"]


def test_map_rr_over_wire(client):
    clock = client.update_objects([
        ("m", "map_rr", "b",
         ("update", [(("cnt", "counter_pn"), ("increment", 3)),
                     (("who", "register_lww"), ("assign", "ada"))])),
    ])
    vals, _ = client.read_objects([("m", "map_rr", "b")], clock=clock)
    assert vals[0][("cnt", "counter_pn")] == 3
    assert vals[0][("who", "register_lww")] == "ada"


def test_certification_conflict_is_remote_abort(client):
    # read-bearing txns: blind increments would take the ISSUE 6
    # commutativity bypass and both commit (see next test)
    t1 = client.start_transaction()
    t2 = client.start_transaction()
    t1.read_objects([("cert", "counter_pn", "b")])
    t2.read_objects([("cert", "counter_pn", "b")])
    t1.update_objects([("cert", "counter_pn", "b", ("increment", 1))])
    t2.update_objects([("cert", "counter_pn", "b", ("increment", 1))])
    t1.commit()
    with pytest.raises(RemoteAbort):
        t2.commit()


def test_blind_interactive_commits_merge_without_conflict(client):
    """Interactive BLIND commits ride the locked worker's merge point
    and the commutativity bypass: concurrent increments to one hot key
    all land (no first-committer aborts), and the value adds up."""
    t1 = client.start_transaction()
    t2 = client.start_transaction()
    t1.update_objects([("blind", "counter_pn", "b", ("increment", 2))])
    t2.update_objects([("blind", "counter_pn", "b", ("increment", 3))])
    t1.commit()
    t2.commit()
    vals, _ = client.read_objects([("blind", "counter_pn", "b")])
    assert vals[0] == 5


def test_error_reply_keeps_connection(client):
    with pytest.raises(RemoteError):
        client.update_objects([("x", "no_such_type", "b", ("inc", 1))])
    # connection still usable
    clock = client.update_objects([("x2", "counter_pn", "b", ("increment", 1))])
    vals, _ = client.read_objects([("x2", "counter_pn", "b")], clock=clock)
    assert vals[0] == 1


def test_unknown_txid_is_error(client):
    with pytest.raises(RemoteError):
        client._call_unknown_commit()


# minimal helper used above — keeps the client API surface clean
def _call_unknown_commit(self):
    from antidote_tpu.proto.codec import MessageCode

    return self._call(MessageCode.COMMIT_TRANSACTION, {"txid": 10**9})


AntidoteClient._call_unknown_commit = _call_unknown_commit


def test_concurrent_clients(server):
    """Many clients hammer the acceptor pool concurrently; every increment
    must land exactly once (the dispatcher serializes the commit stream)."""
    n_clients, n_ops = 8, 10
    errs = []

    def work(i):
        try:
            c = AntidoteClient(port=server.port)
            for _ in range(n_ops):
                c.update_objects([("conc", "counter_pn", "b", ("increment", 1))])
            c.close()
        except Exception as e:  # pragma: no cover
            errs.append(e)

    threads = [threading.Thread(target=work, args=(i,)) for i in range(n_clients)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert not errs
    c = AntidoteClient(port=server.port)
    vals, _ = c.read_objects([("conc", "counter_pn", "b")])
    c.close()
    assert vals[0] == n_clients * n_ops


# ---------------------------------------------------------------------------
# cross-connection static batch gate (r4 VERDICT item 3)
# ---------------------------------------------------------------------------
def test_static_batch_concurrent_reads_and_updates():
    import threading

    from antidote_tpu.api.node import AntidoteNode
    from antidote_tpu.config import AntidoteConfig
    from antidote_tpu.proto.client import AntidoteClient
    from antidote_tpu.proto.server import ProtocolServer

    cfg = AntidoteConfig(n_shards=4, max_dcs=2, keys_per_table=64,
                         batch_buckets=(16, 64))
    node = AntidoteNode(cfg)
    srv = ProtocolServer(node, port=0)
    assert srv.batch_static
    try:
        n_cli, per = 8, 12
        errs = []

        def worker(i):
            try:
                c = AntidoteClient(srv.host, srv.port)
                for j in range(per):
                    c.update_objects([(i * 1000 + j, "counter_pn", "b",
                                       ("increment", 1))])
                    vals, _vc = c.read_objects(
                        [(i * 1000 + j, "counter_pn", "b")])
                    assert vals[0] == 1, vals
                c.close()
            except Exception as e:  # pragma: no cover
                errs.append(repr(e))

        ts = [threading.Thread(target=worker, args=(i,)) for i in range(n_cli)]
        for t in ts:
            t.start()
        for t in ts:
            t.join(timeout=120)
        assert not errs, errs
        # all writes landed: a single merged read sees every counter
        c = AntidoteClient(srv.host, srv.port)
        objs = [(i * 1000 + j, "counter_pn", "b")
                for i in range(n_cli) for j in range(per)]
        vals, _vc = c.read_objects(objs)
        assert all(v == 1 for v in vals)
        c.close()
    finally:
        srv.close()


def test_group_commit_abort_isolation():
    """Two conflicting updates in one group: first commits, second aborts;
    an unrelated update in the same group is untouched."""
    import numpy as np

    from antidote_tpu.api.node import AntidoteNode
    from antidote_tpu.config import AntidoteConfig
    from antidote_tpu.txn.manager import AbortError

    cfg = AntidoteConfig(n_shards=4, max_dcs=2, keys_per_table=64,
                         batch_buckets=(16, 64))
    node = AntidoteNode(cfg)
    txm = node.txm
    # stage two txns on the same key with the same snapshot, plus one
    # disjoint — drive the group commit directly
    t1 = txm.start_transaction()
    t2 = txm.start_transaction()
    t3 = txm.start_transaction()
    # t1/t2 are read-bearing (rmw) so they keep certification — blind
    # increments would take the ISSUE 6 bypass and all commit
    txm.read_objects([("k", "counter_pn", "b")], t1)
    txm.read_objects([("k", "counter_pn", "b")], t2)
    txm.update_objects([("k", "counter_pn", "b", ("increment", 1))], t1)
    txm.update_objects([("k", "counter_pn", "b", ("increment", 5))], t2)
    txm.update_objects([("x", "counter_pn", "b", ("increment", 9))], t3)
    outs = txm.commit_transactions_group([t1, t2, t3])
    assert isinstance(outs[0], np.ndarray)
    assert isinstance(outs[1], AbortError)
    assert isinstance(outs[2], np.ndarray)
    vals, _ = node.read_objects(
        [("k", "counter_pn", "b"), ("x", "counter_pn", "b")]
    )
    assert vals == [1, 9]
