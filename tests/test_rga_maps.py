"""RGA sequence and map composite semantics (reference types
antidote_crdt_rga / antidote_crdt_map_rr / antidote_crdt_map_go)."""

import numpy as np
import pytest

from antidote_tpu.api import AntidoteNode
from antidote_tpu.interdc import DCReplica, LoopbackHub


@pytest.fixture
def node(cfg):
    return AntidoteNode(cfg)


# ---------------------------------------------------------------- RGA

def test_rga_insert_delete(node):
    k = ("doc", "rga", "b")
    node.update_objects([("doc", "rga", "b", ("insert", (0, "a")))])
    node.update_objects([("doc", "rga", "b", ("insert", (1, "c")))])
    node.update_objects([("doc", "rga", "b", ("insert", (1, "b")))])
    vals, _ = node.read_objects([k])
    assert vals == [["a", "b", "c"]]
    node.update_objects([("doc", "rga", "b", ("delete", 1))])
    vals, _ = node.read_objects([k])
    assert vals == [["a", "c"]]
    # insert after a tombstone keeps order
    node.update_objects([("doc", "rga", "b", ("insert", (1, "x")))])
    vals, _ = node.read_objects([k])
    assert vals == [["a", "x", "c"]]


def test_rga_head_inserts(node):
    for ch in "cba":
        node.update_objects([("doc", "rga", "b", ("insert", (0, ch)))])
    vals, _ = node.read_objects([("doc", "rga", "b")])
    assert vals == [["a", "b", "c"]]


def test_rga_concurrent_inserts_converge(cfg):
    # two DCs insert concurrently after the same origin; all replicas
    # converge on the same order
    hub = LoopbackHub()
    nodes = [AntidoteNode(cfg, dc_id=i) for i in range(2)]
    reps = [DCReplica(n, hub) for n in nodes]
    DCReplica.connect_all(reps)
    vc = nodes[0].update_objects([("doc", "rga", "b", ("insert", (0, "base")))])
    hub.pump()
    nodes[0].update_objects([("doc", "rga", "b", ("insert", (1, "L")))],
                            clock=vc)
    nodes[1].update_objects([("doc", "rga", "b", ("insert", (1, "R")))],
                            clock=vc)
    hub.pump()
    target = np.max(np.stack([n.store.dc_max_vc() for n in nodes]), axis=0)
    seqs = []
    for n in nodes:
        vals, _ = n.read_objects([("doc", "rga", "b")], clock=target)
        seqs.append(vals[0])
    assert seqs[0] == seqs[1]
    assert sorted(seqs[0]) == ["L", "R", "base"]
    assert seqs[0][0] == "base"


def test_rga_index_errors(node):
    node.update_objects([("doc", "rga", "b", ("insert", (0, "a")))])
    with pytest.raises(IndexError):
        node.update_objects([("doc", "rga", "b", ("insert", (5, "x")))])
    with pytest.raises(IndexError):
        node.update_objects([("doc", "rga", "b", ("delete", 3))])


# ---------------------------------------------------------------- maps

def test_map_go_update_and_read(node):
    k = ("m", "map_go", "b")
    node.update_objects([("m", "map_go", "b", ("update", {
        ("clicks", "counter_pn"): ("increment", 3),
        ("name", "register_lww"): ("assign", "zoe"),
    }))])
    vals, _ = node.read_objects([k])
    assert vals == [{
        ("clicks", "counter_pn"): 3,
        ("name", "register_lww"): "zoe",
    }]
    node.update_objects([("m", "map_go", "b", ("update", {
        ("clicks", "counter_pn"): ("increment", 2),
    }))])
    vals, _ = node.read_objects([k])
    assert vals[0][("clicks", "counter_pn")] == 5


def test_map_rr_remove(node):
    k = ("m", "map_rr", "b")
    node.update_objects([("m", "map_rr", "b", ("update", {
        ("tags", "set_aw"): ("add_all", ["x", "y"]),
        ("n", "counter_fat"): ("increment", 4),
    }))])
    node.update_objects([("m", "map_rr", "b", ("remove", ("n", "counter_fat")))])
    vals, _ = node.read_objects([k])
    assert vals == [{("tags", "set_aw"): ["x", "y"]}]
    # re-adding the field after reset starts fresh (counter_fat has reset)
    node.update_objects([("m", "map_rr", "b", ("update", {
        ("n", "counter_fat"): ("increment", 1),
    }))])
    vals, _ = node.read_objects([k])
    assert vals[0][("n", "counter_fat")] == 1


def test_map_nested_map(node):
    k = ("m", "map_rr", "b")
    node.update_objects([("m", "map_rr", "b", ("update", {
        ("inner", "map_rr"): ("update", {("c", "counter_pn"): ("increment", 9)}),
    }))])
    vals, _ = node.read_objects([k])
    assert vals == [{("inner", "map_rr"): {("c", "counter_pn"): 9}}]


def test_map_read_your_writes_in_txn(node):
    txn = node.start_transaction()
    node.update_objects([("m", "map_rr", "b", ("update", {
        ("c", "counter_pn"): ("increment", 2),
    }))], txn)
    assert node.read_objects([("m", "map_rr", "b")], txn) == [
        {("c", "counter_pn"): 2}
    ]
    node.abort_transaction(txn)
    vals, _ = node.read_objects([("m", "map_rr", "b")])
    assert vals == [{}]


def test_map_replicates(cfg):
    hub = LoopbackHub()
    nodes = [AntidoteNode(cfg, dc_id=i) for i in range(2)]
    reps = [DCReplica(n, hub) for n in nodes]
    DCReplica.connect_all(reps)
    vc = nodes[0].update_objects([("m", "map_rr", "b", ("update", {
        ("s", "set_aw"): ("add", "v"),
    }))])
    hub.pump()
    vals, _ = nodes[1].read_objects([("m", "map_rr", "b")], clock=vc)
    assert vals == [{("s", "set_aw"): ["v"]}]


def test_rga_apply_host_matches_device_apply():
    """The numpy overlay twin (apply_host) must be semantically
    identical to the compiled apply on random insert/delete tapes,
    including drop/overflow cases."""
    import jax.numpy as jnp
    import numpy as np

    from antidote_tpu.config import AntidoteConfig
    from antidote_tpu.crdt import get_type

    cfg = AntidoteConfig(n_shards=2, max_dcs=3, rga_slots=16,
                         ops_per_key=8, keys_per_table=8,
                         batch_buckets=(8,))
    ty = get_type("rga")
    rng = np.random.default_rng(7)
    for trial in range(20):
        spec = ty.state_spec(cfg)
        st_np = {f: np.zeros(shape, np.dtype(dt.dtype))
                 for f, (shape, dt) in
                 ((f, (sh, jnp.zeros((), d))) for f, (sh, d) in spec.items())}
        st_j = {f: jnp.asarray(x) for f, x in st_np.items()}
        uids = [0]  # head
        for step in range(20):
            d = cfg.max_dcs
            vc = np.zeros(d, np.int32)
            vc[0] = step + 1
            b = np.zeros(2, np.int32)
            a = np.zeros(2, np.int64)
            if rng.random() < 0.75 or len(uids) == 1:
                b[0] = 0  # insert
                b[1] = step  # op seq
                a[0] = int(rng.integers(1, 1 << 40))
                a[1] = int(rng.choice(uids))
                uids.append(((step + 1) << 24) | (step << 8))
            else:
                b[0] = 1  # delete
                a[0] = int(rng.choice(uids[1:]))
            st_np = ty.apply_host(cfg, st_np, a, b, vc, 0)
            st_j = ty.apply(cfg, st_j, jnp.asarray(a), jnp.asarray(b),
                            jnp.asarray(vc), jnp.int32(0))
            for f in st_np:
                np.testing.assert_array_equal(
                    np.asarray(st_np[f]), np.asarray(st_j[f]),
                    err_msg=f"{trial=} {step=} field={f}")
