"""RGA sequence and map composite semantics (reference types
antidote_crdt_rga / antidote_crdt_map_rr / antidote_crdt_map_go)."""

import numpy as np
import pytest

from antidote_tpu.api import AntidoteNode
from antidote_tpu.interdc import DCReplica, LoopbackHub


@pytest.fixture
def node(cfg):
    return AntidoteNode(cfg)


# ---------------------------------------------------------------- RGA

def test_rga_insert_delete(node):
    k = ("doc", "rga", "b")
    node.update_objects([("doc", "rga", "b", ("insert", (0, "a")))])
    node.update_objects([("doc", "rga", "b", ("insert", (1, "c")))])
    node.update_objects([("doc", "rga", "b", ("insert", (1, "b")))])
    vals, _ = node.read_objects([k])
    assert vals == [["a", "b", "c"]]
    node.update_objects([("doc", "rga", "b", ("delete", 1))])
    vals, _ = node.read_objects([k])
    assert vals == [["a", "c"]]
    # insert after a tombstone keeps order
    node.update_objects([("doc", "rga", "b", ("insert", (1, "x")))])
    vals, _ = node.read_objects([k])
    assert vals == [["a", "x", "c"]]


def test_rga_head_inserts(node):
    for ch in "cba":
        node.update_objects([("doc", "rga", "b", ("insert", (0, ch)))])
    vals, _ = node.read_objects([("doc", "rga", "b")])
    assert vals == [["a", "b", "c"]]


def test_rga_concurrent_inserts_converge(cfg):
    # two DCs insert concurrently after the same origin; all replicas
    # converge on the same order
    hub = LoopbackHub()
    nodes = [AntidoteNode(cfg, dc_id=i) for i in range(2)]
    reps = [DCReplica(n, hub) for n in nodes]
    DCReplica.connect_all(reps)
    vc = nodes[0].update_objects([("doc", "rga", "b", ("insert", (0, "base")))])
    hub.pump()
    nodes[0].update_objects([("doc", "rga", "b", ("insert", (1, "L")))],
                            clock=vc)
    nodes[1].update_objects([("doc", "rga", "b", ("insert", (1, "R")))],
                            clock=vc)
    hub.pump()
    target = np.max(np.stack([n.store.dc_max_vc() for n in nodes]), axis=0)
    seqs = []
    for n in nodes:
        vals, _ = n.read_objects([("doc", "rga", "b")], clock=target)
        seqs.append(vals[0])
    assert seqs[0] == seqs[1]
    assert sorted(seqs[0]) == ["L", "R", "base"]
    assert seqs[0][0] == "base"


def test_rga_index_errors(node):
    node.update_objects([("doc", "rga", "b", ("insert", (0, "a")))])
    with pytest.raises(IndexError):
        node.update_objects([("doc", "rga", "b", ("insert", (5, "x")))])
    with pytest.raises(IndexError):
        node.update_objects([("doc", "rga", "b", ("delete", 3))])


# ---------------------------------------------------------------- maps

def test_map_go_update_and_read(node):
    k = ("m", "map_go", "b")
    node.update_objects([("m", "map_go", "b", ("update", {
        ("clicks", "counter_pn"): ("increment", 3),
        ("name", "register_lww"): ("assign", "zoe"),
    }))])
    vals, _ = node.read_objects([k])
    assert vals == [{
        ("clicks", "counter_pn"): 3,
        ("name", "register_lww"): "zoe",
    }]
    node.update_objects([("m", "map_go", "b", ("update", {
        ("clicks", "counter_pn"): ("increment", 2),
    }))])
    vals, _ = node.read_objects([k])
    assert vals[0][("clicks", "counter_pn")] == 5


def test_map_rr_remove(node):
    k = ("m", "map_rr", "b")
    node.update_objects([("m", "map_rr", "b", ("update", {
        ("tags", "set_aw"): ("add_all", ["x", "y"]),
        ("n", "counter_fat"): ("increment", 4),
    }))])
    node.update_objects([("m", "map_rr", "b", ("remove", ("n", "counter_fat")))])
    vals, _ = node.read_objects([k])
    assert vals == [{("tags", "set_aw"): ["x", "y"]}]
    # re-adding the field after reset starts fresh (counter_fat has reset)
    node.update_objects([("m", "map_rr", "b", ("update", {
        ("n", "counter_fat"): ("increment", 1),
    }))])
    vals, _ = node.read_objects([k])
    assert vals[0][("n", "counter_fat")] == 1


def test_map_nested_map(node):
    k = ("m", "map_rr", "b")
    node.update_objects([("m", "map_rr", "b", ("update", {
        ("inner", "map_rr"): ("update", {("c", "counter_pn"): ("increment", 9)}),
    }))])
    vals, _ = node.read_objects([k])
    assert vals == [{("inner", "map_rr"): {("c", "counter_pn"): 9}}]


def test_map_read_your_writes_in_txn(node):
    txn = node.start_transaction()
    node.update_objects([("m", "map_rr", "b", ("update", {
        ("c", "counter_pn"): ("increment", 2),
    }))], txn)
    assert node.read_objects([("m", "map_rr", "b")], txn) == [
        {("c", "counter_pn"): 2}
    ]
    node.abort_transaction(txn)
    vals, _ = node.read_objects([("m", "map_rr", "b")])
    assert vals == [{}]


def test_map_replicates(cfg):
    hub = LoopbackHub()
    nodes = [AntidoteNode(cfg, dc_id=i) for i in range(2)]
    reps = [DCReplica(n, hub) for n in nodes]
    DCReplica.connect_all(reps)
    vc = nodes[0].update_objects([("m", "map_rr", "b", ("update", {
        ("s", "set_aw"): ("add", "v"),
    }))])
    hub.pump()
    vals, _ = nodes[1].read_objects([("m", "map_rr", "b")], clock=vc)
    assert vals == [{("s", "set_aw"): ["v"]}]
