"""The fused serving read (TypedTable.read_resolved / KVStore.read_resolved)
and the in-path Pallas kernel dispatch.

Covers the read path of SURVEY §3.3 as ONE device launch: freshness check,
snapshot-version select, versioned ring fold, device value resolution — and
checks the Pallas variants (cfg.use_pallas) against the plain-XLA fold,
which remains the semantics oracle (the r1 VERDICT asked for production
call sites + dispatch tests).
"""

import dataclasses

import numpy as np
import pytest

from antidote_tpu.config import AntidoteConfig
from antidote_tpu.crdt import get_type
from antidote_tpu.store import TypedTable
from antidote_tpu.store.kv import KVStore


def _mk_cfg(**kw):
    base = dict(
        n_shards=2, max_dcs=3, ops_per_key=8, snap_versions=2,
        set_slots=8, mv_slots=4, rga_slots=16, keys_per_table=16,
        batch_buckets=(16, 64),
    )
    base.update(kw)
    return AntidoteConfig(**base)


def _populate_set(table, n_keys, d):
    """3 adds per key on lane 0, then remove the first add on even keys."""
    clock = 0
    first = {}
    for r in range(n_keys):
        for j in range(3):
            clock += 1
            vc = np.zeros(d, np.int32)
            vc[0] = clock
            elem = 100 * (r + 1) + j
            first.setdefault(r, (elem, clock))
            table.append(
                np.asarray([r % table.n_shards]), np.asarray([r]),
                np.asarray([[elem]], np.int64),
                np.zeros((1, 1 + d), np.int32), vc[None, :],
                np.asarray([0], np.int32),
            )
    mid = clock  # historical read point: before any removes
    for r in range(0, n_keys, 2):
        elem, add_t = first[r]
        clock += 1
        vc = np.zeros(d, np.int32)
        vc[0] = clock
        b = np.zeros((1, 1 + d), np.int32)
        b[0, 0] = 1
        b[0, 1] = add_t
        table.append(
            np.asarray([r % table.n_shards]), np.asarray([r]),
            np.asarray([[elem]], np.int64), b, vc[None, :],
            np.asarray([0], np.int32),
        )
    return mid, clock


@pytest.mark.parametrize("use_pallas", [False, True])
def test_set_aw_read_resolved_fresh_and_historical(use_pallas):
    cfg = _mk_cfg(use_pallas=use_pallas)
    ty = get_type("set_aw")
    d = cfg.max_dcs
    table = TypedTable(ty, cfg, n_rows=16, n_shards=2)
    n_keys = 10
    for s in range(2):
        table.used_rows[s] = n_keys
    mid, final = _populate_set(table, n_keys, d)

    rows = np.arange(n_keys, dtype=np.int64)
    shards = rows % 2
    vc_final = np.zeros((n_keys, d), np.int32)
    vc_final[:, 0] = final
    out, fresh, complete = table.read_resolved(shards, rows, vc_final)
    assert fresh.all() and complete.all()
    for r in range(n_keys):
        want = {100 * (r + 1) + j for j in range(3)}
        if r % 2 == 0:
            want.discard(100 * (r + 1))  # first add removed
        got = {int(x) for x in out["top"][r] if x != 0}
        assert got == want, r
        assert int(out["count"][r]) == len(want)

    # historical read: before the removes — the fold path (not the head)
    vc_mid = np.zeros((n_keys, d), np.int32)
    vc_mid[:, 0] = mid
    out2, fresh2, complete2 = table.read_resolved(shards, rows, vc_mid)
    assert complete2.all()
    assert not fresh2[::2].any()  # removed keys' heads are newer than mid
    for r in range(n_keys):
        want = {100 * (r + 1) + j for j in range(3)}  # removes not visible
        got = {int(x) for x in out2["top"][r] if x != 0}
        assert got == want, r


@pytest.mark.parametrize("use_pallas", [False, True])
def test_counter_read_resolved_matches_oracle(use_pallas):
    cfg = _mk_cfg(use_pallas=use_pallas)
    ty = get_type("counter_pn")
    d = cfg.max_dcs
    table = TypedTable(ty, cfg, n_rows=8, n_shards=1)
    table.used_rows[0] = 4
    rng = np.random.default_rng(0)
    clock = 0
    totals = np.zeros(4, np.int64)
    mid_totals = None
    mid = None
    for i in range(20):
        r = int(rng.integers(0, 4))
        delta = int(rng.integers(-50, 50))
        clock += 1
        vc = np.zeros(d, np.int32)
        vc[0] = clock
        table.append(
            np.asarray([0]), np.asarray([r]),
            np.asarray([[delta]], np.int64),
            np.zeros((1, 1), np.int32), vc[None, :],
            np.asarray([0], np.int32),
        )
        totals[r] += delta
        if i == 9:
            mid, mid_totals = clock, totals.copy()
    rows = np.arange(4, dtype=np.int64)
    shards = np.zeros(4, np.int64)
    for at, want in ((clock, totals), (mid, mid_totals)):
        vcs = np.zeros((4, d), np.int32)
        vcs[:, 0] = at
        out, _, complete = table.read_resolved(shards, rows, vcs)
        assert complete.all()
        assert (out["value"] == want).all(), (at, out["value"], want)
    if use_pallas:
        assert table._pallas_counter_ok()


def test_counter_pallas_falls_back_on_huge_deltas():
    cfg = _mk_cfg(use_pallas=True)
    ty = get_type("counter_pn")
    table = TypedTable(ty, cfg, n_rows=8, n_shards=1)
    table.used_rows[0] = 1
    vc = np.zeros((1, cfg.max_dcs), np.int32)
    vc[0, 0] = 1
    big = 2**40
    table.append(
        np.asarray([0]), np.asarray([0]), np.asarray([[big]], np.int64),
        np.zeros((1, 1), np.int32), vc, np.asarray([0], np.int32),
    )
    assert not table._pallas_counter_ok()  # i32 kernel would overflow
    out, _, _ = table.read_resolved(
        np.asarray([0]), np.asarray([0]), vc
    )
    assert int(out["value"][0]) == big


def test_kvstore_read_resolved_matches_read_values():
    cfg = _mk_cfg()
    store = KVStore(cfg)
    from antidote_tpu.store.kv import Effect

    clock = 0
    d = cfg.max_dcs
    objs = [(f"k{i}", "set_aw", "b") for i in range(6)]
    for i, (k, tname, bucket) in enumerate(objs):
        for j in range(2):
            ty = get_type(tname)
            eff = ty.downstream(("add", f"v{i}{j}"), None, store.blobs, cfg)[0]
            clock += 1
            vc = np.zeros(d, np.int32)
            vc[0] = clock
            store.apply_effects(
                [Effect(k, tname, bucket, eff[0], eff[1], eff[2])], [vc], [0]
            )
    at = store.dc_max_vc()
    values = store.read_values(objs, at)
    resolved = store.read_resolved(objs, at)
    for i, (k, tname, bucket) in enumerate(objs):
        got = sorted(
            store.blobs.resolve(int(h)) for h in resolved[i]["top"] if h != 0
        )
        assert got == sorted(values[i])
        assert int(resolved[i]["count"]) == len(values[i])
    # unseen key → bottom value
    bottom = store.read_resolved([("nope", "set_aw", "b")], at)[0]
    assert int(bottom["count"]) == 0


def test_stable_min_of_pallas_path():
    from antidote_tpu.store.kv import stable_min_of

    cfg = _mk_cfg(use_pallas=True)
    store = KVStore(cfg)
    store.applied_vc[:] = np.asarray([[3, 1, 9], [2, 5, 4]], np.int32)
    assert (store.stable_vc() == np.asarray([2, 1, 4])).all()
    # the large-matrix path (multi-node aggregation) takes the kernel
    big = np.random.default_rng(1).integers(0, 1000, size=(4096, 3)).astype(np.int32)
    assert (stable_min_of(big, use_pallas=True) == big.min(axis=0)).all()


def test_handoff_preserves_serving_gates():
    """import_shard / reshard must carry max_abs_delta / max_commit_vc so
    the Pallas counter dispatch and the provably-fresh fast path stay
    sound after a shard moves (r2 review finding)."""
    from antidote_tpu.store import handoff
    from antidote_tpu.store.kv import Effect

    cfg = _mk_cfg(use_pallas=True)
    src = KVStore(cfg)
    ty = get_type("counter_pn")
    eff = ty.downstream(("increment", 2**40), None, src.blobs, cfg)[0]
    vc = np.zeros(cfg.max_dcs, np.int32)
    vc[0] = 7
    src.apply_effects([Effect("k", "counter_pn", "b", eff[0], eff[1])], [vc], [0])
    t_src = src.tables["counter_pn"]
    assert t_src.max_abs_delta >= 2**40
    shard = src.locate("k", "counter_pn", "b")[1]

    dst = KVStore(cfg)
    handoff.import_shard(dst, handoff.export_shard(src, shard, include_log=False))
    t_dst = dst.tables["counter_pn"]
    assert t_dst.max_abs_delta >= 2**40
    assert not t_dst._pallas_counter_ok()
    assert (t_dst.max_commit_vc == t_src.max_commit_vc).all()

    re = handoff.reshard(src, dataclasses.replace(cfg, n_shards=4))
    t_re = re.tables["counter_pn"]
    assert t_re.max_abs_delta >= 2**40
    assert (t_re.max_commit_vc == t_src.max_commit_vc).all()


def test_client_reads_use_fused_serving_path(monkeypatch):
    """r2 VERDICT item 2: AntidoteNode.read_objects (no-writeset txns) must
    serve through KVStore.read_resolved, with value() reconstruction from
    the resolved top-k, and re-fetch full state only on count overflow."""
    from antidote_tpu.api.node import AntidoteNode

    node = AntidoteNode(_mk_cfg())
    node.update_objects([
        ("c", "counter_pn", "b", ("increment", 7)),
        ("r", "register_lww", "b", ("assign", "hello")),
        ("f", "flag_ew", "b", ("enable", {})),
        ("s", "set_aw", "b", ("add_all", ["x", "y"])),
        # 6 elements > resolve_top=4 -> truncated view -> full-state refetch
        ("big", "set_aw", "b", ("add_all", ["e1", "e2", "e3", "e4", "e5", "e6"])),
        ("q", "rga", "b", ("add_right", (0, "head"))),  # no resolve_spec
    ])

    calls = {"resolved": 0, "states": 0}
    orig_resolved = KVStore.read_resolved
    orig_states = KVStore.read_states

    def spy_resolved(self, *a, **kw):
        calls["resolved"] += 1
        return orig_resolved(self, *a, **kw)

    def spy_states(self, *a, **kw):
        calls["states"] += 1
        return orig_states(self, *a, **kw)

    monkeypatch.setattr(KVStore, "read_resolved", spy_resolved)
    monkeypatch.setattr(KVStore, "read_states", spy_states)

    vals, _ = node.read_objects([
        ("c", "counter_pn", "b"),
        ("r", "register_lww", "b"),
        ("f", "flag_ew", "b"),
        ("s", "set_aw", "b"),
        ("big", "set_aw", "b"),
        ("q", "rga", "b"),
        ("never", "counter_pn", "b"),
    ])
    assert vals[0] == 7
    assert vals[1] == "hello"
    assert vals[2] is True
    assert vals[3] == ["x", "y"]
    assert sorted(vals[4]) == ["e1", "e2", "e3", "e4", "e5", "e6"]
    assert vals[5] == ["head"]
    assert vals[6] == 0
    # one fused launch batch served everything; full-state read happened
    # exactly once, for the truncated 6-element set
    assert calls["resolved"] == 1
    assert calls["states"] == 1

    # a txn WITH pending writes must keep the overlay (full-state) path
    calls["resolved"] = calls["states"] = 0
    txid = node.start_transaction()
    node.update_objects([("c", "counter_pn", "b", ("increment", 1))], txid)
    vals2 = node.read_objects([("c", "counter_pn", "b")], txid)
    node.commit_transaction(txid)
    assert vals2[0] == 8
    assert calls["resolved"] == 0 and calls["states"] >= 1


def test_resolved_view_ships_ovf_and_hatch_prevents_drops():
    """The resolved view carries the ovf counter (so TypedTable-direct
    deployments keep the slot-exhaustion warning on the serving path —
    see test_typed_table.py::test_set_slot_overflow_warns), while the
    KVStore-level escape hatch makes the node path drop-free: 3 adds into
    a 2-slot set promote the key instead of truncating."""
    import warnings

    from antidote_tpu.api.node import AntidoteNode

    cfg = _mk_cfg(set_slots=2)
    ty = get_type("set_aw")
    assert "ovf" in ty.resolve_spec(cfg)

    node = AntidoteNode(cfg)
    node.update_objects([
        ("k", "set_aw", "b", ("add_all", ["a", "b", "c"])),  # 3 > 2 slots
        ("k", "set_aw", "b", ("remove", "a")),
    ])
    with warnings.catch_warnings(record=True) as rec:
        warnings.simplefilter("always")
        vals, _ = node.read_objects([("k", "set_aw", "b")])
    assert sorted(vals[0]) == ["b", "c"]  # nothing dropped
    assert not any("dropped" in str(w.message) for w in rec)
    assert node.store.promotions >= 1


def test_read_resolved_flat_matches_routed():
    """The flat single-gather serving path (read_resolved_flat) and the
    routed [P, M'] path must agree exactly — fresh, historical, and
    absent keys, with and without the Pallas counter dispatch."""
    d = 3
    for tyname, use_pallas in (("set_aw", False), ("counter_pn", False),
                               ("counter_pn", True)):
        cfg = _mk_cfg(use_pallas=use_pallas)
        ty = get_type(tyname)
        table = TypedTable(ty, cfg)
        if tyname == "set_aw":
            _, mid = _populate_set(table, 10, d)
        else:
            clock = 0
            aw = table.ops_a.shape[-1]
            bw = table.ops_b.shape[-1]
            for r in range(10):
                for j in range(3):
                    clock += 1
                    vc = np.zeros(d, np.int32)
                    vc[0] = clock
                    ea = np.zeros((1, aw), np.int64)
                    ea[0, 0] = j + 1
                    table.append(
                        np.asarray([r % table.n_shards]), np.asarray([r]),
                        ea, np.zeros((1, bw), np.int32), vc[None, :],
                        np.asarray([0], np.int32),
                    )
            mid = clock // 2
        keys = np.asarray([0, 1, 2, 5, 9, 9, 3, 0], np.int64)
        ss, rr = keys % table.n_shards, keys
        for t in (mid, 10_000):
            vcs = np.zeros((len(keys), d), np.int32)
            vcs[:, 0] = t
            flat_res, flat_fresh, flat_comp = table.read_resolved_flat(
                ss, rr, vcs)
            routed_out, routed_fresh, routed_comp = table.read_resolved(
                ss, rr, vcs)
            for f, x in routed_out.items():
                np.testing.assert_array_equal(
                    np.asarray(flat_res[f]), x, err_msg=(tyname, f, t))
            np.testing.assert_array_equal(
                np.asarray(flat_fresh), routed_fresh, err_msg=(tyname, t))
            np.testing.assert_array_equal(
                np.asarray(flat_comp), routed_comp, err_msg=(tyname, t))
