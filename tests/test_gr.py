"""GentleRain protocol option — the gr_SUITE analogue
(/root/reference/test/singledc/gr_SUITE.erl, txn_prot=gr): snapshots are
scalar global-stable-time points; remote writes become visible only once
every lane's clock passed their timestamp."""


from antidote_tpu.api import AntidoteNode
from antidote_tpu.config import AntidoteConfig
from antidote_tpu.interdc import DCReplica, LoopbackHub
from antidote_tpu.meta import MetaDataStore


def cfg():
    return AntidoteConfig(
        n_shards=2, max_dcs=2, ops_per_key=8, snap_versions=2,
        set_slots=4, keys_per_table=16, batch_buckets=(8,),
    )


def gr_meta():
    m = MetaDataStore()
    m.set_env("txn_prot", "gr")
    return m


def test_gr_single_dc_roundtrip():
    """On one DC the GST degenerates to the local clock: reads see own
    commits immediately (single-dc gr_SUITE cases)."""
    node = AntidoteNode(AntidoteConfig(
        n_shards=2, max_dcs=1, ops_per_key=8, snap_versions=2,
        set_slots=4, keys_per_table=16, batch_buckets=(8,),
    ), meta=gr_meta())
    assert node.txm.protocol == "gr"
    node.update_objects([("k", "counter_pn", "b", ("increment", 2))])
    vals, _ = node.read_objects([("k", "counter_pn", "b")])
    assert vals[0] == 2


def test_gr_snapshot_lags_until_gst_advances():
    """Two DCs: after DC0 commits, DC1's GST is still 0 (its own lane has
    not advanced), so a gr read misses the write; once DC1 commits, GST
    covers DC0's write and it becomes visible."""
    hub = LoopbackHub()
    nodes = [AntidoteNode(cfg(), dc_id=i, meta=gr_meta()) for i in range(2)]
    reps = [DCReplica(n, hub, f"dc{i}") for i, n in enumerate(nodes)]
    DCReplica.connect_all(reps)
    nodes[0].update_objects([("k", "counter_pn", "b", ("increment", 5))])
    hub.pump()
    # the remote write is applied at DC1 (clocksi would see it)...
    assert nodes[1].store.dc_max_vc()[0] == 1
    # ...but the gr snapshot floor (GST) is min(1, 0) = 0
    vals, _ = nodes[1].read_objects([("k", "counter_pn", "b")])
    assert vals[0] == 0
    # DC1's own commit lifts its lane; GST now covers the remote write
    nodes[1].update_objects([("other", "counter_pn", "b", ("increment", 1))])
    vals, _ = nodes[1].read_objects([("k", "counter_pn", "b")])
    assert vals[0] == 5


def test_gr_snapshot_is_scalar():
    hub = LoopbackHub()
    nodes = [AntidoteNode(cfg(), dc_id=i, meta=gr_meta()) for i in range(2)]
    reps = [DCReplica(n, hub, f"dc{i}") for i, n in enumerate(nodes)]
    DCReplica.connect_all(reps)
    for _ in range(3):
        nodes[0].update_objects([("a", "counter_pn", "b", ("increment", 1))])
    hub.pump()
    nodes[1].update_objects([("b", "counter_pn", "b", ("increment", 1))])
    txn = nodes[1].start_transaction()
    # all remote lanes pinned to one scalar (own lane = commit counter)
    assert txn.snapshot_vc[0] == min(3, 1)
    assert txn.snapshot_vc[1] == 1
    nodes[1].abort_transaction(txn)


def test_clocksi_remains_default():
    node = AntidoteNode(cfg())
    assert node.txm.protocol == "clocksi"
