"""Coordinator-crash takeover + member rejoin (r3 VERDICT missing #1/#2).

The reference survives a coordinator dying mid-commit via supervised
FSMs and vnode takeover (/root/reference/src/antidote_sup.erl:57-158)
and its CT suite kills a node mid-stream and verifies safety
(/root/reference/test/multidc/multiple_dcs_node_failure_SUITE.erl:79-99).
Here: a sequencer-ledgered block/resolve protocol — any member resolves
a wedged ts chain by completing the commit (if ANY owner applied it) or
aborting it everywhere behind a block barrier; a member rejoining on its
log dir restores staged txns + prepared locks from the prepare log.

In-process tier; the 4-OS-process kill -9 cases live in
test_cluster_processes.py.
"""

import pytest

from antidote_tpu.cluster import ClusterMember, ClusterNode
from antidote_tpu.config import AntidoteConfig
from antidote_tpu.store.kv import key_to_shard
from antidote_tpu.txn.manager import AbortError


def _cfg(**kw):
    base = dict(n_shards=4, max_dcs=3, ops_per_key=8, keys_per_table=64,
                batch_buckets=(16, 64))
    base.update(kw)
    return AntidoteConfig(**base)


def _mk_duo(cfg, log0=None, log1=None, recover=False):
    m0 = ClusterMember(cfg, dc_id=0, member_id=0, n_members=2,
                       log_dir=log0, recover=recover)
    m1 = ClusterMember(cfg, dc_id=0, member_id=1, n_members=2,
                       log_dir=log1, recover=recover)
    m0.connect(1, *m1.address)
    m1.connect(0, *m0.address)
    return m0, m1


def _key_on_member(cfg, member, tag="k"):
    """A key routed to a shard owned by ``member``."""
    for i in range(10_000):
        k = f"{tag}{i}"
        if key_to_shard(k, "b", cfg.n_shards) in member.shards:
            return k
    raise AssertionError("no key found")


def _wedge(coord, m_dead_side, updates):
    """Simulate a coordinator crash after sequencing, before ANY commit
    fan-out: prepare everywhere + take a ts, then stop.

    The coordinator's cached sequencer frontier refreshes on a 0.2 s
    cadence; a snapshot taken inside that window after ANOTHER
    coordinator's commit would cert-conflict (by design — clients
    retry).  This helper wedges exactly one txn, so take the snapshot
    at a fresh frontier instead of retrying."""
    coord.member.invalidate_seq_cache()
    txn = coord.start_transaction()
    coord._update(updates, txn)
    by_owner = {}
    shards = set()
    from antidote_tpu.cluster.rpc import eff_to_wire

    for eff in txn.writeset:
        shard = key_to_shard(eff.key, eff.bucket, coord.cfg.n_shards)
        shards.add(shard)
        by_owner.setdefault(coord._owner_of_shard(shard), []).append(eff)
    snap_own = int(txn.snapshot_vc[coord.dc_id])
    for owner, effs in by_owner.items():
        wires = [eff_to_wire(e) for e in effs]
        if owner is None:
            coord.member.m_prepare(txn.txid, wires, snap_own)
        else:
            coord.member.peers[owner].call(
                "m_prepare", txn.txid, wires, snap_own)
    ts, prev = coord._seq(sorted(shards), txn.txid)
    return txn, ts, prev, by_owner


def test_takeover_aborts_wedged_txn():
    """Crash after seq, before fan-out: no owner committed, so takeover
    aborts it everywhere; a later commit buffered behind the hole
    drains, and the wedged txn's effects never surface."""
    cfg = _cfg()
    m0, m1 = _mk_duo(cfg)
    c1 = ClusterNode(m1)  # the "crashing" coordinator (non-sequencer)
    k0 = _key_on_member(cfg, m0, "a")
    k1 = _key_on_member(cfg, m1, "b")
    txn, ts, prev, _ = _wedge(c1, m1, [
        (k0, "counter_pn", "b", ("increment", 100)),
        (k1, "counter_pn", "b", ("increment", 100)),
    ])
    # a fresh coordinator on the surviving member: conflicting keys abort
    # (prepare locks held), disjoint commits chain-buffer behind the hole
    c0 = ClusterNode(m0)
    with pytest.raises(AbortError):
        c0.update_objects([(k0, "counter_pn", "b", ("increment", 1))])
    # takeover from the surviving member
    n = m0.resolve_wedged()
    assert n >= 1
    assert m1.resolve_wedged() >= 0  # m1's shards settle too
    # chains drained: both members' frontiers cover the issued ts
    assert m0.applied_ts[key_to_shard(k0, "b", cfg.n_shards)] >= ts
    assert m1.applied_ts[key_to_shard(k1, "b", cfg.n_shards)] >= ts
    # wedged effects are gone; new commits flow
    c0.update_objects([(k0, "counter_pn", "b", ("increment", 1))])
    vals = c0.read_objects([(k0, "counter_pn", "b"),
                            (k1, "counter_pn", "b")])[0]
    assert vals == [1, 0]
    # zombie coordinator's late commit is refused
    with pytest.raises(Exception):
        m0.m_commit(txn.txid, [ts, 0, 0], {int(s): int(p)
                                           for s, p in prev.items()})
    m0.close(), m1.close()


def test_takeover_completes_partial_commit():
    """Crash mid-fan-out: one owner applied the commit.  Takeover must
    COMPLETE it everywhere (atomicity), never abort."""
    cfg = _cfg()
    m0, m1 = _mk_duo(cfg)
    c1 = ClusterNode(m1)
    k0 = _key_on_member(cfg, m0, "a")
    k1 = _key_on_member(cfg, m1, "b")
    txn, ts, prev, by_owner = _wedge(c1, m1, [
        (k0, "counter_pn", "b", ("increment", 7)),
        (k1, "counter_pn", "b", ("increment", 7)),
    ])
    # fan-out reached m0 only, then the coordinator "died"
    vc = [0] * cfg.max_dcs
    vc[0] = ts
    m0.m_commit(txn.txid, vc, prev)
    # m1's shard chain is wedged; resolution learns m0 committed
    assert m1.resolve_wedged() >= 1
    c0 = ClusterNode(m0)
    vals = c0.read_objects([(k0, "counter_pn", "b"),
                            (k1, "counter_pn", "b")])[0]
    assert vals == [7, 7], "takeover must finish the fan-out atomically"
    m0.close(), m1.close()


def test_takeover_blocks_while_owner_unreachable():
    """2PC safety: an unreachable owner may have applied the commit, so
    takeover must WAIT, not abort behind its back."""
    cfg = _cfg()
    m0, m1 = _mk_duo(cfg)
    c1 = ClusterNode(m1)
    k0 = _key_on_member(cfg, m0, "a")
    k1 = _key_on_member(cfg, m1, "b")
    txn, ts, prev, _ = _wedge(c1, m1, [
        (k0, "counter_pn", "b", ("increment", 9)),
        (k1, "counter_pn", "b", ("increment", 9)),
    ])
    m1.rpc.close()  # m1 "dies" (owner of an involved shard)
    dec = m0.m_resolve_chain(key_to_shard(k0, "b", cfg.n_shards),
                             m0.applied_ts[key_to_shard(k0, "b",
                                                        cfg.n_shards)])
    assert dec[0] == "wait"
    assert m0.resolve_wedged() == 0  # nothing decided, nothing applied
    m0.close(), m1.close()


def test_rejoin_restores_prepare_log_and_resolves(tmp_path):
    """Member crash with a staged txn: rejoin on the same log dir
    restores the staged write-set + prepared lock from the prepare log,
    and a commit decision then applies it (effects were never lost)."""
    cfg = _cfg()
    log0 = str(tmp_path / "m0")
    log1 = str(tmp_path / "m1")
    m0, m1 = _mk_duo(cfg, log0, log1)
    c1 = ClusterNode(m1)
    k0 = _key_on_member(cfg, m0, "a")
    k1 = _key_on_member(cfg, m1, "b")
    # some committed history first
    c1.update_objects([(k1, "counter_pn", "b", ("increment", 5))])
    txn, ts, prev, _ = _wedge(c1, m1, [
        (k0, "counter_pn", "b", ("increment", 7)),
        (k1, "counter_pn", "b", ("increment", 7)),
    ])
    vc = [0] * cfg.max_dcs
    vc[0] = ts
    m0.m_commit(txn.txid, vc, prev)  # partial fan-out, then m1 "dies"
    m1.rpc.close()
    m1.node.store.log.close()
    m1._prep_wal.close()

    # rejoin: fresh process on the same log dir
    m1b = ClusterMember(cfg, dc_id=0, member_id=1, n_members=2,
                        log_dir=log1, recover=True)
    assert txn.txid in m1b.staged, "prepare log must restore staged txns"
    m0.connect(1, *m1b.address)
    m1b.connect(0, *m0.address)
    # recovered applied history survived
    assert int(m1b.node.store.applied_vc[
        key_to_shard(k1, "b", cfg.n_shards), 0]) >= 1
    # resolution completes the partial commit at the rejoined member
    assert m1b.resolve_wedged() >= 1
    c0 = ClusterNode(m0)
    vals = c0.read_objects([(k0, "counter_pn", "b"),
                            (k1, "counter_pn", "b")])[0]
    assert vals == [7, 12]
    m0.close(), m1b.close()


def test_rejoin_learns_abort_decision(tmp_path):
    """The inverse: the surviving members aborted the wedged txn while
    the owner was... reachable (decided pre-crash); the rejoined member
    must learn the sticky decision and drop its staged txn, not apply
    it."""
    cfg = _cfg()
    log1 = str(tmp_path / "m1")
    m0, m1 = _mk_duo(cfg, None, log1)
    c1 = ClusterNode(m1)
    k0 = _key_on_member(cfg, m0, "a")
    k1 = _key_on_member(cfg, m1, "b")
    txn, ts, prev, _ = _wedge(c1, m1, [
        (k0, "counter_pn", "b", ("increment", 3)),
        (k1, "counter_pn", "b", ("increment", 3)),
    ])
    # decided while everyone reachable: abort
    assert m1.resolve_wedged() >= 1
    m1.rpc.close()
    m1.node.store.log.close() if m1.node.store.log else None
    m1._prep_wal.close()
    # rejoin: staged txn must NOT come back (abort was logged)
    m1b = ClusterMember(cfg, dc_id=0, member_id=1, n_members=2,
                        log_dir=log1, recover=True)
    assert txn.txid not in m1b.staged
    assert txn.txid in m1b.aborted_txns
    m0.connect(1, *m1b.address)
    m1b.connect(0, *m0.address)
    c0 = ClusterNode(m0)
    vals = c0.read_objects([(k0, "counter_pn", "b"),
                            (k1, "counter_pn", "b")])[0]
    assert vals == [0, 0]
    m0.close(), m1b.close()


def test_stale_prepared_lock_swept():
    """Coordinator dies BEFORE sequencing: no chain hole exists, but the
    prepared locks must not be held forever — the sweep aborts the
    never-sequenced txn everywhere and the keys become writable."""
    cfg = _cfg()
    m0, m1 = _mk_duo(cfg)
    c1 = ClusterNode(m1)
    k0 = _key_on_member(cfg, m0, "a")
    txn = c1.start_transaction()
    c1._update([(k0, "counter_pn", "b", ("increment", 50))], txn)
    from antidote_tpu.cluster.rpc import eff_to_wire

    wires = [eff_to_wire(e) for e in txn.writeset]
    m1.peers[0].call("m_prepare", txn.txid, wires,
                     int(txn.snapshot_vc[0]))
    # coordinator "dies" here — never sequenced.  Conflicting writes abort
    c0 = ClusterNode(m0)
    with pytest.raises(AbortError):
        c0.update_objects([(k0, "counter_pn", "b", ("increment", 1))])
    # sweep (grace 0 for the test; operations would use ~30 s)
    assert m0.sweep_stale_prepared(grace_s=0.0) >= 1
    c0.update_objects([(k0, "counter_pn", "b", ("increment", 1))])
    vals = c0.read_objects([(k0, "counter_pn", "b")])[0]
    assert vals == [1], "lock released, stale increment aborted"
    # a sequenced txn is NOT swept (the chain protocol owns it)
    txn2, ts2, _, _ = _wedge(c1, m1, [
        (k0, "counter_pn", "b", ("increment", 9))])
    with pytest.raises(AbortError):
        c0.update_objects([(k0, "counter_pn", "b", ("increment", 1))])
    assert m0.sweep_stale_prepared(grace_s=0.0) == 0
    m0.resolve_wedged()  # chain takeover settles it instead
    c0.update_objects([(k0, "counter_pn", "b", ("increment", 1))])
    m0.close(), m1.close()


def test_rejoin_applies_commit_logged_but_not_applied(tmp_path):
    """Crash in the window between the durable commit record and the
    store apply: rejoin must re-apply the staged effects (they exist
    only in the prepare log), not drop them as 'already decided'."""
    cfg = _cfg()
    log1 = str(tmp_path / "m1")
    m0, m1 = _mk_duo(cfg, None, log1)
    c1 = ClusterNode(m1)
    k1 = _key_on_member(cfg, m1, "b")
    txn, ts, prev, _ = _wedge(c1, m1, [
        (k1, "counter_pn", "b", ("increment", 21))])
    # simulate the torn window: append the commit record durably, then
    # "crash" before any store apply
    vc = [0] * cfg.max_dcs
    vc[0] = ts
    m1._prep_append({"ev": "commit", "txid": int(txn.txid),
                     "vc": [int(x) for x in vc],
                     "prev": {int(k): int(v) for k, v in prev.items()}})
    m1.rpc.close()
    m1.node.store.log.close()
    m1._prep_wal.close()
    m1b = ClusterMember(cfg, dc_id=0, member_id=1, n_members=2,
                        log_dir=log1, recover=True)
    shard = key_to_shard(k1, "b", cfg.n_shards)
    assert m1b.applied_ts[shard] >= ts, "recovered commit must re-apply"
    assert txn.txid not in m1b.staged
    m0.connect(1, *m1b.address)
    m1b.connect(0, *m0.address)
    c0 = ClusterNode(m0)
    vals = c0.read_objects([(k1, "counter_pn", "b")])[0]
    assert vals == [21]
    m0.close(), m1b.close()


def test_prepare_log_compaction_preserves_state(tmp_path):
    """Compaction rewrites prepare.wal from live state (undecided preps
    + outcome/ledger tails): a rejoin from the compacted log restores
    exactly what a rejoin from the full history would."""
    cfg = _cfg()
    log1 = str(tmp_path / "m1")
    m0, m1 = _mk_duo(cfg, None, log1)
    c1 = ClusterNode(m1)
    k1 = _key_on_member(cfg, m1, "b")
    # decided history + one in-flight txn
    for i in range(5):
        c1.update_objects([(k1, "counter_pn", "b", ("increment", 1))])
    txn, ts, prev, _ = _wedge(c1, m1, [
        (k1, "counter_pn", "b", ("increment", 100))])
    size_before = __import__("os").path.getsize(f"{log1}/prepare.wal")
    m1._compact_prepare_log()
    size_after = __import__("os").path.getsize(f"{log1}/prepare.wal")
    assert size_after <= size_before
    m1.rpc.close()
    m1.node.store.log.close()
    m1._prep_wal.close()
    m1b = ClusterMember(cfg, dc_id=0, member_id=1, n_members=2,
                        log_dir=log1, recover=True)
    assert txn.txid in m1b.staged, "undecided prep survives compaction"
    shard = key_to_shard(k1, "b", cfg.n_shards)
    assert int(m1b.node.store.applied_vc[shard, 0]) >= 5
    m0.close(), m1b.close()


def test_type_conflict_aborts_at_prepare():
    """A key bound to one CRDT type updated as another must fail as a
    clean prepare abort — discovered only at commit-apply it would
    poison the ts chain (the commit decision is durable before the
    apply)."""
    cfg = _cfg()
    m0, m1 = _mk_duo(cfg)
    c1 = ClusterNode(m1)
    k0 = _key_on_member(cfg, m0, "a")
    c1.update_objects([(k0, "set_aw", "b", ("add", "x"))])
    with pytest.raises(AbortError):
        c1.update_objects([(k0, "counter_pn", "b", ("increment", 1))])
    # the store is untouched and the key still serves its real type
    vals = c1.read_objects([(k0, "set_aw", "b")])[0]
    assert vals == [["x"]]
    # and no lock is leaked
    c1.update_objects([(k0, "set_aw", "b", ("add", "y"))])
    m0.close(), m1.close()
