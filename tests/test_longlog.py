"""Long op-log materialization: associative reduction folds, chunked
scans, and sequence-parallel folds over the device mesh must all agree
with the reference serial fold (fold.fold_key semantics)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from antidote_tpu.config import AntidoteConfig
from antidote_tpu.crdt import get_type
from antidote_tpu.materializer import fold as fold_mod
from antidote_tpu.materializer import longlog


def small_cfg(**kw):
    kw.setdefault("max_dcs", 3)
    return AntidoteConfig(
        n_shards=1, ops_per_key=8, snap_versions=2, set_slots=8,
        keys_per_table=16, batch_buckets=(8,), **kw,
    )


def random_counter_ops(rng, l, d):
    ops_a = rng.integers(-5, 6, size=(l, 1)).astype(np.int64)
    ops_b = np.zeros((l, 1), np.int32)
    # random VCs: some ops inside base, some beyond read
    ops_vc = rng.integers(0, 10, size=(l, d)).astype(np.int32)
    origins = rng.integers(0, d, size=(l,)).astype(np.int32)
    return ops_a, ops_b, ops_vc, origins


def serial_reference(ty, cfg, state0, ops, n_ops, base_vc, read_vc):
    a, b, v, o = ops
    state, applied = fold_mod.fold_key(
        ty, cfg,
        jax.tree.map(jnp.asarray, state0),
        jnp.asarray(a), jnp.asarray(b), jnp.asarray(v), jnp.asarray(o),
        jnp.int32(n_ops), jnp.asarray(base_vc), jnp.asarray(read_vc),
    )
    return jax.tree.map(np.asarray, state), int(applied)


@pytest.mark.parametrize("tyname", ["counter_pn", "flag_ew", "flag_dw"])
def test_assoc_fold_matches_serial(tyname):
    cfg = small_cfg()
    ty = get_type(tyname)
    assert ty.supports_assoc
    rng = np.random.default_rng(1)
    d = cfg.max_dcs
    l = 32
    if tyname == "counter_pn":
        a, b, v, o = random_counter_ops(rng, l, d)
    else:
        a = np.zeros((l, 1), np.int64)
        b = np.zeros((l, ty.eff_b_width(cfg)), np.int32)
        b[:, 0] = rng.integers(0, 2, size=l)           # enable/disable
        b[:, 1:1 + d] = rng.integers(0, 10, size=(l, d))  # observed VCs
        v = rng.integers(0, 10, size=(l, d)).astype(np.int32)
        o = rng.integers(0, d, size=(l,)).astype(np.int32)
    state0 = {
        f: np.zeros(shape, np.dtype(dt.dtype if hasattr(dt, "dtype") else dt))
        for f, (shape, dt) in (
            (f, (s, jnp.zeros((), t).dtype))
            for f, (s, t) in ty.state_spec(cfg).items()
        )
    }
    base_vc = np.asarray([2, 0, 1], np.int32)
    read_vc = np.asarray([7, 7, 7], np.int32)
    n_ops = 29  # last 3 slots unwritten
    ref_state, ref_applied = serial_reference(
        ty, cfg, state0, (a, b, v, o), n_ops, base_vc, read_vc
    )
    got_state, got_applied = jax.jit(
        lambda s, aa, bb, vv, oo: longlog.assoc_fold(
            ty, cfg, s, aa, bb, vv, oo, jnp.int32(n_ops),
            jnp.asarray(base_vc), jnp.asarray(read_vc),
        )
    )(jax.tree.map(jnp.asarray, state0), a, b, v, o)
    assert int(got_applied) == ref_applied
    for f in ref_state:
        np.testing.assert_array_equal(np.asarray(got_state[f]), ref_state[f])


def test_fold_long_chunked_matches_serial():
    """Chunked scan over a 4096-op log (any type; here set_aw,
    order-dependent) equals the one-shot serial fold."""
    cfg = small_cfg()
    ty = get_type("set_aw")
    rng = np.random.default_rng(2)
    d = cfg.max_dcs
    l = 512
    # adds/removes over a small element universe with increasing clocks
    elems = rng.integers(1, 6, size=l).astype(np.int64)
    a = elems[:, None]
    b = np.zeros((l, ty.eff_b_width(cfg)), np.int32)
    b[:, 0] = rng.integers(0, 2, size=l)  # 1 = remove
    v = np.zeros((l, d), np.int32)
    v[:, 0] = np.arange(1, l + 1)
    b[b[:, 0] == 1, 1] = v[b[:, 0] == 1, 0] - 1  # removes observe prior dot
    o = np.zeros(l, np.int32)
    state0 = {
        f: np.zeros(shape, jnp.zeros((), t).dtype)
        for f, (shape, t) in ty.state_spec(cfg).items()
    }
    base_vc = np.zeros(d, np.int32)
    read_vc = np.full(d, l, dtype=np.int32)
    n_ops = l - 7
    ref_state, ref_applied = serial_reference(
        ty, cfg, state0, (a, b, v, o), n_ops, base_vc, read_vc
    )
    got_state, got_applied = jax.jit(
        lambda s, aa, bb, vv, oo: longlog.fold_long(
            ty, cfg, s, aa, bb, vv, oo, jnp.int32(n_ops),
            jnp.asarray(base_vc), jnp.asarray(read_vc), chunk=64,
        )
    )(jax.tree.map(jnp.asarray, state0), a, b, v, o)
    assert int(got_applied) == ref_applied
    for f in ref_state:
        np.testing.assert_array_equal(np.asarray(got_state[f]), ref_state[f])


def test_sharded_assoc_fold_on_mesh():
    """Sequence-parallel monoid fold over the 8-device CPU mesh equals the
    serial fold — the op axis is sharded, one all_gather merges deltas."""
    from antidote_tpu.parallel import make_mesh

    cfg = small_cfg()
    ty = get_type("counter_pn")
    mesh = make_mesh(8)
    rng = np.random.default_rng(3)
    d = cfg.max_dcs
    l = 64
    a, b, v, o = random_counter_ops(rng, l, d)
    base_vc = np.asarray([1, 1, 0], np.int32)
    read_vc = np.asarray([8, 8, 8], np.int32)
    n_ops = 61
    state0 = {"cnt": np.zeros((), np.int64)}
    ref_state, ref_applied = serial_reference(
        ty, cfg, state0, (a, b, v, o), n_ops, base_vc, read_vc
    )
    fn = longlog.sharded_assoc_fold_fn(ty, cfg, mesh)
    got_state, got_applied = fn(
        jax.tree.map(jnp.asarray, state0), a, b, v, o, n_ops,
        jnp.asarray(base_vc), jnp.asarray(read_vc),
    )
    assert int(got_applied) == ref_applied
    np.testing.assert_array_equal(np.asarray(got_state["cnt"]),
                                  ref_state["cnt"])
