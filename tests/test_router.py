"""Native key→shard router: XXH64 correctness against published test
vectors, bit-equality between the C++ and Python implementations, batch
routing, and the integer fast path."""

import numpy as np
import pytest

from antidote_tpu.store import router

pytestmark = pytest.mark.smoke


def test_xxh64_known_vectors_python():
    # published XXH64 reference vectors (seed 0)
    assert router.xxh64_py(b"") == 0xEF46DB3751D8E999
    assert router.xxh64_py(b"a") == 0xD24EC4F1A98C6E5B
    assert router.xxh64_py(b"abc") == 0x44BC2CF5AD770999


@pytest.mark.skipif(not router.native_available(), reason="no compiler")
def test_native_matches_python_bit_for_bit():
    rng = np.random.default_rng(11)
    for ln in list(range(0, 40)) + [63, 64, 65, 100, 1000]:
        data = rng.integers(0, 256, size=ln, dtype=np.uint8).tobytes()
        for seed in (0, 1, 0xDEADBEEF):
            native = router._load_lib().router_hash64(data, len(data), seed)
            assert int(native) == router.xxh64_py(data, seed), (ln, seed)


def test_batch_matches_scalar():
    keys = ["alpha", "beta", ("composite", 3), b"bytes", 17, 0, "x" * 200]
    buckets = ["b1", "b1", "b2", "b1", "b1", "b2", "b3"]
    batch = router.shard_batch(keys, buckets, 16)
    scalar = [router.shard_of(k, b, 16) for k, b in zip(keys, buckets)]
    assert batch.tolist() == scalar


def test_int_fast_path_matches_reference_semantics():
    # direct mod, like log_utilities:get_key_partition's integer case
    assert router.shard_of(42, "any", 16) == 42 % 16
    assert router.shard_of(7, "other", 4) == 3


def test_distribution_is_balanced():
    n_shards = 16
    shards = router.shard_batch(
        [f"key-{i}" for i in range(16000)], ["b"] * 16000, n_shards
    )
    counts = np.bincount(shards, minlength=n_shards)
    assert counts.min() > 16000 / n_shards * 0.8
    assert counts.max() < 16000 / n_shards * 1.2


def test_store_uses_router():
    from antidote_tpu.store.kv import key_to_shard

    assert key_to_shard("k", "b", 8) == router.shard_of("k", "b", 8)
    assert key_to_shard(13, "b", 8) == 13 % 8


def test_locate_many_matches_scalar_routing():
    from antidote_tpu.config import AntidoteConfig
    from antidote_tpu.store.kv import KVStore, key_to_shard

    cfg = AntidoteConfig(n_shards=4, max_dcs=2, ops_per_key=4,
                         snap_versions=2, keys_per_table=16)
    store = KVStore(cfg)
    objs = [(f"key-{i}", "counter_pn", "bk") for i in range(40)]
    objs += [(i, "counter_pn", "bk") for i in range(10)]  # direct-int path
    store.locate_many(objs)
    for key, tname, bucket in objs:
        ent = store.locate(key, tname, bucket, create=False)
        assert ent is not None
        assert ent[1] == key_to_shard(key, bucket, cfg.n_shards)
