"""Seeded chaos scenarios against the self-healing inter-DC fabric.

Every scenario follows the same shape: arm a deterministic FaultPlan
(antidote_tpu/faults), drive commits while the plan drops/duplicates/
corrupts/delays messages, severs links, or kills endpoints — then heal
and assert the invariant that matters: **all DCs converge to identical
materialized snapshots with zero lost effects**.  The reference earns
this with OTP supervision + riak_core handoff retry; we earn it with
subscription reconnect (jittered backoff + opid-gap catch-up), RPC
deadlines/retry budgets, two-phase shard moves, and the commit-lock
serialization of the two write planes.
"""

import threading
import time

import numpy as np
import pytest

from antidote_tpu import faults
from antidote_tpu.api import AntidoteNode
from antidote_tpu.config import AntidoteConfig
from antidote_tpu.interdc import DCReplica
from antidote_tpu.interdc.tcp import TcpFabric
from antidote_tpu.obs.metrics import net_metrics


@pytest.fixture
def cfg():
    # same shapes as test_tcp_interdc: the XLA compile cache is warm
    return AntidoteConfig(
        n_shards=2, max_dcs=3, ops_per_key=8, snap_versions=2,
        set_slots=4, keys_per_table=16, batch_buckets=(8,),
    )


@pytest.fixture(autouse=True)
def _disarm():
    """No fault plan leaks across tests."""
    yield
    faults.uninstall()


def mk_mesh(cfg, n=2, **fabric_kw):
    """n single-node DCs on per-DC TCP fabrics, fully meshed."""
    fabric_kw.setdefault("backoff_base", 0.05)
    fabric_kw.setdefault("backoff_max", 0.5)
    fabrics = [TcpFabric(**fabric_kw) for _ in range(n)]
    nodes = [AntidoteNode(cfg, dc_id=i) for i in range(n)]
    reps = [DCReplica(nd, f, f"dc{i}")
            for i, (nd, f) in enumerate(zip(nodes, fabrics))]
    TcpFabric.interconnect(fabrics)
    for a in reps:
        for b in reps:
            if a is not b:
                a.observe_dc(b)
    return fabrics, nodes, reps


def close_mesh(fabrics):
    for f in fabrics:
        f.close()


def pump_until_converged(fabrics, nodes, reps, deadline=30.0):
    """Heartbeat + pump every DC until every node's STABLE snapshot (min
    over shards — what reads gate on) dominates the joint max clock:
    every shard of every DC has applied every other DC's effects.
    Returns the joint clock, safe to read at everywhere."""
    end = time.monotonic() + deadline
    while True:
        for r in reps:
            r.heartbeat()  # chain heads reveal gaps -> catch-up
        for f in fabrics:
            f.pump(timeout=0.05)
        target = np.maximum.reduce([n.store.dc_max_vc() for n in nodes])
        stables = [n.store.stable_vc() for n in nodes]
        if all((vc >= target).all() for vc in stables):
            return target
        if time.monotonic() > end:
            raise AssertionError(
                f"DCs failed to converge within {deadline}s: "
                f"target {target.tolist()}, stable "
                f"{[vc.tolist() for vc in stables]}")


def assert_identical_snapshots(nodes, objs, clock):
    """The convergence invariant: every DC materializes byte-identical
    values for every object at the joint clock."""
    snaps = []
    for n in nodes:
        vals, _ = n.read_objects(objs, clock=clock)
        snaps.append(vals)
    for other in snaps[1:]:
        assert other == snaps[0], (snaps[0], other)
    return snaps[0]


# ---------------------------------------------------------------------------
# scenario 1: partition during replication, then heal
# ---------------------------------------------------------------------------
def test_partition_during_replication_heals(cfg):
    fabrics, nodes, reps = mk_mesh(cfg, 2)
    try:
        nodes[0].update_objects([("s", "set_aw", "b", ("add", "pre"))])
        pump_until_converged(fabrics, nodes, reps)
        inj = faults.install(faults.FaultPlan(seed=101))
        inj.sever(0, 1)  # cuts stream deliveries AND the catch-up RPC
        # both sides commit into the partition
        nodes[0].update_objects([("s", "set_aw", "b", ("add", "left")),
                                 ("c", "counter_pn", "b", ("increment", 3))])
        nodes[1].update_objects([("s", "set_aw", "b", ("add", "right")),
                                 ("c", "counter_pn", "b", ("increment", 4))])
        for f in fabrics:
            f.pump(timeout=0.2)
        # nothing crossed: each side still sees only its own writes
        va, _ = nodes[0].read_objects([("c", "counter_pn", "b")],
                                      clock=nodes[0].store.dc_max_vc())
        vb, _ = nodes[1].read_objects([("c", "counter_pn", "b")],
                                      clock=nodes[1].store.dc_max_vc())
        assert (va, vb) == ([3], [4])
        inj.heal_all()
        clock = pump_until_converged(fabrics, nodes, reps)
        vals = assert_identical_snapshots(
            nodes, [("s", "set_aw", "b"), ("c", "counter_pn", "b")], clock)
        assert sorted(vals[0]) == ["left", "pre", "right"]
        assert vals[1] == 7  # zero lost effects
    finally:
        close_mesh(fabrics)


# ---------------------------------------------------------------------------
# scenario 2: endpoint crash + restart — reconnect within the backoff bound
# ---------------------------------------------------------------------------
def test_endpoint_crash_restart_reconnects(cfg):
    inj = faults.install(faults.FaultPlan(seed=202))
    fabrics, nodes, reps = mk_mesh(cfg, 2)
    try:
        nodes[0].update_objects([("k", "counter_pn", "b", ("increment", 1))])
        pump_until_converged(fabrics, nodes, reps)
        assert "interdc.ep.0" in inj.endpoints()
        before = net_metrics().snapshot()
        inj.kill("interdc.ep.0")  # dc0's listener + dc1's stream die
        # commits made while the endpoint is down are recovered by
        # catch-up once the subscription heals
        nodes[0].update_objects([("k", "counter_pn", "b", ("increment", 2))])
        time.sleep(0.3)  # let the reconnect loop fail a few dials
        inj.restart("interdc.ep.0")
        t0 = time.monotonic()
        clock = pump_until_converged(fabrics, nodes, reps, deadline=20.0)
        heal_s = time.monotonic() - t0
        vals = assert_identical_snapshots(
            nodes, [("k", "counter_pn", "b")], clock)
        assert vals == [3]
        after = net_metrics().snapshot()
        # the reconnect is observable via the new counters, and resumes
        # well inside the backoff bound (max 0.5s/attempt here)
        assert (after["antidote_interdc_reconnects_total"]
                > before["antidote_interdc_reconnects_total"])
        assert heal_s < 15.0
    finally:
        close_mesh(fabrics)


# ---------------------------------------------------------------------------
# scenario 3: seeded drop/dup/delay storm on every link
# ---------------------------------------------------------------------------
def test_drop_dup_delay_storm_converges(cfg):
    plan = faults.FaultPlan(seed=303)
    plan.drop("interdc.deliver", p=0.25, times=40)
    plan.dup("interdc.deliver", p=0.15, times=20)
    plan.delay("interdc.deliver", p=0.15, times=20)
    inj = faults.install(plan)
    fabrics, nodes, reps = mk_mesh(cfg, 3)
    try:
        total = {k: 0 for k in range(4)}
        for round_ in range(6):
            for dc, n in enumerate(nodes):
                k = (round_ + dc) % 4
                n.update_objects(
                    [(k, "counter_pn", "b", ("increment", dc + 1))])
                total[k] += dc + 1
            for f in fabrics:
                f.pump(timeout=0.1)
        assert inj.fired("interdc.deliver") > 0  # the storm actually hit
        clock = pump_until_converged(fabrics, nodes, reps)
        objs = [(k, "counter_pn", "b") for k in range(4)]
        vals = assert_identical_snapshots(nodes, objs, clock)
        assert vals == [total[k] for k in range(4)]  # zero lost, zero dup
    finally:
        close_mesh(fabrics)


# ---------------------------------------------------------------------------
# scenario 4: mid-handoff crash — the two-phase move never strands data
# ---------------------------------------------------------------------------
def test_mid_handoff_crash_preserves_shard():
    from antidote_tpu.cluster.coordinator import ClusterNode
    from antidote_tpu.cluster.join import live_join
    from antidote_tpu.cluster.member import ClusterMember

    # 4 shards so joining a 3rd member actually moves some (2 % 3 -> m2);
    # shapes match the global conftest cfg -> warm compile cache
    ccfg = AntidoteConfig(
        n_shards=4, max_dcs=3, ops_per_key=8, snap_versions=2,
        set_slots=8, mv_slots=4, rga_slots=16, keys_per_table=64,
        batch_buckets=(16, 64),
    )
    ms = [ClusterMember(ccfg, dc_id=0, member_id=i, n_members=2)
          for i in range(2)]
    try:
        for i, m in enumerate(ms):
            for j, o in enumerate(ms):
                if i != j:
                    m.connect(j, *o.address)
        node = ClusterNode(ms[0])
        for k in range(6):
            node.update_objects([(k, "counter_pn", "b", ("increment", k + 1))])
        joiner = ClusterMember(ccfg, dc_id=0, member_id=2, n_members=3,
                               shards=[])
        ms.append(joiner)
        for i, m in enumerate(ms):
            for j, o in enumerate(ms):
                if i != j and j not in m.peers:
                    m.connect(j, *o.address)
        rpcs = {m.member_id: tuple(m.address) for m in ms}
        # every import RPC dies: the driver must cancel the export and
        # surface the failure WITHOUT dropping the source copy
        faults.install(faults.FaultPlan(seed=404).drop(
            "rpc.call", key="m_import_shard"))
        with pytest.raises(RuntimeError, match="import .* kept failing"):
            live_join(rpcs, new_id=2)
        assert joiner.shards == set()  # nothing landed
        for m in ms[:2]:
            assert not m.moving  # exports were cancelled
        # the data is alive and WRITABLE at the source after the abort
        node.update_objects([(0, "counter_pn", "b", ("increment", 10))])
        # heal, re-run the driver: the move completes from fresh exports
        faults.uninstall()
        moved = live_join(rpcs, new_id=2)
        assert moved > 0
        vals, _ = ClusterNode(joiner).read_objects(
            [(k, "counter_pn", "b") for k in range(6)])
        assert vals == [11, 2, 3, 4, 5, 6]
    finally:
        faults.uninstall()
        for m in ms:
            try:
                m.close()
            except Exception:
                pass


# ---------------------------------------------------------------------------
# scenario 5: native-pump load failure — Python reader fallback still heals
# ---------------------------------------------------------------------------
def test_pump_fallback_replicates_and_reconnects(cfg):
    # the injected load failure forces NativePump.create() -> None, so
    # subscribe() must fall back to per-subscription Python readers
    # instead of blackholing detached fds
    inj = faults.install(faults.FaultPlan(seed=505).error(
        "native_pump.load"))
    fabrics, nodes, reps = mk_mesh(cfg, 2)
    try:
        assert all(f._np is None for f in fabrics)
        nodes[0].update_objects([("k", "counter_pn", "b", ("increment", 5))])
        clock = pump_until_converged(fabrics, nodes, reps)
        assert assert_identical_snapshots(
            nodes, [("k", "counter_pn", "b")], clock) == [5]
        # the fallback plane heals severed streams too (reader-loop
        # reconnect, not just the native sentinel path)
        inj.kill("interdc.ep.0")
        nodes[0].update_objects([("k", "counter_pn", "b", ("increment", 1))])
        time.sleep(0.2)
        inj.restart("interdc.ep.0")
        clock = pump_until_converged(fabrics, nodes, reps, deadline=20.0)
        assert assert_identical_snapshots(
            nodes, [("k", "counter_pn", "b")], clock) == [6]
    finally:
        close_mesh(fabrics)


def test_native_pump_null_handle_returns_none(monkeypatch):
    """NULL from pump_new() (fd exhaustion/seccomp) must yield None —
    the TcpFabric fallback contract — never a pump that closes every fd
    handed to it."""
    from antidote_tpu.interdc import native_pump as npm

    lib = npm._load_lib()
    if lib is None:
        pytest.skip("native pump unavailable in this image")

    class NullLib:
        def pump_new(self):
            return None  # what ctypes maps a NULL return to

    monkeypatch.setattr(npm, "_load_lib", lambda: NullLib())
    assert npm.NativePump.create() is None


# ---------------------------------------------------------------------------
# scenario 6: both write planes at once (remote ingress vs local commits)
# ---------------------------------------------------------------------------
def test_concurrent_local_and_remote_commits_lose_nothing(cfg):
    """Regression for the r5 advisor high: remote-ingress applies now
    hold node.txm.commit_lock, so a pump draining remote effects cannot
    interleave with a local commit's table reassignment and silently
    drop a batch.  Hammer both planes concurrently and count."""
    fabrics, nodes, reps = mk_mesh(cfg, 2)
    N = 24
    try:
        errs = []
        stop = threading.Event()

        def writer(node, amount):
            try:
                for _ in range(N):
                    node.update_objects(
                        [("hot", "counter_pn", "b", ("increment", amount))])
            except Exception as e:  # pragma: no cover - failure detail
                errs.append(e)

        def pumper():
            # remote ingress drains concurrently with the local writers
            while not stop.is_set():
                for f in fabrics:
                    f.pump(timeout=0.05)

        threads = [threading.Thread(target=writer, args=(nodes[0], 1)),
                   threading.Thread(target=writer, args=(nodes[1], 2)),
                   threading.Thread(target=pumper)]
        for t in threads:
            t.start()
        for t in threads[:2]:
            t.join(timeout=120)
        stop.set()
        threads[2].join(timeout=10)
        assert not errs, errs
        clock = pump_until_converged(fabrics, nodes, reps)
        vals = assert_identical_snapshots(
            nodes, [("hot", "counter_pn", "b")], clock)
        assert vals == [N * 1 + N * 2]  # every effect applied exactly once
    finally:
        close_mesh(fabrics)


# ---------------------------------------------------------------------------
# scenario 7: RPC deadlines + retry budget
# ---------------------------------------------------------------------------
def test_rpc_deadline_and_retry_budget():
    from antidote_tpu.cluster.rpc import (RpcClient, RpcServer, RpcTimeout)

    srv = RpcServer()
    srv.register("echo", lambda x: x)
    srv.register("stall", lambda: time.sleep(5))
    cli = RpcClient(srv.host, srv.port, timeout=0.4, retries=3)
    try:
        assert cli.call("echo", 42) == 42
        before = net_metrics().snapshot()
        # a wedged handler hits the DEADLINE, not a forever-hang; no
        # blind resend (the remote may have executed)
        t0 = time.monotonic()
        with pytest.raises(RpcTimeout):
            cli.call("stall")
        assert time.monotonic() - t0 < 3.0
        # a server restart mid-session: the first call on the severed
        # cached conn either redials transparently (send-phase failure)
        # or surfaces RpcTimeout WITHOUT a blind resend (reply-phase
        # failure: the remote may have executed) — at-most-once, the
        # CALLER retries idempotent methods
        assert cli.call("echo", 5) == 5  # re-establish the cached conn
        srv.close()
        srv.restart()
        try:
            assert cli.call("echo", 7) == 7
        except RpcTimeout:
            assert cli.call("echo", 7) == 7  # caller-level retry
        # a dead server exhausts the bounded redial budget instead of
        # hanging forever, and the retries are observable (drop the
        # cached conn first so every attempt fails at CONNECT — a
        # send-phase failure, deterministically retryable)
        srv.close()
        cli.close()
        with pytest.raises(RpcTimeout, match="after 3 attempt"):
            cli.call("echo", 1)
        after = net_metrics().snapshot()
        assert (after["antidote_rpc_retries_total"]
                > before["antidote_rpc_retries_total"])
        assert (after["antidote_rpc_deadline_exceeded_total"]
                > before["antidote_rpc_deadline_exceeded_total"])
    finally:
        faults.uninstall()
        cli.close()
        try:
            srv.close()
        except Exception:
            pass


# ---------------------------------------------------------------------------
# scenario 8: corrupted frames are discarded, counted, and healed
# ---------------------------------------------------------------------------
def test_truncated_frames_recovered_by_catchup(cfg):
    plan = faults.FaultPlan(seed=808)
    plan.truncate("interdc.deliver", key=(0, 1), times=2, keep=6)
    faults.install(plan)
    fabrics, nodes, reps = mk_mesh(cfg, 2)
    try:
        before = net_metrics().snapshot()
        nodes[0].update_objects([("k", "counter_pn", "b", ("increment", 9))])
        clock = pump_until_converged(fabrics, nodes, reps)
        assert assert_identical_snapshots(
            nodes, [("k", "counter_pn", "b")], clock) == [9]
        after = net_metrics().snapshot()
        assert (after["antidote_interdc_corrupt_frames_total"]
                > before["antidote_interdc_corrupt_frames_total"])
    finally:
        close_mesh(fabrics)


# ---------------------------------------------------------------------------
# scenario 9: WAL append faults surface loudly and clear cleanly
# ---------------------------------------------------------------------------
def test_wal_append_fault_surfaces_and_heals(tmp_path):
    from antidote_tpu.log.wal import ShardWAL, replay

    path = str(tmp_path / "shard_0.wal")
    wal = ShardWAL(path)
    wal.append({"id": 1, "v": "pre"})
    wal.commit()
    faults.install(faults.FaultPlan(seed=909).error("wal.append", times=1))
    with pytest.raises(IOError, match="injected fault"):
        wal.append({"id": 2, "v": "lost"})
    # the failed append wrote NOTHING (fault fires before any bytes)
    wal.append({"id": 3, "v": "post"})
    wal.commit()
    wal.close()
    recs = list(replay(path))
    assert [r["id"] for r in recs] == [1, 3]


# ---------------------------------------------------------------------------
# scenario 10: a crashing drain loop restarts under supervision
# ---------------------------------------------------------------------------
def test_supervised_pump_restarts_after_crash(cfg):
    from antidote_tpu.supervise import Supervisor, ThreadLoop

    # one poisoned delivery: the pump's callback raises, the ThreadLoop
    # dies loudly, the supervisor restarts it, replication continues
    faults.install(faults.FaultPlan(seed=1010).error(
        "interdc.deliver", key=(0, 1), times=1))
    fabrics, nodes, reps = mk_mesh(cfg, 2)
    sup = Supervisor(poll_s=0.05)
    try:
        loops = []

        def start_pump():
            lp = ThreadLoop(lambda: fabrics[1].pump(timeout=0.1),
                            interval_s=0.01, name="chaos-pump")
            loops.append(lp)
            return lp.start()

        sup.add("pump", start_pump, alive=lambda lp: lp.is_alive(),
                stop=lambda lp: lp.stop())
        sup.start()
        nodes[0].update_objects([("k", "counter_pn", "b", ("increment", 4))])
        deadline = time.monotonic() + 20.0
        while len(loops) < 2 and time.monotonic() < deadline:
            time.sleep(0.05)  # first loop crashed on the poisoned frame
        assert len(loops) >= 2, "supervisor never restarted the pump"
        assert loops[0].crashed is not None
        # the poisoned txn was lost in delivery; the restarted pump's
        # catch-up (triggered by the next heartbeat ping) replays it
        deadline = time.monotonic() + 20.0
        while time.monotonic() < deadline:
            reps[0].heartbeat()
            fabrics[0].pump(timeout=0.1)
            # stable (min over shards), not max: reads gate on it
            if (nodes[1].store.stable_vc()
                    >= nodes[0].store.dc_max_vc()).all():
                break
            time.sleep(0.05)
        vals, _ = nodes[1].read_objects([("k", "counter_pn", "b")],
                                        clock=nodes[0].store.dc_max_vc())
        assert vals == [4]
    finally:
        sup.shutdown()
        close_mesh(fabrics)


# ---------------------------------------------------------------------------
# scenario 11: mid-id live leave of a geo-replicated cluster under a storm
# ---------------------------------------------------------------------------
def test_live_leave_mid_member_under_cross_dc_storm():
    """The membership-survival invariant (r5 VERDICT items 2/3): DC0 is
    a 3-member cluster, DC1 a single node, both taking writes, with a
    seeded drop/delay storm on every inter-DC link and a brief
    partition severing the leaver mid-epoch-gossip.  Member 1 — a
    MIDDLE id — live-leaves under that load and is then closed (the
    publisher dies).  Ownership-epoch gossip re-routes DC1's catch-up
    to the new owners, the handoff carries each chain's state, and
    both DCs still converge to identical snapshots with zero lost or
    duplicated ops."""
    from antidote_tpu.cluster import (ClusterNode, attach_interdc,
                                      cluster_query_router)
    from antidote_tpu.cluster.join import live_leave
    from antidote_tpu.cluster.member import ClusterMember

    ccfg = AntidoteConfig(
        n_shards=4, max_dcs=3, ops_per_key=8, snap_versions=2,
        set_slots=8, mv_slots=4, rga_slots=16, keys_per_table=64,
        batch_buckets=(16, 64),
    )
    plan = faults.FaultPlan(seed=1111)
    plan.drop("interdc.deliver", p=0.2, times=40)
    plan.delay("interdc.deliver", p=0.2, times=40)
    inj = faults.install(plan)
    fab0 = TcpFabric(backoff_base=0.05, backoff_max=0.5)
    fab1 = TcpFabric(backoff_base=0.05, backoff_max=0.5)
    ms = [ClusterMember(ccfg, dc_id=0, member_id=i, n_members=3)
          for i in range(3)]
    for a in ms:
        for b in ms:
            if a is not b:
                a.connect(b.member_id, *b.address)
    reps0 = [attach_interdc(m, fab0) for m in ms]
    node1 = AntidoteNode(ccfg, dc_id=1)
    rep1 = DCReplica(node1, fab1)
    rep1.route_query = cluster_query_router({0: 3}, ccfg.n_shards)
    TcpFabric.interconnect([fab0, fab1])
    for r in reps0:
        fab0.subscribe(r.fabric_id, rep1.fabric_id, r._on_message)
        fab1.subscribe(rep1.fabric_id, r.fabric_id, rep1._on_message)
    try:
        n_keys = 8
        acked0 = [0] * n_keys   # DC0-coordinated increments (amount 1)
        acked1 = [0] * n_keys   # DC1 increments (amount 2)
        lock = threading.Lock()
        stop = threading.Event()
        errs = []
        coord = ClusterNode(ms[0])

        def w_dc0():
            rng = np.random.default_rng(11)
            while not stop.is_set():
                k = int(rng.integers(n_keys))
                try:
                    coord.update_objects(
                        [(k, "counter_pn", "b", ("increment", 1))])
                except Exception as e:
                    if "abort" in str(e).lower():
                        continue
                    errs.append(repr(e))
                    return
                with lock:
                    acked0[k] += 1

        def w_dc1():
            rng = np.random.default_rng(12)
            while not stop.is_set():
                k = int(rng.integers(n_keys))
                try:
                    node1.update_objects(
                        [(k, "counter_pn", "b", ("increment", 2))])
                except Exception as e:
                    if "abort" in str(e).lower():
                        continue
                    errs.append(repr(e))
                    return
                with lock:
                    acked1[k] += 2

        def pumper():
            while not stop.is_set():
                fab0.pump(timeout=0.05)
                fab1.pump(timeout=0.05)

        threads = [threading.Thread(target=w_dc0),
                   threading.Thread(target=w_dc1),
                   threading.Thread(target=pumper)]
        for t in threads:
            t.start()
        time.sleep(0.6)

        # sever the leaver's stream to DC1 mid-gossip, then drain member
        # 1 (a MIDDLE id) out while both DCs keep writing
        inj.sever(reps0[1].fabric_id, rep1.fabric_id)
        rpcs = {m.member_id: tuple(m.address) for m in ms}
        moved = live_leave(rpcs, leaving_id=1)
        assert moved == len([s for s in range(ccfg.n_shards)
                             if s % 3 == 1])
        inj.heal_all()
        time.sleep(0.6)
        stop.set()
        for t in threads:
            t.join(timeout=120)
        assert not errs, errs
        assert ms[1].shards == set()
        ms[1].close()  # the departed publisher dies for good

        # stop injecting so the mesh drains, then converge BOTH DCs
        faults.uninstall()
        total = [acked0[k] + acked1[k] for k in range(n_keys)]
        objs = [(k, "counter_pn", "b") for k in range(n_keys)]
        deadline = time.monotonic() + 60.0
        while True:
            for r in reps0 + [rep1]:
                if r is not reps0[1]:
                    r.heartbeat()
            fab0.pump(timeout=0.05)
            fab1.pump(timeout=0.05)
            for m in (ms[0], ms[2]):
                m.refresh_peer_clocks()
            v1, _ = node1.read_objects(objs, clock=None)
            v0, _ = coord.read_objects(objs)
            if v0 == total and v1 == total:
                break
            if time.monotonic() > deadline:
                raise AssertionError(
                    f"divergence after leave: dc0={v0} dc1={v1} "
                    f"expected={total}")
        # DC1 learned the drained shard's new owner via epoch gossip
        drained = [s for s in range(ccfg.n_shards) if s % 3 == 1]
        for s in drained:
            owner, epoch = rep1.shard_route[(0, s)]
            assert owner != 1 and epoch >= 1
            assert s in ms[owner].shards
    finally:
        faults.uninstall()
        for m in ms:
            try:
                m.close()
            except Exception:
                pass
        fab0.close()
        fab1.close()


# ---------------------------------------------------------------------------
# scenario 12: saturation storm + ENOSPC — bounded, typed, degraded, healed
# ---------------------------------------------------------------------------
def test_saturation_storm_enospc_bounded_and_converges(cfg, tmp_path):
    """The PR 4 acceptance scenario: a wire-level write storm against a
    deliberately small admission budget, with an injected full-disk
    mid-storm.  Asserts the whole overload story at once: process RSS
    stays bounded, every shed request got a TYPED busy/deadline/
    read-only reply (never a silent drop or an untyped error), the node
    enters and exits read-only degraded mode cleanly, and after the
    pressure lifts both DCs converge to byte-identical snapshots
    containing exactly the acked writes."""
    import resource

    from antidote_tpu.proto.client import (AntidoteClient, RemoteBusy,
                                           RemoteDeadline, RemoteReadOnly)
    from antidote_tpu.proto.server import ProtocolServer

    fabrics = [TcpFabric(backoff_base=0.05, backoff_max=0.5)
               for _ in range(2)]
    # node0 carries the WAL (the ENOSPC target) and the wire server
    nodes = [AntidoteNode(cfg, dc_id=0, log_dir=str(tmp_path / "dc0")),
             AntidoteNode(cfg, dc_id=1)]
    reps = [DCReplica(nd, f, f"dc{i}")
            for i, (nd, f) in enumerate(zip(nodes, fabrics))]
    TcpFabric.interconnect(fabrics)
    for a in reps:
        for b in reps:
            if a is not b:
                a.observe_dc(b)
    srv = ProtocolServer(nodes[0], port=0, max_in_flight=4,
                         max_in_flight_per_client=2, queue_max=8)
    n_keys = 4
    acked0 = [0] * n_keys       # wire-acked increments on node0
    acked1 = [0] * n_keys       # direct increments on node1 (amount 2)
    shed = {"busy": 0, "deadline": 0, "read_only": 0}
    untyped = []
    lock = threading.Lock()
    stop = threading.Event()
    try:
        nodes[0].update_objects([(0, "counter_pn", "b", ("increment", 1))])
        acked0[0] += 1
        pump_until_converged(fabrics, nodes, reps)
        rss0 = resource.getrusage(resource.RUSAGE_SELF).ru_maxrss

        def wire_writer(i):
            c = AntidoteClient(port=srv.port)
            dl = 40.0 if i % 2 else None  # half the storm carries deadlines
            try:
                while not stop.is_set():
                    k = i % n_keys
                    try:
                        c.update_objects(
                            [(k, "counter_pn", "b", ("increment", 1))],
                            deadline_ms=dl)
                    except RemoteBusy as e:
                        with lock:
                            shed["busy"] += 1
                        time.sleep(min(e.retry_after_ms, 50) / 1e3)
                        continue
                    except RemoteDeadline:
                        with lock:
                            shed["deadline"] += 1
                        continue
                    except RemoteReadOnly:
                        with lock:
                            shed["read_only"] += 1
                        time.sleep(0.02)
                        continue
                    with lock:
                        acked0[k] += 1
            except Exception as e:  # anything untyped fails the scenario
                untyped.append(repr(e))
            finally:
                c.close()

        def dc1_writer():
            try:
                while not stop.is_set():
                    k = int(time.monotonic() * 1e6) % n_keys
                    nodes[1].update_objects(
                        [(k, "counter_pn", "b", ("increment", 2))])
                    with lock:
                        acked1[k] += 2
                    time.sleep(0.002)
            except Exception as e:
                untyped.append(repr(e))

        def pumper():
            while not stop.is_set():
                for f in fabrics:
                    try:
                        f.pump(timeout=0.05)
                    except OSError as e:
                        # the injected ENOSPC can also hit node0's
                        # ingress-apply WAL append; the gated messages
                        # stay queued and the drain retries next pump
                        with lock:
                            shed.setdefault("ingress_oserror", 0)
                            shed["ingress_oserror"] += 1
                        time.sleep(0.01)

        threads = [threading.Thread(target=wire_writer, args=(i,))
                   for i in range(6)]
        threads += [threading.Thread(target=dc1_writer),
                    threading.Thread(target=pumper)]
        for t in threads:
            t.start()
        time.sleep(0.7)  # saturation phase: admission sheds under load
        # mid-storm full disk: the node must flip read-only, not wedge
        faults.install(
            faults.FaultPlan(seed=1212).enospc("wal.append", times=4))
        deadline = time.monotonic() + 15.0
        while nodes[0].txm.read_only_reason is None:
            assert time.monotonic() < deadline, "node never entered RO"
            time.sleep(0.01)
        assert nodes[0].metrics.degraded_read_only.value() == 1
        # reads keep serving over the wire while degraded (the reader
        # shares the storm's per-client budget, so honor busy hints)
        ro_reader = AntidoteClient(port=srv.port)
        deadline = time.monotonic() + 15.0
        while True:
            try:
                vals, _ = ro_reader.read_objects([(0, "counter_pn", "b")])
                break
            except RemoteBusy as e:
                assert time.monotonic() < deadline, "read starved out"
                time.sleep(e.retry_after_ms / 1e3)
        assert vals[0] >= 1
        ro_reader.close()
        # the volume "heals" (rule exhausts via recovery probes): the
        # mode exits automatically under the ongoing write pressure
        deadline = time.monotonic() + 20.0
        while nodes[0].txm.read_only_reason is not None:
            assert time.monotonic() < deadline, "node never exited RO"
            nodes[0].txm._ro_probe_at = 0.0  # don't wait out the pacing
            time.sleep(0.02)
        time.sleep(0.4)  # post-recovery writes flow again
        stop.set()
        for t in threads:
            t.join(timeout=120)
        faults.uninstall()
        assert not untyped, untyped
        assert shed["busy"] > 0, "storm never hit the admission cap"
        assert shed["read_only"] > 0, "no write was shed while degraded"
        assert nodes[0].metrics.degraded_read_only.value() == 0
        assert nodes[0].status()["overload"]["read_only"] is None
        # bounded memory: a storm against capped queues must not balloon
        # the process (the pre-PR4 failure mode was unbounded buffering)
        rss1 = resource.getrusage(resource.RUSAGE_SELF).ru_maxrss
        assert rss1 - rss0 < 400_000, f"RSS grew {rss1 - rss0} KB"
        # pressure gone: both DCs converge byte-identical on the acked set
        clock = pump_until_converged(fabrics, nodes, reps, deadline=60.0)
        objs = [(k, "counter_pn", "b") for k in range(n_keys)]
        vals = assert_identical_snapshots(nodes, objs, clock)
        assert vals == [acked0[k] + acked1[k] for k in range(n_keys)]
    finally:
        stop.set()
        faults.uninstall()
        srv.close()
        close_mesh(fabrics)


def test_sigkill_mid_group_fsync_replays_exactly_acked(tmp_path):
    """Chaos scenario 13 (ISSUE 6): SIGKILL the serving process while
    merged commit groups from 3 connections are in flight through the
    group-fsync plane (--sync-log --wal-segments 3).  The durability
    contract under sync_log=true: an ACK implies the record survives
    the kill.  Recovery must replay every acked commit, must not
    resurrect more than was attempted (NACKed/rolled-back sub-groups
    stay gone — the WAL truncates them; unacked in-flight appends MAY
    survive, SIGKILL spares the page cache), and two independent
    recoveries converge byte-identical."""
    import json
    import os
    import signal
    import subprocess
    import sys

    env = dict(os.environ, JAX_PLATFORMS="cpu")
    log_dir = str(tmp_path / "wal")
    proc = subprocess.Popen(
        [sys.executable, "-m", "antidote_tpu.console", "serve",
         "--port", "0", "--shards", "2", "--max-dcs", "2",
         "--log-dir", log_dir, "--sync-log", "--wal-segments", "3"],
        stdout=subprocess.PIPE, stderr=subprocess.DEVNULL, env=env,
        text=True,
    )
    acked = [0, 0, 0]
    attempted = [0, 0, 0]
    errs = []
    try:
        info = json.loads(proc.stdout.readline())
        assert info["ready"] is True
        from antidote_tpu.proto.client import AntidoteClient

        stop = threading.Event()

        def writer(i):
            # each connection hammers its own key so the merged batches
            # at the locked worker always carry 3-way sub-groups
            try:
                c = AntidoteClient(info["host"], info["port"])
                while not stop.is_set():
                    attempted[i] += 1
                    c.update_objects(
                        [(f"k{i}", "counter_pn", "b", ("increment", 1))])
                    acked[i] += 1
            except (ConnectionError, OSError):
                pass  # the kill severed the socket mid-request
            except Exception as e:  # pragma: no cover - failure detail
                errs.append(repr(e))

        threads = [threading.Thread(target=writer, args=(i,))
                   for i in range(3)]
        for t in threads:
            t.start()
        deadline = time.monotonic() + 20.0
        while sum(acked) < 30:  # ensure real merged traffic is flowing
            assert time.monotonic() < deadline, f"no throughput: {acked}"
            time.sleep(0.02)
        time.sleep(0.3)
        proc.send_signal(signal.SIGKILL)  # mid group-fsync, no goodbyes
        proc.wait(timeout=10)
        stop.set()
        for t in threads:
            t.join(timeout=30)
        assert not errs, errs
        assert all(a > 0 for a in acked), acked
    finally:
        if proc.poll() is None:
            proc.kill()
            proc.wait(timeout=10)
    # recover twice, independently — byte-identical convergence
    rcfg = AntidoteConfig(n_shards=2, max_dcs=2, wal_segments=3)
    objs = [(f"k{i}", "counter_pn", "b") for i in range(3)]
    recovered = []
    for _ in range(2):
        node = AntidoteNode(rcfg, log_dir=log_dir, recover=True)
        vals, _ = node.read_objects(objs)
        recovered.append({
            "vals": vals,
            "op_ids": node.store.log.op_ids.tolist(),
            "seqs": node.store.log.seqs.tolist(),
            "stable": [int(x) for x in node.stable_vc()],
        })
        node.store.log.close()
    assert recovered[0] == recovered[1], "recoveries diverged"
    vals = recovered[0]["vals"]
    for i in range(3):
        # every ACK survived the SIGKILL; nothing beyond what was sent
        assert acked[i] <= vals[i] <= attempted[i], (
            f"k{i}: acked={acked[i]} recovered={vals[i]} "
            f"attempted={attempted[i]}")


def test_sigkill_mid_checkpoint_and_mid_truncation_recover_exact(tmp_path):
    """Chaos scenario 14 (ISSUE 8): under live wire load with --sync-log,
    SIGKILL the serving process while the background checkpointer is (a)
    mid-image-stream and (b) mid-WAL-truncation.  The checkpoint plane's
    crash contract: acked writes survive the kill, two independent
    recoveries (checkpoint image + tail replay) are byte-identical —
    including op-id chains, append sequences and the egress positions a
    restarted replica derives — and a geo peer subscribed through the
    whole episode sees neither duplicates nor gaps once the server
    restarts from its checkpoint.

    The kill window is widened deterministically with env-armed fault
    delays (``ANTIDOTE_FAULT_PLAN``) on ``ckpt.write`` (holds the image
    writer mid-stream) and ``wal.truncate_below`` (holds the reclaim
    pass mid-deletion); an aggressive ``--checkpoint-interval-s`` keeps
    the checkpointer inside those windows for most of the load phase,
    so the SIGKILL lands inside one regardless of scheduling."""
    import json
    import os
    import signal
    import subprocess
    import sys

    from antidote_tpu.proto.client import AntidoteClient

    rounds = [
        ("mid-checkpoint", {"site": "ckpt.write", "action": "delay",
                            "arg": 0.15}),
        ("mid-truncation", {"site": "wal.truncate_below",
                            "action": "delay", "arg": 0.15}),
    ]
    rcfg = AntidoteConfig(n_shards=2, max_dcs=2, wal_segments=3)
    for label, rule in rounds:
        log_dir = str(tmp_path / f"wal-{label}")
        env = dict(
            os.environ, JAX_PLATFORMS="cpu",
            ANTIDOTE_FAULT_PLAN=json.dumps(
                {"seed": 14, "rules": [rule]}),
        )
        geo = label == "mid-checkpoint"  # geo continuity checked once

        def spawn():
            args = [
                sys.executable, "-m", "antidote_tpu.console", "serve",
                "--port", "0", "--shards", "2", "--max-dcs", "2",
                "--log-dir", log_dir, "--sync-log", "--wal-segments", "3",
                "--checkpoint-interval-s", "0.3",
            ]
            if geo:
                args += ["--interdc", "--interdc-port", "0"]
            return subprocess.Popen(
                args, stdout=subprocess.PIPE, stderr=subprocess.DEVNULL,
                env=env, text=True,
            )

        proc = spawn()
        acked = [0, 0, 0]
        attempted = [0, 0, 0]
        errs: list = []
        peer = peer_rep = peer_fabric = None
        pump_stop = threading.Event()
        pump_th = None
        try:
            info = json.loads(proc.stdout.readline())
            assert info["ready"] is True
            if geo:
                peer_fabric = TcpFabric(backoff_base=0.05, backoff_max=0.5)
                peer = AntidoteNode(rcfg, dc_id=1)
                peer_rep = DCReplica(peer, peer_fabric, "dc1")
                c0 = AntidoteClient(info["host"], info["port"])
                peer_rep.observe_descriptor(c0.get_connection_descriptor())
                c0.close()

                def pumper():
                    while not pump_stop.is_set():
                        try:
                            peer_fabric.pump(timeout=0.05)
                        except OSError:
                            time.sleep(0.02)

                pump_th = threading.Thread(target=pumper)
                pump_th.start()
            stop = threading.Event()

            def writer(i):
                try:
                    c = AntidoteClient(info["host"], info["port"])
                    while not stop.is_set():
                        attempted[i] += 1
                        c.update_objects(
                            [(f"k{i}", "counter_pn", "b",
                              ("increment", 1))])
                        acked[i] += 1
                except (ConnectionError, OSError):
                    pass  # the kill severed the socket mid-request
                except Exception as e:  # pragma: no cover
                    errs.append(repr(e))

            threads = [threading.Thread(target=writer, args=(i,))
                       for i in range(3)]
            for t in threads:
                t.start()
            # wait until at least one checkpoint PUBLISHED under load (a
            # floor exists, so the kill also exercises the floor-filtered
            # tail replay), then kill inside the fault-stretched window
            mon = AntidoteClient(info["host"], info["port"])
            deadline = time.monotonic() + 40.0
            while True:
                assert time.monotonic() < deadline, "no checkpoint landed"
                st = mon.node_status()
                if (st.get("checkpoint", {}).get("last_id") or 0) >= 1 \
                        and sum(acked) >= 30:
                    break
                time.sleep(0.05)
            mon.close()
            time.sleep(0.45)  # land inside the stretched fault window
            proc.send_signal(signal.SIGKILL)
            proc.wait(timeout=10)
            stop.set()
            for t in threads:
                t.join(timeout=30)
            assert not errs, errs
            assert all(a > 0 for a in acked), acked
        finally:
            if proc.poll() is None:
                proc.kill()
                proc.wait(timeout=10)
        objs = [(f"k{i}", "counter_pn", "b") for i in range(3)]
        recovered = []
        for _ in range(2):  # two independent recoveries, byte-identical
            node = AntidoteNode(rcfg, log_dir=log_dir, recover=True)
            vals, _ = node.read_objects(objs)
            rep = DCReplica(node, TcpFabric(), "dc0-probe")
            rep.restore_from_log()
            recovered.append({
                "vals": vals,
                "op_ids": node.store.log.op_ids.tolist(),
                "seqs": node.store.log.seqs.tolist(),
                "chain_floor": node.store.log.chain_floor.tolist(),
                "stable": [int(x) for x in node.stable_vc()],
                "egress": rep.pub_opid.tolist(),
            })
            rep.hub.close()
            node.store.log.close()
        assert recovered[0] == recovered[1], f"{label}: recoveries diverged"
        vals = recovered[0]["vals"]
        for i in range(3):
            assert acked[i] <= vals[i] <= attempted[i], (
                f"{label} k{i}: acked={acked[i]} recovered={vals[i]} "
                f"attempted={attempted[i]}")
        if geo:
            # restart the server from its checkpoint; the peer's severed
            # subscription reconnects and catch-up fills whatever the
            # outage missed — totals converge EXACTLY (no duplicate
            # increments, no gaps) against the recovered state
            proc2 = spawn()
            try:
                info2 = json.loads(proc2.stdout.readline())
                assert info2["ready"] is True
                c0 = AntidoteClient(info2["host"], info2["port"])
                peer_rep.observe_descriptor(
                    c0.get_connection_descriptor())
                # a couple of post-restart commits prove the egress
                # chain resumed where the recovered positions say
                for i in range(3):
                    c0.update_objects(
                        [(f"k{i}", "counter_pn", "b", ("increment", 1))])
                want = [vals[i] + 1 for i in range(3)]
                deadline = time.monotonic() + 60.0
                while True:
                    # reads serialize against the pump thread's ingress
                    # drain (apply donates device buffers) via the same
                    # commit lock the drain holds
                    with peer.txm.commit_lock:
                        got, _ = peer.read_objects(objs)
                    if got == want:
                        break
                    assert time.monotonic() < deadline, (
                        f"geo peer never converged: {got} != {want}")
                    time.sleep(0.1)
                c0.close()
            finally:
                pump_stop.set()
                if pump_th is not None:
                    pump_th.join(timeout=10)
                proc2.kill()
                proc2.wait(timeout=10)
                peer_fabric.close()
        elif pump_th is not None:
            pump_stop.set()
            pump_th.join(timeout=10)


# ---------------------------------------------------------------------------
# scenario 15: follower read tier under fire (ISSUE 9) — 1 owner + 2
# followers with seeded drop/delay on their streams and a stretched
# image-shipping window (ckpt.ship); SIGKILL one follower mid-catch-up;
# the client session fails over with read-your-writes held; the killed
# follower rejoins from checkpoint images and converges byte-identical
# ---------------------------------------------------------------------------
def test_follower_tier_sigkill_failover_and_rejoin(tmp_path):
    import json
    import os
    import signal
    import subprocess
    import sys

    from antidote_tpu.proto.client import (AntidoteClient, RemoteLagging,
                                           SessionClient)

    env_owner = dict(
        os.environ, JAX_PLATFORMS="cpu",
        # stretch the image-shipping window so follower bootstraps are
        # genuinely mid-flight work (and chaos kills can land inside)
        ANTIDOTE_FAULT_PLAN=json.dumps({"seed": 15, "rules": [
            {"site": "ckpt.ship", "action": "delay", "arg": 0.05},
        ]}),
    )
    env_follower = dict(
        os.environ, JAX_PLATFORMS="cpu",
        # seeded drop/delay storm on the follower's subscription stream:
        # chain gaps open constantly and heal through catch-up
        ANTIDOTE_FAULT_PLAN=json.dumps({"seed": 15, "rules": [
            {"site": "interdc.deliver", "action": "drop", "p": 0.08,
             "times": 200},
            {"site": "interdc.deliver", "action": "delay", "p": 0.08,
             "times": 200},
        ]}),
    )

    def spawn_owner():
        return subprocess.Popen(
            [sys.executable, "-m", "antidote_tpu.console", "serve",
             "--port", "0", "--shards", "2", "--max-dcs", "2",
             "--log-dir", str(tmp_path / "owner"), "--interdc",
             "--interdc-port", "0", "--checkpoint-interval-s", "0.5"],
            stdout=subprocess.PIPE, stderr=subprocess.DEVNULL,
            env=env_owner, text=True,
        )

    def spawn_follower(name, owner_info):
        return subprocess.Popen(
            [sys.executable, "-m", "antidote_tpu.console", "serve",
             "--port", "0", "--log-dir", str(tmp_path / name),
             "--follower-of",
             f"{owner_info['host']}:{owner_info['port']}",
             "--replica-name", name, "--follower-park-ms", "200",
             "--divergence-check-s", "0.5"],
            stdout=subprocess.PIPE,
            stderr=open(str(tmp_path / (name + ".log")), "a"),
            env=env_follower, text=True,
        )

    owner = spawn_owner()
    f1 = f2 = f1b = None
    procs = [owner]
    try:
        oinfo = json.loads(owner.stdout.readline())
        assert oinfo["ready"] is True
        oc = AntidoteClient(oinfo["host"], oinfo["port"])
        keys = [f"k{i}" for i in range(4)]
        totals = {k: 0 for k in keys}
        for r in range(5):
            for k in keys:
                oc.update_objects([(k, "counter_pn", "b",
                                    ("increment", 1))])
                totals[k] += 1
        # wait for a published image so followers IMAGE-bootstrap (the
        # shipping path is the thing under test)
        deadline = time.monotonic() + 30
        while (oc.node_status().get("checkpoint", {}).get("last_id")
               or 0) < 1:
            assert time.monotonic() < deadline, "no owner checkpoint"
            time.sleep(0.1)
        f1 = spawn_follower("f1", oinfo)
        procs.append(f1)
        i1 = json.loads(f1.stdout.readline())
        f2 = spawn_follower("f2", oinfo)
        procs.append(f2)
        i2 = json.loads(f2.stdout.readline())
        assert i1["ready"] and i2["ready"]
        assert i1["bootstrap"] == "image" and i2["bootstrap"] == "image"

        sc = SessionClient((oinfo["host"], oinfo["port"]),
                           [(i1["host"], i1["port"]),
                            (i2["host"], i2["port"])])
        # phase 1: session writes + reads under the seeded storm —
        # read-your-writes must hold on every single read
        for r in range(8):
            k = keys[r % len(keys)]
            sc.update_objects([(k, "counter_pn", "b", ("increment", 1))])
            totals[k] += 1
            vals, _ = sc.read_objects([(k, "counter_pn", "b")])
            assert vals == [totals[k]], (k, vals, totals[k])
        # phase 2: a write burst puts the followers mid-catch-up, then
        # SIGKILL f1 — the session must keep its guarantees by failing
        # over (f2 / owner), never by serving stale data
        for k in keys:
            for _ in range(5):
                oc.update_objects([(k, "counter_pn", "b",
                                    ("increment", 1))])
                totals[k] += 1
        f1.send_signal(signal.SIGKILL)
        f1.wait(timeout=10)
        f1_addr = (i1["host"], i1["port"])
        served_dead_before = sc.served_by.get(f1_addr, 0)
        re_before, fo_before = sc.redirects, sc.failovers
        for r in range(8):
            k = keys[r % len(keys)]
            sc.update_objects([(k, "counter_pn", "b", ("increment", 1))])
            totals[k] += 1
            vals, _ = sc.read_objects([(k, "counter_pn", "b")])
            assert vals == [totals[k]], (k, vals, totals[k])
        # ring semantics: the dead follower served nothing after the
        # kill; arcs it owned failed over (dead socket or one last
        # typed redirect from the dying process — either counter),
        # other arcs were untouched — conditional on arc ownership
        assert sc.served_by.get(f1_addr, 0) == served_dead_before
        if any(sc.ring.preferred(k, "b") == f1_addr for k in keys):
            assert (sc.redirects - re_before
                    + sc.failovers - fo_before) >= 1
        # phase 3: rejoin f1 from its images (local checkpoint + the
        # owner's shipped image/tail) and converge byte-identical
        f1b = spawn_follower("f1", oinfo)
        procs.append(f1b)
        i1b = json.loads(f1b.stdout.readline())
        assert i1b["ready"]
        assert i1b["bootstrap"] in ("image", "delta", "tail")
        fc = AntidoteClient(i1b["host"], i1b["port"])
        objs = [(k, "counter_pn", "b") for k in keys]
        token = sc.token
        deadline = time.monotonic() + 60
        while True:
            try:
                vals, _ = fc.read_objects(objs, clock=token)
            except RemoteLagging:
                vals = None
            if vals == [totals[k] for k in keys]:
                st = fc.node_status()["replicas"]
                # the periodic digest sweep compared clean against the
                # owner at least once, and never found a mismatch
                if (st["state"] == "serving"
                        and st["divergence"].get("ok", 0) >= 1
                        and st["divergence"].get("mismatch", 0) == 0):
                    break
            assert time.monotonic() < deadline, (
                f"rejoined follower never converged: {vals} != {totals}")
            time.sleep(0.2)
        # owner registry: f1 and f2 both live again
        st = oc.replica_admin("status")
        assert st["followers"]["f1"]["state"] == "ok"
        assert st["followers"]["f2"]["state"] == "ok"
        fc.close()
        sc.close()
        oc.close()
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
        for p in procs:
            try:
                p.wait(timeout=10)
            except subprocess.TimeoutExpired:
                pass


# ---------------------------------------------------------------------------
# scenario 16: the planet-scale session fabric under fire (ISSUE 11) — a
# hash-routed fleet of 4 followers shadowing a 2-member CLUSTERED owner
# under a seeded drop/delay storm; SIGKILL one follower mid-storm AND
# live-move a shard between the owner's members mid-storm; every session
# read must satisfy read-your-writes/monotonic reads through it all, and
# the killed follower rejoins digest-clean
# ---------------------------------------------------------------------------
def test_hashed_fleet_clustered_owner_sigkill_and_shard_move(tmp_path):
    import json
    import os
    import signal
    import subprocess
    import sys

    from antidote_tpu.cluster import ClusterNode, attach_interdc
    from antidote_tpu.cluster.join import _move_shard
    from antidote_tpu.cluster.member import ClusterMember
    from antidote_tpu.cluster.rpc import RpcClient
    from antidote_tpu.proto.client import SessionClient
    from antidote_tpu.proto.server import ProtocolServer

    ccfg = AntidoteConfig(n_shards=4, max_dcs=2)
    env_follower = dict(
        os.environ, JAX_PLATFORMS="cpu",
        # seeded drop/delay storm on every follower's subscription
        # streams: chain gaps open constantly and heal through the
        # per-member routed catch-up
        ANTIDOTE_FAULT_PLAN=json.dumps({"seed": 16, "rules": [
            {"site": "interdc.deliver", "action": "drop", "p": 0.08,
             "times": 300},
            {"site": "interdc.deliver", "action": "delay", "p": 0.08,
             "times": 300},
        ]}),
    )
    fab = TcpFabric(backoff_base=0.05, backoff_max=0.5)
    ms = [ClusterMember(ccfg, dc_id=0, member_id=i, n_members=2,
                        log_dir=str(tmp_path / f"m{i}"))
          for i in range(2)]
    for a in ms:
        for b in ms:
            if a is not b:
                a.connect(b.member_id, *b.address)
    reps = [attach_interdc(m, fab) for m in ms]
    # one wire server per member (interdc=rep: serves the member's
    # descriptor + replica registry) — the console follower path learns
    # the fleet endpoint by endpoint from these
    srvs = [ProtocolServer(ClusterNode(m), port=0, interdc=r)
            for m, r in zip(ms, reps)]
    owner_list = ",".join(f"{s.host}:{s.port}" for s in srvs)
    coord = ClusterNode(ms[0])

    stop = threading.Event()

    def pumper():
        while not stop.is_set():
            fab.pump(timeout=0.05)
            for m in ms:
                try:
                    m.refresh_peer_clocks()
                except Exception:
                    pass

    pump_t = threading.Thread(target=pumper, daemon=True)
    pump_t.start()

    def spawn_follower(name):
        return subprocess.Popen(
            [sys.executable, "-m", "antidote_tpu.console", "serve",
             "--port", "0", "--log-dir", str(tmp_path / name),
             "--follower-of", owner_list,
             "--replica-name", name, "--follower-park-ms", "400",
             "--divergence-check-s", "0.5"],
            stdout=subprocess.PIPE,
            stderr=open(str(tmp_path / (name + ".log")), "a"),
            env=env_follower, text=True,
        )

    followers = {}
    procs = []
    f3b = None
    try:
        keys = [f"k{i}" for i in range(8)]  # spread over all 4 shards
        totals = {k: 0 for k in keys}
        for _ in range(3):
            for k in keys:
                coord.update_objects([(k, "counter_pn", "b",
                                       ("increment", 1))])
                totals[k] += 1
        # one image per member, so every follower composes the fleet's
        # images at bootstrap (the path under test)
        for m in ms:
            m.node.checkpoint_now()
        for i in range(4):
            followers[f"f{i}"] = spawn_follower(f"f{i}")
        procs.extend(followers.values())
        infos = {}
        for name, p in followers.items():
            infos[name] = json.loads(p.stdout.readline())
            assert infos[name]["ready"]
            assert infos[name]["bootstrap"] == "image"
            assert infos[name]["fleet"]["owner_members"] == 2
        sc = SessionClient(
            (srvs[0].host, srvs[0].port),
            [(infos[f"f{i}"]["host"], infos[f"f{i}"]["port"])
             for i in range(4)],
            seed=1616,
        )

        def session_round(r):
            k = keys[r % len(keys)]
            sc.update_objects([(k, "counter_pn", "b", ("increment", 1))])
            totals[k] += 1
            vals, _ = sc.read_objects([(k, "counter_pn", "b")])
            assert vals == [totals[k]], (k, vals, totals[k])

        # phase 1: the storm alone — RYW on every single read
        for r in range(8):
            session_round(r)
        # phase 2: a write burst (catch-up pressure), then SIGKILL f3
        # mid-storm — the ring sheds only f3's arcs, sessions keep RYW
        for k in keys:
            for _ in range(3):
                coord.update_objects([(k, "counter_pn", "b",
                                       ("increment", 1))])
                totals[k] += 1
        f3 = followers["f3"]
        f3.send_signal(signal.SIGKILL)
        f3.wait(timeout=10)
        f3_addr = (infos["f3"]["host"], infos["f3"]["port"])
        served_dead_before = sc.served_by.get(f3_addr, 0)
        for r in range(8):
            session_round(r)
        assert sc.served_by.get(f3_addr, 0) == served_dead_before
        # phase 3: LIVE shard move between the owner's members,
        # mid-storm — epoch gossip re-points every follower's catch-up
        # with no reconnect; sessions keep RYW through the move
        moved = next(s for s in range(ccfg.n_shards)
                     if s in ms[1].shards)
        clients = {m.member_id: RpcClient(*m.address) for m in ms}
        try:
            _move_shard(clients, moved, 1, 0, 2)
        finally:
            for c in clients.values():
                c.close()
        assert moved in ms[0].shards
        for r in range(12):
            session_round(r)
        # phase 4: rejoin f3 from its local state + the fleet's images
        # and require a digest-clean convergence (ok sweeps, zero
        # mismatches) plus the full totals at the session token
        f3b = spawn_follower("f3")
        procs.append(f3b)
        i3b = json.loads(f3b.stdout.readline())
        assert i3b["ready"]
        from antidote_tpu.proto.client import AntidoteClient, RemoteLagging

        fc = AntidoteClient(i3b["host"], i3b["port"])
        objs = [(k, "counter_pn", "b") for k in keys]
        token = sc.token
        deadline = time.monotonic() + 90
        while True:
            try:
                vals, _ = fc.read_objects(objs, clock=token)
            except RemoteLagging:
                vals = None
            if vals == [totals[k] for k in keys]:
                st = fc.node_status()["replicas"]
                if (st["state"] == "serving"
                        and st["divergence"].get("ok", 0) >= 1
                        and st["divergence"].get("mismatch", 0) == 0):
                    break
            assert time.monotonic() < deadline, (
                f"rejoined follower never converged digest-clean: "
                f"{vals} != {totals}")
            time.sleep(0.2)
        # both members' registries see the surviving fleet as ok
        reg = reps[0].replica_status()["followers"]
        for name in ("f0", "f1", "f2", "f3"):
            assert reg[name]["state"] in ("ok", "lagging"), reg
        fc.close()
        sc.close()
    finally:
        stop.set()
        pump_t.join(timeout=10)
        for p in procs:
            if p.poll() is None:
                p.kill()
        for p in procs:
            try:
                p.wait(timeout=10)
            except subprocess.TimeoutExpired:
                pass
        for s in srvs:
            s.close()
        for m in ms:
            try:
                m.close()
            except Exception:
                pass
        fab.close()


# ---------------------------------------------------------------------------
# long soak (excluded from tier-1 via -m 'not slow'; run with `make chaos`)
# ---------------------------------------------------------------------------
# ---------------------------------------------------------------------------
# scenario 17: beyond-RAM survival under fire (ISSUE 13) — console serve
# with a cold tier + incremental checkpoint chains under a seeded write
# storm over a keyspace larger than the resident budget; SIGKILL lands
# mid-chain-stamp (fault-stretched window), ONE mid-chain link is
# corrupted on disk, and a follower gets a single row byte flipped.
# Contract: resident rows stay bounded by --resident-rows through the
# storm AND through recovery, acked ⊆ recovered ⊆ attempted, two
# recoveries are byte-identical despite the corrupt link (prefix + WAL
# tail fallback), and the follower's divergence heals through the
# Merkle range fetch touching ONLY the diverged leaf — no re-bootstrap.
# ---------------------------------------------------------------------------
def test_coldtier_chain_storm_sigkill_corrupt_link_and_merkle_heal(tmp_path):
    import json
    import os
    import signal
    import subprocess
    import sys

    from antidote_tpu.interdc import FollowerReplica, LoopbackHub
    from antidote_tpu.log import checkpoint as ckpt
    from antidote_tpu.proto.client import AntidoteClient

    N_KEYS = 96          # keyspace per the whole storm
    BUDGET = 40          # resident-rows budget (≪ keyspace)
    rcfg = AntidoteConfig(n_shards=2, max_dcs=2, wal_segments=3,
                          keys_per_table=32)
    log_dir = str(tmp_path / "wal")
    env = dict(
        os.environ, JAX_PLATFORMS="cpu",
        # stretch every stamp's write window so the SIGKILL lands
        # mid-chain-stamp regardless of scheduling
        ANTIDOTE_FAULT_PLAN=json.dumps({"seed": 17, "rules": [
            {"site": "ckpt.write", "action": "delay", "arg": 0.1},
        ]}),
    )
    proc = subprocess.Popen(
        [sys.executable, "-m", "antidote_tpu.console", "serve",
         "--port", "0", "--shards", "2", "--max-dcs", "2",
         "--log-dir", log_dir, "--sync-log", "--wal-segments", "3",
         "--keys-per-table", "32",
         "--checkpoint-interval-s", "0.25",
         "--checkpoint-rebase-every", "3",
         "--resident-rows", str(BUDGET)],
        stdout=subprocess.PIPE, stderr=subprocess.DEVNULL, env=env,
        text=True,
    )
    acked = [0] * N_KEYS
    attempted = [0] * N_KEYS
    errs: list = []
    max_resident = [0]
    try:
        info = json.loads(proc.stdout.readline())
        assert info["ready"] is True
        stop = threading.Event()
        # one populate sweep over the WHOLE beyond-budget keyspace: the
        # tail goes cold once the first full image covers it, while the
        # storm below keeps a hot set (smaller than the budget) dirty
        cpop = AntidoteClient(info["host"], info["port"])
        for k in range(N_KEYS):
            attempted[k] += 1
            cpop.update_objects([(k, "counter_pn", "b",
                                  ("increment", 1))])
            acked[k] += 1
        cpop.close()

        def writer(base):
            try:
                c = AntidoteClient(info["host"], info["port"])
                n = 0
                while not stop.is_set():
                    k = base + n % 8  # 24 hot keys across 3 writers
                    n += 1
                    attempted[k] += 1
                    c.update_objects([(k, "counter_pn", "b",
                                       ("increment", 1))])
                    acked[k] += 1
            except (ConnectionError, OSError):
                pass  # the kill severed the socket mid-request
            except Exception as e:  # pragma: no cover
                errs.append(repr(e))

        threads = [threading.Thread(target=writer, args=(i * 8,))
                   for i in range(3)]
        for t in threads:
            t.start()
        # run until the chain has a full image + at least one delta AND
        # the cold tier is actively bounding residency under the storm
        mon = AntidoteClient(info["host"], info["port"])
        deadline = time.monotonic() + 60.0
        settled_at = None
        while True:
            assert time.monotonic() < deadline, "chain never formed"
            st = mon.node_status()
            ck = st.get("checkpoint", {})
            cold = st.get("cold_tier", {})
            resident = cold.get("resident_rows", 0)
            # the budget becomes enforceable once a full image covers
            # the populated tail (keys written since a stamp are not
            # evictable until the next stamp — by design: eviction can
            # never lose a write); from the first settled observation
            # onward, the storm's hot set (< budget) must keep
            # residency bounded
            if settled_at is None:
                if resident and resident <= BUDGET:
                    settled_at = time.monotonic()
            else:
                max_resident[0] = max(max_resident[0], resident)
            if settled_at is not None \
                    and time.monotonic() - settled_at >= 1.0 \
                    and (ck.get("last_id") or 0) >= 2 \
                    and (ck.get("chain_len") or 0) >= 1 \
                    and cold.get("cold_keys", 0) > 0 \
                    and sum(acked) >= 200:
                break
            time.sleep(0.05)
        # bounded RSS through the storm's steady state: residency tracks
        # the budget (hot-set writes + one commit batch of slack)
        assert 0 < max_resident[0] <= BUDGET + 32, max_resident[0]
        mon.close()
        time.sleep(0.3)  # land inside a stretched stamp window
        proc.send_signal(signal.SIGKILL)
        proc.wait(timeout=10)
        stop.set()
        for t in threads:
            t.join(timeout=30)
        assert not errs, errs
        assert sum(acked) > 0
    finally:
        if proc.poll() is None:
            proc.kill()
            proc.wait(timeout=10)
    # corrupt ONE mid-chain link on disk (bit rot between crash and
    # recovery); recovery must fall back to the prefix + WAL tail
    cks = ckpt.list_checkpoints(ckpt.checkpoint_root(log_dir))
    deltas = [(i, p) for i, p in cks
              if ckpt.manifest_kind(ckpt.load_manifest(p) or {}) == "delta"]
    if deltas:
        victim = deltas[len(deltas) // 2][1]
        with open(os.path.join(victim, "image.bin"), "r+b") as f:
            f.seek(12)
            f.write(b"\xff\xff\xff\xff")
    objs = [(k, "counter_pn", "b") for k in range(N_KEYS)]
    recovered = []
    for _ in range(2):  # two independent recoveries, byte-identical
        node = AntidoteNode(rcfg, log_dir=log_dir, recover=True,
                            resident_rows=BUDGET)
        # bounded recovery: the budget pass re-evicts everything the
        # surviving chain covers; only rows the corrupt link's
        # truncation left uncovered (WAL-tail-overlaid) may exceed the
        # budget — never the whole keyspace
        assert node.store.cold.resident_rows() < N_KEYS
        assert len(node.store.cold.cold_set) > 0
        vals, _ = node.read_objects(objs)  # faults cold keys in, exact
        recovered.append({
            "vals": vals,
            "op_ids": node.store.log.op_ids.tolist(),
            "seqs": node.store.log.seqs.tolist(),
            "stable": [int(x) for x in node.stable_vc()],
        })
        node.store.log.close()
    assert recovered[0] == recovered[1], "recoveries diverged"
    vals = recovered[0]["vals"]
    for k in range(N_KEYS):
        assert acked[k] <= vals[k] <= attempted[k], (
            f"k{k}: acked={acked[k]} recovered={vals[k]} "
            f"attempted={attempted[k]}")
    # ---- follower leg: flip ONE row byte, heal ONLY that range --------
    hub = LoopbackHub()
    owner = AntidoteNode(rcfg, log_dir=log_dir, recover=True)
    orep = DCReplica(owner, hub, "dc0")
    orep.restore_from_log()
    owner.checkpoint_now(full=True)
    fnode = AntidoteNode(rcfg, log_dir=str(tmp_path / "fol"))
    fol = FollowerReplica(fnode, hub, "f17",
                          owner_client_addr=("h", 1), fabric_id=177)
    fol.attach(orep.descriptor())
    for _ in range(40):
        orep.heartbeat()
        hub.pump()
        if (fnode.store.stable_vc() >= owner.store.dc_max_vc()).all():
            break
    assert all(v == "ok" for v in fol.check_divergence().values())
    victim_key = next(k for k in range(N_KEYS) if vals[k] > 0)
    tname, shard, row = fnode.store.directory[(victim_key, "b")]
    t = fnode.store.tables[tname]
    f0 = next(iter(t.head))
    t.head[f0] = t.head[f0].at[shard, row].set(10**6)
    fnode.store.drop_cached_value((victim_key, "b"))
    # snapshot every OTHER row of the shard: the heal must not touch it
    others_before = np.asarray(t.head[f0]).copy()
    boots_before = fol.boots
    res = fol.check_divergence([shard])
    assert res == {shard: "mismatch"}, res
    assert fnode.metrics.divergence_heals.value(mode="range") == 1
    assert fnode.metrics.divergence_heals.value(mode="image") == 0
    assert fol.boots == boots_before, "range heal must not re-bootstrap"
    got, _ = fnode.read_objects([(victim_key, "counter_pn", "b")])
    assert got == [vals[victim_key]]
    # locality: only the flipped row changed; every other row of the
    # table is byte-identical to its pre-heal state
    others_after = np.asarray(t.head[f0])
    mask = np.ones(others_after.shape, bool)
    mask[shard, row] = False
    assert (others_after[mask] == others_before[mask]).all()
    assert all(v == "ok" for v in fol.check_divergence().values())
    owner.store.log.close(), fnode.store.log.close()


# ---------------------------------------------------------------------------
# scenario 18: the symmetric serving fabric under fire (ISSUE 17) —
# 1 owner + 3 followers (console serve), a RING-OBLIVIOUS client bolted
# to ONE entry follower driving a mixed read/write storm with its own
# session token, proxy hops fault-stretched (proxy.forward delay) so
# the kill lands inside forwarded work; SIGKILL the proxy target the
# storm's keys prefer.  Contract: the entry node fails over
# SERVER-SIDE (local DEAD_S observation bridges the registry's
# staleness window) — the bare client sees ZERO typed redirects and
# read-your-writes holds on every read through the kill; a bare apb
# client gets the same failover; acked ⊆ recovered at the owner; the
# surviving followers' digest sweeps converge byte-identical.
# ---------------------------------------------------------------------------
def test_proxy_fabric_sigkill_target_serverside_failover(tmp_path):
    import json
    import os
    import signal
    import subprocess
    import sys

    from antidote_tpu.proto.client import (AntidoteClient, ApbClient,
                                           HashRing)

    env_entry = dict(
        os.environ, JAX_PLATFORMS="cpu",
        # stretch every proxy hop so the SIGKILL lands inside forwarded
        # work instead of between requests
        ANTIDOTE_FAULT_PLAN=json.dumps({"seed": 18, "rules": [
            {"site": "proxy.forward", "action": "delay", "p": 0.25,
             "arg": 0.02, "times": 400},
        ]}),
    )
    env_plain = dict(os.environ, JAX_PLATFORMS="cpu")

    def spawn_follower(name, oinfo, env):
        return subprocess.Popen(
            [sys.executable, "-m", "antidote_tpu.console", "serve",
             "--port", "0", "--log-dir", str(tmp_path / name),
             "--follower-of", f"{oinfo['host']}:{oinfo['port']}",
             "--replica-name", name, "--follower-park-ms", "100",
             "--divergence-check-s", "0.5"],
            stdout=subprocess.PIPE,
            stderr=open(str(tmp_path / (name + ".log")), "a"),
            env=env, text=True,
        )

    owner = subprocess.Popen(
        [sys.executable, "-m", "antidote_tpu.console", "serve",
         "--port", "0", "--shards", "2", "--max-dcs", "2",
         "--log-dir", str(tmp_path / "owner"), "--interdc",
         "--interdc-port", "0", "--checkpoint-interval-s", "0.5"],
        stdout=subprocess.PIPE, stderr=subprocess.DEVNULL,
        env=env_plain, text=True,
    )
    procs = [owner]
    try:
        oinfo = json.loads(owner.stdout.readline())
        assert oinfo["ready"] is True
        oc = AntidoteClient(oinfo["host"], oinfo["port"])
        keys = [f"ck{i}" for i in range(10)]
        totals = {k: 0 for k in keys}
        for k in keys:
            oc.update_objects([(k, "counter_pn", "b", ("increment", 1))])
            totals[k] += 1
        deadline = time.monotonic() + 30
        while (oc.node_status().get("checkpoint", {}).get("last_id")
               or 0) < 1:
            assert time.monotonic() < deadline, "no owner checkpoint"
            time.sleep(0.1)
        infos = []
        for i in range(3):
            p = spawn_follower(f"f{i + 1}", oinfo,
                               env_entry if i == 0 else env_plain)
            procs.append(p)
            infos.append(json.loads(p.stdout.readline()))
        assert all(i["ready"] for i in infos)
        eps = [(i["host"], i["port"]) for i in infos]

        # the entry node must learn the full serving fleet (liveness
        # reports piggyback the registry snapshot) before the storm
        fc = AntidoteClient(*eps[0])
        deadline = time.monotonic() + 30
        while True:
            st = fc.node_status()["pipeline"]["proxy"]
            if len(st["fleet"]["endpoints"]) == 3:
                break
            assert time.monotonic() < deadline, st
            time.sleep(0.2)

        # placement is unseeded and fleet-wide: the test computes every
        # node's arc assignment with the same ring the planes run, and
        # kills the follower that owns the FIRST key's arc (never the
        # entry node — re-pick the key if needed)
        ring = HashRing(eps, vnodes=64)
        victim_key = next(k for k in keys
                          if ring.preferred(k, "b") != eps[0])
        victim_ep = ring.preferred(victim_key, "b")
        victim = procs[1 + eps.index(victim_ep)]

        # phase 1: ring-oblivious mixed storm through the ONE entry
        # follower — every write forwards, every read holds RYW
        vc = None
        for r in range(4):
            for k in keys:
                vc = fc.update_objects(
                    [(k, "counter_pn", "b", ("increment", 1))], clock=vc)
                totals[k] += 1
                vals, vc = fc.read_objects([(k, "counter_pn", "b")],
                                           clock=vc)
                assert vals == [totals[k]], (k, vals, totals[k])

        # phase 2: SIGKILL the proxy target mid-storm and keep going —
        # zero typed errors allowed; the entry node's local fleet
        # health covers the registry's REPLICA_DOWN_S staleness window
        victim.send_signal(signal.SIGKILL)
        victim.wait(timeout=10)
        for r in range(4):
            for k in keys:
                vc = fc.update_objects(
                    [(k, "counter_pn", "b", ("increment", 1))], clock=vc)
                totals[k] += 1
                vals, vc = fc.read_objects([(k, "counter_pn", "b")],
                                           clock=vc)
                assert vals == [totals[k]], (k, vals, totals[k])
        st = fc.node_status()["pipeline"]["proxy"]
        assert st["forwarded"]["write"] >= 8 * len(keys)
        assert st["forwarded"]["read"] >= 1
        assert st["forwarded"]["failover"] >= 1, st

        # a bare apb client at the same entry follower gets the same
        # server-side failover + RYW (bytes keyspace)
        ac = ApbClient(*eps[0])
        avc = ac.update_objects([(victim_key.encode(), "counter_pn",
                                  b"b", ("increment", 1))])
        avals, _ = ac.read_objects([(victim_key.encode(), "counter_pn",
                                     b"b")], clock=avc)
        assert avals == [1]
        ac.close()

        # acked ⊆ recovered: every acked increment is visible at the
        # owner (no ForwardFailed surfaced, so acked == recovered)
        ovals, ovc = oc.read_objects([(k, "counter_pn", "b")
                                      for k in keys])
        assert ovals == [totals[k] for k in keys]

        # surviving followers converge byte-identical: the periodic
        # digest sweep compares clean against the owner, zero mismatch
        for ep in eps:
            if ep == victim_ep:
                continue
            c = AntidoteClient(*ep)
            deadline = time.monotonic() + 60
            while True:
                rs = c.node_status()["replicas"]
                if (rs["state"] == "serving"
                        and rs["divergence"].get("ok", 0) >= 1
                        and rs["divergence"].get("mismatch", 0) == 0):
                    break
                assert time.monotonic() < deadline, rs
                time.sleep(0.2)
            c.close()
        # the owner's registry agrees about who is dead
        deadline = time.monotonic() + 30
        vname = f"f{1 + eps.index(victim_ep)}"
        while True:
            reg = oc.replica_admin("status")["followers"]
            if reg[vname]["state"] == "down":
                break
            assert time.monotonic() < deadline, reg
            time.sleep(0.2)
        fc.close()
        oc.close()
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
        for p in procs:
            try:
                p.wait(timeout=10)
            except subprocess.TimeoutExpired:
                pass


# ---------------------------------------------------------------------------
# scenario 19: the escrow economy under partition + crash (ISSUE 18) —
# a Zipf-contended 2-DC flash sale over bounded counters.  Sever the
# link mid-sale: each side keeps selling its OWN escrow, then refuses
# typed (insufficient_rights with a retry hint) — never oversells.
# SIGKILL the granter mid-transfer (the grant window stretched by an
# env-armed ``bcounter.transfer`` delay), respawn it from its WAL, and
# heal: the supervised rights-transfer loop survives every failure
# typed (no blind resend on the at-most-once query channel), grants
# resume, and both DCs converge to the exact global inventory —
# oversell == 0, acked sales all survive, rights conserved per lane.
# ---------------------------------------------------------------------------
def test_flash_sale_partition_and_granter_crash_never_oversells(tmp_path):
    import json
    import os
    import random
    import signal
    import subprocess
    import sys

    from antidote_tpu.overload import InsufficientRightsError
    from antidote_tpu.proto.client import (AntidoteClient, RemoteAbort,
                                           RemoteBusy,
                                           RemoteInsufficientRights)
    from antidote_tpu.txn.manager import AbortError

    rcfg = AntidoteConfig(n_shards=2, max_dcs=2, wal_segments=3)
    log_dir = str(tmp_path / "wal-dc0")
    env = dict(
        os.environ, JAX_PLATFORMS="cpu",
        # stretch every grant DC0 serves so the SIGKILL below lands
        # mid-transfer deterministically
        ANTIDOTE_FAULT_PLAN=json.dumps({"seed": 19, "rules": [
            {"site": "bcounter.transfer", "action": "delay",
             "arg": 0.35}]}),
    )

    def spawn():
        return subprocess.Popen(
            [sys.executable, "-m", "antidote_tpu.console", "serve",
             "--port", "0", "--shards", "2", "--max-dcs", "2",
             "--log-dir", log_dir, "--sync-log", "--wal-segments", "3",
             "--interdc", "--interdc-port", "0"],
            stdout=subprocess.PIPE, stderr=subprocess.DEVNULL,
            env=env, text=True,
        )

    skus = ["sku0", "sku1", "sku2"]
    inv = {"sku0": 40, "sku1": 24, "sku2": 16}
    restock = {"sku0": 20, "sku1": 10, "sku2": 6}
    weights = [8, 3, 1]  # Zipf-ish contention: sku0 is the hot item
    acked = {s: 0 for s in skus}     # committed-and-acked sales
    lost = {s: 0 for s in skus}      # in-flight at a socket death
    refused = [0, 0]                 # typed refusals per DC
    aborts = [0]                     # cert conflicts (retried, not sold)
    errs: list = []                  # anything NOT typed = protocol error
    acct = threading.Lock()
    stop = threading.Event()

    proc = spawn()
    peer = peer_rep = peer_fabric = loop = None
    pump_stop = threading.Event()
    pump_th = None
    sellers = []
    try:
        info = json.loads(proc.stdout.readline())
        assert info["ready"] is True
        assert info.get("escrow", {}).get("loop") is True  # console wired
        # in-process DC1 on its own fabric, subscribed both ways
        peer_fabric = TcpFabric(backoff_base=0.05, backoff_max=0.5)
        peer = AntidoteNode(rcfg, dc_id=1)
        peer_rep = DCReplica(peer, peer_fabric, "dc1")
        c0 = AntidoteClient(info["host"], info["port"])
        peer_rep.observe_descriptor(c0.get_connection_descriptor())
        c0.connect_to_dcs([peer_rep.descriptor().to_wire()])
        # mint the opening inventory at DC0 (all rights on lane 0)
        for s in skus:
            c0.update_objects([(s, "counter_b", "b",
                                ("increment", (inv[s], 0)))])
        c0.close()

        def pumper():
            while not pump_stop.is_set():
                try:
                    peer_fabric.pump(timeout=0.05)
                except OSError:
                    time.sleep(0.02)

        pump_th = threading.Thread(target=pumper)
        pump_th.start()
        # the tentpole under test: the SUPERVISED background transfer
        # loop drives DC1's side of the escrow economy
        loop = peer_rep.start_escrow_loop()
        mgr = peer.txm.bcounters

        def sell_dc0(seed):
            """Wire seller against DC0; exits when the kill severs it."""
            rng = random.Random(seed)
            c = AntidoteClient(info["host"], info["port"])
            try:
                while not stop.is_set():
                    s = rng.choices(skus, weights)[0]
                    try:
                        c.update_objects(
                            [(s, "counter_b", "b", ("decrement", (1, 0)))])
                        with acct:
                            acked[s] += 1
                    except RemoteInsufficientRights as e:
                        with acct:
                            refused[0] += 1
                        assert e.retry_after_ms > 0
                        time.sleep(min(e.retry_after_ms, 250) / 1e3)
                    except (RemoteBusy, RemoteAbort):
                        with acct:
                            aborts[0] += 1
                        time.sleep(0.01)
                    except (ConnectionError, OSError):
                        with acct:
                            lost[s] += 1  # outcome unknown: the kill
                        return
            except Exception as e:  # pragma: no cover
                errs.append(repr(e))
            finally:
                try:
                    c.close()
                except OSError:
                    pass

        def sell_dc1(seed):
            """In-process seller on DC1's own lane — its rights arrive
            only through the transfer loop's grants."""
            rng = random.Random(seed)
            try:
                while not stop.is_set():
                    s = rng.choices(skus, weights)[0]
                    try:
                        peer.update_objects(
                            [(s, "counter_b", "b", ("decrement", (1, 1)))])
                        with acct:
                            acked[s] += 1
                    except InsufficientRightsError as e:
                        with acct:
                            refused[1] += 1
                        assert e.retry_after_ms > 0
                        time.sleep(min(e.retry_after_ms, 250) / 1e3)
                    except AbortError:
                        with acct:
                            aborts[0] += 1
                        time.sleep(0.01)
            except Exception as e:  # pragma: no cover
                errs.append(repr(e))

        sellers = [threading.Thread(target=sell_dc0, args=(100 + i,))
                   for i in range(2)]
        sellers += [threading.Thread(target=sell_dc1, args=(200 + i,))
                    for i in range(2)]
        for t in sellers:
            t.start()
        # -- phase 1: open sale — both DCs sell; DC1 starts with ZERO
        # rights, so any DC1 sale proves a grant crossed the wire
        deadline = time.monotonic() + 90.0
        while True:
            with acct:
                dc1_sold = mgr.grants_arrived_total
                total = sum(acked.values())
            if dc1_sold >= 1 and total >= 8 and refused[1] >= 1:
                break
            assert time.monotonic() < deadline, (
                f"open sale stalled: acked={acked} refused={refused} "
                f"escrow={mgr.status()}")
            assert not errs, errs
            time.sleep(0.05)
        # -- phase 2: sever mid-sale.  No grants can cross; each side
        # drains its OWN escrow then refuses typed — zero oversell
        inj = faults.install(faults.FaultPlan(seed=19))
        inj.sever(0, 1)
        with acct:
            r0, r1 = refused
        deadline = time.monotonic() + 60.0
        while True:
            with acct:
                if refused[0] > r0 and refused[1] > r1 + 1:
                    break
            assert time.monotonic() < deadline, (
                f"partitioned sides never went dry+typed: "
                f"refused={refused} (was {r0},{r1}) acked={acked}")
            assert not errs, errs
            time.sleep(0.05)
        # restock DC0 while partitioned (the second drop): this is the
        # escrow the post-heal grant — and the mid-transfer kill — rides
        cr = AntidoteClient(info["host"], info["port"])
        for s in skus:
            cr.update_objects([(s, "counter_b", "b",
                                ("increment", (restock[s], 0)))])
        cr.close()
        # -- phase 3: heal, then SIGKILL the granter mid-transfer.  The
        # env-armed delay holds DC0's grant open 0.35s; we kill inside
        # that window, right after DC1's loop sends a request
        inj.heal_all()
        rs0 = mgr.requests_sent_total
        deadline = time.monotonic() + 30.0
        while mgr.requests_sent_total <= rs0:
            assert time.monotonic() < deadline, (
                f"no post-heal transfer request: {mgr.status()}")
            time.sleep(0.01)
        time.sleep(0.15)  # inside the stretched grant window
        proc.send_signal(signal.SIGKILL)
        proc.wait(timeout=10)
        # the supervised loop survived the mid-transfer death typed
        time.sleep(0.5)
        assert loop.crashed is None, f"escrow loop crashed: {loop.crashed}"
        assert loop.is_alive()
        # -- phase 4: respawn DC0 from its WAL, rewire, finish the sale
        proc2 = spawn()
        proc = proc2
        info2 = json.loads(proc2.stdout.readline())
        assert info2["ready"] is True
        c0 = AntidoteClient(info2["host"], info2["port"])
        peer_rep.observe_descriptor(c0.get_connection_descriptor())
        c0.connect_to_dcs([peer_rep.descriptor().to_wire()])
        c0.close()
        info = info2
        with acct:
            sold_at_respawn = sum(acked.values())
        deadline = time.monotonic() + 90.0
        while True:
            with acct:
                if sum(acked.values()) >= sold_at_respawn + 2:
                    break  # the grant economy resumed post-crash
            assert time.monotonic() < deadline, (
                f"no sales after respawn: acked={acked} "
                f"escrow={mgr.status()}")
            assert not errs, errs
            time.sleep(0.05)
        stop.set()
        for t in sellers:
            t.join(timeout=30)
        sellers = []
        assert not errs, errs
        # -- phase 5: convergence + the escrow ledger.  Both DCs settle
        # to IDENTICAL values at the joint clock; every SKU accounts
        # exactly: sold ⊆ acked-or-lost, oversell == 0, rights conserved
        from antidote_tpu.crdt import get_type

        ty = get_type("counter_b")
        total_inv = {s: inv[s] + restock[s] for s in skus}
        objs = [(s, "counter_b", "b") for s in skus]
        cv = AntidoteClient(info["host"], info["port"])
        deadline = time.monotonic() + 90.0
        while True:
            with peer.txm.commit_lock:
                vc1 = peer.txm.store.dc_max_vc()
                v1, _ = peer.read_objects(objs, clock=vc1)
            try:
                v0, _ = cv.read_objects(objs,
                                        clock=[int(x) for x in vc1])
            except Exception:
                v0 = None  # DC0 still catching up to DC1's lane
            if v0 == v1:
                break
            assert time.monotonic() < deadline, (
                f"DCs never converged: dc0={v0} dc1={v1}")
            time.sleep(0.2)
        cv.close()
        with acct:
            for i, s in enumerate(skus):
                committed = total_inv[s] - v1[i]
                assert v1[i] >= 0, f"{s}: OVERSOLD to {v1[i]}"
                assert acked[s] <= committed <= acked[s] + lost[s], (
                    f"{s}: acked={acked[s]} committed={committed} "
                    f"lost={lost[s]}")
        # rights conservation per SKU: the mint total (diagonal) is the
        # exact global inventory; per-lane holdings sum to the value and
        # no lane ever went negative — transfers moved, never minted
        with peer.txm.commit_lock:
            states = peer.txm.store.read_states(objs, vc1)
        for i, s in enumerate(skus):
            st = states[i]
            d = np.asarray(st["used"]).shape[0]
            assert int(np.trace(np.asarray(st["rights"]))) == total_inv[s]
            assert sum(ty.local_rights(st, dc) for dc in range(d)) == v1[i]
            assert all(ty.local_rights(st, dc) >= 0 for dc in range(d))
        # the economy's paper trail: typed refusals on both sides, a
        # failed (killed/severed) grant that was never blind-resent,
        # and successful requester-side grants
        assert refused[0] >= 1 and refused[1] >= 2, refused
        m = peer.metrics
        assert m.escrow_grants.value(role="requester") >= 1
        assert m.escrow_grants.value(role="failed") >= 1
        assert mgr.grants_arrived_total >= 1
    finally:
        stop.set()
        for t in sellers:
            t.join(timeout=30)
        if loop is not None:
            loop.stop()
        pump_stop.set()
        if pump_th is not None:
            pump_th.join(timeout=10)
        if peer_fabric is not None:
            peer_fabric.close()
        if proc.poll() is None:
            proc.kill()
            proc.wait(timeout=10)


@pytest.mark.slow
def test_storm_soak_many_rounds(cfg):
    """A longer seeded storm across 3 DCs with partitions opening and
    closing between rounds — the `make chaos` soak."""
    plan = faults.FaultPlan(seed=4242)
    plan.drop("interdc.deliver", p=0.2)
    plan.dup("interdc.deliver", p=0.1)
    plan.delay("interdc.deliver", p=0.1)
    inj = faults.install(plan)
    fabrics, nodes, reps = mk_mesh(cfg, 3)
    try:
        total = {k: 0 for k in range(6)}
        for round_ in range(12):
            if round_ % 4 == 1:
                inj.sever(round_ % 3, (round_ + 1) % 3)
            if round_ % 4 == 3:
                inj.heal_all()
            for dc, n in enumerate(nodes):
                k = (round_ + dc) % 6
                n.update_objects(
                    [(k, "counter_pn", "b", ("increment", 1 + dc))])
                total[k] += 1 + dc
            for f in fabrics:
                f.pump(timeout=0.1)
        inj.heal_all()
        # stop injecting (rules have no times bound) so the mesh drains
        faults.uninstall()
        clock = pump_until_converged(fabrics, nodes, reps, deadline=60.0)
        objs = [(k, "counter_pn", "b") for k in range(6)]
        vals = assert_identical_snapshots(nodes, objs, clock)
        assert vals == [total[k] for k in range(6)]
    finally:
        close_mesh(fabrics)


# ---------------------------------------------------------------------------
# scenario 20: the noisy neighbor (ISSUE 19) — tenant `aggro` drives a
# saturating write storm through its own weighted-fair lane while
# tenant `vip` keeps reading, under seeded wal-fsync delays and seeded
# frame drops/delays at the front end.  SIGKILL the serving process
# mid-storm and respawn it from its WAL.  The isolation contract:
# vip's read p99 under the storm stays within 3x its SOLO baseline
# (both phases measured against a warm, fault-seeded server), vip sees
# ZERO typed refusals (every shed lands on aggro's OWN quota — proven
# by aggro's typed tenant_busy count), and per-tenant acked writes are
# all recovered, byte-identical across two independent recoveries.
# ---------------------------------------------------------------------------
def test_noisy_neighbor_storm_sigkill_isolation(tmp_path):
    import json
    import os
    import signal
    import subprocess
    import sys

    from antidote_tpu.proto.client import (AntidoteClient, RemoteBusy,
                                           RemoteTenantBusy)

    log_dir = str(tmp_path / "wal")
    env = dict(
        os.environ, JAX_PLATFORMS="cpu",
        ANTIDOTE_FAULT_PLAN=json.dumps({"seed": 20, "rules": [
            # a stalling volume: the write plane is genuinely slow, so
            # the aggressor's backlog is real pressure, not a no-op
            {"site": "wal.fsync", "action": "delay", "p": 0.3,
             "arg": 0.01},
            # seeded front-end chop: delayed frames and dropped
            # connections hit BOTH tenants impartially
            {"site": "frontend.recv", "action": "delay", "p": 0.05,
             "arg": 0.008},
            {"site": "frontend.recv", "action": "drop", "p": 0.01},
        ]}),
    )

    def spawn():
        return subprocess.Popen(
            [sys.executable, "-m", "antidote_tpu.console", "serve",
             "--port", "0", "--shards", "2", "--max-dcs", "2",
             "--log-dir", log_dir, "--sync-log", "--wal-segments", "3",
             "--tenant", "aggro:1,max_in_flight=2,max_backlog=4",
             "--tenant", "vip:4"],
            stdout=subprocess.PIPE, stderr=subprocess.DEVNULL,
            env=env, text=True,
        )

    N_AGGRO = 5  # vs max_in_flight=2: the storm MUST trip its quota
    addr = {}
    acked = {"aggro": [0] * N_AGGRO, "vip": 0}
    attempted = {"aggro": [0] * N_AGGRO, "vip": 0}
    aggro_tenant_busy = [0]
    vip_typed: list = []   # MUST stay empty: B's contract
    errs: list = []
    stop = threading.Event()
    #: aggressors run only while set — cleared for the solo-baseline
    #: phase and the kill window
    storm_on = threading.Event()
    acct = threading.Lock()
    lat_solo: list = []
    lat_storm: list = []
    #: where the vip reader records latencies right now (None = not
    #: measuring: warmup, kill window, respawn compile)
    sink: list = [None]

    def dial():
        """Redial the CURRENT address until the server answers (rides
        out both seeded connection drops and the kill window)."""
        deadline = time.monotonic() + 60.0
        while not stop.is_set():
            try:
                return AntidoteClient(addr["host"], addr["port"])
            except (ConnectionError, OSError):
                assert time.monotonic() < deadline, "server never came back"
                time.sleep(0.05)
        return None

    def aggressor(i):
        try:
            c = dial()
            while not stop.is_set():
                if not storm_on.is_set():
                    time.sleep(0.02)
                    continue
                with acct:
                    attempted["aggro"][i] += 1
                try:
                    c.update_objects(
                        [(f"k{i}", "counter_pn", "aggro/b",
                          ("increment", 1))])
                    with acct:
                        acked["aggro"][i] += 1
                except RemoteTenantBusy as e:
                    with acct:
                        aggro_tenant_busy[0] += 1
                    assert e.tenant == "aggro"
                    time.sleep(min(e.retry_after_ms, 100) / 1e3)
                except RemoteBusy as e:
                    time.sleep(min(e.retry_after_ms, 100) / 1e3)
                except (ConnectionError, OSError):
                    try:
                        c.close()
                    except OSError:
                        pass
                    c = dial()  # outcome unknown: attempted, not acked
            if c is not None:
                c.close()
        except Exception as e:  # pragma: no cover - failure detail
            errs.append(f"aggressor{i}: {e!r}")

    def vip_writer():
        """B's own modest write load — part of B's workload in BOTH
        phases, so the baseline is B-alone, not reads-alone."""
        try:
            c = dial()
            while not stop.is_set():
                with acct:
                    attempted["vip"] += 1
                try:
                    c.update_objects(
                        [("vkey", "counter_pn", "vip/b",
                          ("increment", 1))])
                    with acct:
                        acked["vip"] += 1
                except (RemoteTenantBusy, RemoteBusy) as e:
                    vip_typed.append(repr(e))
                except (ConnectionError, OSError):
                    try:
                        c.close()
                    except OSError:
                        pass
                    c = dial()
                time.sleep(0.03)  # modest, well under vip's share
            if c is not None:
                c.close()
        except Exception as e:  # pragma: no cover - failure detail
            errs.append(f"vip_writer: {e!r}")

    def vip_reader():
        try:
            c = dial()
            while not stop.is_set():
                t0 = time.monotonic()
                try:
                    c.read_objects([("vkey", "counter_pn", "vip/b")])
                    out = sink[0]
                    if out is not None:
                        out.append(time.monotonic() - t0)
                except (RemoteTenantBusy, RemoteBusy) as e:
                    vip_typed.append(repr(e))
                except (ConnectionError, OSError):
                    try:
                        c.close()
                    except OSError:
                        pass
                    c = dial()
            if c is not None:
                c.close()
        except Exception as e:  # pragma: no cover - failure detail
            errs.append(f"vip_reader: {e!r}")

    def p99(lats):
        xs = sorted(lats)
        return xs[min(len(xs) - 1, int(len(xs) * 0.99))]

    def wait_for(cond, why, budget=90.0):
        deadline = time.monotonic() + budget
        while not cond():
            assert time.monotonic() < deadline, why()
            assert not errs, errs
            time.sleep(0.05)

    proc = spawn()
    threads = []
    try:
        info = json.loads(proc.stdout.readline())
        assert info["ready"] is True
        assert set(info.get("tenants", ())) >= {"aggro", "vip"}
        addr.update(host=info["host"], port=info["port"])
        c = dial()
        c.update_objects([("vkey", "counter_pn", "vip/b",
                           ("increment", 1))])
        c.close()
        threads = [threading.Thread(target=aggressor, args=(i,))
                   for i in range(N_AGGRO)]
        threads += [threading.Thread(target=vip_writer),
                    threading.Thread(target=vip_reader)]
        for t in threads:
            t.start()
        # -- phase 0: warmup burst.  The first merged commit batches
        # compile their XLA kernels (each width once per process);
        # neither measured phase may bill that one-time cost
        storm_on.set()
        wait_for(lambda: sum(acked["aggro"]) >= 30 and acked["vip"] >= 2,
                 lambda: f"warmup stalled: {acked}")
        # -- phase 1: SOLO baseline — B alone on the warm server,
        # same fault plan
        storm_on.clear()
        time.sleep(0.5)  # drain the aggressors' in-flight tail
        sink[0] = lat_solo
        wait_for(lambda: len(lat_solo) >= 250,
                 lambda: f"solo baseline stalled: {len(lat_solo)}")
        sink[0] = None
        # -- phase 2: the storm — 8 aggressor writers vs vip's lane
        base = sum(acked["aggro"])
        storm_on.set()
        sink[0] = lat_storm
        wait_for(lambda: (sum(acked["aggro"]) >= base + 40
                          and aggro_tenant_busy[0] >= 1
                          and len(lat_storm) >= 250),
                 lambda: (f"storm never saturated: "
                          f"aggro={sum(acked['aggro'])} "
                          f"tenant_busy={aggro_tenant_busy[0]} "
                          f"vip_reads={len(lat_storm)}"))
        # -- phase 3: SIGKILL mid-storm, respawn from the WAL, keep the
        # storm running.  Latency recording pauses for the kill window
        # and the reborn process's one-time compile (restart warmup any
        # single-tenant deployment pays identically), then resumes
        sink[0] = None
        proc.send_signal(signal.SIGKILL)
        proc.wait(timeout=10)
        proc = spawn()
        info = json.loads(proc.stdout.readline())
        assert info["ready"] is True
        addr.update(host=info["host"], port=info["port"])
        a0 = sum(acked["aggro"])
        wait_for(lambda: sum(acked["aggro"]) >= a0 + 10,
                 lambda: (f"storm never resumed post-kill: "
                          f"{sum(acked['aggro'])} (was {a0})"))
        reads0 = len(lat_storm)
        sink[0] = lat_storm
        wait_for(lambda: (sum(acked["aggro"]) >= a0 + 30
                          and len(lat_storm) >= reads0 + 100),
                 lambda: (f"post-kill storm stalled: "
                          f"aggro={sum(acked['aggro'])} "
                          f"vip_reads={len(lat_storm)}"))
        stop.set()
        for t in threads:
            t.join(timeout=30)
        threads = []
        assert not errs, errs
        # -- the isolation guarantee -----------------------------------
        # vip's p99 under the storm within 3x its solo baseline, with a
        # 14 ms noise floor: the XLA CPU backend runs device work
        # serially, so a read gather that arrives while ANY commit
        # group occupies the device waits out that computation —
        # a ~10-30 ms floor on a shared 2-core box that exists even
        # with a single tenant committing its own writes.  A genuine
        # lane leak parks reads behind the aggressor's *backlog*
        # (100 ms+ at these queue depths); the 3x-over-floor bound
        # separates the two cleanly.
        solo, storm = p99(lat_solo), p99(lat_storm)
        assert storm <= 3.0 * max(solo, 0.014), (
            f"noisy neighbor leaked: solo p99={solo * 1e3:.2f}ms "
            f"storm p99={storm * 1e3:.2f}ms")
        # B saw ZERO typed refusals — every shed landed on A's quota
        assert vip_typed == [], vip_typed
        assert aggro_tenant_busy[0] >= 1  # the storm really saturated
    finally:
        stop.set()
        for t in threads:
            t.join(timeout=30)
        if proc.poll() is None:
            proc.kill()
            proc.wait(timeout=10)
    # -- per-tenant durability: acked ⊆ recovered, double recovery
    # byte-identical (the kill must not have eaten either tenant's acks)
    rcfg = AntidoteConfig(n_shards=2, max_dcs=2, wal_segments=3)
    objs = ([(f"k{i}", "counter_pn", "aggro/b") for i in range(N_AGGRO)]
            + [("vkey", "counter_pn", "vip/b")])
    recovered = []
    for _ in range(2):
        node = AntidoteNode(rcfg, log_dir=log_dir, recover=True)
        vals, _ = node.read_objects(objs)
        recovered.append({
            "vals": vals,
            "op_ids": node.store.log.op_ids.tolist(),
            "seqs": node.store.log.seqs.tolist(),
        })
        node.store.log.close()
    assert recovered[0] == recovered[1], "recoveries diverged"
    vals = recovered[0]["vals"]
    for i in range(N_AGGRO):
        assert acked["aggro"][i] <= vals[i] <= attempted["aggro"][i], (
            f"aggro k{i}: acked={acked['aggro'][i]} recovered={vals[i]} "
            f"attempted={attempted['aggro'][i]}")
    # vip's seed write rides the same key: +1 on both bounds
    assert acked["vip"] + 1 <= vals[-1] <= attempted["vip"] + 1, (
        f"vip: acked={acked['vip']} recovered={vals[-1]} "
        f"attempted={attempted['vip']}")
