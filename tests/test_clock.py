import jax.numpy as jnp
import numpy as np

from antidote_tpu.clock import vector as vc
from antidote_tpu.clock import orddict
import pytest

pytestmark = pytest.mark.smoke


def c(*xs):
    return jnp.asarray(xs, jnp.int32)


def test_partial_order():
    a, b = c(1, 2, 3), c(2, 2, 3)
    assert bool(vc.le(a, b))
    assert not bool(vc.le(b, a))
    assert bool(vc.lt(a, b))
    assert not bool(vc.lt(a, a))
    assert bool(vc.eq(a, a))


def test_concurrent():
    a, b = c(2, 0, 0), c(0, 3, 0)
    assert bool(vc.concurrent(a, b))
    assert not bool(vc.concurrent(a, a))
    assert not bool(vc.concurrent(a, c(2, 1, 0)))


def test_merge_min():
    a, b = c(1, 5, 2), c(3, 1, 2)
    assert (np.asarray(vc.merge(a, b)) == [3, 5, 2]).all()
    assert (np.asarray(vc.vmin(a, b)) == [1, 1, 2]).all()


def test_dominates_ignoring():
    # inter_dc_dep_vnode gate: local VC must dominate with origin zeroed
    local = c(5, 0, 2)
    snap = c(5, 9, 1)
    assert bool(vc.dominates_ignoring(local, snap, 1))
    assert not bool(vc.dominates_ignoring(local, snap, 0))


def test_broadcast_batched():
    batch = jnp.stack([c(1, 1, 1), c(9, 9, 9)])
    r = vc.le(batch, c(2, 2, 2))
    assert list(np.asarray(r)) == [True, False]


def test_get_smaller_picks_newest_dominated():
    # versions: v0 at [1,0,0] seq 1; v1 at [2,0,0] seq 2
    snap_vc = jnp.asarray([[[1, 0, 0], [2, 0, 0]]], jnp.int32)
    snap_seq = jnp.asarray([[1, 2]], jnp.int64)
    idx, found = orddict.get_smaller(snap_vc, snap_seq, c(2, 5, 5)[None])
    assert bool(found[0]) and int(idx[0]) == 1
    idx, found = orddict.get_smaller(snap_vc, snap_seq, c(1, 0, 0)[None])
    assert bool(found[0]) and int(idx[0]) == 0
    idx, found = orddict.get_smaller(snap_vc, snap_seq, c(0, 9, 9)[None])
    assert not bool(found[0])


def test_get_smaller_skips_empty_slots():
    snap_vc = jnp.asarray([[[0, 0, 0], [2, 0, 0]]], jnp.int32)
    snap_seq = jnp.asarray([[0, 5]], jnp.int64)  # slot 0 empty
    idx, found = orddict.get_smaller(snap_vc, snap_seq, c(9, 9, 9)[None])
    assert bool(found[0]) and int(idx[0]) == 1
    # read below the only version: the zero-clock empty slot must NOT match
    idx, found = orddict.get_smaller(snap_vc, snap_seq, c(1, 0, 0)[None])
    assert not bool(found[0])
