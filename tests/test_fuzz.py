"""Randomized multi-DC convergence fuzzing.

The reference's CT suites drive fixed scenarios; this adds seeded random
op tapes over a 3-DC mesh with random pump interleavings and random
message loss (healed by the opid-gap catch-up protocol), asserting:

  * CONVERGENCE: after quiescence every DC reads identical values at
    the global max clock;
  * counter oracle: totals equal the sum of all increments everywhere;
  * set bounds: an element added somewhere and never removed anywhere
    is present; an element never added is absent;
  * lww registers: converged to SOME assigned value.
"""

import numpy as np
import pytest

from antidote_tpu.api import AntidoteNode
from antidote_tpu.config import AntidoteConfig
from antidote_tpu.interdc import DCReplica, LoopbackHub


def _cfg():
    return AntidoteConfig(n_shards=4, max_dcs=3, ops_per_key=8,
                          snap_versions=2, set_slots=16,
                          keys_per_table=64, batch_buckets=(16, 64))


@pytest.mark.parametrize("seed,lossy", [(1, False), (2, False),
                                        (3, True), (4, True),
                                        (5, True), (6, True)])
def test_random_ops_converge(seed, lossy):
    rng = np.random.default_rng(seed)
    hub = LoopbackHub()
    nodes = [AntidoteNode(_cfg(), dc_id=i) for i in range(3)]
    reps = [DCReplica(n, hub, f"dc{i}") for i, n in enumerate(nodes)]
    DCReplica.connect_all(reps)
    for r in reps:
        # fast re-ping so lossy trials heal within the quiesce loop (the
        # liveness re-send is wall-clock-driven, 1 s in production)
        r.HEARTBEAT_INTERVAL_S = 0.05
    counters = [f"c{i}" for i in range(4)]
    sets = [f"s{i}" for i in range(4)]
    regs = [f"r{i}" for i in range(2)]
    inc_total = {k: 0 for k in counters}
    added, removed, assigned = set(), set(), set()

    for step in range(120):
        dc = int(rng.integers(3))
        node = nodes[dc]
        kind = rng.random()
        if kind < 0.4:
            k = counters[int(rng.integers(len(counters)))]
            n = int(rng.integers(1, 9))
            node.update_objects([(k, "counter_pn", "b", ("increment", n))])
            inc_total[k] += n
        elif kind < 0.7:
            k = sets[int(rng.integers(len(sets)))]
            e = f"e{int(rng.integers(12))}"
            node.update_objects([(k, "set_aw", "b", ("add", e))])
            added.add((k, e))
        elif kind < 0.85:
            k = sets[int(rng.integers(len(sets)))]
            e = f"e{int(rng.integers(12))}"
            node.update_objects([(k, "set_aw", "b", ("remove", e))])
            removed.add((k, e))
        else:
            k = regs[int(rng.integers(len(regs)))]
            v = f"v{step}"
            node.update_objects([(k, "register_lww", "b", ("assign", v))])
            assigned.add((k, v))
        if lossy and rng.random() < 0.15:
            # drop the next message on a random directed link; the
            # opid-gap catch-up must heal it
            a, b = rng.choice(3, size=2, replace=False)
            hub.drop_next(int(a), int(b), 1)
        if rng.random() < 0.3:
            hub.pump()

    # quiesce: pump until every DC's clock converged (lost FINAL
    # messages heal via the wall-clock re-ping, so pace the loop past
    # the interval)
    import time as _t

    for _ in range(120):
        hub.pump()
        clocks = [n.store.dc_max_vc() for n in nodes]
        stables = [n.store.stable_vc() for n in nodes]
        tgt = np.max(np.stack(clocks), axis=0)
        if all((c == tgt).all() for c in clocks) and \
                all((s >= tgt).all() for s in stables):
            break
        _t.sleep(0.06)
    else:
        raise AssertionError(
            f"never converged: clocks={clocks} stables={stables}")
    target = np.max(np.stack([n.store.dc_max_vc() for n in nodes]), axis=0)
    objs = ([(k, "counter_pn", "b") for k in counters]
            + [(k, "set_aw", "b") for k in sets]
            + [(k, "register_lww", "b") for k in regs])
    reads = []
    for n in nodes:
        vals, _ = n.read_objects(objs, clock=target)
        reads.append(vals)
    # convergence
    assert reads[0] == reads[1] == reads[2], (seed, lossy, reads)
    vals = reads[0]
    # counter oracle
    for j, k in enumerate(counters):
        assert vals[j] == inc_total[k], (k, vals[j], inc_total[k])
    # set bounds
    off = len(counters)
    for j, k in enumerate(sets):
        got = set(vals[off + j])
        must = {e for (kk, e) in added
                if kk == k and (kk, e) not in removed}
        assert must <= got, (k, "missing", must - got)
        never_added = got - {e for (kk, e) in added if kk == k}
        assert not never_added, (k, "phantom", never_added)
    # registers: some assigned value (or empty if never assigned)
    off = len(counters) + len(sets)
    for j, k in enumerate(regs):
        v = vals[off + j]
        opts = {vv for (kk, vv) in assigned if kk == k}
        if opts:
            assert v in opts, (k, v, opts)


@pytest.mark.parametrize("seed", [11, 12, 13])
def test_random_ops_survive_crash_recovery(seed, tmp_path):
    """Seeded random single-node tape with a crash (WAL-only restart)
    mid-tape: the recovered node must answer every key exactly as the
    pre-crash node would, and keep accepting ops afterwards."""
    rng = np.random.default_rng(seed)
    cfg = _cfg()
    log_dir = str(tmp_path / "wal")
    node = AntidoteNode(cfg, log_dir=log_dir)
    model_cnt = {}
    model_set_add = {}

    def random_op(n):
        kind = rng.random()
        if kind < 0.45:
            k = f"c{int(rng.integers(5))}"
            amt = int(rng.integers(1, 9))
            n.update_objects([(k, "counter_pn", "b", ("increment", amt))])
            model_cnt[k] = model_cnt.get(k, 0) + amt
        elif kind < 0.8:
            k = f"s{int(rng.integers(5))}"
            e = f"e{int(rng.integers(10))}"
            n.update_objects([(k, "set_aw", "b", ("add", e))])
            model_set_add.setdefault(k, set()).add(e)
        else:
            k = f"s{int(rng.integers(5))}"
            e = f"e{int(rng.integers(10))}"
            n.update_objects([(k, "set_aw", "b", ("remove", e))])
            model_set_add.setdefault(k, set()).discard(e)

    for _ in range(60):
        random_op(node)
    node.store.log.close()  # crash

    node2 = AntidoteNode(cfg, log_dir=log_dir, recover=True)
    objs = ([(k, "counter_pn", "b") for k in sorted(model_cnt)]
            + [(k, "set_aw", "b") for k in sorted(model_set_add)])
    vals, _ = node2.read_objects(objs)
    i = 0
    for k in sorted(model_cnt):
        assert vals[i] == model_cnt[k], (k, vals[i], model_cnt[k])
        i += 1
    for k in sorted(model_set_add):
        assert set(vals[i]) == model_set_add.get(k, set()), (k, vals[i])
        i += 1
    # and the recovered node keeps working (chains continue)
    for _ in range(20):
        random_op(node2)
    vals, _ = node2.read_objects(objs)
    i = 0
    for k in sorted(model_cnt):
        assert vals[i] == model_cnt[k]
        i += 1


@pytest.mark.parametrize("seed", [21, 22])
def test_random_ops_cluster_coordinators(seed):
    """Seeded random tape against a 2-member DC with the coordinator
    chosen at random per op (sequencer chains, owner routing, RYW txns):
    final reads agree between coordinators and match the oracle."""
    from antidote_tpu.cluster import ClusterMember, ClusterNode

    rng = np.random.default_rng(seed)
    cfg = _cfg()
    m0 = ClusterMember(cfg, dc_id=0, member_id=0, n_members=2)
    m1 = ClusterMember(cfg, dc_id=0, member_id=1, n_members=2)
    m0.connect(1, *m1.address)
    m1.connect(0, *m0.address)
    coords = [ClusterNode(m0), ClusterNode(m1)]
    model_cnt = {}
    model_set = {}

    def commit_retrying(c, updates, tries=10):
        # a fresh coordinator's snapshot may trail another coordinator's
        # just-committed ts by the seq-cache staleness window: first-
        # committer-wins aborts it, the client retries (the reference's
        # clients do the same on {aborted, ...})
        from antidote_tpu.txn.manager import AbortError as _Abort

        for _ in range(tries):
            try:
                c.update_objects(updates)
                return
            except _Abort:
                import time as _t

                _t.sleep(0.02)
        raise AssertionError(f"aborted {tries} times: {updates}")

    try:
        for step in range(60):
            c = coords[int(rng.integers(2))]
            kind = rng.random()
            if kind < 0.4:
                k = f"c{int(rng.integers(4))}"
                amt = int(rng.integers(1, 9))
                commit_retrying(c, [(k, "counter_pn", "b",
                                     ("increment", amt))])
                model_cnt[k] = model_cnt.get(k, 0) + amt
            elif kind < 0.7:
                k = f"s{int(rng.integers(4))}"
                e = f"e{int(rng.integers(8))}"
                commit_retrying(c, [(k, "set_aw", "b", ("add", e))])
                model_set.setdefault(k, set()).add(e)
            elif kind < 0.85:
                k = f"s{int(rng.integers(4))}"
                e = f"e{int(rng.integers(8))}"
                commit_retrying(c, [(k, "set_aw", "b", ("remove", e))])
                model_set.setdefault(k, set()).discard(e)
            else:
                # interactive multi-key txn with RYW check (retried on
                # cert aborts like any interactive client)
                from antidote_tpu.txn.manager import AbortError as _Abort

                k1, k2 = f"c{int(rng.integers(4))}", f"s{int(rng.integers(4))}"
                for _ in range(10):
                    txn = c.start_transaction()
                    try:
                        before = c.read_objects([(k1, "counter_pn", "b")],
                                                txn)[0]
                        c.update_objects(
                            [(k1, "counter_pn", "b", ("increment", 2)),
                             (k2, "set_aw", "b", ("add", "T"))], txn)
                        v = c.read_objects([(k1, "counter_pn", "b")], txn)
                        # RYW relative to the txn's own snapshot (the
                        # snapshot may trail other coordinators' commits
                        # within the cache window; cert settles that)
                        assert v[0] == before + 2
                        c.commit_transaction(txn)
                        break
                    except _Abort:
                        import time as _t

                        _t.sleep(0.02)
                else:
                    raise AssertionError("interactive txn aborted 10x")
                model_cnt[k1] = model_cnt.get(k1, 0) + 2
                model_set.setdefault(k2, set()).add("T")
        objs = ([(k, "counter_pn", "b") for k in sorted(model_cnt)]
                + [(k, "set_aw", "b") for k in sorted(model_set)])
        reads = []
        for c in coords:
            vals, _ = c.read_objects(objs)
            reads.append(vals)
        assert reads[0] == reads[1], (seed, reads)
        i = 0
        for k in sorted(model_cnt):
            assert reads[0][i] == model_cnt[k], (k, reads[0][i])
            i += 1
        for k in sorted(model_set):
            assert set(reads[0][i]) == model_set[k], (k, reads[0][i])
            i += 1
    finally:
        m0.close(), m1.close()
