"""Stable metadata store: durable KV, DC broadcast, merge-broadcast,
env mirroring, replicated runtime flags — mirroring
stable_meta_data_server + dc_meta_data_utilities (SURVEY §2.6)."""

import os


from antidote_tpu.api import AntidoteNode
from antidote_tpu.config import AntidoteConfig
from antidote_tpu.meta import MetaCluster, MetaDataStore
import pytest

pytestmark = pytest.mark.smoke


def test_local_put_get_and_persistence(tmp_path):
    p = str(tmp_path / "meta.bin")
    s = MetaDataStore(path=p)
    s.put("dc_id", 3)
    s.put("descriptors", [[0, "dc0", 8], [1, "dc1", 8]])
    # restart: reload from disk (recover_meta_data_on_start)
    s2 = MetaDataStore(path=p)
    assert s2.get("dc_id") == 3
    assert s2.get("descriptors") == [[0, "dc0", 8], [1, "dc1", 8]]


def test_atomic_persist_no_torn_file(tmp_path):
    p = str(tmp_path / "meta.bin")
    s = MetaDataStore(path=p)
    for i in range(50):
        s.put(f"k{i}", "x" * 100)
    assert MetaDataStore(path=p).get("k49") == "x" * 100
    assert not os.path.exists(p + ".tmp")


def test_cluster_broadcast_reaches_all_nodes(tmp_path):
    cluster = MetaCluster()
    stores = [MetaDataStore(path=str(tmp_path / f"n{i}.bin"), node_id=i)
              for i in range(3)]
    for s in stores:
        cluster.join(s)
    stores[0].put("flag", True)
    assert all(s.get("flag") is True for s in stores)
    # survives each node's restart independently
    assert MetaDataStore(path=str(tmp_path / "n2.bin")).get("flag") is True


def test_merge_broadcast():
    cluster = MetaCluster()
    stores = [MetaDataStore(node_id=i) for i in range(2)]
    for s in stores:
        cluster.join(s)
    merge = lambda new, cur: sorted(set(cur) | {new})
    out = stores[0].put_merge("members", 5, merge, default=[])
    assert out == [5]
    out = stores[1].put_merge("members", 2, merge, default=[])
    assert out == [2, 5]
    assert stores[0].get("members") == [2, 5]


def test_late_joiner_catches_up():
    cluster = MetaCluster()
    a = MetaDataStore(node_id=0)
    cluster.join(a)
    a.put("seed", 42)
    b = MetaDataStore(node_id=1)
    cluster.join(b)
    assert b.get("seed") == 42


def test_env_mirroring(monkeypatch):
    monkeypatch.setenv("ANTIDOTE_TXN_CERT", "false")
    s = MetaDataStore()
    assert s.get_env("txn_cert", True) is False
    # first lookup seeds the replicated table: later env changes don't flip it
    monkeypatch.setenv("ANTIDOTE_TXN_CERT", "true")
    assert s.get_env("txn_cert", True) is False


def test_env_default_and_parse(monkeypatch):
    monkeypatch.delenv("ANTIDOTE_MISSING", raising=False)
    s = MetaDataStore()
    assert s.get_env("missing", 7) == 7
    monkeypatch.setenv("ANTIDOTE_NUM", "123")
    assert s.get_env("num") == 123


def test_sync_log_flip_reaches_other_live_nodes(tmp_path):
    """Flipping the flag on one node must apply to every member node's
    RUNNING log via the meta watcher, not only at restart."""
    cfg = AntidoteConfig(
        n_shards=2, max_dcs=2, ops_per_key=4, snap_versions=2,
        set_slots=4, keys_per_table=16, batch_buckets=(8,),
    )
    cluster = MetaCluster()
    metas = [MetaDataStore(node_id=i) for i in range(2)]
    nodes = [
        AntidoteNode(cfg, log_dir=str(tmp_path / f"wal{i}"), meta=metas[i])
        for i in range(2)
    ]
    for m in metas:
        cluster.join(m)
    nodes[0].set_sync_log(True)
    assert all(w.sync_on_commit for w in nodes[1].store.log.wals)
    nodes[1].set_sync_log(False)
    assert not any(w.sync_on_commit for w in nodes[0].store.log.wals)


def test_sync_log_replicated_flag(tmp_path):
    cfg = AntidoteConfig(
        n_shards=2, max_dcs=2, ops_per_key=4, snap_versions=2,
        set_slots=4, keys_per_table=16, batch_buckets=(8,),
    )
    node = AntidoteNode(cfg, log_dir=str(tmp_path / "wal"))
    assert node.store.log.wals[0].sync_on_commit is False
    node.set_sync_log(True)
    assert node.meta.get_env("sync_log") is True
    assert all(w.sync_on_commit for w in node.store.log.wals)
    # committing with sync on still works end-to-end
    node.update_objects([("k", "counter_pn", "b", ("increment", 1))])
    vals, _ = node.read_objects([("k", "counter_pn", "b")])
    assert vals[0] == 1
