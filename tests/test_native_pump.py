"""Native inter-DC receive pump (interdc/cpp/pump.cc) edge cases.

The integration suites (test_tcp_interdc, test_dc_management) already
exercise the happy path end to end; these pin the contract details a
transport must not regress on: partial-frame reassembly, multi-frame
segments, EOF tail delivery, batch drains, closed-pump behavior, and
the Python-reader fallback toggle.
"""

import socket
import struct
import time

import pytest

from antidote_tpu.interdc.native_pump import NativePump

pytestmark = pytest.mark.smoke

_HDR = struct.Struct(">IB")


def _frame(kind: int, payload: bytes) -> bytes:
    return _HDR.pack(len(payload) + 1, kind) + payload


@pytest.fixture
def pump():
    p = NativePump.create()
    if p is None:
        pytest.skip("native pump unavailable (no g++/epoll)")
    yield p
    p.close()


def test_reassembly_and_batching(pump):
    a, b = socket.socketpair()
    pump.add(b.detach(), tag=3)
    # three frames: one split across sends, two glued in one segment
    f1, f2, f3 = (_frame(2, b"x" * 10), _frame(2, b"y" * 1000),
                  _frame(7, b"z"))
    a.sendall(f1[:7])
    time.sleep(0.02)
    a.sendall(f1[7:] + f2 + f3)
    got = []
    deadline = time.time() + 5
    while len(got) < 3 and time.time() < deadline:
        got.extend(pump.take_batch(200))
    assert [(t, k, len(p)) for t, k, p in got] == [
        (3, 2, 10), (3, 2, 1000), (3, 7, 1)]
    a.close()


def test_eof_tail_delivered(pump):
    """Frames sent immediately before the peer closes must still be
    delivered (the stream's last commits ride exactly there) — and the
    close itself must surface as the kind-0 drop SENTINEL (PR 2's
    resubscribe hook), strictly AFTER the tail frames: a sentinel
    overtaking data would make Python resubscribe while the last
    commits die in the buffer."""
    a, b = socket.socketpair()
    pump.add(b.detach(), tag=9)
    a.sendall(_frame(2, b"final-1") + _frame(2, b"final-2"))
    a.close()  # EOF races the reads
    got = []
    deadline = time.time() + 5
    while time.time() < deadline and not any(k == 0 for _, k, _ in got):
        got.extend(pump.take_batch(200))
    assert [p for _, k, p in got if k != 0] == [b"final-1", b"final-2"]
    # exactly one drop sentinel, carrying the stream's tag, at the end
    assert [(t, k, p) for t, k, p in got if k == 0] == [(9, 0, b"")]
    assert got[-1][1] == 0


def test_large_frame_grows_buffer(pump):
    a, b = socket.socketpair()
    pump.add(b.detach(), tag=1)
    big = b"B" * (2 << 20)  # larger than the 1 MiB scratch buffer
    a.sendall(_frame(2, big))
    got = []
    deadline = time.time() + 10
    while not got and time.time() < deadline:
        got.extend(pump.take_batch(200))
    assert got[0][2] == big
    a.close()


def test_closed_pump_is_inert():
    p = NativePump.create()
    if p is None:
        pytest.skip("native pump unavailable")
    a, b = socket.socketpair()
    p.close()
    p.add(b.detach(), tag=1)  # fd closed, not leaked
    assert p.take(10) is None
    assert p.take_batch(10) == []
    assert p.queued() == 0
    p.close()  # idempotent
    a.close()


def test_env_toggle_forces_fallback(monkeypatch):
    monkeypatch.setenv("ANTIDOTE_NATIVE_PUMP", "off")
    assert NativePump.create() is None
