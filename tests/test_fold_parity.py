"""Fold-strategy parity (ISSUE 15): every materializer fold strategy —
serial scan, associative delta fold, chunked long fold, mesh-sharded
sequence fold, and the Pallas set_aw kernel — must produce byte-identical
states to the serial `fold.fold_key` / `fold.fold_batch` oracle, on the
strategy's declared domain:

* counter/flags deltas are exact from ARBITRARY bases;
* set deltas are exact from the BOTTOM base (``assoc_bottom_only``), and
  set_aw additionally only for all-adds logs (``assoc_add_only``);
* chunked/sharded set delta MERGES are exact when each chunk touches at
  most ``set_slots`` distinct handles (the store's slot-promotion
  invariant), and committed ops carry a positive own-lane commit dot;
* the Pallas set_aw kernel has no such restrictions (it replays the op
  ring in order, like the oracle) — removes and arbitrary bases included.

Also covers the live dispatch: TypedTable's serving-path strategy pick,
KVStore's over-ring replay ladder, and the fold metrics both feed.
"""

import dataclasses

import jax.numpy as jnp
import numpy as np
import pytest

from antidote_tpu.config import AntidoteConfig
from antidote_tpu.crdt import get_type
from antidote_tpu.materializer import fold as fold_mod
from antidote_tpu.materializer import longlog
from antidote_tpu.materializer import pallas_kernels as pk


def _mk_cfg(**kw):
    base = dict(
        n_shards=2, max_dcs=3, ops_per_key=8, snap_versions=2,
        set_slots=8, mv_slots=4, rga_slots=16, keys_per_table=16,
        batch_buckets=(16, 64),
    )
    base.update(kw)
    return AntidoteConfig(**base)


def _bottom(ty, cfg):
    return {
        f: jnp.zeros(s, dt) for f, (s, dt) in ty.state_spec(cfg).items()
    }


def _rand_set_ops(rng, l, d, n_handles, add_only):
    """One key's set op log: committed ops always carry a positive dot on
    their origin lane (the delta-merge exactness precondition)."""
    handles = rng.integers(1, n_handles + 1, size=(l,)).astype(np.int64)
    handles *= 0x1_0000_0003  # exercise both i32 planes of the i64 split
    is_rm = (np.zeros((l,), np.int32) if add_only
             else rng.integers(0, 2, size=(l,)).astype(np.int32))
    obs = rng.integers(0, 5, size=(l, d)).astype(np.int32)
    ops_a = handles[..., None]
    ops_b = np.concatenate([is_rm[..., None], obs], axis=-1).astype(np.int32)
    ops_vc = rng.integers(0, 8, size=(l, d)).astype(np.int32)
    ops_origin = rng.integers(0, d, size=(l,)).astype(np.int32)
    ops_vc[np.arange(l), ops_origin] = rng.integers(1, 9, size=(l,))
    base_vc = np.zeros((d,), np.int32)
    read_vc = rng.integers(0, 8, size=(d,)).astype(np.int32)
    return ops_a, ops_b, ops_vc, ops_origin, base_vc, read_vc


def _assert_states_equal(ref, got, msg):
    for f in ref:
        np.testing.assert_array_equal(
            np.asarray(ref[f]), np.asarray(got[f]), err_msg=f"{msg}:{f}")


# ---------------------------------------------------------------------------
# Pallas set_aw kernel vs fold_batch oracle
# ---------------------------------------------------------------------------

def test_pallas_set_aw_fold_matches_oracle():
    """Both kernel entries (host + trace-safe local), random op rings with
    removes and ARBITRARY non-bottom bases, n_ops edges 0 and full ring."""
    cfg = _mk_cfg(n_shards=1)
    ty = get_type("set_aw")
    b, k, e, d = 16, cfg.ops_per_key, cfg.set_slots, cfg.max_dcs
    rng = np.random.default_rng(7)
    for trial in range(2):
        handles = rng.integers(1, 6, size=(b, k)).astype(np.int64)
        handles *= 0x1_0000_0003
        is_rm = rng.integers(0, 2, size=(b, k)).astype(np.int32)
        obs = rng.integers(0, 5, size=(b, k, d)).astype(np.int32)
        ops_a = handles[..., None]
        ops_b = np.concatenate([is_rm[..., None], obs], -1).astype(np.int32)
        ops_vc = rng.integers(0, 8, size=(b, k, d)).astype(np.int32)
        ops_origin = rng.integers(0, d, size=(b, k)).astype(np.int32)
        n_ops = rng.integers(0, k + 1, size=(b,)).astype(np.int32)
        n_ops[0], n_ops[1] = 0, k
        base_vc = rng.integers(0, 4, size=(b, d)).astype(np.int32)
        read_vc = np.maximum(
            base_vc, rng.integers(0, 8, size=(b, d))).astype(np.int32)
        state = {
            "elems": jnp.asarray(
                rng.integers(0, 4, size=(b, e)).astype(np.int64)
                * 0x1_0000_0003),
            "addvc": jnp.asarray(
                rng.integers(0, 4, size=(b, e, d)).astype(np.int32)),
            "rmvc": jnp.asarray(
                rng.integers(0, 4, size=(b, e, d)).astype(np.int32)),
            "ovf": jnp.asarray(rng.integers(0, 3, size=(b,)).astype(np.int32)),
        }
        ref_state, ref_applied = fold_mod.fold_batch(
            ty, cfg, state, jnp.asarray(ops_a), jnp.asarray(ops_b),
            jnp.asarray(ops_vc), jnp.asarray(ops_origin),
            jnp.asarray(n_ops), jnp.asarray(base_vc), jnp.asarray(read_vc))
        got_state, got_applied = pk.set_aw_fold(
            state, ops_a, ops_b, ops_vc, ops_origin, n_ops, base_vc,
            read_vc, block=8)
        _assert_states_equal(ref_state, got_state, f"trial{trial}")
        np.testing.assert_array_equal(
            np.asarray(ref_applied), np.asarray(got_applied))
        got2, app2 = pk.set_aw_fold_local(
            state, jnp.asarray(ops_a), jnp.asarray(ops_b),
            jnp.asarray(ops_vc), jnp.asarray(ops_origin),
            jnp.asarray(n_ops), jnp.asarray(base_vc),
            jnp.asarray(read_vc), block=8)
        _assert_states_equal(ref_state, got2, f"trial{trial} local")
        np.testing.assert_array_equal(
            np.asarray(ref_applied), np.asarray(app2))


# ---------------------------------------------------------------------------
# set delta folds (assoc_fold / delta_merge) vs fold_key oracle
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("tyname,add_only,n_handles", [
    ("set_aw", True, 6),    # within capacity
    ("set_aw", True, 20),   # single-window capacity overflow (still exact)
    ("set_go", True, 6),
    ("set_go", False, 6),   # set_go deltas are exact with removes too
    ("set_go", True, 20),
])
def test_set_assoc_fold_matches_serial(tyname, add_only, n_handles):
    cfg = _mk_cfg(n_shards=1)
    ty = get_type(tyname)
    assert ty.supports_assoc and ty.assoc_bottom_only
    d = cfg.max_dcs
    rng = np.random.default_rng(11 + n_handles)
    for l, n_ops in ((64, 57), (32, 0), (32, 32)):
        ops_a, ops_b, ops_vc, ops_origin, base_vc, read_vc = _rand_set_ops(
            rng, l, d, n_handles, add_only)
        s0 = _bottom(ty, cfg)
        ref_s, ref_n = fold_mod.fold_key(
            ty, cfg, s0, jnp.asarray(ops_a), jnp.asarray(ops_b),
            jnp.asarray(ops_vc), jnp.asarray(ops_origin), jnp.int32(n_ops),
            jnp.asarray(base_vc), jnp.asarray(read_vc))
        got_s, got_n = longlog.assoc_fold(
            ty, cfg, s0, jnp.asarray(ops_a), jnp.asarray(ops_b),
            jnp.asarray(ops_vc), jnp.asarray(ops_origin), jnp.int32(n_ops),
            jnp.asarray(base_vc), jnp.asarray(read_vc))
        _assert_states_equal(ref_s, got_s, f"{tyname} l={l}")
        assert int(got_n) == int(ref_n)
        if n_handles > cfg.set_slots:
            continue  # merge exactness needs per-chunk distinct <= slots
        mask = longlog.include_mask(
            jnp.asarray(ops_vc), jnp.int32(n_ops),
            jnp.asarray(base_vc), jnp.asarray(read_vc))
        h = l // 2
        d1 = ty.delta_of_ops(
            cfg, jnp.asarray(ops_a[:h]), jnp.asarray(ops_b[:h]),
            jnp.asarray(ops_vc[:h]), jnp.asarray(ops_origin[:h]), mask[:h])
        d2 = ty.delta_of_ops(
            cfg, jnp.asarray(ops_a[h:]), jnp.asarray(ops_b[h:]),
            jnp.asarray(ops_vc[h:]), jnp.asarray(ops_origin[h:]), mask[h:])
        merged = ty.delta_apply(s0, ty.delta_merge(d1, d2))
        _assert_states_equal(ref_s, merged, f"{tyname} merged l={l}")


# ---------------------------------------------------------------------------
# mesh-sharded sequence fold vs single-device oracle
# ---------------------------------------------------------------------------

def test_sharded_set_aw_fold_matches_single_device():
    from antidote_tpu.parallel import make_mesh

    cfg = _mk_cfg(n_shards=1)
    ty = get_type("set_aw")
    mesh = make_mesh(8)
    d = cfg.max_dcs
    rng = np.random.default_rng(5)
    l = 64  # multiple of 8 devices; 6 handles <= set_slots per chunk
    ops_a, ops_b, ops_vc, ops_origin, base_vc, read_vc = _rand_set_ops(
        rng, l, d, 6, add_only=True)
    n_ops = 57
    s0 = _bottom(ty, cfg)
    ref_s, ref_n = fold_mod.fold_key(
        ty, cfg, s0, jnp.asarray(ops_a), jnp.asarray(ops_b),
        jnp.asarray(ops_vc), jnp.asarray(ops_origin), jnp.int32(n_ops),
        jnp.asarray(base_vc), jnp.asarray(read_vc))
    fn = longlog.sharded_assoc_fold_fn(ty, cfg, mesh)
    got_s, got_n = fn(s0, ops_a, ops_b, ops_vc, ops_origin, n_ops,
                      jnp.asarray(base_vc), jnp.asarray(read_vc))
    _assert_states_equal(ref_s, got_s, "sharded set_aw")
    assert int(got_n) == int(ref_n)


def test_mesh_fold_giant_key_pads_and_matches():
    """fold_giant_key pads a non-power-of-two log up to a device multiple
    (pad slots land beyond n_ops / inside base, so the mask drops them)
    and must still equal the serial fold; works for counters too."""
    from antidote_tpu.parallel import MeshServingPlane

    cfg = _mk_cfg(n_shards=8)
    plane = MeshServingPlane(cfg, 8)
    d = cfg.max_dcs
    rng = np.random.default_rng(9)
    cases = []
    ty_set = get_type("set_aw")
    a, b, v, o, bvc, rvc = _rand_set_ops(rng, 37, d, 6, add_only=True)
    cases.append((ty_set, a, b, v, o, 33, bvc, rvc))
    ty_cnt = get_type("counter_pn")
    l = 50
    ca = rng.integers(-5, 6, size=(l, 1)).astype(np.int64)
    cb = np.zeros((l, 1), np.int32)
    cv = rng.integers(0, 10, size=(l, d)).astype(np.int32)
    co = rng.integers(0, d, size=(l,)).astype(np.int32)
    cases.append((ty_cnt, ca, cb, cv, co, 47,
                  np.asarray([1, 0, 1], np.int32),
                  np.asarray([9, 9, 9], np.int32)))
    for ty, a, b, v, o, n_ops, bvc, rvc in cases:
        s0 = _bottom(ty, cfg)
        ref_s, ref_n = fold_mod.fold_key(
            ty, cfg, s0, jnp.asarray(a), jnp.asarray(b), jnp.asarray(v),
            jnp.asarray(o), jnp.int32(n_ops), jnp.asarray(bvc),
            jnp.asarray(rvc))
        got_s, got_n = plane.fold_giant_key(
            ty, cfg, s0, a, b, v, o, np.int32(n_ops), bvc, rvc)
        _assert_states_equal(ref_s, got_s, f"giant {ty.name}")
        assert int(got_n) == int(ref_n)
    assert plane.giant_folds == len(cases)


# ---------------------------------------------------------------------------
# live serving dispatch: strategy pick + byte parity + tallies
# ---------------------------------------------------------------------------

def _populate_set_table(table, n_keys, d):
    clock = 0
    first = {}
    for r in range(n_keys):
        for j in range(3):
            clock += 1
            vc = np.zeros(d, np.int32)
            vc[0] = clock
            elem = 100 * (r + 1) + j
            first.setdefault(r, (elem, clock))
            table.append(
                np.asarray([r % table.n_shards]), np.asarray([r]),
                np.asarray([[elem]], np.int64),
                np.zeros((1, 1 + d), np.int32), vc[None, :],
                np.asarray([0], np.int32))
    mid = clock
    for r in range(0, n_keys, 2):
        elem, add_t = first[r]
        clock += 1
        vc = np.zeros(d, np.int32)
        vc[0] = clock
        b = np.zeros((1, 1 + d), np.int32)
        b[0, 0], b[0, 1] = 1, add_t
        table.append(
            np.asarray([r % table.n_shards]), np.asarray([r]),
            np.asarray([[elem]], np.int64), b, vc[None, :],
            np.asarray([0], np.int32))
    return mid, clock


def test_table_set_aw_dispatch_strategies_agree(monkeypatch):
    """The serving read of the SAME populated set_aw table must be
    byte-identical with the Pallas kernel on and off, and each run must
    tally the strategy it actually dispatched.  The serving picker is
    platform-gated (interpret-mode Pallas on CPU is a regression, not an
    upgrade), so the test sets the parity-escape env flag to drive the
    interpret kernel in-path anyway."""
    from antidote_tpu.store import TypedTable

    monkeypatch.setenv("ANTIDOTE_PALLAS_INTERPRET", "1")
    d = 3
    outs = {}
    for use_pallas in (False, True):
        cfg = _mk_cfg(use_pallas=use_pallas)
        ty = get_type("set_aw")
        table = TypedTable(ty, cfg, n_rows=16, n_shards=2)
        n_keys = 8
        for s in range(2):
            table.used_rows[s] = n_keys
        mid, final = _populate_set_table(table, n_keys, d)
        want = "pallas_set_aw" if use_pallas else "serial"
        assert table._fold_strategy() == want
        rows = np.arange(n_keys, dtype=np.int64)
        shards = rows % 2
        vcs = np.zeros((n_keys, d), np.int32)
        vcs[:, 0] = mid  # historical: forces the ring fold, not the head
        out, fresh, complete = table.read_resolved(shards, rows, vcs)
        assert complete.all()
        assert table.fold_dispatches.get(want, 0) >= 1
        outs[use_pallas] = {f: np.asarray(x) for f, x in out.items()}
    for f in outs[False]:
        np.testing.assert_array_equal(
            outs[False][f], outs[True][f], err_msg=f)


def test_table_assoc_serving_strategy_matches_serial(monkeypatch):
    """flag_ew serves through the 'assoc' strategy (supports_assoc, not
    bottom-only); forcing the same table back to 'serial' must not change
    a single byte of the resolved batch."""
    from antidote_tpu.store import TypedTable

    cfg = _mk_cfg()
    ty = get_type("flag_ew")
    d = cfg.max_dcs
    rng = np.random.default_rng(3)
    table = TypedTable(ty, cfg, n_rows=16, n_shards=2)
    n_keys = 8
    for s in range(2):
        table.used_rows[s] = n_keys
    bw = table.ops_b.shape[-1]
    clock = 0
    for r in range(n_keys):
        for _ in range(4):
            clock += 1
            vc = np.zeros(d, np.int32)
            vc[0] = clock
            b = np.zeros((1, bw), np.int32)
            b[0, 0] = int(rng.integers(0, 2))  # enable/disable
            b[0, 1] = max(0, clock - 1)
            table.append(
                np.asarray([r % 2]), np.asarray([r]),
                np.zeros((1, 1), np.int64), b, vc[None, :],
                np.asarray([0], np.int32))
    assert table._fold_strategy() == "assoc"
    rows = np.arange(n_keys, dtype=np.int64)
    shards = rows % 2
    vcs = np.zeros((n_keys, d), np.int32)
    vcs[:, 0] = clock // 2
    out_a, fresh_a, comp_a = table.read_resolved(shards, rows, vcs)
    monkeypatch.setattr(
        type(table), "_fold_strategy", lambda self: "serial")
    out_s, fresh_s, comp_s = table.read_resolved(shards, rows, vcs)
    for f, x in out_a.items():
        np.testing.assert_array_equal(
            np.asarray(x), np.asarray(out_s[f]), err_msg=f)
    np.testing.assert_array_equal(np.asarray(fresh_a), np.asarray(fresh_s))
    np.testing.assert_array_equal(np.asarray(comp_a), np.asarray(comp_s))
    assert table.fold_dispatches.get("assoc", 0) >= 1


# ---------------------------------------------------------------------------
# replay ladder: strategies differ with fold_chunk, values must not
# ---------------------------------------------------------------------------

def _drive_replay_node(tmp_path, cfg, fold_chunk):
    from antidote_tpu.api.node import AntidoteNode

    rcfg = dataclasses.replace(cfg, fold_chunk=fold_chunk)
    node = AntidoteNode(rcfg, log_dir=str(tmp_path / f"logs{fold_chunk}"))
    vcs = []
    for i in range(25):
        upd = [("c", "counter_pn", "b", ("increment", 1))]
        if i < 6:
            upd.append(("sl", "set_aw", "b", ("add", f"e{i}")))
        elif i == 6:
            upd.append(("sl", "set_aw", "b", ("remove", "e0")))
        elif i == 7:
            upd.append(("sl", "set_aw", "b", ("remove", "e1")))
        elif i == 8:
            upd.append(("sl", "set_aw", "b", ("add", "e0")))
        else:
            upd.append(("sl", "set_aw", "b", ("add", f"e{2 + (i % 4)}")))
        vcs.append(upd and node.update_objects(upd))
    cut = vcs[12]
    txn = node.start_transaction()
    txn.snapshot_vc = np.asarray(cut, np.int32)
    vals = node.read_objects(
        [("c", "counter_pn", "b"), ("sl", "set_aw", "b")], txn)
    return node, vals


def test_replay_ladder_strategies_agree(tmp_path, cfg):
    """The same 25-op logs replayed with fold_chunk=8 (routing the
    order-sensitive set to 'long' and the counter to 'assoc') and with a
    huge chunk (everything 'serial') must read identical values, and each
    run's dispatch tally + fold metrics must show the expected ladder."""
    expected_c = 13                      # 13 increments at the cut
    expected_sl = ["e0", "e2", "e3", "e4", "e5"]  # e1 removed, e0 re-added

    node8, vals8 = _drive_replay_node(tmp_path, cfg, 8)
    assert vals8[0] == expected_c
    assert sorted(vals8[1]) == expected_sl
    disp = node8.store.replay_fold_dispatches
    assert disp.get("assoc", 0) >= 1    # counter log is assoc-safe
    assert disp.get("long", 0) >= 1     # set log has removes, 13 > 8 ops
    assert node8.metrics.fold_dispatch.value(strategy="long") >= 1
    assert node8.metrics.fold_seconds.count >= 2
    st = node8.store.materializer_status()
    assert st["fold_chunk"] == 8 and st["replay_folds"] == disp

    node_big, vals_big = _drive_replay_node(tmp_path, cfg, 100_000)
    assert vals_big[0] == expected_c
    assert sorted(vals_big[1]) == expected_sl
    disp_big = node_big.store.replay_fold_dispatches
    assert disp_big.get("serial", 0) >= 1  # set log now under the chunk
    assert disp_big.get("long", 0) == 0


def test_replay_mesh_assoc_over_ring(tmp_path):
    """With a mesh attached and an over-chunk assoc-safe log, the replay
    ladder dispatches the mesh-sharded giant-key fold."""
    from antidote_tpu.api.node import AntidoteNode
    from antidote_tpu.parallel import MeshServingPlane

    cfg = _mk_cfg(n_shards=8, fold_chunk=8)
    node = AntidoteNode(cfg, log_dir=str(tmp_path / "logs_mesh"))
    MeshServingPlane(cfg, 8).attach(node.store)
    vcs = [node.update_objects([("c", "counter_pn", "b", ("increment", 1))])
           for _ in range(25)]
    txn = node.start_transaction()
    txn.snapshot_vc = np.asarray(vcs[12], np.int32)
    vals = node.read_objects([("c", "counter_pn", "b")], txn)
    assert vals[0] == 13
    assert node.store.replay_fold_dispatches.get("mesh_assoc", 0) >= 1
    assert node.store.mesh.giant_folds >= 1
    assert node.store.materializer_status()["giant_folds"] >= 1
