"""ISSUE 5 serving-pipeline tests: lock-split epoch reads, the hot-key
snapshot cache, bounded publication cost, and the staged wire server.

The load-bearing properties:

  * epoch-pinned static reads execute OUTSIDE the server/commit locks —
    a held commit lock (a stalled commit group, a publication tick) can
    no longer stall a parked read batch;
  * every read returns a published-epoch-consistent snapshot: a commit
    group is never split across an epoch boundary (no torn reads), and
    a read admitted after a write's ack sees that write (no
    stale-past-epoch values);
  * the snapshot cache invalidates on epoch advance for written rows
    and revalidates across arbitrarily many unrelated publishes;
  * publication cost scales with rows written since the last publish
    (never table size) and is capped per tick.
"""

from __future__ import annotations

import threading
import time

import pytest

from antidote_tpu.api.node import AntidoteNode
from antidote_tpu.config import AntidoteConfig
from antidote_tpu.proto.client import AntidoteClient
from antidote_tpu.proto.server import ProtocolServer

pytestmark = pytest.mark.smoke


def _mk(**kw):
    cfg = AntidoteConfig(n_shards=4, max_dcs=2, keys_per_table=256, **kw)
    node = AntidoteNode(cfg)
    srv = ProtocolServer(node, port=0, epoch_tick_ms=25)
    return node, srv


def _wait_epoch_covers(node, timeout=5.0):
    """Wait until the published serving epoch covers every acked commit
    (rapid write batches defer inline publishes behind the ISSUE 6 rate
    limit; the ticker covers them within a tick)."""
    txm = node.txm
    deadline = time.monotonic() + timeout
    while (node.store.serving_epoch is None
           or int(node.store.serving_epoch.vc[txm.my_dc])
           < txm.commit_counter):
        assert time.monotonic() < deadline, "epoch never covered commits"
        time.sleep(0.005)


# ---------------------------------------------------------------------------
# lock-split: reads never park behind the commit/server locks
# ---------------------------------------------------------------------------
def test_epoch_reads_not_stalled_by_held_commit_lock():
    node, srv = _mk()
    c = AntidoteClient(srv.host, srv.port, timeout=30)
    try:
        c.update_objects([("hot", "counter_pn", "b", ("increment", 7))])
        c.update_objects([("cold", "counter_pn", "b", ("increment", 3))])
        c.read_objects([("hot", "counter_pn", "b")])  # prime the cache
        assert node.store.serving_epoch is not None
        # wedge BOTH locks the old path parked behind: a publication
        # tick / commit group in progress must not stall epoch reads
        with node.txm.commit_lock, srv._lock:
            c2 = AntidoteClient(srv.host, srv.port, timeout=5)
            t0 = time.monotonic()
            vals, _ = c2.read_objects([("hot", "counter_pn", "b")])
            assert vals == [7]  # cache plane
            vals, _ = c2.read_objects([("cold", "counter_pn", "b")])
            assert vals == [3]  # gather plane (first read of this key)
            elapsed = time.monotonic() - t0
            c2.close()
        assert elapsed < 4.0, f"reads stalled {elapsed:.1f}s behind locks"
    finally:
        c.close()
        srv.close()


# ---------------------------------------------------------------------------
# read/write concurrency: epoch-consistent snapshots, no torn reads
# ---------------------------------------------------------------------------
def test_concurrent_commits_and_epoch_reads_see_consistent_snapshots():
    node, srv = _mk()
    stop = time.monotonic() + 3.0
    errors: list = []
    pair = [("a", "counter_pn", "b"), ("b", "counter_pn", "b")]

    def writer():
        try:
            c = AntidoteClient(srv.host, srv.port)
            while time.monotonic() < stop:
                # ONE txn bumps both keys: any epoch-consistent snapshot
                # shows them EQUAL — a mismatch is a torn read
                c.update_objects([
                    ("a", "counter_pn", "b", ("increment", 1)),
                    ("b", "counter_pn", "b", ("increment", 1)),
                ])
            c.close()
        except Exception as e:  # pragma: no cover - failure detail
            errors.append(repr(e))

    def reader():
        try:
            c = AntidoteClient(srv.host, srv.port)
            last_v = -1
            last_vc = None
            while time.monotonic() < stop:
                vals, vc = c.read_objects(pair)
                if vals[0] != vals[1]:
                    errors.append(f"torn read: {vals}")
                    break
                if vals[0] < last_v:
                    errors.append(f"snapshot went backwards: {vals[0]} "
                                  f"< {last_v}")
                    break
                if last_vc is not None and any(
                        n < o for n, o in zip(vc, last_vc)):
                    errors.append(f"clock went backwards: {vc} < {last_vc}")
                    break
                last_v, last_vc = vals[0], vc
            c.close()
        except Exception as e:  # pragma: no cover - failure detail
            errors.append(repr(e))

    ts = [threading.Thread(target=writer)] + [
        threading.Thread(target=reader) for _ in range(3)
    ]
    for t in ts:
        t.start()
    for t in ts:
        t.join(timeout=30)
    srv.close()
    assert not errors, errors
    # the epoch plane actually served (not everything fell to locked)
    m = node.metrics
    assert (m.serving_reads.value(path="cache")
            + m.serving_reads.value(path="gather")) > 0


def test_write_then_clockless_read_sees_the_write():
    node, srv = _mk()
    c = AntidoteClient(srv.host, srv.port)
    try:
        for i in range(1, 40):
            c.update_objects([("rw", "counter_pn", "b", ("increment", 1))])
            vals, _ = c.read_objects([("rw", "counter_pn", "b")])
            assert vals == [i], (i, vals)
    finally:
        c.close()
        srv.close()


# ---------------------------------------------------------------------------
# snapshot cache correctness
# ---------------------------------------------------------------------------
def test_cache_hit_after_epoch_advance_on_written_key_misses():
    node, srv = _mk()
    c = AntidoteClient(srv.host, srv.port)
    try:
        c.update_objects([("k", "set_aw", "b", ("add", 1))])
        vals, _ = c.read_objects([("k", "set_aw", "b")])
        assert vals[0] == [1]
        m = node.metrics
        hits0 = m.snapshot_cache.value(event="hit")
        # same-epoch re-read: a hit
        vals, _ = c.read_objects([("k", "set_aw", "b")])
        assert vals[0] == [1]
        assert m.snapshot_cache.value(event="hit") == hits0 + 1
        # the write advances the epoch and re-freezes k's row: the
        # cached entry MUST miss (serving it would lose the new element)
        c.update_objects([("k", "set_aw", "b", ("add", 2))])
        hits1 = m.snapshot_cache.value(event="hit")
        vals, _ = c.read_objects([("k", "set_aw", "b")])
        assert sorted(vals[0]) == [1, 2]
        assert m.snapshot_cache.value(event="hit") == hits1
    finally:
        c.close()
        srv.close()


def test_cache_revalidates_across_unrelated_epoch_advances():
    node, srv = _mk()
    c = AntidoteClient(srv.host, srv.port)
    try:
        # two priming writes first: the double buffer's first TWO
        # publishes are whole-table copies (both slots must exist), and
        # a copy in the history chain correctly blocks revalidation
        c.update_objects([("warm0", "set_aw", "b", ("add", 1))])
        c.update_objects([("warm1", "set_aw", "b", ("add", 1))])
        c.update_objects([("stable", "set_aw", "b", ("add", 9))])
        _wait_epoch_covers(node)  # rapid writes defer inline publishes
        # (ISSUE 6 rate limit); the cache fill needs a covering epoch
        vals, _ = c.read_objects([("stable", "set_aw", "b")])
        assert vals[0] == [9]
        ep0 = node.store.serving_epoch.id
        # many unrelated writes advance the epoch (rapid-fire batches
        # defer behind the inline-publish rate limit, ISSUE 6 — the
        # ticker covers them within a tick, so wait for the advance and
        # for the epoch to cover every acked commit)
        for i in range(10):
            c.update_objects([(f"other{i}", "set_aw", "b", ("add", i))])
        _wait_epoch_covers(node)
        assert node.store.serving_epoch.id > ep0
        m = node.metrics
        hits0 = m.snapshot_cache.value(event="hit")
        vals, _ = c.read_objects([("stable", "set_aw", "b")])
        assert vals[0] == [9]
        assert m.snapshot_cache.value(event="hit") == hits0 + 1, (
            "untouched key failed to revalidate across unrelated epochs")
    finally:
        c.close()
        srv.close()


# ---------------------------------------------------------------------------
# publication cost: scales with writes, capped, never stalls readers
# ---------------------------------------------------------------------------
def test_publish_cost_scales_with_rows_written_not_table_size():
    cfg = AntidoteConfig(n_shards=4, max_dcs=2, keys_per_table=512)
    node = AntidoteNode(cfg)
    txm = node.txm
    store = node.store
    m = node.metrics
    # seed + the first two publishes are whole-table copies (both slots
    # of the double buffer must exist before incremental freezes begin)
    node.update_objects([("seed", "counter_pn", "b", ("increment", 1))])
    assert store.publish_serving_epoch(txm.serving_epoch_vc()) == "published"
    node.update_objects([("seed", "counter_pn", "b", ("increment", 1))])
    assert store.publish_serving_epoch(txm.serving_epoch_vc()) == "published"
    assert m.epoch_publish.value(mode="copy") == 2
    # k rows written => the next publish scatters the rows written
    # since the SPARE slot's freeze (two publish windows: the one seed
    # row from before the second copy, plus the k fresh rows) —
    # independent of the table's 4*512 row capacity
    k = 7
    node.update_objects([
        (f"k{i}", "counter_pn", "b", ("increment", 1)) for i in range(k)
    ])
    rows0 = m.epoch_rows.value(mode="scatter")
    assert store.publish_serving_epoch(txm.serving_epoch_vc()) == "published"
    assert m.epoch_rows.value(mode="scatter") - rows0 == k + 1
    assert m.epoch_publish.value(mode="copy") == 2  # still no full copy
    # noop when nothing changed
    assert store.publish_serving_epoch(txm.serving_epoch_vc()) == "noop"
    # past the dirty cap the freeze degrades to an EXPLICIT full copy
    # (a 10k-row scatter stops beating the copy) — the cost cap is
    # visible in the mode counters either way
    t = store.table("counter_pn")
    t._SERVING_DIRTY_CAP = 4
    node.update_objects([
        (f"w{i}", "counter_pn", "b", ("increment", 1)) for i in range(6)
    ])
    assert store.publish_serving_epoch(txm.serving_epoch_vc()) == "published"
    assert m.epoch_publish.value(mode="copy") == 3


def test_table_epoch_ladder_budget_one_per_tick():
    cfg = AntidoteConfig(n_shards=4, max_dcs=2, keys_per_table=256)
    node = AntidoteNode(cfg)
    srv = ProtocolServer(node, port=0, epoch_tick_ms=0)
    # stop the ticker (it drives the ladder even with the epoch plane
    # off) so the budgeted calls below can't race it
    srv._ticker_stop.set()
    srv._ticker.join(timeout=5)
    c = AntidoteClient(srv.host, srv.port)
    try:
        store = node.store
        # two dirty tables, both eligible for a ladder publish
        c.update_objects([("x", "counter_pn", "b", ("increment", 1))])
        c.update_objects([("y", "set_aw", "b", ("add", 1))])
        for t in store.tables.values():
            t.slow_serves += 1
            t._pub_at = 0.0
            if hasattr(t, "_pub_slow_serves"):
                del t._pub_slow_serves
        n_tables = len(store.tables)
        assert n_tables >= 2
        # each tick publishes AT MOST one table's full-head epoch copy
        assert srv._publish_table_epochs_capped() == 1
        assert srv._publish_table_epochs_capped() == 1
        assert sum(
            1 for t in store.tables.values() if t.epochs
        ) == 2
    finally:
        c.close()
        srv.close()


# ---------------------------------------------------------------------------
# epoch ticker: publication without static-batch traffic
# ---------------------------------------------------------------------------
def test_ticker_publishes_without_any_static_traffic():
    cfg = AntidoteConfig(n_shards=4, max_dcs=2, keys_per_table=256)
    node = AntidoteNode(cfg)
    # data lands BEFORE the server exists (no publish hooks active)
    node.update_objects([("pre", "counter_pn", "b", ("increment", 5))])
    assert node.store.serving_epoch is None
    srv = ProtocolServer(node, port=0, epoch_tick_ms=25)
    try:
        deadline = time.monotonic() + 5.0
        while node.store.serving_epoch is None:
            assert time.monotonic() < deadline, (
                "ticker never published an epoch")
            time.sleep(0.05)
        assert int(node.store.serving_epoch.vc[0]) >= 1
    finally:
        srv.close()


def test_epoch_tick_zero_disables_the_epoch_plane():
    cfg = AntidoteConfig(n_shards=4, max_dcs=2, keys_per_table=256)
    node = AntidoteNode(cfg)
    srv = ProtocolServer(node, port=0, epoch_tick_ms=0)
    c = AntidoteClient(srv.host, srv.port)
    try:
        assert not srv._epoch_reads
        c.update_objects([("k", "counter_pn", "b", ("increment", 2))])
        vals, _ = c.read_objects([("k", "counter_pn", "b")])
        assert vals == [2]
        assert node.store.serving_epoch is None
    finally:
        c.close()
        srv.close()


# ---------------------------------------------------------------------------
# promotion: the serving epoch survives a tier crossing
# ---------------------------------------------------------------------------
def test_promotion_keeps_serving_epoch_and_reads_stay_exact():
    node, srv = _mk()
    c = AntidoteClient(srv.host, srv.port)
    try:
        store = node.store
        cap = store.cfg.set_slots
        # grow one set key across at least one slot-tier boundary while
        # reading it back between writes
        n = cap * 3
        for i in range(n):
            c.update_objects([("grow", "set_aw", "b", ("add", i))])
            if i % 7 == 0:
                vals, _ = c.read_objects([("grow", "set_aw", "b")])
                assert sorted(vals[0]) == list(range(i + 1))
        assert store.promotions >= 1
        # the fix under test: a promotion no longer nukes the serving
        # epoch (no whole-table copy republish storm)
        assert store.serving_epoch is not None
        vals, _ = c.read_objects([("grow", "set_aw", "b")])
        assert sorted(vals[0]) == list(range(n))
        # reads of OTHER keys kept their cache/gather plane alive
        c.update_objects([("bystander", "set_aw", "b", ("add", 1))])
        vals, _ = c.read_objects([("bystander", "set_aw", "b")])
        assert vals[0] == [1]
    finally:
        c.close()
        srv.close()


# ---------------------------------------------------------------------------
# clocked reads against the epoch plane
# ---------------------------------------------------------------------------
def test_clocked_read_at_returned_epoch_clock():
    node, srv = _mk()
    c = AntidoteClient(srv.host, srv.port)
    try:
        c.update_objects([("ck", "counter_pn", "b", ("increment", 4))])
        vals, vc = c.read_objects([("ck", "counter_pn", "b")])
        assert vals == [4]
        # hand the epoch clock back as the causal clock: still served,
        # still exact (covered => epoch-eligible)
        vals2, vc2 = c.read_objects([("ck", "counter_pn", "b")], clock=vc)
        assert vals2 == [4]
        assert all(b >= a for a, b in zip(vc, vc2))
        # a clock AHEAD of the epoch falls back to the locked path
        ahead = list(vc)
        ahead[0] += 1
        c.update_objects([("ck", "counter_pn", "b", ("increment", 1))])
        vals3, _ = c.read_objects([("ck", "counter_pn", "b")], clock=ahead)
        assert vals3 == [5]
    finally:
        c.close()
        srv.close()


def test_wrong_type_read_raises_even_when_cached():
    """Cache residency must never change observable behavior: a read of
    a key under the WRONG CRDT type raises the same TypeError whether
    the key's value sits in the snapshot cache or not."""
    from antidote_tpu.proto.client import RemoteError

    node, srv = _mk()
    c = AntidoteClient(srv.host, srv.port)
    try:
        c.update_objects([("typed", "counter_pn", "b", ("increment", 3))])
        vals, _ = c.read_objects([("typed", "counter_pn", "b")])
        assert vals == [3]  # cached now
        with pytest.raises(RemoteError, match="bound"):
            c.read_objects([("typed", "set_aw", "b")])
    finally:
        c.close()
        srv.close()


def test_pipeline_status_block_exposed():
    node, srv = _mk()
    c = AntidoteClient(srv.host, srv.port)
    try:
        c.update_objects([("s", "counter_pn", "b", ("increment", 1))])
        c.read_objects([("s", "counter_pn", "b")])
        st = c.node_status()
        pl = st["pipeline"]
        assert pl["epoch_reads"] is True
        assert set(pl["stages"]) == {"decode", "parked", "launch",
                                     "writeback"}
        for s in pl["stages"].values():
            assert {"count", "sum_ms", "mean_us", "p50_us",
                    "p99_us"} <= set(s)
        assert pl["serving_epoch_id"] >= 1
        assert "hit" in pl["snapshot_cache"] or pl["snapshot_cache"]
    finally:
        c.close()
        srv.close()
