"""CT tier-3: four OS processes over real sockets (r2 VERDICT item 7).

The reference's Common Test harness boots several BEAM nodes on one
machine and clusters them ([n1, n2], [n3], [n4] — two-node DC0 plus two
single-node DCs), then runs the multiple_dcs/inter_dc_repl causality and
atomicity cases (/root/reference/test/utils/test_utils.erl:110-165,
/root/reference/test/multidc/).  This suite does exactly that with
``python -m antidote_tpu.cluster.boot`` processes: every hop — client
protocol, intra-DC RPC, inter-DC stream + catch-up — crosses a real
socket between real processes.
"""

import json
import os
import subprocess
import sys
import time

import pytest

from antidote_tpu.cluster.rpc import RpcClient
from antidote_tpu.proto.client import AntidoteClient

TOPOLOGY = [
    # (dc_id, member, members)
    (0, 0, 2),
    (0, 1, 2),
    (1, 0, 1),
    (2, 0, 1),
]


@pytest.fixture(scope="module")
def procs():
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = "/root/repo:" + env.get("PYTHONPATH", "")
    spawned, infos = [], []
    try:
        for dc, member, members in TOPOLOGY:
            p = subprocess.Popen(
                [sys.executable, "-m", "antidote_tpu.cluster.boot",
                 "--dc-id", str(dc), "--member", str(member),
                 "--members", str(members), "--shards", "4",
                 "--max-dcs", "3"],
                env=env, stdout=subprocess.PIPE,
                stderr=subprocess.DEVNULL,
            )
            spawned.append(p)
        for p in spawned:
            line = p.stdout.readline().decode()
            assert line, "boot process died before announcing"
            infos.append(json.loads(line))
        # phase 2: wire the topology through each process' control RPC
        remotes = {info["fabric_id"]: info["fabric"] for info in infos}
        members_by_dc = {0: 2, 1: 1, 2: 1}
        for (dc, member, members), info in zip(TOPOLOGY, infos):
            peers = {
                m: i["rpc"]
                for (d2, m, _), i in zip(TOPOLOGY, infos) if d2 == dc
            }
            ctl = RpcClient(*info["rpc"])
            assert ctl.call("ctl_wire", peers, remotes, members_by_dc)
            ctl.close()
        yield infos
    finally:
        for p in spawned:
            p.terminate()
        for p in spawned:
            try:
                p.wait(timeout=10)
            except subprocess.TimeoutExpired:
                p.kill()


def _client(info):
    return AntidoteClient(*info["client"])


def _read_at(client, objects, clock, tries=200):
    for _ in range(tries):
        try:
            return client.read_objects(objects, clock=clock)
        except Exception:
            time.sleep(0.05)
    return client.read_objects(objects, clock=clock)


def test_replication_across_four_processes(procs):
    n1, n2, dc1, dc2 = procs
    c1 = _client(n1)
    # keys on both DC0 members' shards (int key k -> shard k % 4;
    # member 0 owns {0, 2}, member 1 owns {1, 3})
    vc = c1.update_objects([
        (0, "counter_pn", "b", ("increment", 11)),
        (1, "set_aw", "b", ("add", "spread")),
    ])
    for info in (dc1, dc2):
        c = _client(info)
        vals, _ = _read_at(c, [(0, "counter_pn", "b"), (1, "set_aw", "b")],
                           vc)
        assert vals[0] == 11 and vals[1] == ["spread"]
        c.close()
    # the second DC0 member serves the same data (intra-DC routing)
    c2 = _client(n2)
    vals, _ = _read_at(c2, [(0, "counter_pn", "b"), (1, "set_aw", "b")], vc)
    assert vals[0] == 11 and vals[1] == ["spread"]
    c1.close(), c2.close()


def test_causality_chain_across_dcs(procs):
    n1, n2, dc1, dc2 = procs
    # DC0 (via member 1) writes x; DC1 reads x then writes y; DC2 reading
    # at y's clock MUST see x (transitive causality through three DCs)
    c2 = _client(n2)
    vcx = c2.update_objects([("x", "counter_pn", "cb", ("increment", 1))])
    c_dc1 = _client(dc1)
    vals, vc_read = _read_at(c_dc1, [("x", "counter_pn", "cb")], vcx)
    assert vals[0] == 1
    vcy = c_dc1.update_objects([("y", "counter_pn", "cb", ("increment", 2))],
                               clock=vc_read)
    c_dc2 = _client(dc2)
    vals, _ = _read_at(c_dc2, [("y", "counter_pn", "cb"),
                               ("x", "counter_pn", "cb")], vcy)
    assert vals[0] == 2
    assert vals[1] == 1, "causality violated: y visible without x"
    c2.close(), c_dc1.close(), c_dc2.close()


def test_atomic_multi_member_txn_visibility(procs):
    n1, n2, dc1, _ = procs
    c1 = _client(n1)
    # one interactive txn spanning BOTH DC0 members' shards
    txn = c1.start_transaction()
    txn.update_objects([
        (4, "counter_pn", "ab", ("increment", 1)),   # shard 0 -> member 0
        (5, "counter_pn", "ab", ("increment", 1)),   # shard 1 -> member 1
    ])
    vc = txn.commit()
    c_dc1 = _client(dc1)
    vals, _ = _read_at(c_dc1, [(4, "counter_pn", "ab"),
                               (5, "counter_pn", "ab")], vc)
    assert vals == [1, 1]
    # snapshots never show the txn partially: sample unpinned reads
    for _ in range(10):
        vals, _ = c_dc1.read_objects([(4, "counter_pn", "ab"),
                                      (5, "counter_pn", "ab")])
        assert vals in ([0, 0], [1, 1]), f"partial txn visible: {vals}"
    c1.close(), c_dc1.close()


def _update_retrying(client, updates, tries=50):
    """Cert aborts are first-committer-wins doing its job; clients retry
    (exactly how basho_bench drives the reference)."""
    from antidote_tpu.proto.client import RemoteAbort

    for _ in range(tries):
        try:
            return client.update_objects(updates)
        except RemoteAbort:
            time.sleep(0.02)
    return client.update_objects(updates)


def test_concurrent_writes_from_both_members_converge(procs):
    n1, n2, dc1, dc2 = procs
    c1, c2 = _client(n1), _client(n2)
    vc1 = _update_retrying(c1, [("cs", "set_aw", "vb", ("add", "from-n1"))])
    vc2 = _update_retrying(c2, [("cs", "set_aw", "vb", ("add", "from-n2"))])
    top = [max(a, b) for a, b in zip(vc1, vc2)]
    for info in procs:
        c = _client(info)
        vals, _ = _read_at(c, [("cs", "set_aw", "vb")], top)
        assert sorted(vals[0]) == ["from-n1", "from-n2"]
        c.close()
    c1.close(), c2.close()


# ---------------------------------------------------------------------------
# coordinator-crash takeover + rejoin, OS-process tier (r3 VERDICT missing
# #1/#2; the reference kills a node mid-stream and verifies safety,
# /root/reference/test/multidc/multiple_dcs_node_failure_SUITE.erl:79-99)
# ---------------------------------------------------------------------------
def _spawn_duo(tmp_path):
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = "/root/repo:" + env.get("PYTHONPATH", "")
    spawned, infos = [], []
    for member in (0, 1):
        p = subprocess.Popen(
            [sys.executable, "-m", "antidote_tpu.cluster.boot",
             "--dc-id", "0", "--member", str(member), "--members", "2",
             "--shards", "4", "--max-dcs", "2",
             "--log-dir", str(tmp_path / f"m{member}")],
            env=env, stdout=subprocess.PIPE, stderr=subprocess.DEVNULL,
        )
        spawned.append(p)
    for p in spawned:
        line = p.stdout.readline().decode()
        assert line, "boot process died before announcing"
        infos.append(json.loads(line))
    _wire_duo(infos)
    return env, spawned, infos


def _wire_duo(infos):
    peers = {m: infos[m]["rpc"] for m in (0, 1)}
    remotes = {i["fabric_id"]: i["fabric"] for i in infos}
    for info in infos:
        ctl = RpcClient(*info["rpc"])
        assert ctl.call("ctl_wire", peers, remotes, {0: 2})
        ctl.close()


def _respawn_member(env, tmp_path, member):
    p = subprocess.Popen(
        [sys.executable, "-m", "antidote_tpu.cluster.boot",
         "--dc-id", "0", "--member", str(member), "--members", "2",
         "--shards", "4", "--max-dcs", "2",
         "--log-dir", str(tmp_path / f"m{member}"), "--recover"],
        env=env, stdout=subprocess.PIPE, stderr=subprocess.DEVNULL,
    )
    line = p.stdout.readline().decode()
    assert line, "rejoin process died before announcing"
    return p, json.loads(line)


def test_kill9_mid_commit_fanout_then_takeover_and_rejoin(tmp_path):
    """The full crash story over real processes: the coordinator member
    dies (os._exit, kill -9 shape) after delivering the commit to ONE
    owner; the survivor's takeover completes the commit (atomicity);
    the dead member rejoins from its logs and converges."""
    env, spawned, infos = _spawn_duo(tmp_path)
    try:
        # member 1 coordinates; die after the first owner's commit.
        # int keys: shard = k % 4 -> key 0 on m0's shard 0 (goes first
        # in the fan-out), key 1 on m1's shard 1 (never gets the commit)
        ctl1 = RpcClient(*infos[1]["rpc"])
        assert ctl1.call("ctl_failpoint", "after_first_commit")
        c1 = _client(infos[1])
        with pytest.raises(Exception):
            c1.update_objects([
                (0, "counter_pn", "b", ("increment", 7)),
                (1, "counter_pn", "b", ("increment", 7)),
            ])
        assert spawned[1].wait(timeout=30) == 137  # really died
        # survivor takeover: learns m0 already committed -> completes
        ctl0 = RpcClient(*infos[0]["rpc"])
        ctl0.call("ctl_resolve", 0.0)
        c0 = _client(infos[0])
        vals, _ = c0.read_objects([(0, "counter_pn", "b")])
        assert vals[0] == 7
        # rejoin member 1 on its log dir; it restores the staged txn
        # from the prepare log and the sticky commit decision applies it
        p1b, info1b = _respawn_member(env, tmp_path, 1)
        spawned[1] = p1b
        infos[1] = info1b
        _wire_duo(infos)
        ctl1b = RpcClient(*info1b["rpc"])
        assert ctl1b.call("ctl_resolve", 0.0) >= 1
        c1b = _client(info1b)
        vals, _ = c1b.read_objects([(0, "counter_pn", "b"),
                                    (1, "counter_pn", "b")])
        assert vals == [7, 7], "rejoined member must converge"
        # and the cluster is live again end-to-end
        c1b.update_objects([(1, "counter_pn", "b", ("increment", 1))])
        vals, _ = c0.read_objects([(1, "counter_pn", "b")])
        assert vals[0] == 8
        for c in (c0, c1b):
            c.close()
        for ctl in (ctl0, ctl1, ctl1b):
            ctl.close()
    finally:
        for p in spawned:
            p.terminate()
        for p in spawned:
            try:
                p.wait(timeout=10)
            except subprocess.TimeoutExpired:
                p.kill()


def test_kill9_after_seq_wedge_aborted_by_survivor(tmp_path):
    """Coordinator dies between sequencing and ANY commit delivery: the
    survivor's takeover aborts the txn (nobody applied it) and unwedges
    the shard chain so later commits flow."""
    env, spawned, infos = _spawn_duo(tmp_path)
    try:
        ctl1 = RpcClient(*infos[1]["rpc"])
        assert ctl1.call("ctl_failpoint", "after_seq")
        c1 = _client(infos[1])
        with pytest.raises(Exception):
            c1.update_objects([(0, "counter_pn", "b", ("increment", 100))])
        assert spawned[1].wait(timeout=30) == 137
        ctl0 = RpcClient(*infos[0]["rpc"])
        assert ctl0.call("ctl_resolve", 0.0) >= 1
        # the wedged increment is gone and the shard takes new commits
        c0 = _client(infos[0])
        c0.update_objects([(0, "counter_pn", "b", ("increment", 1))])
        vals, _ = c0.read_objects([(0, "counter_pn", "b")])
        assert vals[0] == 1
        c0.close(), ctl0.close(), ctl1.close()
    finally:
        for p in spawned:
            p.terminate()
        for p in spawned:
            try:
                p.wait(timeout=10)
            except subprocess.TimeoutExpired:
                p.kill()


def test_live_join_across_processes(tmp_path):
    """Live membership across REAL OS processes (r4 VERDICT item 5): a
    2-member DC serves protocol clients while a third `cluster.boot
    --joining` process joins via the OPERATOR CONSOLE path (`console
    cluster-join`, r5 item 4) over the control RPC; writes continue
    through the join and every acked op survives."""
    import threading

    from antidote_tpu import console

    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = "/root/repo:" + env.get("PYTHONPATH", "")
    spawned, infos = [], []

    def boot(member, members, joining=False):
        cmd = [sys.executable, "-m", "antidote_tpu.cluster.boot",
               "--dc-id", "0", "--member", str(member),
               "--members", str(members), "--shards", "8",
               "--max-dcs", "2",
               "--log-dir", str(tmp_path / f"m{member}")]
        if joining:
            cmd.append("--joining")
        p = subprocess.Popen(cmd, env=env, stdout=subprocess.PIPE,
                             stderr=subprocess.DEVNULL)
        spawned.append(p)
        line = p.stdout.readline().decode()
        assert line, "boot process died before announcing"
        info = json.loads(line)
        infos.append(info)
        return info

    try:
        for m in (0, 1):
            boot(m, 2)
        remotes = {i["fabric_id"]: i["fabric"] for i in infos}
        for i in infos:
            peers = {m: infos[m]["rpc"] for m in (0, 1)}
            ctl = RpcClient(*i["rpc"])
            assert ctl.call("ctl_wire", peers, remotes, {0: 2})
            ctl.close()

        n_keys = 16
        acked = [0] * n_keys
        acked_lock = threading.Lock()
        stop = threading.Event()
        errs = []

        def writer(port_info, seed):
            import numpy as np

            rng = np.random.default_rng(seed)
            c = AntidoteClient(*port_info["client"])
            try:
                while not stop.is_set():
                    k = int(rng.integers(n_keys))
                    try:
                        c.update_objects(
                            [(k, "counter_pn", "b", ("increment", 1))])
                    except Exception as e:
                        if "abort" in str(e).lower():
                            continue
                        errs.append(repr(e))
                        return
                    with acked_lock:
                        acked[k] += 1
            finally:
                c.close()

        ts = [threading.Thread(target=writer, args=(infos[i % 2], 90 + i))
              for i in range(2)]
        for t in ts:
            t.start()
        time.sleep(1.0)

        # boot + wire the joiner process, then live-join it under load
        j = boot(2, 3, joining=True)
        peers3 = {m: infos[m]["rpc"] for m in (0, 1, 2)}
        for i in infos:
            ctl = RpcClient(*i["rpc"])
            assert ctl.call("ctl_wire", peers3, remotes, {0: 3})
            ctl.close()
        # the operator console drives the join (progress lines land on
        # stderr; the JSON summary on stdout)
        spec = ",".join(f"{m}={infos[m]['rpc'][0]}:{infos[m]['rpc'][1]}"
                        for m in (0, 1, 2))
        assert console.main(["cluster-join", "--rpcs", spec,
                             "--joiner", "2"]) == 0
        ctl2 = RpcClient(*infos[2]["rpc"])
        assert ctl2.call("ctl_status")["owned_shards"], \
            "console join moved nothing to the joiner"
        ctl2.close()

        time.sleep(1.0)
        stop.set()
        for t in ts:
            t.join(timeout=60)
        assert not errs, errs

        # acked counts readable from ALL THREE processes' client ports
        objs = [(k, "counter_pn", "b") for k in range(n_keys)]
        for i in infos:
            c = AntidoteClient(*i["client"])
            vals, _ = c.read_objects(objs)
            c.close()
            assert vals == acked, (i["rpc"], vals, acked)
    finally:
        for p in spawned:
            p.terminate()
        for p in spawned:
            try:
                p.wait(timeout=10)
            except subprocess.TimeoutExpired:
                p.kill()
