"""Live membership change under load (r4 VERDICT item 5; r5 items 3/4).

The reference joins/leaves nodes while serving (riak_core staged
join/leave + ownership handoff, antidote_dc_manager:create_dc /
antidote_console); here shards stream between members one at a time
while coordinators keep committing — the tests drive continuous writes
THROUGH joins and leaves and assert zero lost/duplicated ops.

Routing truth is the explicit shard→(owner, epoch) map: joins stream
shards only TO the joiner (balanced, minimal moves), and ANY member id
except the sequencer can live-leave — a mid-id departure leaves a gap
in the id space that nothing routes modularly across.
"""

import threading
import time

import numpy as np
import pytest

from antidote_tpu.cluster.coordinator import ClusterNode
from antidote_tpu.cluster.join import (live_join, live_leave,
                                       plan_join_moves, plan_leave_moves)
from antidote_tpu.cluster.member import ClusterMember
from antidote_tpu.config import AntidoteConfig


@pytest.fixture
def cfg():
    return AntidoteConfig(n_shards=8, max_dcs=2, ops_per_key=8,
                          snap_versions=2, set_slots=8, keys_per_table=64,
                          batch_buckets=(8, 64))


def _wire(members):
    for i, m in enumerate(members):
        for j, o in enumerate(members):
            if i != j and o.member_id not in m.peers:
                m.connect(o.member_id, *o.address)


def _rpcs(members):
    return {m.member_id: tuple(m.address) for m in members}


def _assert_consistent_layout(members, n_shards):
    """Every member agrees on one complete map; owned sets partition the
    shard space and match the shared map."""
    ref = members[0].shard_map
    for m in members[1:]:
        assert m.shard_map == ref, (m.member_id, m.shard_map, ref)
    owned = {}
    for m in members:
        for s in m.shards:
            assert s not in owned, f"shard {s} owned twice"
            owned[s] = m.member_id
    assert set(owned) == set(range(n_shards))
    assert owned == ref
    return ref


def test_live_join_under_load_then_leave(cfg):
    ms = [ClusterMember(cfg, dc_id=0, member_id=i, n_members=2)
          for i in range(2)]
    _wire(ms)
    live = list(ms)
    try:
        nodes = [ClusterNode(m) for m in ms]
        n_keys = 24
        acked = np.zeros(n_keys, np.int64)
        acked_lock = threading.Lock()
        stop = threading.Event()
        errs = []

        def writer(node, seed):
            rng = np.random.default_rng(seed)
            while not stop.is_set():
                k = int(rng.integers(n_keys))
                try:
                    node.update_objects(
                        [(k, "counter_pn", "b", ("increment", 1))])
                except Exception as e:
                    if "abort" in str(e).lower():
                        continue  # cert conflict: not acked, retryable
                    import traceback
                    errs.append(traceback.format_exc())
                    return
                with acked_lock:
                    acked[k] += 1

        ts = [threading.Thread(target=writer, args=(nodes[i % 2], 40 + i))
              for i in range(3)]
        for t in ts:
            t.start()
        time.sleep(1.0)  # load running against the 2-member cluster

        # ---- live join member 2, WHILE the writers run: the balanced
        # plan streams shards only TO the joiner (2 of 8 here), never
        # reshuffling the survivors — minimal moves, not a modular remap
        joiner = ClusterMember(cfg, dc_id=0, member_id=2, n_members=3,
                               shards=[])
        live.append(joiner)
        _wire(live)
        moved = live_join(_rpcs(live), new_id=2)
        assert moved == len(plan_join_moves(
            {s: s % 2 for s in range(cfg.n_shards)}, 2)) == 2
        assert joiner.shards == {0, 1}

        time.sleep(1.0)  # load continues on the 3-member cluster
        stop.set()
        for t in ts:
            t.join(timeout=60)
        assert not errs, errs

        # every member agrees on one balanced layout covering all shards
        layout = _assert_consistent_layout(live, cfg.n_shards)
        loads = [sum(1 for o in layout.values() if o == m) for m in range(3)]
        assert max(loads) - min(loads) <= 1, loads

        # zero lost, zero duplicated: every acked increment is readable
        # exactly once, from every member's coordinator
        objs = [(k, "counter_pn", "b") for k in range(n_keys)]
        for node in (ClusterNode(joiner), nodes[0], nodes[1]):
            vals, _ = node.read_objects(objs)
            got = np.asarray(vals, np.int64)
            assert (got == acked).all(), (got.tolist(), acked.tolist())

        # ---- live leave: member 2 drains back out, data survives
        live_leave(_rpcs(live), leaving_id=2)
        assert joiner.shards == set()
        vals, _ = nodes[0].read_objects(objs)
        assert (np.asarray(vals, np.int64) == acked).all()
        _assert_consistent_layout(ms, cfg.n_shards)
        # the shrunk cluster still commits
        nodes[1].update_objects([(0, "counter_pn", "b", ("increment", 5))])
        vals, _ = nodes[0].read_objects([(0, "counter_pn", "b")])
        assert vals[0] == int(acked[0]) + 5
    finally:
        for m in live:
            try:
                m.close()
            except Exception:
                pass


def test_live_leave_middle_member_under_load(cfg):
    """The r5 VERDICT item 3 acceptance shape: member 1 of 3 — a MIDDLE
    id — live-leaves under write load.  Its shards drain to the
    least-loaded survivors, the id space keeps its gap (no renumbering),
    and zero acked ops are lost or duplicated."""
    ms = [ClusterMember(cfg, dc_id=0, member_id=i, n_members=3)
          for i in range(3)]
    _wire(ms)
    try:
        nodes = [ClusterNode(m) for m in ms]
        n_keys = 24
        acked = np.zeros(n_keys, np.int64)
        acked_lock = threading.Lock()
        stop = threading.Event()
        errs = []

        def writer(node, seed):
            rng = np.random.default_rng(seed)
            while not stop.is_set():
                k = int(rng.integers(n_keys))
                try:
                    node.update_objects(
                        [(k, "counter_pn", "b", ("increment", 1))])
                except Exception as e:
                    if "abort" in str(e).lower():
                        continue
                    import traceback
                    errs.append(traceback.format_exc())
                    return
                with acked_lock:
                    acked[k] += 1

        # drive through members 0 and 2 (the survivors): the leaver's
        # clients would need re-pointing at a survivor anyway (its
        # process goes away), exactly like draining a real node
        ts = [threading.Thread(target=writer, args=(nodes[i], 70 + i))
              for i in (0, 2, 0)]
        for t in ts:
            t.start()
        time.sleep(1.0)

        before = {s: int(o) for s, o in ms[0].shard_map.items()}
        moved = live_leave(_rpcs(ms), leaving_id=1)
        assert moved == len(plan_leave_moves(before, 1)) == 3

        time.sleep(1.0)
        stop.set()
        for t in ts:
            t.join(timeout=60)
        assert not errs, errs

        assert ms[1].shards == set()
        survivors = [ms[0], ms[2]]
        layout = _assert_consistent_layout(survivors, cfg.n_shards)
        assert set(layout.values()) == {0, 2}  # the gap stays a gap
        # the departed peer is forgotten everywhere
        for m in survivors:
            assert 1 not in m.peers

        objs = [(k, "counter_pn", "b") for k in range(n_keys)]
        for node in (nodes[0], ClusterNode(ms[2])):
            vals, _ = node.read_objects(objs)
            got = np.asarray(vals, np.int64)
            assert (got == acked).all(), (got.tolist(), acked.tolist())

        # the gapped cluster still serves writes on every shard
        for k in range(cfg.n_shards):
            nodes[0].update_objects(
                [(k, "counter_pn", "b", ("increment", 1))])
        vals, _ = ClusterNode(ms[2]).read_objects(
            [(k, "counter_pn", "b") for k in range(cfg.n_shards)])
        assert vals == [int(acked[k]) + 1 for k in range(cfg.n_shards)]
    finally:
        for m in ms:
            try:
                m.close()
            except Exception:
                pass


def test_leave_plan_includes_zero_shard_survivors():
    """A survivor owning nothing is invisible in the shard map but is
    the least-loaded placement target by definition — the planner must
    see it (r6 review finding)."""
    shard_map = {0: 0, 1: 1, 2: 0, 3: 1}
    moves = plan_leave_moves(shard_map, 1, members={0, 1, 2})
    assert [dst for _s, _src, dst in moves] == [2, 2]
    # without the member hint the old occupancy-only behavior remains
    moves = plan_leave_moves(shard_map, 1)
    assert all(dst == 0 for _s, _src, dst in moves)


def test_departed_id_is_never_reused(cfg):
    """The id-space bound is monotone EVERYWHERE (m_forget_member,
    m_set_owner broadcasts, recovery replay): after leaves — including
    the highest-then-middle sequence whose second drive recomputes a
    SMALLER bound from its shrunken rpcs map — a join reusing any
    departed id must be refused; its durable state and the routes
    remote DCs learned for its fabric id would alias the new member."""
    ms = [ClusterMember(cfg, dc_id=0, member_id=i, n_members=3)
          for i in range(3)]
    _wire(ms)
    try:
        live_leave(_rpcs(ms), leaving_id=2)   # highest id departs...
        live_leave({0: tuple(ms[0].address),
                    1: tuple(ms[1].address)}, leaving_id=1)  # ...then mid
        assert ms[0].n_members == 3  # bound never shrank
        assert ms[0].departed == {1, 2}  # durable never-reuse set
        for dead in (1, 2):
            with pytest.raises(ValueError, match="never be reused"):
                live_join({0: tuple(ms[0].address),
                           dead: ("127.0.0.1", 1)}, new_id=dead)
        # even a reused id the operator already WIRED back into the peer
        # set (indistinguishable from an interrupted join by liveness
        # alone) is refused — the durable departed set catches it
        imposter = ClusterMember(cfg, dc_id=0, member_id=2, n_members=3,
                                 shards=[])
        try:
            ms[0].connect(2, *imposter.address)
            with pytest.raises(ValueError, match="never be reused"):
                live_join({0: tuple(ms[0].address),
                           2: tuple(imposter.address)}, new_id=2)
        finally:
            imposter.close()
            ms[0].peers.pop(2).close()
        # a genuinely fresh id is welcome (validation passes the bound
        # check; the dummy address then fails at wiring, which proves
        # the refusal above came from the bound, not the address)
        with pytest.raises(Exception,
                           match="(?i)connect|refused|timed|attempt"):
            live_join({0: tuple(ms[0].address),
                       3: ("127.0.0.1", 1)}, new_id=3)
    finally:
        for m in ms:
            m.close()


def test_sequencer_cannot_live_leave(cfg):
    ms = [ClusterMember(cfg, dc_id=0, member_id=i, n_members=2)
          for i in range(2)]
    _wire(ms)
    try:
        with pytest.raises(ValueError, match="sequencer"):
            live_leave(_rpcs(ms), leaving_id=0)
    finally:
        for m in ms:
            m.close()


def test_rpcs_must_cover_every_live_member(cfg):
    """A driver that forgets a live member would half-commit the change
    (the omitted member never hears the broadcasts); both drivers refuse
    up front, before any durable mutation."""
    ms = [ClusterMember(cfg, dc_id=0, member_id=i, n_members=3)
          for i in range(3)]
    _wire(ms)
    try:
        partial = {0: tuple(ms[0].address), 1: tuple(ms[1].address)}
        with pytest.raises(ValueError, match="cover every live member"):
            live_leave(partial, leaving_id=1)  # member 2 omitted
        # nothing moved, nothing forgotten
        assert 2 in ms[0].peers and ms[1].shards
    finally:
        for m in ms:
            m.close()


def test_membership_state_survives_log_compaction(cfg, tmp_path):
    """Prepare-log compaction rewrites the WAL from live state; it must
    re-emit the membership records (boot_layout + full map/epochs +
    id-space bound + departed set), or a post-move member would recover
    with the modular guess of its recover-time count — silently
    claiming shards it gave away."""
    dirs = [str(tmp_path / f"m{i}") for i in range(3)]
    ms = [ClusterMember(cfg, dc_id=0, member_id=i, n_members=3,
                        log_dir=dirs[i]) for i in range(3)]
    _wire(ms)
    try:
        live_leave(_rpcs(ms), leaving_id=1)
        m0 = ms[0]
        before = (set(m0.shards), dict(m0.shard_map),
                  dict(m0.shard_epoch), set(m0.departed), m0.n_members)
        m0._compact_prepare_log()
        m0.close()
        m0.node.store.log.close()
        rec = ClusterMember(cfg, dc_id=0, member_id=0, n_members=3,
                            log_dir=dirs[0], recover=True)
        ms[0] = rec
        assert (set(rec.shards), dict(rec.shard_map),
                dict(rec.shard_epoch), set(rec.departed),
                rec.n_members) == before
    finally:
        for m in ms:
            try:
                m.close()
            except Exception:
                pass


def test_join_recovers_from_crash_mid_move(cfg, tmp_path):
    """Two-phase move crash safety: a crash after export (before the
    import is confirmed) destroys NOTHING — the source still owns the
    only durable copy (ownership flips only at relinquish), the volatile
    mid-move mark clears on restart, and a driver re-run completes the
    move with a fresh export."""
    dirs = [str(tmp_path / f"m{i}") for i in range(2)]
    ms = [ClusterMember(cfg, dc_id=0, member_id=i, n_members=2,
                        log_dir=dirs[i]) for i in range(2)]
    _wire(ms)
    joiner_dir = str(tmp_path / "m2")
    try:
        node = ClusterNode(ms[0])
        for k in range(12):
            node.update_objects([(k, "counter_pn", "b", ("increment", k + 1))])
        joiner = ClusterMember(cfg, dc_id=0, member_id=2, n_members=3,
                               shards=[], log_dir=joiner_dir)
        ms.append(joiner)
        _wire(ms)
        for m in ms:
            m.m_join_begin(2, list(joiner.address), 3)
        # move ONE shard by hand, crashing the exporter before the
        # import lands: two-phase export copied WITHOUT dropping, so the
        # crash destroys nothing
        moves = plan_join_moves({s: int(o[0]) for s, o in
                                 ms[0].m_shard_map().items()}, 2)
        shard, src, dst = moves[0]
        data = ms[src].m_export_shard(shard, dst)
        assert shard in ms[src].shards      # still the owner (phase 1)
        assert shard in ms[src].moving      # but refusing new work
        del data  # the driver "crashes"; its package dies with it
        ms[src].close()
        ms[src].node.store.log.close()
        ms[src]._prep_wal.close()
        rec = ClusterMember(cfg, dc_id=0, member_id=src, n_members=3,
                            log_dir=dirs[src], recover=True)
        ms[src] = rec
        # rejoin re-wiring: peers must learn the recovered member's NEW
        # address (the takeover rejoin flow's re-ctl_wire step)
        for m in ms:
            if m is not rec:
                m.connect(src, *rec.address)
        _wire(ms)
        # the recovered source still owns the shard (no durable own
        # event until relinquish) and the volatile mid-move mark cleared
        assert shard in rec.shards
        assert shard not in rec.moving
        assert rec.shard_map[shard] == src
        # a driver re-run completes the whole plan with fresh exports
        for shard2, src2, dst2 in moves:
            d2 = ms[src2].m_export_shard(shard2, dst2)
            ms[dst2].m_import_shard(d2)
            ms[src2].m_relinquish_shard(shard2, dst2)
            for m in ms:
                if m.member_id not in (src2, dst2):
                    m.m_set_owner(shard2, dst2, 3)
        vals, _ = ClusterNode(ms[1]).read_objects(
            [(k, "counter_pn", "b") for k in range(12)])
        assert vals == [k + 1 for k in range(12)]
    finally:
        for m in ms:
            try:
                m.close()
            except Exception:
                pass
