"""Live membership change under load (r4 VERDICT item 5).

The reference joins/leaves nodes while serving (riak_core staged
join/leave + ownership handoff, antidote_dc_manager:create_dc /
antidote_console); here shards stream between members one at a time
while coordinators keep committing — the test drives continuous writes
THROUGH the whole join and asserts zero lost/duplicated ops.
"""

import threading
import time

import numpy as np
import pytest

from antidote_tpu.cluster.coordinator import ClusterNode
from antidote_tpu.cluster.join import live_join, live_leave, plan_moves
from antidote_tpu.cluster.member import ClusterMember, owned_shards
from antidote_tpu.config import AntidoteConfig


@pytest.fixture
def cfg():
    return AntidoteConfig(n_shards=8, max_dcs=2, ops_per_key=8,
                          snap_versions=2, set_slots=8, keys_per_table=64,
                          batch_buckets=(8, 64))


def _wire(members):
    for i, m in enumerate(members):
        for j, o in enumerate(members):
            if i != j and j not in m.peers:
                m.connect(j, *o.address)


def _rpcs(members):
    return {m.member_id: tuple(m.address) for m in members}


def test_live_join_under_load_then_leave(cfg):
    ms = [ClusterMember(cfg, dc_id=0, member_id=i, n_members=2)
          for i in range(2)]
    _wire(ms)
    live = list(ms)
    try:
        nodes = [ClusterNode(m) for m in ms]
        n_keys = 24
        acked = np.zeros(n_keys, np.int64)
        acked_lock = threading.Lock()
        stop = threading.Event()
        errs = []

        def writer(node, seed):
            rng = np.random.default_rng(seed)
            while not stop.is_set():
                k = int(rng.integers(n_keys))
                try:
                    node.update_objects(
                        [(k, "counter_pn", "b", ("increment", 1))])
                except Exception as e:
                    if "abort" in str(e).lower():
                        continue  # cert conflict: not acked, retryable
                    import traceback
                    errs.append(traceback.format_exc())
                    return
                with acked_lock:
                    acked[k] += 1

        ts = [threading.Thread(target=writer, args=(nodes[i % 2], 40 + i))
              for i in range(3)]
        for t in ts:
            t.start()
        time.sleep(1.0)  # load running against the 2-member cluster

        # ---- live join member 2, WHILE the writers run
        joiner = ClusterMember(cfg, dc_id=0, member_id=2, n_members=3,
                               shards=[])
        live.append(joiner)
        _wire(live)
        moved = live_join(_rpcs(live), new_id=2)
        assert moved == len(plan_moves(
            {s: s % 2 for s in range(cfg.n_shards)}, 3))
        assert joiner.shards == set(owned_shards(cfg, 2, 3))

        time.sleep(1.0)  # load continues on the 3-member cluster
        stop.set()
        for t in ts:
            t.join(timeout=60)
        assert not errs, errs

        # every member agrees on the modular 3-member map
        for m in live:
            assert m.shard_map == {s: s % 3 for s in range(cfg.n_shards)}
        assert {s for m in live for s in m.shards} == set(range(cfg.n_shards))

        # zero lost, zero duplicated: every acked increment is readable
        # exactly once, from every member's coordinator
        objs = [(k, "counter_pn", "b") for k in range(n_keys)]
        for node in (ClusterNode(joiner), nodes[0], nodes[1]):
            vals, _ = node.read_objects(objs)
            got = np.asarray(vals, np.int64)
            assert (got == acked).all(), (got.tolist(), acked.tolist())

        # ---- live leave: member 2 drains back out, data survives
        live_leave(_rpcs(live), leaving_id=2)
        assert joiner.shards == set()
        vals, _ = nodes[0].read_objects(objs)
        assert (np.asarray(vals, np.int64) == acked).all()
        for m in ms:
            assert m.shard_map == {s: s % 2 for s in range(cfg.n_shards)}
        # the shrunk cluster still commits
        nodes[1].update_objects([(0, "counter_pn", "b", ("increment", 5))])
        vals, _ = nodes[0].read_objects([(0, "counter_pn", "b")])
        assert vals[0] == int(acked[0]) + 5
    finally:
        for m in live:
            try:
                m.close()
            except Exception:
                pass


def test_join_recovers_from_crash_mid_move(cfg, tmp_path):
    """Two-phase move crash safety: a crash after export (before the
    import is confirmed) destroys NOTHING — the source still owns the
    only durable copy (ownership flips only at relinquish), the volatile
    mid-move mark clears on restart, and a driver re-run completes the
    move with a fresh export."""
    dirs = [str(tmp_path / f"m{i}") for i in range(2)]
    ms = [ClusterMember(cfg, dc_id=0, member_id=i, n_members=2,
                        log_dir=dirs[i]) for i in range(2)]
    _wire(ms)
    joiner_dir = str(tmp_path / "m2")
    try:
        node = ClusterNode(ms[0])
        for k in range(12):
            node.update_objects([(k, "counter_pn", "b", ("increment", k + 1))])
        joiner = ClusterMember(cfg, dc_id=0, member_id=2, n_members=3,
                               shards=[], log_dir=joiner_dir)
        ms.append(joiner)
        _wire(ms)
        for m in ms:
            m.m_join_begin(2, list(joiner.address), 3)
        # move ONE shard by hand, crashing the exporter before the
        # import lands: two-phase export copied WITHOUT dropping, so the
        # crash destroys nothing
        moves = plan_moves({s: int(o) for s, (o, _e) in
                            ms[0].m_shard_map().items()}, 3)
        shard, src, dst = moves[0]
        data = ms[src].m_export_shard(shard, dst)
        assert shard in ms[src].shards      # still the owner (phase 1)
        assert shard in ms[src].moving      # but refusing new work
        del data  # the driver "crashes"; its package dies with it
        ms[src].close()
        ms[src].node.store.log.close()
        ms[src]._prep_wal.close()
        rec = ClusterMember(cfg, dc_id=0, member_id=src, n_members=3,
                            log_dir=dirs[src], recover=True)
        ms[src] = rec
        # rejoin re-wiring: peers must learn the recovered member's NEW
        # address (the takeover rejoin flow's re-ctl_wire step)
        for m in ms:
            if m is not rec:
                m.connect(src, *rec.address)
        _wire(ms)
        # the recovered source still owns the shard (no durable own
        # event until relinquish) and the volatile mid-move mark cleared
        assert shard in rec.shards
        assert shard not in rec.moving
        assert rec.shard_map[shard] == src
        # a driver re-run completes the whole plan with fresh exports
        for shard2, src2, dst2 in moves:
            d2 = ms[src2].m_export_shard(shard2, dst2)
            ms[dst2].m_import_shard(d2)
            ms[src2].m_relinquish_shard(shard2, dst2)
            for m in ms:
                if m.member_id not in (src2, dst2):
                    m.m_set_owner(shard2, dst2, 3)
        vals, _ = ClusterNode(ms[1]).read_objects(
            [(k, "counter_pn", "b") for k in range(12)])
        assert vals == [k + 1 for k in range(12)]
    finally:
        for m in ms:
            try:
                m.close()
            except Exception:
                pass
