"""Node failure & restart system tests.

Mirrors the reference's failure suites (SURVEY §4 tier-3):
``log_recovery_SUITE`` (updates → kill → restart → log replay,
/root/reference/test/singledc/log_recovery_SUITE.erl:59-79) and
``multiple_dcs_node_failure_SUITE`` (kill a DC's node mid-stream, restart,
verify safety, /root/reference/test/multidc/multiple_dcs_node_failure_SUITE.erl:79-99).
"Kill" here = discard every in-memory object (node, replica, hub handlers);
only the WAL directory survives, exactly what kill -9 leaves behind.
"""

import numpy as np
import pytest

from antidote_tpu.api import AntidoteNode
from antidote_tpu.config import AntidoteConfig
from antidote_tpu.interdc import DCReplica
from antidote_tpu.interdc.transport import LoopbackHub


@pytest.fixture
def cfg():
    return AntidoteConfig(
        n_shards=2, max_dcs=3, ops_per_key=8, snap_versions=2,
        set_slots=4, keys_per_table=16, batch_buckets=(8,),
    )


def mk_dc(cfg, hub, dc_id, log_dir, recover=False):
    node = AntidoteNode(cfg, dc_id=dc_id, log_dir=str(log_dir),
                        recover=recover)
    rep = DCReplica(node, hub, f"dc{dc_id}")
    if recover:
        rep.restore_from_log()
    return node, rep


def kill(hub, dc_id):
    """Simulate kill -9: the hub forgets the dead DC's callbacks."""
    hub.unregister(dc_id)


def test_restart_preserves_and_resumes_replication(cfg, tmp_path):
    hub = LoopbackHub()
    n0, r0 = mk_dc(cfg, hub, 0, tmp_path / "dc0")
    n1, r1 = mk_dc(cfg, hub, 1, tmp_path / "dc1")
    r0.observe_dc(r1), r1.observe_dc(r0)
    n0.update_objects([("k", "counter_pn", "b", ("increment", 5)),
                       ("s", "set_aw", "b", ("add", "x"))])
    hub.pump()
    # kill DC1, restart from its WAL alone
    kill(hub, 1)
    del n1, r1
    n1, r1 = mk_dc(cfg, hub, 1, tmp_path / "dc1", recover=True)
    r1.observe_dc(r0), r0.observe_dc(r1)
    # the reference's 1 s heartbeat timers re-advance idle shard clocks
    # after a restart; fire them explicitly (the loopback has no timers)
    r0.heartbeat(), r1.heartbeat()
    hub.pump()
    vals, _ = n1.read_objects([("k", "counter_pn", "b"), ("s", "set_aw", "b")],
                              clock=n1.store.dc_max_vc())
    assert vals == [5, ["x"]]
    # replication resumes in BOTH directions after the restart
    n0.update_objects([("k", "counter_pn", "b", ("increment", 1))])
    n1.update_objects([("k2", "counter_pn", "b", ("increment", 7))])
    hub.pump()
    tgt = np.maximum(n0.store.dc_max_vc(), n1.store.dc_max_vc())
    for n in (n0, n1):
        vals, _ = n.read_objects(
            [("k", "counter_pn", "b"), ("k2", "counter_pn", "b")], clock=tgt)
        assert vals == [6, 7]


def test_restarted_origin_serves_catch_up(cfg, tmp_path):
    """DC0 commits, is killed, restarts — then a late subscriber's catch-up
    query must still replay the pre-crash txns (rebuilt egress chains)."""
    hub = LoopbackHub()
    n0, r0 = mk_dc(cfg, hub, 0, tmp_path / "dc0")
    n0.update_objects([("k", "counter_pn", "b", ("increment", 3))])
    n0.update_objects([("k", "counter_pn", "b", ("increment", 4))])
    kill(hub, 0)
    del n0, r0
    n0, r0 = mk_dc(cfg, hub, 0, tmp_path / "dc0", recover=True)
    # DC1 arrives only now
    n1, r1 = mk_dc(cfg, hub, 1, tmp_path / "dc1")
    r1.observe_dc(r0)
    r0.heartbeat()  # chain head reveals the gap → catch-up query
    hub.pump()
    vals, _ = n1.read_objects([("k", "counter_pn", "b")],
                              clock=n1.store.dc_max_vc())
    assert vals == [7]


def test_restart_does_not_reapply_duplicates(cfg, tmp_path):
    """After restart, a conservative catch-up may re-deliver already-applied
    txns; the dependency gate must drop them (idempotent re-delivery)."""
    hub = LoopbackHub()
    n0, r0 = mk_dc(cfg, hub, 0, tmp_path / "dc0")
    n1, r1 = mk_dc(cfg, hub, 1, tmp_path / "dc1")
    r1.observe_dc(r0)
    n0.update_objects([("k", "counter_pn", "b", ("increment", 5))])
    hub.pump()
    # restart DC1; force its ingress chains back to zero so the next ping
    # triggers a full-history catch-up (worst-case re-delivery)
    kill(hub, 1)
    del n1, r1
    n1, r1 = mk_dc(cfg, hub, 1, tmp_path / "dc1", recover=True)
    r1.last_seen.clear()
    r1.observe_dc(r0)
    r0.heartbeat()
    hub.pump()
    vals, _ = n1.read_objects([("k", "counter_pn", "b")],
                              clock=n1.store.dc_max_vc())
    assert vals == [5]  # not 10


def test_kill_mid_stream_then_converge(cfg, tmp_path):
    """The node-failure suite's core scenario: DC1 dies while DC0 keeps
    committing; after restart the missed txns flow via catch-up and both
    DCs converge (no lost or duplicated updates)."""
    hub = LoopbackHub()
    n0, r0 = mk_dc(cfg, hub, 0, tmp_path / "dc0")
    n1, r1 = mk_dc(cfg, hub, 1, tmp_path / "dc1")
    r0.observe_dc(r1), r1.observe_dc(r0)
    for i in range(3):
        n0.update_objects([("c", "counter_pn", "b", ("increment", 1))])
    hub.pump()
    kill(hub, 1)
    survivors_only = [
        n0.update_objects([("c", "counter_pn", "b", ("increment", 1))])
        for _ in range(4)
    ]
    del n1, r1, survivors_only
    n1, r1 = mk_dc(cfg, hub, 1, tmp_path / "dc1", recover=True)
    r1.observe_dc(r0), r0.observe_dc(r1)
    r0.heartbeat()
    hub.pump()
    vals, _ = n1.read_objects([("c", "counter_pn", "b")],
                              clock=n1.store.dc_max_vc())
    assert vals == [7]
    # and the restarted DC can still write; DC0 sees it
    n1.update_objects([("c", "counter_pn", "b", ("increment", 10))])
    hub.pump()
    vals, _ = n0.read_objects([("c", "counter_pn", "b")],
                              clock=np.maximum(n0.store.dc_max_vc(),
                                               n1.store.dc_max_vc()))
    assert vals == [17]


def test_tcp_restart_and_reconnect(cfg, tmp_path):
    """Same kill/restart flow over real sockets: the reborn DC binds a new
    endpoint, the survivor learns the new address (descriptor re-exchange,
    /root/reference/src/inter_dc_manager.erl:156-206) and both converge."""
    from antidote_tpu.interdc.tcp import TcpFabric

    fab0, fab1 = TcpFabric(), TcpFabric()
    n0 = AntidoteNode(cfg, dc_id=0, log_dir=str(tmp_path / "dc0"))
    n1 = AntidoteNode(cfg, dc_id=1, log_dir=str(tmp_path / "dc1"))
    r0, r1 = DCReplica(n0, fab0, "dc0"), DCReplica(n1, fab1, "dc1")
    TcpFabric.interconnect([fab0, fab1])
    r0.observe_dc(r1), r1.observe_dc(r0)
    try:
        n0.update_objects([("k", "counter_pn", "b", ("increment", 2))])
        fab0.pump(timeout=0.2), fab1.pump(timeout=0.2)
        # kill DC1's process: sockets die, memory gone; WAL survives
        fab1.close()
        del n1, r1
        n0.update_objects([("k", "counter_pn", "b", ("increment", 3))])
        fab1 = TcpFabric()
        n1 = AntidoteNode(cfg, dc_id=1, log_dir=str(tmp_path / "dc1"),
                          recover=True)
        r1 = DCReplica(n1, fab1, "dc1")
        r1.restore_from_log()
        # descriptor re-exchange: both sides learn current addresses
        TcpFabric.interconnect([fab0, fab1])
        fab0.connect_remote(1, *fab1.address_of(1))
        r1.observe_dc(r0), r0.observe_dc(r1)
        r0.heartbeat()
        for _ in range(4):
            fab1.pump(timeout=0.3), fab0.pump(timeout=0.3)
        vals, _ = n1.read_objects([("k", "counter_pn", "b")],
                                  clock=n1.store.dc_max_vc())
        assert vals == [5]
    finally:
        fab0.close(), fab1.close()


def test_partition_heal_converges(cfg, tmp_path):
    """Network partition (all links drop) then heal: commits made on both
    sides during the partition converge afterwards
    (partition_cluster/heal_cluster, /root/reference/test/utils/test_utils.erl:239-256)."""
    hub = LoopbackHub()
    n0, r0 = mk_dc(cfg, hub, 0, tmp_path / "dc0")
    n1, r1 = mk_dc(cfg, hub, 1, tmp_path / "dc1")
    r0.observe_dc(r1), r1.observe_dc(r0)
    n0.update_objects([("s", "set_aw", "b", ("add", "pre"))])
    hub.pump()
    # partition: drop everything published while split (both directions)
    hub.drop_next(0, 1, 10_000)
    hub.drop_next(1, 0, 10_000)
    n0.update_objects([("s", "set_aw", "b", ("add", "left"))])
    n1.update_objects([("s", "set_aw", "b", ("add", "right"))])
    hub.pump()
    # heal + heartbeats reveal the opid gaps → catch-up both ways
    hub.drop.clear()
    r0.heartbeat(), r1.heartbeat()
    hub.pump()
    tgt = np.maximum(n0.store.dc_max_vc(), n1.store.dc_max_vc())
    for n in (n0, n1):
        vals, _ = n.read_objects([("s", "set_aw", "b")], clock=tgt)
        assert sorted(vals[0]) == ["left", "pre", "right"]
