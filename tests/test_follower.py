"""Follower read replicas (ISSUE 9): checkpoint-image bootstrap,
session-token failover, divergence detection.

Part A drives FollowerReplica deterministically over the LoopbackHub:
bootstrap modes (image / tail / delta), the below-compaction-floor
repair that closes PR 7's residual, divergence detection + self-heal,
crash rejoin, and the session gate's park/redirect semantics.  Part B
runs the real wire stack — owner + followers on TCP fabrics with
ProtocolServers — and pins the SessionClient's read-your-writes across
follower kills and rejoins.
"""

import threading
import time

import numpy as np
import pytest

from antidote_tpu import faults
from antidote_tpu.api import AntidoteNode
from antidote_tpu.config import AntidoteConfig
from antidote_tpu.interdc import DCReplica, FollowerReplica, LoopbackHub
from antidote_tpu.store.kv import shard_digest

pytestmark = pytest.mark.smoke


@pytest.fixture
def cfg():
    # same shapes as the chaos/tcp suites: the XLA compile cache is warm
    return AntidoteConfig(
        n_shards=2, max_dcs=3, ops_per_key=8, snap_versions=2,
        set_slots=4, keys_per_table=16, batch_buckets=(8,),
    )


@pytest.fixture(autouse=True)
def _disarm():
    yield
    faults.uninstall()


def mk_owner(cfg, hub, tmp_path, name="owner"):
    node = AntidoteNode(cfg, dc_id=0, log_dir=str(tmp_path / name))
    rep = DCReplica(node, hub, "dc0")
    return node, rep


def mk_follower(cfg, hub, tmp_path, owner_rep, name="f1", fid=77,
                recover=False, **kw):
    node = AntidoteNode(cfg, dc_id=0, log_dir=str(tmp_path / name),
                        recover=recover)
    fol = FollowerReplica(node, hub, name,
                          owner_client_addr=("owner-host", 1234),
                          fabric_id=fid, **kw)
    mode = fol.attach(owner_rep.descriptor())
    return node, fol, mode


def converge(owner, owner_rep, hub, follower_node, objs, rounds=40):
    """Heartbeat + pump until the follower's stable snapshot covers the
    owner's max clock, then return both sides' values there."""
    for _ in range(rounds):
        owner_rep.heartbeat()
        hub.pump()
        target = owner.store.dc_max_vc()
        if (follower_node.store.stable_vc() >= target).all():
            break
    else:
        raise AssertionError(
            f"follower never converged: {follower_node.store.stable_vc()}"
            f" < {owner.store.dc_max_vc()}")
    target = owner.store.dc_max_vc()
    want, _ = owner.read_objects(objs, clock=target)
    got, _ = follower_node.read_objects(objs, clock=target)
    return want, got, target


# ---------------------------------------------------------------------------
# Part A — deterministic (LoopbackHub)
# ---------------------------------------------------------------------------
def test_image_bootstrap_then_tail_replication(cfg, tmp_path):
    hub = LoopbackHub()
    owner, orep = mk_owner(cfg, hub, tmp_path)
    for i in range(6):
        owner.update_objects([("k", "counter_pn", "b", ("increment", 1)),
                              ("s", "set_aw", "b", ("add", f"e{i}"))])
    owner.checkpoint_now()
    owner.update_objects([("k", "counter_pn", "b", ("increment", 10))])

    fnode, fol, mode = mk_follower(cfg, hub, tmp_path, orep)
    assert mode == "image"
    assert fol.state == "serving"
    assert fnode.metrics.follower_bootstrap.value(mode="image") == 1
    objs = [("k", "counter_pn", "b"), ("s", "set_aw", "b")]
    want, got, _ = converge(owner, orep, hub, fnode, objs)
    assert got == want and want[0] == 16
    # live tail keeps flowing through the ordinary chain machinery
    owner.update_objects([("k", "counter_pn", "b", ("increment", 1))])
    want, got, _ = converge(owner, orep, hub, fnode, objs)
    assert got == want and want[0] == 17
    # the image bootstrap sealed itself with a LOCAL checkpoint, so the
    # follower's own crash recovery is self-sufficient
    from antidote_tpu.log import checkpoint as ckpt

    assert ckpt.list_checkpoints(
        ckpt.checkpoint_root(fnode.store.log.dir))
    # digests agree at equal clocks
    assert all(v == "ok" for v in fol.check_divergence().values())
    owner.store.log.close(), fnode.store.log.close()


def test_tail_bootstrap_without_owner_checkpoint(cfg, tmp_path):
    hub = LoopbackHub()
    owner, orep = mk_owner(cfg, hub, tmp_path)
    owner.update_objects([("k", "counter_pn", "b", ("increment", 5))])
    fnode, fol, mode = mk_follower(cfg, hub, tmp_path, orep)
    assert mode == "tail"  # no image published: whole-chain catch-up
    want, got, _ = converge(owner, orep, hub, fnode,
                            [("k", "counter_pn", "b")])
    assert got == want == [5]
    owner.store.log.close(), fnode.store.log.close()


def test_below_compaction_floor_repairs_via_image_delta(cfg, tmp_path,
                                                        monkeypatch):
    """PR 7's residual, closed: a follower whose chain position fell
    below the owner's compaction floor converges via image shipping
    instead of a refused catch-up — byte-identical to the owner."""
    # a tiny egress window so the partition outlives the in-memory
    # catch-up fast path (in production that's SENT_WINDOW commits of
    # uptime, or any owner restart) and the WAL path's floor refusal is
    # what the follower actually meets
    monkeypatch.setattr(DCReplica, "SENT_WINDOW", 2)
    hub = LoopbackHub()
    owner, orep = mk_owner(cfg, hub, tmp_path)
    owner.update_objects([("k", "counter_pn", "b", ("increment", 1))])
    fnode, fol, mode = mk_follower(cfg, hub, tmp_path, orep)
    objs = [("k", "counter_pn", "b"), ("s", "set_aw", "b")]
    converge(owner, orep, hub, fnode, [objs[0]])
    pre_position = dict(fol.last_seen)
    # partition the stream (every published frame to the follower is
    # lost) while the owner commits past a NEW checkpoint floor
    hub.drop_next(0, fol.fabric_id, n=1_000_000)
    for i in range(5):
        owner.update_objects([("k", "counter_pn", "b", ("increment", 1)),
                              ("s", "set_aw", "b", ("add", f"x{i}"))])
    owner.checkpoint_now()
    owner.update_objects([("k", "counter_pn", "b", ("increment", 100))])
    assert owner.store.log.chain_floor.sum() > 0
    # the follower's position is now below the floor: a plain catch-up
    # is refused there (the PR 7 behavior this tier repairs)
    shard = owner.store.directory[("k", "b")][1]
    with pytest.raises(RuntimeError, match="compaction floor"):
        orep._serve_log_query(shard, 0,
                              pre_position.get((0, shard), 0))
    # heal the link: the next heartbeat reveals the gap, the refused
    # catch-up triggers the image-delta repair on the delivery path
    hub.drop[(0, fol.fabric_id)] = 0
    want, got, _ = converge(owner, orep, hub, fnode, objs)
    assert got == want and want[0] == 106
    assert fol.last_bootstrap_mode == "delta"
    assert fnode.metrics.follower_bootstrap.value(mode="delta") == 1
    assert all(v == "ok" for v in fol.check_divergence().values())
    owner.store.log.close(), fnode.store.log.close()


def test_divergence_detected_and_self_healed(cfg, tmp_path):
    """A deliberately corrupted follower row is caught by the digest
    comparison; the follower quarantines (session reads redirect) and
    re-bootstraps from the image — it never serves the corrupt value to
    a session-token read."""
    from antidote_tpu.overload import ReplicaLagging

    hub = LoopbackHub()
    owner, orep = mk_owner(cfg, hub, tmp_path)
    for i in range(4):
        owner.update_objects([("k", "counter_pn", "b", ("increment", 1)),
                              ("r", "register_lww", "b",
                               ("assign", f"v{i}"))])
    owner.checkpoint_now()
    fnode, fol, _mode = mk_follower(cfg, hub, tmp_path, orep)
    objs = [("k", "counter_pn", "b"), ("r", "register_lww", "b")]
    converge(owner, orep, hub, fnode, objs)
    assert all(v == "ok" for v in fol.check_divergence().values())
    # corrupt the follower's device row for "k" (silent bit damage)
    tname, shard, row = fnode.store.directory[("k", "b")]
    t = fnode.store.tables[tname]
    field = next(iter(t.head))
    t.head[field] = t.head[field].at[shard, row].set(999)
    token = [int(x) for x in owner.store.dc_max_vc()]
    res = fol.check_divergence()
    assert res.get(shard) == "mismatch", res
    assert fnode.metrics.divergence_checks.value(result="mismatch") == 1
    assert fol.last_bootstrap_mode == "image"
    # healed: the session-token read serves the TRUE value
    got, _ = fnode.read_objects(objs, clock=token)
    want, _ = owner.read_objects(objs, clock=token)
    assert got == want and want[0] == 4
    assert all(v == "ok" for v in fol.check_divergence().values())
    # while quarantined, the gate redirects instead of serving
    fol.state = "healing"
    with pytest.raises(ReplicaLagging):
        fol.gate_read(objs, np.asarray(token))
    fol.state = "serving"
    owner.store.log.close(), fnode.store.log.close()


def test_follower_crash_rejoins_from_local_state(cfg, tmp_path):
    """A killed follower rejoins fast from its OWN WAL + local
    checkpoint (mode tail) and converges byte-identical."""
    hub = LoopbackHub()
    owner, orep = mk_owner(cfg, hub, tmp_path)
    for i in range(5):
        owner.update_objects([("k", "counter_pn", "b", ("increment", 1))])
    owner.checkpoint_now()
    fnode, fol, mode = mk_follower(cfg, hub, tmp_path, orep)
    assert mode == "image"
    converge(owner, orep, hub, fnode, [("k", "counter_pn", "b")])
    # SIGKILL-equivalent: drop the live objects, keep only the disk
    hub.unregister(fol.fabric_id)
    fnode.store.log.close()
    del fnode, fol
    # the owner moves on meanwhile
    owner.update_objects([("k", "counter_pn", "b", ("increment", 10))])
    f2, fol2, mode2 = mk_follower(cfg, hub, tmp_path, orep, name="f1",
                                  fid=78, recover=True)
    assert mode2 == "tail"  # local image + WAL carried it to the floor
    want, got, clock = converge(owner, orep, hub, f2,
                                [("k", "counter_pn", "b")])
    assert got == want == [15]
    with owner.txm.commit_lock:
        own_digest = shard_digest(owner.store,
                                  owner.store.directory[("k", "b")][1])
    with f2.txm.commit_lock:
        fol_digest = shard_digest(f2.store,
                                  f2.store.directory[("k", "b")][1])
    assert own_digest == fol_digest
    owner.store.log.close(), f2.store.log.close()


def test_corrupt_newest_owner_image_falls_back_older(cfg, tmp_path):
    """Image shipping survives a bit-rotted newest image on the owner:
    the follower's fetch fails CRC verification and falls back to the
    next OLDER retained image (the owner's own recovery discipline),
    then replays the longer tail to the same state."""
    hub = LoopbackHub()
    owner, orep = mk_owner(cfg, hub, tmp_path)
    owner.update_objects([("k", "counter_pn", "b", ("increment", 3))])
    owner.checkpoint_now()
    owner.update_objects([("k", "counter_pn", "b", ("increment", 4))])
    owner.checkpoint_now()
    # bit-rot the newest image (id 2) on the owner's disk
    import os

    from antidote_tpu.log import checkpoint as ckpt

    newest = ckpt.image_path(owner.store.log.dir, 2)
    with open(newest, "r+b") as f:
        f.seek(16)
        f.write(b"\xff\xff\xff\xff")
    assert os.path.exists(ckpt.image_path(owner.store.log.dir, 1))
    fnode, fol, mode = mk_follower(cfg, hub, tmp_path, orep)
    assert mode == "image"
    want, got, _ = converge(owner, orep, hub, fnode,
                            [("k", "counter_pn", "b")])
    assert got == want == [7]
    assert all(v == "ok" for v in fol.check_divergence().values())
    owner.store.log.close(), fnode.store.log.close()


def test_apb_dialect_refused_on_follower(cfg, tmp_path):
    """The follower's apb edge stays safe without a proxy plane: with
    ``--no-server-proxy`` every apb write/txn request answers the
    typed not_owner redirect (never an acked-then-discarded write).
    With the plane attached but the owner UNREACHABLE, forwarding
    exhausts its send-phase dial budget and degrades to the SAME typed
    redirect — the fabric never invents a third failure mode."""
    import socket
    import struct

    from antidote_tpu.proto import apb as apb_mod
    from antidote_tpu.proto.client import ApbClient, RemoteNotOwner
    from antidote_tpu.proto.server import ProtocolServer

    hub = LoopbackHub()
    owner, orep = mk_owner(cfg, hub, tmp_path)
    owner.update_objects([("k", "counter_pn", "b", ("increment", 1))])
    fnode, fol, _ = mk_follower(cfg, hub, tmp_path, orep)
    srv = ProtocolServer(fnode, port=0, follower=fol,
                         server_proxy=False)
    try:
        code = sorted(apb_mod.APB_REQUEST_CODES)[0]
        sock = socket.create_connection((srv.host, srv.port), timeout=10)
        body = bytes([code])
        sock.sendall(struct.pack(">I", len(body)) + body)
        hdr = sock.recv(4)
        (n,) = struct.unpack(">I", hdr)
        reply = b""
        while len(reply) < n:
            reply += sock.recv(n - len(reply))
        assert b"not_owner" in reply, reply
        sock.close()
        # plane attached, owner unreachable (the fake bootstrap addr):
        # a well-formed apb write exhausts the dial budget and surfaces
        # the typed redirect carrying the owner endpoint
        srv2 = ProtocolServer(fnode, port=0, follower=fol)
        try:
            fc = ApbClient(srv2.host, srv2.port)
            with pytest.raises(RemoteNotOwner) as ei:
                fc.update_objects([(b"k", "counter_pn", b"b",
                                    ("increment", 1))])
            assert ei.value.redirect == ["owner-host", 1234]
            fc.close()
        finally:
            srv2.close()
        # a follower server also refuses the unsafe inline-read mode
        with pytest.raises(ValueError, match="batch_static"):
            ProtocolServer(fnode, port=0, follower=fol,
                           batch_static=False)
    finally:
        srv.close()
        owner.store.log.close(), fnode.store.log.close()


def test_gate_read_parks_then_redirects(cfg, tmp_path):
    from antidote_tpu.overload import ReplicaLagging

    hub = LoopbackHub()
    owner, orep = mk_owner(cfg, hub, tmp_path)
    owner.update_objects([("k", "counter_pn", "b", ("increment", 1))])
    fnode, fol, _ = mk_follower(cfg, hub, tmp_path, orep,
                                park_s=0.05)
    converge(owner, orep, hub, fnode, [("k", "counter_pn", "b")])
    # a token the follower covers: gate passes without parking
    fol.gate_read([("k", "counter_pn", "b")],
                  np.asarray(fnode.store.dc_max_vc()))
    # a token ahead of everything the follower applied: parks ~park_s,
    # then the typed redirect carries the owner endpoint + retry hint
    ahead = owner.store.dc_max_vc().astype(np.int64) + 50
    t0 = time.monotonic()
    with pytest.raises(ReplicaLagging) as ei:
        fol.gate_read([("k", "counter_pn", "b")], ahead)
    assert time.monotonic() - t0 >= 0.04
    assert ei.value.redirect == ["owner-host", 1234]
    assert ei.value.retry_after_ms > 0
    assert fnode.metrics.session_redirects.value(
        kind="lagging", dialect="native") >= 1
    owner.store.log.close(), fnode.store.log.close()


def test_owner_replica_registry_and_liveness(cfg, tmp_path):
    hub = LoopbackHub()
    owner, orep = mk_owner(cfg, hub, tmp_path)
    owner.update_objects([("k", "counter_pn", "b", ("increment", 1))])
    fnode, fol, _ = mk_follower(cfg, hub, tmp_path, orep)
    st = orep.replica_status()
    assert st["role"] == "owner" and st["followers"]["f1"]["state"] == "ok"
    assert st["followers"]["f1"]["lag"] == 0
    # reports age out into the typed DOWN state
    orep.REPLICA_DOWN_S = 0.0
    time.sleep(0.01)
    assert orep.replica_status()["followers"]["f1"]["state"] == "down"
    orep.REPLICA_DOWN_S = DCReplica.REPLICA_DOWN_S
    fol._send_report()
    assert orep.replica_status()["followers"]["f1"]["state"] == "ok"
    # decommission: the registry forgets it and refuses its reports
    out = orep.replica_admin({"op": "remove", "name": "f1"})
    assert "f1" not in out["followers"]
    fol._send_report()
    assert "f1" not in orep.replica_status()["followers"]
    # re-add clears the tombstone (shows down until it reports again)
    out = orep.replica_admin({"op": "add", "name": "f1",
                              "addr": ["h", 9]})
    assert out["followers"]["f1"]["state"] == "down"
    fol._send_report()
    assert orep.replica_status()["followers"]["f1"]["state"] == "ok"
    owner.store.log.close(), fnode.store.log.close()


# ---------------------------------------------------------------------------
# Part C — fleet shadowing (ISSUE 11): clustered / geo owners, the apb
# session tier, streak-scaled gate hints
# ---------------------------------------------------------------------------
def test_clustered_owner_fleet_shadowing_and_live_shard_move(tmp_path):
    """A follower shadows a 2-member CLUSTERED owner: bootstrap composes
    both members' checkpoint images (each restricted to its owned
    shards), the live tail flows over per-member subscriptions, session
    reads are byte-identical to the owner at equal applied clocks
    (divergence digest clean on every shard against whichever member
    owns it), and a LIVE shard move mid-stream re-points catch-up +
    digest routing through the ownership-epoch gossip with no
    reconnect."""
    from antidote_tpu.cluster import ClusterNode, attach_interdc
    from antidote_tpu.cluster.join import _move_shard
    from antidote_tpu.cluster.member import ClusterMember
    from antidote_tpu.cluster.rpc import RpcClient
    from antidote_tpu.interdc.tcp import TcpFabric

    ccfg = AntidoteConfig(
        n_shards=4, max_dcs=3, ops_per_key=8, snap_versions=2,
        set_slots=4, keys_per_table=32, batch_buckets=(8,),
    )
    fab = TcpFabric(backoff_base=0.05, backoff_max=0.5)
    ffab = TcpFabric(backoff_base=0.05, backoff_max=0.5)
    ms = [ClusterMember(ccfg, dc_id=0, member_id=i, n_members=2,
                        log_dir=str(tmp_path / f"m{i}"))
          for i in range(2)]
    for a in ms:
        for b in ms:
            if a is not b:
                a.connect(b.member_id, *b.address)
    reps = [attach_interdc(m, fab) for m in ms]
    coord = ClusterNode(ms[0])
    fnode = fol = None
    try:
        n_keys = 8
        for r in range(3):
            for k in range(n_keys):
                coord.update_objects([(k, "counter_pn", "b",
                                       ("increment", 1))])
        for m in ms:
            m.node.checkpoint_now()
        # blank follower: per-member image composition
        fnode = AntidoteNode(ccfg, dc_id=0,
                             log_dir=str(tmp_path / "fol"))
        fol = FollowerReplica(fnode, ffab, "cf1",
                              owner_client_addr=("owner-host", 1),
                              fabric_id=301)
        mode = fol.attach([r.descriptor() for r in reps])
        assert mode == "image"
        assert fol.state == "serving"
        assert len(fol.member_fids) == 2
        objs = [(k, "counter_pn", "b") for k in range(n_keys)]

        def converge_fleet(expect):
            deadline = time.monotonic() + 60
            while True:
                for r in reps:
                    r.heartbeat()
                for m in ms:
                    m.refresh_peer_clocks()
                fab.pump(timeout=0.05)
                ffab.pump(timeout=0.05)
                target = np.maximum.reduce(
                    [m.node.store.dc_max_vc() for m in ms])
                if (fnode.store.stable_vc() >= target).all():
                    got, _ = fnode.read_objects(objs, clock=target)
                    if got == expect:
                        return target
                assert time.monotonic() < deadline, (
                    f"fleet follower never converged: "
                    f"{fnode.store.stable_vc()} < {target}")

        converge_fleet([3] * n_keys)
        res = fol.check_divergence()
        assert all(v == "ok" for v in res.values()), res
        # live tail keeps flowing from BOTH members
        for k in range(n_keys):
            coord.update_objects([(k, "counter_pn", "b",
                                   ("increment", 1))])
        converge_fleet([4] * n_keys)
        # LIVE shard move mid-fleet: member 1 -> member 0; the follower
        # keeps its (already-open) subscriptions and the ownership-epoch
        # gossip re-points catch-up + digest routing — no reconnect
        moved = next(s for s in range(ccfg.n_shards)
                     if s in ms[1].shards)
        clients = {m.member_id: RpcClient(*m.address) for m in ms}
        try:
            _move_shard(clients, moved, 1, 0, 2)
        finally:
            for c in clients.values():
                c.close()
        assert moved in ms[0].shards and moved not in ms[1].shards
        for k in range(n_keys):
            coord.update_objects([(k, "counter_pn", "b",
                                   ("increment", 1))])
        converge_fleet([5] * n_keys)
        # the follower learned the move from the egress gossip and now
        # routes the moved shard's digest to the NEW owner
        assert fol.shard_route[(0, moved)][0] == 0
        res = fol.check_divergence()
        assert all(v == "ok" for v in res.values()), res
        assert fol._route(0, moved) == reps[0].fabric_id
        # both members' registries saw the follower's reports
        for r in reps:
            st = r.replica_status()
            assert "cf1" in st["followers"], st
    finally:
        for m in ms:
            try:
                m.close()
            except Exception:
                pass
        fab.close()
        ffab.close()
        if fnode is not None and fnode.store.log is not None:
            fnode.store.log.close()


def test_geo_owner_shadowing_peer_chains(cfg, tmp_path):
    """A follower of a GEO-REPLICATED owner subscribes to the peer DC's
    stream too (its descriptor is part of the fleet), applies the peer's
    origin chain through the same causal gate the owner does, and
    converges byte-identical — divergence digests clean across every
    lane at equal applied clocks."""
    hub = LoopbackHub()
    owner, orep = mk_owner(cfg, hub, tmp_path)
    peer = AntidoteNode(cfg, dc_id=1, log_dir=str(tmp_path / "peer"))
    prep = DCReplica(peer, hub, "dc1")
    orep.observe_dc(prep)
    prep.observe_dc(orep)
    for i in range(3):
        owner.update_objects([("k", "counter_pn", "b", ("increment", 1))])
        peer.update_objects([("k", "counter_pn", "b", ("increment", 10))])
    owner.checkpoint_now()
    fnode = AntidoteNode(cfg, dc_id=0, log_dir=str(tmp_path / "gf"))
    fol = FollowerReplica(fnode, hub, "gf1",
                          owner_client_addr=("owner-host", 1234),
                          fabric_id=99)
    mode = fol.attach([orep.descriptor(), prep.descriptor()])
    assert mode == "image"
    assert sorted(fol.fleet_by_dc) == [0, 1]
    objs = [("k", "counter_pn", "b")]
    # the live tail: both origins' later commits reach the follower over
    # its OWN subscriptions (the owner never re-publishes peer effects)
    owner.update_objects([("k", "counter_pn", "b", ("increment", 1))])
    peer.update_objects([("k", "counter_pn", "b", ("increment", 10))])
    deadline = time.monotonic() + 30
    while True:
        orep.heartbeat()
        prep.heartbeat()
        hub.pump()
        target = np.maximum(owner.store.dc_max_vc(),
                            peer.store.dc_max_vc())
        if (fnode.store.stable_vc() >= target).all():
            break
        assert time.monotonic() < deadline
    want, _ = owner.read_objects(objs, clock=target)
    got, _ = fnode.read_objects(objs, clock=target)
    assert got == want == [44]
    res = fol.check_divergence()
    assert all(v == "ok" for v in res.values()), res
    assert fol.replica_status()["fleet"]["peer_dcs"] == [1]
    owner.store.log.close()
    peer.store.log.close()
    fnode.store.log.close()


def test_apb_session_tier_on_follower(cfg, tmp_path):
    """The apb protobuf dialect gets the SAME session discipline the
    msgpack dialect has on a follower (ISSUE 11) — and with the
    symmetric serving fabric (ISSUE 17) the follower is a safe apb
    entrypoint: writes FORWARD to the owner write plane instead of
    bouncing on a typed not_owner, a token-ahead read fails over
    server-side to the owner instead of surfacing typed lagging, and
    the session tier keeps read-your-writes either way."""
    from antidote_tpu.proto.client import ApbClient, SessionClient
    from antidote_tpu.proto.server import ProtocolServer

    hub = LoopbackHub()
    owner, orep = mk_owner(cfg, hub, tmp_path)
    owner.update_objects([("k", "counter_pn", "b", ("increment", 1))])
    owner.checkpoint_now()
    fnode, fol, _ = mk_follower(cfg, hub, tmp_path, orep, park_s=0.05)
    osrv = ProtocolServer(owner, port=0, interdc=orep)
    fsrv = ProtocolServer(fnode, port=0, follower=fol)
    fol.owner_client_addr = (osrv.host, osrv.port)
    try:
        # apb write at the follower: forwarded to the owner write plane
        # with RYW at the returned commit clock (the apb keyspace is
        # bytes — distinct from the native str "k" above)
        fc = ApbClient(fsrv.host, fsrv.port)
        vc = fc.update_objects([(b"k", "counter_pn", b"b",
                                 ("increment", 1))])
        vals, _ = fc.read_objects([(b"k", "counter_pn", b"b")],
                                  clock=vc)
        assert vals == [1]
        assert fnode.metrics.session_redirects.value(
            kind="not_owner", dialect="apb") == 0
        assert fsrv.proxy.counts["write"] >= 1
        # apb session over the fleet: write owner, read follower, RYW
        sc = SessionClient((osrv.host, osrv.port),
                           [(fsrv.host, fsrv.port)], dialect="apb")
        total = 0
        for i in range(4):
            sc.update_objects([(b"ak", "counter_pn", b"b",
                                ("increment", 1))])
            total += 1
            # converge the follower so the gate admits promptly
            for _ in range(40):
                orep.heartbeat()
                hub.pump()
                if (fnode.store.dc_max_vc()
                        >= owner.store.dc_max_vc()).all():
                    break
            vals, _ = sc.read_objects([(b"ak", "counter_pn", b"b")])
            assert vals == [total], (i, vals, total)
        assert sc.served_by.get((fsrv.host, fsrv.port), 0) >= 1
        # a token ahead of the replica (in the owner's own lane): the
        # gate refuses locally but the fabric fails over SERVER-SIDE to
        # the owner — the bare apb client gets the value, not typed
        # lagging, and the proxied reply teaches it the ring
        ahead = [int(x) for x in owner.store.dc_max_vc()]
        ahead[0] += 50
        fc2 = ApbClient(fsrv.host, fsrv.port)
        vals, _ = fc2.read_objects([(b"ak", "counter_pn", b"b")],
                                   clock=ahead)
        assert vals == [total]
        assert fsrv.proxy.counts["read"] >= 1
        assert fc2.ring_hint is not None
        assert fc2.ring_hint["owner"] == [osrv.host, osrv.port]
        fc.close(), fc2.close(), sc.close()
    finally:
        fsrv.close()
        osrv.close()
        owner.store.log.close(), fnode.store.log.close()


def test_gate_retry_hint_scales_with_refusal_streak(cfg, tmp_path):
    """Satellite: the follower gate's retry hint scales with the
    refusal streak since the last admitted read (25..500 ms, the
    AdmissionGate discipline) — a parked fleet backs off instead of
    hammering a lagging follower on a fixed hint."""
    from antidote_tpu.overload import ReplicaLagging

    hub = LoopbackHub()
    owner, orep = mk_owner(cfg, hub, tmp_path)
    owner.update_objects([("k", "counter_pn", "b", ("increment", 1))])
    fnode, fol, _ = mk_follower(cfg, hub, tmp_path, orep, park_s=0.0)
    converge(owner, orep, hub, fnode, [("k", "counter_pn", "b")])
    ahead = owner.store.dc_max_vc().astype(np.int64) + 50
    hints = []
    for _ in range(40):
        with pytest.raises(ReplicaLagging) as ei:
            fol.gate_read([("k", "counter_pn", "b")], ahead)
        hints.append(ei.value.retry_after_ms)
    assert hints[0] == 25
    assert hints[-1] > hints[0]
    assert max(hints) <= 500
    # an admitted read resets the streak — hints start over
    fol.gate_read([("k", "counter_pn", "b")],
                  np.asarray(fnode.store.dc_max_vc()))
    with pytest.raises(ReplicaLagging) as ei:
        fol.gate_read([("k", "counter_pn", "b")], ahead)
    assert ei.value.retry_after_ms == 25
    owner.store.log.close(), fnode.store.log.close()


# ---------------------------------------------------------------------------
# Part B — the wire stack (TCP fabrics + ProtocolServers + SessionClient)
# ---------------------------------------------------------------------------
class _Pump:
    def __init__(self, *fabrics):
        self.stop = threading.Event()
        self.threads = [
            threading.Thread(target=self._loop, args=(f,), daemon=True)
            for f in fabrics
        ]
        for t in self.threads:
            t.start()

    def _loop(self, fabric):
        while not self.stop.is_set():
            try:
                fabric.pump(timeout=0.05)
            except OSError:
                time.sleep(0.02)

    def close(self):
        self.stop.set()
        for t in self.threads:
            t.join(timeout=10)


def _wire_follower(cfg, tmp_path, owner_srv, name, fid, recover=False,
                   park_s=0.3):
    from antidote_tpu.interdc.tcp import TcpFabric
    from antidote_tpu.proto.client import AntidoteClient
    from antidote_tpu.proto.server import ProtocolServer

    fabric = TcpFabric(backoff_base=0.05, backoff_max=0.5)
    node = AntidoteNode(cfg, dc_id=0, log_dir=str(tmp_path / name),
                        recover=recover)
    fol = FollowerReplica(node, fabric, name,
                          owner_client_addr=(owner_srv.host,
                                             owner_srv.port),
                          fabric_id=fid, park_s=park_s)
    srv = ProtocolServer(node, port=0, follower=fol)
    fol.client_addr = (srv.host, srv.port)
    c = AntidoteClient(owner_srv.host, owner_srv.port)
    desc = c.get_connection_descriptor()
    c.close()
    mode = fol.attach(desc)
    return {"node": node, "fol": fol, "srv": srv, "fabric": fabric,
            "mode": mode}


def test_wire_session_survives_follower_kill_and_rejoin(cfg, tmp_path):
    """The acceptance flow end-to-end on real sockets: write on the
    owner, read own writes via followers with a session token, SIGKILL
    one follower mid-session (client fails over with read-your-writes
    held), rejoin it from its image, converge byte-identical."""
    from antidote_tpu.interdc.tcp import TcpFabric
    from antidote_tpu.proto.client import AntidoteClient, SessionClient
    from antidote_tpu.proto.server import ProtocolServer

    ofab = TcpFabric(backoff_base=0.05, backoff_max=0.5)
    owner = AntidoteNode(cfg, dc_id=0, log_dir=str(tmp_path / "owner"))
    orep = DCReplica(owner, ofab, "dc0")
    osrv = ProtocolServer(owner, port=0, interdc=orep)
    pump = _Pump(ofab)
    f1 = f2 = None
    try:
        oc = AntidoteClient(osrv.host, osrv.port)
        for i in range(4):
            oc.update_objects([("k", "counter_pn", "b", ("increment", 1))])
        oc.checkpoint_now()
        f1 = _wire_follower(cfg, tmp_path, osrv, "wf1", 101)
        f2 = _wire_follower(cfg, tmp_path, osrv, "wf2", 102)
        assert f1["mode"] == "image" and f2["mode"] == "image"
        pump2 = _Pump(f1["fabric"], f2["fabric"])
        try:
            # a write sent AT a follower FORWARDS to the owner write
            # plane (ISSUE 17): the ring-oblivious client gets a commit
            # clock and read-your-writes, not a typed redirect
            fc = AntidoteClient(f1["srv"].host, f1["srv"].port)
            vc = fc.update_objects([("k", "counter_pn", "b",
                                     ("increment", 1))])
            vals, _ = fc.read_objects([("k", "counter_pn", "b")],
                                      clock=vc)
            assert vals == [5]
            fc.close()
            sc = SessionClient(
                (osrv.host, osrv.port),
                [(f1["srv"].host, f1["srv"].port),
                 (f2["srv"].host, f2["srv"].port)],
            )
            # session loop: every read (served by a follower) must see
            # the session's own writes
            total = 5
            for i in range(6):
                sc.update_objects([("k", "counter_pn", "b",
                                    ("increment", 1))])
                total += 1
                vals, _ = sc.read_objects([("k", "counter_pn", "b")])
                assert vals == [total], (i, vals, total)
            assert sc.failovers == 0
            # kill follower 1 mid-session: its replication stops (fabric
            # closed) and its server winds down — the session keeps
            # holding read-your-writes by redirecting/failing over (f2,
            # then owner).  A real SIGKILL (dead-socket failover) is
            # chaos scenario 15's job.
            f1["srv"].close()
            f1["fabric"].close()
            f1["node"].store.log.close()
            f1_addr = (f1["srv"].host, f1["srv"].port)
            served_dead_before = sc.served_by.get(f1_addr, 0)
            re_before, fo_before = sc.redirects, sc.failovers
            for i in range(4):
                sc.update_objects([("k", "counter_pn", "b",
                                    ("increment", 1))])
                total += 1
                vals, _ = sc.read_objects([("k", "counter_pn", "b")])
                assert vals == [total], (i, vals, total)
            # ring semantics under the symmetric fabric (ISSUE 17):
            # after the wind-down the follower either drops off (dead
            # socket / one last typed redirect — the client fails over
            # and its served_by counter stops moving) or its
            # still-draining server keeps the session alive by
            # RESCUING gate refusals through the proxy plane — its
            # applied clock is frozen (fabric closed), so any read it
            # still answered MUST have crossed the proxy to the owner.
            # Which branch runs depends on whether the fleet reports
            # had distributed before the kill; both hold RYW.
            served_delta = (sc.served_by.get(f1_addr, 0)
                            - served_dead_before)
            if served_delta:
                assert f1["srv"].proxy is not None
                assert (f1["srv"].proxy.counts["read"]
                        >= served_delta)
            elif sc.ring.preferred("k", "b") == f1_addr:
                assert (sc.redirects - re_before
                        + sc.failovers - fo_before) >= 1
            # rejoin follower 1 from its local image + the owner's tail
            f1b = _wire_follower(cfg, tmp_path, osrv, "wf1", 103,
                                 recover=True)
            pump3 = _Pump(f1b["fabric"])
            try:
                assert f1b["mode"] in ("tail", "delta", "image")
                token = [int(x) for x in oc.node_status()["stable_vc"]]
                sc2 = SessionClient((osrv.host, osrv.port),
                                    [(f1b["srv"].host, f1b["srv"].port)])
                sc2.observe(token)
                deadline = time.monotonic() + 30
                while True:
                    vals, _ = sc2.read_objects([("k", "counter_pn", "b")])
                    if sc2.redirects == 0 and sc2.failovers == 0:
                        break  # served by the rejoined follower itself
                    sc2.redirects = sc2.failovers = 0
                    assert time.monotonic() < deadline
                    time.sleep(0.1)
                assert vals == [total]
                # byte-identical: digests agree on every shard
                deadline = time.monotonic() + 30
                while True:
                    res = f1b["fol"].check_divergence()
                    assert "mismatch" not in res.values(), res
                    if all(v == "ok" for v in res.values()):
                        break
                    assert time.monotonic() < deadline
                    time.sleep(0.1)
                # owner-side registry sees both live followers
                st = oc.replica_admin("status")
                assert st["followers"]["wf1"]["state"] == "ok"
                assert st["followers"]["wf2"]["state"] == "ok"
                sc2.close()
            finally:
                pump3.close()
                f1b["srv"].close()
                f1b["fabric"].close()
                f1b["node"].store.log.close()
            sc.close()
        finally:
            pump2.close()
            f2["srv"].close()
            f2["fabric"].close()
            f2["node"].store.log.close()
        oc.close()
    finally:
        pump.close()
        osrv.close()
        ofab.close()
        owner.store.log.close()
