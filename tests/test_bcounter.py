"""Bounded-counter escrow manager — the bcountermgr_SUITE analogue
(/root/reference/test/multidc/bcountermgr_SUITE.erl): decrement guard,
queued transfer requests from richer DCs, grace-period throttling, and the
granter side committing transfer updates that replicate back."""

import pytest

from antidote_tpu.api import AntidoteNode
from antidote_tpu.config import AntidoteConfig
from antidote_tpu.interdc import DCReplica, LoopbackHub
from antidote_tpu.overload import InsufficientRightsError
from antidote_tpu.txn.manager import AbortError


@pytest.fixture
def cfg():
    return AntidoteConfig(
        n_shards=2, max_dcs=3, ops_per_key=8, snap_versions=2,
        set_slots=4, keys_per_table=16, batch_buckets=(8,),
    )


@pytest.fixture
def dcs(cfg):
    hub = LoopbackHub()
    nodes = [AntidoteNode(cfg, dc_id=i) for i in range(3)]
    reps = [DCReplica(n, hub, f"dc{i}") for i, n in enumerate(nodes)]
    DCReplica.connect_all(reps)
    return hub, nodes, reps


def test_decrement_within_rights(dcs):
    hub, nodes, _ = dcs
    nodes[0].update_objects([("c", "counter_b", "b", ("increment", (10, 0)))])
    nodes[0].update_objects([("c", "counter_b", "b", ("decrement", (4, 0)))])
    vals, _ = nodes[0].read_objects([("c", "counter_b", "b")])
    assert vals[0] == 6


def test_decrement_beyond_rights_aborts(dcs):
    hub, nodes, _ = dcs
    nodes[0].update_objects([("c", "counter_b", "b", ("increment", (3, 0)))])
    with pytest.raises(InsufficientRightsError, match="insufficient rights"):
        nodes[0].update_objects([("c", "counter_b", "b", ("decrement", (5, 0)))])
    # value untouched; the needed amount is queued for the transfer loop
    vals, _ = nodes[0].read_objects([("c", "counter_b", "b")])
    assert vals[0] == 3
    assert nodes[0].txm.bcounters.pending == {("c", "b"): 5}


def test_transfer_loop_moves_rights_between_dcs(dcs):
    """DC1 cannot decrement until DC0 grants rights via the query channel
    (the new_dc / transfer flow of bcountermgr_SUITE)."""
    hub, nodes, reps = dcs
    vc = nodes[0].update_objects([("c", "counter_b", "b", ("increment", (10, 0)))])
    hub.pump()
    # DC1 sees the value but holds no rights
    vals, _ = nodes[1].read_objects([("c", "counter_b", "b")], clock=vc)
    assert vals[0] == 10
    with pytest.raises(InsufficientRightsError):
        nodes[1].update_objects([("c", "counter_b", "b", ("decrement", (4, 1)))])
    # transfer loop: DC1 asks DC0 (the richest lane); DC0 commits a
    # transfer; replication delivers it back to DC1
    sent = reps[1].bcounter_tick()
    assert sent == 1
    hub.pump()
    nodes[1].update_objects([("c", "counter_b", "b", ("decrement", (4, 1)))])
    hub.pump()
    for n in nodes:
        vals, _ = n.read_objects([("c", "counter_b", "b")],
                                 clock=nodes[1].txm.store.dc_max_vc())
        assert vals[0] == 6
    assert nodes[1].txm.bcounters.pending == {}


def test_transfer_request_throttled_by_grace_period(dcs):
    hub, nodes, reps = dcs
    t = [0.0]
    nodes[1].txm.bcounters.clock = lambda: t[0]
    nodes[0].update_objects([("c", "counter_b", "b", ("increment", (10, 0)))])
    hub.pump()
    with pytest.raises(InsufficientRightsError):
        nodes[1].update_objects([("c", "counter_b", "b", ("decrement", (20, 1)))])
    # drop the granted transfer so the shortfall persists
    hub.drop_next(0, 1, n=10)
    assert reps[1].bcounter_tick() == 1
    hub.pump()
    # same instant: throttled, no second request
    with pytest.raises(InsufficientRightsError):
        nodes[1].update_objects([("c", "counter_b", "b", ("decrement", (20, 1)))])
    assert reps[1].bcounter_tick() == 0
    # after the grace period the request is retried
    t[0] += 2.0
    assert reps[1].bcounter_tick() >= 1


def test_granter_refuses_when_broke(dcs):
    hub, nodes, reps = dcs
    nodes[0].update_objects([("c", "counter_b", "b", ("increment", (2, 0)))])
    hub.pump()
    granted = nodes[0].txm.bcounters.process_transfer(
        nodes[0].txm, "c", "b", 5, 1
    )
    assert granted == 2  # grants only what it holds
    granted = nodes[2].txm.bcounters.process_transfer(
        nodes[2].txm, "c", "b", 5, 1
    )
    assert granted == 0  # DC2 holds nothing


def test_foreign_lane_decrement_rejected(dcs):
    """A decrement naming another replica's lane would spend rights this
    replica does not own — must abort even if that lane is rich."""
    hub, nodes, _ = dcs
    vc = nodes[0].update_objects([("c", "counter_b", "b", ("increment", (9, 0)))])
    hub.pump()
    with pytest.raises(AbortError, match="lane"):
        nodes[1].update_objects(
            [("c", "counter_b", "b", ("decrement", (1, 0)))], clock=vc
        )


def test_client_transfer_requires_local_rights(dcs):
    """A client-issued transfer must originate at the owning replica and
    be covered by its rights — otherwise DC1 could steal DC0's escrow."""
    hub, nodes, _ = dcs
    vc = nodes[0].update_objects([("c", "counter_b", "b", ("increment", (5, 0)))])
    hub.pump()
    # theft attempt: DC1 names DC0 as the source
    with pytest.raises(AbortError, match="lane"):
        nodes[1].update_objects(
            [("c", "counter_b", "b", ("transfer", (5, 1, 0)))], clock=vc
        )
    # over-transfer from own (empty) lane
    with pytest.raises(InsufficientRightsError, match="insufficient rights"):
        nodes[1].update_objects(
            [("c", "counter_b", "b", ("transfer", (1, 0, 1)))], clock=vc
        )
    # legitimate transfer from the owner works
    nodes[0].update_objects([("c", "counter_b", "b", ("transfer", (2, 1, 0)))])
    hub.pump()
    nodes[1].update_objects([("c", "counter_b", "b", ("decrement", (2, 1)))])


def test_transfer_queue_retires_when_rights_arrive(dcs):
    """Once grants land, the tick drops the queue entry instead of
    re-requesting forever (abandoned-client scenario)."""
    hub, nodes, reps = dcs
    nodes[0].update_objects([("c", "counter_b", "b", ("increment", (10, 0)))])
    hub.pump()
    with pytest.raises(InsufficientRightsError):
        nodes[1].update_objects([("c", "counter_b", "b", ("decrement", (4, 1)))])
    assert reps[1].bcounter_tick() == 1   # request sent, grant replicates
    hub.pump()
    # client never retries; the next tick sees the rights and retires the
    # entry without another request
    assert reps[1].bcounter_tick() == 0
    assert nodes[1].txm.bcounters.pending == {}


def test_concurrent_decrements_never_go_negative(dcs):
    """Escrow safety: both DCs decrement concurrently from their own
    rights; the merged value stays ≥ 0."""
    hub, nodes, reps = dcs
    vc = nodes[0].update_objects([
        ("c", "counter_b", "b", ("increment", (6, 0))),
        ("c", "counter_b", "b", ("transfer", (3, 1, 0))),
    ])
    hub.pump()
    nodes[0].update_objects([("c", "counter_b", "b", ("decrement", (3, 0)))])
    nodes[1].update_objects([("c", "counter_b", "b", ("decrement", (3, 1)))],
                            clock=vc)
    hub.pump()
    for n in nodes:
        vals, _ = n.read_objects([("c", "counter_b", "b")],
                                 clock=n.txm.store.dc_max_vc())
        assert vals[0] == 0
    # both replicas are now dry: further decrements refuse everywhere
    for i in (0, 1):
        with pytest.raises(InsufficientRightsError):
            nodes[i].update_objects(
                [("c", "counter_b", "b", ("decrement", (1, i)))]
            )


def test_refusal_streak_scales_hint_and_rebalance(dcs):
    """Repeated refusals on the same key build a streak: the retry hint
    grows with it and the transfer loop over-asks (proactive rebalance)
    once the streak crosses the threshold."""
    from antidote_tpu.txn import bcounter as bc
    hub, nodes, reps = dcs
    nodes[0].update_objects([("c", "counter_b", "b", ("increment", (100, 0)))])
    hub.pump()
    mgr = nodes[1].txm.bcounters
    base = int(bc.TRANSFER_FREQ * 1e3)
    for streak in (1, 2, 3):
        with pytest.raises(InsufficientRightsError) as ei:
            nodes[1].update_objects(
                [("c", "counter_b", "b", ("decrement", (5, 1)))]
            )
        assert ei.value.retry_after_ms == min(
            bc.HINT_CAP_MS, base * (1 + streak)
        )
    # streak 3 >= REBALANCE_STREAK: the request over-asks by the factor
    # (exercise the per-key fallback; the batched twin has its own test)
    captured = []
    mgr.request_transfer_many = None
    mgr.request_transfer = lambda dc, key, bucket, n: captured.append(n)
    reps[1].bcounter_tick()
    assert captured == [5 * min(bc.REBALANCE_MAX_FACTOR, 3)]
    assert mgr.refused_total == 3
    assert mgr.requests_sent_total == 1


def test_refusal_state_prunes_and_status(dcs):
    """_last_request and stale streaks are pruned each tick; status()
    reports the live escrow picture (bounded observability)."""
    from antidote_tpu.txn import bcounter as bc
    hub, nodes, reps = dcs
    t = [0.0]
    mgr = nodes[1].txm.bcounters
    mgr.clock = lambda: t[0]
    nodes[0].update_objects([("c", "counter_b", "b", ("increment", (10, 0)))])
    hub.pump()
    with pytest.raises(InsufficientRightsError):
        nodes[1].update_objects([("c", "counter_b", "b", ("decrement", (4, 1)))])
    st = mgr.status()
    assert st["pending_keys"] == 1 and st["shortfall"] == 4
    assert st["refused_total"] == 1
    assert reps[1].bcounter_tick() == 1
    assert (("c", "b"), 0) in mgr._last_request
    hub.pump()
    # grant landed: the next tick retires the entry and its streak
    assert reps[1].bcounter_tick() == 0
    assert mgr.pending == {} and mgr._refusals == {}
    # throttle entries older than the grace period are pruned
    t[0] += bc.GRACE_PERIOD + 0.1
    reps[1].bcounter_tick()
    assert mgr._last_request == {}
    assert mgr.status()["shortfall"] == 0


def test_rights_conservation_under_seeded_interleavings(dcs):
    """Property: across seeded transfer/decrement interleavings the
    global invariant holds at every converged point — value equals total
    increments minus total successful decrements, never negative, and
    rights are conserved (transfers move, never mint)."""
    import random

    hub, nodes, reps = dcs
    for seed in (1, 7, 42):
        rng = random.Random(seed)
        key = f"inv{seed}"
        total = 60
        nodes[0].update_objects(
            [("c", "counter_b", key, ("increment", (total, 0)))]
        )
        hub.pump()
        sold = 0
        for step in range(30):
            dc = rng.randrange(2)
            n = rng.randint(1, 5)
            action = rng.random()
            try:
                if action < 0.6:
                    nodes[dc].update_objects(
                        [("c", "counter_b", key, ("decrement", (n, dc)))]
                    )
                    sold += n
                else:
                    to = 1 - dc
                    nodes[dc].update_objects(
                        [("c", "counter_b", key, ("transfer", (n, to, dc)))]
                    )
            except InsufficientRightsError:
                pass
            if rng.random() < 0.3:
                hub.pump()
                for r in reps:
                    r.bcounter_tick()
        hub.pump()
        for r in reps:
            r.bcounter_tick()
        hub.pump()
        assert sold <= total
        vc = nodes[0].txm.store.dc_max_vc()
        for n_ in nodes:
            vals, _ = n_.read_objects([("c", "counter_b", key)], clock=vc)
            assert vals[0] == total - sold
            assert vals[0] >= 0
        # conservation: transfers move rights, never mint — the per-lane
        # holdings always sum to the value, and the mint total (diagonal)
        # never changes
        import numpy as np

        from antidote_tpu.crdt import get_type

        ty = get_type("counter_b")
        st = nodes[0].txm.store.read_states([("c", "counter_b", key)], vc)[0]
        d = np.asarray(st["used"]).shape[0]
        assert sum(ty.local_rights(st, dc) for dc in range(d)) == total - sold
        assert int(np.trace(np.asarray(st["rights"]))) == total
        assert all(ty.local_rights(st, dc) >= 0 for dc in range(d))


def test_transfer_requests_batch_into_one_round_trip(dcs):
    """Satellite (b) of ISSUE 19: many shortfall keys aimed at the same
    granter ride ONE ``bcounter_many`` query-channel round trip.  The
    throttle is stamped at accumulation time, so batching changes the
    FRAMING, not the retry contract — a second tick in the same grace
    period sends nothing."""
    hub, nodes, reps = dcs
    t = [0.0]
    mgr = nodes[1].txm.bcounters
    mgr.clock = lambda: t[0]
    for k in ("c1", "c2", "c3"):
        nodes[0].update_objects(
            [(k, "counter_b", "b", ("increment", (10, 0)))])
    hub.pump()
    for k in ("c1", "c2", "c3"):
        with pytest.raises(InsufficientRightsError):
            nodes[1].update_objects(
                [(k, "counter_b", "b", ("decrement", (4, 1)))])
    assert len(mgr.pending) == 3
    captured = []
    mgr.request_transfer_many = (
        lambda dc, entries: captured.append((dc, list(entries))))
    assert reps[1].bcounter_tick() == 3  # per-ask accounting unchanged
    # one call, one target DC, all three asks inside
    assert len(captured) == 1
    dc, entries = captured[0]
    assert dc == 0
    assert sorted(k for k, _b, _n in entries) == ["c1", "c2", "c3"]
    assert all(b == "b" and n == 4 for _k, b, n in entries)
    assert mgr.requests_sent_total == 3
    # same instant: every ask is inside its grace period — no frame
    assert reps[1].bcounter_tick() == 0
    assert captured == [(dc, entries)]
    # after the grace period the batch is re-framed
    t[0] += 2.0
    assert reps[1].bcounter_tick() == 3
    assert len(captured) == 2


def test_batched_transfer_grants_end_to_end(dcs):
    """The ``bcounter_many`` frame round-trips over the real query
    channel: one request carries three shortfalls, the granter commits
    three transfers, replication delivers the rights, and the blocked
    decrements succeed."""
    hub, nodes, reps = dcs
    calls = []
    orig = hub.request

    def counting(target_dc, kind, payload):
        calls.append(kind)
        return orig(target_dc, kind, payload)

    hub.request = counting
    for k in ("c1", "c2", "c3"):
        nodes[0].update_objects(
            [(k, "counter_b", "b", ("increment", (10, 0)))])
    hub.pump()
    for k in ("c1", "c2", "c3"):
        with pytest.raises(InsufficientRightsError):
            nodes[1].update_objects(
                [(k, "counter_b", "b", ("decrement", (4, 1)))])
    assert reps[1].bcounter_tick() == 3
    assert calls == ["bcounter_many"]  # ONE round trip for all three
    hub.pump()
    for k in ("c1", "c2", "c3"):
        nodes[1].update_objects(
            [(k, "counter_b", "b", ("decrement", (4, 1)))])
    hub.pump()
    vc = nodes[1].txm.store.dc_max_vc()
    for k in ("c1", "c2", "c3"):
        vals, _ = nodes[0].read_objects([(k, "counter_b", "b")], clock=vc)
        assert vals[0] == 6
    assert nodes[1].txm.bcounters.pending == {}
