"""Observability layer: metric parity with antidote_stats_collector
(/root/reference/src/antidote_stats_collector.erl:80-93), error monitor,
HTTP exposition, and wiring into the transaction manager."""

import logging
import urllib.request

import numpy as np
import pytest

from antidote_tpu.api.node import AntidoteNode
from antidote_tpu.config import AntidoteConfig
from antidote_tpu.obs import (
    Histogram,
    NodeMetrics,
    Timer,
    install_error_monitor,
)
from antidote_tpu.txn.manager import AbortError


def small_cfg():
    return AntidoteConfig(
        n_shards=2, max_dcs=2, ops_per_key=4, snap_versions=2,
        set_slots=4, keys_per_table=16, batch_buckets=(8,),
    )

pytestmark = pytest.mark.smoke


def test_txn_metrics_wiring():
    node = AntidoteNode(small_cfg())
    m = node.metrics
    txn = node.start_transaction()
    assert m.open_transactions.value() == 1
    node.update_objects([("k", "counter_pn", "b", ("increment", 3))], txn)
    node.read_objects([("k", "counter_pn", "b")], txn)
    node.commit_transaction(txn)
    assert m.open_transactions.value() == 0
    assert m.operations.value(type="update") == 1
    assert m.operations.value(type="read") == 1
    assert m.commit_batch_size.count == 1

    t2 = node.start_transaction()
    node.abort_transaction(t2)
    assert m.aborted_transactions.value() == 1
    assert m.open_transactions.value() == 0


def test_certification_abort_counts():
    node = AntidoteNode(small_cfg())
    t1 = node.start_transaction()
    t2 = node.start_transaction()
    # read-bearing txns keep certification (blind increments would take
    # the ISSUE 6 commutativity bypass and both commit)
    node.read_objects([("k", "counter_pn", "b")], t1)
    node.read_objects([("k", "counter_pn", "b")], t2)
    node.update_objects([("k", "counter_pn", "b", ("increment", 1))], t1)
    node.update_objects([("k", "counter_pn", "b", ("increment", 1))], t2)
    node.commit_transaction(t1)
    with pytest.raises(AbortError):
        node.commit_transaction(t2)
    assert node.metrics.aborted_transactions.value() == 1
    assert node.metrics.open_transactions.value() == 0


def test_certify_per_txn_property():
    """txn prop certify=False disables first-committer-wins for that txn
    (the certify txn property, reference get_txn_property)."""
    node = AntidoteNode(small_cfg())
    t1 = node.start_transaction()
    t2 = node.start_transaction(props={"certify": False})
    node.update_objects([("k", "counter_pn", "b", ("increment", 1))], t1)
    node.update_objects([("k", "counter_pn", "b", ("increment", 5))], t2)
    node.commit_transaction(t1)
    node.commit_transaction(t2)  # would abort under certification
    vals, _ = node.read_objects([("k", "counter_pn", "b")])
    assert vals[0] == 6


def test_hook_abort_keeps_gauge_exact():
    """A failing pre-commit hook must decrement open_transactions and count
    the abort (the hook-abort path closes the txn outside abort_transaction)."""
    node = AntidoteNode(small_cfg())
    node.register_pre_hook("b", lambda *a: (_ for _ in ()).throw(ValueError("no")))
    txn = node.start_transaction()
    with pytest.raises(AbortError):
        node.update_objects([("k", "counter_pn", "b", ("increment", 1))], txn)
    node.abort_transaction(txn)  # idempotent: must not double-count
    assert node.metrics.open_transactions.value() == 0
    assert node.metrics.aborted_transactions.value() == 1


def test_map_read_counts_one_client_op():
    """Composite map reads recurse internally; only the client-level read
    is counted (antidote_stats_collector counts coordinator-level ops)."""
    node = AntidoteNode(small_cfg())
    node.update_objects([
        ("m", "map_rr", "b", ("update", [(("f1", "counter_pn"), ("increment", 2)),
                                         (("f2", "counter_pn"), ("increment", 3))])),
    ])
    before = node.metrics.operations.value(type="read")
    vals, _ = node.read_objects([("m", "map_rr", "b")])
    assert vals[0][("f1", "counter_pn")] == 2
    assert node.metrics.operations.value(type="read") == before + 1
    # static reads must close their internal txn (gauge leak regression)
    assert node.metrics.open_transactions.value() == 0


def test_error_monitor_increments_error_count():
    m = NodeMetrics()
    logger = logging.getLogger("antidote_tpu.test_err")
    h = install_error_monitor(m, logger)
    try:
        logger.error("boom")
        logger.warning("not counted")
        assert m.error_count.value() == 1
    finally:
        logger.removeHandler(h)


def test_histogram_buckets_and_percentile():
    h = Histogram("h", buckets=(1, 10, 100))
    for v in (0.5, 5, 5, 50, 500):
        h.observe(v)
    assert h.count == 5
    assert h.percentile(0.5) == 10.0
    text = "\n".join(h.expose())
    assert 'h_bucket{le="10"} 3' in text
    assert "h_count 5" in text


def test_timer_feeds_histogram():
    h = Histogram("t", buckets=(10,))
    with Timer(h):
        pass
    assert h.count == 1


def test_metrics_http_exposition():
    node = AntidoteNode(small_cfg())
    txn = node.start_transaction()
    node.update_objects([("k", "counter_pn", "b", ("increment", 3))], txn)
    node.commit_transaction(txn)
    node.metrics.observe_staleness(12.5)
    srv = node.serve_metrics(port=0)
    try:
        body = urllib.request.urlopen(
            f"http://127.0.0.1:{srv.port}/metrics", timeout=5
        ).read().decode()
        assert 'antidote_operations_total{type="update"} 1' in body
        assert "antidote_staleness_count 1" in body
        assert "antidote_open_transactions 0" in body
    finally:
        srv.close()


def test_staleness_observed_from_stable_vc():
    node = AntidoteNode(small_cfg())
    vc = node.stable_vc()
    assert (vc == np.zeros(2)).all()
    node.metrics.observe_staleness(3.0)
    assert node.metrics.staleness.count == 1
