"""Inter-DC replication: the multidc suites on a loopback fabric.

Mirrors /root/reference/test/multidc/: multiple_dcs_SUITE (replication,
parallel writes), inter_dc_repl_SUITE (causality, atomicity) and the
message-loss catch-up path of inter_dc_sub_buf.
"""

import numpy as np
import pytest

from antidote_tpu.api import AntidoteNode
from antidote_tpu.interdc import DCReplica, LoopbackHub


@pytest.fixture
def dcs(cfg):
    hub = LoopbackHub()
    nodes = [AntidoteNode(cfg, dc_id=i) for i in range(3)]
    reps = [DCReplica(n, hub, f"dc{i}") for i, n in enumerate(nodes)]
    DCReplica.connect_all(reps)
    return hub, nodes, reps


def test_replication_basic(dcs):
    hub, nodes, reps = dcs
    vc = nodes[0].update_objects([("k", "counter_pn", "b", ("increment", 5))])
    hub.pump()
    for n in nodes[1:]:
        vals, _ = n.read_objects([("k", "counter_pn", "b")], clock=vc)
        assert vals == [5]


def test_replication_multi_shard_txn(dcs):
    hub, nodes, reps = dcs
    ups = [(i, "counter_pn", "b", ("increment", i + 1)) for i in range(10)]
    vc = nodes[0].update_objects(ups)
    hub.pump()
    objs = [(i, "counter_pn", "b") for i in range(10)]
    vals, _ = nodes[2].read_objects(objs, clock=vc)
    assert vals == [i + 1 for i in range(10)]


def test_causality_chain_across_dcs(dcs):
    # write at DC0 -> read at DC1 -> dependent write at DC1 -> read at DC2
    # (causality_test, /root/reference/test/multidc/inter_dc_repl_SUITE.erl:79-84)
    hub, nodes, reps = dcs
    vc0 = nodes[0].update_objects([("k", "set_aw", "b", ("add", "a"))])
    hub.pump()
    vals, vc1 = nodes[1].read_objects([("k", "set_aw", "b")], clock=vc0)
    assert vals == [["a"]]
    vc2 = nodes[1].update_objects([("k", "set_aw", "b", ("remove", "a"))],
                                  clock=vc1)
    hub.pump()
    vals, _ = nodes[2].read_objects([("k", "set_aw", "b")], clock=vc2)
    assert vals == [[]]


def test_causal_gate_and_ping_revealed_gap(dcs):
    # DC0 writes x; the txn message to DC2 is lost. DC1 observes x and
    # writes y (dependent). DC2 must not expose a snapshot claiming x until
    # a later DC0 ping reveals the gap and catch-up fills it.
    hub, nodes, reps = dcs
    # lose the txn message AND the deferred heartbeat flush the next pump
    # emits (whose chain head would reveal the gap immediately)
    hub.drop_next(0, 2, n=1 + nodes[0].cfg.n_shards)
    vc0 = nodes[0].update_objects([("x", "counter_pn", "b", ("increment", 1))])
    hub.pump()
    vc1 = nodes[1].read_objects([("x", "counter_pn", "b")], clock=vc0)[1]
    vc2 = nodes[1].update_objects([("y", "counter_pn", "b", ("increment", 2))],
                                  clock=vc1)
    hub.pump()
    # x's shard at DC2 never saw DC0's commit: stable lane0 stuck below vc0
    assert nodes[2].store.stable_vc()[0] < vc0[0]
    # a DC0 heartbeat reveals the chain gap -> catch-up -> x arrives
    reps[0].heartbeat()
    hub.pump()
    vals, _ = nodes[2].read_objects(
        [("x", "counter_pn", "b"), ("y", "counter_pn", "b")], clock=vc2)
    assert vals == [1, 2]


def test_message_loss_triggers_catch_up(dcs):
    hub, nodes, reps = dcs
    nodes[0].update_objects([("k", "counter_pn", "b", ("increment", 1))])
    hub.pump()
    # lose DC0 -> DC1 messages for the next commit (txn + heartbeats)
    hub.drop_next(0, 1, n=nodes[0].cfg.n_shards)
    nodes[0].update_objects([("k", "counter_pn", "b", ("increment", 10))])
    hub.pump()
    # next commit's chained opid reveals the gap; catch-up query fills it
    vc = nodes[0].update_objects([("k", "counter_pn", "b", ("increment", 100))])
    hub.pump()
    vals, _ = nodes[1].read_objects([("k", "counter_pn", "b")], clock=vc)
    assert vals == [111]
    assert hub.dropped > 0


def test_concurrent_writes_converge(dcs):
    hub, nodes, reps = dcs
    # concurrent (unsynced) adds at all three DCs
    nodes[0].update_objects([("s", "set_aw", "b", ("add", "a0"))])
    nodes[1].update_objects([("s", "set_aw", "b", ("add", "a1"))])
    nodes[2].update_objects([("s", "set_aw", "b", ("add", "a2"))])
    hub.pump()
    clocks = [n.store.dc_max_vc() for n in nodes]
    target = np.max(np.stack(clocks), axis=0)
    for n in nodes:
        vals, _ = n.read_objects([("s", "set_aw", "b")], clock=target)
        assert vals == [["a0", "a1", "a2"]]


def test_concurrent_counter_increments_sum(dcs):
    hub, nodes, reps = dcs
    for i, n in enumerate(nodes):
        n.update_objects([("c", "counter_pn", "b", ("increment", 10 ** i))])
    hub.pump()
    target = np.max(np.stack([n.store.dc_max_vc() for n in nodes]), axis=0)
    for n in nodes:
        vals, _ = n.read_objects([("c", "counter_pn", "b")], clock=target)
        assert vals == [111]


def test_stable_snapshot_advances_via_heartbeats(dcs):
    hub, nodes, reps = dcs
    nodes[0].update_objects([("k", "counter_pn", "b", ("increment", 1))])
    hub.pump()
    # all shards of DC1 saw DC0's heartbeat, so stable advances even though
    # only one shard got the txn
    stable = nodes[1].store.stable_vc()
    assert stable[0] >= 1


def test_atomicity_across_dcs(dcs):
    # a multi-key txn is visible atomically at remote DCs
    # (atomicity_test, inter_dc_repl_SUITE)
    hub, nodes, reps = dcs
    txn_updates = [
        ("a", "counter_pn", "b", ("increment", 1)),
        ("b", "counter_pn", "b", ("increment", 1)),
    ]
    vc = nodes[0].update_objects(txn_updates)
    hub.pump()
    vals, _ = nodes[1].read_objects(
        [("a", "counter_pn", "b"), ("b", "counter_pn", "b")], clock=vc)
    assert vals == [1, 1]


def test_lww_register_across_dcs(dcs):
    hub, nodes, reps = dcs
    nodes[0].update_objects([("r", "register_lww", "b", ("assign", "first"))])
    hub.pump()
    vc = nodes[1].update_objects([("r", "register_lww", "b", ("assign", "second"))])
    hub.pump()
    target = np.max(np.stack([n.store.dc_max_vc() for n in nodes]), axis=0)
    for n in nodes:
        vals, _ = n.read_objects([("r", "register_lww", "b")], clock=target)
        assert vals == ["second"]
