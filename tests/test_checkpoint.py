"""Checkpointed fast restart (ISSUE 8): VC-stamped images, WAL tail
truncation, crash-safe compaction.

The invariant everything here pins: recovery from (checkpoint image +
WAL tail) is OBSERVABLY IDENTICAL to a full-log replay — same values at
every readable clock, same op-id chains, same append sequences, same
stable snapshot — and a failed/interrupted checkpoint changes nothing
at all (no floor movement, no truncation, no read-only flip).
"""

import os

import numpy as np
import pytest

from antidote_tpu import faults
from antidote_tpu.api import AntidoteNode
from antidote_tpu.config import AntidoteConfig
from antidote_tpu.log import checkpoint as ckpt

pytestmark = pytest.mark.smoke


@pytest.fixture(autouse=True)
def _no_leftover_faults():
    yield
    faults.uninstall()


@pytest.fixture
def dcfg():
    # small tables + several WAL segments so checkpoints exercise the
    # generation rotation and tier promotion paths cheaply
    return AntidoteConfig(
        n_shards=4, max_dcs=3, ops_per_key=8, snap_versions=2,
        set_slots=8, mv_slots=4, rga_slots=16, keys_per_table=64,
        batch_buckets=(16, 64), wal_segments=3,
    )


def wal_bytes(log_dir) -> int:
    return sum(
        os.path.getsize(os.path.join(log_dir, f))
        for f in os.listdir(log_dir) if f.endswith(".wal")
    )


def digest(node) -> dict:
    """The byte-identical-recovery digest (the chaos suite's shape)."""
    return {
        "op_ids": node.store.log.op_ids.tolist(),
        "seqs": node.store.log.seqs.tolist(),
        "stable": [int(x) for x in node.stable_vc()],
        "commit_counter": int(node.txm.commit_counter),
        "keys": len(node.store.directory),
    }


def populate(node, rounds=3):
    for i in range(rounds):
        node.update_objects([
            ("c", "counter_pn", "b", ("increment", 7 + i)),
            (f"c{i}", "counter_pn", "b", ("increment", i + 1)),
            ("s", "set_aw", "b", ("add_all", [f"x{i}", f"y{i}"])),
            ("r", "register_lww", "b", ("assign", f"val{i}")),
        ])
    node.update_objects([("s", "set_aw", "b", ("remove", "x0"))])


def test_checkpoint_then_tail_recovery_byte_identical(dcfg, tmp_path):
    log_dir = str(tmp_path / "wal")
    node = AntidoteNode(dcfg, log_dir=log_dir)
    populate(node)
    summary = node.checkpoint_now()
    assert summary["n_keys"] == len(node.store.directory)
    assert summary["reclaimed_bytes"] > 0, "no WAL file fell below the floor"
    # tail: writes after the stamp, including a map (composite keys)
    vc = node.update_objects([
        ("c", "counter_pn", "b", ("increment", 100)),
        ("s", "set_aw", "b", ("add", "z")),
        ("m", "map_rr", "b", ("update", {
            ("f", "counter_pn"): ("increment", 3)})),
    ])
    objs = [("c", "counter_pn", "b"), ("s", "set_aw", "b"),
            ("r", "register_lww", "b"), ("m", "map_rr", "b")]
    want_vals, _ = node.read_objects(objs, clock=vc)
    want = digest(node)
    node.store.log.close()

    for _ in range(2):  # two independent recoveries must agree
        n2 = AntidoteNode(dcfg, log_dir=log_dir, recover=True)
        vals, _ = n2.read_objects(objs, clock=vc)
        assert vals == want_vals
        assert digest(n2) == want
        assert (n2.store.log.floor_seqs > 0).any(), "fast path not engaged"
        n2.store.log.close()
    # chains continue: a post-recovery commit minted fresh dots and is
    # itself recovered by the NEXT restart
    n3 = AntidoteNode(dcfg, log_dir=log_dir, recover=True)
    vc2 = n3.update_objects([("c", "counter_pn", "b", ("increment", 1))])
    assert vc2[n3.dc_id] > vc[n3.dc_id]
    n3.store.log.close()
    n4 = AntidoteNode(dcfg, log_dir=log_dir, recover=True)
    vals, _ = n4.read_objects([("c", "counter_pn", "b")], clock=vc2)
    assert vals == [want_vals[0] + 1]
    n4.store.log.close()


def test_fast_path_replays_only_the_tail(dcfg, tmp_path):
    log_dir = str(tmp_path / "wal")
    node = AntidoteNode(dcfg, log_dir=log_dir)
    populate(node, rounds=5)
    node.checkpoint_now()
    node.update_objects([("c", "counter_pn", "b", ("increment", 1)),
                         ("s", "set_aw", "b", ("add", "tail"))])
    node.store.log.close()
    n2 = AntidoteNode(dcfg, log_dir=log_dir, recover=True)
    # exactly the two tail records were replayed (the recovery counter
    # satellite): the pre-stamp history came from the image
    assert n2.store.last_recovery_records == 2
    assert n2.metrics.recovery_records.value() == 2
    assert n2.metrics.recovery_seconds.value(phase="tail") > 0
    assert n2.metrics.recovery_seconds.value(phase="checkpoint") > 0
    blk = n2.status()["checkpoint"]
    assert blk["last_id"] == 1 and blk["image_bytes"] > 0
    n2.store.log.close()


def test_wal_bounded_under_sustained_writes(dcfg, tmp_path):
    log_dir = str(tmp_path / "wal")
    node = AntidoteNode(dcfg, log_dir=log_dir)
    node.start_checkpointer(interval_s=0.0, rebase_every=2)
    sizes = []
    for round_ in range(6):
        for i in range(40):
            node.update_objects([
                (i % 8, "counter_pn", "b", ("increment", 1))])
        node.checkpoint_now()
        sizes.append(wal_bytes(log_dir))
    # delta links advance the replay floor but only REBASES reclaim (a
    # corrupt mid-chain link must always fall back to full + tail): at
    # rebase_every=2 the steady state stays flat while total writes
    # grow linearly
    assert sizes[-1] <= sizes[1] * 3.5, sizes
    assert node.metrics.wal_reclaimed.value() > 0
    assert node.checkpointer.reclaimed_total > 0
    # retention: 2 FULL images (default) + the live chain's links
    published = [ckpt.load_manifest(p) for _i, p in
                 ckpt.list_checkpoints(ckpt.checkpoint_root(log_dir))]
    fulls = [m for m in published if ckpt.manifest_kind(m) == "full"]
    assert len(fulls) == 2
    # every surviving delta link sits ABOVE the newest full (older ones
    # were swept by the rebase that covered them)
    newest_full = max(m["id"] for m in fulls)
    assert all(m["id"] > newest_full for m in published
               if ckpt.manifest_kind(m) == "delta")
    vals, _ = node.read_objects([(i, "counter_pn", "b") for i in range(8)])
    assert vals == [30] * 8
    node.store.log.close()
    n2 = AntidoteNode(dcfg, log_dir=log_dir, recover=True)
    vals, _ = n2.read_objects([(i, "counter_pn", "b") for i in range(8)])
    assert vals == [30] * 8
    n2.store.log.close()


def test_checkpoint_enospc_never_flips_read_only_or_truncates(dcfg,
                                                              tmp_path):
    log_dir = str(tmp_path / "wal")
    node = AntidoteNode(dcfg, log_dir=log_dir)
    populate(node)
    before_files = {
        f: os.path.getsize(os.path.join(log_dir, f))
        for f in os.listdir(log_dir) if f.endswith(".wal")
    }
    faults.install(faults.FaultPlan(seed=1).enospc("ckpt.write"))
    with pytest.raises(ckpt.CheckpointError):
        node.checkpoint_now()
    # satellite contract: a checkpoint ENOSPC is NOT a WAL ENOSPC — the
    # store stays writable, nothing was truncated, nothing published
    assert node.txm.read_only_reason is None
    assert node.metrics.degraded_read_only.value() == 0
    assert (node.store.log.floor_seqs == 0).all()
    after_files = {
        f: os.path.getsize(os.path.join(log_dir, f))
        for f in os.listdir(log_dir)
        if f.endswith(".wal") and f in before_files
    }
    assert after_files == before_files, "a failed checkpoint touched the WAL"
    assert ckpt.list_checkpoints(ckpt.checkpoint_root(log_dir)) == []
    assert node.metrics.checkpoint_total.value(status="error") == 1
    node.update_objects([("c", "counter_pn", "b", ("increment", 1))])
    # the volume "heals": the next cycle publishes normally
    faults.uninstall()
    assert node.checkpoint_now()["id"] == 2
    node.store.log.close()


def test_checkpoint_fsync_and_rename_faults_abort_cleanly(dcfg, tmp_path):
    log_dir = str(tmp_path / "wal")
    node = AntidoteNode(dcfg, log_dir=log_dir)
    populate(node, rounds=1)
    for site in ("ckpt.fsync", "ckpt.rename"):
        faults.install(faults.FaultPlan(seed=2).io_error(site, times=1))
        with pytest.raises(ckpt.CheckpointError):
            node.checkpoint_now()
        faults.uninstall()
        assert ckpt.list_checkpoints(ckpt.checkpoint_root(log_dir)) == []
        assert node.txm.read_only_reason is None
    summary = node.checkpoint_now()
    # crashed attempts' temp dirs are swept by the successful publish
    leftovers = [f for f in os.listdir(ckpt.checkpoint_root(log_dir))
                 if f.startswith("tmp.")]
    assert leftovers == []
    assert summary["id"] >= 3
    node.store.log.close()


def test_corrupt_newest_image_falls_back_to_older(dcfg, tmp_path):
    log_dir = str(tmp_path / "wal")
    node = AntidoteNode(dcfg, log_dir=log_dir)
    node.update_objects([("c", "counter_pn", "b", ("increment", 1))])
    node.checkpoint_now()
    node.update_objects([("c", "counter_pn", "b", ("increment", 2))])
    node.checkpoint_now()
    vc = node.update_objects([("c", "counter_pn", "b", ("increment", 4))])
    node.store.log.close()
    # bit-rot the newest image: recovery must fall back to image 1 and
    # replay a LONGER tail to the same state.  The floor-filtered replay
    # makes this safe: image 1's floor keeps every record above it.
    cks = ckpt.list_checkpoints(ckpt.checkpoint_root(log_dir))
    assert len(cks) == 2
    newest = os.path.join(cks[-1][1], "image.bin")
    with open(newest, "r+b") as f:
        f.seek(10)
        f.write(b"\xff\xff\xff\xff")
    n2 = AntidoteNode(dcfg, log_dir=log_dir, recover=True)
    vals, _ = n2.read_objects([("c", "counter_pn", "b")], clock=vc)
    assert vals == [7]
    n2.store.log.close()


def test_ro_degraded_store_serves_reads_after_checkpoint_restart(dcfg,
                                                                 tmp_path):
    log_dir = str(tmp_path / "wal")
    node = AntidoteNode(dcfg, log_dir=log_dir)
    populate(node)
    node.checkpoint_now()
    node.store.log.close()
    # restart from the checkpoint onto a "full disk": writes shed typed,
    # reads serve the checkpointed state (the RO satellite's second half)
    from antidote_tpu.overload import ReadOnlyError

    n2 = AntidoteNode(dcfg, log_dir=log_dir, recover=True)
    faults.install(faults.FaultPlan(seed=3).enospc("wal.append"))
    with pytest.raises((ReadOnlyError, OSError)):
        n2.update_objects([("c", "counter_pn", "b", ("increment", 1))])
    assert n2.txm.read_only_reason is not None
    vals, _ = n2.read_objects([("c", "counter_pn", "b"),
                               ("r", "register_lww", "b")])
    assert vals[0] >= 7 and vals[1] == "val2"
    # a checkpoint is still possible while degraded (reads-only state)
    faults.uninstall()
    n2.txm._ro_probe_at = 0.0
    n2.update_objects([("c", "counter_pn", "b", ("increment", 1))])
    assert n2.txm.read_only_reason is None
    n2.store.log.close()


def test_read_below_compaction_horizon_raises_typed(dcfg, tmp_path):
    log_dir = str(tmp_path / "wal")
    node = AntidoteNode(dcfg, log_dir=log_dir)
    vcs = [node.update_objects([("k", "counter_pn", "b", ("increment", 1))])
           for _ in range(25)]  # beyond ring+versions device coverage
    node.checkpoint_now()
    node.store.log.close()
    n2 = AntidoteNode(dcfg, log_dir=log_dir, recover=True)
    # at/above the stamp: served exactly
    vals, _ = n2.read_objects([("k", "counter_pn", "b")])
    assert vals == [25]
    # far below the stamp: the pre-checkpoint per-op history is gone —
    # a typed horizon error, never a silently wrong value
    txn = n2.start_transaction()
    txn.snapshot_vc = np.asarray(vcs[2], np.int32)
    with pytest.raises(RuntimeError, match="compaction horizon"):
        n2.read_objects([("k", "counter_pn", "b")], txn)
    n2.abort_transaction(txn)
    n2.store.log.close()


def test_promoted_keys_roundtrip_through_checkpoint(dcfg, tmp_path):
    log_dir = str(tmp_path / "wal")
    node = AntidoteNode(dcfg, log_dir=log_dir)
    # overflow the base tier before the stamp, and again after
    node.update_objects([("big", "set_aw", "b",
                          ("add_all", [f"e{i}" for i in range(20)]))])
    assert node.store.promotions > 0
    node.checkpoint_now()
    node.update_objects([("big", "set_aw", "b",
                          ("add_all", [f"t{i}" for i in range(40)]))])
    want, _ = node.read_objects([("big", "set_aw", "b")])
    node.store.log.close()
    n2 = AntidoteNode(dcfg, log_dir=log_dir, recover=True)
    vals, _ = n2.read_objects([("big", "set_aw", "b")])
    assert sorted(vals[0]) == sorted(want[0])
    n2.store.log.close()


def test_relinquished_shard_does_not_resurrect_from_image(dcfg, tmp_path):
    from antidote_tpu.store import handoff

    log_dir = str(tmp_path / "wal")
    node = AntidoteNode(dcfg, log_dir=log_dir)
    keys = list(range(16))
    node.update_objects([(k, "counter_pn", "b", ("increment", k + 1))
                         for k in keys])
    node.checkpoint_now()
    # a shard moves away AFTER the stamp (two-phase move's relinquish
    # leg): its WAL truncation bumps the durable shard-reset epoch
    victim = node.store.directory[(0, "b")][1]
    moved = {k for k in keys
             if node.store.directory[(k, "b")][1] == victim}
    handoff.drop_shard(node.store, victim)
    node.store.log.close()
    n2 = AntidoteNode(dcfg, log_dir=log_dir, recover=True)
    for k in keys:
        if k in moved:
            # the image predates the move: the shard must NOT resurrect
            assert (k, "b") not in n2.store.directory
        else:
            vals, _ = n2.read_objects([(k, "counter_pn", "b")])
            assert vals == [k + 1]
    n2.store.log.close()


def test_interdc_chain_positions_survive_checkpointed_restart(dcfg,
                                                              tmp_path):
    """Egress opids and ingress positions resume from the image's chain
    floors: after a checkpointed restart the geo peer sees neither
    duplicates nor gaps — totals stay exact."""
    from antidote_tpu.interdc import DCReplica
    from antidote_tpu.interdc.transport import LoopbackHub

    hub = LoopbackHub()
    n0 = AntidoteNode(dcfg, dc_id=0, log_dir=str(tmp_path / "dc0"))
    n1 = AntidoteNode(dcfg, dc_id=1, log_dir=str(tmp_path / "dc1"))
    r0 = DCReplica(n0, hub, "dc0")
    r1 = DCReplica(n1, hub, "dc1")
    r0.observe_dc(r1), r1.observe_dc(r0)
    total = 0
    for i in range(5):
        n0.update_objects([("g", "counter_pn", "b", ("increment", i + 1))])
        total += i + 1
    n1.update_objects([("g", "counter_pn", "b", ("increment", 100))])
    total += 100
    hub.pump()
    n0.checkpoint_now()
    n0.update_objects([("g", "counter_pn", "b", ("increment", 50))])
    total += 50
    hub.pump()
    pre_pub = r0.pub_opid.copy()
    pre_seen = dict(r0.last_seen)
    # kill -9 DC0: only the WAL dir + checkpoint survive
    hub.unregister(0)
    n0.store.log.close()
    del n0, r0
    n0 = AntidoteNode(dcfg, dc_id=0, log_dir=str(tmp_path / "dc0"),
                      recover=True)
    r0 = DCReplica(n0, hub, "dc0")
    r0.restore_from_log()
    # chain positions byte-identical to the pre-kill live state (the
    # image's chain floor + tail recount), not restarted at zero
    assert (r0.pub_opid == pre_pub).all(), (r0.pub_opid, pre_pub)
    assert r0.last_seen == pre_seen
    r0.observe_dc(r1), r1.observe_dc(r0)
    n0.update_objects([("g", "counter_pn", "b", ("increment", 7))])
    total += 7
    r0.heartbeat(), r1.heartbeat()
    hub.pump()
    target = np.maximum(n0.store.dc_max_vc(), n1.store.dc_max_vc())
    for n in (n0, n1):
        vals, _ = n.read_objects([("g", "counter_pn", "b")], clock=target)
        assert vals == [total], (vals, total)
    n0.store.log.close(), n1.store.log.close()


def test_compacted_handoff_carries_chain_floor(dcfg, tmp_path):
    """A shard exported from a checkpoint-compacted source ships its
    replication chain floor: the importer's WAL-derived opid numbering
    (restore_from_log, extras-less adopt, catch-up serving) continues
    the true chain instead of restarting at the tail count — remote
    subscribers would otherwise drop the new owner's commits as
    duplicates."""
    from antidote_tpu.interdc import DCReplica
    from antidote_tpu.interdc.transport import LoopbackHub
    from antidote_tpu.store import handoff

    src = AntidoteNode(dcfg, log_dir=str(tmp_path / "src"))
    for i in range(6):
        src.update_objects([("hk", "counter_pn", "b", ("increment", 1))])
    src.checkpoint_now()
    src.update_objects([("hk", "counter_pn", "b", ("increment", 1))])
    shard = src.store.directory[("hk", "b")][1]
    # the source's true egress position for the shard's chain
    src_rep = DCReplica(src, LoopbackHub(), "src")
    src_rep.restore_from_log()
    true_opid = int(src_rep.pub_opid[shard])
    assert true_opid == 7
    pkg = handoff.export_shard(src.store, shard)
    assert pkg["compacted"] is True
    assert pkg["chain_floor"] is not None and sum(pkg["chain_floor"]) > 0
    dst = AntidoteNode(dcfg, log_dir=str(tmp_path / "dst"))
    dst.receive_handoff(pkg)
    # the import-then-checkpoint barrier (ISSUE 9) seals the import with
    # a local image, which may advance the importer's floor PAST the
    # source's (it covers the ride-along tail too) — never below it
    assert dst.store.log.chain_base(shard, 0) >= \
        src.store.log.chain_base(shard, 0)
    dst_rep = DCReplica(dst, LoopbackHub(), "dst")
    dst_rep.restore_from_log()
    assert int(dst_rep.pub_opid[shard]) == true_opid, (
        dst_rep.pub_opid[shard], true_opid)
    clock = [7] + [0] * (dcfg.max_dcs - 1)
    vals, _ = dst.read_objects([("hk", "counter_pn", "b")], clock=clock)
    assert vals == [7]
    src.store.log.close(), dst.store.log.close()


def test_compacted_import_checkpoint_barrier_survives_sigkill(dcfg,
                                                              tmp_path):
    """ISSUE 9 satellite, closing the PR-7 handoff residual: importing a
    shard FROM a checkpoint-compacted source is now a SYNCHRONOUS
    import-then-checkpoint barrier — ``receive_handoff`` does not return
    until a LOCAL image covers the moved rows.  Pinned with a real
    SIGKILL inside the old bug's window: the importer is killed -9
    immediately after the import returns (before any graceful shutdown),
    and recovery must still serve the moved rows' FULL pre-checkpoint
    history (the nudge-only behavior recovered a silently wrong
    tail-only value — the ride-along log holds just the tail)."""
    import json
    import os
    import signal
    import subprocess
    import sys
    import time

    from antidote_tpu.store import handoff

    src = AntidoteNode(dcfg, log_dir=str(tmp_path / "src"))
    for _ in range(6):
        src.update_objects([("hk", "counter_pn", "b", ("increment", 1))])
    src.checkpoint_now()
    src.update_objects([("hk", "counter_pn", "b", ("increment", 1))])
    shard = src.store.directory[("hk", "b")][1]
    pkg = handoff.export_shard(src.store, shard)
    assert pkg["compacted"] is True
    assert len(pkg["log"]) == 1  # the ride-along log is tail-only
    pkg_path = str(tmp_path / "pkg.bin")
    with open(pkg_path, "wb") as f:
        f.write(handoff.pack(pkg))
    dst_dir = str(tmp_path / "dst")
    import dataclasses

    child_src = (
        "import json, sys, time\n"
        "from antidote_tpu.api import AntidoteNode\n"
        "from antidote_tpu.config import AntidoteConfig\n"
        "from antidote_tpu.store import handoff\n"
        "cfgd = json.loads(sys.argv[1])\n"
        "cfgd['batch_buckets'] = tuple(cfgd['batch_buckets'])\n"
        "cfg = AntidoteConfig(**cfgd)\n"
        "pkg = handoff.unpack(open(sys.argv[2], 'rb').read())\n"
        "node = AntidoteNode(cfg, log_dir=sys.argv[3])\n"
        "node.receive_handoff(pkg)\n"
        "print('IMPORTED', flush=True)\n"
        "time.sleep(120)\n"
    )
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               PYTHONPATH=os.path.dirname(os.path.dirname(
                   os.path.abspath(__file__))))
    proc = subprocess.Popen(
        [sys.executable, "-c", child_src,
         json.dumps(dataclasses.asdict(dcfg)), pkg_path, dst_dir],
        stdout=subprocess.PIPE, stderr=subprocess.DEVNULL, env=env,
        text=True,
    )
    try:
        t0 = time.monotonic()
        line = proc.stdout.readline().strip()
        assert line == "IMPORTED", (line, proc.poll())
        assert time.monotonic() - t0 < 120
        # the window the nudge left open: kill -9 right after the
        # import acknowledged
        proc.send_signal(signal.SIGKILL)
        proc.wait(timeout=10)
    finally:
        if proc.poll() is None:
            proc.kill()
            proc.wait(timeout=10)
    # the barrier's artifact: a local image was published BEFORE the
    # import returned
    assert ckpt.list_checkpoints(ckpt.checkpoint_root(dst_dir))
    # two independent recoveries serve the moved rows' full history
    for _ in range(2):
        d2 = AntidoteNode(dcfg, log_dir=dst_dir, recover=True)
        vals, _ = d2.read_objects([("hk", "counter_pn", "b")])
        assert vals == [7], vals
        d2.store.log.close()
    src.store.log.close()


# ---------------------------------------------------------------------------
# incremental chains (ISSUE 13): compose / rebase / corrupt-link matrix
# ---------------------------------------------------------------------------
def _chain_store(dcfg, tmp_path, links=3, writes_per_link=6):
    """full image + ``links`` delta links + a WAL tail; returns
    (log_dir, oracle values dict)."""
    log_dir = str(tmp_path / "wal")
    node = AntidoteNode(dcfg, log_dir=log_dir)
    node.start_checkpointer(interval_s=0.0, rebase_every=64)
    vals = {}
    for i in range(12):
        node.update_objects([(i, "counter_pn", "b", ("increment", i + 1))])
        vals[i] = i + 1
    node.checkpoint_now(full=True)
    for link in range(links):
        for j in range(writes_per_link):
            k = (link * writes_per_link + j) % 12
            node.update_objects([(k, "counter_pn", "b", ("increment", 10))])
            vals[k] += 10
        s = node.checkpoint_now()
        assert s["kind"] == "delta", s
    # WAL tail above the chain head
    node.update_objects([(1, "counter_pn", "b", ("increment", 7))])
    vals[1] += 7
    node.store.log.close()
    return log_dir, vals


def _assert_recovers(dcfg, log_dir, vals, rounds=2):
    for _ in range(rounds):
        n = AntidoteNode(dcfg, log_dir=log_dir, recover=True)
        got, _ = n.read_objects([(i, "counter_pn", "b")
                                 for i in sorted(vals)])
        assert got == [vals[i] for i in sorted(vals)], got
        dig = digest(n)
        n.store.log.close()
    return dig


def test_chain_composes_byte_identical(dcfg, tmp_path):
    """full + deltas + tail compose to the exact live state, twice."""
    log_dir, vals = _chain_store(dcfg, tmp_path)
    chain = ckpt.load_chain(log_dir)
    assert chain is not None and len(chain[2]) == 3
    d1 = _assert_recovers(dcfg, log_dir, vals)
    d2 = _assert_recovers(dcfg, log_dir, vals)
    assert d1 == d2


def test_corrupt_mid_chain_link_falls_back_to_prefix(dcfg, tmp_path):
    """Bit-rot ONE mid-chain link: recovery composes the prefix before
    it and replays a LONGER WAL tail — byte-identical, never lost."""
    log_dir, vals = _chain_store(dcfg, tmp_path)
    cks = ckpt.list_checkpoints(ckpt.checkpoint_root(log_dir))
    deltas = [(i, p) for i, p in cks
              if ckpt.manifest_kind(ckpt.load_manifest(p)) == "delta"]
    mid = deltas[1]  # the MIDDLE link of the 3-link chain
    with open(os.path.join(mid[1], "image.bin"), "r+b") as f:
        f.seek(8)
        f.write(b"\xff\xff\xff\xff")
    chain = ckpt.load_chain(log_dir)
    assert len(chain[2]) == 1  # stops before the corrupt link
    _assert_recovers(dcfg, log_dir, vals)


def test_missing_mid_chain_link_falls_back_to_prefix(dcfg, tmp_path):
    """A DELETED mid-chain link breaks parent linkage the same way."""
    import shutil

    log_dir, vals = _chain_store(dcfg, tmp_path)
    cks = ckpt.list_checkpoints(ckpt.checkpoint_root(log_dir))
    deltas = [(i, p) for i, p in cks
              if ckpt.manifest_kind(ckpt.load_manifest(p)) == "delta"]
    shutil.rmtree(deltas[1][1])
    chain = ckpt.load_chain(log_dir)
    assert len(chain[2]) == 1
    _assert_recovers(dcfg, log_dir, vals)


def test_delta_stamp_cost_tracks_dirty_rows(dcfg, tmp_path):
    """The incremental-cost contract: a delta link's size and row count
    scale with the dirty set, not the table extent."""
    log_dir = str(tmp_path / "wal")
    node = AntidoteNode(dcfg, log_dir=log_dir)
    node.start_checkpointer(interval_s=0.0, rebase_every=64)
    for i in range(200):
        node.update_objects([(i, "counter_pn", "b", ("increment", 1))])
    full = node.checkpoint_now(full=True)
    node.update_objects([(3, "counter_pn", "b", ("increment", 1))])
    small = node.checkpoint_now()
    assert small["kind"] == "delta"
    assert small["n_rows"] == 1
    assert small["image_bytes"] < full["image_bytes"] / 5
    for i in range(50):
        node.update_objects([(i, "counter_pn", "b", ("increment", 1))])
    bigger = node.checkpoint_now()
    assert bigger["kind"] == "delta"
    assert bigger["n_rows"] == 50
    assert small["image_bytes"] < bigger["image_bytes"] \
        < full["image_bytes"]
    node.store.log.close()
    n2 = AntidoteNode(dcfg, log_dir=log_dir, recover=True)
    got, _ = n2.read_objects([(i, "counter_pn", "b") for i in range(200)])
    want = [1 + (1 if i < 50 else 0) + (1 if i == 3 else 0)
            for i in range(200)]
    assert got == want
    n2.store.log.close()


def test_failed_delta_stamp_forces_rebase(dcfg, tmp_path):
    """A failed stamp consumed the dirty windows — the NEXT stamp must
    be a full rebase (nothing can fall through the gap)."""
    log_dir = str(tmp_path / "wal")
    node = AntidoteNode(dcfg, log_dir=log_dir)
    node.start_checkpointer(interval_s=0.0, rebase_every=64)
    populate(node)
    node.checkpoint_now(full=True)
    node.update_objects([("c", "counter_pn", "b", ("increment", 5))])
    faults.install(faults.FaultPlan(seed=9).enospc("ckpt.write", times=1))
    with pytest.raises(ckpt.CheckpointError):
        node.checkpoint_now()
    faults.uninstall()
    assert node.checkpointer.force_rebase is True
    s = node.checkpoint_now()
    assert s["kind"] == "full"
    node.update_objects([("c", "counter_pn", "b", ("increment", 1))])
    want, _ = node.read_objects([("c", "counter_pn", "b")])
    node.store.log.close()
    n2 = AntidoteNode(dcfg, log_dir=log_dir, recover=True)
    got, _ = n2.read_objects([("c", "counter_pn", "b")])
    assert got == want
    n2.store.log.close()


def test_scrubber_retires_corrupt_link_and_forces_rebase(dcfg, tmp_path):
    """The background scrub finds bit rot BEFORE a restart does: the
    corrupt delta link is retired, a rebase forced, the metric bumped —
    and the store still recovers byte-identical afterwards."""
    log_dir, vals = _chain_store(dcfg, tmp_path)
    n = AntidoteNode(dcfg, log_dir=log_dir, recover=True)
    n.start_checkpointer(interval_s=0.0, rebase_every=64)
    cks = ckpt.list_checkpoints(ckpt.checkpoint_root(log_dir))
    deltas = [(i, p) for i, p in cks
              if ckpt.manifest_kind(ckpt.load_manifest(p)) == "delta"]
    with open(os.path.join(deltas[1][1], "image.bin"), "r+b") as f:
        f.seek(8)
        f.write(b"\xff\xff\xff\xff")
    out = n.checkpointer.scrub()
    assert out["corrupt"] == 1 and out["ok"] >= 2
    assert n.metrics.checkpoint_scrub.value(result="corrupt") == 1
    assert not os.path.isdir(deltas[1][1])  # retired on the spot
    assert n.checkpointer.force_rebase is True
    s = n.checkpoint_now()
    assert s["kind"] == "full"
    assert n.checkpointer.scrub()["corrupt"] == 0
    n.store.log.close()
    _assert_recovers(dcfg, log_dir, vals)


def test_checkpoint_now_over_the_wire(dcfg, tmp_path):
    """The console's `checkpoint-now` path: CHECKPOINT_NOW over the
    native dialect runs one synchronous cycle and returns the manifest;
    node status exposes the checkpoint block with the published stamp."""
    from antidote_tpu.proto.client import AntidoteClient
    from antidote_tpu.proto.server import ProtocolServer

    node = AntidoteNode(dcfg, log_dir=str(tmp_path / "wal"))
    srv = ProtocolServer(node, port=0)
    try:
        c = AntidoteClient(port=srv.port)
        c.update_objects([("w", "counter_pn", "b", ("increment", 3))])
        summary = c.checkpoint_now()
        assert summary["id"] == 1 and summary["n_keys"] >= 1
        st = c.node_status()
        assert st["checkpoint"]["last_id"] == 1
        assert st["checkpoint"]["reclaimed_bytes_total"] > 0
        c.close()
    finally:
        srv.close()
        node.store.log.close()


def test_inspect_checkpoint_console(dcfg, tmp_path, capsys):
    from antidote_tpu import console

    log_dir = str(tmp_path / "wal")
    node = AntidoteNode(dcfg, log_dir=log_dir)
    populate(node, rounds=1)
    node.checkpoint_now()
    node.store.log.close()
    rc = console.main(["inspect-checkpoint", "--log-dir", log_dir])
    assert rc == 0
    import json

    out = json.loads(capsys.readouterr().out)
    assert out["latest"]["verified"] is True
    assert out["latest"]["keys"] == len(node.store.directory)
    assert out["published"][-1]["id"] == out["latest"]["id"]
