"""Protocol-driven DC mesh bootstrap (r4 VERDICT item 6).

The reference serves CreateDC / GetConnectionDescriptor / ConnectToDCs to
protocol clients (antidote_pb_process:process,
/root/reference/src/antidote_pb_process.erl:103-135), so a stock client
can assemble a geo-replicated mesh without touching the nodes.  Both wire
dialects must support the same flow end to end: fetch each DC's
descriptor over the socket, cross-connect them over the socket, then
verify replication actually flows.
"""

import struct
import threading
import time

import pytest

from antidote_tpu.api.node import AntidoteNode
from antidote_tpu.config import AntidoteConfig
from antidote_tpu.interdc import DCReplica
from antidote_tpu.interdc.tcp import TcpFabric
from antidote_tpu.proto import apb
from antidote_tpu.proto.client import AntidoteClient
from antidote_tpu.proto.server import ProtocolServer


@pytest.fixture
def duo():
    """Two independent DC deployments, each: node + TCP fabric + replica +
    protocol server + pump thread.  Nothing is pre-connected."""
    cfg = AntidoteConfig(n_shards=2, max_dcs=3, ops_per_key=8,
                         snap_versions=2, set_slots=8, keys_per_table=64,
                         batch_buckets=(8, 64))
    stops = []
    dcs = []
    for i in range(2):
        node = AntidoteNode(cfg, dc_id=i)
        fabric = TcpFabric()
        rep = DCReplica(node, fabric, name=f"dc{i}")
        srv = ProtocolServer(node, port=0, interdc=rep)
        stop = threading.Event()

        def pump(f=fabric, s=stop):
            while not s.is_set():
                f.pump(timeout=0.1)
                time.sleep(0.005)

        t = threading.Thread(target=pump, daemon=True)
        t.start()
        stops.append(stop)
        dcs.append((node, fabric, rep, srv))
    yield dcs
    for s in stops:
        s.set()
    for _, fabric, _, srv in dcs:
        srv.close()
        fabric.close()


def _poll_read(client, obj, expect, clock=None, timeout=10.0):
    deadline = time.time() + timeout
    while time.time() < deadline:
        vals, _ = client.read_objects([obj], clock=clock)
        if vals[0] == expect:
            return
        time.sleep(0.05)
    raise AssertionError(f"never saw {expect!r} for {obj!r} (last {vals})")


def test_msgpack_dialect_mesh_bootstrap(duo):
    (n0, f0, r0, s0), (n1, f1, r1, s1) = duo
    c0 = AntidoteClient(s0.host, s0.port)
    c1 = AntidoteClient(s1.host, s1.port)
    try:
        c0.create_dc(["dc0"])  # single-node DC: acknowledged
        d0 = c0.get_connection_descriptor()
        d1 = c1.get_connection_descriptor()
        assert d0["address"] and d1["address"]
        # cross-connect THROUGH THE PROTOCOL only
        c0.connect_to_dcs([d1])
        c1.connect_to_dcs([d0])
        vc = c0.update_objects([("k", "counter_pn", "b", ("increment", 7))])
        # replication flows dc0 -> dc1 (poll without a clock: waiting on
        # the remote clock would block inside the snapshot wait instead)
        _poll_read(c1, ("k", "counter_pn", "b"), 7)
        # and the reverse direction
        c1.update_objects([("k2", "set_aw", "b", ("add", 3))])
        _poll_read(c0, ("k2", "set_aw", "b"), [3])
    finally:
        c0.close()
        c1.close()


def test_create_dc_multi_node_refused(duo):
    (n0, f0, r0, s0), _ = duo
    c0 = AntidoteClient(s0.host, s0.port)
    try:
        with pytest.raises(Exception):
            c0.create_dc(["dc0@host1", "dc0@host2"])
    finally:
        c0.close()


# ---------------------------------------------------------------------------
# apb (protobuf) dialect: the same flow as a stock antidotec_pb client
# ---------------------------------------------------------------------------
def _apb_call(sock, name, payload: dict):
    body = apb.encode_frame_body(name, payload)
    sock.sendall(struct.pack(">I", len(body)) + body)
    n = struct.unpack(">I", _read_exact(sock, 4))[0]
    frame = _read_exact(sock, n)
    return apb.CODE_TO_NAME[frame[0]], apb.decode_msg(
        apb.CODE_TO_NAME[frame[0]], frame[1:]
    )


def _read_exact(sock, n):
    buf = b""
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        assert chunk, "connection closed"
        buf += chunk
    return buf


def test_apb_dialect_mesh_bootstrap(duo):
    import socket

    (n0, f0, r0, s0), (n1, f1, r1, s1) = duo
    k0 = socket.create_connection((s0.host, s0.port))
    k1 = socket.create_connection((s1.host, s1.port))
    try:
        rn, resp = _apb_call(k0, "ApbCreateDC", {"nodes": [b"dc0"]})
        assert rn == "ApbOperationResp" and resp["success"]
        rn, d0 = _apb_call(k0, "ApbGetConnectionDescriptor", {})
        assert rn == "ApbGetConnectionDescriptorResp" and d0["success"]
        rn, d1 = _apb_call(k1, "ApbGetConnectionDescriptor", {})
        assert d1["success"]
        # descriptors are opaque blobs, shipped back verbatim
        rn, resp = _apb_call(k0, "ApbConnectToDCs",
                             {"descriptors": [d1["descriptor"]]})
        assert rn == "ApbOperationResp" and resp["success"], resp
        rn, resp = _apb_call(k1, "ApbConnectToDCs",
                             {"descriptors": [d0["descriptor"]]})
        assert resp["success"]
        # write on dc0 via apb static update
        rn, resp = _apb_call(k0, "ApbStaticUpdateObjects", {
            "transaction": {},
            "updates": [{
                "boundobject": {"key": b"pk", "type": apb.TYPE_IDS["counter_pn"],
                                "bucket": b"b"},
                "operation": {"counterop": {"inc": 9}},
            }],
        })
        assert rn == "ApbCommitResp" and resp["success"], resp
        # poll-read on dc1 via apb static read until replicated
        deadline = time.time() + 10
        val = None
        while time.time() < deadline:
            rn, resp = _apb_call(k1, "ApbStaticReadObjects", {
                "transaction": {},
                "objects": [{"key": b"pk",
                             "type": apb.TYPE_IDS["counter_pn"],
                             "bucket": b"b"}],
            })
            val = resp["objects"]["objects"][0]["counter"]["value"]
            if val == 9:
                break
            time.sleep(0.05)
        assert val == 9, val
    finally:
        k0.close()
        k1.close()
