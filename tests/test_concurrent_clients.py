"""Concurrent clients against the TCP protocol server (r2 VERDICT item 8).

The reference provisions 100 acceptors / 20 read servers per partition
(/root/reference/src/antidote_pb_sup.erl:47-56,
/root/reference/include/antidote.hrl:28) — an explicit concurrency story.
Here N client threads drive mixed read/update workloads over real
sockets; the single-commit-stream lock must serialize correctly
(per-key outcomes exact, every committed increment counted once) while
connections interleave, in both wire dialects.
"""

import struct
import threading


from antidote_tpu.api.node import AntidoteNode
from antidote_tpu.config import AntidoteConfig
from antidote_tpu.proto.client import AntidoteClient
from antidote_tpu.proto.server import ProtocolServer


def _mk_server():
    cfg = AntidoteConfig(n_shards=4, max_dcs=2, keys_per_table=256,
                         batch_buckets=(16, 64))
    node = AntidoteNode(cfg)
    return node, ProtocolServer(node, port=0)


def test_concurrent_mixed_read_update_clients():
    node, srv = _mk_server()
    n_clients, n_ops = 8, 30
    errors = []
    reads_seen = [0] * n_clients

    def worker(i):
        try:
            c = AntidoteClient("127.0.0.1", srv.port)
            for j in range(n_ops):
                # own counter: exact per-key outcome
                c.update_objects([(f"own{i}", "counter_pn", "b",
                                   ("increment", 1))])
                # shared counter: total must equal all increments
                c.update_objects([("shared", "counter_pn", "b",
                                   ("increment", 1))])
                # shared set: every client's elements must survive
                c.update_objects([("sset", "set_aw", "b",
                                   ("add", f"c{i}-{j}"))])
                if j % 5 == 0:
                    vals, _ = c.read_objects(
                        [(f"own{i}", "counter_pn", "b"),
                         ("shared", "counter_pn", "b")]
                    )
                    assert vals[0] == j + 1, (i, j, vals)
                    reads_seen[i] = vals[1]
            c.close()
        except Exception as e:  # pragma: no cover - surfaced below
            errors.append((i, repr(e)))

    threads = [threading.Thread(target=worker, args=(i,))
               for i in range(n_clients)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=120)
    assert not errors, errors
    vals, _ = node.read_objects(
        [("shared", "counter_pn", "b"), ("sset", "set_aw", "b")]
        + [(f"own{i}", "counter_pn", "b") for i in range(n_clients)]
    )
    assert vals[0] == n_clients * n_ops
    assert len(vals[1]) == n_clients * n_ops
    assert vals[2:] == [n_ops] * n_clients
    srv.close()


def test_concurrent_interactive_txns_certification():
    """Concurrent interactive txns on ONE key: exactly the serialized
    winners commit (first-committer-wins), no lost updates, aborts
    surface as errors not corruption."""
    from antidote_tpu.proto.client import RemoteAbort

    node, srv = _mk_server()
    n_clients, rounds = 6, 10
    committed = [0] * n_clients
    errors = []

    def worker(i):
        try:
            c = AntidoteClient("127.0.0.1", srv.port)
            for _ in range(rounds):
                txn = c.start_transaction()
                try:
                    txn.update_objects([("hot", "counter_pn", "b",
                                         ("increment", 1))])
                    txn.commit()
                    committed[i] += 1
                except RemoteAbort:
                    try:
                        txn.abort()
                    except Exception:
                        pass
            c.close()
        except Exception as e:  # pragma: no cover
            errors.append((i, repr(e)))

    threads = [threading.Thread(target=worker, args=(i,))
               for i in range(n_clients)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=120)
    assert not errors, errors
    vals, _ = node.read_objects([("hot", "counter_pn", "b")])
    # the counter equals exactly the number of successful commits
    assert vals[0] == sum(committed)
    assert sum(committed) >= 1


def test_concurrent_apb_and_msgpack_dialects():
    """Both wire dialects interleave safely across threads on one server."""
    import socket

    from antidote_tpu.proto import apb

    node, srv = _mk_server()
    errors = []

    def apb_worker(i):
        try:
            s = socket.create_connection(("127.0.0.1", srv.port))

            def call(name, d):
                body = apb.encode_frame_body(name, d)
                s.sendall(struct.pack(">I", len(body)) + body)
                hdr = b""
                while len(hdr) < 4:
                    hdr += s.recv(4 - len(hdr))
                (n,) = struct.unpack(">I", hdr)
                buf = b""
                while len(buf) < n:
                    buf += s.recv(n - len(buf))
                return apb.decode_frame_body(buf)

            for j in range(20):
                name, r = call("ApbStaticUpdateObjects", {
                    "transaction": {},
                    "updates": [{"boundobject": {"key": b"mix",
                                                 "type": 3, "bucket": b"b"},
                                 "operation": {"counterop": {"inc": 1}}}],
                })
                assert name == "ApbCommitResp" and r["success"], (name, r)
            s.close()
        except Exception as e:  # pragma: no cover
            errors.append(("apb", i, repr(e)))

    def native_worker(i):
        try:
            c = AntidoteClient("127.0.0.1", srv.port)
            for j in range(20):
                c.update_objects([(b"mix", "counter_pn", b"b",
                                   ("increment", 1))])
            c.close()
        except Exception as e:  # pragma: no cover
            errors.append(("native", i, repr(e)))

    threads = ([threading.Thread(target=apb_worker, args=(i,))
                for i in range(3)]
               + [threading.Thread(target=native_worker, args=(i,))
                  for i in range(3)])
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=120)
    assert not errors, errors
    vals, _ = node.read_objects([(b"mix", "counter_pn", b"b")])
    assert vals[0] == 6 * 20
    srv.close()


def test_connection_cap_backpressure():
    """r3 VERDICT weak #8: the server holds at most ``max_connections``
    live connections (the reference's ranch cap of 1024,
    /root/reference/src/antidote_pb_sup.erl:47-56).  The (cap+1)-th
    client queues in the accept backlog — it is NOT served until a slot
    frees — then proceeds cleanly once one closes; nothing is dropped."""
    cfg = AntidoteConfig(n_shards=4, max_dcs=2, keys_per_table=256,
                         batch_buckets=(16, 64))
    node = AntidoteNode(cfg)
    cap = 4
    srv = ProtocolServer(node, port=0, max_connections=cap)
    # fill every slot with a live client (a request proves it's served)
    holders = []
    for i in range(cap):
        c = AntidoteClient("127.0.0.1", srv.port)
        c.update_objects([("cc", "counter_pn", "b", ("increment", 1))])
        holders.append(c)
    # the cap+1-th client connects (kernel backlog) but must not be
    # served while all slots are held
    done = threading.Event()
    result = {}

    def overflow_worker():
        c = AntidoteClient("127.0.0.1", srv.port)
        c.update_objects([("cc", "counter_pn", "b", ("increment", 1))])
        vals, _ = c.read_objects([("cc", "counter_pn", "b")])
        result["val"] = vals[0]
        c.close()
        done.set()

    t = threading.Thread(target=overflow_worker, daemon=True)
    t.start()
    assert not done.wait(timeout=1.0), (
        "connection beyond the cap was served while all slots were held")
    holders[0].close()  # free a slot
    assert done.wait(timeout=30), "queued connection never got served"
    assert result["val"] == cap + 1
    for c in holders[1:]:
        c.close()
    srv.close()


def test_reads_monotonic_under_concurrent_writes():
    """Value-cache coherence over the wire: while one client increments
    a counter, other clients' reads must never go BACKWARD (a stale
    cache entry served after a newer value was observed would violate
    session monotonicity)."""
    node, srv = _mk_server()
    stop = threading.Event()
    errors = []

    def writer():
        try:
            c = AntidoteClient("127.0.0.1", srv.port)
            for _ in range(200):
                c.update_objects([("mono", "counter_pn", "b",
                                   ("increment", 1))])
            c.close()
        except Exception as e:  # pragma: no cover
            errors.append(repr(e))
        finally:
            stop.set()

    def reader(i):
        try:
            c = AntidoteClient("127.0.0.1", srv.port)
            last = -1
            while not stop.is_set():
                vals, _ = c.read_objects([("mono", "counter_pn", "b")])
                v = vals[0]
                assert v >= last, f"read went backward: {last} -> {v}"
                last = v
            c.close()
        except Exception as e:  # pragma: no cover
            errors.append(repr(e))

    ts = [threading.Thread(target=writer)] + [
        threading.Thread(target=reader, args=(i,)) for i in range(4)]
    for t in ts:
        t.start()
    for t in ts:
        t.join(timeout=180)
    assert not errors, errors
    vals, _ = node.read_objects([("mono", "counter_pn", "b")])
    assert vals[0] == 200
    srv.close()
