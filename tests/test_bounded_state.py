"""Bounded long-run state + WAL-backed catch-up (r2 VERDICT item 4).

Covers: catch-up served from the durable log once the in-memory window
has rolled past the requested opid; O(touched-shards) fabric messages
per commit (heartbeats timer/threshold/pump-driven, not per-commit);
committed_keys certification-table GC below every open snapshot; and
restore_from_log grouping txns by (origin, vc) identity rather than
record adjacency (r1 advisor medium (c)).
"""

import pytest

from antidote_tpu.api.node import AntidoteNode
from antidote_tpu.config import AntidoteConfig
from antidote_tpu.interdc.replica import DCReplica
from antidote_tpu.interdc.transport import LoopbackHub


def _cfg(**kw):
    base = dict(n_shards=4, max_dcs=3, ops_per_key=8, keys_per_table=64,
                batch_buckets=(16, 64))
    base.update(kw)
    return AntidoteConfig(**base)


def _mk_dc(dc_id, hub, tmp_path=None):
    cfg = _cfg()
    log_dir = str(tmp_path / f"dc{dc_id}") if tmp_path is not None else None
    node = AntidoteNode(cfg, dc_id=dc_id, log_dir=log_dir)
    return DCReplica(node, hub)


def test_catch_up_below_window_served_from_wal(tmp_path, monkeypatch):
    """Drop a txn, roll the in-memory window fully past it, and verify the
    gap still heals — the catch-up query regroups the chain from the WAL."""
    monkeypatch.setattr(DCReplica, "SENT_WINDOW", 4)
    hub = LoopbackHub()
    r0 = _mk_dc(0, hub, tmp_path)
    r1 = _mk_dc(1, hub, tmp_path)
    DCReplica.connect_all([r0, r1])

    r0.node.update_objects([("k0", "counter_pn", "b", ("increment", 1))])
    hub.pump()
    # lose the next message to DC1, then commit enough to roll the window
    # (same shard key) far past the lost opid
    hub.drop_next(0, 1, n=1)
    for i in range(10):
        r0.node.update_objects([("k0", "counter_pn", "b", ("increment", 1))])
        hub.pump()
    assert len(r0.sent[r0.node.store.locate("k0", "counter_pn", "b")[1]]) == 4
    r0.heartbeat()
    hub.pump()
    vals, _ = r1.node.read_objects([("k0", "counter_pn", "b")])
    assert vals[0] == 11


def test_no_wal_below_window_raises(monkeypatch):
    monkeypatch.setattr(DCReplica, "SENT_WINDOW", 2)
    hub = LoopbackHub()
    r0 = _mk_dc(0, hub)
    shard = None
    for i in range(6):
        r0.node.update_objects([("k0", "counter_pn", "b", ("increment", 1))])
        shard = r0.node.store.locate("k0", "counter_pn", "b")[1]
    with pytest.raises(RuntimeError, match="below the in-memory window"):
        r0._serve_log_query(shard, 0, 0)


def test_commit_publishes_only_touched_shards():
    """r2 VERDICT weak #5: a commit publishes one message per TOUCHED
    shard; idle-shard safe times flush once per pump, not per commit."""
    hub = LoopbackHub()
    r0 = _mk_dc(0, hub)
    r1 = _mk_dc(1, hub)
    DCReplica.connect_all([r0, r1])
    # pin the wall-clock heartbeat out of the way: first-compile latency can
    # stretch the commit loop past 1 s, and a mid-loop timer flush would
    # break the exact message counts this test is about
    r0.HEARTBEAT_INTERVAL_S = r1.HEARTBEAT_INTERVAL_S = 1e9
    published = []
    orig = hub.publish
    hub.publish = lambda f, d: (published.append(f), orig(f, d))

    n_commits = 5
    for i in range(n_commits):
        r0.node.update_objects([(f"k{i}", "counter_pn", "b",
                                 ("increment", 1))])
    # 5 commits, each touching one shard -> exactly 5 txn messages so far
    # (no per-commit heartbeat fan-out)
    assert len(published) == n_commits
    hub.pump()  # tick flushes ONE heartbeat round (n_shards pings)
    assert len(published) == n_commits + r0.node.cfg.n_shards
    hub.pump()  # quiescent: no commits since flush -> no more pings
    assert len(published) == n_commits + r0.node.cfg.n_shards
    # remote still converges
    vals, _ = r1.node.read_objects([("k0", "counter_pn", "b")])
    assert vals[0] == 1


def test_committed_keys_gc_bounded():
    # register_lww assigns: NOT blind-commutative, so every commit
    # stamps the certification table (blind counter increments take the
    # ISSUE 6 bypass and never stamp — this test exercises the table's
    # GC, so it needs writes that populate it)
    node = AntidoteNode(_cfg(keys_per_table=8192))
    txm = node.txm
    txm._cert_gc_every = 256
    txm._next_cert_gc = 256
    for i in range(1000):
        node.update_objects([(f"k{i}", "register_lww", "b",
                              ("assign", f"v{i}"))])
    # GC fired at least thrice; all but the entries since the last floor
    # advance are gone
    assert len(txm.committed_keys) <= 2 * txm._cert_gc_every
    # correctness: first-committer-wins still aborts on a real conflict
    t1 = node.start_transaction()
    node.update_objects([("kX", "register_lww", "b", ("assign", "a"))], t1)
    node.update_objects([("kX", "register_lww", "b", ("assign", "b"))])
    from antidote_tpu.txn.manager import AbortError
    with pytest.raises(AbortError):
        node.commit_transaction(t1)
    # an open txn pins the floor: entries above its snapshot survive GC
    t2 = node.start_transaction()
    for i in range(600):
        node.update_objects([(f"pin{i}", "register_lww", "b",
                              ("assign", f"p{i}"))])
    assert any(
        v > txm._open_snaps[t2.txid] for v in txm.committed_keys.values()
    )
    node.commit_transaction(t2)


def test_restore_groups_txns_by_identity_not_adjacency(tmp_path):
    """r1 advisor medium (c): a multi-shard txn whose WAL records get
    re-chained non-adjacently (handoff/reshard replay order) must count as
    ONE chain opid after restore."""
    cfg = _cfg(n_shards=2)
    node = AntidoteNode(cfg, log_dir=str(tmp_path / "src"))
    # txn T writes two keys on DIFFERENT shards; a later txn writes one
    ka, kb = 0, 1  # int keys: shard = key % n_shards
    node.update_objects([
        (ka, "counter_pn", "b", ("increment", 1)),
        (kb, "counter_pn", "b", ("increment", 2)),
    ])
    node.update_objects([(ka, "counter_pn", "b", ("increment", 3))])
    node.store.log.close()

    # reshard to ONE shard: both old shards' chains re-log into shard 0,
    # so T's two records are separated by replay order
    from antidote_tpu.log import LogManager
    from antidote_tpu.store import handoff
    from antidote_tpu.store.kv import KVStore

    src_log = LogManager(cfg, str(tmp_path / "src"))
    src = KVStore(cfg, log=src_log)
    src.recover()
    import dataclasses
    cfg1 = dataclasses.replace(cfg, n_shards=1)
    new_log = LogManager(cfg1, str(tmp_path / "dst"))
    dst = handoff.reshard(src, cfg1, log=new_log)

    node2 = AntidoteNode(cfg1, store=dst)
    hub = LoopbackHub()
    r2 = DCReplica(node2, hub)
    r2.restore_from_log()
    # 2 transactions total -> chain opid exactly 2 (adjacency grouping
    # would have split T into two groups iff its records interleaved; with
    # identity grouping the count is exact either way)
    assert int(r2.pub_opid[0]) == 2
    groups = r2._wal_txn_groups(0)
    assert len(groups) == 2
    assert sorted(len(g[2]) for g in groups) == [1, 2]


def test_proto_server_aborts_orphaned_txns():
    """A client connection that dies mid-transaction must not pin the
    certification-GC floor forever (r3 review)."""
    import socket
    import time as _time

    from antidote_tpu.proto.client import AntidoteClient
    from antidote_tpu.proto.server import ProtocolServer

    node = AntidoteNode(_cfg())
    srv = ProtocolServer(node, port=0)
    try:
        c = AntidoteClient("127.0.0.1", srv.port)
        txn = c.start_transaction()
        txn.update_objects([("k", "counter_pn", "b", ("increment", 1))])
        assert node.txm._open_snaps  # open txn tracked
        c.close()
        for _ in range(100):
            if not node.txm._open_snaps:
                break
            _time.sleep(0.05)
        assert not node.txm._open_snaps, "orphaned txn not aborted"
        assert not srv._txns
    finally:
        srv.close()


def test_log_dir_shape_persisted_and_validated(tmp_path):
    """r1 advisor medium (a): booting a WAL dir under a different
    {n_shards, max_dcs} fails loudly instead of silently stranding
    committed shards / mis-laning clocks."""
    import dataclasses

    from antidote_tpu.log import LogDirMismatch, LogManager, load_dir_meta

    cfg = _cfg()
    d = str(tmp_path / "wal")
    node = AntidoteNode(cfg, log_dir=d)
    node.update_objects([("k", "counter_pn", "b", ("increment", 1))])
    node.store.log.close()
    assert load_dir_meta(d) == {"n_shards": cfg.n_shards,
                                "max_dcs": cfg.max_dcs, "version": 1}
    with pytest.raises(LogDirMismatch, match="n_shards"):
        LogManager(dataclasses.replace(cfg, n_shards=cfg.n_shards // 2), d)
    with pytest.raises(LogDirMismatch, match="max_dcs"):
        LogManager(dataclasses.replace(cfg, max_dcs=cfg.max_dcs + 1), d)
    # the recorded shape reopens fine
    LogManager(cfg, d).close()
    # legacy dir (no meta): both shrink AND grow refuse — the eager
    # shard-file count IS the written shape
    import os
    legacy = str(tmp_path / "legacy")
    os.makedirs(legacy)
    for i in range(4):
        open(os.path.join(legacy, f"shard_{i}.wal"), "wb").close()
    with pytest.raises(LogDirMismatch, match="written with n_shards=4"):
        LogManager(dataclasses.replace(cfg, n_shards=2), legacy)
    with pytest.raises(LogDirMismatch, match="written with n_shards=4"):
        LogManager(dataclasses.replace(cfg, n_shards=8), legacy)
    # a truncated meta file fails actionably, naming the path
    broken = str(tmp_path / "broken")
    os.makedirs(broken)
    open(os.path.join(broken, "antidote_meta.json"), "w").close()
    with pytest.raises(LogDirMismatch, match="unreadable"):
        LogManager(cfg, broken)


def test_console_serve_defaults_shape_from_log_dir(tmp_path):
    """cmd_serve's shape resolution: explicit flag > recorded dir shape >
    defaults (r3 review: drive the real console logic, not just stamping)."""
    from antidote_tpu.console import resolve_serve_shape
    from antidote_tpu.log import LogManager

    cfg = _cfg(n_shards=2)
    d = str(tmp_path / "wal")
    LogManager(cfg, d).close()
    # recorded shape wins over defaults
    assert resolve_serve_shape(d, None, None) == (2, cfg.max_dcs)
    # explicit flag wins over the recorded shape (LogManager then refuses)
    assert resolve_serve_shape(d, 8, None) == (8, cfg.max_dcs)
    # no dir: defaults
    assert resolve_serve_shape(None, None, None) == (16, 8)
    assert resolve_serve_shape(str(tmp_path / "missing"), None, 3) == (16, 3)


def test_reshard_refuses_inflight_replication(tmp_path):
    """r1 advisor medium (b): reshard must assert replication quiescence
    — gated/pending remote txns or unequal remote lanes refuse."""
    import dataclasses

    from antidote_tpu.store import handoff

    hub = LoopbackHub()
    r0 = _mk_dc(0, hub, tmp_path)
    r1 = _mk_dc(1, hub, tmp_path)
    DCReplica.connect_all([r0, r1])
    r0.node.update_objects([("k", "counter_pn", "b", ("increment", 1))])
    # NOT pumped: r1 has nothing yet; r0's lanes are its own -> r0 itself
    # is quiescent, but after partial delivery r1 is not
    hub.pump()
    r0.node.update_objects([("k2", "counter_pn", "b", ("increment", 1))])
    # deliver the txn but NOT the heartbeat flush: lane 0 unequal across
    # r1's shards
    while hub.queues:
        to_dc, cb, data = hub.queues.popleft()
        cb(data)
    cfg1 = dataclasses.replace(r1.node.cfg, n_shards=2)
    with pytest.raises(RuntimeError, match="origin lane 0 differs"):
        handoff.reshard(r1.node.store, cfg1, my_dc=1, replica=r1)
    # after full pump + heartbeat the lanes equalize and reshard proceeds
    r0.heartbeat()
    hub.pump()
    handoff.reshard(r1.node.store, cfg1, my_dc=1, replica=r1)
