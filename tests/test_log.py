"""Durability: C++ WAL, crash recovery, log-replay fallback.

Mirrors the reference's log_recovery_SUITE (updates, kill node, restart,
verify replay — /root/reference/test/singledc/log_recovery_SUITE.erl:59-79).
"""

import os

import numpy as np
import pytest

from antidote_tpu.api import AntidoteNode
from antidote_tpu.log.wal import ShardWAL, replay, _load_lib

pytestmark = pytest.mark.smoke


def test_wal_native_build():
    assert _load_lib() is not None, "C++ WAL must compile with g++"


def test_wal_roundtrip(tmp_path):
    p = str(tmp_path / "a.wal")
    w = ShardWAL(p)
    assert w.native
    for i in range(100):
        w.append({"i": i, "blob": b"x" * i})
    w.commit()
    w.close()
    recs = list(replay(p))
    assert [r["i"] for r in recs] == list(range(100))


def test_wal_torn_tail_recovery(tmp_path):
    p = str(tmp_path / "b.wal")
    w = ShardWAL(p)
    for i in range(10):
        w.append({"i": i})
    w.commit()
    w.close()
    # simulate a crash mid-append: truncate into the last record
    size = os.path.getsize(p)
    with open(p, "r+b") as f:
        f.truncate(size - 3)
    recs = list(replay(p))
    assert [r["i"] for r in recs] == list(range(9))


def test_node_recovery(tmp_path, cfg):
    log_dir = str(tmp_path / "logs")
    node = AntidoteNode(cfg, log_dir=log_dir)
    node.update_objects([
        ("c", "counter_pn", "b", ("increment", 7)),
        ("s", "set_aw", "b", ("add_all", ["x", "y"])),
        ("r", "register_lww", "b", ("assign", "val")),
    ])
    node.update_objects([("s", "set_aw", "b", ("remove", "x"))])
    vc = node.update_objects([("c", "counter_pn", "b", ("increment", 5))])
    node.store.log.close()

    # "restart": fresh node, same log dir, recover
    node2 = AntidoteNode(cfg, log_dir=log_dir, recover=True)
    vals, _ = node2.read_objects(
        [("c", "counter_pn", "b"), ("s", "set_aw", "b"),
         ("r", "register_lww", "b")], clock=vc)
    assert vals == [12, ["y"], "val"]
    # commit counter restored: next commit continues the chain
    vc2 = node2.update_objects([("c", "counter_pn", "b", ("increment", 1))])
    assert vc2[node2.dc_id] > vc[node2.dc_id]
    vals, _ = node2.read_objects([("c", "counter_pn", "b")], clock=vc2)
    assert vals == [13]


def test_recovery_preserves_certification(tmp_path, cfg):
    log_dir = str(tmp_path / "logs")
    node = AntidoteNode(cfg, log_dir=log_dir)
    node.update_objects([("k", "counter_pn", "b", ("increment", 1))])
    node.store.log.close()
    node2 = AntidoteNode(cfg, log_dir=log_dir, recover=True)
    # a txn whose snapshot predates the recovered commit must abort
    from antidote_tpu.txn.manager import Transaction

    stale = Transaction(np.zeros(cfg.max_dcs, np.int32))
    # read-bearing: a blind increment would take the commutativity
    # bypass (ISSUE 6) and legitimately skip certification
    node2.txm.read_objects([("k", "counter_pn", "b")], stale)
    node2.txm.update_objects(
        [("k", "counter_pn", "b", ("increment", 1))], stale)
    from antidote_tpu.api import AbortError

    with pytest.raises(AbortError):
        node2.txm.commit_transaction(stale)


def test_incomplete_read_falls_back_to_log(tmp_path, cfg):
    log_dir = str(tmp_path / "logs")
    node = AntidoteNode(cfg, log_dir=log_dir)
    vcs = []
    for i in range(25):  # far beyond ring+versions coverage (8 ops, 2 vers)
        vcs.append(node.update_objects(
            [("k", "counter_pn", "b", ("increment", 1))]))
    # read far in the past — device coverage is gone, log replay serves it
    old = vcs[2]
    vals, _ = node.read_objects([("k", "counter_pn", "b")], clock=None)
    txn = node.start_transaction()
    txn.snapshot_vc = np.asarray(old, np.int32)
    assert node.read_objects([("k", "counter_pn", "b")], txn) == [3]
    assert vals[0] == 25


def test_opid_chains(tmp_path, cfg):
    log_dir = str(tmp_path / "logs")
    node = AntidoteNode(cfg, log_dir=log_dir)
    node.update_objects([(i, "counter_pn", "b", ("increment", 1))
                         for i in range(12)])
    ids = node.store.log.op_ids
    # every op got a chained id on this DC's lane; totals match op count
    assert ids[:, node.dc_id].sum() == 12
    assert (ids[:, 1:] == 0).all()


def test_mixed_type_incomplete_read_fallback(tmp_path, cfg):
    # regression: the log-replay fallback must map type-batch-local indices
    # back to the right global object (bug: replayed the wrong key/type)
    log_dir = str(tmp_path / "logs")
    node = AntidoteNode(cfg, log_dir=log_dir)
    early = None
    for i in range(25):
        vc = node.update_objects([
            ("c", "counter_pn", "b", ("increment", 1)),
            ("s", "set_aw", "b", ("add", f"e{i % 3}")),
        ])
        if i == 2:
            early = vc
    txn = node.start_transaction()
    txn.snapshot_vc = np.asarray(early, np.int32)
    vals = node.read_objects(
        [("c", "counter_pn", "b"), ("s", "set_aw", "b")], txn)
    assert vals[0] == 3
    assert vals[1] == ["e0", "e1", "e2"]


def _seq_of(recs):
    return [int(r["q"]) for r in recs]


def test_resegment_across_restarts_replays_exact_order(tmp_path, cfg):
    """ISSUE 8 satellite: a WAL written with one ``--wal-segments``
    count and recovered with ANOTHER (both fewer and more) replays
    every record in exact append-sequence order.  PR 6 claimed the
    fewer-segments case; this pins both directions, plus appends AFTER
    the re-segmented reopen continuing the same total order."""
    import dataclasses

    from antidote_tpu.log import LogManager

    log_dir = str(tmp_path / "wal")

    def entries(base, n):
        return [
            (s, f"k{base + i}", "counter_pn", "b",
             np.asarray([base + i], np.int64), np.asarray([], np.int32),
             np.asarray([base + i + 1, 0, 0], np.int32), 0, ())
            for i in range(n) for s in (0, 1)
        ]

    cfg3 = dataclasses.replace(cfg, wal_segments=3)
    lm = LogManager(cfg3, log_dir)
    for i in range(8):  # several barriers so records spread over segments
        lm.log_effects(entries(i * 10, 1))
        lm.commit_barrier([0, 1])
    lm.close()

    for n_seg in (1, 6, 2):  # fewer, more, and fewer again
        cfg_n = dataclasses.replace(cfg, wal_segments=n_seg)
        lm2 = LogManager(cfg_n, log_dir)
        for shard in (0, 1):
            recs = list(lm2.replay_shard(shard))
            qs = _seq_of(recs)
            assert qs == sorted(qs), (n_seg, shard, qs)
            assert len(qs) == len(set(qs)), "duplicate append sequences"
        lm2.close()

    # reopen with MORE segments, append more, then recover with fewer:
    # the cross-restart interleaving must still merge into one exact
    # total order per shard with nothing lost
    cfg6 = dataclasses.replace(cfg, wal_segments=6)
    lm3 = LogManager(cfg6, log_dir)
    n_before = [len(list(lm3.replay_shard(s))) for s in (0, 1)]
    for i in range(5):
        lm3.log_effects(entries(1000 + i * 10, 1))
        lm3.commit_barrier([0, 1])
    lm3.close()
    cfg2 = dataclasses.replace(cfg, wal_segments=2)
    lm4 = LogManager(cfg2, log_dir)
    for shard in (0, 1):
        recs = list(lm4.replay_shard(shard))
        qs = _seq_of(recs)
        assert len(recs) == n_before[shard] + 5
        assert qs == list(range(1, len(qs) + 1)), (shard, qs)
    lm4.close()


def test_resegment_recovery_through_node(tmp_path, cfg):
    """The node-level twin: write under wal_segments=3, recover under 1
    and under 6 — values and op-id chains identical both ways."""
    import dataclasses

    cfg3 = dataclasses.replace(cfg, wal_segments=3)
    log_dir = str(tmp_path / "wal")
    node = AntidoteNode(cfg3, log_dir=log_dir)
    vc = None
    for i in range(10):
        vc = node.update_objects([
            ("k", "counter_pn", "b", ("increment", 1)),
            (f"s{i % 3}", "set_aw", "b", ("add", f"e{i}")),
        ])
    want_ops = node.store.log.op_ids.copy()
    node.store.log.close()
    for n_seg in (1, 6):
        cfg_n = dataclasses.replace(cfg, wal_segments=n_seg)
        n2 = AntidoteNode(cfg_n, log_dir=log_dir, recover=True)
        vals, _ = n2.read_objects([("k", "counter_pn", "b")], clock=vc)
        assert vals == [10]
        assert (n2.store.log.op_ids == want_ops).all()
        n2.store.log.close()


def test_get_log_operations(tmp_path, cfg):
    """antidote:get_log_operations parity
    (/root/reference/src/antidote.erl:69-90): per object, all logged
    update ops newer than the given snapshot time, in log order."""
    node = AntidoteNode(cfg, log_dir=str(tmp_path / "logs"))
    vc1 = node.update_objects([("c", "counter_pn", "b", ("increment", 3))])
    node.update_objects([("c", "counter_pn", "b", ("increment", 4))])
    node.update_objects([("s", "set_aw", "b", ("add", "x"))])

    # clock=None -> everything logged for the object
    (all_c,), = [node.get_log_operations([(("c", "counter_pn", "b"), None)])]
    assert len(all_c) == 2
    opids = [opid for opid, _ in all_c]
    assert opids == sorted(opids)
    assert all_c[0][1]["effect"].type_name == "counter_pn"

    # clock=vc1 -> only the second increment is newer
    (newer,), = [node.get_log_operations([(("c", "counter_pn", "b"), vc1)])]
    assert len(newer) == 1
    assert newer[0][0] == all_c[1][0]
    assert (newer[0][1]["commit_vc"][node.dc_id]
            > np.asarray(vc1)[node.dc_id])

    # multiple objects in one call; missing key -> empty list
    res = node.get_log_operations([
        (("s", "set_aw", "b"), None), (("nope", "counter_pn", "b"), None)])
    assert len(res[0]) == 1 and res[1] == []
