"""Durability: C++ WAL, crash recovery, log-replay fallback.

Mirrors the reference's log_recovery_SUITE (updates, kill node, restart,
verify replay — /root/reference/test/singledc/log_recovery_SUITE.erl:59-79).
"""

import os

import numpy as np
import pytest

from antidote_tpu.api import AntidoteNode
from antidote_tpu.log.wal import ShardWAL, replay, _load_lib

pytestmark = pytest.mark.smoke


def test_wal_native_build():
    assert _load_lib() is not None, "C++ WAL must compile with g++"


def test_wal_roundtrip(tmp_path):
    p = str(tmp_path / "a.wal")
    w = ShardWAL(p)
    assert w.native
    for i in range(100):
        w.append({"i": i, "blob": b"x" * i})
    w.commit()
    w.close()
    recs = list(replay(p))
    assert [r["i"] for r in recs] == list(range(100))


def test_wal_torn_tail_recovery(tmp_path):
    p = str(tmp_path / "b.wal")
    w = ShardWAL(p)
    for i in range(10):
        w.append({"i": i})
    w.commit()
    w.close()
    # simulate a crash mid-append: truncate into the last record
    size = os.path.getsize(p)
    with open(p, "r+b") as f:
        f.truncate(size - 3)
    recs = list(replay(p))
    assert [r["i"] for r in recs] == list(range(9))


def test_node_recovery(tmp_path, cfg):
    log_dir = str(tmp_path / "logs")
    node = AntidoteNode(cfg, log_dir=log_dir)
    node.update_objects([
        ("c", "counter_pn", "b", ("increment", 7)),
        ("s", "set_aw", "b", ("add_all", ["x", "y"])),
        ("r", "register_lww", "b", ("assign", "val")),
    ])
    node.update_objects([("s", "set_aw", "b", ("remove", "x"))])
    vc = node.update_objects([("c", "counter_pn", "b", ("increment", 5))])
    node.store.log.close()

    # "restart": fresh node, same log dir, recover
    node2 = AntidoteNode(cfg, log_dir=log_dir, recover=True)
    vals, _ = node2.read_objects(
        [("c", "counter_pn", "b"), ("s", "set_aw", "b"),
         ("r", "register_lww", "b")], clock=vc)
    assert vals == [12, ["y"], "val"]
    # commit counter restored: next commit continues the chain
    vc2 = node2.update_objects([("c", "counter_pn", "b", ("increment", 1))])
    assert vc2[node2.dc_id] > vc[node2.dc_id]
    vals, _ = node2.read_objects([("c", "counter_pn", "b")], clock=vc2)
    assert vals == [13]


def test_recovery_preserves_certification(tmp_path, cfg):
    log_dir = str(tmp_path / "logs")
    node = AntidoteNode(cfg, log_dir=log_dir)
    node.update_objects([("k", "counter_pn", "b", ("increment", 1))])
    node.store.log.close()
    node2 = AntidoteNode(cfg, log_dir=log_dir, recover=True)
    # a txn whose snapshot predates the recovered commit must abort
    from antidote_tpu.txn.manager import Transaction

    stale = Transaction(np.zeros(cfg.max_dcs, np.int32))
    # read-bearing: a blind increment would take the commutativity
    # bypass (ISSUE 6) and legitimately skip certification
    node2.txm.read_objects([("k", "counter_pn", "b")], stale)
    node2.txm.update_objects(
        [("k", "counter_pn", "b", ("increment", 1))], stale)
    from antidote_tpu.api import AbortError

    with pytest.raises(AbortError):
        node2.txm.commit_transaction(stale)


def test_incomplete_read_falls_back_to_log(tmp_path, cfg):
    log_dir = str(tmp_path / "logs")
    node = AntidoteNode(cfg, log_dir=log_dir)
    vcs = []
    for i in range(25):  # far beyond ring+versions coverage (8 ops, 2 vers)
        vcs.append(node.update_objects(
            [("k", "counter_pn", "b", ("increment", 1))]))
    # read far in the past — device coverage is gone, log replay serves it
    old = vcs[2]
    vals, _ = node.read_objects([("k", "counter_pn", "b")], clock=None)
    txn = node.start_transaction()
    txn.snapshot_vc = np.asarray(old, np.int32)
    assert node.read_objects([("k", "counter_pn", "b")], txn) == [3]
    assert vals[0] == 25


def test_opid_chains(tmp_path, cfg):
    log_dir = str(tmp_path / "logs")
    node = AntidoteNode(cfg, log_dir=log_dir)
    node.update_objects([(i, "counter_pn", "b", ("increment", 1))
                         for i in range(12)])
    ids = node.store.log.op_ids
    # every op got a chained id on this DC's lane; totals match op count
    assert ids[:, node.dc_id].sum() == 12
    assert (ids[:, 1:] == 0).all()


def test_mixed_type_incomplete_read_fallback(tmp_path, cfg):
    # regression: the log-replay fallback must map type-batch-local indices
    # back to the right global object (bug: replayed the wrong key/type)
    log_dir = str(tmp_path / "logs")
    node = AntidoteNode(cfg, log_dir=log_dir)
    early = None
    for i in range(25):
        vc = node.update_objects([
            ("c", "counter_pn", "b", ("increment", 1)),
            ("s", "set_aw", "b", ("add", f"e{i % 3}")),
        ])
        if i == 2:
            early = vc
    txn = node.start_transaction()
    txn.snapshot_vc = np.asarray(early, np.int32)
    vals = node.read_objects(
        [("c", "counter_pn", "b"), ("s", "set_aw", "b")], txn)
    assert vals[0] == 3
    assert vals[1] == ["e0", "e1", "e2"]


def test_get_log_operations(tmp_path, cfg):
    """antidote:get_log_operations parity
    (/root/reference/src/antidote.erl:69-90): per object, all logged
    update ops newer than the given snapshot time, in log order."""
    node = AntidoteNode(cfg, log_dir=str(tmp_path / "logs"))
    vc1 = node.update_objects([("c", "counter_pn", "b", ("increment", 3))])
    node.update_objects([("c", "counter_pn", "b", ("increment", 4))])
    node.update_objects([("s", "set_aw", "b", ("add", "x"))])

    # clock=None -> everything logged for the object
    (all_c,), = [node.get_log_operations([(("c", "counter_pn", "b"), None)])]
    assert len(all_c) == 2
    opids = [opid for opid, _ in all_c]
    assert opids == sorted(opids)
    assert all_c[0][1]["effect"].type_name == "counter_pn"

    # clock=vc1 -> only the second increment is newer
    (newer,), = [node.get_log_operations([(("c", "counter_pn", "b"), vc1)])]
    assert len(newer) == 1
    assert newer[0][0] == all_c[1][0]
    assert (newer[0][1]["commit_vc"][node.dc_id]
            > np.asarray(vc1)[node.dc_id])

    # multiple objects in one call; missing key -> empty list
    res = node.get_log_operations([
        (("s", "set_aw", "b"), None), (("nope", "counter_pn", "b"), None)])
    assert len(res[0]) == 1 and res[1] == []
