"""Overload-protection tests (ISSUE 4): typed shed errors on every
plane, deadline discipline, and the WAL-failure read-only degraded mode.

The invariants under test mirror riak_core's vnode overload protection:
a saturated plane answers a TYPED busy/deadline/read-only error (with a
retry hint where that helps the client), in-flight work still completes,
and degraded modes exit automatically once the underlying fault clears —
no silent queue growth, no wedged node, no operator restart.
"""

import threading
import time

import pytest

from antidote_tpu import faults
from antidote_tpu.api.node import AntidoteNode
from antidote_tpu.txn.manager import AbortError
from antidote_tpu.config import AntidoteConfig
from antidote_tpu.overload import (
    AdmissionGate,
    BusyError,
    DeadlineExceeded,
    check_deadline,
    deadline_from_ms,
)
from antidote_tpu.proto.client import (
    AntidoteClient,
    RemoteBusy,
    RemoteDeadline,
    RemoteReadOnly,
)
from antidote_tpu.proto.server import ProtocolServer


@pytest.fixture(autouse=True)
def _disarm():
    yield
    faults.uninstall()


def mk_cfg():
    # same shapes as test_proto: the XLA compile cache stays warm
    return AntidoteConfig(
        n_shards=2, max_dcs=2, ops_per_key=8, snap_versions=2,
        set_slots=8, rga_slots=16, keys_per_table=64, batch_buckets=(8, 64),
    )


# ---------------------------------------------------------------------------
# primitives
# ---------------------------------------------------------------------------
@pytest.mark.smoke
def test_admission_gate_caps_and_hints():
    g = AdmissionGate(max_in_flight=2, max_per_client=1)
    g.enter(1)
    # per-client cap fires before the global one
    with pytest.raises(BusyError) as e1:
        g.enter(1)
    assert e1.value.retry_after_ms >= 25
    g.enter(2)
    with pytest.raises(BusyError) as e2:
        g.enter(3)  # global cap
    assert "max_in_flight=2" in str(e2.value)
    g.exit(1)
    g.enter(3)  # freed slot readmits
    g.exit(2)
    g.exit(3)
    assert g.in_flight() == 0


@pytest.mark.smoke
def test_deadline_helpers():
    assert deadline_from_ms(None, None) is None
    # client budget wins over the configured default
    d = deadline_from_ms(10_000, 1.0)
    assert d is not None and d > time.monotonic() + 5
    check_deadline(None, "anywhere")  # no deadline = never expires
    check_deadline(time.monotonic() + 5, "dispatch")
    with pytest.raises(DeadlineExceeded, match="dequeue"):
        check_deadline(time.monotonic() - 0.01, "dequeue")


# ---------------------------------------------------------------------------
# WAL failure -> read-only degraded mode -> auto-recovery
# ---------------------------------------------------------------------------
@pytest.fixture(params=["native", "python"])
def wal_plane(request, monkeypatch):
    """Run the degraded-mode path over both WAL implementations."""
    from antidote_tpu.log import wal as walmod

    if request.param == "python":
        monkeypatch.setattr(walmod, "_load_lib", lambda: None)
    elif walmod._load_lib() is None:
        pytest.skip("native WAL unavailable in this image")
    return request.param


def test_wal_probe_consults_fault_site(tmp_path, wal_plane):
    from antidote_tpu.log.wal import ShardWAL

    wal = ShardWAL(str(tmp_path / "shard_0.wal"))
    assert wal.native == (wal_plane == "native")
    wal.probe()  # healthy volume: no-op
    faults.install(faults.FaultPlan(seed=1).enospc("wal.append", times=2))
    import errno

    with pytest.raises(OSError) as e:
        wal.probe()
    assert e.value.errno == errno.ENOSPC
    with pytest.raises(OSError):
        wal.probe()
    wal.probe()  # rule exhausted: the volume is "writable" again
    wal.close()
    # the probe's sidecar never pollutes the log directory
    assert list(tmp_path.iterdir()) == [tmp_path / "shard_0.wal"]


@pytest.mark.parametrize("action", ["enospc", "io_error"])
def test_node_wal_failure_enters_and_exits_read_only(tmp_path, wal_plane,
                                                     action):
    node = AntidoteNode(mk_cfg(), log_dir=str(tmp_path))
    node.update_objects([("k", "counter_pn", "b", ("increment", 1))])
    plan = faults.FaultPlan(seed=7)
    getattr(plan, action)("wal.append", times=3)
    faults.install(plan)
    from antidote_tpu.overload import ReadOnlyError

    # the failing append aborts the commit and flips the node read-only
    with pytest.raises(ReadOnlyError):
        node.update_objects([("k", "counter_pn", "b", ("increment", 2))])
    assert node.txm.read_only_reason is not None
    assert node.metrics.degraded_read_only.value() == 1
    # reads keep serving (and see only the pre-fault commit)
    vals, _ = node.read_objects([("k", "counter_pn", "b")])
    assert vals == [1]
    # while the volume still fails, writes stay rejected (each attempt
    # probes; the probe consumes the remaining injected failures)
    for _ in range(2):
        node.txm._ro_probe_at = 0.0
        with pytest.raises(ReadOnlyError):
            node.update_objects([("k", "counter_pn", "b", ("increment", 9))])
    # fault clears -> the next write attempt's probe succeeds and the
    # mode exits automatically; the write goes through
    node.txm._ro_probe_at = 0.0
    node.update_objects([("k", "counter_pn", "b", ("increment", 5))])
    assert node.txm.read_only_reason is None
    assert node.metrics.degraded_read_only.value() == 0
    vals, _ = node.read_objects([("k", "counter_pn", "b")])
    assert vals == [6]  # the rejected increments never half-applied
    assert node.status()["overload"]["read_only"] is None


def test_read_only_survives_recovery_replay(tmp_path, wal_plane):
    """Nothing a failed append half-wrote may resurrect at restart."""
    cfg = mk_cfg()
    node = AntidoteNode(cfg, log_dir=str(tmp_path))
    node.update_objects([("k", "counter_pn", "b", ("increment", 1))])
    faults.install(faults.FaultPlan(seed=9).enospc("wal.append", times=1))
    from antidote_tpu.overload import ReadOnlyError

    with pytest.raises(ReadOnlyError):
        node.update_objects([("k", "counter_pn", "b", ("increment", 7))])
    faults.uninstall()
    node.store.log.close()
    re = AntidoteNode(cfg, log_dir=str(tmp_path), recover=True)
    vals, _ = re.read_objects([("k", "counter_pn", "b")])
    assert vals == [1]


# ---------------------------------------------------------------------------
# wire server: admission caps, bounded gate, deadlines, typed replies
# ---------------------------------------------------------------------------
def _mk_server(tmp_path=None, **kw):
    node = AntidoteNode(mk_cfg(),
                        log_dir=None if tmp_path is None else str(tmp_path))
    return node, ProtocolServer(node, port=0, **kw)


def test_saturated_server_sheds_busy_and_inflight_completes():
    node, srv = _mk_server(max_in_flight=1, max_in_flight_per_client=1)
    a, b = AntidoteClient(port=srv.port), AntidoteClient(port=srv.port)
    try:
        res = {}
        with node.txm.commit_lock:  # wedge the commit plane
            ta = threading.Thread(target=lambda: res.update(
                ok=a.update_objects(
                    [("k", "counter_pn", "b", ("increment", 3))])))
            ta.start()
            deadline = time.monotonic() + 10
            while srv.admission.in_flight() < 1:  # a is admitted + parked
                assert time.monotonic() < deadline
                time.sleep(0.005)
            # the server is at max_in_flight: b gets a TYPED busy reply
            # with a retry hint, not a parked-forever connection
            with pytest.raises(RemoteBusy) as e:
                b.read_objects([("k", "counter_pn", "b")])
            assert e.value.retry_after_ms >= 25
        ta.join(timeout=30)
        assert res["ok"] is not None  # the in-flight request completed
        # pressure gone: the same connection serves again
        vals, _ = b.read_objects([("k", "counter_pn", "b")])
        assert vals == [3]
        assert node.metrics.shed.value(plane="server") >= 1
    finally:
        a.close()
        b.close()
        srv.close()


def test_full_batch_gate_answers_busy():
    node, srv = _mk_server(queue_max=1)
    cs = [AntidoteClient(port=srv.port) for _ in range(3)]
    try:
        with node.txm.commit_lock:
            done = []
            ts = []
            for c in cs[:2]:
                t = threading.Thread(target=lambda c=c: done.append(
                    c.update_objects(
                        [("g", "counter_pn", "b", ("increment", 1))])))
                t.start()
                ts.append(t)
                time.sleep(0.2)  # 1st grabbed by the batcher, 2nd parked
            with pytest.raises(RemoteBusy, match="batch gate full"):
                cs[2].update_objects(
                    [("g", "counter_pn", "b", ("increment", 1))])
        for t in ts:
            t.join(timeout=30)
        assert len(done) == 2
        assert node.metrics.shed.value(plane="server_queue") >= 1
    finally:
        for c in cs:
            c.close()
        srv.close()


def test_deadline_aborts_parked_work_at_dequeue():
    node, srv = _mk_server()
    a, b = AntidoteClient(port=srv.port), AntidoteClient(port=srv.port)
    try:
        with node.txm.commit_lock:
            res = {}
            ta = threading.Thread(target=lambda: res.update(
                ok=a.update_objects(
                    [("d", "counter_pn", "b", ("increment", 1))])))
            ta.start()
            time.sleep(0.3)  # the batcher holds a's work at the lock
            tb_err = []

            def send_b():
                try:
                    b.update_objects(
                        [("d", "counter_pn", "b", ("increment", 1))],
                        deadline_ms=200)
                except Exception as e:
                    tb_err.append(e)

            tb = threading.Thread(target=send_b)
            tb.start()
            time.sleep(0.6)  # b's deadline passes while parked
        ta.join(timeout=30)
        tb.join(timeout=30)
        assert res["ok"] is not None
        assert len(tb_err) == 1 and isinstance(tb_err[0], RemoteDeadline)
        # the expired update was aborted at dequeue, NOT executed
        vals, _ = a.read_objects([("d", "counter_pn", "b")])
        assert vals == [1]
        assert node.metrics.shed.value(plane="deadline") >= 1
    finally:
        a.close()
        b.close()
        srv.close()


def test_commit_backlog_cap_sheds_typed_busy():
    node, srv = _mk_server()
    c = AntidoteClient(port=srv.port)
    try:
        node.txm.max_commit_backlog = 0
        with pytest.raises(RemoteBusy, match="commit backlog"):
            c.update_objects([("cb", "counter_pn", "b", ("increment", 1))])
        assert node.metrics.shed.value(plane="txn") >= 1
        node.txm.max_commit_backlog = 64
        c.update_objects([("cb", "counter_pn", "b", ("increment", 1))])
        # shed commits never leak open transactions (they would pin the
        # certification-GC floor forever)
        assert not node.txm._open_snaps
    finally:
        c.close()
        srv.close()


def test_interactive_commit_busy_is_retryable():
    """A commit-backlog shed must leave the interactive txn OPEN: the
    busy reply invites a retry, so retrying the SAME commit (same txid)
    has to work — the shed happens before the group touches the txn."""
    node, srv = _mk_server()
    c = AntidoteClient(port=srv.port)
    try:
        txn = c.start_transaction()
        txn.update_objects([("ic", "counter_pn", "b", ("increment", 4))])
        node.txm.max_commit_backlog = 0
        with pytest.raises(RemoteBusy):
            txn.commit()
        node.txm.max_commit_backlog = 64
        txn.commit()  # the honest retry: same txid, now admitted
        vals, _ = c.read_objects([("ic", "counter_pn", "b")])
        assert vals == [4]
        assert not node.txm._open_snaps
    finally:
        c.close()
        srv.close()


def test_read_only_over_the_wire(tmp_path):
    node, srv = _mk_server(tmp_path=tmp_path)
    c = AntidoteClient(port=srv.port)
    try:
        c.update_objects([("w", "counter_pn", "b", ("increment", 2))])
        faults.install(
            faults.FaultPlan(seed=3).enospc("wal.append", times=1))
        with pytest.raises(RemoteReadOnly):
            c.update_objects([("w", "counter_pn", "b", ("increment", 5))])
        # reads keep serving over the wire while the node is degraded
        vals, _ = c.read_objects([("w", "counter_pn", "b")])
        assert vals == [2]
        st = c.node_status()["overload"]
        assert st["read_only"] is not None
        assert st["max_in_flight"] == srv.admission.max_in_flight
        # volume heals (rule exhausted): auto-recovery on the next write
        node.txm._ro_probe_at = 0.0
        clock = c.update_objects([("w", "counter_pn", "b", ("increment", 5))])
        vals, _ = c.read_objects([("w", "counter_pn", "b")], clock=clock)
        assert vals == [7]
        assert c.node_status()["overload"]["read_only"] is None
    finally:
        c.close()
        srv.close()


def test_default_deadline_config_applies_to_plain_requests():
    node, srv = _mk_server(default_deadline_ms=250.0)
    a, b = AntidoteClient(port=srv.port), AntidoteClient(port=srv.port)
    try:
        with node.txm.commit_lock:
            res, errs = {}, []
            ta = threading.Thread(target=lambda: res.update(
                ok=a.update_objects(
                    [("x", "counter_pn", "b", ("increment", 1))])))
            ta.start()
            time.sleep(0.3)  # the batcher holds a's work at the lock

            def send_b():  # carries NO deadline_ms: the default applies
                try:
                    b.update_objects(
                        [("x", "counter_pn", "b", ("increment", 1))])
                except Exception as e:
                    errs.append(e)

            tb = threading.Thread(target=send_b)
            tb.start()
            time.sleep(0.6)  # past the configured default while parked
        ta.join(timeout=30)
        tb.join(timeout=30)
        assert res["ok"] is not None  # no deadline default for round 1
        assert len(errs) == 1 and isinstance(errs[0], RemoteDeadline)
    finally:
        a.close()
        b.close()
        srv.close()


# ---------------------------------------------------------------------------
# mid-group ENOSPC: the failed group must leave NO durable trace
# ---------------------------------------------------------------------------
def test_log_effects_mid_group_rolls_back_prefix(tmp_path, wal_plane):
    """A group whose LATER record hits ENOSPC must roll back the records,
    op-id chains and blob-dedup memory it already appended — a durable
    prefix of a NACKed group would resurrect on recovery replay, and an
    advanced op-id chain would publish a permanent gap to subscribers."""
    import numpy as np

    from antidote_tpu.log import LogManager, replay

    lm = LogManager(mk_cfg(), str(tmp_path / "wal"))
    vc = np.zeros(2, np.int64)

    def ent(shard, key):
        return (shard, key, "counter_pn", "b",
                np.array([1], np.int64), np.array([], np.int32), vc, 0, ())

    lm.log_effect(*ent(0, "seed"))
    lm.commit_barrier([0])
    before_ids = lm.op_ids.copy()
    before_off = lm.wals[0].tell()
    faults.install(
        faults.FaultPlan(seed=2).enospc("wal.append", key="shard_1.wal",
                                        times=1))
    with pytest.raises(OSError):
        lm.log_effects([ent(0, "x"), ent(1, "y")])
    faults.uninstall()
    assert np.array_equal(lm.op_ids, before_ids)
    assert lm.wals[0].tell() == before_off  # shard 0's record rolled back
    lm.commit_barrier([0, 1])
    p0 = str(tmp_path / "wal" / "shard_0.wal")
    p1 = str(tmp_path / "wal" / "shard_1.wal")
    assert [r["k"] for r in replay(p0)] == ["seed"]
    assert [r["k"] for r in replay(p1)] == []
    # the log still works after a rollback: the same group re-logs clean
    lm.log_effects([ent(0, "x"), ent(1, "y")])
    lm.commit_barrier([0, 1])
    assert [r["k"] for r in replay(p0)] == ["seed", "x"]
    assert [(r["k"], r["id"]) for r in replay(p1)] == [("y", 1)]
    lm.close()


def test_enospc_mid_group_nacks_only_its_subgroup(tmp_path):
    """Node-level mid-merged-batch ENOSPC (ISSUE 6 sub-group atomicity):
    the sub-group whose shard file refuses the append fails TYPED and
    rolls back alone — op-id chain, certification stamps, recovery
    replay — while its sibling sub-group commits and stays durable.  A
    pre-group transaction must not first-committer-abort against the
    NACKed member's phantom stamps, but must still abort against the
    committed sibling's real ones."""
    import numpy as np

    from antidote_tpu.overload import ReadOnlyError

    cfg = mk_cfg()
    node = AntidoteNode(cfg, log_dir=str(tmp_path))
    # seed a pool and find two keys on DIFFERENT shards, so a fault
    # scoped to the second key's shard file fails exactly one sub-group
    pool = [f"k{i}" for i in range(8)]
    node.update_objects(
        [(k, "counter_pn", "b", ("increment", 1)) for k in pool])
    by_shard = {}
    for k in pool:
        by_shard.setdefault(
            int(node.store.locate(k, "counter_pn", "b")[1]), k)
    assert len(by_shard) == 2, "pool never spanned both shards"
    k_first, k_second = by_shard[0], by_shard[1]

    def rmw(key, amount):
        # read-bearing: keeps certification (and its stamps) in play —
        # blind increments would take the commutativity bypass
        t = node.start_transaction()
        node.read_objects([(key, "counter_pn", "b")], t)
        node.update_objects([(key, "counter_pn", "b",
                              ("increment", amount))], t)
        return t

    # transactions whose snapshots predate the doomed merged batch
    pre_second = rmw(k_second, 10)
    pre_first = rmw(k_first, 10)
    ids_before = node.store.log.op_ids.copy()
    counter_before = node.txm.commit_counter
    t1 = rmw(k_first, 100)
    t2 = rmw(k_second, 100)
    shard_first = int(node.store.locate(k_first, "counter_pn", "b")[1])
    shard_second = int(node.store.locate(k_second, "counter_pn", "b")[1])
    faults.install(faults.FaultPlan(seed=5).enospc(
        "wal.append", key=f"shard_{shard_second}.wal", times=1))
    outs = node.txm.commit_transactions_group([t1, t2])
    faults.uninstall()
    # sibling committed, refused sub-group NACKed typed
    assert isinstance(outs[0], np.ndarray)
    assert isinstance(outs[1], ReadOnlyError)
    assert node.txm.read_only_reason is not None
    # t1's chain advanced; t2's rolled back
    ids_after = ids_before.copy()
    ids_after[shard_first, 0] += 1
    assert np.array_equal(node.store.log.op_ids, ids_after)
    # t2's counter stays a HOLE (holes are safe; nothing reuses them)
    assert node.txm.commit_counter == counter_before + 2
    # recovery probe exits read-only; the NACKed member's stamps are
    # gone (pre_second commits — a phantom stamp would abort it) while
    # the committed sibling's stamps stand (pre_first aborts)
    node.txm._ro_probe_at = 0.0
    node.commit_transaction(pre_second)
    with pytest.raises(AbortError):
        node.commit_transaction(pre_first)
    vals, _ = node.read_objects([(k_first, "counter_pn", "b"),
                                 (k_second, "counter_pn", "b")])
    assert vals == [101, 11]  # t1 + seeds + pre_second; t2 never landed
    node.store.log.close()
    # replay must agree: the committed sibling survives restart, the
    # NACKed sub-group does not resurrect
    re = AntidoteNode(cfg, log_dir=str(tmp_path), recover=True)
    vals, _ = re.read_objects([(k_first, "counter_pn", "b"),
                               (k_second, "counter_pn", "b")])
    assert vals == [101, 11]
