"""ISSUE 10 mesh serving plane: the serving-epoch store sharded over a
device Mesh with collective stable time.

Runs on the 8 virtual CPU devices the conftest forces
(``XLA_FLAGS=--xla_force_host_platform_device_count=8``).  The
load-bearing properties:

  * mesh-plane epoch reads are BYTE-identical to the single-chip
    serving-epoch plane at equal epoch ids (same workload, same wire
    encoding);
  * epoch publication is per-shard incremental: a hot shard's write
    burst advances only its own ``antidote_mesh_publish_total{shard}``
    label, by its dirty-row count — never table size;
  * the pmin stable-time collective equals the host-computed stable VC
    entry-wise, for any applied-clock matrix;
  * the degenerate 1-device mesh behaves like the full one;
  * the pin/graveyard donation protocol holds for sharded buffers under
    concurrent commits (no gather ever reads a donated buffer);
  * the Pallas fold inside the sharded step (shard-local extents)
    matches the generic scan fold.
"""

from __future__ import annotations

import dataclasses
import threading
import time

import jax
import msgpack
import numpy as np
import pytest

from antidote_tpu.api.node import AntidoteNode
from antidote_tpu.config import AntidoteConfig
from antidote_tpu.crdt import get_type
from antidote_tpu.obs import NodeMetrics
from antidote_tpu.parallel import (
    MeshServingPlane,
    make_mesh,
    shard_axis_sharding,
    sharded_step_fn,
)
from antidote_tpu.proto.codec import encode_value
from antidote_tpu.store import TypedTable
from antidote_tpu.store.kv import Effect, KVStore, shard_digest, split_tier

MESH_CFG = AntidoteConfig(n_shards=8, max_dcs=2, keys_per_table=64,
                          batch_buckets=(16, 64))


def _mk_node(mesh_devices=None):
    plane = None
    if mesh_devices:
        plane = MeshServingPlane(MESH_CFG, mesh_devices)
    node = AntidoteNode(
        MESH_CFG, sharding=plane.sharding if plane is not None else None)
    if plane is not None:
        plane.metrics = node.metrics
        plane.attach(node.store)
    return node, plane


#: deterministic mixed-type workload: both replicas apply the identical
#: commit sequence, so clocks, layouts and epoch ids line up exactly
def _apply_workload(node):
    for i in range(24):
        node.update_objects([
            (i, "counter_pn", "b", ("increment", i + 1)),
            (f"s{i % 5}", "set_aw", "b", ("add", f"e{i}")),
            (f"r{i % 3}", "register_lww", "b", ("assign", f"v{i}")),
        ])


_WORKLOAD_OBJS = (
    [(i, "counter_pn", "b") for i in range(24)]
    + [(f"s{j}", "set_aw", "b") for j in range(5)]
    + [(f"r{j}", "register_lww", "b") for j in range(3)]
)


def _epoch_read(store, objs):
    ep = store.pin_serving_epoch()
    assert ep is not None
    try:
        pending, fallback = store.epoch_read_launch(objs, ep)
        assert not fallback, fallback
        vals = store.epoch_read_finish(pending)
    finally:
        store.unpin_serving_epoch(ep)
    return ep.id, [int(x) for x in ep.vc], vals


def _wire_bytes(vals, vc):
    """The reply encoding the writeback stage would serialize — the
    byte-identity oracle."""
    return msgpack.packb(
        {"values": [encode_value(v) for v in vals], "commit_clock": vc},
        use_bin_type=True, default=repr)


# ---------------------------------------------------------------------------
# parity: mesh plane ≡ single-chip plane, byte for byte
# ---------------------------------------------------------------------------
def test_mesh_reads_byte_identical_to_single_chip():
    assert len(jax.devices()) == 8, "conftest must force 8 devices"
    chip, _ = _mk_node()
    mesh, _plane = _mk_node(mesh_devices=8)
    _apply_workload(chip)
    _apply_workload(mesh)
    chip.txm.publish_serving_epoch()
    mesh.txm.publish_serving_epoch()
    cid, cvc, cvals = _epoch_read(chip.store, _WORKLOAD_OBJS)
    mid, mvc, mvals = _epoch_read(mesh.store, _WORKLOAD_OBJS)
    assert cid == mid, "epoch ids must line up for the comparison"
    assert _wire_bytes(cvals, cvc) == _wire_bytes(mvals, mvc)
    # second round: incremental publishes on both sides stay identical
    _apply_workload(chip)
    _apply_workload(mesh)
    chip.txm.publish_serving_epoch()
    mesh.txm.publish_serving_epoch()
    cid, cvc, cvals = _epoch_read(chip.store, _WORKLOAD_OBJS)
    mid, mvc, mvals = _epoch_read(mesh.store, _WORKLOAD_OBJS)
    assert cid == mid
    assert _wire_bytes(cvals, cvc) == _wire_bytes(mvals, mvc)


def test_mesh_parity_on_2_and_4_device_meshes():
    """Shards-per-device > 1: the routed layouts split 8 shards over
    fewer devices and must serve the same bytes."""
    chip, _ = _mk_node()
    _apply_workload(chip)
    chip.txm.publish_serving_epoch()
    _, cvc, cvals = _epoch_read(chip.store, _WORKLOAD_OBJS)
    for n_dev in (2, 4):
        node, _plane = _mk_node(mesh_devices=n_dev)
        _apply_workload(node)
        node.txm.publish_serving_epoch()
        _, mvc, mvals = _epoch_read(node.store, _WORKLOAD_OBJS)
        assert _wire_bytes(cvals, cvc) == _wire_bytes(mvals, mvc)


def test_degenerate_1_device_mesh():
    node, plane = _mk_node(mesh_devices=1)
    _apply_workload(node)
    node.txm.publish_serving_epoch()
    _, _, vals = _epoch_read(node.store, _WORKLOAD_OBJS)
    direct, _ = node.read_objects(_WORKLOAD_OBJS)
    assert _wire_bytes(vals, [0]) == _wire_bytes(direct, [0])
    assert (node.store.stable_vc()
            == node.store.applied_vc.min(axis=0)).all()
    assert plane.status()["shards_per_device"] == MESH_CFG.n_shards


def test_mesh_rejects_indivisible_device_count():
    with pytest.raises(ValueError):
        MeshServingPlane(MESH_CFG, 3)  # 8 % 3 != 0


# ---------------------------------------------------------------------------
# per-shard incremental publish
# ---------------------------------------------------------------------------
def test_per_shard_publish_touches_only_dirty_shard():
    """A hot shard's write burst republishes ITS device slice only:
    the per-shard counter advances for exactly that shard, by the
    dirty-row count — not table size (the acceptance criterion)."""
    plane = MeshServingPlane(MESH_CFG, 8)
    store = KVStore(MESH_CFG, sharding=plane.sharding)
    store.metrics = NodeMetrics()
    plane.attach(store)
    ty = get_type("counter_pn")
    aw, bw = ty.eff_a_width(MESH_CFG), ty.eff_b_width(MESH_CFG)
    counter = [0]

    def write(keys):
        effs = [Effect(k, "counter_pn", "b", np.full(aw, 1, np.int64),
                       np.zeros(bw, np.int32)) for k in keys]
        vcs = []
        for _ in keys:
            counter[0] += 1
            vcs.append(np.asarray([counter[0], 0], np.int32))
        store.apply_effects(effs, vcs, [0] * len(keys))

    # two copy publishes fill both double-buffer slots, a third drains
    # the cross-window scatter set, so the probed publish's scatter is
    # exactly the hot burst
    write(range(32))
    store.publish_serving_epoch(store.dc_max_vc())
    write(range(32))
    store.publish_serving_epoch(store.dc_max_vc())
    write([3, 11, 19])
    store.publish_serving_epoch(store.dc_max_vc())
    write([3, 11, 27])  # shard 3 only (integer keys map key % n_shards)
    before = dict(store.metrics.mesh_publish.snapshot())
    assert store.publish_serving_epoch(store.dc_max_vc()) == "published"
    delta = {k: v - before.get(k, 0)
             for k, v in store.metrics.mesh_publish.snapshot().items()}
    hot = {k: v for k, v in delta.items() if v}
    # only shard 3's slice was republished: 4 dirty rows across the two
    # burst windows — vs 64 rows/shard table size
    assert hot == {("3",): 4.0}, hot
    # and the published epoch still serves every key exactly
    objs = [(i, "counter_pn", "b") for i in range(32)]
    _, _, vals = _epoch_read(store, objs)
    assert vals == store.read_values(objs, store.dc_max_vc())


# ---------------------------------------------------------------------------
# stable time: pmin collective ≡ host min
# ---------------------------------------------------------------------------
def test_pmin_stable_time_equals_host_min():
    plane = MeshServingPlane(MESH_CFG, 8)
    store = KVStore(MESH_CFG, sharding=plane.sharding)
    plane.attach(store)
    rng = np.random.default_rng(7)
    for _ in range(5):
        store.applied_vc[:] = rng.integers(
            0, 1000, size=store.applied_vc.shape).astype(np.int32)
        want = store.applied_vc.min(axis=0)
        got = store.stable_vc()
        assert (got == want).all(), (got, want)
    n0 = plane.stable_collectives
    # unchanged clocks hit the cache — no relaunch per txn start
    for _ in range(10):
        store.stable_vc()
    assert plane.stable_collectives == n0


# ---------------------------------------------------------------------------
# pin/graveyard donation under concurrent commits (sharded buffers)
# ---------------------------------------------------------------------------
def test_pin_graveyard_holds_for_sharded_buffers_under_commits():
    """Concurrent commit+publish storms donate sharded spare buffers
    while lock-free gathers hold pins: no gather may ever observe a
    donated ('deleted') buffer, and served counter values must be
    monotone per key."""
    node, _plane = _mk_node(mesh_devices=8)
    store = node.store
    node.update_objects([(k, "counter_pn", "b", ("increment", 1))
                         for k in range(16)])
    node.txm.publish_serving_epoch()
    stop = time.monotonic() + 3.0
    errors: list = []

    def writer():
        try:
            while time.monotonic() < stop:
                node.update_objects(
                    [(k, "counter_pn", "b", ("increment", 1))
                     for k in range(16)])
                node.txm.publish_serving_epoch()
        except BaseException as e:  # surfaced by the main thread
            errors.append(e)

    t = threading.Thread(target=writer)
    t.start()
    objs = [(k, "counter_pn", "b") for k in range(16)]
    last = [0] * 16
    reads = 0
    try:
        while time.monotonic() < stop:
            ep = store.pin_serving_epoch()
            if ep is None:
                continue
            try:
                pending, fallback = store.epoch_read_launch(objs, ep)
                vals = store.epoch_read_finish(pending)
            finally:
                store.unpin_serving_epoch(ep)
            fb = set(fallback)
            for i, v in enumerate(vals):
                if i in fb:
                    continue
                assert v >= last[i], (i, v, last[i])
                last[i] = v
            reads += 1
    finally:
        t.join()
    assert not errors, errors
    assert reads > 5, "the reader never overlapped the write storm"


# ---------------------------------------------------------------------------
# Pallas fold inside the sharded step (shard-local extents)
# ---------------------------------------------------------------------------
def test_sharded_step_pallas_fold_matches_generic():
    cfg = AntidoteConfig(n_shards=8, max_dcs=2, ops_per_key=4,
                         snap_versions=2, keys_per_table=16,
                         batch_buckets=(8,))
    mesh = make_mesh(8)
    sharding = shard_axis_sharding(mesh)
    ty = get_type("counter_pn")

    def run(use_pallas):
        c = dataclasses.replace(cfg, use_pallas=use_pallas)
        table = TypedTable(ty, c, sharding=sharding)
        step = sharded_step_fn(ty, c, mesh)
        p, ma, mr, d = c.n_shards, 8, 8, c.max_dcs
        app_rows = np.zeros((p, ma), np.int64)
        app_rows[:, 2:] = table.n_rows  # padding
        app_slots = np.zeros((p, ma), np.int64)
        app_slots[:, 1] = 1
        app_a = np.zeros((p, ma, ty.eff_a_width(c)), np.int64)
        app_a[:, 0, 0] = np.arange(p) + 1
        app_a[:, 1, 0] = 10
        app_b = np.zeros((p, ma, ty.eff_b_width(c)), np.int32)
        app_vc = np.zeros((p, ma, d), np.int32)
        app_vc[:, 0, 0] = 1
        app_vc[:, 1, 0] = 2
        app_origin = np.zeros((p, ma), np.int32)
        read_rows = np.zeros((p, mr), np.int64)
        read_n_ops = np.full((p, mr), 2, np.int32)
        read_vcs = np.ones((p, mr, d), np.int32)  # sees op 1, not op 2
        applied_vc = np.zeros((p, d), np.int32)
        return step(
            table.snap, table.snap_vc, table.snap_seq,
            table.ops_a, table.ops_b, table.ops_vc, table.ops_origin,
            app_rows, app_slots, app_a, app_b, app_vc, app_origin,
            read_rows, read_n_ops, read_vcs, applied_vc,
        )

    o_gen, o_pal = run(False), run(True)
    assert (np.asarray(o_gen[4]["cnt"]) == np.asarray(o_pal[4]["cnt"])).all()
    assert (np.asarray(o_gen[5]) == np.asarray(o_pal[5])).all()  # applied
    assert (np.asarray(o_gen[8]) == np.asarray(o_pal[8])).all()  # stable
    # the clock-filtered fold saw exactly the first op per shard
    assert (np.asarray(o_pal[4]["cnt"])[:, 0] == np.arange(8) + 1).all()


# ---------------------------------------------------------------------------
# per-shard directory index (satellite): digests unchanged, index exact
# ---------------------------------------------------------------------------
def test_shard_digest_unchanged_by_index():
    node, _ = _mk_node()
    _apply_workload(node)
    store = node.store
    with node.txm.commit_lock:
        indexed = [shard_digest(store, s)
                   for s in range(MESH_CFG.n_shards)]
    # the pre-index oracle: filter the whole directory per shard
    import hashlib

    def legacy(shard):
        objs = []
        for (key, bucket), (tname, s, _row) in store.directory.items():
            if s == shard:
                objs.append((key, split_tier(tname)[0], bucket))
        objs.sort(key=lambda o: msgpack.packb(
            [o[0], o[2], o[1]], use_bin_type=True, default=repr))
        h = hashlib.sha256()
        h.update(np.ascontiguousarray(store.applied_vc[shard],
                                      dtype=np.int64).tobytes())
        if objs:
            vals = store.read_values(objs, store.applied_vc[shard])
            from antidote_tpu.store.kv import _canon

            for (key, tname, bucket), v in zip(objs, vals):
                h.update(msgpack.packb(
                    [_canon(key), bucket, tname, _canon(v)],
                    use_bin_type=True, default=repr))
        return h.hexdigest()

    with node.txm.commit_lock:
        assert indexed == [legacy(s) for s in range(MESH_CFG.n_shards)]


def test_shard_directory_index_tracks_mutations():
    from antidote_tpu.store import handoff

    node, _ = _mk_node()
    _apply_workload(node)
    store = node.store

    def recomputed():
        idx: dict = {}
        for dk, ent in dict.items(store.directory):
            idx.setdefault(ent[1], set()).add(dk)
        return idx

    # the lazy index matches a from-scratch grouping...
    got = {s: set(store.directory.shard_keys(s))
           for s in range(MESH_CFG.n_shards)}
    assert {s: v for s, v in got.items() if v} == recomputed()
    # ...stays exact across incremental mutation (drop_shard pops every
    # key through the index path)...
    victims = [s for s in range(MESH_CFG.n_shards)
               if store.directory.shard_keys(s)]
    victim = victims[0]
    handoff.drop_shard(store, victim)
    assert store.directory.shard_keys(victim) == set()
    got = {s: set(store.directory.shard_keys(s))
           for s in range(MESH_CFG.n_shards)}
    assert {s: v for s, v in got.items() if v} == recomputed()
    # ...and across bulk update (index rebuilds lazily)
    store.directory.update({("zz", "b"): ("counter_pn", victim, 0)})
    assert ("zz", "b") in store.directory.shard_keys(victim)
