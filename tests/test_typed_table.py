"""TypedTable + materializer fold semantics.

These are the tensor analogues of the reference's materializer EUnit truth
tables (/root/reference/src/clocksi_materializer.erl:277-473): snapshot
filtering by VC dominance, base-snapshot exclusion, GC folds, and
incomplete-read detection.
"""

import numpy as np

from antidote_tpu.crdt import get_type
from antidote_tpu.crdt.blob import BlobStore
from antidote_tpu.store import TypedTable


class Driver:
    """Tiny single-key commit driver: assigns commit VCs on one DC lane."""

    def __init__(self, ty_name, cfg, dc=0):
        self.cfg = cfg
        self.ty = get_type(ty_name)
        self.table = TypedTable(self.ty, cfg, n_rows=8, n_shards=1)
        self.blobs = BlobStore()
        self.clock = np.zeros(cfg.max_dcs, np.int32)

    def commit(self, row, op, dc=0, vc_override=None):
        state = None
        if self.ty.require_state_downstream(op):
            state = self.read(row, self.clock)[0]
        effs = self.ty.downstream(op, state, self.blobs, self.cfg)
        for a, b, _ in effs:
            if vc_override is not None:
                cvc = np.asarray(vc_override, np.int32)
                self.clock = np.maximum(self.clock, cvc)
            else:
                self.clock = self.clock.copy()
                self.clock[dc] += 1
                cvc = self.clock.copy()
            self.table.append(
                np.asarray([0]), np.asarray([row]),
                a[None, :], b[None, :], cvc[None, :],
                np.asarray([dc], np.int32),
            )
        return self.clock.copy()

    def read(self, row, at_vc):
        state, _, complete = self.table.read(
            np.asarray([0]), np.asarray([row]), np.asarray(at_vc, np.int32)[None, :]
        )
        one = {f: x[0] for f, x in state.items()}
        return one, bool(complete[0])

    def value(self, row, at_vc):
        state, complete = self.read(row, at_vc)
        assert complete
        return self.ty.value(state, self.blobs, self.cfg)


def test_counter_basic(cfg):
    d = Driver("counter_pn", cfg)
    d.commit(0, ("increment", 5))
    d.commit(0, ("increment", 3))
    vc2 = d.clock.copy()
    d.commit(0, ("decrement", 2))
    assert d.value(0, d.clock) == 6
    # snapshot isolation: read at the older VC misses the decrement
    assert d.value(0, vc2) == 8


def test_counter_snapshot_excludes_concurrent_dc(cfg):
    d = Driver("counter_pn", cfg)
    d.commit(0, ("increment", 10), dc=0, vc_override=[1, 0, 0])
    # a truly concurrent commit from DC1 (does not depend on DC0's)
    d.commit(0, ("increment", 100), dc=1, vc_override=[0, 1, 0])
    # read seeing only DC0's commit
    assert d.value(0, [1, 0, 0]) == 10
    # read seeing both
    assert d.value(0, [1, 1, 0]) == 110
    # read seeing only DC1
    assert d.value(0, [0, 1, 0]) == 100


def test_gc_fold_and_versions(cfg):
    d = Driver("counter_pn", cfg)
    # overflow the 8-slot ring twice over
    for i in range(20):
        d.commit(0, ("increment", 1))
    assert d.value(0, d.clock) == 20
    # ring was folded at least once
    assert d.table.n_ops[0, 0] < 20
    # older reads within retained coverage still work
    state, complete = d.read(0, d.clock)
    assert complete


def test_incomplete_read_detection(cfg):
    d = Driver("counter_pn", cfg)
    for i in range(20):
        d.commit(0, ("increment", 1))
    # a read far below the oldest retained snapshot version is incomplete
    _, complete = d.read(0, [1, 0, 0])
    if complete:
        # only acceptable if a retained version is exactly dominated
        seqs = np.asarray(d.table.snap_seq[0, 0])
        vcs = np.asarray(d.table.snap_vc[0, 0])
        ok = any(
            s > 0 and (v <= np.asarray([1, 0, 0])).all()
            for s, v in zip(seqs, vcs)
        )
        assert ok
    else:
        assert not complete


def test_two_keys_independent(cfg):
    d = Driver("counter_pn", cfg)
    d.commit(0, ("increment", 1))
    d.commit(1, ("increment", 7))
    assert d.value(0, d.clock) == 1
    assert d.value(1, d.clock) == 7


def test_register_lww(cfg):
    d = Driver("register_lww", cfg)
    d.commit(0, ("assign", "a"))
    d.commit(0, ("assign", "b"))
    assert d.value(0, d.clock) == "b"


def test_register_mv_concurrent_assigns_coexist(cfg):
    d = Driver("register_mv", cfg)
    d.commit(0, ("assign", "x"))
    # two concurrent assigns: neither observes the other.
    # simulate by generating both downstreams from the same observed state.
    state, _ = d.read(0, d.clock)
    e1 = d.ty.downstream(("assign", "l"), state, d.blobs, d.cfg)[0]
    e2 = d.ty.downstream(("assign", "r"), state, d.blobs, d.cfg)[0]
    vc1 = np.asarray([2, 0, 0], np.int32)
    vc2 = np.asarray([1, 1, 0], np.int32)
    d.table.append(np.asarray([0]), np.asarray([0]), e1[0][None], e1[1][None], vc1[None],
                   np.asarray([0], np.int32))
    d.table.append(np.asarray([0]), np.asarray([0]), e2[0][None], e2[1][None], vc2[None],
                   np.asarray([1], np.int32))
    assert d.value(0, [2, 1, 0]) == ["l", "r"]
    # sequential assign observing both collapses to one value
    d.clock = np.asarray([2, 1, 0], np.int32)
    d.commit(0, ("assign", "z"))
    assert d.value(0, d.clock) == ["z"]


def test_set_aw_add_remove(cfg):
    d = Driver("set_aw", cfg)
    d.commit(0, ("add", "x"))
    d.commit(0, ("add", "y"))
    assert d.value(0, d.clock) == ["x", "y"]
    d.commit(0, ("remove", "x"))
    assert d.value(0, d.clock) == ["y"]
    d.commit(0, ("add", "x"))
    assert d.value(0, d.clock) == ["x", "y"]


def test_set_aw_concurrent_add_wins(cfg):
    d = Driver("set_aw", cfg)
    d.commit(0, ("add", "x"))
    # concurrent: DC1 removes x (observing the add), DC2 re-adds x
    state, _ = d.read(0, d.clock)
    rm = d.ty.downstream(("remove", "x"), state, d.blobs, d.cfg)[0]
    ad = d.ty.downstream(("add", "x"), None, d.blobs, d.cfg)[0]
    vc_rm = np.asarray([1, 1, 0], np.int32)
    vc_ad = np.asarray([1, 0, 1], np.int32)
    d.table.append(np.asarray([0]), np.asarray([0]), rm[0][None], rm[1][None], vc_rm[None],
                   np.asarray([1], np.int32))
    d.table.append(np.asarray([0]), np.asarray([0]), ad[0][None], ad[1][None], vc_ad[None],
                   np.asarray([2], np.int32))
    # add wins: x present when both are visible
    assert d.value(0, [1, 1, 1]) == ["x"]
    # remove-only view: x absent
    assert d.value(0, [1, 1, 0]) == []


def test_set_aw_add_all(cfg):
    d = Driver("set_aw", cfg)
    d.commit(0, ("add_all", ["a", "b", "c"]))
    assert d.value(0, d.clock) == ["a", "b", "c"]
    d.commit(0, ("remove_all", ["a", "c"]))
    assert d.value(0, d.clock) == ["b"]


def test_set_rw_concurrent_remove_wins(cfg):
    d = Driver("set_rw", cfg)
    d.commit(0, ("add", "x"))
    state, _ = d.read(0, d.clock)
    ad = d.ty.downstream(("add", "x"), state, d.blobs, d.cfg)[0]
    rm = d.ty.downstream(("remove", "x"), state, d.blobs, d.cfg)[0]
    vc_ad = np.asarray([1, 1, 0], np.int32)
    vc_rm = np.asarray([1, 0, 1], np.int32)
    d.table.append(np.asarray([0]), np.asarray([0]), ad[0][None], ad[1][None], vc_ad[None],
                   np.asarray([1], np.int32))
    d.table.append(np.asarray([0]), np.asarray([0]), rm[0][None], rm[1][None], vc_rm[None],
                   np.asarray([2], np.int32))
    assert d.value(0, [1, 1, 1]) == []


def test_set_rw_sequential_add_after_remove(cfg):
    d = Driver("set_rw", cfg)
    d.commit(0, ("add", "x"))
    d.commit(0, ("remove", "x"))
    assert d.value(0, d.clock) == []
    d.commit(0, ("add", "x"))
    assert d.value(0, d.clock) == ["x"]


def test_set_go(cfg):
    d = Driver("set_go", cfg)
    d.commit(0, ("add", "p"))
    d.commit(0, ("add", "q"))
    d.commit(0, ("add", "p"))
    assert d.value(0, d.clock) == ["p", "q"]


def test_flag_ew(cfg):
    d = Driver("flag_ew", cfg)
    assert d.value(0, d.clock) is False
    d.commit(0, ("enable", None))
    assert d.value(0, d.clock) is True
    d.commit(0, ("disable", None))
    assert d.value(0, d.clock) is False
    # concurrent enable vs disable: enable wins
    state, _ = d.read(0, d.clock)
    en = d.ty.downstream(("enable", None), state, d.blobs, d.cfg)[0]
    di = d.ty.downstream(("disable", None), state, d.blobs, d.cfg)[0]
    vc_en = np.asarray([d.clock[0], 1, 0], np.int32)
    vc_di = np.asarray([d.clock[0], 0, 1], np.int32)
    d.table.append(np.asarray([0]), np.asarray([0]), en[0][None], en[1][None], vc_en[None],
                   np.asarray([1], np.int32))
    d.table.append(np.asarray([0]), np.asarray([0]), di[0][None], di[1][None], vc_di[None],
                   np.asarray([2], np.int32))
    v = d.value(0, np.maximum(vc_en, vc_di))
    assert v is True


def test_flag_dw(cfg):
    d = Driver("flag_dw", cfg)
    d.commit(0, ("enable", None))
    assert d.value(0, d.clock) is True
    # concurrent enable vs disable: disable wins
    state, _ = d.read(0, d.clock)
    en = d.ty.downstream(("enable", None), state, d.blobs, d.cfg)[0]
    di = d.ty.downstream(("disable", None), state, d.blobs, d.cfg)[0]
    vc_en = np.asarray([d.clock[0], 1, 0], np.int32)
    vc_di = np.asarray([d.clock[0], 0, 1], np.int32)
    d.table.append(np.asarray([0]), np.asarray([0]), en[0][None], en[1][None], vc_en[None],
                   np.asarray([1], np.int32))
    d.table.append(np.asarray([0]), np.asarray([0]), di[0][None], di[1][None], vc_di[None],
                   np.asarray([2], np.int32))
    assert d.value(0, np.maximum(vc_en, vc_di)) is False


def test_counter_fat_reset(cfg):
    d = Driver("counter_fat", cfg)
    d.commit(0, ("increment", 10))
    d.commit(0, ("increment", 5))
    assert d.value(0, d.clock) == 15
    d.commit(0, ("reset", None))
    assert d.value(0, d.clock) == 0
    d.commit(0, ("increment", 3))
    assert d.value(0, d.clock) == 3


def test_counter_fat_concurrent_increment_survives_reset(cfg):
    d = Driver("counter_fat", cfg)
    d.commit(0, ("increment", 10))
    state, _ = d.read(0, d.clock)
    # reset observes 10; a concurrent increment of 7 at DC1 is unobserved
    rs = d.ty.downstream(("reset", None), state, d.blobs, d.cfg)[0]
    inc = d.ty.downstream(("increment", 7), None, d.blobs, d.cfg)[0]
    vc_rs = np.asarray([2, 0, 0], np.int32)
    vc_inc = np.asarray([1, 1, 0], np.int32)
    d.table.append(np.asarray([0]), np.asarray([0]), rs[0][None], rs[1][None], vc_rs[None],
                   np.asarray([0], np.int32))
    d.table.append(np.asarray([0]), np.asarray([0]), inc[0][None], inc[1][None], vc_inc[None],
                   np.asarray([1], np.int32))
    assert d.value(0, [2, 1, 0]) == 7


def test_counter_b(cfg):
    d = Driver("counter_b", cfg)
    d.commit(0, ("increment", (10, 0)))
    assert d.value(0, d.clock) == 10
    d.commit(0, ("decrement", (4, 0)))
    assert d.value(0, d.clock) == 6
    d.commit(0, ("transfer", (3, 1, 0)))
    assert d.value(0, d.clock) == 6
    state, _ = d.read(0, d.clock)
    assert d.ty.local_rights(state, 0) == 3
    assert d.ty.local_rights(state, 1) == 3


def test_batched_read_many_keys(cfg):
    d = Driver("counter_pn", cfg)
    for row in range(6):
        d.commit(row, ("increment", row + 1))
    rows = np.arange(6)
    vcs = np.broadcast_to(d.clock, (6, cfg.max_dcs))
    state, applied, complete = d.table.read(np.zeros(6, np.int64), rows, vcs)
    assert complete.all()
    assert list(state["cnt"]) == [1, 2, 3, 4, 5, 6]


def test_read_between_versions_flagged_incomplete(cfg):
    # regression: ops folded into a newer snapshot version must not be
    # silently missing from a read served off an older version
    d = Driver("counter_pn", cfg)
    for i in range(20):
        d.commit(0, ("increment", 1))
    # two retained versions exist at [8,..] and [16,..]; ring holds 17-20
    seqs = np.asarray(d.table.snap_seq[0, 0])
    assert (seqs > 0).sum() >= 2
    vcs = np.asarray(d.table.snap_vc[0, 0])
    older = vcs[np.argsort(seqs)][-2]  # older retained version's VC
    probe = older.copy()
    probe[0] += 2  # between the two versions
    state, complete = d.read(0, probe)
    assert not complete  # must demand log-replay, not serve stale 'older'


def test_set_slot_overflow_warns(cfg):
    d = Driver("set_aw", cfg)
    for i in range(cfg.set_slots + 3):
        d.commit(0, ("add", f"e{i}"))
    import warnings as _w

    with _w.catch_warnings(record=True) as rec:
        _w.simplefilter("always")
        v = d.value(0, d.clock)
    assert len(v) == cfg.set_slots
    assert any("op(s) dropped" in str(r.message) for r in rec)


# ---------------------------------------------------------------------------
# serving epochs (read-while-write double buffer, r4 VERDICT item 2)
# ---------------------------------------------------------------------------
def _flat_value(table, ty, row, vc, blobs, cfg):
    resolved, fresh, complete = table.read_resolved_flat(
        np.asarray([0]), np.asarray([row]), np.asarray(vc, np.int32)[None, :]
    )
    return ({f: np.asarray(x)[0] for f, x in resolved.items()},
            bool(np.asarray(fresh)[0]), bool(np.asarray(complete)[0]))


def test_epoch_pinned_reads_survive_writes(cfg):
    d = Driver("counter_pn", cfg)
    t = d.table
    d.commit(0, ("increment", 5))
    d.commit(1, ("increment", 7))
    pin = d.clock.copy()
    t.publish_epoch()
    assert len(t.epochs) == 1
    # writes race ahead of the pin
    for _ in range(20):
        d.commit(0, ("increment", 1))
    # pinned read = epoch cap: pure frozen gather, all fresh
    res, fresh0, complete0 = _flat_value(t, d.ty, 0, pin, d.blobs, cfg)
    assert fresh0 and complete0
    assert int(res["value"]) == 5
    # a read at the live frontier still sees everything
    res, _, _ = _flat_value(t, d.ty, 0, d.clock, d.blobs, cfg)
    assert int(res["value"]) == 25
    # a read BELOW the pin takes the two-phase fold and is still exact
    below = pin.copy()
    below[0] -= 1  # excludes row 1's commit
    res, fresh1, complete1 = _flat_value(t, d.ty, 1, below, d.blobs, cfg)
    assert complete1
    assert int(res["value"]) == 0


def test_epoch_mixed_batch_two_phase(cfg):
    """A batch mixing frozen-fresh and epoch-stale rows merges exactly."""
    d = Driver("set_aw", cfg)
    t = d.table
    d.commit(0, ("add", 11))
    d.commit(1, ("add", 22))
    pin = d.clock.copy()
    rows = np.asarray([0, 1])
    vcs = np.broadcast_to(pin, (2, cfg.max_dcs)).astype(np.int32)

    def read_at(v):
        resolved, fresh, complete = t.read_resolved_flat(
            np.zeros(2, np.int64), rows, v
        )
        return ({f: np.asarray(x).copy() for f, x in resolved.items()},
                np.asarray(fresh).copy(), np.asarray(complete).copy())

    expect_pin, _, c0 = read_at(vcs)
    assert c0.all()
    t.publish_epoch()
    d.commit(0, ("add", 33))  # row 0 advances past the pin
    after_w = d.clock.copy()
    t.publish_epoch()  # second epoch at the later cap
    assert len(t.epochs) == 2
    vcs2 = np.broadcast_to(after_w, (2, cfg.max_dcs)).astype(np.int32)
    expect_w, _, _ = read_at(vcs2)
    d.commit(1, ("add", 44))
    # read at the OLD pin: served from the old epoch, exact pre-write values
    got, fresh, complete = read_at(vcs)
    assert complete.all() and fresh.all()  # old epoch cap == pin: pure gather
    for f in expect_pin:
        assert (got[f] == expect_pin[f]).all(), f
    # read at the second epoch's cap picks it (row 0 includes the 33 add)
    got, fresh, complete = read_at(vcs2)
    assert complete.all() and fresh.all()
    for f in expect_w:
        assert (got[f] == expect_w[f]).all(), f
    # reads below both pins still fold exactly (two-phase path)
    below = vcs.copy(); below[:, 0] -= 1
    _, _, complete = read_at(below)
    assert complete.all()


def test_epoch_invalidated_on_growth(cfg):
    d = Driver("counter_pn", cfg)
    t = d.table
    d.commit(0, ("increment", 3))
    t.publish_epoch()
    t._grow()
    assert t.epochs == []


def test_epoch_lru_retention(cfg):
    d = Driver("counter_pn", cfg)
    t = d.table
    d.commit(0, ("increment", 1))
    pin0 = d.clock.copy()
    t.publish_epoch()
    d.commit(0, ("increment", 1))
    t.publish_epoch()
    # keep epoch 0 hot: a pinned reader at its cap
    for _ in range(3):
        _flat_value(t, d.ty, 0, pin0, d.blobs, cfg)
    d.commit(0, ("increment", 1))
    t.publish_epoch()  # evicts the UNUSED middle epoch, not the hot pin
    caps = sorted(int(e["cap"][0]) for e in t.epochs)
    assert int(pin0[0]) in caps
    assert len(t.epochs) == 2
