"""The slot-overflow escape hatch (r2 VERDICT item 3).

The reference's slotted types grow without bound
(antidote_crdt_set_aw/map_rr/rga have no capacity limit); fixed device
layouts do.  Keys that outgrow their slot budget must PROMOTE to a
wider-slot tier table (KVStore._promote_key) before any op is dropped —
never truncate.  Done-criterion from the VERDICT: write 10x
``cfg.set_slots`` elements to one key and read them all back.
"""

import numpy as np

from antidote_tpu.api.node import AntidoteNode
from antidote_tpu.config import AntidoteConfig
from antidote_tpu.store.kv import KVStore, scaled_cfg, split_tier, tiered_name


def _mk_cfg(**kw):
    base = dict(
        n_shards=2, max_dcs=3, ops_per_key=8, snap_versions=2,
        set_slots=4, mv_slots=2, rga_slots=8, keys_per_table=16,
        batch_buckets=(16, 64),
    )
    base.update(kw)
    return AntidoteConfig(**base)


def test_set_aw_10x_slots_roundtrip():
    """The VERDICT done-criterion: 10x set_slots elements on ONE key, all
    readable, zero drops."""
    node = AntidoteNode(_mk_cfg())
    n = 10 * node.cfg.set_slots
    elems = [f"e{i:03d}" for i in range(n)]
    for lo in range(0, n, 8):
        node.update_objects([
            ("k", "set_aw", "b", ("add_all", elems[lo:lo + 8]))
        ])
    vals, _ = node.read_objects([("k", "set_aw", "b")])
    assert sorted(vals[0]) == sorted(elems)
    store = node.store
    ent = store.directory[("k", "b")]
    base, tier = split_tier(ent[0])
    assert base == "set_aw" and tier >= 1
    assert store.promotions >= 1
    # no drops anywhere: every table's total ovf is zero
    for t in store.tables.values():
        if "ovf" in t.head:
            assert int(np.asarray(t.head["ovf"]).sum()) == 0


def test_set_aw_remove_after_promotion_and_history():
    node = AntidoteNode(_mk_cfg())
    n = 3 * node.cfg.set_slots
    elems = [f"x{i}" for i in range(n)]
    node.update_objects([("k", "set_aw", "b", ("add_all", elems))])
    mid_vc = node.read_objects([("k", "set_aw", "b")])[1]
    node.update_objects([("k", "set_aw", "b", ("remove", "x0")),
                         ("k", "set_aw", "b", ("add", "extra"))])
    vals, _ = node.read_objects([("k", "set_aw", "b")])
    assert sorted(vals[0]) == sorted(elems[1:] + ["extra"])
    # snapshot isolation across the promotion: a store-level read at the
    # pre-remove clock still sees x0 (the ring + versions migrated with
    # the key; txn snapshots are always fresh, so read the store directly)
    old = node.store.read_values([("k", "set_aw", "b")],
                                 np.asarray(mid_vc, np.int32))
    assert "x0" in old[0] and "extra" not in old[0]


def test_mv_register_promotes_for_wide_observed_lanes():
    """Concurrent assigns beyond mv_slots: the escape hatch widens the id
    lanes instead of dropping a concurrent value."""
    cfg = _mk_cfg()
    store = KVStore(cfg)
    from antidote_tpu.crdt import get_type
    from antidote_tpu.store.kv import Effect

    ty = get_type("register_mv")
    # 5 concurrent assigns (> mv_slots=2): distinct origins/counters, none
    # observing the others — all five must coexist
    for i in range(3):
        a = np.zeros((1 + cfg.mv_slots,), np.int64)
        a[0] = store.blobs.intern(f"v{i}")
        vc = np.zeros(cfg.max_dcs, np.int32)
        vc[i] = 1
        store.apply_effects(
            [Effect("r", "register_mv", "b", a,
                    np.zeros(1, np.int32), [])],
            [vc], [i],
        )
    # two more from lane 0 at later counters, still not observing others
    for j in (2, 3):
        a = np.zeros((1 + cfg.mv_slots,), np.int64)
        a[0] = store.blobs.intern(f"w{j}")
        vc = np.zeros(cfg.max_dcs, np.int32)
        vc[0] = j
        store.apply_effects(
            [Effect("r", "register_mv", "b", a,
                    np.zeros(1, np.int32), [])],
            [vc], [0],
        )
    vals = store.read_values(
        [("r", "register_mv", "b")], np.full(cfg.max_dcs, 10, np.int32)
    )
    assert sorted(vals[0]) == ["v0", "v1", "v2", "w2", "w3"]
    assert split_tier(store.directory[("r", "b")][0])[1] >= 1


def test_rga_grows_past_slots():
    node = AntidoteNode(_mk_cfg())
    n = 3 * node.cfg.rga_slots
    for i in range(n):
        node.update_objects([("q", "rga", "b", ("insert", (i, f"c{i}")))])
    vals, _ = node.read_objects([("q", "rga", "b")])
    assert vals[0] == [f"c{i}" for i in range(n)]
    assert split_tier(node.store.directory[("q", "b")][0])[1] >= 1


def test_map_field_set_overflows_via_membership():
    """map_rr's membership set and a set field both ride the hatch."""
    node = AntidoteNode(_mk_cfg())
    nf = 3 * node.cfg.set_slots
    for i in range(nf):
        node.update_objects([
            ("m", "map_rr", "b", ("update", [((f"f{i:02d}", "counter_pn"),
                                              ("increment", i))]))
        ])
    vals, _ = node.read_objects([("m", "map_rr", "b")])
    assert len(vals[0]) == nf
    assert vals[0][("f05", "counter_pn")] == 5


def test_promotion_survives_wal_recovery(tmp_path):
    from antidote_tpu.log import LogManager

    cfg = _mk_cfg()
    node = AntidoteNode(cfg, log_dir=str(tmp_path / "wal"))
    n = 6 * cfg.set_slots
    elems = [f"p{i}" for i in range(n)]
    node.update_objects([("k", "set_aw", "b", ("add_all", elems))])
    assert node.store.promotions >= 1
    node.store.log.close()

    log2 = LogManager(cfg, str(tmp_path / "wal"))
    store2 = KVStore(cfg, log=log2)
    store2.recover()
    vals = store2.read_values(
        [("k", "set_aw", "b")], store2.dc_max_vc()
    )
    assert sorted(vals[0]) == sorted(elems)
    assert split_tier(store2.directory[("k", "b")][0])[1] >= 1
    log2.close()


def test_scaled_cfg_and_names():
    cfg = _mk_cfg()
    assert split_tier("set_aw") == ("set_aw", 0)
    assert split_tier("set_aw#3") == ("set_aw", 3)
    assert tiered_name("set_aw", 0) == "set_aw"
    assert tiered_name("set_aw", 2) == "set_aw#2"
    c2 = scaled_cfg(cfg, 2)
    assert c2.set_slots == cfg.set_slots * 16
    assert c2.mv_slots == cfg.mv_slots * 16
    assert c2.rga_slots == cfg.rga_slots * 16
    assert c2.n_shards == cfg.n_shards


def test_handoff_carries_promoted_keys(tmp_path):
    from antidote_tpu.store import handoff

    cfg = _mk_cfg()
    node = AntidoteNode(cfg)
    n = 4 * cfg.set_slots
    elems = [f"h{i}" for i in range(n)]
    node.update_objects([("hk", "set_aw", "b", ("add_all", elems))])
    src = node.store
    tname_t, shard, _ = src.directory[("hk", "b")]
    assert split_tier(tname_t)[1] >= 1
    pkg = handoff.unpack(handoff.pack(handoff.export_shard(src, shard)))
    dst = KVStore(cfg)
    handoff.import_shard(dst, pkg, shard)
    vals = dst.read_values([("hk", "set_aw", "b")], src.dc_max_vc())
    assert sorted(vals[0]) == sorted(elems)
    # and the moved key keeps absorbing adds without drops
    from antidote_tpu.crdt import get_type
    t = dst.table(dst.directory[("hk", "b")][0])
    assert int(np.asarray(t.head["ovf"]).sum()) == 0


def test_add_remove_churn_does_not_ratchet_tiers():
    """r3 review: re-adding/removing the same element forever must not
    migrate the key through ever-wider tiers — the stale bound re-tightens
    to the exact used count in place."""
    node = AntidoteNode(_mk_cfg())
    for _ in range(10 * node.cfg.set_slots):
        node.update_objects([("c", "set_aw", "b", ("add", "x"))])
        node.update_objects([("c", "set_aw", "b", ("remove", "x"))])
    assert split_tier(node.store.directory[("c", "b")][0])[1] == 0
    vals, _ = node.read_objects([("c", "set_aw", "b")])
    assert vals[0] == []
