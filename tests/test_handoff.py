"""Shard handoff & resharding — the riak_core handoff analogue
(materializer fold /root/reference/src/materializer_vnode.erl:221-246,
logging fold /root/reference/src/logging_vnode.erl:781-812)."""

import numpy as np
import pytest

from antidote_tpu.api import AntidoteNode
from antidote_tpu.config import AntidoteConfig
from antidote_tpu.store import handoff
from antidote_tpu.store.kv import key_to_shard


def mk_cfg(n_shards=4):
    return AntidoteConfig(
        n_shards=n_shards, max_dcs=2, ops_per_key=8, snap_versions=2,
        set_slots=8, keys_per_table=16, batch_buckets=(16,),
    )


def populate(node, n=24):
    """Mixed-type workload; returns the bound objects and expected values."""
    expect = {}
    for i in range(n):
        node.update_objects([
            (f"c{i}", "counter_pn", "bk", ("increment", i + 1)),
            (f"s{i}", "set_aw", "bk", ("add", f"e{i}")),
        ])
        expect[(f"c{i}", "counter_pn", "bk")] = i + 1
        expect[(f"s{i}", "set_aw", "bk")] = [f"e{i}"]
    # removes + extra increments exercise non-trivial folds
    for i in range(0, n, 3):
        node.update_objects([(f"s{i}", "set_aw", "bk", ("remove", f"e{i}"))])
        expect[(f"s{i}", "set_aw", "bk")] = []
    return expect


def check(node, expect):
    objs = list(expect)
    vals, _ = node.read_objects(objs)
    for (obj, want), got in zip(expect.items(), vals):
        assert got == want, (obj, got, want)


def test_export_import_roundtrip():
    cfg = mk_cfg()
    a = AntidoteNode(cfg)
    expect = populate(a)
    b = AntidoteNode(cfg)
    moved = 0
    for shard in range(cfg.n_shards):
        pkg = handoff.unpack(handoff.pack(handoff.export_shard(a.store, shard)))
        b.receive_handoff(pkg)
        moved += len(pkg["directory"])
    assert moved == len(a.store.directory)
    # replica B now answers every read with identical values
    check(b, expect)


def test_certification_sees_moved_commits():
    """A txn whose snapshot predates a handoff must not silently overwrite
    a moved commit (first-committer-wins carries across the move)."""
    from antidote_tpu.txn.manager import AbortError

    cfg = mk_cfg()
    a = AntidoteNode(cfg)
    a.update_objects([("k", "counter_pn", "bk", ("increment", 1))])
    b = AntidoteNode(cfg)
    txn = b.start_transaction()  # snapshot taken BEFORE the import
    b.read_objects([("k", "counter_pn", "bk")], txn)  # read-bearing:
    # a blind increment would take the ISSUE 6 commutativity bypass
    for shard in range(cfg.n_shards):
        b.receive_handoff(handoff.export_shard(a.store, shard))
    b.update_objects([("k", "counter_pn", "bk", ("increment", 10))], txn)
    with pytest.raises(AbortError):
        b.commit_transaction(txn)


def test_import_rejects_collision():
    cfg = mk_cfg()
    a = AntidoteNode(cfg)
    a.update_objects([("k", "counter_pn", "bk", ("increment", 1))])
    shard = a.store.locate("k", "counter_pn", "bk")[1]
    pkg = handoff.export_shard(a.store, shard)
    with pytest.raises(ValueError, match="already bound"):
        handoff.import_shard(a.store, pkg)  # same replica: keys collide


def test_drop_shard_clears_source():
    cfg = mk_cfg()
    a = AntidoteNode(cfg)
    populate(a, n=8)
    victim = a.store.locate("c0", "counter_pn", "bk")[1]
    before = len(a.store.directory)
    dropped = [dk for dk, ent in a.store.directory.items() if ent[1] == victim]
    handoff.drop_shard(a.store, victim)
    assert len(a.store.directory) == before - len(dropped)
    assert a.store.locate("c0", "counter_pn", "bk", create=False) is None
    for t in a.store.tables.values():
        assert t.used_rows[victim] == 0
        assert (t.n_ops[victim] == 0).all()


def test_drop_shard_truncates_wal_no_resurrection(tmp_path):
    """After handoff + drop, a recover on the SOURCE must not resurrect the
    moved keys (their WAL records moved with them)."""
    cfg = mk_cfg()
    a = AntidoteNode(cfg, log_dir=str(tmp_path / "a"))
    a.update_objects([("k", "counter_pn", "bk", ("increment", 9))])
    victim = a.store.locate("k", "counter_pn", "bk")[1]
    b = AntidoteNode(cfg, log_dir=str(tmp_path / "b"))
    b.receive_handoff(handoff.export_shard(a.store, victim))
    handoff.drop_shard(a.store, victim)
    a2 = AntidoteNode(cfg, log_dir=str(tmp_path / "a"), recover=True)
    assert a2.store.locate("k", "counter_pn", "bk", create=False) is None
    vals, _ = b.read_objects([("k", "counter_pn", "bk")])
    assert vals == [9]


def test_import_failure_leaves_destination_untouched():
    """A colliding import must reject BEFORE mutating anything."""
    cfg = mk_cfg()
    a = AntidoteNode(cfg)
    a.update_objects([("k", "counter_pn", "bk", ("increment", 1)),
                      ("other", "counter_pn", "bk", ("increment", 2))])
    shard = a.store.locate("k", "counter_pn", "bk")[1]
    pkg = handoff.export_shard(a.store, shard)
    used_before = {t: a.store.tables[t].used_rows.copy()
                   for t in a.store.tables}
    dir_before = dict(a.store.directory)
    with pytest.raises(ValueError, match="already bound"):
        handoff.import_shard(a.store, pkg)
    assert dict(a.store.directory) == dir_before
    for t, used in used_before.items():
        np.testing.assert_array_equal(a.store.tables[t].used_rows, used)


def test_handoff_with_log_recovers(tmp_path):
    cfg = mk_cfg()
    a = AntidoteNode(cfg, log_dir=str(tmp_path / "a"))
    expect = populate(a, n=10)
    b = AntidoteNode(cfg, log_dir=str(tmp_path / "b"))
    for shard in range(cfg.n_shards):
        b.receive_handoff(handoff.export_shard(a.store, shard))
    check(b, expect)
    # B's WAL now re-chains the moved records: a cold replica recovered
    # from B's log alone serves the same values
    c = AntidoteNode(cfg, log_dir=str(tmp_path / "b"), recover=True)
    check(c, expect)


@pytest.mark.parametrize("new_n", [2, 8])
def test_reshard_preserves_values_and_routing(new_n, tmp_path):
    from antidote_tpu.log import LogManager

    cfg = mk_cfg(4)
    a = AntidoteNode(cfg, log_dir=str(tmp_path / "a"))
    expect = populate(a, n=20)
    new_cfg = mk_cfg(new_n)
    log_new = LogManager(new_cfg, str(tmp_path / "n"))
    new_store = handoff.reshard(a.store, new_cfg, log=log_new)
    b = AntidoteNode(new_cfg, store=new_store)
    check(b, expect)
    for (key, bucket), (_, s, _) in new_store.directory.items():
        assert s == key_to_shard(key, bucket, new_n)
    # the re-chained log alone can rebuild the resharded replica
    c = AntidoteNode(new_cfg, log_dir=str(tmp_path / "n"), recover=True)
    check(c, expect)
