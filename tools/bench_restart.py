#!/usr/bin/env python
"""Restart benchmark: full-WAL replay vs checkpoint + tail (ISSUE 8).

Flow (each phase a fresh subprocess so wall-clocks are honest —
SIGKILL'd populate, cold recoveries):

  1. populate   — N counter keys through the durable commit path
                  (WAL append + device scatter), then SIGKILL itself:
                  exactly what a crashed server leaves behind.
  2. recover-full — boot with recover=True BEFORE any checkpoint
                  exists: the seed behavior, whole-WAL replay.  Emits a
                  state digest (values sample, op-id chains, append
                  sequences, stable VC).
  3. checkpoint — recover again, publish one checkpoint (image bytes,
                  WAL bytes reclaimed), SIGKILL itself mid-flight after
                  more tail writes land.
  4. recover-fast — boot from (image + tail); time it, digest it.

The parent asserts the two digests are byte-identical (adjusted for the
tail writes), takes best-of-N for both recovery numbers, and — with
--json — freezes BENCH_RESTART_cpu.json (no ratchet: the artifact
records, the smoke gate only asserts structure: fast < full, exact
state, bytes reclaimed).

Usage:
  python tools/bench_restart.py --smoke --assert-bounds   # CI gate
  python tools/bench_restart.py --keys 1000000 --json BENCH_RESTART_cpu.json
"""

from __future__ import annotations

import argparse
import json
import os
import signal
import subprocess
import sys
import time

_T0 = time.time()
_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)

#: tail writes landed between the checkpoint and the kill — the fast
#: restart must replay exactly these on top of the image
TAIL_WRITES = 512


def log(*a):
    print(f"[restart {time.time() - _T0:7.1f}s]", *a, file=sys.stderr,
          flush=True)


def _cfg(n_keys: int):
    from antidote_tpu.config import AntidoteConfig

    return AntidoteConfig(
        n_shards=16, max_dcs=4, keys_per_table=max(n_keys // 16, 1024),
        wal_segments=4,
    )


def _mk_node(n_keys: int, log_dir: str, recover: bool):
    from antidote_tpu.api import AntidoteNode

    return AntidoteNode(_cfg(n_keys), log_dir=log_dir, recover=recover)


def _digest(node, n_keys: int) -> dict:
    """Byte-identical-recovery digest: sampled values + chain state."""
    sample = list(range(0, n_keys, max(n_keys // 512, 1)))
    objs = [(k, "counter_pn", "b") for k in sample]
    vals, _ = node.read_objects(objs)
    return {
        "sample_keys": sample[:4] + sample[-4:],
        "sample_sum": int(sum(vals)),
        "sample_vals": [int(v) for v in vals[:16]],
        "keys": len(node.store.directory),
        "op_ids": node.store.log.op_ids.tolist(),
        "seqs": node.store.log.seqs.tolist(),
        "stable": [int(x) for x in node.stable_vc()],
        "commit_counter": int(node.txm.commit_counter),
    }


def _wal_bytes(log_dir: str) -> int:
    return sum(
        os.path.getsize(os.path.join(log_dir, f))
        for f in os.listdir(log_dir) if f.endswith(".wal")
    )


def _populate(node, n_keys: int, start_vc: int = 0):
    """Commit N increments through the durable path in recovery-sized
    batches (the same apply_effects + WAL append machinery a live
    commit drives, minus per-txn wire overhead)."""
    import numpy as np

    from antidote_tpu.store.kv import Effect

    store = node.store
    batch = 4096
    counter = start_vc
    keys = list(range(n_keys))
    for base in range(0, len(keys), batch):
        chunk = keys[base:base + batch]
        counter += 1
        vc = np.zeros(node.cfg.max_dcs, np.int32)
        vc[node.dc_id] = counter
        effs = [
            Effect(k, "counter_pn", "b",
                   np.asarray([1], np.int64), np.asarray([], np.int32))
            for k in chunk
        ]
        store.apply_effects(effs, [vc] * len(effs), [node.dc_id] * len(effs))
    node.txm.commit_counter = counter
    return counter


def _maxrss_mb() -> float:
    import resource

    return round(
        resource.getrusage(resource.RUSAGE_SELF).ru_maxrss / 1024, 1)


def child_main(argv) -> int:
    phase = argv[0]
    n_keys = int(argv[1])
    log_dir = argv[2]
    budget = int(argv[3]) if len(argv) > 3 else 0
    from antidote_tpu.config import apply_jax_platform_env

    apply_jax_platform_env()
    t0 = time.monotonic()
    if phase == "populate-cold":
        # beyond-RAM populate (ISSUE 13): resident rows bounded by the
        # budget, periodic chain stamps (full rebases carry the cold
        # appendix forward), SIGKILL at the end like a real outage
        from antidote_tpu.api import AntidoteNode

        node = AntidoteNode(_cfg(n_keys), log_dir=log_dir, recover=False,
                            resident_rows=budget)
        # evictability anchors to FULL images (delta links carry no
        # sidecar), so worst-case residency = budget + one rebase
        # window of not-yet-covered rows: rebase every other stamp
        # keeps that window at one stamp's writes — O(budget), never
        # O(total keys)
        node.start_checkpointer(interval_s=0.0, rebase_every=2)
        import numpy as np

        from antidote_tpu.store.kv import Effect

        store = node.store
        batch, counter = 4096, 0
        stamp_every = max(budget // 2, 4096)
        since_stamp = 0
        max_resident = 0
        t1 = time.monotonic()
        for base in range(0, n_keys, batch):
            chunk = range(base, min(base + batch, n_keys))
            counter += 1
            vc = np.zeros(node.cfg.max_dcs, np.int32)
            vc[node.dc_id] = counter
            effs = [Effect(k, "counter_pn", "b",
                           np.asarray([1], np.int64),
                           np.asarray([], np.int32)) for k in chunk]
            store.apply_effects(effs, [vc] * len(effs),
                                [node.dc_id] * len(effs))
            since_stamp += len(effs)
            if since_stamp >= stamp_every:
                since_stamp = 0
                node.checkpoint_now()
                max_resident = max(max_resident,
                                   store.cold.resident_rows())
        node.txm.commit_counter = counter
        node.checkpoint_now(full=True)
        store.cold.enforce_budget()
        max_resident = max(max_resident, store.cold.resident_rows())
        print(json.dumps({
            "populate_s": round(time.monotonic() - t1, 2),
            "wal_bytes": _wal_bytes(log_dir),
            "max_resident_rows": int(max_resident),
            "final_resident_rows": int(store.cold.resident_rows()),
            "cold_keys": len(store.cold.cold_set),
            "evictions": int(store.cold.evictions),
            "maxrss_mb": _maxrss_mb(),
        }), flush=True)
        os.kill(os.getpid(), signal.SIGKILL)
    if phase == "recover-cold":
        from antidote_tpu.api import AntidoteNode

        node = AntidoteNode(_cfg(n_keys), log_dir=log_dir, recover=True,
                            resident_rows=budget)
        recover_s = time.monotonic() - t0
        resident_after_install = int(node.store.cold.resident_rows())
        dig = _digest(node, n_keys)  # the sample read faults cold rows in
        print(json.dumps({
            "recover_s": round(recover_s, 2),
            "phase_checkpoint_s": round(
                node.metrics.recovery_seconds.value(phase="checkpoint"),
                3),
            "resident_rows_after_install": resident_after_install,
            "cold_keys_after_install": len(node.store.cold.cold_set)
            + node.store.cold.faults,
            "sample_faults": int(node.store.cold.faults),
            "maxrss_mb": _maxrss_mb(),
            "digest": dig,
        }), flush=True)
        return 0
    if phase == "stamp-compare":
        # incremental-vs-full stamp cost (ISSUE 13): a delta link's
        # cost tracks the dirty set, a full rebase the resident extent
        from antidote_tpu.api import AntidoteNode

        node = _mk_node(n_keys, log_dir, recover=False)
        node.start_checkpointer(interval_s=0.0, rebase_every=1 << 30)
        _populate(node, n_keys)
        t1 = time.monotonic()
        full = node.checkpoint_now(full=True)
        full_s = time.monotonic() - t1
        dirty = max(n_keys // 100, 64)  # 1% dirty working set
        _populate(node, dirty, start_vc=node.txm.commit_counter)
        t1 = time.monotonic()
        delta = node.checkpoint_now(full=False)
        delta_s = time.monotonic() - t1
        print(json.dumps({
            "full_stamp_s": round(full_s, 3),
            "full_bytes": full["image_bytes"],
            "full_rows": full["n_rows"],
            "delta_stamp_s": round(delta_s, 3),
            "delta_bytes": delta["image_bytes"],
            "delta_rows": delta["n_rows"],
            "dirty_writes": dirty,
        }), flush=True)
        return 0
    if phase == "populate":
        node = _mk_node(n_keys, log_dir, recover=False)
        boot_s = time.monotonic() - t0
        t1 = time.monotonic()
        _populate(node, n_keys)
        print(json.dumps({
            "boot_s": round(boot_s, 2),
            "populate_s": round(time.monotonic() - t1, 2),
            "wal_bytes": _wal_bytes(log_dir),
        }), flush=True)
        os.kill(os.getpid(), signal.SIGKILL)  # crash, like a real outage
    if phase == "recover-full" or phase == "recover-fast":
        node = _mk_node(n_keys, log_dir, recover=True)
        recover_s = time.monotonic() - t0
        m = node.metrics
        print(json.dumps({
            "recover_s": round(recover_s, 2),
            "phase_checkpoint_s": round(
                m.recovery_seconds.value(phase="checkpoint"), 3),
            "phase_tail_s": round(
                m.recovery_seconds.value(phase="tail"), 3),
            "records": int(m.recovery_records.value()),
            "digest": _digest(node, n_keys),
        }), flush=True)
        return 0
    if phase == "checkpoint":
        node = _mk_node(n_keys, log_dir, recover=True)
        recover_s = time.monotonic() - t0
        t1 = time.monotonic()
        summary = node.checkpoint_now()
        ckpt_s = time.monotonic() - t1
        # tail: more committed writes AFTER the stamp, then crash — the
        # fast restart must land exactly these on top of the image
        _populate(node, min(TAIL_WRITES, n_keys),
                  start_vc=node.txm.commit_counter)
        print(json.dumps({
            "recover_s": round(recover_s, 2),
            "checkpoint_s": round(ckpt_s, 2),
            "image_bytes": summary["image_bytes"],
            "reclaimed_bytes": summary["reclaimed_bytes"],
            "barrier_ms": summary.get("barrier_ms"),
            "wal_bytes_after": _wal_bytes(log_dir),
        }), flush=True)
        os.kill(os.getpid(), signal.SIGKILL)
    raise SystemExit(f"unknown phase {phase!r}")


def run_child(phase, n_keys, log_dir, timeout_s, budget=0) -> dict:
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    log(f"phase {phase} ...")
    res = subprocess.run(
        [sys.executable, os.path.abspath(__file__), "--child", phase,
         str(n_keys), log_dir, str(budget)],
        stdout=subprocess.PIPE, stderr=sys.stderr, env=env,
        timeout=timeout_s,
    )
    out = res.stdout.decode(errors="replace").strip().splitlines()
    if not out:
        raise RuntimeError(f"phase {phase} produced no output "
                           f"(rc={res.returncode})")
    parsed = json.loads(out[-1])
    log(f"phase {phase}: {parsed if len(str(parsed)) < 300 else '<ok>'}")
    return parsed


def _freeze(args, key: str, result: dict) -> None:
    if not args.json:
        return
    path = os.path.join(_REPO, args.json) \
        if not os.path.isabs(args.json) else args.json
    merged = {}
    if os.path.exists(path):
        with open(path) as f:
            merged = json.load(f)
    merged[key] = result
    with open(path, "w") as f:
        json.dump(merged, f, indent=2)
    log(f"artifact frozen to {path} [{key}]")


def main_coldtier(args) -> int:
    """Beyond-RAM bench leg: populate ``--keys`` counters under a
    ``--resident-rows`` device budget with chain stamps, SIGKILL, then
    a cold recovery whose sample reads fault rows back in.  Structural
    gates only (resident ≤ budget+slack, cold keys exist, sample
    byte-exact) — the frozen numbers are never a ratchet."""
    import tempfile

    n_keys = 100_000 if args.coldtier_smoke else args.keys
    budget = args.resident_rows or max(n_keys // 10, 4096)
    scratch = args.dir or tempfile.mkdtemp(prefix="antidote-cold-")
    log_dir = os.path.join(scratch, "wal")
    timeout_s = 900 if args.coldtier_smoke else 7200
    pop = run_child("populate-cold", n_keys, log_dir, timeout_s,
                    budget=budget)
    rec = run_child("recover-cold", n_keys, log_dir, timeout_s,
                    budget=budget)
    stride = max(n_keys // 512, 1)
    n_sampled = len(range(0, n_keys, stride))
    result = {
        "metric": "coldtier_bounded_rss",
        "n_keys": n_keys,
        "resident_rows_budget": budget,
        "populate": pop,
        "recover": rec,
        "host_note": (
            "structural gates only: resident rows ≤ budget (+ one "
            "commit batch + one uncovered stamp window of slack), cold "
            "keys exist, and the post-recovery sample reads are "
            "byte-exact after faulting their rows back in.  maxrss "
            "includes the interpreter + jax/XLA and the O(total keys) "
            "host directory — the budget bounds DEVICE TABLE rows, "
            "which are the per-key heavyweight (head + snapshot ring + "
            "op ring); never a ratchet."
        ),
    }
    print(json.dumps(result, indent=2))
    _freeze(args, f"coldtier_keys_{n_keys}", result)
    if args.assert_bounds:
        # slack: one in-flight commit batch + one REBASE WINDOW of rows
        # no full image covers yet (evictability anchors to fulls) —
        # O(budget) regardless of total keys
        slack = 4096 + 2 * max(budget // 2, 4096)
        assert pop["max_resident_rows"] <= budget + slack, pop
        assert pop["final_resident_rows"] <= budget, pop
        assert pop["cold_keys"] > 0 and pop["evictions"] > 0, pop
        assert rec["resident_rows_after_install"] <= budget + slack, rec
        assert rec["digest"]["sample_sum"] == n_sampled, rec["digest"]
        assert rec["digest"]["keys"] + rec["cold_keys_after_install"] \
            >= n_keys, rec
        assert rec["sample_faults"] > 0, rec
        log("assert-bounds: all cold-tier structural gates passed")
    return 0


def main_incremental(args) -> int:
    """Incremental-vs-full stamp cost: a delta link's cost must track
    the dirty set (rows == dirty writes), not the table extent."""
    import tempfile

    n_keys = 50_000 if args.smoke else args.keys
    scratch = args.dir or tempfile.mkdtemp(prefix="antidote-incr-")
    log_dir = os.path.join(scratch, "wal")
    cmp_ = run_child("stamp-compare", n_keys, log_dir,
                     600 if args.smoke else 3600)
    result = {
        "metric": "incremental_stamp_cost",
        "n_keys": n_keys,
        **cmp_,
        "full_over_delta_bytes": round(
            cmp_["full_bytes"] / max(cmp_["delta_bytes"], 1), 1),
        "host_note": (
            "structural gates only: the delta link's row count equals "
            "the dirty write set and its bytes/wall-clock undercut the "
            "full rebase — write cost ∝ dirty rows, not table size; "
            "never a ratchet."
        ),
    }
    print(json.dumps(result, indent=2))
    _freeze(args, f"incremental_keys_{n_keys}", result)
    if args.assert_bounds:
        assert cmp_["delta_rows"] == cmp_["dirty_writes"], cmp_
        assert cmp_["delta_bytes"] < cmp_["full_bytes"], cmp_
        assert cmp_["delta_stamp_s"] < cmp_["full_stamp_s"], cmp_
        log("assert-bounds: all incremental structural gates passed")
    return 0


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--child", action="store_true")
    ap.add_argument("--keys", type=int, default=1_000_000)
    ap.add_argument("--smoke", action="store_true",
                    help="small keyspace CI gate (~1-2 min)")
    ap.add_argument("--assert-bounds", action="store_true",
                    help="fail unless fast < full, digests identical, "
                         "and WAL bytes were reclaimed")
    ap.add_argument("--best-of", type=int, default=2)
    ap.add_argument("--json", default=None,
                    help="freeze the artifact here (merge-by-n_keys; "
                         "never a ratchet)")
    ap.add_argument("--dir", default=None, help="scratch dir override")
    ap.add_argument("--coldtier", action="store_true",
                    help="beyond-RAM run (ISSUE 13): populate --keys "
                         "under --resident-rows, SIGKILL, recover cold")
    ap.add_argument("--coldtier-smoke", action="store_true",
                    help="small cold-tier CI gate (~1-2 min)")
    ap.add_argument("--incremental", action="store_true",
                    help="incremental-vs-full stamp cost comparison")
    ap.add_argument("--resident-rows", type=int, default=None,
                    help="cold-tier budget (default keys // 10)")
    args, rest = ap.parse_known_args()
    if args.child:
        return child_main(rest)

    if args.coldtier or args.coldtier_smoke:
        return main_coldtier(args)
    if args.incremental:
        return main_incremental(args)

    n_keys = 50_000 if args.smoke else args.keys
    import tempfile

    scratch = args.dir or tempfile.mkdtemp(prefix="antidote-restart-")
    log_dir = os.path.join(scratch, "wal")
    timeout_s = 600 if args.smoke else 3600

    pop = run_child("populate", n_keys, log_dir, timeout_s)
    wal_before = pop["wal_bytes"]

    fulls = [run_child("recover-full", n_keys, log_dir, timeout_s)
             for _ in range(args.best_of)]
    full = min(fulls, key=lambda r: r["recover_s"])

    ck = run_child("checkpoint", n_keys, log_dir, timeout_s)

    fasts = [run_child("recover-fast", n_keys, log_dir, timeout_s)
             for _ in range(args.best_of)]
    fast = min(fasts, key=lambda r: r["recover_s"])

    # byte-identical modulo the known tail: the checkpoint child landed
    # TAIL_WRITES more increments (one per key on the first TAIL_WRITES
    # keys, +1 commit counter lane) after the full-replay measurement
    dig_full, dig_fast = full["digest"], fast["digest"]
    tail_keys = min(TAIL_WRITES, n_keys)
    stride = max(n_keys // 512, 1)
    sampled_tail = len([k for k in range(0, n_keys, stride)
                        if k < tail_keys])
    exact = (
        dig_fast["keys"] == dig_full["keys"]
        and dig_fast["sample_sum"] == dig_full["sample_sum"] + sampled_tail
        and dig_fast["commit_counter"] > dig_full["commit_counter"]
    )
    speedup = full["recover_s"] / max(fast["recover_s"], 1e-9)
    result = {
        "metric": "restart_recovery_wall_clock",
        "n_keys": n_keys,
        "smoke": bool(args.smoke),
        "best_of": args.best_of,
        "populate_s": pop["populate_s"],
        "full_replay_s": full["recover_s"],
        "full_replay_records": full["records"],
        "fast_restart_s": fast["recover_s"],
        "fast_restart_phases": {
            "checkpoint_s": fast["phase_checkpoint_s"],
            "tail_s": fast["phase_tail_s"],
            "tail_records": fast["records"],
        },
        "speedup": round(speedup, 2),
        "checkpoint": {
            "image_bytes": ck["image_bytes"],
            "write_s": ck["checkpoint_s"],
            "stamp_barrier_ms": ck.get("barrier_ms"),
            "wal_bytes_before": wal_before,
            "wal_bytes_after": ck["wal_bytes_after"],
            "reclaimed_bytes": ck["reclaimed_bytes"],
        },
        "byte_identical": exact,
        "host_note": (
            "2-core shared-CPU container (same host class as BENCH_WIRE: "
            "co-tenant load swings adjacent windows; both recovery "
            "numbers are best-of-N cold-process wall clocks incl. "
            "jax/XLA import+init, so the floor is interpreter+backend "
            "boot, not replay).  No ratchet: the smoke gate asserts "
            "structure only (fast < full, byte-identical digest, "
            "reclaimed > 0), never this artifact's numbers."
        ),
    }
    print(json.dumps(result, indent=2))
    if args.json:
        path = os.path.join(_REPO, args.json) \
            if not os.path.isabs(args.json) else args.json
        merged = {}
        if os.path.exists(path):
            with open(path) as f:
                merged = json.load(f)
        merged[f"keys_{n_keys}"] = result
        with open(path, "w") as f:
            json.dump(merged, f, indent=2)
        log(f"artifact frozen to {path}")
    if args.assert_bounds:
        assert exact, (
            f"recovered state diverged: full={dig_full} fast={dig_fast}")
        assert fast["recover_s"] < full["recover_s"], (
            f"fast restart ({fast['recover_s']}s) not faster than full "
            f"replay ({full['recover_s']}s)")
        assert ck["reclaimed_bytes"] > 0, "no WAL bytes reclaimed"
        assert fast["phase_checkpoint_s"] > 0, "fast path not engaged"
        assert fast["records"] <= TAIL_WRITES + 1, (
            f"fast restart replayed {fast['records']} records — more "
            f"than the tail")
        log("assert-bounds: all structural gates passed")
    return 0


if __name__ == "__main__":
    sys.exit(main())
