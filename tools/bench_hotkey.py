#!/usr/bin/env python
"""Celebrity-key materializer benchmark (ISSUE 15): ONE key, a
million-op log, every fold strategy.

The scenario the sequence-parallel materializer exists for: a single
hot key whose op log dwarfs the ring, replayed at read time.  The child
(fresh backend, 8 forced virtual CPU devices) builds one add-only
set_aw log of L committed ops (bottom base, <= set_slots distinct
elements — the store's slot-promotion invariant) and times every
strategy the store can route it to:

  serial      — fold.fold_key, the masked one-op-at-a-time scan oracle
  assoc       — longlog.assoc_fold, one O(log L)-depth delta window
  long        — longlog.fold_long, chunked scan (fold_chunk-sized)
  mesh_assoc  — longlog.sharded_assoc_fold_fn over the 8-device mesh
                (op axis sharded, deltas merged in sequence order)
  pallas_ring — the Pallas set_aw ring kernel at the same op volume,
                reshaped to [L/K, K] independent rings: a kernel-rate
                proxy (the kernel serves ring folds, not over-ring
                replays), parity-pinned against fold_batch separately

Parity: serial / assoc / long / mesh_assoc states must be
byte-identical on the SAME log.  While the giant assoc fold runs, a
small serving store keeps taking epoch-plane snapshot reads from
concurrent reader threads — the bench records reader throughput during
the fold vs idle (the fold must not wedge the serving plane).

The parent freezes BENCH_HOTKEY_cpu.json.  --assert-bounds is
STRUCTURAL in --smoke (parity clean, every strategy ran, readers
progressed) and NEVER a throughput ratchet; the full freeze run
additionally asserts the ISSUE 15 acceptance floor — assoc and
mesh_assoc >= 4x faster than the serial scan on this CPU proxy.

Usage:
  python tools/bench_hotkey.py --smoke --assert-bounds   # CI gate
  python tools/bench_hotkey.py --json BENCH_HOTKEY_cpu.json  # freeze
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import threading
import time

_T0 = time.time()
_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)

HOST_NOTE = (
    "2-core shared CPU container: the 8 mesh 'devices' are XLA "
    "host-platform threads multiplexed over 2 cores with co-tenant "
    "load, so mesh_assoc measures the sequence-sharding STRUCTURE, not "
    "chip scaling, and adjacent windows swing several x.  The "
    "speedup_vs_serial figures compare compiled XLA programs on the "
    "same host and are the frozen CPU proxy for the ROADMAP item-6 "
    "giant-key target; real-TPU numbers are the success metric."
)


def log(*a):
    print(f"[hotkey {time.time() - _T0:6.1f}s]", *a, file=sys.stderr,
          flush=True)


# ---------------------------------------------------------------------------
# child: one fresh backend, every strategy over the same giant log
# ---------------------------------------------------------------------------
def run_child(l_ops: int, repeats: int) -> dict:
    import jax
    import jax.numpy as jnp
    import numpy as np

    from antidote_tpu.config import (AntidoteConfig,
                                     enable_compilation_cache)

    enable_compilation_cache()
    from antidote_tpu.crdt import get_type
    from antidote_tpu.materializer import fold as fold_mod
    from antidote_tpu.materializer import longlog
    from antidote_tpu.materializer import pallas_kernels as pk
    from antidote_tpu.parallel import make_mesh
    from antidote_tpu.store.kv import Effect, KVStore

    cfg = AntidoteConfig(
        n_shards=8, max_dcs=2, ops_per_key=8, set_slots=8,
        keys_per_table=4096, batch_buckets=(64, 512),
    )
    ty = get_type("set_aw")
    d, k, e = cfg.max_dcs, cfg.ops_per_key, cfg.set_slots
    chunk = cfg.fold_chunk
    assert l_ops % chunk == 0 and l_ops % 8 == 0 and l_ops % k == 0

    # -- the celebrity log: L committed add-only ops over 6 elements ----
    rng = np.random.default_rng(15)
    handles = rng.integers(1, 7, size=(l_ops,)).astype(np.int64)
    handles *= 0x1_0000_0003
    ops_a = handles[:, None]
    ops_b = np.zeros((l_ops, 1 + d), np.int32)  # all adds
    ops_origin = rng.integers(0, d, size=(l_ops,)).astype(np.int32)
    ops_vc = rng.integers(0, 1 << 20, size=(l_ops, d)).astype(np.int32)
    ops_vc[np.arange(l_ops), ops_origin] = rng.integers(
        1, 1 << 20, size=(l_ops,))
    base_vc = np.zeros((d,), np.int32)
    read_vc = np.full((d,), 1 << 21, np.int32)
    state0 = {f: jnp.zeros(s, dt)
              for f, (s, dt) in ty.state_spec(cfg).items()}
    ja, jb, jv, jo = map(jnp.asarray, (ops_a, ops_b, ops_vc, ops_origin))
    jbase, jread = jnp.asarray(base_vc), jnp.asarray(read_vc)
    n_ops = jnp.int32(l_ops)

    def timed(label, fn, reps):
        out = fn()
        jax.block_until_ready(out)  # warmup = compile
        best = float("inf")
        for _ in range(max(reps, 1)):
            t0 = time.monotonic()
            out = fn()
            jax.block_until_ready(out)
            best = min(best, time.monotonic() - t0)
        log(f"{label:12s} {best * 1e3:10.1f} ms "
            f"({l_ops / best / 1e6:8.2f} Mops/s)")
        return out, best

    # -- a small serving store + concurrent snapshot readers ------------
    store = KVStore(cfg)
    aw, bw = (get_type("counter_pn").eff_a_width(cfg),
              get_type("counter_pn").eff_b_width(cfg))
    n_keys, counter = 2048, 0
    effs, vcs = [], []
    for kk in range(n_keys):
        counter += 1
        effs.append(Effect(kk, "counter_pn", "b",
                           np.full(aw, kk % 97 + 1, np.int64),
                           np.zeros(bw, np.int32)))
        vcs.append(np.asarray([counter, 0], np.int32))
    store.apply_effects(effs, vcs, [0] * len(effs))
    store.publish_serving_epoch(store.dc_max_vc())

    reads = {"n": 0}
    stop = threading.Event()

    def reader():
        r = np.random.default_rng(threading.get_ident() % 2**32)
        while not stop.is_set():
            objs = [(int(x), "counter_pn", "b")
                    for x in r.integers(0, n_keys, size=256)]
            ep = store.pin_serving_epoch()
            pending, fb = store.epoch_read_launch(objs, ep)
            vals = store.epoch_read_finish(pending)
            store.unpin_serving_epoch(ep)
            assert not fb and len(vals) == 256
            reads["n"] += 256

    # idle reader rate (no fold competing)
    threads = [threading.Thread(target=reader, daemon=True)
               for _ in range(2)]
    for t in threads:
        t.start()
    time.sleep(0.8)
    idle_reads = reads["n"]
    idle_rate = idle_reads / 0.8

    # -- the strategies over the same log --------------------------------
    results: dict = {}
    parity: dict = {}

    serial_fn = jax.jit(lambda: fold_mod.fold_key(
        ty, cfg, state0, ja, jb, jv, jo, n_ops, jbase, jread))
    (ref_state, ref_applied), s_serial = timed(
        "serial", serial_fn, max(repeats - 1, 1))
    results["serial"] = s_serial

    t_fold0 = time.monotonic()
    assoc_fn = jax.jit(lambda: longlog.assoc_fold(
        ty, cfg, state0, ja, jb, jv, jo, n_ops, jbase, jread))
    (assoc_state, assoc_applied), s_assoc = timed(
        "assoc", assoc_fn, repeats)
    results["assoc"] = s_assoc
    during_span = time.monotonic() - t_fold0

    long_fn = jax.jit(lambda: longlog.fold_long(
        ty, cfg, state0, ja, jb, jv, jo, n_ops, jbase, jread,
        chunk=chunk))
    (long_state, long_applied), s_long = timed("long", long_fn, repeats)
    results["long"] = s_long

    mesh = make_mesh(8)
    mesh_fn = longlog.sharded_assoc_fold_fn(ty, cfg, mesh)
    (mesh_state, mesh_applied), s_mesh = timed(
        "mesh_assoc",
        lambda: mesh_fn(state0, ja, jb, jv, jo, l_ops, jbase, jread),
        repeats)
    results["mesh_assoc"] = s_mesh

    # reader progress while the giant folds were running
    during_reads = reads["n"] - idle_reads
    stop.set()
    for t in threads:
        t.join(timeout=5)

    # -- Pallas ring-rate proxy: same op volume as [L/K, K] rings --------
    b_rings = l_ops // k
    ra = ops_a.reshape(b_rings, k, 1)
    rb = ops_b.reshape(b_rings, k, 1 + d)
    rv = ops_vc.reshape(b_rings, k, d)
    ro = ops_origin.reshape(b_rings, k)
    rn = np.full((b_rings,), k, np.int32)
    rbase = np.zeros((b_rings, d), np.int32)
    rread = np.broadcast_to(read_vc, (b_rings, d)).copy()
    rstate = {f: jnp.zeros((b_rings,) + s, dt)
              for f, (s, dt) in ty.state_spec(cfg).items()}
    jra, jrb, jrv, jro, jrn, jrbase, jrread = map(
        jnp.asarray, (ra, rb, rv, ro, rn, rbase, rread))
    interpret = not pk._on_tpu()
    (p_state, p_applied), s_pallas = timed(
        "pallas_ring",
        lambda: pk.set_aw_fold_local(
            rstate, jra, jrb, jrv, jro, jrn, jrbase, jrread,
            block=256, interpret=interpret),
        repeats)
    results["pallas_ring"] = s_pallas
    # parity for the kernel: oracle fold_batch over a slice of rings
    nb = 64
    oracle_state, oracle_applied = fold_mod.fold_batch(
        ty, cfg, {f: x[:nb] for f, x in rstate.items()},
        jra[:nb], jrb[:nb], jrv[:nb], jro[:nb], jrn[:nb],
        jrbase[:nb], jrread[:nb])
    parity["pallas_ring"] = bool(
        all(np.array_equal(np.asarray(oracle_state[f]),
                           np.asarray(p_state[f][:nb]))
            for f in oracle_state)
        and np.array_equal(np.asarray(oracle_applied),
                           np.asarray(p_applied[:nb])))

    # -- byte parity across the over-ring strategies ---------------------
    ref_np = {f: np.asarray(x) for f, x in ref_state.items()}
    for name, (st, ap) in (("assoc", (assoc_state, assoc_applied)),
                           ("long", (long_state, long_applied)),
                           ("mesh_assoc", (mesh_state, mesh_applied))):
        parity[name] = bool(
            all(np.array_equal(ref_np[f], np.asarray(st[f]))
                for f in ref_np)
            and int(ap) == int(ref_applied))

    strategies = {
        name: {
            "seconds": round(s, 4),
            "mops_per_s": round(l_ops / s / 1e6, 2),
            "speedup_vs_serial": round(s_serial / s, 2),
        }
        for name, s in results.items()
    }
    return {
        "l_ops": l_ops,
        "distinct_elements": 6,
        "fold_chunk": chunk,
        "applied": int(ref_applied),
        "strategies": strategies,
        "parity": parity,
        "readers": {
            "threads": 2,
            "idle_reads_per_s": round(idle_rate, 1),
            "during_fold_reads_per_s": round(
                during_reads / during_span, 1) if during_span else 0.0,
            "during_fold_reads": int(during_reads),
        },
    }


# ---------------------------------------------------------------------------
# parent: fresh-backend child, artifact freeze, gates
# ---------------------------------------------------------------------------
def run_parent(args) -> int:
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    flags = env.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        env["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8").strip()
    log(f"child: one set_aw key, {args.l_ops} ops")
    out = subprocess.run(
        [sys.executable, os.path.abspath(__file__), "--one",
         "--l-ops", str(args.l_ops), "--repeats", str(args.repeats)],
        capture_output=True, text=True, cwd=_REPO, env=env, timeout=1800,
    )
    sys.stderr.write(out.stderr)
    if out.returncode != 0:
        log("child FAILED")
        return 1
    res = json.loads(out.stdout.strip().splitlines()[-1])

    artifact = {
        "metric": "hotkey_fold_strategies",
        "unit": "one set_aw key, L-op over-ring log: seconds per full "
                "fold by strategy (+ concurrent snapshot-reader rate)",
        "driver_rev": 1,
        "result": res,
        "target": {
            "metric": "assoc + mesh_assoc >= 4x the serial scan on the "
                      "giant-key replay (ISSUE 15); real-TPU sequence "
                      "sharding is the ROADMAP item-6 success metric",
            "cpu_proxy": "frozen on the shared container; the smoke "
                         "gate is structural only",
        },
        "host_note": HOST_NOTE,
        "smoke": bool(args.smoke),
        "created_at": time.time(),
    }
    if args.json:
        path = os.path.join(_REPO, args.json)
        with open(path, "w") as f:
            json.dump(artifact, f, indent=1)
            f.write("\n")
        log(f"froze {args.json}")
    else:
        print(json.dumps(artifact, indent=1))

    if args.assert_bounds:
        st = res["strategies"]
        for name in ("serial", "assoc", "long", "mesh_assoc",
                     "pallas_ring"):
            assert name in st and st[name]["seconds"] > 0, (
                name, "strategy missing / zero time")
        for name, ok in res["parity"].items():
            assert ok, (name, "parity broke")
        assert res["readers"]["during_fold_reads"] > 0, (
            "snapshot readers starved during the giant fold")
        if not args.smoke:
            # the ISSUE 15 acceptance floor — full freeze runs only;
            # the CI smoke gate stays structural (never a ratchet)
            assert st["assoc"]["speedup_vs_serial"] >= 4, st["assoc"]
            assert st["mesh_assoc"]["speedup_vs_serial"] >= 4, (
                st["mesh_assoc"])
        log("gates OK")
    return 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--one", action="store_true",
                    help="(internal) run the child measurement")
    ap.add_argument("--l-ops", type=int, default=1_048_576)
    ap.add_argument("--repeats", type=int, default=2)
    ap.add_argument("--smoke", action="store_true",
                    help="64k-op log, structural gates (CI)")
    ap.add_argument("--assert-bounds", action="store_true")
    ap.add_argument("--json", default=None,
                    help="freeze the artifact to this repo-relative path")
    args = ap.parse_args(argv)
    if args.smoke and args.l_ops == 1_048_576:
        args.l_ops = 65_536
    if args.one:
        from antidote_tpu.config import apply_jax_platform_env

        apply_jax_platform_env()
        print(json.dumps(run_child(args.l_ops, args.repeats)))
        return 0
    return run_parent(args)


if __name__ == "__main__":
    sys.exit(main())
