#!/usr/bin/env python
"""Stdlib static-analysis gate (no third-party linter ships in this
image; ruff/mypy configs in pyproject.toml cover richer CI hosts).

Checks (each one has caught a real bug class in this codebase's history):
  * syntax: every file must compile (the round-4 advisor patch cycle
    shipped an IndentationError mid-session);
  * unused imports (module scope);
  * duplicate top-level / class-level function definitions (a paste slip
    silently shadows the first definition);
  * mutable default arguments;
  * bare ``except:`` (swallows KeyboardInterrupt/SystemExit);
  * broad except-and-continue inside ``while`` loops (a thread loop
    that swallows every exception and spins on is a silently-dead
    subsystem — the failure class the supervised ThreadLoop exists to
    prevent; surface the error or supervise the loop instead);
  * unbounded queue construction in the overload-protected planes
    (``proto/``, ``interdc/``, ``txn/``): ``queue.Queue()`` without a
    maxsize, ``collections.deque()`` without a maxlen, and
    queue-factory ``defaultdict``s must either carry an explicit bound
    or a ``# bounded-by: <reason>`` annotation within the three lines
    above — saturation must shed, never buffer without limit (PR 4);
  * file deletion (``os.remove``/``os.unlink``/``rmtree``) outside
    ``antidote_tpu/log/`` without a ``# reclaim-ok:`` note — WAL and
    checkpoint files are reclaimed only through the guarded floor APIs
    (ISSUE 8);
  * serving-epoch publishes in ``antidote_tpu/interdc/`` (the
    follower/replica plane) that bypass the applied-VC stamp without a
    ``# vc-stamped:`` note — a follower publishing an epoch ahead of
    its applied clock silently violates causality (ISSUE 9).

Usage: python tools/lint.py [paths...]   (default: antidote_tpu tests
bench.py bench_suite.py bench_wire.py tpu_smoke.py __graft_entry__.py)
"""

from __future__ import annotations

import ast
import os
import sys


def iter_py(paths):
    for p in paths:
        if os.path.isfile(p) and p.endswith(".py"):
            yield p
        elif os.path.isdir(p):
            for root, _dirs, files in os.walk(p):
                for f in files:
                    if f.endswith(".py"):
                        yield os.path.join(root, f)


def used_names(tree: ast.AST):
    names = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Name):
            names.add(node.id)
        elif isinstance(node, ast.Constant) and isinstance(node.value, str):
            # names listed in __all__ (and doctest-ish strings) count as
            # used — re-export surfaces are intentional
            if node.value.isidentifier():
                names.add(node.value)
    return names


def check_file(path: str):
    problems = []
    with open(path) as f:
        src = f.read()
    try:
        tree = ast.parse(src, path)
    except SyntaxError as e:
        return [f"{path}:{e.lineno}: syntax error: {e.msg}"]
    used = used_names(tree)
    # "# noqa" on the import line opts out (re-export modules etc.)
    lines = src.splitlines()

    def noqa(lineno: int) -> bool:
        return "noqa" in lines[lineno - 1]

    is_init = os.path.basename(path) == "__init__.py"
    for node in tree.body:
        if isinstance(node, (ast.Import, ast.ImportFrom)):
            if is_init:
                continue  # package __init__: re-export surface
            if isinstance(node, ast.ImportFrom) and node.module == "__future__":
                continue
            for alias in node.names:
                if alias.name == "*":
                    continue
                bound = (alias.asname or alias.name).split(".")[0]
                if bound not in used and not noqa(node.lineno):
                    problems.append(
                        f"{path}:{node.lineno}: unused import '{bound}'"
                    )
    for scope in ast.walk(tree):
        if isinstance(scope, (ast.Module, ast.ClassDef)):
            seen = {}
            body = scope.body
            for node in body:
                if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    if node.name in seen and not noqa(node.lineno):
                        problems.append(
                            f"{path}:{node.lineno}: duplicate definition "
                            f"of '{node.name}' (first at line "
                            f"{seen[node.name]})"
                        )
                    seen[node.name] = node.lineno
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for d in node.args.defaults + node.args.kw_defaults:
                if isinstance(d, (ast.List, ast.Dict, ast.Set)):
                    problems.append(
                        f"{path}:{d.lineno}: mutable default argument in "
                        f"'{node.name}'"
                    )
        elif isinstance(node, ast.ExceptHandler):
            if node.type is None and not noqa(node.lineno):
                problems.append(f"{path}:{node.lineno}: bare 'except:'")
    _check_swallow_loops(tree, path, noqa, problems)
    _check_unbounded_queues(tree, path, lines, problems)
    _check_serving_syncs(path, lines, problems)
    _check_fsync_policy(path, lines, problems)
    _check_reclaim_policy(path, lines, problems)
    _check_epoch_stamp(path, lines, problems)
    _check_evict_policy(path, lines, problems)
    _check_py_socket(path, lines, problems)
    _check_tenant_labels(tree, path, lines, problems)
    return problems


#: planes under overload protection: every queue here is bounded or
#: carries a written justification (ISSUE 4 tentpole discipline)
_BOUNDED_PLANES = (
    os.path.join("antidote_tpu", "proto"),
    os.path.join("antidote_tpu", "interdc"),
    os.path.join("antidote_tpu", "txn"),
)


def _check_unbounded_queues(tree, path, lines, problems) -> None:
    """In proto/, interdc/, txn/: flag queue constructions with no bound
    (queue.Queue()/LifoQueue() without maxsize, SimpleQueue(),
    collections.deque() without maxlen, defaultdict(list|deque)
    buffer registries) unless a ``# bounded-by:`` annotation within the
    three preceding lines (or the construction line) states the bound."""
    norm = os.path.normpath(path)
    if not any(plane in norm for plane in _BOUNDED_PLANES):
        return

    def annotated(lineno: int) -> bool:
        lo = max(0, lineno - 4)
        return any("bounded-by:" in ln for ln in lines[lo:lineno])

    def call_name(fn) -> str:
        if isinstance(fn, ast.Attribute):
            return fn.attr
        if isinstance(fn, ast.Name):
            return fn.id
        return ""

    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        name = call_name(node.func)
        bad = None
        if name in ("Queue", "LifoQueue"):
            if not node.args and not any(k.arg == "maxsize"
                                         for k in node.keywords):
                bad = f"{name}() without maxsize"
        elif name == "SimpleQueue":
            bad = "SimpleQueue() (never bounded)"
        elif name == "deque":
            if len(node.args) < 2 and not any(k.arg == "maxlen"
                                              for k in node.keywords):
                bad = "deque() without maxlen"
        elif name == "defaultdict" and node.args:
            factory = call_name(node.args[0])
            if factory in ("list", "deque"):
                bad = f"defaultdict({factory}) buffer registry"
        if bad and not annotated(node.lineno):
            problems.append(
                f"{path}:{node.lineno}: unbounded queue in an "
                f"overload-protected plane: {bad} — give it an explicit "
                "bound or justify with '# bounded-by: <reason>' above"
            )


#: files that ARE the wire-serving hot path: a device sync on a
#: dispatcher-stage thread stalls every parked request behind one
#: materialize (the staged pipeline confines syncs to the writeback
#: stage) — ISSUE 5 discipline, mirroring the unbounded-queue rule.
#: The mesh serving plane (antidote_tpu/parallel/, ISSUE 10) is held to
#: the same bar: its launch/placement/collective paths run on
#: dispatcher-stage threads, so a sync there must carry the same
#: written justification.  The materializer plane
#: (antidote_tpu/materializer/, ISSUE 15) joined when its folds became
#: the live serving path: the Pallas kernels and the assoc/long-log
#: strategies run inside jitted serving reads, where a stray sync
#: serializes the whole launch pipeline.
_SERVING_HOT_PATH = (os.path.join("antidote_tpu", "proto", "server.py"),)
_SERVING_HOT_PLANES = (
    os.path.join("antidote_tpu", "parallel") + os.sep,
    os.path.join("antidote_tpu", "materializer") + os.sep,
)
_SYNC_TOKENS = ("block_until_ready(", ".item()", "np.asarray(")


def _check_serving_syncs(path, lines, problems) -> None:
    """In the serving hot path — proto/server.py and the whole mesh
    plane (antidote_tpu/parallel/) — flag device-sync idioms:
    ``block_until_ready(``, ``.item()``, ``np.asarray(`` — unless a
    ``# sync-ok: <reason>`` annotation on the line or within the three
    preceding lines justifies it (e.g. the writeback stage, which owns
    the sync, or a conversion of host data that never touches a jax
    array)."""
    norm = os.path.normpath(path)
    if not (any(norm.endswith(p) for p in _SERVING_HOT_PATH)
            or any(pl in norm for pl in _SERVING_HOT_PLANES)):
        return

    def annotated(lineno: int) -> bool:
        lo = max(0, lineno - 4)
        return any("sync-ok:" in ln for ln in lines[lo:lineno])

    def hits(code: str, tok: str) -> bool:
        # 'np.asarray(' must not match the trace-safe 'jnp.asarray('
        start = 0
        while (j := code.find(tok, start)) >= 0:
            if not (tok == "np.asarray(" and j > 0
                    and code[j - 1].isalnum()):
                return True
            start = j + 1
        return False

    for i, ln in enumerate(lines, start=1):
        code = ln.split("#", 1)[0]
        for tok in _SYNC_TOKENS:
            if hits(code, tok) and not annotated(i) and "sync-ok:" not in ln:
                problems.append(
                    f"{path}:{i}: device-sync idiom '{tok}' in the "
                    "serving hot path — move it to the writeback stage "
                    "or justify with '# sync-ok: <reason>'"
                )


#: the one file allowed to call os.fsync freely: the WAL owns durability
#: (group-fsync coordinator, background syncer, commit barriers).  An
#: os.fsync anywhere else in the package is either a policy leak (per-
#: call fsyncs are exactly the serial floor ISSUE 6 removed) or a
#: deliberate non-log use (atomic metadata replace, probe sidecars) that
#: must say so with a ``# fsync-ok: <reason>`` note.
_FSYNC_OWNER = os.path.join("antidote_tpu", "log", "wal.py")


def _check_fsync_policy(path, lines, problems) -> None:
    """Reject direct ``os.fsync`` outside log/wal.py without a
    ``# fsync-ok: <reason>`` annotation on the line or within the three
    preceding lines — the group-fsync policy stays centralized."""
    norm = os.path.normpath(path)
    if norm.endswith(_FSYNC_OWNER) or os.sep + "tests" + os.sep in norm \
            or norm.startswith("tests" + os.sep) \
            or os.path.basename(norm) == "lint.py":  # the rule's own source
        return

    def annotated(lineno: int) -> bool:
        lo = max(0, lineno - 4)
        return any("fsync-ok:" in ln for ln in lines[lo:lineno])

    for i, ln in enumerate(lines, start=1):
        code = ln.split("#", 1)[0]
        if "os.fsync(" in code and not annotated(i) \
                and "fsync-ok:" not in ln:
            problems.append(
                f"{path}:{i}: direct os.fsync outside log/wal.py — "
                "route durability through the WAL's group-fsync "
                "coordinator, or justify with '# fsync-ok: <reason>'"
            )


#: the one package allowed to delete durable files freely: log/ owns the
#: WAL + checkpoint lifecycle and its deletions run behind guarded APIs
#: (reclaim_below scans every record against the published floor before
#: an unlink; truncate_shard is the handoff drop).  A file deletion
#: anywhere else is either a durability bug waiting to happen (WAL or
#: checkpoint data silently removed outside the floor discipline —
#: ISSUE 8) or a deliberate temp/sidecar cleanup that must say so with a
#: ``# reclaim-ok: <reason>`` note.
_RECLAIM_OWNER = os.path.join("antidote_tpu", "log") + os.sep
_RECLAIM_TOKENS = ("os.remove(", "os.unlink(", "rmtree(")


def _check_reclaim_policy(path, lines, problems) -> None:
    """Reject file deletion (``os.remove``/``os.unlink``/``rmtree``)
    outside ``antidote_tpu/log/`` without a ``# reclaim-ok: <reason>``
    annotation on the line or within the three preceding lines — WAL and
    checkpoint files are only ever reclaimed through the guarded floor
    APIs."""
    norm = os.path.normpath(path)
    if _RECLAIM_OWNER in norm or os.sep + "tests" + os.sep in norm \
            or norm.startswith("tests" + os.sep) \
            or os.path.basename(norm) == "lint.py":  # the rule's source
        return

    def annotated(lineno: int) -> bool:
        lo = max(0, lineno - 4)
        return any("reclaim-ok:" in ln for ln in lines[lo:lineno])

    for i, ln in enumerate(lines, start=1):
        code = ln.split("#", 1)[0]
        for tok in _RECLAIM_TOKENS:
            if tok in code and not annotated(i) and "reclaim-ok:" not in ln:
                problems.append(
                    f"{path}:{i}: file deletion '{tok}' outside "
                    "antidote_tpu/log/ — WAL/checkpoint reclaim must go "
                    "through the guarded floor APIs (LogManager."
                    "reclaim_below / truncate_shard), or justify with "
                    "'# reclaim-ok: <reason>'"
                )


#: the replica plane (interdc/ — follower + peer replicas): a serving
#: epoch published there claims "every op ≤ this VC has applied", and a
#: follower stamping one AHEAD of its applied clock (e.g. from the
#: owner's commit counter) is a silent causal-violation machine —
#: session reads would be told their token is covered by data that
#: never arrived.  Publishes in this plane must ride
#: FollowerReplica.publish_applied_epoch_locked (which slaves the
#: counter to the applied clock first) or carry a written
#: ``# vc-stamped: <why the VC is the applied clock>`` justification.
_EPOCH_STAMP_PLANE = os.path.join("antidote_tpu", "interdc")
_EPOCH_STAMP_TOKENS = ("publish_serving_epoch(",
                       "_publish_serving_epoch_locked(")


def _check_epoch_stamp(path, lines, problems) -> None:
    """In interdc/ (follower/replica paths), reject serving-epoch
    publishes that bypass the applied-VC stamp: flag the publish calls
    unless a ``# vc-stamped:`` annotation on the line or within the
    three preceding lines states why the published VC is exactly the
    applied clock."""
    norm = os.path.normpath(path)
    if _EPOCH_STAMP_PLANE not in norm:
        return

    def annotated(lineno: int) -> bool:
        lo = max(0, lineno - 4)
        return any("vc-stamped:" in ln for ln in lines[lo:lineno])

    for i, ln in enumerate(lines, start=1):
        code = ln.split("#", 1)[0]
        for tok in _EPOCH_STAMP_TOKENS:
            if tok in code and not annotated(i) and "vc-stamped:" not in ln:
                problems.append(
                    f"{path}:{i}: serving-epoch publish '{tok}' in the "
                    "interdc/follower plane without the applied-VC "
                    "stamp — route through FollowerReplica."
                    "publish_applied_epoch_locked, or justify with "
                    "'# vc-stamped: <reason>'"
                )


#: the one module allowed to drop device table rows freely: the cold
#: tier owns the evict lifecycle (ISSUE 13) and its calls run behind the
#: verified-coverage checks (live head_vc byte-equal to the anchor
#: sidecar's stamp).  A ``.evict_rows(`` call anywhere else is either a
#: data-loss bug waiting to happen (a device row dropped with no sidecar
#: covering it) or a deliberate compose/heal step that must say so with
#: an ``# evict-ok: <reason>`` note.
_EVICT_OWNER = os.path.join("antidote_tpu", "store", "coldtier.py")
_EVICT_DEF = os.path.join("antidote_tpu", "store", "typed_table.py")


def _check_evict_policy(path, lines, problems) -> None:
    """Reject ``.evict_rows(`` outside store/coldtier.py (and its
    defining module) without an ``# evict-ok: <reason>`` annotation on
    the line or within the three preceding lines — cold-tier
    device-buffer drops go through the guarded evict API with written
    justification."""
    norm = os.path.normpath(path)
    if norm.endswith(_EVICT_OWNER) or norm.endswith(_EVICT_DEF) \
            or os.sep + "tests" + os.sep in norm \
            or norm.startswith("tests" + os.sep) \
            or os.path.basename(norm) == "lint.py":  # the rule's source
        return

    def annotated(lineno: int) -> bool:
        lo = max(0, lineno - 4)
        return any("evict-ok:" in ln for ln in lines[lo:lineno])

    for i, ln in enumerate(lines, start=1):
        code = ln.split("#", 1)[0]
        if ".evict_rows(" in code and not annotated(i) \
                and "evict-ok:" not in ln:
            problems.append(
                f"{path}:{i}: device-row drop '.evict_rows(' outside "
                "the cold tier — route it through store/coldtier.py's "
                "guarded evict (verified sidecar coverage), or justify "
                "with '# evict-ok: <reason>'"
            )


#: the serving front-end's socket I/O belongs to the native plane
#: (proto/cpp/frontend.cc — accept, framing, hot decode, whole-batch
#: hits all off the GIL, ISSUE 16).  A raw ``.recv(`` / ``.sendall(``
#: creeping back into server.py's hot stages quietly re-serializes the
#: serving path behind the GIL; the surviving Python sites (the
#: socketserver fallback plane) must say which plane they are with a
#: ``# py-socket-ok: <reason>`` note.
_PY_SOCKET_FILE = os.path.join("antidote_tpu", "proto", "server.py")


def _check_py_socket(path, lines, problems) -> None:
    """Reject raw ``.recv(`` / ``.sendall(`` in proto/server.py without
    a ``# py-socket-ok: <reason>`` annotation on the line or within the
    three preceding lines — socket I/O on the serving path lives in the
    native front-end; Python-plane sites carry written justification."""
    norm = os.path.normpath(path)
    if not norm.endswith(_PY_SOCKET_FILE):
        return

    def annotated(lineno: int) -> bool:
        lo = max(0, lineno - 4)
        return any("py-socket-ok:" in ln for ln in lines[lo:lineno])

    for i, ln in enumerate(lines, start=1):
        code = ln.split("#", 1)[0]
        if (".recv(" in code or ".sendall(" in code) \
                and not annotated(i) and "py-socket-ok:" not in ln:
            problems.append(
                f"{path}:{i}: raw socket I/O in the serving front-end "
                "— the native plane (proto/cpp/frontend.cc) owns "
                "accept/framing/replies; a Python-plane site must "
                "justify with '# py-socket-ok: <reason>'"
            )


#: tenant-labeled metrics (ISSUE 19): a ``tenant=`` label fed from the
#: wire (a client-chosen string) is an unbounded-cardinality leak — one
#: hostile client mints one Prometheus series per request.  Every
#: tenant-labeled ``.inc(``/``.set(``/``.observe(`` in the package must
#: clamp its value through the bounded TenantRegistry label set
#: (``registry.label(...)`` / a ``TenantLanes`` lane name) and say so
#: with a ``# tenant-label-ok: <where the value was clamped>`` note on
#: the line or within the three preceding lines.
_TENANT_LABEL_PLANE = "antidote_tpu" + os.sep
_TENANT_METRIC_METHODS = ("inc", "set", "observe")


def _check_tenant_labels(tree, path, lines, problems) -> None:
    """Reject metric calls carrying a ``tenant=`` label unless annotated
    ``# tenant-label-ok:`` — the label value must come from the bounded
    TenantRegistry set, never straight from the wire."""
    norm = os.path.normpath(path)
    if not (norm.startswith(_TENANT_LABEL_PLANE)
            or os.sep + _TENANT_LABEL_PLANE in norm) \
            or os.path.basename(norm) == "tenancy.py":  # defines the clamp
        return

    def annotated(lineno: int) -> bool:
        lo = max(0, lineno - 4)
        return any("tenant-label-ok:" in ln for ln in lines[lo:lineno])

    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        if not (isinstance(node.func, ast.Attribute)
                and node.func.attr in _TENANT_METRIC_METHODS):
            continue
        if not any(k.arg == "tenant" for k in node.keywords):
            continue
        if not annotated(node.lineno):
            problems.append(
                f"{path}:{node.lineno}: tenant-labeled metric without a "
                "'# tenant-label-ok:' note — clamp the value through "
                "the bounded TenantRegistry label set "
                "(registry.label(...)) and annotate where it was clamped"
            )


def _broad_handler(h: ast.ExceptHandler) -> bool:
    return h.type is None or (
        isinstance(h.type, ast.Name)
        and h.type.id in ("Exception", "BaseException")
    )


def _check_swallow_loops(tree, path, noqa, problems) -> None:
    """Flag broad ``except``s whose entire body is ``continue`` when the
    nearest enclosing loop is a ``while`` — the swallow-and-spin shape
    that turns a crashed thread loop into a silent zombie.  ``for``
    loops are exempt (bounded retries over peers/attempts), as is any
    handler that records/raises/logs before continuing."""

    def visit(node, in_while):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, ast.ExceptHandler) and in_while:
                body = [s for s in child.body
                        if not isinstance(s, ast.Pass)]
                if (_broad_handler(child) and body
                        and all(isinstance(s, ast.Continue) for s in body)
                        and not noqa(child.lineno)):
                    problems.append(
                        f"{path}:{child.lineno}: broad except-and-continue "
                        "inside a while loop (silently swallows every "
                        "fault forever; surface it or supervise the loop)"
                    )
            nw = in_while
            if isinstance(child, ast.While):
                nw = True
            elif isinstance(child, (ast.For, ast.AsyncFor, ast.FunctionDef,
                                    ast.AsyncFunctionDef, ast.Lambda)):
                nw = False  # continue targets the inner loop / new scope
            visit(child, nw)

    visit(tree, False)


def main(argv):
    paths = argv[1:] or ["antidote_tpu", "tests", "bench.py",
                         "bench_suite.py", "bench_wire.py", "tpu_smoke.py",
                         "__graft_entry__.py", "tools"]
    all_problems = []
    n = 0
    for path in iter_py(paths):
        n += 1
        all_problems.extend(check_file(path))
    for p in all_problems:
        print(p)
    print(f"lint: {n} files, {len(all_problems)} problem(s)",
          file=sys.stderr)
    return 1 if all_problems else 0


if __name__ == "__main__":
    sys.exit(main(sys.argv))
