#!/usr/bin/env python
"""Mesh serving-plane benchmark: 1/2/4/8-device serving-read curves
(ISSUE 10).

Each device count runs in a FRESH subprocess (its own XLA backend with
8 forced virtual CPU devices, mesh over the first N), so compile caches
and device state never bleed between curves:

  populate  — N counter keys through apply_effects (the mesh placement
              path), one serving-epoch publish
  measure   — merged epoch-read batches (launch + finish — exactly the
              wire dispatcher/writeback split) for a fixed window;
              per-batch gather-launch and fold/materialize stage times
              recorded separately
  extras    — stable-time pmin collective latency (forced cache
              misses), per-shard incremental publish cost for a
              one-shard burst, and a value-parity spot check against
              the locked read path

The parent freezes BENCH_MESH_cpu.json.  STRUCTURAL gates only
(--assert-bounds): every curve present, nonzero throughput, parity
clean, burst publish rows == dirty rows (never table size).  Never a
throughput ratchet — this 2-core shared container cannot hold one (see
host_note); the ROADMAP ≥6x 1→8-device target is the REAL-TPU success
metric, with this CPU-container curve as the frozen proxy.

Usage:
  python tools/bench_mesh.py --smoke --assert-bounds       # CI gate
  python tools/bench_mesh.py --json BENCH_MESH_cpu.json    # freeze
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import time

_T0 = time.time()
_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)

DEVICE_CURVE = (1, 2, 4, 8)

HOST_NOTE = (
    "2-core shared CPU container: the 8 'devices' are XLA host-platform "
    "threads multiplexed over 2 cores with co-tenant load (adjacent "
    "windows swing several x — see BENCH_WIRE host_note), so the curve "
    "measures the mesh plane's STRUCTURE (routed shard-local gathers, "
    "per-shard publishes, pmin collective), not chip scaling.  The "
    "ROADMAP item-3 success metric — device-kernel reads/s scaling "
    ">=6x from 1->8 devices — is judged on real ICI-connected TPU "
    "hardware; this artifact is the frozen CPU proxy."
)


def log(*a):
    print(f"[mesh {time.time() - _T0:6.1f}s]", *a, file=sys.stderr,
          flush=True)


# ---------------------------------------------------------------------------
# child: one device count, one fresh backend
# ---------------------------------------------------------------------------
def run_child(n_dev: int, n_keys: int, window_s: float,
              batch: int) -> dict:
    import numpy as np

    from antidote_tpu.config import (AntidoteConfig,
                                     enable_compilation_cache)

    enable_compilation_cache()
    from antidote_tpu.crdt import get_type
    from antidote_tpu.obs import NodeMetrics
    from antidote_tpu.parallel import MeshServingPlane
    from antidote_tpu.store.kv import Effect, KVStore

    cfg = AntidoteConfig(
        n_shards=8, max_dcs=2,
        keys_per_table=max(n_keys, 1024), batch_buckets=(64, 512, 4096),
    )
    plane = MeshServingPlane(cfg, n_dev)
    store = KVStore(cfg, sharding=plane.sharding)
    store.metrics = NodeMetrics()
    plane.attach(store)
    ty = get_type("counter_pn")
    aw, bw = ty.eff_a_width(cfg), ty.eff_b_width(cfg)

    t0 = time.monotonic()
    counter = 0
    chunk = 4096
    for lo in range(0, n_keys, chunk):
        keys = range(lo, min(lo + chunk, n_keys))
        effs = [Effect(k, "counter_pn", "b",
                       np.full(aw, (k % 97) + 1, np.int64),
                       np.zeros(bw, np.int32)) for k in keys]
        vcs = []
        for _ in keys:
            counter += 1
            vcs.append(np.asarray([counter, 0], np.int32))
        store.apply_effects(effs, vcs, [0] * len(effs))
    populate_s = time.monotonic() - t0
    store.publish_serving_epoch(store.dc_max_vc())

    rng = np.random.default_rng(11)

    def one_batch():
        ks = rng.integers(0, n_keys, size=batch)
        objs = [(int(k), "counter_pn", "b") for k in ks]
        ep = store.pin_serving_epoch()
        t1 = time.monotonic()
        pending, fb = store.epoch_read_launch(objs, ep)
        t2 = time.monotonic()
        vals = store.epoch_read_finish(pending)
        t3 = time.monotonic()
        store.unpin_serving_epoch(ep)
        assert not fb
        return len(vals), t2 - t1, t3 - t2

    # shape warm: bucket-family compiles land before the window
    for _ in range(3):
        one_batch()
    n_reads = 0
    launch_s = fold_s = 0.0
    t_end = time.monotonic() + window_s
    t_start = time.monotonic()
    batches = 0
    while time.monotonic() < t_end:
        n, dl, df = one_batch()
        n_reads += n
        launch_s += dl
        fold_s += df
        batches += 1
    elapsed = time.monotonic() - t_start

    # parity spot check vs the locked read path
    ks = rng.integers(0, n_keys, size=min(256, n_keys))
    objs = [(int(k), "counter_pn", "b") for k in ks]
    ep = store.pin_serving_epoch()
    pending, fb = store.epoch_read_launch(objs, ep)
    got = store.epoch_read_finish(pending)
    store.unpin_serving_epoch(ep)
    want = store.read_values(objs, store.dc_max_vc())
    parity_ok = (not fb) and got == want

    # stable-time pmin collective: force cache misses
    pmin_us = []
    for i in range(10):
        store.applied_vc[0, 0] += 1
        t1 = time.monotonic()
        store.stable_vc()
        pmin_us.append((time.monotonic() - t1) * 1e6)
    pmin_us.sort()

    # per-shard incremental publish: one-shard burst (two publishes
    # drain the cross-window scatter set first)
    def burst(keys):
        nonlocal counter
        effs = [Effect(int(k), "counter_pn", "b",
                       np.full(aw, 1, np.int64), np.zeros(bw, np.int32))
                for k in keys]
        vcs = []
        for _ in keys:
            counter += 1
            vcs.append(np.asarray([counter, 0], np.int32))
        store.apply_effects(effs, vcs, [0] * len(effs))

    burst([8 * i + 3 for i in range(16)])   # shard 3
    store.publish_serving_epoch(store.dc_max_vc())
    burst([8 * i + 3 for i in range(16)])
    store.publish_serving_epoch(store.dc_max_vc())
    burst([8 * i + 3 for i in range(16)])
    m = store.metrics
    before = dict(m.mesh_publish.snapshot())
    t1 = time.monotonic()
    store.publish_serving_epoch(store.dc_max_vc())
    burst_publish_ms = (time.monotonic() - t1) * 1e3
    delta = {k[0]: v - before.get(k, 0)
             for k, v in m.mesh_publish.snapshot().items()}
    burst_rows = {k: int(v) for k, v in delta.items() if v}

    return {
        "n_devices": n_dev,
        "n_keys": n_keys,
        "batch": batch,
        "reads_per_s": round(n_reads / elapsed, 1),
        "batches": batches,
        "gather_launch_us_mean": round(launch_s / max(batches, 1) * 1e6,
                                       1),
        "fold_materialize_us_mean": round(fold_s / max(batches, 1) * 1e6,
                                          1),
        "stable_pmin_us_p50": round(pmin_us[len(pmin_us) // 2], 1),
        "burst_publish_rows_by_shard": burst_rows,
        "burst_publish_ms": round(burst_publish_ms, 2),
        "populate_s": round(populate_s, 2),
        "parity_ok": bool(parity_ok),
    }


# ---------------------------------------------------------------------------
# parent: curve over device counts, artifact freeze, structural gates
# ---------------------------------------------------------------------------
def run_parent(args) -> int:
    results = {}
    for n_dev in DEVICE_CURVE:
        log(f"curve point: {n_dev} device(s)")
        env = dict(os.environ)
        env["JAX_PLATFORMS"] = "cpu"
        flags = env.get("XLA_FLAGS", "")
        if "xla_force_host_platform_device_count" not in flags:
            env["XLA_FLAGS"] = (
                flags + " --xla_force_host_platform_device_count=8"
            ).strip()
        out = subprocess.run(
            [sys.executable, os.path.abspath(__file__), "--one",
             str(n_dev), "--keys", str(args.keys), "--window",
             str(args.window), "--batch", str(args.batch)],
            capture_output=True, text=True, cwd=_REPO, env=env,
            timeout=1800,
        )
        if out.returncode != 0:
            log(f"child {n_dev} FAILED:\n{out.stderr[-2000:]}")
            return 1
        results[str(n_dev)] = json.loads(out.stdout.strip().splitlines()[-1])
        log(f"  -> {results[str(n_dev)]['reads_per_s']} reads/s")

    r1 = results["1"]["reads_per_s"]
    r8 = results["8"]["reads_per_s"]
    artifact = {
        "metric": "mesh_serving_read_scaling",
        "unit": "epoch-plane reads/s by mesh device count",
        "driver_rev": 1,
        "curves": results,
        "scaling_1_to_8": round(r8 / r1, 2) if r1 else None,
        "target": {
            "metric": "device-kernel reads/s scale >=6x from 1->8 "
                      "devices on real TPU (ROADMAP item 3); >=10x vs "
                      "BASELINE.json when hardware is available",
            "cpu_proxy": "this artifact freezes the container curve; "
                         "never gated on throughput",
        },
        "host_note": HOST_NOTE,
        "smoke": bool(args.smoke),
        "created_at": time.time(),
    }
    if args.json:
        path = os.path.join(_REPO, args.json)
        with open(path, "w") as f:
            json.dump(artifact, f, indent=1)
            f.write("\n")
        log(f"froze {args.json}")
    else:
        print(json.dumps(artifact, indent=1))
    if args.assert_bounds:
        # STRUCTURAL gates only (never a throughput ratchet)
        for n_dev in DEVICE_CURVE:
            r = results[str(n_dev)]
            assert r["reads_per_s"] > 0, (n_dev, "zero throughput")
            assert r["parity_ok"], (n_dev, "mesh/locked parity broke")
            rows = r["burst_publish_rows_by_shard"]
            assert set(rows) == {"3"}, (
                n_dev, "burst republished beyond its shard", rows)
            assert rows["3"] <= 64, (
                n_dev, "burst publish cost not ∝ dirty rows", rows)
        log("structural gates OK")
    return 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--one", type=int, default=0,
                    help="(internal) run one child curve point")
    ap.add_argument("--keys", type=int, default=65536)
    ap.add_argument("--window", type=float, default=3.0)
    ap.add_argument("--batch", type=int, default=1024)
    ap.add_argument("--smoke", action="store_true",
                    help="small keys + short window (CI)")
    ap.add_argument("--assert-bounds", action="store_true")
    ap.add_argument("--json", default=None,
                    help="freeze the artifact to this repo-relative path")
    args = ap.parse_args(argv)
    if args.smoke and args.keys == 65536:
        args.keys, args.window = 8192, 1.0
    if args.one:
        from antidote_tpu.config import apply_jax_platform_env

        apply_jax_platform_env()
        print(json.dumps(run_child(args.one, args.keys, args.window,
                                   args.batch)))
        return 0
    return run_parent(args)


if __name__ == "__main__":
    sys.exit(main())
