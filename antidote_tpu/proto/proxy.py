"""Server-side proxy / forwarding plane: the symmetric serving fabric.

ISSUE 17 — any node is a safe entrypoint.  The riak_core reference lets
ANY node coordinate a request (``log_utilities:get_key_partition`` →
``riak_core_vnode_master:command`` from whichever node the client hit,
SURVEY L1); here the same role lands on the follower fleet:

* **Read proxying** — a follower receiving a session read outside its
  ring arcs relays it to the arc owner over a pooled internal channel
  (one hop max: the proxied request carries a ``proxied`` no-reproxy
  flag, and a node serving a proxied frame answers locally or refuses
  typed — it never proxies again).
* **Write forwarding** — a follower receiving a write/txn forwards it
  to the owner write plane under the at-most-once ``request_sent``
  discipline: send-phase transport failures redial within a bounded
  budget, reply-phase failures surface the typed
  :class:`~antidote_tpu.overload.ForwardFailed` ("may have executed"),
  never a blind resend of a non-idempotent commit.  Forwarded work
  re-enters the owner's admission gate and re-checks its (shrunken)
  deadline there, so a proxy hop can never amplify overload.
* **Fleet health** — the client tier's DEAD_S endpoint cooldown and
  seeded-jittered failover (PR 11) move server-side into
  :class:`FleetHealth`: the owner's liveness registry (piggybacked on
  every ``follower_report`` reply) merged with this node's own
  connect/timeout observations.  When a proxy target dies mid-request
  the forwarding node fails over to a live shadow of the arc itself —
  a bare apb client pointed at one arbitrary follower gets the same
  RYW failover the native SessionClient implements client-side.

The plane proxies at the SEMANTIC level (objects/updates/clock), always
over native-dialect pooled channels — an apb edge request is decoded
once, forwarded native, and re-encoded, so both dialects share one
failover loop and one fault site (``proxy.forward``).
"""

from __future__ import annotations

import os
import threading
import time
from typing import Dict, List, Optional, Tuple

from antidote_tpu import faults as _faults
from antidote_tpu.overload import (
    BusyError,
    TenantBusyError,
    ColdMiss,
    DeadlineExceeded,
    ForwardFailed,
    InsufficientRightsError,
    NotOwnerError,
    ReadOnlyError,
    ReplicaLagging,
    check_deadline,
)
from antidote_tpu.proto.client import (
    AntidoteClient,
    HashRing,
    RemoteAbort,
    RemoteBusy,
    RemoteColdMiss,
    RemoteDeadline,
    RemoteError,
    RemoteInsufficientRights,
    RemoteLagging,
    RemoteNotOwner,
    RemoteReadOnly,
    RemoteTenantBusy,
)

Addr = Tuple[str, int]


class ProxyExhausted(Exception):
    """Internal: every candidate hop of a proxied READ failed (dead,
    refused, or fault-injected).  The serving path catches this and
    falls back to a terminal LOCAL attempt — whose typed gate error is
    the honest last resort the client sees.  Never crosses the wire."""

    def __init__(self, last: Optional[BaseException]):
        super().__init__(str(last) if last is not None else "no candidates")
        self.last = last


def _rethrow(e: BaseException) -> None:
    """Map a pooled channel's client-side ``Remote*`` error back to the
    server-side typed exception vocabulary, so the edge reply encodes
    exactly what the owner answered (both dialects' error mappers key
    on these types)."""
    from antidote_tpu.txn.manager import AbortError

    if isinstance(e, RemoteTenantBusy):
        # preserve the tenant attribution across the hop: the edge
        # reply must still say WHICH lane refused, not "node busy"
        raise TenantBusyError(str(e), tenant=e.tenant,
                              retry_after_ms=e.retry_after_ms) from e
    if isinstance(e, RemoteBusy):
        raise BusyError(str(e), e.retry_after_ms) from e
    if isinstance(e, RemoteDeadline):
        raise DeadlineExceeded(str(e)) from e
    if isinstance(e, RemoteAbort):
        raise AbortError(str(e)) from e
    if isinstance(e, RemoteReadOnly):
        raise ReadOnlyError(str(e)) from e
    if isinstance(e, RemoteColdMiss):
        raise ColdMiss(str(e), e.retry_after_ms, permanent=e.permanent) from e
    if isinstance(e, RemoteLagging):
        raise ReplicaLagging(str(e), e.retry_after_ms,
                             redirect=e.redirect) from e
    if isinstance(e, RemoteNotOwner):
        raise NotOwnerError(e.redirect) from e
    if isinstance(e, RemoteInsufficientRights):
        raise InsufficientRightsError(str(e), e.retry_after_ms) from e
    raise RuntimeError(str(e)) from e


class FleetHealth:
    """A node's live view of the serving fleet: the owner registry's
    typed states (learned from ``follower_report`` replies) merged with
    LOCAL connect/timeout observations under a bounded cooldown — the
    server-side twin of SessionClient's ``_dead`` map.  Placement rides
    the same unseeded :class:`HashRing` every client uses (fleet-wide
    agreement on arc owners); the failover tail is seeded per NODE so
    a dead endpoint's arcs spread over the survivors instead of every
    proxying node stampeding the same shadow."""

    #: a locally-observed-dead endpoint is skipped for this long before
    #: its arcs are retried (the registry may still say "ok" for up to
    #: REPLICA_DOWN_S — local observations win in the meantime)
    DEAD_S = 2.0

    def __init__(self, vnodes: int = 64, seed: Optional[int] = None,
                 metrics=None):
        if seed is None:
            seed = int.from_bytes(os.urandom(8), "big")
        self.vnodes = int(vnodes)
        self.seed = int(seed)
        self.metrics = metrics
        self._lock = threading.Lock()
        #: addr -> monotonic time until which it is locally dead
        self._dead: Dict[Addr, float] = {}
        #: addr -> registry state (ok | lagging | down | bootstrapping…)
        self._states: Dict[Addr, str] = {}
        self.ring = HashRing((), vnodes=self.vnodes, seed=self.seed)

    # -- registry feed --------------------------------------------------
    def update_fleet(self, followers: Dict[str, dict]) -> None:
        """Absorb one registry snapshot (``name -> {addr, state}``).
        The ring is rebuilt only when the serving membership actually
        changed — snapshots arrive once per report interval."""
        eps: List[Addr] = []
        states: Dict[Addr, str] = {}
        for _name, ent in sorted((followers or {}).items()):
            addr = ent.get("addr")
            if not addr:
                continue
            ep = (addr[0], int(addr[1]))
            states[ep] = str(ent.get("state", "ok"))
            if states[ep] != "down":
                eps.append(ep)
        with self._lock:
            self._states = states
            if eps != self.ring.endpoints:
                self.ring = HashRing(eps, vnodes=self.vnodes,
                                     seed=self.seed)
        if self.metrics is not None:
            for ep, st in states.items():
                self.metrics.fleet_health.set(
                    0 if (st == "down" or not self.alive(ep)) else 1,
                    endpoint=f"{ep[0]}:{ep[1]}")

    # -- local observations ---------------------------------------------
    def mark_dead(self, ep: Addr) -> None:
        with self._lock:
            self._dead[ep] = time.monotonic() + self.DEAD_S
        if self.metrics is not None:
            self.metrics.fleet_health.set(0, endpoint=f"{ep[0]}:{ep[1]}")

    def mark_ok(self, ep: Addr) -> None:
        with self._lock:
            was_dead = self._dead.pop(ep, None) is not None
        if was_dead and self.metrics is not None:
            self.metrics.fleet_health.set(1, endpoint=f"{ep[0]}:{ep[1]}")

    def alive(self, ep: Addr) -> bool:
        with self._lock:
            until = self._dead.get(ep)
            if until is not None:
                if until > time.monotonic():
                    return False
                del self._dead[ep]  # cooldown over: arcs come back
            return self._states.get(ep, "ok") != "down"

    # -- routing --------------------------------------------------------
    def preferred(self, key, bucket) -> Optional[Addr]:
        with self._lock:
            ring = self.ring
        return ring.preferred(key, bucket)

    def candidates(self, key, bucket) -> List[Addr]:
        """Alive-filtered failover order for one key's arc: preferred
        first (fleet-wide agreement), then this node's seeded-jitter
        survivor order."""
        with self._lock:
            ring = self.ring
        return [ep for ep in ring.order(key, bucket) if self.alive(ep)]

    def snapshot(self) -> dict:
        with self._lock:
            now = time.monotonic()
            return {
                "endpoints": [f"{h}:{p}" for h, p in self.ring.endpoints],
                "states": {f"{h}:{p}": s
                           for (h, p), s in sorted(self._states.items())},
                "locally_dead": [f"{h}:{p}"
                                 for (h, p), t in sorted(self._dead.items())
                                 if t > now],
            }


class ProxyPlane:
    """Pooled, deadline-bounded internal channels from one serving node
    to the rest of the fleet, plus the forwarding state machines on top
    of them.  One instance per follower :class:`ProtocolServer`."""

    #: idle channels kept per target (each borrow past this dials)
    POOL_PER_TARGET = 4
    #: dial/IO timeout of an internal channel (the per-request deadline
    #: still shrinks the forwarded budget below this)
    DIAL_TIMEOUT_S = 5.0
    #: send-phase redials of a forwarded write before the typed refusal
    FORWARD_ATTEMPTS = 2

    def __init__(self, follower, metrics, vnodes: int = 64,
                 seed: Optional[int] = None):
        self.follower = follower
        self.metrics = metrics
        self.health = FleetHealth(vnodes=vnodes, seed=seed,
                                  metrics=metrics)
        self._pool_lock = threading.Lock()
        #: bounded-by: POOL_PER_TARGET idle channels per target addr
        self._pools: Dict[Addr, List[AntidoteClient]] = {}
        #: sticky owner channel for interactive txns: the owner's txn
        #: registry is global across connections, and the owner's own
        #: conn-drop discipline aborts whatever a dead channel orphans
        self._txn_lock = threading.Lock()
        self._txn_chan: Optional[AntidoteClient] = None
        #: txids forwarded through the sticky channel and not yet
        #: finished — an edge client dying mid-txn aborts these at the
        #: owner (the follower-side twin of _abort_orphan)
        self.forwarded_txns: set = set()
        self._fleet_v = object()  # always != first observed version
        self._closed = False
        #: forwarded-traffic counters for node_status / the bench gate
        self._stats_lock = threading.Lock()
        self.counts: Dict[str, int] = {
            "read": 0, "write": 0, "txn": 0, "failover": 0}

    # -- fleet plumbing -------------------------------------------------
    def _refresh(self) -> None:
        fol = self.follower
        v = getattr(fol, "fleet_table_v", 0)
        if v != self._fleet_v:
            self._fleet_v = v
            self.health.update_fleet(getattr(fol, "fleet_table", None)
                                     or {})

    def _self_addr(self) -> Optional[Addr]:
        addr = getattr(self.follower, "client_addr", None)
        return (addr[0], int(addr[1])) if addr else None

    def _owner_addr(self) -> Optional[Addr]:
        addr = getattr(self.follower, "owner_client_addr", None)
        return (addr[0], int(addr[1])) if addr else None

    def route(self, objects) -> Optional[Addr]:
        """The arc owner a read should serve from, or None when this
        node should serve it locally (in-arc, unknown fleet, or no
        self-identity yet).  The first object's key owns the routing —
        a multi-object session read is one snapshot unit."""
        self._refresh()
        me = self._self_addr()
        if me is None or not objects:
            return None
        key, _t, bucket = objects[0]
        pref = self.health.preferred(key, bucket)
        if pref is None or pref == me or not self.health.alive(pref):
            return None
        return pref

    def ring_hint(self) -> Optional[dict]:
        """The fleet+arcs hint attached to proxied replies and typed
        follower errors: capable clients rebuild their ring from it in
        place and converge back to zero-hop."""
        self._refresh()
        owner = self._owner_addr()
        eps = self.health.ring.endpoints
        if owner is None and not eps:
            return None
        return {
            "owner": list(owner) if owner else None,
            "followers": [[h, p] for h, p in eps],
            "vnodes": self.health.vnodes,
        }

    # -- channel pool ---------------------------------------------------
    def _borrow(self, ep: Addr) -> AntidoteClient:
        with self._pool_lock:
            lst = self._pools.get(ep)
            if lst:
                return lst.pop()
        return AntidoteClient(ep[0], ep[1], timeout=self.DIAL_TIMEOUT_S)

    def _return(self, ep: Addr, c: AntidoteClient) -> None:
        with self._pool_lock:
            if not self._closed:
                lst = self._pools.setdefault(ep, [])
                if len(lst) < self.POOL_PER_TARGET:
                    lst.append(c)
                    return
        c.close()

    @staticmethod
    def _scrap(c: AntidoteClient) -> None:
        try:
            c.close()
        except OSError:
            pass

    @staticmethod
    def _remaining_ms(deadline: Optional[float]) -> Optional[float]:
        """The deadline budget LEFT for the inner hop — the forwarded
        request re-checks it at the target, so queue time spent here is
        never granted back (deadline propagation, not reset)."""
        if deadline is None:
            return None
        return max(1.0, (deadline - time.monotonic()) * 1e3)

    def _count(self, kind: str, failed_hops: int = 0) -> None:
        with self._stats_lock:
            self.counts[kind] += 1
            if failed_hops:
                self.counts["failover"] += 1

    def _fault(self, ep: Addr) -> Optional[str]:
        """Consult the ``proxy.forward`` chaos site for one hop.  Keyed
        by the target ``"host:port"``: drop = hop is dead, error =
        send-phase transport failure, delay = slow link."""
        d = _faults.hit("proxy.forward", key=f"{ep[0]}:{ep[1]}")
        if d is None:
            return None
        if d.action == "delay":
            time.sleep(float(d.arg or 0.01))
            return None
        return d.action

    # -- read proxying --------------------------------------------------
    def proxy_read(self, objects, clock, deadline: Optional[float],
                   first: Optional[Addr] = None,
                   tenant: Optional[str] = None):
        """Relay a read to the arc owner, failing over server-side
        through the arc's live shadows and the owner.  Returns
        ``(values, commit_clock)`` exactly as the target answered;
        raises :class:`ProxyExhausted` when every hop failed (the
        caller's terminal local attempt owns the last-resort typed
        error) — deterministic refusals (deadline, abort, cold-miss)
        re-raise immediately instead of burning hops."""
        self._refresh()
        check_deadline(deadline, "proxy read")
        me = self._self_addr()
        cands: List[Addr] = []
        if first is not None:
            cands.append(first)
        if objects:
            key, _t, bucket = objects[0]
            for ep in self.health.candidates(key, bucket):
                if ep != me and ep not in cands:
                    cands.append(ep)
        owner = self._owner_addr()
        if owner is not None and owner != me and owner not in cands:
            cands.append(owner)
        last: Optional[BaseException] = None
        failed = 0
        for ep in cands:
            check_deadline(deadline, "proxy read hop")
            act = self._fault(ep)
            if act is not None:
                self.health.mark_dead(ep)
                last = ConnectionError(f"proxy.forward fault: {act}")
                failed += 1
                continue
            try:
                c = self._borrow(ep)
            except (ConnectionError, OSError) as e:
                self.health.mark_dead(ep)
                last, failed = e, failed + 1
                continue
            t0 = time.monotonic()
            try:
                vals, vc = c.read_objects(
                    objects, clock=clock,
                    deadline_ms=self._remaining_ms(deadline),
                    proxied=True, tenant=tenant)
            except (RemoteLagging, RemoteNotOwner, RemoteBusy) as e:
                # the hop is up but refused (behind the token / ring
                # disagreement / shedding): try the next shadow — its
                # no-reproxy discipline kept the refusal one hop deep
                self._return(ep, c)
                last, failed = e, failed + 1
                continue
            except (RemoteDeadline, RemoteColdMiss, RemoteAbort,
                    RemoteReadOnly) as e:
                self._return(ep, c)
                _rethrow(e)
            except RemoteError as e:
                self._return(ep, c)
                _rethrow(e)
            except (ConnectionError, OSError) as e:
                self._scrap(c)
                self.health.mark_dead(ep)
                last, failed = e, failed + 1
                continue
            self._return(ep, c)
            self.health.mark_ok(ep)
            self.metrics.proxy_hop_seconds.observe(time.monotonic() - t0)
            self.metrics.proxy_total.inc(
                kind="read", outcome="failover" if failed else "ok")
            self._count("read", failed)
            return vals, vc
        self.metrics.proxy_total.inc(kind="read", outcome="error")
        raise ProxyExhausted(last)

    # -- write forwarding -----------------------------------------------
    def forward_update(self, updates, clock, deadline: Optional[float],
                       tenant: Optional[str] = None):
        """Forward a static write to the owner write plane, at most
        once: dial/send-phase failures redial within the bounded
        budget; a reply-phase failure surfaces the typed
        :class:`ForwardFailed` — the owner may have committed.  Send
        exhaustion surfaces the classic typed ``not_owner`` redirect,
        so a ring-aware client still learns where the owner lives."""
        check_deadline(deadline, "forward write")
        owner = self._owner_addr()
        if owner is None:
            raise NotOwnerError(None)
        last: Optional[BaseException] = None
        for attempt in range(self.FORWARD_ATTEMPTS):
            check_deadline(deadline, "forward write attempt")
            act = self._fault(owner)
            if act is not None:
                # injected hop death BEFORE the send phase: safe redial
                last = ConnectionError(f"proxy.forward fault: {act}")
                continue
            try:
                c = self._borrow(owner)
            except (ConnectionError, OSError) as e:
                last = e  # dial failure: the request never left
                continue
            t0 = time.monotonic()
            try:
                vc = c.update_objects(
                    updates, clock=clock,
                    deadline_ms=self._remaining_ms(deadline),
                    proxied=True, tenant=tenant)
            except (ConnectionError, OSError) as e:
                self._scrap(c)
                if getattr(e, "request_sent", True):
                    self.metrics.proxy_total.inc(kind="write",
                                                 outcome="error")
                    raise ForwardFailed(
                        "forwarded write: the owner connection died "
                        "awaiting the reply — the owner may have "
                        "executed it; not resending (re-read at your "
                        "session token to find out)") from e
                last = e
                continue
            except RemoteError as e:
                # a typed refusal at the owner (busy/deadline/abort/
                # read_only…) passes through verbatim — the proxy adds
                # no retry of its own, so it cannot amplify overload
                self._return(owner, c)
                self.metrics.proxy_total.inc(kind="write",
                                             outcome="refused")
                self._count("write")
                _rethrow(e)
            self._return(owner, c)
            self.metrics.proxy_hop_seconds.observe(time.monotonic() - t0)
            self.metrics.proxy_total.inc(
                kind="write", outcome="failover" if attempt else "ok")
            self._count("write", attempt)
            return vc
        self.metrics.proxy_total.inc(kind="write", outcome="error")
        raise NotOwnerError(owner) if last is None else \
            self._owner_unreachable(owner, last)

    @staticmethod
    def _owner_unreachable(owner: Addr, last: BaseException):
        err = NotOwnerError(owner)
        err.__cause__ = last
        return err

    # -- interactive txn forwarding -------------------------------------
    def txn_call(self, code, body):
        """Forward one interactive-txn op over the sticky owner
        channel and return the decoded reply body.  START redials once
        on a send-phase failure (no txn state exists yet); any later
        op whose channel dies surfaces :class:`ForwardFailed` — the
        owner aborts whatever the dead channel orphaned."""
        from antidote_tpu.proto.codec import MessageCode

        owner = self._owner_addr()
        if owner is None:
            raise NotOwnerError(None)
        with self._txn_lock:
            redialed = False
            while True:
                c = self._txn_chan
                if c is None:
                    try:
                        c = AntidoteClient(owner[0], owner[1],
                                           timeout=self.DIAL_TIMEOUT_S)
                    except (ConnectionError, OSError) as e:
                        self.metrics.proxy_total.inc(kind="txn",
                                                     outcome="error")
                        raise self._owner_unreachable(owner, e)
                    self._txn_chan = c
                t0 = time.monotonic()
                try:
                    resp = c._call(code, body)
                except RemoteError as e:
                    self.metrics.proxy_total.inc(kind="txn",
                                                 outcome="refused")
                    self._count("txn")
                    _rethrow(e)
                except (ConnectionError, OSError) as e:
                    self._txn_chan = None
                    self._scrap(c)
                    safe_redial = (not getattr(e, "request_sent", True)
                                   and code == MessageCode.START_TRANSACTION
                                   and not redialed)
                    if not safe_redial:
                        self.metrics.proxy_total.inc(kind="txn",
                                                     outcome="error")
                        raise ForwardFailed(
                            "forwarded transaction op: the owner "
                            "channel died — the op may have executed "
                            "and the owner aborts orphans of a dead "
                            "channel; restart the transaction") from e
                    redialed = True
                    continue
                self.metrics.proxy_hop_seconds.observe(
                    time.monotonic() - t0)
                self.metrics.proxy_total.inc(kind="txn", outcome="ok")
                self._count("txn")
                return resp

    def abort_forwarded(self, txid) -> None:
        """Best-effort abort of a forwarded txn whose EDGE client died
        (the follower-side twin of the owner's conn-drop rollback)."""
        from antidote_tpu.proto.codec import MessageCode

        self.forwarded_txns.discard(txid)
        try:
            self.txn_call(MessageCode.ABORT_TRANSACTION, {"txid": txid})
        except Exception:
            pass  # the owner's own orphan discipline is the backstop

    # -- observability / lifecycle --------------------------------------
    def stats(self) -> dict:
        self._refresh()  # status must show the CURRENT learned fleet
        with self._stats_lock:
            counts = dict(self.counts)
        return {"forwarded": counts, "fleet": self.health.snapshot()}

    def close(self) -> None:
        with self._pool_lock:
            self._closed = True
            pools, self._pools = self._pools, {}
        for lst in pools.values():
            for c in lst:
                self._scrap(c)
        with self._txn_lock:
            c, self._txn_chan = self._txn_chan, None
        if c is not None:
            self._scrap(c)
