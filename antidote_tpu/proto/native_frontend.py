"""ctypes binding for the native serving front-end (cpp/frontend.cc).

One C++ epoll thread owns the client listen socket: accept, per-conn
read buffers, 4-byte framing, hot-read decode, admission (the
overload.py global/per-host caps + retry hints, natively) and the
whole-batch snapshot-cache fast path all run off the GIL.  Python sees
only cache misses, writes, interactive txns and apb-dialect frames via
one packed batch-drain crossing per wakeup (``take_batch`` — the
``pump_take_batch`` discipline).

The mirror protocol (kv.py pushes, epoch-id-stamped entries):

* ``fill(key, bucket, type_name, value, epoch_id)`` — pushed wherever
  Python itself fills/serves from the snapshot cache (kv.py
  ``snapshot_cache_fill`` + the whole-batch bottom path);
* ``invalidate(key, bucket)`` — pushed EAGERLY under the commit lock for
  every applied effect (kv.py ``_apply_effect_groups_inner``) and from
  ``drop_cached_value`` / ``mark_epoch_fallback``;
* ``advance(epoch_id, vc, clockless_ok)`` — the server's epoch ticker
  after every publish: entries stamped with the previous epoch survive
  (every mutation in between invalidated its keys before publish),
  older ones drop;
* ``reset()`` — ``drop_serving_epoch``: native serving disabled until
  the next advance.

Loading failure falls back to the Python socketserver plane.
"""

from __future__ import annotations

import ctypes
import logging
import os
import pathlib
from typing import Optional

import msgpack

from antidote_tpu import faults
from antidote_tpu.proto.codec import encode_value

log = logging.getLogger(__name__)

_DIR = pathlib.Path(__file__).parent / "cpp"
_SRC = _DIR / "frontend.cc"
_SO = _DIR / "_frontend.so"

_lib = None
_lib_tried = False


def _fallback(reason: Optional[str]) -> None:
    if reason is not None:
        log.warning("native frontend unavailable (%s); falling back to "
                    "the Python socketserver plane", reason)
    try:
        from antidote_tpu.obs.metrics import net_metrics

        net_metrics().frontend_fallback.inc()
    except Exception:
        pass
    return None


def _load_lib():
    global _lib, _lib_tried
    if _lib_tried:
        return _lib
    _lib_tried = True
    try:
        from antidote_tpu import native_build

        native_build.ensure(_SRC, _SO)
        lib = ctypes.CDLL(str(_SO))
        lib.frontend_create.restype = ctypes.c_void_p
        lib.frontend_create.argtypes = [
            ctypes.c_char_p, ctypes.c_int, ctypes.c_int, ctypes.c_long,
            ctypes.c_long, ctypes.c_long,
        ]
        lib.frontend_port.restype = ctypes.c_int
        lib.frontend_port.argtypes = [ctypes.c_void_p]
        lib.frontend_take_batch.restype = ctypes.c_long
        lib.frontend_take_batch.argtypes = [
            ctypes.c_void_p, ctypes.c_char_p, ctypes.c_long,
            ctypes.POINTER(ctypes.c_long), ctypes.c_long, ctypes.c_long,
        ]
        lib.frontend_send.restype = None
        lib.frontend_send.argtypes = [
            ctypes.c_void_p, ctypes.c_long, ctypes.c_char_p,
            ctypes.c_long, ctypes.c_long,
        ]
        lib.frontend_close_conn.restype = None
        lib.frontend_close_conn.argtypes = [ctypes.c_void_p, ctypes.c_long]
        lib.frontend_advance.restype = None
        lib.frontend_advance.argtypes = [
            ctypes.c_void_p, ctypes.c_long, ctypes.c_char_p,
            ctypes.c_long, ctypes.c_int,
        ]
        lib.frontend_fill.restype = None
        lib.frontend_fill.argtypes = [
            ctypes.c_void_p, ctypes.c_char_p, ctypes.c_long,
            ctypes.c_char_p, ctypes.c_long, ctypes.c_char_p,
            ctypes.c_long, ctypes.c_long,
        ]
        lib.frontend_invalidate.restype = None
        lib.frontend_invalidate.argtypes = [
            ctypes.c_void_p, ctypes.c_char_p, ctypes.c_long,
        ]
        lib.frontend_mirror_reset.restype = None
        lib.frontend_mirror_reset.argtypes = [ctypes.c_void_p]
        lib.frontend_set_fast_serve.restype = None
        lib.frontend_set_fast_serve.argtypes = [ctypes.c_void_p,
                                                ctypes.c_int]
        lib.frontend_set_clockless_ok.restype = None
        lib.frontend_set_clockless_ok.argtypes = [ctypes.c_void_p,
                                                  ctypes.c_int]
        lib.frontend_stats.restype = None
        lib.frontend_stats.argtypes = [
            ctypes.c_void_p, ctypes.POINTER(ctypes.c_long), ctypes.c_int,
        ]
        lib.frontend_stop.restype = None
        lib.frontend_stop.argtypes = [ctypes.c_void_p]
        lib.frontend_free.restype = None
        lib.frontend_free.argtypes = [ctypes.c_void_p]
        _lib = lib
    except Exception:
        _lib = None
    return _lib


def _packb(v) -> bytes:
    # the SAME packer settings as codec.encode — fragment-level byte
    # parity with the Python reply path depends on it
    return msgpack.packb(v, use_bin_type=True)


class NativeFrontend:
    """Owns the client listen socket; yields (conn_id, kind, aux,
    payload) frames.  kind 0 = conn closed, 1 = admitted frame,
    2 = admission-shed frame (aux carries the retry hint)."""

    _BATCH = 512

    K_CONN_DROP = 0
    K_FRAME = 1
    K_SHED = 2

    STAT_FIELDS = ("accepted", "closed", "frames", "native_hits",
                   "hit_objects", "sheds", "forwarded", "drains",
                   "mirror_size", "in_flight", "open_conns", "bad_frames")

    def __init__(self, lib, h):
        self._lib = lib
        self._h = h
        self._buf = ctypes.create_string_buffer(1 << 20)
        self._descs = (ctypes.c_long * (4 * self._BATCH))()

    @staticmethod
    def create(host: str, port: int, max_connections: int,
               max_in_flight: int, max_per_host: int,
               mirror_cap: int = 1 << 18) -> Optional["NativeFrontend"]:
        if os.environ.get("ANTIDOTE_NATIVE_FRONTEND", "on") == "off":
            return None
        if faults.hit("native_frontend.load") is not None:
            return _fallback(None)  # injected load failure (chaos tests)
        lib = _load_lib()
        if lib is None:
            return _fallback("compile/load failed")
        h = lib.frontend_create(host.encode(), int(port),
                                int(max_connections), int(max_in_flight),
                                int(max_per_host), int(mirror_cap))
        if not h:
            return _fallback(f"bind/listen on {host}:{port} failed")
        return NativeFrontend(lib, h)

    # -- serving plane --------------------------------------------------
    @property
    def port(self) -> int:
        return int(self._lib.frontend_port(self._h))

    def take_batch(self, timeout_ms: int) -> list:
        """Drain up to _BATCH crossings — [(conn_id, kind, aux,
        payload)], [] after timeout or once stopped."""
        h = self._h  # capture: close() may null the handle concurrently
        if h is None:
            return []
        n = self._lib.frontend_take_batch(h, self._buf,
                                          len(self._buf), self._descs,
                                          self._BATCH, int(timeout_ms))
        if n == -2:
            # head frame alone exceeds the scratch buffer: grow, retake
            need = int(self._descs[2])
            self._buf = ctypes.create_string_buffer(need + 1024)
            return self.take_batch(timeout_ms)
        if n <= 0:
            return []
        d = self._descs
        total = sum(d[i * 4 + 2] for i in range(n))
        raw = ctypes.string_at(self._buf, total)
        out = []
        off = 0
        for i in range(n):
            ln = d[i * 4 + 2]
            out.append((int(d[i * 4]), int(d[i * 4 + 1]),
                        int(d[i * 4 + 3]), raw[off:off + ln]))
            off += ln
        return out

    def send(self, conn_id: int, buf: bytes, admitted: int) -> None:
        """Queue one framed reply (b"" = account only); releases
        ``admitted`` admission slots."""
        h = self._h
        if h is None:
            return
        self._lib.frontend_send(h, int(conn_id), buf, len(buf),
                                int(admitted))

    def close_conn(self, conn_id: int) -> None:
        h = self._h
        if h is not None:
            self._lib.frontend_close_conn(h, int(conn_id))

    # -- mirror protocol ------------------------------------------------
    @staticmethod
    def _mirror_key(key, bucket) -> Optional[bytes]:
        try:
            return _packb(key) + _packb(bucket)
        except Exception:
            return None  # unpackable key shapes are simply never mirrored

    def fill(self, key, bucket, type_name: str, value, epoch_id: int):
        h = self._h
        if h is None:
            return
        k = self._mirror_key(key, bucket)
        if k is None:
            return
        try:
            # the SAME wire shape the Python reply path produces
            # (tuple-keyed CRDT maps ride as tagged pair lists) — the
            # byte-parity contract depends on packing encode_value(v),
            # not v
            val = _packb(encode_value(value))
        except Exception:
            return
        t = _packb(type_name)
        self._lib.frontend_fill(h, k, len(k), t, len(t), val,
                                len(val), int(epoch_id))

    def invalidate(self, key, bucket) -> None:
        h = self._h
        if h is None:
            return
        k = self._mirror_key(key, bucket)
        if k is not None:
            self._lib.frontend_invalidate(h, k, len(k))

    def advance(self, epoch_id: int, vc_list, clockless_ok: bool) -> None:
        h = self._h
        if h is None:
            return
        frag = _packb([int(x) for x in vc_list])
        self._lib.frontend_advance(h, int(epoch_id), frag,
                                   len(frag), 1 if clockless_ok else 0)

    def reset(self) -> None:
        h = self._h
        if h is not None:
            self._lib.frontend_mirror_reset(h)

    def set_fast_serve(self, on: bool) -> None:
        h = self._h
        if h is not None:
            self._lib.frontend_set_fast_serve(h, 1 if on else 0)

    def set_clockless_ok(self, on: bool) -> None:
        h = self._h
        if h is not None:
            self._lib.frontend_set_clockless_ok(h, 1 if on else 0)

    # -- observability / lifecycle -------------------------------------
    def stats(self) -> dict:
        h = self._h
        if h is None:
            return {}
        out = (ctypes.c_long * len(self.STAT_FIELDS))()
        self._lib.frontend_stats(h, out, len(self.STAT_FIELDS))
        return {f: int(v) for f, v in zip(self.STAT_FIELDS, out)}

    def close(self) -> None:
        if self._h is not None:
            h, self._h = self._h, None
            self._lib.frontend_stop(h)
            self._lib.frontend_free(h)
