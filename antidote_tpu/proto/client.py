"""Python client for the wire protocol — the antidotec_pb analogue.

One socket, request/response in lockstep (the reference client multiplexes
the same way: each request waits for its reply before the next —
/root/reference/src/antidote_pb_protocol.erl:51-64 is a strict loop).
"""

from __future__ import annotations

import socket
import threading
from typing import Any, List, Optional, Sequence, Tuple

import msgpack

from antidote_tpu.proto.codec import (
    MessageCode,
    decode,
    decode_value,
    encode_with,
    merge_clock,
    read_frame_buffered,
)


class RemoteAbort(Exception):
    """Server aborted the transaction."""


class RemoteError(Exception):
    """Server-side error reply."""


class RemoteBusy(RemoteError):
    """Server shed the request (overload admission / bounded queue).
    ``retry_after_ms`` is the server's backoff hint."""

    def __init__(self, msg: str, retry_after_ms: int = 50):
        super().__init__(msg)
        self.retry_after_ms = int(retry_after_ms)


class RemoteDeadline(RemoteError):
    """The request outlived its deadline server-side; it was aborted at
    dequeue — never executed."""


class RemoteReadOnly(RemoteError):
    """The node is in degraded read-only mode (WAL appends failing);
    writes are rejected, reads keep serving."""


class RemoteNotOwner(RemoteError):
    """The node is a follower read replica; writes and interactive
    transactions must go to the owner.  ``redirect`` is the owner's
    ``[host, port]`` when the follower knows it."""

    def __init__(self, msg: str, redirect=None):
        super().__init__(msg)
        self.redirect = redirect


class RemoteLagging(RemoteError):
    """A follower's applied clock was still behind the session token
    after its park window (or it is mid-bootstrap/heal): the read was
    NOT served.  Retry after ``retry_after_ms`` or fail over —
    ``redirect`` names the owner."""

    def __init__(self, msg: str, retry_after_ms: int = 50, redirect=None):
        super().__init__(msg)
        self.retry_after_ms = int(retry_after_ms)
        self.redirect = redirect


class ClientTxn:
    def __init__(self, client: "AntidoteClient", txid: int):
        self._client = client
        self.txid = txid

    def read_objects(self, objects: Sequence[Tuple[Any, str, str]]) -> List[Any]:
        body = self._client._call(MessageCode.READ_OBJECTS, {
            "txid": self.txid, "objects": list(objects),
        })
        return [decode_value(v) for v in body["values"]]

    def update_objects(self, updates: Sequence[Tuple]) -> None:
        self._client._call(MessageCode.UPDATE_OBJECTS, {
            "txid": self.txid, "updates": list(updates),
        })

    def commit(self) -> List[int]:
        body = self._client._call(MessageCode.COMMIT_TRANSACTION,
                                  {"txid": self.txid})
        return body["commit_clock"]

    def abort(self) -> None:
        self._client._call(MessageCode.ABORT_TRANSACTION, {"txid": self.txid})


class AntidoteClient:
    def __init__(self, host: str = "127.0.0.1", port: int = 8087,
                 timeout: float = 30.0):
        self._sock = socket.create_connection((host, port), timeout=timeout)
        self._sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        self._lock = threading.Lock()
        # hot-path plumbing: a buffered reader coalesces the header+body
        # reads into ~one syscall per reply, and one persistent Packer
        # skips per-call packer construction — this client is the load
        # generator in bench_wire, where its CPU bills against the server
        self._rfile = self._sock.makefile("rb")
        self._packer = msgpack.Packer(use_bin_type=True)

    # ------------------------------------------------------------------
    def _call(self, code: MessageCode, body: Any):
        with self._lock:
            # tag transport failures with whether the request LEFT the
            # socket: a send-phase failure is always safe to retry, a
            # reply-phase one means the server may have executed it
            # (the at-most-once discipline TcpFabric._rpc documents) —
            # SessionClient keys its write-retry decision on this
            try:
                self._sock.sendall(encode_with(self._packer, code, body))
            except (ConnectionError, OSError) as e:
                e.request_sent = False
                raise
            try:
                resp_code, resp = decode(read_frame_buffered(self._rfile))
            except (ConnectionError, OSError) as e:
                e.request_sent = True
                raise
        if resp_code == MessageCode.ERROR_RESP:
            err = resp.get("error")
            if err == "aborted":
                raise RemoteAbort(resp.get("detail", ""))
            if err == "busy":
                raise RemoteBusy(resp.get("detail", ""),
                                 int(resp.get("retry_after_ms", 50)))
            if err == "deadline":
                raise RemoteDeadline(resp.get("detail", ""))
            if err == "read_only":
                raise RemoteReadOnly(resp.get("detail", ""))
            if err == "not_owner":
                raise RemoteNotOwner(resp.get("detail", ""),
                                     redirect=resp.get("redirect"))
            if err == "lagging":
                raise RemoteLagging(resp.get("detail", ""),
                                    int(resp.get("retry_after_ms", 50)),
                                    redirect=resp.get("redirect"))
            raise RemoteError(f"{err}: {resp.get('detail')}")
        return resp

    # ------------------------------------------------------------------
    def start_transaction(self, clock: Optional[Sequence[int]] = None,
                          props: Optional[dict] = None) -> ClientTxn:
        body = self._call(MessageCode.START_TRANSACTION, {
            "clock": None if clock is None else [int(x) for x in clock],
            "props": props,
        })
        return ClientTxn(self, body["txid"])

    def update_objects(self, updates: Sequence[Tuple],
                       clock: Optional[Sequence[int]] = None,
                       deadline_ms: Optional[float] = None) -> List[int]:
        req = {
            "updates": list(updates),
            "clock": None if clock is None else [int(x) for x in clock],
        }
        if deadline_ms is not None:
            # relative budget; the server aborts the request at dequeue
            # once it has outlived this (RemoteDeadline reply)
            req["deadline_ms"] = float(deadline_ms)
        body = self._call(MessageCode.STATIC_UPDATE_OBJECTS, req)
        return body["commit_clock"]

    def read_objects(self, objects: Sequence[Tuple[Any, str, str]],
                     clock: Optional[Sequence[int]] = None,
                     deadline_ms: Optional[float] = None):
        req = {
            "objects": list(objects),
            "clock": None if clock is None else [int(x) for x in clock],
        }
        if deadline_ms is not None:
            req["deadline_ms"] = float(deadline_ms)
        body = self._call(MessageCode.STATIC_READ_OBJECTS, req)
        return ([decode_value(v) for v in body["values"]],
                body["commit_clock"])

    def get_connection_descriptor(self) -> dict:
        return self._call(MessageCode.GET_CONNECTION_DESCRIPTOR,
                          {})["descriptor"]

    def connect_to_dcs(self, descriptors) -> None:
        """Subscribe this node's DC to remote DCs' txn streams
        (antidote_dc_manager:subscribe_updates_from)."""
        self._call(MessageCode.CONNECT_TO_DCS,
                   {"descriptors": list(descriptors)})

    def create_dc(self, nodes) -> None:
        self._call(MessageCode.CREATE_DC, {"nodes": list(nodes)})

    def node_status(self, include_ready: bool = False) -> dict:
        """Operator snapshot (console `status`; no reference pb
        equivalent — the reference exposes this via riak-admin/console).
        ``include_ready`` additionally runs the server-side readiness
        probe (heavier: device round trip + WAL barrier)."""
        return self._call(MessageCode.NODE_STATUS,
                          {"include_ready": include_ready})["status"]

    def checkpoint_now(self) -> dict:
        """Run one synchronous checkpoint cycle on the server (console
        `checkpoint-now`); returns the published manifest summary.
        Blocks for the image stream — admin use, not a data-path call."""
        return self._call(MessageCode.CHECKPOINT_NOW, {})["checkpoint"]

    def replica_admin(self, op: str = "status", name: Optional[str] = None,
                      addr=None) -> dict:
        """Follower-replica registry op against an owner (console
        `replica add/remove/status`); `status` also works against a
        follower (its self view)."""
        body: dict = {"op": op}
        if name is not None:
            body["name"] = name
        if addr is not None:
            body["addr"] = list(addr)
        return self._call(MessageCode.REPLICA_ADMIN, body)["replicas"]

    def close(self) -> None:
        try:
            self._rfile.close()
        except OSError:
            pass
        self._sock.close()


class SessionClient:
    """Causal session over an owner + follower fleet (ISSUE 9).

    Carries a compact VC session token: every commit clock and read
    snapshot the session observes folds into the token
    (:func:`~antidote_tpu.proto.codec.merge_clock`), and the token rides
    as the causal clock of every request — so **read-your-writes** and
    **monotonic reads** hold no matter which replica serves, across
    arbitrary follower kills.

    Routing: writes always go to the owner; reads stick to one follower
    and fail over — to the next follower, and finally to the owner — on
    a connection death or a typed ``lagging`` redirect (the follower's
    applied clock hadn't caught the token inside its park window).  When
    every endpoint fails, the typed
    :class:`~antidote_tpu.overload.ReplicaDown` surfaces.
    """

    def __init__(self, owner, followers=(), timeout: float = 30.0):
        self.owner = (owner[0], int(owner[1]))
        self.followers = [(h, int(p)) for h, p in followers]
        self.timeout = timeout
        #: the session token (None until the first clock is observed)
        self.token: Optional[List[int]] = None
        self._conns: dict = {}
        self._ridx = 0
        #: session observability: typed lagging/not_owner redirects
        #: honored, and endpoint failovers on connection death
        self.redirects = 0
        self.failovers = 0

    # -- connections -----------------------------------------------------
    def _conn(self, addr) -> AntidoteClient:
        c = self._conns.get(addr)
        if c is None:
            try:
                c = AntidoteClient(addr[0], addr[1],
                                   timeout=self.timeout)
            except (ConnectionError, OSError) as e:
                # a DIAL failure never carried a request: tag it so the
                # at-most-once write logic knows a retry is safe
                e.request_sent = False
                raise
            self._conns[addr] = c
        return c

    def _drop(self, addr) -> None:
        c = self._conns.pop(addr, None)
        if c is not None:
            try:
                c.close()
            except OSError:
                pass

    def observe(self, clock) -> None:
        """Fold an observed clock into the session token."""
        self.token = merge_clock(self.token, clock)

    # -- session ops -----------------------------------------------------
    def update_objects(self, updates: Sequence[Tuple]) -> List[int]:
        """Session write: always the owner; the commit clock folds into
        the token so any replica serving a later read must cover it.
        AT-MOST-ONCE: only a SEND-phase transport failure (the request
        never left — e.g. a cached connection gone stale across an
        owner restart) is redialed; a connection dying while awaiting
        the reply surfaces typed, because the owner may have executed
        the (non-idempotent) write and a blind resend would apply it
        twice — the same discipline the inter-DC query channel keeps."""
        from antidote_tpu.overload import ReplicaDown

        last: Optional[BaseException] = None
        for _attempt in range(2):
            try:
                vc = self._conn(self.owner).update_objects(
                    updates, clock=self.token)
                self.observe(vc)
                return vc
            except RemoteNotOwner as e:
                # the "owner" endpoint is itself a follower (operator
                # misconfiguration) but told us where to go
                if not e.redirect:
                    raise
                self.redirects += 1
                self.owner = (e.redirect[0], int(e.redirect[1]))
                last = e
            except (ConnectionError, OSError) as ex:
                self._drop(self.owner)
                self.failovers += 1
                if getattr(ex, "request_sent", True):
                    raise ConnectionError(
                        f"session write: connection to owner "
                        f"{self.owner} died awaiting the reply — the "
                        "write may have executed; not resending"
                    ) from ex
                last = ex
        raise ReplicaDown(
            f"session write: owner {self.owner} unreachable"
        ) from last

    def read_objects(self, objects: Sequence[Tuple[Any, str, str]]):
        """Session read: current follower first, then the remaining
        followers, then the owner.  The reply's snapshot clock folds
        into the token (monotonic reads)."""
        from antidote_tpu.overload import ReplicaDown

        n = len(self.followers)
        order = [self.followers[(self._ridx + i) % n] for i in range(n)] \
            if n else []
        order.append(self.owner)
        last: Optional[BaseException] = None
        for i, addr in enumerate(order):
            try:
                vals, vc = self._conn(addr).read_objects(
                    objects, clock=self.token)
            except RemoteLagging as e:
                self.redirects += 1
                last = e
                if n:
                    self._ridx = (self._ridx + 1) % n
                continue
            except RemoteNotOwner as e:
                self.redirects += 1
                last = e
                continue
            except (ConnectionError, OSError) as ex:
                self._drop(addr)
                self.failovers += 1
                last = ex
                if n and i < n:
                    self._ridx = (self._ridx + 1) % n
                continue
            self.observe(vc)
            return vals, vc
        raise ReplicaDown(
            "session read: every endpoint (followers and owner) "
            "refused or dropped the request"
        ) from last

    def close(self) -> None:
        for addr in list(self._conns):
            self._drop(addr)
