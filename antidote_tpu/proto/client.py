"""Python client for the wire protocol — the antidotec_pb analogue.

One socket, request/response in lockstep (the reference client multiplexes
the same way: each request waits for its reply before the next —
/root/reference/src/antidote_pb_protocol.erl:51-64 is a strict loop).
"""

from __future__ import annotations

import socket
import threading
from typing import Any, List, Optional, Sequence, Tuple

from antidote_tpu.proto.codec import (
    MessageCode,
    decode,
    decode_value,
    read_frame,
    write_message,
)


class RemoteAbort(Exception):
    """Server aborted the transaction."""


class RemoteError(Exception):
    """Server-side error reply."""


class ClientTxn:
    def __init__(self, client: "AntidoteClient", txid: int):
        self._client = client
        self.txid = txid

    def read_objects(self, objects: Sequence[Tuple[Any, str, str]]) -> List[Any]:
        body = self._client._call(MessageCode.READ_OBJECTS, {
            "txid": self.txid, "objects": list(objects),
        })
        return [decode_value(v) for v in body["values"]]

    def update_objects(self, updates: Sequence[Tuple]) -> None:
        self._client._call(MessageCode.UPDATE_OBJECTS, {
            "txid": self.txid, "updates": list(updates),
        })

    def commit(self) -> List[int]:
        body = self._client._call(MessageCode.COMMIT_TRANSACTION,
                                  {"txid": self.txid})
        return body["commit_clock"]

    def abort(self) -> None:
        self._client._call(MessageCode.ABORT_TRANSACTION, {"txid": self.txid})


class AntidoteClient:
    def __init__(self, host: str = "127.0.0.1", port: int = 8087,
                 timeout: float = 30.0):
        self._sock = socket.create_connection((host, port), timeout=timeout)
        self._sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        self._lock = threading.Lock()

    # ------------------------------------------------------------------
    def _call(self, code: MessageCode, body: Any):
        with self._lock:
            write_message(self._sock, code, body)
            resp_code, resp = decode(read_frame(self._sock))
        if resp_code == MessageCode.ERROR_RESP:
            if resp.get("error") == "aborted":
                raise RemoteAbort(resp.get("detail", ""))
            raise RemoteError(f"{resp.get('error')}: {resp.get('detail')}")
        return resp

    # ------------------------------------------------------------------
    def start_transaction(self, clock: Optional[Sequence[int]] = None,
                          props: Optional[dict] = None) -> ClientTxn:
        body = self._call(MessageCode.START_TRANSACTION, {
            "clock": None if clock is None else [int(x) for x in clock],
            "props": props,
        })
        return ClientTxn(self, body["txid"])

    def update_objects(self, updates: Sequence[Tuple],
                       clock: Optional[Sequence[int]] = None) -> List[int]:
        body = self._call(MessageCode.STATIC_UPDATE_OBJECTS, {
            "updates": list(updates),
            "clock": None if clock is None else [int(x) for x in clock],
        })
        return body["commit_clock"]

    def read_objects(self, objects: Sequence[Tuple[Any, str, str]],
                     clock: Optional[Sequence[int]] = None):
        body = self._call(MessageCode.STATIC_READ_OBJECTS, {
            "objects": list(objects),
            "clock": None if clock is None else [int(x) for x in clock],
        })
        return ([decode_value(v) for v in body["values"]],
                body["commit_clock"])

    def get_connection_descriptor(self) -> dict:
        return self._call(MessageCode.GET_CONNECTION_DESCRIPTOR,
                          {})["descriptor"]

    def connect_to_dcs(self, descriptors) -> None:
        """Subscribe this node's DC to remote DCs' txn streams
        (antidote_dc_manager:subscribe_updates_from)."""
        self._call(MessageCode.CONNECT_TO_DCS,
                   {"descriptors": list(descriptors)})

    def create_dc(self, nodes) -> None:
        self._call(MessageCode.CREATE_DC, {"nodes": list(nodes)})

    def node_status(self, include_ready: bool = False) -> dict:
        """Operator snapshot (console `status`; no reference pb
        equivalent — the reference exposes this via riak-admin/console).
        ``include_ready`` additionally runs the server-side readiness
        probe (heavier: device round trip + WAL barrier)."""
        return self._call(MessageCode.NODE_STATUS,
                          {"include_ready": include_ready})["status"]

    def close(self) -> None:
        self._sock.close()
