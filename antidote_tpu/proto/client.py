"""Python client for the wire protocol — the antidotec_pb analogue.

One socket, request/response in lockstep (the reference client multiplexes
the same way: each request waits for its reply before the next —
/root/reference/src/antidote_pb_protocol.erl:51-64 is a strict loop).
"""

from __future__ import annotations

import socket
import threading
from typing import Any, List, Optional, Sequence, Tuple

import msgpack

from antidote_tpu.proto.codec import (
    MessageCode,
    decode,
    decode_value,
    encode_with,
    read_frame_buffered,
)


class RemoteAbort(Exception):
    """Server aborted the transaction."""


class RemoteError(Exception):
    """Server-side error reply."""


class RemoteBusy(RemoteError):
    """Server shed the request (overload admission / bounded queue).
    ``retry_after_ms`` is the server's backoff hint."""

    def __init__(self, msg: str, retry_after_ms: int = 50):
        super().__init__(msg)
        self.retry_after_ms = int(retry_after_ms)


class RemoteDeadline(RemoteError):
    """The request outlived its deadline server-side; it was aborted at
    dequeue — never executed."""


class RemoteReadOnly(RemoteError):
    """The node is in degraded read-only mode (WAL appends failing);
    writes are rejected, reads keep serving."""


class ClientTxn:
    def __init__(self, client: "AntidoteClient", txid: int):
        self._client = client
        self.txid = txid

    def read_objects(self, objects: Sequence[Tuple[Any, str, str]]) -> List[Any]:
        body = self._client._call(MessageCode.READ_OBJECTS, {
            "txid": self.txid, "objects": list(objects),
        })
        return [decode_value(v) for v in body["values"]]

    def update_objects(self, updates: Sequence[Tuple]) -> None:
        self._client._call(MessageCode.UPDATE_OBJECTS, {
            "txid": self.txid, "updates": list(updates),
        })

    def commit(self) -> List[int]:
        body = self._client._call(MessageCode.COMMIT_TRANSACTION,
                                  {"txid": self.txid})
        return body["commit_clock"]

    def abort(self) -> None:
        self._client._call(MessageCode.ABORT_TRANSACTION, {"txid": self.txid})


class AntidoteClient:
    def __init__(self, host: str = "127.0.0.1", port: int = 8087,
                 timeout: float = 30.0):
        self._sock = socket.create_connection((host, port), timeout=timeout)
        self._sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        self._lock = threading.Lock()
        # hot-path plumbing: a buffered reader coalesces the header+body
        # reads into ~one syscall per reply, and one persistent Packer
        # skips per-call packer construction — this client is the load
        # generator in bench_wire, where its CPU bills against the server
        self._rfile = self._sock.makefile("rb")
        self._packer = msgpack.Packer(use_bin_type=True)

    # ------------------------------------------------------------------
    def _call(self, code: MessageCode, body: Any):
        with self._lock:
            self._sock.sendall(encode_with(self._packer, code, body))
            resp_code, resp = decode(read_frame_buffered(self._rfile))
        if resp_code == MessageCode.ERROR_RESP:
            err = resp.get("error")
            if err == "aborted":
                raise RemoteAbort(resp.get("detail", ""))
            if err == "busy":
                raise RemoteBusy(resp.get("detail", ""),
                                 int(resp.get("retry_after_ms", 50)))
            if err == "deadline":
                raise RemoteDeadline(resp.get("detail", ""))
            if err == "read_only":
                raise RemoteReadOnly(resp.get("detail", ""))
            raise RemoteError(f"{err}: {resp.get('detail')}")
        return resp

    # ------------------------------------------------------------------
    def start_transaction(self, clock: Optional[Sequence[int]] = None,
                          props: Optional[dict] = None) -> ClientTxn:
        body = self._call(MessageCode.START_TRANSACTION, {
            "clock": None if clock is None else [int(x) for x in clock],
            "props": props,
        })
        return ClientTxn(self, body["txid"])

    def update_objects(self, updates: Sequence[Tuple],
                       clock: Optional[Sequence[int]] = None,
                       deadline_ms: Optional[float] = None) -> List[int]:
        req = {
            "updates": list(updates),
            "clock": None if clock is None else [int(x) for x in clock],
        }
        if deadline_ms is not None:
            # relative budget; the server aborts the request at dequeue
            # once it has outlived this (RemoteDeadline reply)
            req["deadline_ms"] = float(deadline_ms)
        body = self._call(MessageCode.STATIC_UPDATE_OBJECTS, req)
        return body["commit_clock"]

    def read_objects(self, objects: Sequence[Tuple[Any, str, str]],
                     clock: Optional[Sequence[int]] = None,
                     deadline_ms: Optional[float] = None):
        req = {
            "objects": list(objects),
            "clock": None if clock is None else [int(x) for x in clock],
        }
        if deadline_ms is not None:
            req["deadline_ms"] = float(deadline_ms)
        body = self._call(MessageCode.STATIC_READ_OBJECTS, req)
        return ([decode_value(v) for v in body["values"]],
                body["commit_clock"])

    def get_connection_descriptor(self) -> dict:
        return self._call(MessageCode.GET_CONNECTION_DESCRIPTOR,
                          {})["descriptor"]

    def connect_to_dcs(self, descriptors) -> None:
        """Subscribe this node's DC to remote DCs' txn streams
        (antidote_dc_manager:subscribe_updates_from)."""
        self._call(MessageCode.CONNECT_TO_DCS,
                   {"descriptors": list(descriptors)})

    def create_dc(self, nodes) -> None:
        self._call(MessageCode.CREATE_DC, {"nodes": list(nodes)})

    def node_status(self, include_ready: bool = False) -> dict:
        """Operator snapshot (console `status`; no reference pb
        equivalent — the reference exposes this via riak-admin/console).
        ``include_ready`` additionally runs the server-side readiness
        probe (heavier: device round trip + WAL barrier)."""
        return self._call(MessageCode.NODE_STATUS,
                          {"include_ready": include_ready})["status"]

    def checkpoint_now(self) -> dict:
        """Run one synchronous checkpoint cycle on the server (console
        `checkpoint-now`); returns the published manifest summary.
        Blocks for the image stream — admin use, not a data-path call."""
        return self._call(MessageCode.CHECKPOINT_NOW, {})["checkpoint"]

    def close(self) -> None:
        try:
            self._rfile.close()
        except OSError:
            pass
        self._sock.close()
