"""Python client for the wire protocol — the antidotec_pb analogue.

One socket, request/response in lockstep (the reference client multiplexes
the same way: each request waits for its reply before the next —
/root/reference/src/antidote_pb_protocol.erl:51-64 is a strict loop).
"""

from __future__ import annotations

import bisect
import hashlib
import socket
import struct
import threading
import time
from typing import Any, Dict, List, Optional, Sequence, Tuple

import msgpack

from antidote_tpu.proto.codec import (
    MessageCode,
    decode,
    decode_value,
    encode_with,
    merge_clock,
    read_frame_buffered,
)


class RemoteAbort(Exception):
    """Server aborted the transaction."""


class RemoteError(Exception):
    """Server-side error reply."""


class RemoteBusy(RemoteError):
    """Server shed the request (overload admission / bounded queue).
    ``retry_after_ms`` is the server's backoff hint."""

    def __init__(self, msg: str, retry_after_ms: int = 50):
        super().__init__(msg)
        self.retry_after_ms = int(retry_after_ms)


class RemoteTenantBusy(RemoteBusy):
    """The request was refused by its TENANT's quota (weighted-fair
    lane full or per-tenant in-flight cap) while the node as a whole
    had headroom — retrying against a sibling node won't help until
    this tenant's own backlog drains.  ``tenant`` names the lane;
    subclasses :class:`RemoteBusy` so generic backoff loops keep
    working, while fairness-aware callers can tell quota pressure
    apart from global overload."""

    def __init__(self, msg: str, retry_after_ms: int = 50, tenant: str = ""):
        super().__init__(msg, retry_after_ms=retry_after_ms)
        self.tenant = str(tenant)


class RemoteDeadline(RemoteError):
    """The request outlived its deadline server-side; it was aborted at
    dequeue — never executed."""


class RemoteReadOnly(RemoteError):
    """The node is in degraded read-only mode (WAL appends failing);
    writes are rejected, reads keep serving."""


class RemoteNotOwner(RemoteError):
    """The node is a follower read replica; writes and interactive
    transactions must go to the owner.  ``redirect`` is the owner's
    ``[host, port]`` when the follower knows it."""

    def __init__(self, msg: str, redirect=None):
        super().__init__(msg)
        self.redirect = redirect


class RemoteLagging(RemoteError):
    """A follower's applied clock was still behind the session token
    after its park window (or it is mid-bootstrap/heal): the read was
    NOT served.  Retry after ``retry_after_ms`` or fail over —
    ``redirect`` names the owner."""

    def __init__(self, msg: str, retry_after_ms: int = 50, redirect=None):
        super().__init__(msg)
        self.retry_after_ms = int(retry_after_ms)
        self.redirect = redirect


class RemoteForwardFailed(RemoteError):
    """A follower forwarding this write/txn op to the owner (ISSUE 17)
    lost the owner connection AFTER the request left its socket: the
    owner **may have executed** it, and the at-most-once contract
    forbids a blind resend.  Re-read at the session token to learn the
    outcome (or retry only if the op is idempotent)."""

    def __init__(self, msg: str):
        super().__init__(msg)
        self.maybe_executed = True


class RemoteInsufficientRights(RemoteError):
    """A bounded-counter (``counter_b``) decrement/transfer exceeded the
    serving DC's locally-held escrow rights — the op was NOT executed
    (zero oversell).  ``retry_after_ms`` is scaled by the expected grant
    arrival: the server's background rights-transfer loop has been told
    about the shortfall, so waiting out the hint usually finds rights
    rebalanced here."""

    def __init__(self, msg: str, retry_after_ms: int = 100):
        super().__init__(msg)
        self.retry_after_ms = int(retry_after_ms)


class RemoteColdMiss(RemoteError):
    """A cold-tier key's fault-in was refused (rate cap, I/O fault, or
    sidecar CRC failure): the read/write was NOT served — retry after
    ``retry_after_ms``.  ``permanent=True`` means the key's backing row
    is verifiably lost on every retained image (operator repair:
    re-bootstrap the store from a peer/follower)."""

    def __init__(self, msg: str, retry_after_ms: int = 50,
                 permanent: bool = False):
        super().__init__(msg)
        self.retry_after_ms = int(retry_after_ms)
        self.permanent = bool(permanent)


class ClientTxn:
    def __init__(self, client: "AntidoteClient", txid: int):
        self._client = client
        self.txid = txid

    def read_objects(self, objects: Sequence[Tuple[Any, str, str]]) -> List[Any]:
        body = self._client._call(MessageCode.READ_OBJECTS, {
            "txid": self.txid, "objects": list(objects),
        })
        return [decode_value(v) for v in body["values"]]

    def update_objects(self, updates: Sequence[Tuple]) -> None:
        self._client._call(MessageCode.UPDATE_OBJECTS, {
            "txid": self.txid, "updates": list(updates),
        })

    def commit(self) -> List[int]:
        body = self._client._call(MessageCode.COMMIT_TRANSACTION,
                                  {"txid": self.txid})
        return body["commit_clock"]

    def abort(self) -> None:
        self._client._call(MessageCode.ABORT_TRANSACTION, {"txid": self.txid})


class AntidoteClient:
    def __init__(self, host: str = "127.0.0.1", port: int = 8087,
                 timeout: float = 30.0, tenant: Optional[str] = None):
        #: connection-level tenant tag (ISSUE 19): attached to every
        #: static read/update body so the server's weighted-fair lanes
        #: classify this connection even when its buckets are untagged.
        #: A registered ``tenant/bucket`` prefix still wins server-side.
        self.tenant = tenant
        self._sock = socket.create_connection((host, port), timeout=timeout)
        self._sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        self._lock = threading.Lock()
        # hot-path plumbing: a buffered reader coalesces the header+body
        # reads into ~one syscall per reply, and one persistent Packer
        # skips per-call packer construction — this client is the load
        # generator in bench_wire, where its CPU bills against the server
        self._rfile = self._sock.makefile("rb")
        self._packer = msgpack.Packer(use_bin_type=True)
        #: last ring hint a follower attached to a reply (ISSUE 17):
        #: ``{owner, followers, vnodes}`` — consumed (and cleared) by
        #: SessionClient to refresh its fleet in place
        self.ring_hint: Optional[dict] = None

    # ------------------------------------------------------------------
    def _call(self, code: MessageCode, body: Any):
        with self._lock:
            # tag transport failures with whether the request LEFT the
            # socket: a send-phase failure is always safe to retry, a
            # reply-phase one means the server may have executed it
            # (the at-most-once discipline TcpFabric._rpc documents) —
            # SessionClient keys its write-retry decision on this
            try:
                self._sock.sendall(encode_with(self._packer, code, body))
            except (ConnectionError, OSError) as e:
                e.request_sent = False
                raise
            try:
                resp_code, resp = decode(read_frame_buffered(self._rfile))
            except (ConnectionError, OSError) as e:
                e.request_sent = True
                raise
        if isinstance(resp, dict) and resp.get("ring_hint") is not None:
            self.ring_hint = resp["ring_hint"]
        if resp_code == MessageCode.ERROR_RESP:
            err = resp.get("error")
            if err == "aborted":
                raise RemoteAbort(resp.get("detail", ""))
            if err == "tenant_busy":
                raise RemoteTenantBusy(resp.get("detail", ""),
                                       int(resp.get("retry_after_ms", 50)),
                                       tenant=resp.get("tenant") or "")
            if err == "busy":
                raise RemoteBusy(resp.get("detail", ""),
                                 int(resp.get("retry_after_ms", 50)))
            if err == "deadline":
                raise RemoteDeadline(resp.get("detail", ""))
            if err == "read_only":
                raise RemoteReadOnly(resp.get("detail", ""))
            if err == "not_owner":
                raise RemoteNotOwner(resp.get("detail", ""),
                                     redirect=resp.get("redirect"))
            if err == "lagging":
                raise RemoteLagging(resp.get("detail", ""),
                                    int(resp.get("retry_after_ms", 50)),
                                    redirect=resp.get("redirect"))
            if err == "cold_miss":
                raise RemoteColdMiss(resp.get("detail", ""),
                                     int(resp.get("retry_after_ms", 50)),
                                     permanent=bool(
                                         resp.get("permanent")))
            if err == "forward_failed":
                raise RemoteForwardFailed(resp.get("detail", ""))
            if err == "insufficient_rights":
                raise RemoteInsufficientRights(
                    resp.get("detail", ""),
                    int(resp.get("retry_after_ms", 100)))
            raise RemoteError(f"{err}: {resp.get('detail')}")
        return resp

    # ------------------------------------------------------------------
    def start_transaction(self, clock: Optional[Sequence[int]] = None,
                          props: Optional[dict] = None) -> ClientTxn:
        body = self._call(MessageCode.START_TRANSACTION, {
            "clock": None if clock is None else [int(x) for x in clock],
            "props": props,
        })
        return ClientTxn(self, body["txid"])

    def update_objects(self, updates: Sequence[Tuple],
                       clock: Optional[Sequence[int]] = None,
                       deadline_ms: Optional[float] = None,
                       proxied: bool = False,
                       tenant: Optional[str] = None) -> List[int]:
        req = {
            "updates": list(updates),
            "clock": None if clock is None else [int(x) for x in clock],
        }
        if tenant is None:
            tenant = self.tenant
        if tenant:
            req["tenant"] = tenant
        if deadline_ms is not None:
            # relative budget; the server aborts the request at dequeue
            # once it has outlived this (RemoteDeadline reply)
            req["deadline_ms"] = float(deadline_ms)
        if proxied:
            # no-reforward flag (ISSUE 17): this request already crossed
            # one server-side hop — the receiver answers locally or
            # refuses typed, never forwards again
            req["proxied"] = True
        body = self._call(MessageCode.STATIC_UPDATE_OBJECTS, req)
        return body["commit_clock"]

    def read_objects(self, objects: Sequence[Tuple[Any, str, str]],
                     clock: Optional[Sequence[int]] = None,
                     deadline_ms: Optional[float] = None,
                     proxied: bool = False,
                     tenant: Optional[str] = None):
        req = {
            "objects": list(objects),
            "clock": None if clock is None else [int(x) for x in clock],
        }
        if tenant is None:
            tenant = self.tenant
        if tenant:
            req["tenant"] = tenant
        if deadline_ms is not None:
            req["deadline_ms"] = float(deadline_ms)
        if proxied:
            # no-reproxy flag (ISSUE 17): one hop max
            req["proxied"] = True
        body = self._call(MessageCode.STATIC_READ_OBJECTS, req)
        return ([decode_value(v) for v in body["values"]],
                body["commit_clock"])

    def get_connection_descriptor(self) -> dict:
        return self._call(MessageCode.GET_CONNECTION_DESCRIPTOR,
                          {})["descriptor"]

    def connect_to_dcs(self, descriptors) -> None:
        """Subscribe this node's DC to remote DCs' txn streams
        (antidote_dc_manager:subscribe_updates_from)."""
        self._call(MessageCode.CONNECT_TO_DCS,
                   {"descriptors": list(descriptors)})

    def create_dc(self, nodes) -> None:
        self._call(MessageCode.CREATE_DC, {"nodes": list(nodes)})

    def node_status(self, include_ready: bool = False) -> dict:
        """Operator snapshot (console `status`; no reference pb
        equivalent — the reference exposes this via riak-admin/console).
        ``include_ready`` additionally runs the server-side readiness
        probe (heavier: device round trip + WAL barrier)."""
        return self._call(MessageCode.NODE_STATUS,
                          {"include_ready": include_ready})["status"]

    def checkpoint_now(self) -> dict:
        """Run one synchronous checkpoint cycle on the server (console
        `checkpoint-now`); returns the published manifest summary.
        Blocks for the image stream — admin use, not a data-path call."""
        return self._call(MessageCode.CHECKPOINT_NOW, {})["checkpoint"]

    def replica_admin(self, op: str = "status", name: Optional[str] = None,
                      addr=None) -> dict:
        """Follower-replica registry op against an owner (console
        `replica add/remove/status`); `status` also works against a
        follower (its self view)."""
        body: dict = {"op": op}
        if name is not None:
            body["name"] = name
        if addr is not None:
            body["addr"] = list(addr)
        return self._call(MessageCode.REPLICA_ADMIN, body)["replicas"]

    def close(self) -> None:
        try:
            self._rfile.close()
        except OSError:
            pass
        self._sock.close()


def _h64(data: bytes) -> int:
    """Stable 64-bit hash for ring placement (never Python's salted
    ``hash``: every client must map a key to the same arc)."""
    return int.from_bytes(hashlib.blake2b(data, digest_size=8).digest(),
                          "big")


class HashRing:
    """Consistent-hash ring over a follower fleet (ISSUE 11) — the
    reference's riak_core chash ring role (SURVEY §1 L1,
    ``log_utilities`` key→partition via ``chash_key``) applied to
    REPLICA selection: keys map to a preferred follower through virtual
    nodes, so adding/removing a follower remaps only its own arcs
    (~1/N of the keyspace) instead of reshuffling everything, and a
    fleet-wide client population agrees on the mapping with no
    coordination.

    The PLACEMENT hash is unseeded — every client must route a key to
    the same preferred replica (that is what makes the fleet's snapshot
    caches compose).  The FALLBACK order is seeded per client: when an
    arc's owner dies, each client walks a differently-jittered order
    over the survivors, so a fleet-wide follower death spreads across
    the remaining fleet instead of stampeding every client onto the
    same next endpoint (the satellite fix for PR 9's list-order
    failover)."""

    def __init__(self, endpoints: Sequence[Tuple[str, int]],
                 vnodes: int = 64, seed: int = 0):
        self.vnodes = int(vnodes)
        self.seed = int(seed)
        self.endpoints: List[Tuple[str, int]] = [
            (h, int(p)) for h, p in endpoints]
        pts: List[Tuple[int, int]] = []
        for i, (host, port) in enumerate(self.endpoints):
            for v in range(self.vnodes):
                pts.append((_h64(f"{host}:{port}#{v}".encode()), i))
        pts.sort()
        self._points = pts
        self._hashes = [h for h, _ in pts]

    def __len__(self) -> int:
        return len(self.endpoints)

    def _key_hash(self, key, bucket) -> int:
        return _h64(msgpack.packb([key, bucket], use_bin_type=True,
                                  default=repr))

    def preferred(self, key, bucket) -> Optional[Tuple[str, int]]:
        """The key's arc owner (None on an empty ring)."""
        if not self._points:
            return None
        kh = self._key_hash(key, bucket)
        i = bisect.bisect_right(self._hashes, kh) % len(self._points)
        return self.endpoints[self._points[i][1]]

    def order(self, key, bucket) -> List[Tuple[str, int]]:
        """Failover order for a key: the arc owner first (fleet-wide
        agreement), then every other endpoint in this client's
        deterministic seeded-jitter order (fleet-wide disagreement, on
        purpose)."""
        pref = self.preferred(key, bucket)
        if pref is None:
            return []
        kh = self._key_hash(key, bucket)
        tail = [ep for ep in self.endpoints if ep != pref]
        tail.sort(key=lambda ep: _h64(
            struct.pack(">QQ", self.seed & ((1 << 64) - 1), kh)
            + f"{ep[0]}:{ep[1]}".encode()))
        return [pref] + tail

    def arc_share(self) -> Dict[Tuple[str, int], float]:
        """Fraction of the hash space each endpoint owns (console/bench
        observability: ring balance, and the fleet-smoke 'all arcs
        served' gate)."""
        if not self._points:
            return {}
        span = float(1 << 64)
        out = {ep: 0.0 for ep in self.endpoints}
        prev = self._points[-1][0] - (1 << 64)
        for h, idx in self._points:
            out[self.endpoints[idx]] += (h - prev) / span
            prev = h
        return out

    def arc_share_by_name(self, digits: int = 4) -> Dict[str, float]:
        """:meth:`arc_share` keyed ``"host:port"`` and rounded — the one
        presentation every surface (console replica-status, session
        stats, the bench artifact) shows."""
        return {f"{h}:{p}": round(v, digits)
                for (h, p), v in self.arc_share().items()}


class ApbClient:
    """Session-capable client speaking the antidote_pb protobuf dialect
    (ISSUE 11): static reads/updates with the session token riding the
    ApbStartTransaction timestamp, typed errors decoded from the errmsg
    prefix (:func:`antidote_tpu.proto.apb.parse_error_text`) into the
    SAME ``Remote*`` exceptions the native client raises — so
    :class:`SessionClient` drives either dialect with one failover loop,
    and protobuf clients get real read-your-writes failover instead of
    a blanket refusal.  Carries the native client's at-most-once
    tagging: transport failures are marked with whether the request
    left the socket."""

    def __init__(self, host: str = "127.0.0.1", port: int = 8087,
                 timeout: float = 30.0):
        self._sock = socket.create_connection((host, port),
                                              timeout=timeout)
        self._sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        self._lock = threading.Lock()
        self._rfile = self._sock.makefile("rb")
        #: last ring hint learned from a reply (ISSUE 17): proxied reads
        #: carry it as an optional msgpack field, typed redirects as the
        #: errmsg-encoded ``fleet=`` param — same consumer contract as
        #: the native client's attribute
        self.ring_hint: Optional[dict] = None

    def _call(self, name: str, body: Dict[str, Any]):
        from antidote_tpu.proto import apb

        frame = apb.encode_frame_body(name, body)
        with self._lock:
            try:
                self._sock.sendall(struct.pack(">I", len(frame)) + frame)
            except (ConnectionError, OSError) as e:
                e.request_sent = False
                raise
            try:
                data = read_frame_buffered(self._rfile)
            except (ConnectionError, OSError) as e:
                e.request_sent = True
                raise
        resp_name, resp = apb.decode_frame_body(data)
        if resp_name == "ApbErrorResp":
            err = apb.parse_error_text(resp.get("errmsg", b""))
            kind, detail = err["kind"], err["detail"]
            if err.get("fleet") or err.get("redirect"):
                self.ring_hint = {
                    "owner": err.get("redirect"),
                    "followers": err.get("fleet") or [],
                    "vnodes": None,
                }
            if kind == "tenant_busy":
                raise RemoteTenantBusy(detail, err["retry_after_ms"],
                                       tenant=err.get("tenant") or "")
            if kind == "busy":
                raise RemoteBusy(detail, err["retry_after_ms"])
            if kind == "deadline":
                raise RemoteDeadline(detail)
            if kind == "read_only":
                raise RemoteReadOnly(detail)
            if kind == "not_owner":
                raise RemoteNotOwner(detail, redirect=err["redirect"])
            if kind == "lagging":
                raise RemoteLagging(detail, err["retry_after_ms"],
                                    redirect=err["redirect"])
            if kind == "forward_failed":
                raise RemoteForwardFailed(detail)
            if kind == "insufficient_rights":
                raise RemoteInsufficientRights(detail,
                                               err["retry_after_ms"])
            raise RemoteError(f"{kind}: {detail}")
        hint = resp.get("ring_hint") if isinstance(resp, dict) else None
        if hint is not None:
            self.ring_hint = msgpack.unpackb(hint, raw=False)
        return resp_name, resp

    @staticmethod
    def _txn_clock(clock) -> Dict[str, Any]:
        if clock is None:
            return {}
        return {"timestamp": msgpack.packb([int(x) for x in clock])}

    def read_objects(self, objects: Sequence[Tuple[Any, str, str]],
                     clock: Optional[Sequence[int]] = None,
                     deadline_ms=None):
        from antidote_tpu.proto import apb

        name, resp = self._call("ApbStaticReadObjects", {
            "transaction": self._txn_clock(clock),
            "objects": [
                {"key": apb.to_bytes(k), "type": apb.TYPE_IDS[t],
                 "bucket": apb.to_bytes(b)}
                for k, t, b in objects
            ],
        })
        vals = [apb.read_resp_to_value(r)
                for r in resp["objects"]["objects"]]
        vc = msgpack.unpackb(resp["committime"]["commit_time"],
                             raw=False)
        return vals, vc

    def update_objects(self, updates: Sequence[Tuple],
                       clock: Optional[Sequence[int]] = None,
                       deadline_ms=None) -> List[int]:
        from antidote_tpu.proto import apb

        name, resp = self._call("ApbStaticUpdateObjects", {
            "transaction": self._txn_clock(clock),
            "updates": [apb.update_op_from_native(u) for u in updates],
        })
        return msgpack.unpackb(resp["commit_time"], raw=False)

    def close(self) -> None:
        try:
            self._rfile.close()
        except OSError:
            pass
        self._sock.close()


class SessionClient:
    """Causal session over an owner + follower fleet (ISSUE 9).

    Carries a compact VC session token: every commit clock and read
    snapshot the session observes folds into the token
    (:func:`~antidote_tpu.proto.codec.merge_clock`), and the token rides
    as the causal clock of every request — so **read-your-writes** and
    **monotonic reads** hold no matter which replica serves, across
    arbitrary follower kills.

    Routing (ISSUE 11): reads route over a consistent-hash ring
    (:class:`HashRing`) across the follower fleet — each key has one
    preferred replica fleet-wide (virtual-node arcs), failover walks a
    per-client seeded-jittered order over the survivors, and the owner
    is always the last resort — so a killed follower sheds only its
    ring arcs, failover is one hop instead of an O(fleet) endpoint
    walk, and a fleet-wide death never stampedes every client onto the
    same next endpoint.  Writes always go to the owner.  Typed
    ``lagging`` / ``not_owner`` redirects and connection deaths fail
    over identically; when every endpoint fails, the typed
    :class:`~antidote_tpu.overload.ReplicaDown` surfaces.

    The fleet can be passed statically (``followers``) or learned LIVE
    from the owner's replica registry (``discover=True`` — the
    ``replica-status`` surface; :meth:`refresh_fleet` re-learns it, and
    a fully-failed read triggers one automatic re-learn before giving
    up).  ``dialect`` selects the wire codec per endpoint: ``native``
    (msgpack) or ``apb`` (antidote_pb protobuf) — both carry the same
    token semantics and the same at-most-once write discipline.
    """

    #: a connection-dead endpoint is skipped for this long before being
    #: retried (its ring arcs fail over; everyone else's are untouched)
    DEAD_S = 2.0

    def __init__(self, owner, followers=(), timeout: float = 30.0,
                 dialect: str = "native", ring_vnodes: int = 64,
                 seed: Optional[int] = None, discover: bool = False):
        self.owner = (owner[0], int(owner[1]))
        self.timeout = timeout
        if dialect not in ("native", "apb"):
            raise ValueError(f"unknown dialect {dialect!r}")
        self.dialect = dialect
        self.ring_vnodes = int(ring_vnodes)
        if seed is None:
            import os as _os

            seed = int.from_bytes(_os.urandom(8), "big")
        self.seed = int(seed)
        #: the session token (None until the first clock is observed)
        self.token: Optional[List[int]] = None
        self._conns: dict = {}
        #: addr -> monotonic time until which it is skipped (conn death)
        self._dead: Dict[Tuple[str, int], float] = {}
        #: session observability: typed lagging/not_owner redirects
        #: honored, endpoint failovers on connection death, and reads
        #: served per endpoint (the fleet-smoke arc coverage signal)
        self.redirects = 0
        self.failovers = 0
        #: ring hints absorbed from server replies (ISSUE 17): each one
        #: refreshed the fleet/owner in place with zero extra round trips
        self.hints_applied = 0
        self.served_by: Dict[Tuple[str, int], int] = {}
        self.followers: List[Tuple[str, int]] = []
        self.ring = HashRing((), vnodes=self.ring_vnodes, seed=self.seed)
        self._discover = bool(discover)
        self._set_fleet(followers)
        if self._discover and not self.followers:
            self.refresh_fleet()

    # -- fleet -----------------------------------------------------------
    def _set_fleet(self, followers) -> None:
        self.followers = [(h, int(p)) for h, p in followers]
        self.ring = HashRing(self.followers, vnodes=self.ring_vnodes,
                             seed=self.seed)

    def refresh_fleet(self) -> List[Tuple[str, int]]:
        """Re-learn the follower fleet from the owner's replica
        registry: every follower the owner reports live-and-serving
        (state ok/lagging — a lagging replica still serves most
        sessions) with a known client address joins the ring.  The
        registry op rides the native dialect (it is an ops surface,
        served on the same port either way)."""
        c = AntidoteClient(self.owner[0], self.owner[1],
                           timeout=self.timeout)
        try:
            st = c.replica_admin("status")
        finally:
            c.close()
        fleet = []
        for _name, f in sorted((st.get("followers") or {}).items()):
            if f.get("state") in ("ok", "lagging") and f.get("addr"):
                fleet.append((f["addr"][0], int(f["addr"][1])))
        self._set_fleet(fleet)
        return self.followers

    # -- connections -----------------------------------------------------
    def _conn(self, addr):
        c = self._conns.get(addr)
        if c is None:
            cls = AntidoteClient if self.dialect == "native" else ApbClient
            try:
                c = cls(addr[0], addr[1], timeout=self.timeout)
            except (ConnectionError, OSError) as e:
                # a DIAL failure never carried a request: tag it so the
                # at-most-once write logic knows a retry is safe
                e.request_sent = False
                raise
            self._conns[addr] = c
        return c

    def _drop(self, addr) -> None:
        c = self._conns.pop(addr, None)
        if c is not None:
            try:
                c.close()
            except OSError:
                pass

    def observe(self, clock) -> None:
        """Fold an observed clock into the session token."""
        self.token = merge_clock(self.token, clock)

    def _absorb_hint(self, conn) -> None:
        """Apply a server-attached ring hint (ISSUE 17) in place: a
        follower that proxied/redirected for us tells us the current
        owner + fleet, so the NEXT read routes zero-hop — no
        refresh_fleet round trip.  A hint never shrinks knowledge: an
        owner-only hint (errmsg redirect) leaves the ring alone."""
        hint = getattr(conn, "ring_hint", None)
        if not hint:
            return
        conn.ring_hint = None
        changed = False
        owner = hint.get("owner")
        if owner and (owner[0], int(owner[1])) != self.owner:
            self.owner = (owner[0], int(owner[1]))
            changed = True
        fleet = [(h, int(p)) for h, p in (hint.get("followers") or ())]
        if fleet and fleet != self.followers:
            self._set_fleet(fleet)
            changed = True
        if changed:
            self.hints_applied += 1

    # -- session ops -----------------------------------------------------
    def update_objects(self, updates: Sequence[Tuple]) -> List[int]:
        """Session write: always the owner; the commit clock folds into
        the token so any replica serving a later read must cover it.
        AT-MOST-ONCE: only a SEND-phase transport failure (the request
        never left — e.g. a cached connection gone stale across an
        owner restart) is redialed; a connection dying while awaiting
        the reply surfaces typed, because the owner may have executed
        the (non-idempotent) write and a blind resend would apply it
        twice — the same discipline the inter-DC query channel keeps."""
        from antidote_tpu.overload import ReplicaDown

        last: Optional[BaseException] = None
        for _attempt in range(2):
            try:
                addr = self.owner
                vc = self._conn(addr).update_objects(
                    updates, clock=self.token)
                self.observe(vc)
                self._absorb_hint(self._conns.get(addr))
                return vc
            except RemoteNotOwner as e:
                # the "owner" endpoint is itself a follower (operator
                # misconfiguration) but told us where to go
                self._absorb_hint(self._conns.get(self.owner))
                if not e.redirect:
                    raise
                self.redirects += 1
                self.owner = (e.redirect[0], int(e.redirect[1]))
                last = e
            except (ConnectionError, OSError) as ex:
                self._drop(self.owner)
                self.failovers += 1
                if getattr(ex, "request_sent", True):
                    raise ConnectionError(
                        f"session write: connection to owner "
                        f"{self.owner} died awaiting the reply — the "
                        "write may have executed; not resending"
                    ) from ex
                last = ex
        raise ReplicaDown(
            f"session write: owner {self.owner} unreachable"
        ) from last

    def _read_candidates(self, objects):
        """Hash-ring failover order for a read, LAZILY: the first
        object's key owns the routing decision (a multi-object session
        read is one unit — splitting it across replicas would need
        cross-replica snapshot agreement).  The healthy hot path pays
        one key hash + bisect for the preferred endpoint; the
        seeded-jitter tail (N-1 hashes + a sort) is only computed once
        the preferred attempt has actually failed.  Recently-dead
        endpoints are skipped (their arcs fail over; everything else is
        untouched), and the owner is always the terminal fallback."""
        now = time.monotonic()
        for ep, until in list(self._dead.items()):
            if until <= now:
                del self._dead[ep]  # cooldown over: arcs come back
        if len(self.ring) and objects:
            key, _t, bucket = objects[0]
            pref = self.ring.preferred(key, bucket)
            if pref is not None and pref not in self._dead:
                yield pref
            for ep in self.ring.order(key, bucket)[1:]:
                if ep not in self._dead:
                    yield ep
        yield self.owner

    def read_objects(self, objects: Sequence[Tuple[Any, str, str]],
                     _relearn: bool = True):
        """Session read: the key's ring-preferred follower first, then
        the seeded-jittered survivor order, then the owner.  The reply's
        snapshot clock folds into the token (monotonic reads).  A read
        every endpoint refused re-learns the fleet once (when discovery
        is wired) before surfacing the typed ReplicaDown."""
        from antidote_tpu.overload import ReplicaDown

        last: Optional[BaseException] = None
        for addr in self._read_candidates(objects):
            try:
                vals, vc = self._conn(addr).read_objects(
                    objects, clock=self.token)
            except RemoteLagging as e:
                self.redirects += 1
                self._absorb_hint(self._conns.get(addr))
                last = e
                continue
            except RemoteNotOwner as e:
                self.redirects += 1
                self._absorb_hint(self._conns.get(addr))
                last = e
                continue
            except (ConnectionError, OSError) as ex:
                self._drop(addr)
                if addr != self.owner:
                    # shed only this endpoint's arcs for a cooldown —
                    # the rest of the ring keeps its routing
                    self._dead[addr] = time.monotonic() + self.DEAD_S
                self.failovers += 1
                last = ex
                continue
            self.observe(vc)
            # a PROXIED reply carries the ring hint: absorb it so the
            # next read for this arc routes zero-hop
            self._absorb_hint(self._conns.get(addr))
            self.served_by[addr] = self.served_by.get(addr, 0) + 1
            return vals, vc
        if self._discover and _relearn:
            # the whole learned fleet may be stale (rolling restarts):
            # one registry re-learn, then one more pass
            try:
                self.refresh_fleet()
            except Exception:
                pass
            else:
                return self.read_objects(objects, _relearn=False)
        raise ReplicaDown(
            "session read: every endpoint (followers and owner) "
            "refused or dropped the request"
        ) from last

    def stats(self) -> dict:
        """Session/ring observability: ring size, per-endpoint arc
        shares, reads served per endpoint, redirects, failovers."""
        return {
            "ring_size": len(self.ring),
            "arc_share": self.ring.arc_share_by_name(),
            "served_by": {f"{h}:{p}": n
                          for (h, p), n in sorted(self.served_by.items())},
            "redirects": self.redirects,
            "failovers": self.failovers,
            "hints_applied": self.hints_applied,
        }

    def close(self) -> None:
        for addr in list(self._conns):
            self._drop(addr)
