"""Protocol server: TCP acceptor pool + request dispatcher.

The ranch listener (100 acceptors, max 1024 conns, port 8087 —
/root/reference/src/antidote_pb_sup.erl:47-56) becomes a
``ThreadingTCPServer``; the decode→process→encode loop with error replies
mirrors ``antidote_pb_protocol:loop/handle``
(/root/reference/src/antidote_pb_protocol.erl:51-88), and the dispatch
table mirrors ``antidote_pb_process:process/1``
(/root/reference/src/antidote_pb_process.erl:49-135).

The node's transaction manager is a single commit stream, so requests are
serialized through one lock — concurrency buys pipelining of socket IO,
matching the single-writer-per-partition design (SURVEY §2.10 row 2).
"""

from __future__ import annotations

import logging
import socketserver
import threading
from typing import Any, Dict, Optional

import numpy as np

from antidote_tpu.api.node import AntidoteNode
from antidote_tpu.proto import apb
from antidote_tpu.proto.codec import (
    MessageCode,
    decode,
    encode_value,
    freeze,
    read_frame,
    write_frame_body,
    write_message,
)
from antidote_tpu.txn.manager import AbortError, Transaction

DEFAULT_PORT = 8087
log = logging.getLogger(__name__)


def _decode_objects(objs):
    return [(freeze(k), t, b) for k, t, b in (freeze(o) for o in objs)]


def _decode_updates(ups):
    return [(freeze(k), t, b, freeze(op)) for k, t, b, op in
            (freeze(u) for u in ups)]


def _vc(x) -> Optional[np.ndarray]:
    return None if x is None else np.asarray(x, np.int32)


class ProtocolServer:
    def __init__(self, node: AntidoteNode, host: str = "127.0.0.1",
                 port: int = 0, interdc=None, max_connections: int = 1024):
        self.node = node
        #: DCReplica for the descriptor/connect requests (optional)
        self.interdc = interdc
        self._lock = threading.Lock()
        self._txns: Dict[int, Transaction] = {}
        #: connection cap (the reference's ranch listener caps at 1024,
        #: /root/reference/src/antidote_pb_sup.erl:47-56).  The accept
        #: loop blocks on the semaphore when the cap is reached, so
        #: excess connections queue in the kernel listen backlog instead
        #: of exhausting server threads — ranch's backpressure shape.
        self.max_connections = max_connections
        self._conn_slots = threading.BoundedSemaphore(max_connections)
        handler = self._make_handler()
        conn_slots = self._conn_slots

        class Server(socketserver.ThreadingTCPServer):
            daemon_threads = True
            allow_reuse_address = True
            closing = False
            # while the accept loop parks on the cap, excess connections
            # must queue in the kernel listen backlog (ranch's shape) —
            # the socketserver default of 5 would drop their SYNs
            request_queue_size = max_connections

            def shutdown(self):
                self.closing = True
                super().shutdown()

            def process_request(self, request, client_address):
                # hold the accept loop until a slot frees: backpressure,
                # not thread-per-connection without bound.  Poll so a
                # shutdown() issued while the cap is saturated can still
                # unpark the serve_forever loop instead of deadlocking.
                while not conn_slots.acquire(timeout=0.1):
                    if self.closing:
                        self.shutdown_request(request)
                        return
                try:
                    super().process_request(request, client_address)
                except BaseException:
                    conn_slots.release()
                    raise

            def process_request_thread(self, request, client_address):
                try:
                    super().process_request_thread(request, client_address)
                finally:
                    conn_slots.release()

        self._server = Server((host, port), handler)
        self.host, self.port = self._server.server_address
        self._thread = threading.Thread(
            target=self._server.serve_forever, daemon=True,
            name=f"antidote-proto:{self.port}",
        )
        self._thread.start()

    # ------------------------------------------------------------------
    def _make_handler(server_self):
        class Handler(socketserver.BaseRequestHandler):
            def handle(self):
                # txns this connection started and has not finished: a
                # dropped connection must not pin open transactions (they
                # hold the certification-GC floor — manager._open_snaps —
                # forever; the reference's coordinator FSMs die with the
                # client process and roll back the same way)
                conn_txns = set()
                try:
                    self._serve(conn_txns)
                finally:
                    for txid in conn_txns:
                        server_self._abort_orphan(txid)

            def _serve(self, conn_txns):
                while True:
                    try:
                        frame = read_frame(self.request)
                    except (ConnectionError, OSError):
                        return
                    # dialect dispatch on the code byte: antidote_pb
                    # request codes (apb.APB_REQUEST_CODES) are disjoint
                    # from the native msgpack codes, so existing
                    # antidotec_pb clients connect to the same port
                    if frame and frame[0] in apb.APB_REQUEST_CODES:
                        resp_body = apb.handle_request(
                            server_self, frame[0], frame[1:], conn_txns,
                            lock=server_self._lock,
                        )
                        try:
                            write_frame_body(self.request, resp_body)
                        except (ConnectionError, OSError):
                            return
                        continue
                    try:
                        code, body = decode(frame)
                        resp_code, resp = server_self._process(code, body)
                        if code == MessageCode.START_TRANSACTION:
                            conn_txns.add(resp["txid"])
                        elif code in (MessageCode.COMMIT_TRANSACTION,
                                      MessageCode.ABORT_TRANSACTION):
                            conn_txns.discard(body.get("txid"))
                    except AbortError as e:
                        if code == MessageCode.UPDATE_OBJECTS:
                            conn_txns.discard(body.get("txid"))
                        resp_code, resp = MessageCode.ERROR_RESP, {
                            "error": "aborted", "detail": str(e)
                        }
                    except Exception as e:  # error reply, keep the conn
                        log.exception("request failed")
                        resp_code, resp = MessageCode.ERROR_RESP, {
                            "error": type(e).__name__, "detail": str(e)
                        }
                    try:
                        write_message(self.request, resp_code, resp)
                    except (ConnectionError, OSError):
                        return

        return Handler

    def _abort_orphan(self, txid: int) -> None:
        """Roll back a transaction whose client connection died."""
        with self._lock:
            txn = self._txns.pop(txid, None)
            if txn is not None and txn.active:
                self.node.abort_transaction(txn)

    # ------------------------------------------------------------------
    def _process(self, code: MessageCode, body: Any):
        with self._lock:
            return self._dispatch(code, body)

    def _dispatch(self, code: MessageCode, body: Any):
        node = self.node
        if code == MessageCode.START_TRANSACTION:
            txn = node.start_transaction(
                clock=_vc(body.get("clock")), props=body.get("props"),
            )
            self._txns[txn.txid] = txn
            return MessageCode.START_TRANSACTION_RESP, {"txid": txn.txid}
        if code == MessageCode.READ_OBJECTS:
            txn = self._txn(body["txid"])
            vals = node.read_objects(_decode_objects(body["objects"]), txn)
            return MessageCode.READ_OBJECTS_RESP, {
                "values": [encode_value(v) for v in vals]
            }
        if code == MessageCode.UPDATE_OBJECTS:
            txn = self._txn(body["txid"])
            try:
                node.update_objects(_decode_updates(body["updates"]), txn)
            except AbortError:
                self._txns.pop(body["txid"], None)
                raise
            return MessageCode.OPERATION_RESP, {"ok": True}
        if code == MessageCode.COMMIT_TRANSACTION:
            txn = self._txns.pop(body["txid"])
            commit_vc = node.commit_transaction(txn)
            return MessageCode.COMMIT_RESP, {
                "commit_clock": [int(x) for x in commit_vc]
            }
        if code == MessageCode.ABORT_TRANSACTION:
            txn = self._txns.pop(body["txid"])
            node.abort_transaction(txn)
            return MessageCode.OPERATION_RESP, {"ok": True}
        if code == MessageCode.STATIC_UPDATE_OBJECTS:
            vc = node.update_objects(
                _decode_updates(body["updates"]), clock=_vc(body.get("clock"))
            )
            return MessageCode.COMMIT_RESP, {
                "commit_clock": [int(x) for x in vc]
            }
        if code == MessageCode.STATIC_READ_OBJECTS:
            vals, vc = node.read_objects(
                _decode_objects(body["objects"]), clock=_vc(body.get("clock"))
            )
            return MessageCode.READ_OBJECTS_RESP, {
                "values": [encode_value(v) for v in vals],
                "commit_clock": [int(x) for x in vc],
            }
        if code == MessageCode.GET_CONNECTION_DESCRIPTOR:
            if self.interdc is None:
                raise RuntimeError("no inter-DC replica attached")
            d = self.interdc.descriptor()
            return MessageCode.OPERATION_RESP, {
                "descriptor": {"dc_id": d.dc_id, "name": d.name,
                               "n_shards": d.n_shards,
                               "address": d.address},
            }
        if code == MessageCode.NODE_STATUS:
            return MessageCode.OPERATION_RESP, {
                "status": node.status(
                    include_ready=bool(body.get("include_ready"))
                )
            }
        raise ValueError(f"unhandled message code {code!r}")

    def _txn(self, txid: int) -> Transaction:
        txn = self._txns.get(txid)
        if txn is None:
            raise KeyError(f"unknown or finished transaction {txid}")
        return txn

    # ------------------------------------------------------------------
    def is_alive(self) -> bool:
        """Supervision probe (supervise.Supervisor child health)."""
        return self._thread.is_alive()

    def close(self) -> None:
        self._server.shutdown()
        self._server.server_close()
        self._thread.join(timeout=5)
